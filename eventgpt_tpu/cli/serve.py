"""Network-facing serving front-end over the continuous batcher.

The reference's LLaVA lineage implies a controller/worker serving stack it
never shipped (the heartbeat vestiges at
``/root/reference/dataset/constants.py:1-4`` — CONTROLLER_HEART_BEAT_
EXPIRATION etc. with no server behind them). This module is that surface,
TPU-first: ONE process owns the chip and the resident decode batch
(``eventgpt_tpu/serve.py``); a stdlib ThreadingHTTPServer front end feeds
it through a thread-safe engine, so concurrency lives in the scheduler's
row-level admission — not in process fan-out. A controller tier is not
re-created: on TPU the accelerator is single-owner, and multi-host
serving scales by sharding the batcher over the mesh
(``--mesh_data/fsdp/model``), not by LLaVA's worker pools.

Endpoints:
  POST /v1/generate  {"query": str,
                      "event_path": .npy path under --event_root |
                      "event_b64": base64 .npy bytes,
                      "max_new_tokens": int = 64,
                      "stream": bool = false}
      -> {"answer": str, "tokens": N, "ttft_s": x, "latency_s": y}
      or (stream) chunked text deltas as they commit, newline-framed JSON.
  GET  /health       -> {"status": "ok", "active": N, "queued": N}
      (lock-free snapshot: answers inside a probe timeout even mid-segment)
  GET  /stats        -> serverwide counters + recent request stats +
      a summary of the telemetry registry (obs/metrics.py).
  GET  /prefix_cache -> prefix-KV cache snapshot (entries, bytes,
      hit/miss/eviction counters); POST /prefix inserts an entry.
  GET  /metrics      -> Prometheus text exposition (scrape target:
      TTFT / inter-token-latency / queue-wait histograms, counters,
      breaker state — the catalogue is in OBSERVABILITY.md).
  GET  /trace        -> Chrome trace JSON of the live span ring
      (request lifecycles + scheduler dispatch/harvest; load in
      Perfetto or chrome://tracing).
  POST /profile      {"seconds": N} -> capture a jax.profiler window of
      live traffic into --profile_dir; returns the trace directory.

``event_path`` is directory-allowlisted: without ``--event_root`` it is
disabled entirely (clients upload streams inline via ``event_b64``), and
with it the resolved path must stay inside the root.

Smoke (tiny random weights):
  python -m eventgpt_tpu.cli.serve --model_path tiny-random --port 8600 \
      --event_root /root/reference/samples &
  curl -s localhost:8600/v1/generate -d '{"query": "What is happening?",
      "event_path": "sample1.npy"}'
"""

from __future__ import annotations

import argparse
import base64
import itertools
import json
import math
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

from eventgpt_tpu import faults  # stdlib-only; safe before jax loads
from eventgpt_tpu.obs import journey as obs_journey  # stdlib-only too
from eventgpt_tpu.obs import metrics as obs_metrics
from eventgpt_tpu.obs import series as obs_series  # stdlib-only too
from eventgpt_tpu.obs import trace as obs_trace


class ServingEngine:
    """Thread-safe wrapper around one ``ContinuousBatcher``.

    The batcher itself is single-threaded by design (every method touches
    resident device buffers); the engine serializes access behind one
    lock and runs the scheduler loop on a dedicated thread, parking it
    when no work exists. HTTP handler threads only do host-side prep
    (event file -> pixels, tokenize) and block on per-request events.

    Request-lifecycle hardening: a scheduler-thread exception no longer
    kills the engine for good. The dying thread fails the in-flight rows
    cleanly (their waiters/streams get the fault), keeps queued requests
    for re-admission, and RESTARTS the scheduler thread. A circuit
    breaker counts consecutive faults: at ``breaker_threshold`` it trips
    — queued requests are failed too, ``/health`` flips to ``degraded``
    and submits are refused (503) until ``breaker_cooldown_s`` elapses
    (half-open: traffic is admitted again; the first clean step closes
    the breaker, the next fault re-trips it instantly). ``heartbeat_dir``
    arms the same atomic liveness file the trainer writes
    (``train/resilience.Heartbeat``) so one external watchdog convention
    covers both.

    Lock discipline (egpt_check rule ``lock``): ``_GUARDED_BY`` below is
    the checkable contract. Full-guard attributes are only touched under
    ``_lock`` (or in ``*_locked`` helpers); ``/w`` attributes take the
    lock to WRITE but are read lock-free by design — the snapshot/flag
    pattern that lets ``/health``, ``/stats`` and ``breaker_open()``
    answer inside a probe timeout while the scheduler thread holds the
    lock through a multi-second decode segment (reads of a
    GIL-atomically swapped dict/bool/int are safe; readers tolerate
    one-step staleness). ``_wake``/``_stop``/``_thread`` and the
    scheduler-thread-private fields (``_n_steps``, ``_last_beat``) are
    deliberately undeclared: Event is self-synchronizing and the rest
    are single-thread state.
    """

    _GUARDED_BY = {
        # full guard: multi-step mutations that must be atomic
        "batcher": "_lock",
        "_answers": "_lock",
        "_sent": "_lock",
        "_abandoned": "_lock",
        # writes locked, lock-free reads by design (see docstring)
        "_done": "_lock/w",
        "_status": "_lock/w",
        "_streams": "_lock/w",
        "_dead": "_lock/w",
        "_snapshot": "_lock/w",
        "_consec_faults": "_lock/w",
        "_t_fault": "_lock/w",
        "fault": "_lock/w",
        "n_faults": "_lock/w",
        "n_restarts": "_lock/w",
        "n_requests": "_lock/w",
    }

    def __init__(self, batcher, tokenizer, conv_mode: str = "eventgpt_v1",
                 breaker_threshold: int = 3,
                 breaker_cooldown_s: float = 5.0,
                 heartbeat_dir: Optional[str] = None,
                 heartbeat_interval_s: float = 1.0,
                 trace_out: Optional[str] = None):
        self.batcher = batcher
        # Chrome-trace dump destination written at shutdown (--trace_out);
        # GET /trace snapshots the live ring any time before that.
        self.trace_out = trace_out
        self.tokenizer = tokenizer
        self.conv_mode = conv_mode
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = False
        self._done: Dict[int, threading.Event] = {}
        self._answers: Dict[int, list] = {}
        self._status: Dict[int, str] = {}  # terminal status per rid
        self._streams: Dict[int, queue.Queue] = {}
        self._sent: Dict[int, int] = {}
        self._abandoned: set = set()  # timed-out rids: drop at harvest
        self.n_requests = 0
        self.t_start = time.time()
        self.fault: Any = None  # repr of the LAST scheduler fault
        self.n_faults = 0          # total scheduler faults survived
        self.n_restarts = 0        # scheduler-thread restarts
        self._consec_faults = 0    # consecutive (no clean step between)
        self._t_fault = 0.0        # monotonic time of the last fault
        self.breaker_threshold = max(int(breaker_threshold), 1)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        # Fleet kill state (ISSUE 7): a killed replica parks its
        # scheduler loop and refuses submits until revive() — the
        # supervisor drained its requests for re-admission elsewhere.
        self._dead = False
        self._n_steps = 0
        self._heartbeat = None
        self._hb_interval = float(heartbeat_interval_s)
        self._last_beat = 0.0
        if heartbeat_dir:
            from eventgpt_tpu.train.resilience import Heartbeat

            self._heartbeat = Heartbeat(heartbeat_dir)
        # Lock-free stats snapshot: /health and /stats must answer inside
        # a load balancer's probe timeout even while the scheduler thread
        # holds the lock through a multi-second decode segment. Rebuilt
        # after every step; staleness is bounded by one segment.
        self._snapshot: Dict[str, Any] = self._build_snapshot_locked()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    # -- client side ------------------------------------------------------

    def breaker_open(self) -> bool:
        """True while the circuit breaker refuses new work: the fault
        count hit the threshold and the cooldown has not elapsed. After
        the cooldown the breaker is HALF-OPEN — submits flow again, one
        clean step resets the count, one more fault re-trips."""
        return (self._consec_faults >= self.breaker_threshold
                and time.monotonic() - self._t_fault < self.breaker_cooldown_s)

    def breaker_retry_after_s(self) -> Optional[float]:
        """Derived Retry-After for breaker-open 503s (ISSUE 11
        satellite, the 429 paths' discipline): the REMAINING cooldown
        before the half-open probe admits traffic — the one number the
        engine actually knows about when it will take work again.
        None while the breaker is closed (the caller falls back to the
        goodput-derived hint)."""
        if not self.breaker_open():
            return None
        remaining = (self.breaker_cooldown_s
                     - (time.monotonic() - self._t_fault))
        return max(remaining, 1.0)

    def submit(self, query: str, pixels, max_new_tokens: int,
               stream: bool = False,
               deadline_s: Optional[float] = None,
               slo=None) -> int:
        from eventgpt_tpu.data.conversation import prepare_event_prompt
        from eventgpt_tpu.data.tokenizer import tokenize_with_event

        ids = tokenize_with_event(
            prepare_event_prompt(query, self.conv_mode), self.tokenizer
        )
        return self.submit_ids(ids, pixels, max_new_tokens, stream=stream,
                               deadline_s=deadline_s, slo=slo)

    def submit_ids(self, ids, pixels, max_new_tokens: int,
                   stream: bool = False,
                   deadline_s: Optional[float] = None,
                   slo=None) -> int:
        """``submit`` for a pre-tokenized prompt — the fleet router's
        entry point (it tokenized once already, to compute the request's
        prefix-affinity key)."""
        if self.breaker_open() or self._dead:
            raise RuntimeError(f"serving engine is down: {self.fault}")
        with self._lock:
            # Re-check under the lock: a breaker trip (or kill) while
            # the caller prepared the request has already swept _done —
            # an event registered after the sweep would burn its
            # caller's full timeout.
            if self.breaker_open() or self._dead:
                raise RuntimeError(f"serving engine is down: {self.fault}")
            rid = self.batcher.submit(ids, pixels, max_new_tokens,
                                      deadline_s=deadline_s, slo=slo)
            self._done[rid] = threading.Event()
            if stream:
                self._streams[rid] = queue.Queue()
                self._sent[rid] = 0
            self.n_requests += 1
        self._wake.set()
        return rid

    def cancel(self, rid: int) -> bool:
        """Cancel a queued or in-flight request; its waiter is released
        with whatever tokens were committed, under status ``cancelled``.
        False when the rid is unknown or already finished."""
        with self._lock:
            ok = self.batcher.cancel(rid)
            if ok:
                self._harvest_locked()
                self._snapshot = self._build_snapshot_locked()
        if ok:
            self._wake.set()
        return ok

    def set_prefix(self, prefix_prompt: str, pixels=None) -> int:
        """Install a shared-prefix KV seed (``ContinuousBatcher.set_prefix``)
        from raw prompt text (may contain the ``<event>`` placeholder, in
        which case ``pixels`` carries its stream). Matching admissions skip
        the prefix's encode + prefill from then on; non-matching prompts
        fall back to the full path untouched. Returns the prefix length in
        cache positions. Safe on a live engine: the prefix prefill builds
        its own row cache and never touches resident rows."""
        from eventgpt_tpu.data.tokenizer import tokenize_with_event

        ids = tokenize_with_event(prefix_prompt, self.tokenizer)
        with self._lock:
            return self.batcher.set_prefix(ids, pixel_values=pixels)

    def status(self, rid: int) -> str:
        """Terminal status of a finished request ('ok' when it finished
        normally or is unknown/still running)."""
        return self._status.get(rid, "ok")

    def result(self, rid: int, timeout: float = 600.0):
        """Block until the request finishes; returns its token ids."""
        ev = self._done[rid]
        if not ev.wait(timeout):
            with self._lock:
                # The batcher will still finish this request; with its
                # waiter gone the answer would sit in _answers forever
                # (unbounded host growth on a long-lived server). Either
                # take the answer that landed in the race window, or mark
                # the rid for drop-at-harvest like an orphaned stream.
                self._done.pop(rid, None)
                if rid in self._answers:
                    return self._answers.pop(rid)
                self._abandoned.add(rid)
            raise TimeoutError(f"request {rid} did not finish in {timeout}s")
        with self._lock:
            self._done.pop(rid, None)
            if rid not in self._answers:
                raise RuntimeError(
                    f"serving engine is down: "
                    f"{self.fault or self._status.get(rid, 'unknown fault')}")
            return self._answers.pop(rid)

    def try_result(self, rid: int):
        """Non-blocking collection for the fleet supervisor: ``(tokens,
        status)`` once the request is terminal — ``(None,
        "engine_fault")`` when a scheduler fault failed it (the
        supervisor's cue to fail it over) — else ``None`` (still
        running). Consuming: a delivered answer is popped, like
        ``result``."""
        with self._lock:
            if rid in self._answers:
                self._done.pop(rid, None)
                return self._answers.pop(rid), self._status.get(rid, "ok")
            if self._status.get(rid) == "engine_fault":
                self._done.pop(rid, None)
                return None, "engine_fault"
        return None

    def try_status(self, rid: int):
        """Terminal status of a STREAMED request once its harvest
        delivered through the stream queue (answers never reach
        ``_answers`` there), else None — the supervisor's stream-side
        counterpart of ``try_result``."""
        with self._lock:
            st = self._status.get(rid)
            if st is not None and rid not in self._streams:
                return st
        return None

    def kill(self) -> list:
        """Simulated replica death (the fleet chaos contract): deliver
        anything already finished, then strip EVERY unfinished request
        out of the batcher (``ContinuousBatcher.export_requests``) and
        return the re-admission records — the supervisor re-routes them
        to survivors. The scheduler loop parks and submits are refused
        until ``revive()``. Engine-side waiter state for the exported
        rids is dropped: the fleet owns those clients now."""
        with self._lock:
            self._dead = True
            # Finished-but-uncollected answers are real results — hand
            # them to try_result instead of re-running them elsewhere.
            self._push_stream_deltas_locked()
            self._harvest_locked()
            recs = self.batcher.export_requests()
            # export_requests settles the in-flight pipelined segment
            # first (_drain), which can FINISH a request right here —
            # after the harvest above, and out of rows so never
            # exported. Harvest again or the answer strands in
            # batcher.finished (the parked loop will not run again) and
            # the fleet supervisor polls try_result forever.
            self._harvest_locked()
            for rec in recs:
                rid = rec["rid"]
                self._done.pop(rid, None)
                self._streams.pop(rid, None)
                self._sent.pop(rid, None)
                self._abandoned.discard(rid)
            self._snapshot = self._build_snapshot_locked()
        self._wake.set()
        return recs

    def collect_handoffs(self) -> List[Dict[str, Any]]:
        """Drain the prefill-role batcher's handoff outbox (ISSUE 17) —
        the coordinator pulls these on its probe cadence and ships each
        to a decode worker. Empty on colocated/decode engines."""
        with self._lock:
            b = self.batcher
            if not hasattr(b, "pop_handoffs"):
                return []
            return b.pop_handoffs()

    def import_handoff(self, ids, max_new_tokens: int, rec,
                       tokens=(), prompt_len: int = 0,
                       deadline_s=None, slo=None,
                       elapsed_s: float = 0.0, ttft_s=None) -> int:
        """Accept a prefill worker's gathered block-run record into the
        decode-role batcher (ISSUE 17). Same breaker/kill gate as
        ``submit_ids`` — a degraded decode worker must refuse the ship
        so the coordinator retries elsewhere instead of stranding KV."""
        if self.breaker_open() or self._dead:
            raise RuntimeError(f"serving engine is down: {self.fault}")
        with self._lock:
            if self.breaker_open() or self._dead:
                raise RuntimeError(
                    f"serving engine is down: {self.fault}")
            rid = self.batcher.import_handoff(
                ids, max_new_tokens, rec, tokens=tokens,
                prompt_len=prompt_len, deadline_s=deadline_s, slo=slo,
                elapsed_s=elapsed_s, ttft_s=ttft_s)
            self._done[rid] = threading.Event()
            self.n_requests += 1
        self._wake.set()
        return rid

    def revive(self) -> None:
        """Recovery half of ``kill``: the replica re-enters service with
        a clean slate (the kill already swept the batcher) and a closed
        breaker."""
        with self._lock:
            self._dead = False
            self._consec_faults = 0
            self.fault = None
            self._snapshot = self._build_snapshot_locked()
        self._wake.set()

    @property
    def alive(self) -> bool:
        return not self._dead

    def snapshot(self) -> Dict[str, Any]:
        """The lock-free stats snapshot (staleness bounded by one
        scheduler step) — the fleet supervisor's cheap health/load
        read."""
        return self._snapshot

    def goodput_ratio(self) -> float:
        """Windowed SLO-attainment of this engine, 1.0 until the window
        holds anything (an empty window is no evidence of overload) —
        the 429 Retry-After derivation reads this."""
        slo = self._snapshot.get("slo", {})
        if not slo.get("window_n"):
            return 1.0
        return float(slo.get("goodput_ratio", 1.0))

    def stream_queue(self, rid: int) -> queue.Queue:
        """Per-request queue of cumulative token-id lists. Two sentinels:
        ``None`` = request finished normally; a ``dict`` = engine fault
        (``{"fault": repr}``) — consumers must surface it, not decode it."""
        return self._streams[rid]

    def _build_snapshot_locked(self) -> Dict[str, Any]:
        """Caller holds the lock (or the batcher is idle at init)."""
        b = self.batcher
        return {
            "active_rows": sum(r is not None for r in b.rows),
            "queued": len(b.queue),
            "max_batch": b.max_batch,
            "max_len": b.max_len,
            "max_queue": b.max_queue,
            "speculative": b.speculative,
            "faults": self.n_faults,
            "restarts": self.n_restarts,
            "admission_s": round(b.admission_s, 3),
            # Pipelined-scheduler overlap story (PERFORMANCE.md): how much
            # host scheduling the in-flight segment is hiding.
            "pipeline": bool(getattr(b, "pipeline", False)),
            # Stall-free admission (ISSUE 5): live piggyback lanes and
            # the per-boundary prompt-token budget driving them.
            "prefill_budget": getattr(b, "prefill_budget", 0),
            "lanes": len(getattr(b, "_lanes", ()) or ()),
            "overlap_ratio": round(b.overlap_ratio(), 3)
            if hasattr(b, "overlap_ratio") else 0.0,
            # SLO classes + windowed goodput (ISSUE 6): per-class
            # attainment so /stats carries the class alongside /metrics.
            "slo": b.slo_stats() if hasattr(b, "slo_stats") else {},
            # Memory ledger (ISSUE 9): totals + per-component bytes +
            # headroom-guard state, merged the way "slo" was — one
            # /stats poll shows latency, goodput AND bytes. Host ints
            # only (the jax.live_arrays reconcile lives on /memory).
            "memory": (b.memory_summary()
                       if hasattr(b, "memory_summary") else {}),
            **({"spec_tokens_per_iteration":
                round(b.spec_tokens_per_iteration(), 2),
                # Adaptive speculation (ISSUE 13): accepted tokens per
                # dispatch, mean chosen window, controller EMA + masked
                # rows — the /stats face of egpt_serve_spec_*.
                "spec": b.spec_stats() if hasattr(b, "spec_stats")
                else {}}
               if b.speculative else {}),
            # Disaggregated serving (ISSUE 17): the worker's role, its
            # block-pool headroom (the decode-placement signal — bytes
            # compare across a fleet, block counts only within one
            # geometry) and the staged handoff counters.
            "role": getattr(b, "role", "colocated"),
            **({"kv_free_blocks": b._pool.free_blocks(),
                "kv_free_bytes": b._pool.free_bytes()}
               if getattr(b, "_pool", None) is not None else {}),
            **({"handoff": {
                "pending": len(b.handoff_ready),
                "gathered": b.handoffs_gathered,
                "gathered_bytes": b.handoffs_gathered_bytes,
                "spliced": b.handoffs_spliced,
                "spliced_bytes": b.handoffs_spliced_bytes}}
               if hasattr(b, "handoff_ready") else {}),
            # reversed() on a dict view walks newest-first without
            # materializing the (bounded-at-8192) stats map each step.
            "recent": {
                str(k): {kk: round(vv, 3)
                         for kk, vv in b.request_stats[k].items()}
                for k in itertools.islice(reversed(b.request_stats), 8)
            },
        }

    def journey(self, rid: int) -> Optional[Dict[str, Any]]:
        """One request's flight-recorder timeline + decomposition
        (ISSUE 10, ``GET /request?rid=N``). Lock-free: the recorder
        guards its own host-side state, like the metrics registry."""
        # egpt-check: ignore[lock] -- the batcher binding is set once in __init__ and never rebound; the journey surface reads the recorder's own lock-guarded host state only (the /memory rule)
        return self.batcher.journey(rid)

    def journeys(self, n: int = 64) -> List[Dict[str, Any]]:
        """Recent finished request timelines (``GET /requests``)."""
        # egpt-check: ignore[lock] -- same read-only recorder surface as journey()
        return self.batcher.journey_index(n)

    def series(self, window_s: Optional[float] = None,
               n: Optional[int] = None) -> Dict[str, Any]:
        """The ``GET /series`` payload (ISSUE 15): the sampled
        time-series ring + windowed derivations. Lock-free here — the
        store guards its own host-side state, like the recorder."""
        return obs_series.snapshot(window_s=window_s, n=n)

    def alerts(self) -> Dict[str, Any]:
        """The ``GET /alerts`` payload (ISSUE 15): per-rule hysteresis
        state + the bounded transition log."""
        return obs_series.alerts()

    def memory_stats(self) -> Dict[str, Any]:
        """The ``GET /memory`` payload (ISSUE 9): ledger + fresh
        live-array reconciliation + static estimate + compiled
        footprint. Deliberately OUTSIDE the engine lock — the reconcile
        walks every live buffer and a cold-probe compile can take
        seconds; both read metadata/host state only, and the batcher's
        memory surface takes no scheduler-owned mutable state."""
        # egpt-check: ignore[lock] -- the batcher binding is set once in __init__ and never rebound; memory_stats reads its ledger/metadata surface only, and holding the engine lock across a live-array walk or an AOT compile would block the scheduler for seconds (the render-outside-the-lock rule /metrics follows)
        return self.batcher.memory_stats()

    def stats(self) -> Dict[str, Any]:
        # Lock-free by design (see _snapshot); counters are GIL-atomic.
        return {
            "uptime_s": round(time.time() - self.t_start, 1),
            "requests": self.n_requests,
            "status": "degraded" if self.breaker_open() else "ok",
            **self._snapshot,
            # Registry merge (ISSUE 3): the same numbers /metrics exposes
            # in Prometheus text, summarized — histogram p50/p99 are log2-
            # bucket upper bounds, see obs/metrics.py.
            "metrics": obs_metrics.serve_summary(),
            # Health state next to latency and bytes (ISSUE 15): active
            # alert rules + the last few transitions; the full log and
            # the series behind it ride GET /alerts and GET /series.
            "alerts": obs_series.alert_stats(),
        }

    def shutdown(self) -> None:
        self._stop = True
        self._wake.set()
        self._thread.join(timeout=10)
        if self.trace_out:
            tracer = obs_trace.active()
            if tracer is not None:
                n = tracer.write(self.trace_out)
                print(f"[serve] wrote {n} trace events to {self.trace_out}")

    # -- scheduler thread -------------------------------------------------

    def _loop(self) -> None:
        while not self._stop:
            try:
                faults.maybe_fail("serve.loop")
                with self._lock:
                    # A killed replica parks: the fleet drained its work
                    # and will revive() it (or not) — stepping a swept
                    # batcher would be harmless but dishonest health.
                    busy = (not self._dead
                            and (self.batcher.queue
                                 or any(r is not None
                                        for r in self.batcher.rows)))
                    if busy:
                        self.batcher.step()
                        self._push_stream_deltas_locked()
                        self._harvest_locked()
                        self._n_steps += 1
                        if self._consec_faults:
                            # A clean step closes the breaker: the fault
                            # streak is over and /health returns to ok.
                            self._consec_faults = 0
                            self.fault = None
                            obs_metrics.SERVE_BREAKER_OPEN.set(0)
                        # Snapshot only when state moved (idle polls would
                        # rebuild 10x/s for nothing); submits wake the
                        # loop, so queue growth shows within one pass.
                        self._snapshot = self._build_snapshot_locked()
            except Exception as e:  # scheduler death must be LOUD
                self._on_fault(e)
                if not self._stop:
                    # Restart the scheduler on a FRESH thread (the fault
                    # may have left this one's stack in a weird spot);
                    # brief backoff so a hard fault loop cannot spin.
                    time.sleep(min(0.05 * self._consec_faults, 0.5))
                    with self._lock:
                        self.n_restarts += 1
                    obs_metrics.SERVE_SCHED_RESTARTS.inc()
                    self._thread = threading.Thread(
                        target=self._loop, daemon=True)
                    self._thread.start()
                return
            if not busy:
                self._maybe_beat()
                self._wake.wait(timeout=0.1)
                self._wake.clear()
            else:
                self._maybe_beat()

    def _maybe_beat(self) -> None:
        """Serving liveness beat (same file format + staleness predicate
        as the trainer's): step count, queue depth, breaker state."""
        if self._heartbeat is None:
            return
        now = time.monotonic()
        if now - self._last_beat < self._hb_interval:
            return
        self._last_beat = now
        try:
            s = self._snapshot
            self._heartbeat.beat(
                self._n_steps,
                status="degraded" if self.breaker_open() else "ok",
                active=s.get("active_rows", 0), queued=s.get("queued", 0),
                faults=self.n_faults, restarts=self.n_restarts,
            )
        except OSError:
            pass  # liveness reporting must never kill the scheduler

    def _on_fault(self, e: Exception) -> None:
        """One scheduler fault: fail the IN-FLIGHT rows cleanly (their
        waiters get the fault instead of burning timeouts), keep queued
        requests for the restarted scheduler to re-admit, and trip the
        circuit breaker when the streak reaches the threshold (then
        queued requests are failed too and submits are refused until the
        cooldown's half-open probe)."""
        with self._lock:
            # Fault bookkeeping mutates under the lock (the race detector
            # caught the old lock-free increments): revive() zeroes
            # _consec_faults under the lock from another thread, so an
            # unlocked += here could lose the trip that opens the
            # breaker.
            self.fault = repr(e)
            self.n_faults += 1
            self._consec_faults += 1
            self._t_fault = time.monotonic()
            tripped = self._consec_faults >= self.breaker_threshold
        obs_metrics.SERVE_SCHED_FAULTS.inc()
        obs_trace.instant("scheduler_fault", cat="engine", error=repr(e))
        if tripped:
            obs_metrics.SERVE_BREAKER_OPEN.set(1)
            obs_trace.instant("breaker_trip", cat="engine")
        with self._lock:
            b = self.batcher
            # A fault can land mid-pipeline (e.g. at the serve.dispatch
            # boundary) with a segment still in flight: drop the in-flight
            # record and the device carry so the restarted scheduler's
            # first dispatch re-uploads the repaired host view instead of
            # resuming from stale device state.
            if hasattr(b, "abort_pipeline"):
                b.abort_pipeline()
            if getattr(b, "_lanes", None):
                # Piggybacked admissions mid-prefill: their requests are
                # failed by the rows sweep below (the row is reserved);
                # drop the lane records so the restarted scheduler never
                # tries to finish a dead lane.
                b._lanes.clear()
                b._lane_free = list(range(b._lane_cap))
            failed = []
            j_owner = getattr(b, "_journey_owner", None)
            t_sweep = time.perf_counter()

            def _fail_journey(req):
                # The sweep bypasses _record_finish, so it closes the
                # flight-recorder timeline itself: the journey's finish
                # must match the engine-side terminal status
                # byte-for-byte (the ISSUE 10 terminal-status audit).
                if j_owner is not None:
                    slo = getattr(req, "slo", None)
                    obs_journey.finish(
                        j_owner, req.rid, "engine_fault",
                        t_submit=req.t_submit, t_done=t_sweep,
                        slo_class=(slo.name if slo is not None else None))

            for r, req in enumerate(b.rows):
                if req is None:
                    continue
                b.rows[r] = None
                b.frozen[r] = True
                b.n_rem[r] = 0
                ent = getattr(req, "prefix_entry", None)
                if ent is not None:
                    # The sweep bypasses _record_finish: drain the
                    # prefix-cache refcount pin here or the entry would
                    # stay unevictable forever.
                    ent.pins -= 1
                    req.prefix_entry = None
                failed.append(req.rid)
                _fail_journey(req)
            b._pending = None
            if tripped:
                for req in b.queue:
                    failed.append(req.rid)
                    _fail_journey(req)
                b.queue.clear()
            for rid in failed:
                self._status[rid] = "engine_fault"
                if rid in self._streams:
                    # A dict sentinel, not None: the stream handler must
                    # surface the fault, not end the body as a normal done.
                    self._streams.pop(rid).put({"fault": self.fault})
                    self._sent.pop(rid, None)
                    self._done.pop(rid, None)
                elif rid in self._done:
                    # result() sees no answer -> raises the fault (the
                    # entry stays for a waiter that arrives post-sweep).
                    self._done[rid].set()
                self._abandoned.discard(rid)
            self._snapshot = self._build_snapshot_locked()

    def _push_stream_deltas_locked(self) -> None:
        for req in self.batcher.rows:
            if req is None or req.rid not in self._streams:
                continue
            n = len(req.tokens)
            if n > self._sent[req.rid]:
                self._streams[req.rid].put(list(req.tokens[:n]))
                self._sent[req.rid] = n

    def _harvest_locked(self) -> None:
        if not self.batcher.finished:
            return
        done, self.batcher.finished = self.batcher.finished, {}
        for rid, toks in done.items():
            status = self.batcher.finish_status.pop(rid, "ok")
            if rid in self._abandoned:
                # Its waiter timed out and went away; keeping the answer
                # would leak it (result() registered the drop).
                self._abandoned.discard(rid)
                continue
            # Bounded terminal-status map (same oldest-first rule as the
            # batcher's request_stats): the handler reads it right after
            # result(), eviction only matters for abandoned waiters.
            while len(self._status) >= 8192:
                self._status.pop(next(iter(self._status)))
            self._status[rid] = status
            if rid in self._streams:
                # Stream consumers hold their own queue reference; drop
                # ALL engine-side state here — a streamed request never
                # calls result(), so nothing else would (unbounded growth
                # on a long-lived server otherwise; the batcher bounds
                # request_stats for the same reason).
                q = self._streams.pop(rid)
                q.put(list(toks))
                # None = finished normally; a status dict = forced finish
                # (deadline/cancel/quarantine) the handler must surface.
                q.put(None if status == "ok" else {"status": status})
                self._sent.pop(rid, None)
                self._done.pop(rid, None)
                continue
            self._answers[rid] = toks
            if rid in self._done:
                self._done[rid].set()


def _decode_pixels(payload: Dict[str, Any], cfg, event_root=None):
    """event_path (confined under --event_root) or event_b64 (inline npy)
    -> pixel frames."""
    from eventgpt_tpu.ops.image import process_event_file
    from eventgpt_tpu.utils.paths import resolve_event_path

    if "event_path" in payload:
        # Network-facing file access is allowlisted by directory: without
        # --event_root, server-local paths are disabled outright (clients
        # upload via event_b64); with it, the resolved path must stay
        # inside the root — no probing the server's filesystem. The
        # confinement logic is shared with scripts/serve_demo.py.
        path = resolve_event_path(event_root, payload["event_path"])
        try:
            _, pixels = process_event_file(
                path, cfg.num_event_frames, cfg.vision.image_size
            )
        except FileNotFoundError:
            raise ValueError(
                f"no such event file under --event_root: "
                f"{payload['event_path']}"
            )
        return pixels
    if "event_b64" in payload:
        import tempfile

        raw = base64.b64decode(payload["event_b64"])
        # Round-trip through a real file so one loader (load_event_npy's
        # restricted unpickler included) serves both entry points.
        with tempfile.NamedTemporaryFile(suffix=".npy") as f:
            f.write(raw)
            f.flush()
            _, pixels = process_event_file(
                f.name, cfg.num_event_frames, cfg.vision.image_size
            )
        return pixels
    raise ValueError("request needs event_path or event_b64")


def make_handler(engine: ServingEngine, cfg, event_root=None,
                 default_budget: int = 64,
                 max_body_bytes: int = 32 * 1024 * 1024,
                 default_deadline_s: Optional[float] = None,
                 slo_classes: Optional[Dict[str, Any]] = None):
    if slo_classes is None:
        # Server-default SLO targets per class (ISSUE 6); build_server
        # overrides from --slo_* flags. A payload "slo_class" picks one;
        # optional payload slo_ttft_s / slo_itl_s / slo_latency_s
        # override the targets for that request only.
        from eventgpt_tpu.workload import SLO

        slo_classes = {
            "interactive": SLO("interactive", ttft_s=1.0, itl_s=0.25),
            "batch": SLO("batch", latency_s=30.0),
        }

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # quiet by default
            pass

        def _json(self, code: int, obj, headers=None) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            from urllib.parse import parse_qs, urlsplit

            # Routes take query strings since ISSUE 10 (/request?rid=N,
            # /trace?rid=N); bare paths behave exactly as before.
            split = urlsplit(self.path)
            route, query = split.path, parse_qs(split.query)
            if route == "/request":
                # Flight recorder (ISSUE 10): one request's full event
                # timeline + phase decomposition + dominant miss cause.
                try:
                    rid = int(query["rid"][0])
                except (KeyError, ValueError, IndexError):
                    self._json(400, {"error": "need ?rid=N"})
                    return
                rec = engine.journey(rid)
                if rec is None:
                    self._json(404, {
                        "error": f"no journey for rid {rid} (unknown, "
                                 f"evicted from the retention ring, or "
                                 f"the recorder is disarmed — "
                                 f"--journey_keep)"})
                    return
                self._json(200, rec)
                return
            if route == "/requests":
                # Recent finished index: rid / status / slo / cause —
                # the "which request should I look at" entry point of
                # the slow-request runbook (OBSERVABILITY.md).
                try:
                    n = int(query.get("n", ["64"])[0])
                except ValueError:
                    self._json(400, {"error": "bad ?n="})
                    return
                self._json(200, {"requests": engine.journeys(n),
                                 "enabled": obs_journey.enabled()})
                return
            if route == "/series":
                # Time-series store (ISSUE 15): the sampled ring +
                # windowed derivations (?window_s=S bounds the
                # derivation window, ?n=N the returned points). Fleet
                # engines aggregate per-replica/per-worker stores.
                try:
                    window_s = (float(query["window_s"][0])
                                if "window_s" in query else None)
                    n = int(query["n"][0]) if "n" in query else None
                except (ValueError, IndexError):
                    self._json(400, {"error": "bad ?window_s= or ?n="})
                    return
                self._json(200, engine.series(window_s=window_s, n=n))
                return
            if route == "/alerts":
                # Burn-rate alert state (ISSUE 15): per-rule hysteresis
                # state + the bounded firing/clearing log — the runbook
                # entry point (/alerts -> /series -> /requests ->
                # /request?rid=N, OBSERVABILITY.md).
                self._json(200, engine.alerts())
                return
            if route == "/trace":
                tracer = obs_trace.active()
                if tracer is None:
                    self._json(404, {"error": "tracing disarmed "
                                              "(--trace_buffer 0)"})
                    return
                evs = tracer.events()
                if "rid" in query:
                    # ?rid=N filters the ring to one request's spans
                    # (ISSUE 10 satellite): the async lifecycle events
                    # carry the rid as their Chrome-trace id, and
                    # rid-stamped args match too — the device-level
                    # half of a flight-recorder timeline.
                    try:
                        rid = int(query["rid"][0])
                    except (ValueError, IndexError):
                        self._json(400, {"error": "bad ?rid="})
                        return
                    evs = [e for e in evs
                           if e.get("id") == rid
                           or (e.get("args") or {}).get("rid") == rid]
                self._json(200, {"traceEvents": evs,
                                 "droppedEvents": tracer.dropped()})
                return
            if self.path == "/health":
                if engine.breaker_open():
                    # Breaker open: the load balancer should drain this
                    # replica until the cooldown's half-open probe. The
                    # derived Retry-After (remaining cooldown, else the
                    # goodput-derived hint) rides here too, so probes
                    # and clients share one backoff story (ISSUE 11).
                    from eventgpt_tpu.fleet import retry_after_s

                    ra = getattr(engine, "breaker_retry_after_s",
                                 lambda: None)()
                    if ra is None:
                        ra = retry_after_s("batch",
                                           engine.goodput_ratio())
                    self._json(503, {"status": "degraded",
                                     "error": engine.fault,
                                     "faults": engine.n_faults,
                                     "restarts": engine.n_restarts,
                                     "retry_after_s": round(ra, 3)},
                               headers={"Retry-After":
                                        str(max(1, math.ceil(ra)))})
                    return
                s = engine.stats()
                self._json(200, {"status": "ok",
                                 "active": s["active_rows"],
                                 "queued": s["queued"],
                                 "restarts": engine.n_restarts})
            elif self.path == "/stats":
                self._json(200, engine.stats())
            elif self.path == "/fleet" and hasattr(engine, "fleet_stats"):
                # Fleet topology + routing/shedding policy + per-replica
                # health (ISSUE 7) — only mounted when the engine IS a
                # fleet router (cli fleet mode).
                self._json(200, engine.fleet_stats())
            elif self.path == "/memory":
                # HBM memory ledger (ISSUE 9): per-component bytes,
                # jax.live_arrays reconciliation (accounted/unaccounted
                # split), the static capacity estimate and the compiled
                # executable footprint. Runs outside the engine lock
                # like /metrics — pollable mid-segment.
                self._json(200, engine.memory_stats())
            elif self.path == "/prefix_cache":
                # Prefix-KV cache snapshot (ISSUE 4): entry list, byte
                # budget/usage, hit/miss/eviction counters. Lock-free
                # like /stats — the cache guards its own host-side state.
                self._json(200, engine.batcher.prefix_cache_stats())
            elif self.path == "/metrics":
                # Prometheus text exposition (scrape target). Rendering
                # walks the registry outside the engine lock — safe inside
                # a probe timeout even mid-segment, like /health.
                body = obs_metrics.REGISTRY.render_prometheus().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._json(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            if self.path not in ("/v1/generate", "/cancel", "/prefix",
                                 "/profile"):
                self._json(404, {"error": f"no route {self.path}"})
                return
            try:
                cl = self.headers.get("Content-Length")
                if cl is None:
                    # Missing Content-Length (ISSUE 11 hardening): every
                    # POST here carries a JSON body, so "no length" is
                    # either a broken client or a smuggling probe —
                    # reject instead of treating it as an empty body.
                    raise ValueError
                n = int(cl)
                if n < 0:
                    # read(-1) would block until client EOF, pinning this
                    # handler thread forever.
                    raise ValueError
            except ValueError:
                # Rejecting without reading the body desynchronizes
                # HTTP/1.1 keep-alive framing (unread body bytes would be
                # parsed as the next request line) — close the connection.
                self.close_connection = True
                self._json(400, {"error": "bad Content-Length"})
                return
            if n > max_body_bytes:
                # Reject BEFORE reading: Content-Length is attacker-
                # controlled, and decoding an arbitrarily large event_b64
                # would let any client that reaches the port allocate
                # unbounded host memory per request.
                self.close_connection = True  # unread body: see above
                self._json(413, {"error":
                                 f"body {n} bytes exceeds the "
                                 f"{max_body_bytes}-byte limit "
                                 f"(--max_body_mb)"})
                return
            if self.path == "/profile":
                # On-demand jax.profiler window on the RUNNING server:
                # {"seconds": N} captures N seconds of live traffic into
                # --profile_dir (or a fresh temp dir) and returns the
                # trace directory for TensorBoard/XProf. Blocks this
                # handler thread for the window; the scheduler keeps
                # serving — that is the traffic being profiled.
                from eventgpt_tpu.obs import profiling as obs_profiling

                try:
                    payload = json.loads(self.rfile.read(n) or b"{}")
                    seconds = float(payload.get("seconds", 2.0))
                    if not (0.0 <= seconds <= 120.0):
                        raise ValueError(
                            f"seconds must be in [0, 120], got {seconds}")
                except Exception as e:  # bad request
                    self._json(400, {"error": str(e)})
                    return
                try:
                    d = obs_profiling.capture(seconds)
                except obs_profiling.CaptureBusyError as e:
                    self._json(409, {"error": str(e)})
                    return
                except Exception as e:
                    self._json(500, {"error": str(e)})
                    return
                self._json(200, {"profile_dir": d, "seconds": seconds})
                return
            if self.path == "/cancel":
                try:
                    payload = json.loads(self.rfile.read(n) or b"{}")
                    rid = int(payload["rid"])
                except Exception as e:  # bad request
                    self._json(400, {"error": str(e)})
                    return
                self._json(200, {"rid": rid,
                                 "cancelled": engine.cancel(rid)})
                return
            if self.path == "/prefix":
                # Admin route: INSERT a prefix-KV cache entry on a
                # RUNNING server — {"prefix_prompt": str, optional
                # "event_path"/"event_b64" when the prefix runs through
                # the event block}. Since ISSUE 4 the cache is a
                # multi-entry trie, so repeated POSTs accumulate entries
                # (same key = replace) next to the ones admission prefill
                # inserts automatically; GET /prefix_cache lists them.
                try:
                    payload = json.loads(self.rfile.read(n) or b"{}")
                    prompt = payload["prefix_prompt"]
                    pixels = None
                    if "event_path" in payload or "event_b64" in payload:
                        pixels = _decode_pixels(payload, cfg, event_root)
                    plen = engine.set_prefix(prompt, pixels)
                except (KeyError, ValueError) as e:  # bad request
                    self._json(400, {"error": str(e)})
                    return
                except Exception as e:
                    self._json(500, {"error": str(e)})
                    return
                st = engine.batcher.prefix_cache_stats()
                self._json(200, {"prefix_len": plen,
                                 "entries": st.get("n_entries", 0),
                                 "bytes": st.get("bytes", 0)})
                return
            from eventgpt_tpu.fleet import FleetShedError, retry_after_s
            from eventgpt_tpu.serve import QueueFullError

            try:
                payload = json.loads(self.rfile.read(n) or b"{}")
                query = payload["query"]
                budget = int(payload.get("max_new_tokens", default_budget))
                deadline = payload.get("deadline_s", default_deadline_s)
                deadline = float(deadline) if deadline else None
                slo = None
                if "slo_class" in payload:
                    # Per-request SLO class (ISSUE 6): unknown names are
                    # the client's fault — the class set is closed
                    # (bounded metric-label cardinality).
                    name = str(payload["slo_class"])
                    if name not in slo_classes:
                        raise ValueError(
                            f"unknown slo_class {name!r}: one of "
                            f"{sorted(slo_classes)}")
                    slo = slo_classes[name]
                    overrides = {
                        k[4:]: float(payload[k])
                        for k in ("slo_ttft_s", "slo_itl_s",
                                  "slo_latency_s") if k in payload
                    }
                    if overrides:
                        import dataclasses

                        slo = dataclasses.replace(slo, **overrides)
                pixels = _decode_pixels(payload, cfg, event_root)
            except Exception as e:  # bad request, not a server fault
                self._json(400, {"error": str(e)})
                return
            stream = bool(payload.get("stream", False))
            t0 = time.perf_counter()
            try:
                rid = engine.submit(query, pixels, budget, stream=stream,
                                    deadline_s=deadline, slo=slo)
            except (QueueFullError, FleetShedError) as e:
                # Backpressure, not failure: tell the client to come
                # back (bounded admission queue — ISSUE 1; fleet shed —
                # ISSUE 7). Retry-After is CLASS-AWARE and derived from
                # the current goodput window (fleet.retry_after_s), not
                # a fixed constant: batch traffic backs off harder, and
                # both classes back off longer the further attainment
                # has sunk. A shed carries its hint on the exception;
                # queue-full derives it here from the engine's window.
                cls_name = slo.name if slo is not None else "batch"
                ra = getattr(e, "retry_after_s", None)
                if ra is None:
                    ra = retry_after_s(cls_name, engine.goodput_ratio())
                body = json.dumps({
                    "error": str(e),
                    "slo_class": cls_name,
                    "retry_after_s": round(ra, 3),
                }).encode()
                self.send_response(429)
                self.send_header("Content-Type", "application/json")
                self.send_header("Retry-After", str(max(1, math.ceil(ra))))
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            except ValueError as e:
                # submit()'s own validation (budget does not fit max_len,
                # malformed sentinel count) is still the client's fault.
                self._json(400, {"error": str(e)})
                return
            except RuntimeError as e:
                # Engine degraded (circuit breaker open): surface the loud
                # 503 /health already advertises instead of letting this
                # handler thread throw and drop the connection. Like the
                # 429 paths, the 503 carries a DERIVED Retry-After
                # (ISSUE 11 satellite): the breaker's remaining cooldown
                # when the engine knows it, else the class-aware
                # goodput-derived hint.
                cls_name = slo.name if slo is not None else "batch"
                ra = getattr(engine, "breaker_retry_after_s",
                             lambda: None)()
                if ra is None:
                    ra = retry_after_s(cls_name, engine.goodput_ratio())
                body = json.dumps({
                    "error": str(e),
                    "retry_after_s": round(ra, 3),
                }).encode()
                self.send_response(503)
                self.send_header("Content-Type", "application/json")
                self.send_header("Retry-After", str(max(1, math.ceil(ra))))
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if stream:
                try:
                    self._stream_response(rid)
                except (BrokenPipeError, ConnectionResetError, OSError):
                    # Client went away mid-stream. Never write a second
                    # status line into a started chunked body; the engine
                    # drains and drops the orphaned queue at harvest.
                    pass
                return
            try:
                toks = engine.result(rid)
            except RuntimeError as e:
                # Scheduler fault failed this request (engine restarted
                # behind it) — same 503 contract as a refused submit.
                self._json(503, {"error": str(e)})
                return
            except Exception as e:
                self._json(500, {"error": str(e)})
                return
            try:
                text = engine.tokenizer.batch_decode(
                    [toks], skip_special_tokens=True
                )[0].strip()
                status = engine.status(rid)
                stats = engine.batcher.request_stats.get(rid, {})
                obj = {
                    "answer": text, "tokens": len(toks), "rid": rid,
                    "status": status,
                    "ttft_s": round(stats.get("ttft_s", 0.0), 3),
                    "latency_s": round(
                        stats.get("latency_s",
                                  time.perf_counter() - t0), 3),
                }
                if slo is not None:
                    obj["slo_class"] = slo.name
                    if "slo_met" in stats:
                        obj["slo_met"] = bool(stats["slo_met"])
                if payload.get("debug"):
                    # Flight recorder (ISSUE 10): {"debug": true} rides
                    # the request's own response with its full timeline
                    # + phase decomposition — no second round trip to
                    # /request?rid=N needed while debugging a client.
                    obj["debug"] = engine.journey(rid)
                # Forced finishes map to structured HTTP errors (the
                # partial answer rides along): deadline -> 504,
                # cancel -> 499 (client asked), NaN quarantine -> 500,
                # resource exhaustion (block pool AND spill budget both
                # spent — ISSUE 16) -> 503 with the same derived
                # Retry-After the breaker/shed paths carry.
                code = {"ok": 200, "deadline_exceeded": 504,
                        "cancelled": 499,
                        "resource_exhausted": 503,
                        "nan_quarantined": 500}.get(status, 500)
                if code != 200:
                    obj["error"] = status
                if code == 503:
                    cls_name = slo.name if slo is not None else "batch"
                    ra = getattr(engine, "breaker_retry_after_s",
                                 lambda: None)()
                    if ra is None:
                        ra = retry_after_s(cls_name,
                                           engine.goodput_ratio())
                    obj["retry_after_s"] = round(ra, 3)
                    self._json(code, obj, headers={
                        "Retry-After": str(max(1, math.ceil(ra)))})
                else:
                    self._json(code, obj)
            except Exception as e:
                self._json(500, {"error": str(e)})

        def _stream_response(self, rid: int) -> None:
            """Chunked transfer: one JSON line per delta. Deltas re-decode
            the cumulative prefix each time, and hold back any trailing
            U+FFFD replacement chars: a multibyte char split across decode
            segments first decodes as \\ufffd and is REPLACED in the next
            cumulative decode — emitted eagerly it would corrupt the
            stream (a chunked body cannot retract bytes). Stripped tails
            that never resolve (genuinely invalid bytes) flush in the
            terminal delta. When a longer decode REWRITES earlier text
            (sentencepiece whitespace/detokenization effects make the
            cumulative decode non-prefix-stable), a corrective
            ``{"restart": full_text}`` event replaces the client's buffer
            — so apply(deltas ∘ restarts) == the final answer always."""
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

            def chunk(obj) -> None:
                line = (json.dumps(obj) + "\n").encode()
                self.wfile.write(f"{len(line):x}\r\n".encode())
                self.wfile.write(line + b"\r\n")

            sent = ""

            def emit(new_text: str) -> None:
                nonlocal sent
                if new_text == sent:
                    return
                if new_text.startswith(sent):
                    chunk({"delta": new_text[len(sent):], "rid": rid})
                else:
                    chunk({"restart": new_text, "rid": rid})
                sent = new_text

            q = engine.stream_queue(rid)
            text = ""
            while True:
                toks = q.get()
                if toks is None:
                    break
                if isinstance(toks, dict):
                    if "status" in toks:  # forced finish (deadline/
                        break             # cancel/quarantine): terminal
                    chunk({"done": True, "rid": rid,  # engine fault
                           "error": toks["fault"],
                           "answer": sent.strip()})
                    self.wfile.write(b"0\r\n\r\n")
                    return
                text = engine.tokenizer.batch_decode(
                    [toks], skip_special_tokens=True
                )[0]
                emit(text.rstrip("�"))
            emit(text)  # flush any held-back tail, rewritten or not
            status = engine.status(rid)
            final = {"done": True, "rid": rid, "answer": sent.strip(),
                     "status": status}
            if status != "ok":
                final["error"] = status
            chunk(final)
            self.wfile.write(b"0\r\n\r\n")

    return Handler


# Every flag that shapes a worker's batcher/engine MUST cross the
# process boundary to --worker processes (workers load their own model
# and build their own engine — separate processes share no state). The
# forwarding is DECLARED here, not buried in an argv builder, so the
# regression guard (tests/test_fleet_proc.py::test_worker_argv_*) can
# assert two things mechanically: (1) every entry round-trips through a
# fully-populated args namespace, and (2) every parser flag is
# classified — forwarded, coordinator-only, or per-slot — so a new
# serving flag cannot silently stay coordinator-side (the bug class
# that once ran paged-pool workers dense).
#
# Kinds: "value"  — always forwarded as --dest str(value);
#        "opt"    — forwarded only when set (None/empty skipped);
#        "flag"   — store_true, forwarded only when truthy.
WORKER_FORWARDED_FLAGS = (
    ("model_path", "value", "tiny-random"),
    ("conv_mode", "value", "eventgpt_v1"),
    ("dtype", "value", "bfloat16"),
    ("quant", "value", "none"),
    ("kv_cache", "value", "bf16"),
    ("kv_layout", "value", "dense"),
    ("kv_pool_blocks", "value", 0),
    ("spill_capacity_mb", "value", 0),
    ("max_batch", "value", 4),
    ("max_len", "value", 1024),
    ("chunk", "value", 128),
    ("temperature", "value", 0.0),
    ("speculative", "value", 0),
    ("prefill_chunk", "value", 0),
    ("prefill_budget", "value", -1),
    ("first_chunk", "value", 0),
    ("max_queue", "value", 256),
    ("prefix_cache_mb", "value", 512.0),
    ("mem_headroom_mb", "value", 0.0),
    ("mem_capacity_mb", "value", 0.0),
    ("breaker_threshold", "value", 3),
    ("breaker_cooldown_s", "value", 5.0),
    ("slo_window", "value", 256),
    ("journey_keep", "value", 512),
    ("series_interval_s", "value", 1.0),
    ("series_keep", "value", 512),
    ("spec_ema_alpha", "value", 0.3),
    ("spec_draft_cost", "value", 0.05),
    ("spec_row_window", "value", 4),
    ("spec_head_min_yield", "value", 0.05),
    ("spec_buckets", "opt", ""),
    ("tokenizer_path", "opt", None),
    ("draft_head", "opt", None),
    ("preempt", "flag", False),
    ("fuse_params", "flag", False),
    ("no_pipeline", "flag", False),
    ("no_prefix_cache", "flag", False),
    ("no_telemetry", "flag", False),
    ("warmup", "flag", False),
)

# Parser flags that deliberately do NOT cross to workers: the HTTP
# front-end, fleet topology/policy (the coordinator owns routing), the
# coordinator-side telemetry sinks, and knobs whose payloads ride the
# RPC ops instead of argv (SLO targets travel inside each submit's SLO
# object; --faults crosses via the inherited EGPT_FAULTS env var;
# --prefix_prompt installs through the set_prefix op). Mesh flags stay
# here too: a proc-fleet worker owns a single-chip mesh — the
# multi-host sharded-generate leg is the ROADMAP's open half.
WORKER_COORDINATOR_ONLY = frozenset({
    "host", "port", "event_root", "max_body_mb", "max_new_tokens",
    "default_deadline_s", "prefix_prompt", "prefix_event",
    "heartbeat_dir",  # per-slot: _spawn appends the slot's own dir
    "fleet", "proc_fleet", "proc_fleet_roles", "drain_timeout_s",
    "fleet_shed_goodput", "fleet_shed_queue", "fleet_probe_interval_s",
    "fleet_heartbeat_stale_s", "fleet_restart_s",
    "procfleet_rpc_deadline_s", "procfleet_rpc_retries",
    "procfleet_spawn_timeout_s", "procfleet_respawn_backoff_s",
    "procfleet_crash_window_s", "procfleet_crash_limit",
    "procfleet_handoff_retries",
    "slo_interactive_ttft_s", "slo_interactive_itl_s",
    "slo_batch_latency_s",
    "trace_buffer", "trace_out", "profile_dir", "faults",
    "mesh_data", "mesh_fsdp", "mesh_model",
    "use_event_qformer", "pretrain_query_embedder",
    "pretrain_attention_layers",
})

# Flags the coordinator appends PER SLOT in fleet_proc._spawn (never
# taken from the coordinator's own namespace): the worker marker, the
# readiness handshake, the slot index, and the slot's serving role.
WORKER_PER_SLOT = frozenset({
    "worker", "worker_ready_file", "worker_slot", "role",
})


def _worker_argv(args) -> list:
    """The worker process's command line: the coordinator's own model +
    engine flags, re-serialized behind ``--worker`` from the
    ``WORKER_FORWARDED_FLAGS`` declaration above."""
    import sys

    argv = [sys.executable, "-m", "eventgpt_tpu.cli.serve", "--worker"]
    for dest, kind, default in WORKER_FORWARDED_FLAGS:
        val = getattr(args, dest, default)
        if kind == "flag":
            if val:
                argv.append(f"--{dest}")
        elif kind == "opt":
            if val:
                argv += [f"--{dest}", str(val)]
        else:
            argv += [f"--{dest}", str(val)]
    return argv


def build_engine(args, force_single: bool = False):
    """(cfg, engine) — everything below the HTTP layer: telemetry
    arming, model load, batcher/engine construction, and the fleet
    tiers (``--fleet N`` threads, ``--proc_fleet N`` worker processes).
    Shared by ``build_server`` and the process-fleet ``--worker``
    entrypoint (``force_single`` makes a worker build exactly one
    engine whatever the fleet flags say — a worker must never recurse
    into spawning its own fleet)."""
    from eventgpt_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()
    # Telemetry arming (ISSUE 3): metrics are on unless --no_telemetry;
    # the span tracer keeps a bounded ring (0 disarms); --profile_dir
    # arms the jax.profiler annotations and sets the POST /profile
    # destination. All three are chain-neutral — they read clocks, never
    # jax values (tests/test_obs.py::test_chain_neutrality).
    if getattr(args, "no_telemetry", False):
        obs_metrics.configure(False)
        obs_trace.disable()
        obs_journey.disable()
        obs_series.disable()
    else:
        buf = int(getattr(args, "trace_buffer", 65536) or 0)
        if buf > 0:
            obs_trace.configure(buf)
        # Flight recorder (ISSUE 10): last N finished request
        # timelines, armed like the span tracer (0 disarms; disarmed =
        # one global check per probe, chains byte-identical either way).
        keep = int(getattr(args, "journey_keep", 512) or 0)
        if keep > 0:
            obs_journey.configure(keep)
        # Time-series store + burn-rate alerts (ISSUE 15): samples the
        # registry on a fixed cadence into a bounded ring and evaluates
        # ALERT_RULES each tick (0 disarms either flag; armed cost is
        # one registry read per interval, chain-neutral like the rest).
        interval = float(getattr(args, "series_interval_s", 1.0) or 0.0)
        skeep = int(getattr(args, "series_keep", 512) or 0)
        if interval > 0 and skeep > 0:
            cap_mb = float(getattr(args, "mem_capacity_mb", 0.0) or 0.0)
            obs_series.configure(
                interval_s=interval, keep=skeep,
                mem_capacity_bytes=(int(cap_mb * 2 ** 20)
                                    if cap_mb > 0 else None))
    if getattr(args, "profile_dir", None):
        from eventgpt_tpu.obs import profiling as obs_profiling

        obs_profiling.configure(args.profile_dir)
    if getattr(args, "faults", None):
        # Arm fault injection from the CLI (EGPT_FAULTS works too): chaos
        # drills against a live server use the same spec grammar as tests.
        faults.configure(getattr(args, "faults"))
    n_proc = int(getattr(args, "proc_fleet", 0) or 0)
    if n_proc > 1 and not force_single:
        # Process-fleet mode (ISSUE 11): the coordinator loads NO model
        # — workers own their engines in their own processes (separate
        # failure domains, the whole point). It only needs the config
        # (pixel preprocessing in the handler) and a tokenizer (submit
        # + routing key).
        from eventgpt_tpu.data.tokenizer import load_tokenizer
        from eventgpt_tpu.fleet_proc import ProcFleet

        if (getattr(args, "proc_fleet_roles", None)
                and getattr(args, "kv_layout", "dense") != "paged"):
            # Fail HERE, not as a worker crash loop: the handoff moves
            # block runs, so split roles without the paged layout can
            # never boot.
            raise ValueError(
                "--proc_fleet_roles requires --kv_layout paged (the "
                "prefill->decode handoff ships paged-KV block runs)")
        if args.model_path == "tiny-random":
            from eventgpt_tpu.config import EventChatConfig

            cfg = EventChatConfig.tiny()
            tokenizer = load_tokenizer("byte")
        else:
            import json as _json
            import os as _os

            from eventgpt_tpu.models.convert import from_hf_config

            with open(_os.path.join(args.model_path,
                                    "config.json")) as f:
                cfg = from_hf_config(_json.load(f))
            tokenizer = load_tokenizer(
                getattr(args, "tokenizer_path", None) or args.model_path)
        engine = ProcFleet(
            _worker_argv(args), n_proc,
            tokenizer=tokenizer, conv_mode=args.conv_mode,
            # Prefill/decode disaggregation (ISSUE 17): "P:D" splits the
            # worker pool into roles; unset = every worker colocated.
            roles=getattr(args, "proc_fleet_roles", None) or None,
            handoff_retries=int(getattr(args, "procfleet_handoff_retries",
                                        3)),
            heartbeat_dir=getattr(args, "heartbeat_dir", None),
            probe_interval_s=getattr(args, "fleet_probe_interval_s",
                                     0.05),
            heartbeat_stale_s=getattr(args, "fleet_heartbeat_stale_s",
                                      5.0),
            rpc_deadline_s=getattr(args, "procfleet_rpc_deadline_s",
                                   15.0),
            rpc_retries=int(getattr(args, "procfleet_rpc_retries", 3)),
            spawn_timeout_s=getattr(args, "procfleet_spawn_timeout_s",
                                    180.0),
            respawn_backoff_s=getattr(args,
                                      "procfleet_respawn_backoff_s",
                                      0.25),
            crash_window_s=getattr(args, "procfleet_crash_window_s",
                                   60.0),
            crash_limit=int(getattr(args, "procfleet_crash_limit", 3)),
            shutdown_drain_s=getattr(args, "drain_timeout_s", 30.0),
        )
        return cfg, engine
    from eventgpt_tpu.cli.infer import load_model, prepare_model
    from eventgpt_tpu.parallel.serving import build_serving_mesh
    from eventgpt_tpu.serve import ContinuousBatcher

    cfg, params, tokenizer = load_model(
        args.model_path, args.dtype, None, args.tokenizer_path
    )
    # prepare_model places the host tree straight onto the mesh — a
    # post-hoc reshard would first materialize the full unsharded tree in
    # one chip's HBM (exactly what the mesh path exists to avoid at 7B+).
    mesh = build_serving_mesh(args.mesh_data, args.mesh_fsdp, args.mesh_model)
    cfg, params = prepare_model(cfg, params, tokenizer, args, mesh=mesh)
    draft_head = None
    if getattr(args, "draft_head", None):
        from eventgpt_tpu.models.medusa import load_medusa

        draft_head = load_medusa(args.draft_head)

    def _make_batcher():
        return ContinuousBatcher(
            params, cfg, max_batch=args.max_batch, max_len=args.max_len,
            chunk=args.chunk, temperature=args.temperature,
            eos_token_id=getattr(tokenizer, "eos_token_id", None),
            kv_quant=args.kv_cache == "int8", speculative=args.speculative,
            mesh=mesh, prefill_chunk=args.prefill_chunk,
            draft_head=draft_head,
            first_chunk=getattr(args, "first_chunk", 0),
            max_queue=getattr(args, "max_queue", 0),
            pipeline=not getattr(args, "no_pipeline", False),
            prefix_cache=not getattr(args, "no_prefix_cache", False),
            prefix_cache_bytes=int(
                getattr(args, "prefix_cache_mb", 512.0) * 1024 * 1024),
            # Stall-free admission (ISSUE 5): -1 = auto (one segment's
            # worth of prompt tokens per boundary), 0 = off (waves).
            prefill_budget=(args.chunk
                            if getattr(args, "prefill_budget", -1) < 0
                            else int(args.prefill_budget)),
            slo_window=int(getattr(args, "slo_window", 256)),
            # Memory headroom guard (ISSUE 9): 0 disarms (the default);
            # capacity 0 = the device's own reported limit.
            mem_headroom_bytes=int(
                getattr(args, "mem_headroom_mb", 0.0) * 1024 * 1024),
            mem_capacity_bytes=int(
                getattr(args, "mem_capacity_mb", 0.0) * 1024 * 1024),
            # Paged KV block pool (ISSUE 12): block-granular allocation
            # + used-token admission; "dense" is the A/B escape hatch.
            kv_layout=getattr(args, "kv_layout", "dense"),
            kv_pool_blocks=int(getattr(args, "kv_pool_blocks", 0)),
            # Block-tier preemption + host-RAM KV spill (ISSUE 16):
            # under block exhaustion an interactive admission preempts
            # the lowest-value batch row (spill-or-recompute priced per
            # victim) instead of deferring behind it.
            preempt=bool(getattr(args, "preempt", False)),
            spill_capacity_mb=int(getattr(args, "spill_capacity_mb", 0)),
            # Adaptive speculation (ISSUE 13): empty = fixed-K serving.
            spec_buckets=getattr(args, "spec_buckets", None) or None,
            spec_ema_alpha=float(getattr(args, "spec_ema_alpha", 0.3)),
            spec_draft_cost=float(getattr(args, "spec_draft_cost", 0.05)),
            spec_row_window=int(getattr(args, "spec_row_window", 4)),
            spec_head_min_yield=float(
                getattr(args, "spec_head_min_yield", 0.05)),
            # Disaggregated serving role (ISSUE 17): per-worker, set by
            # the coordinator's _spawn; colocated everywhere else.
            role=getattr(args, "role", "colocated"),
        )

    def _make_engine(batcher, hb_dir):
        return ServingEngine(
            batcher, tokenizer, args.conv_mode,
            breaker_threshold=getattr(args, "breaker_threshold", 3),
            breaker_cooldown_s=getattr(args, "breaker_cooldown_s", 5.0),
            heartbeat_dir=hb_dir,
            trace_out=getattr(args, "trace_out", None),
        )

    n_fleet = 0 if force_single else int(getattr(args, "fleet", 0) or 0)
    hb_root = getattr(args, "heartbeat_dir", None)
    if n_fleet > 1:
        # Fleet mode (ISSUE 7): N in-process replicas (one weight tree,
        # N resident caches/schedulers — the jit cache shares their
        # executables) behind the prefix-affinity router. The handler
        # serves the router through the same engine surface.
        import os as _os

        from eventgpt_tpu.fleet import Fleet

        batchers = [_make_batcher() for _ in range(n_fleet)]
        if args.warmup:
            t0 = time.perf_counter()
            n = sum(b.warmup() for b in batchers)
            print(f"[serve] warmup: {n} executables in "
                  f"{time.perf_counter() - t0:.1f}s")
        engines = [
            _make_engine(b, _os.path.join(hb_root, f"replica{i}")
                         if hb_root else None)
            for i, b in enumerate(batchers)
        ]
        engine = Fleet(
            engines, tokenizer, args.conv_mode,
            probe_interval_s=getattr(args, "fleet_probe_interval_s", 0.05),
            heartbeat_stale_s=getattr(args, "fleet_heartbeat_stale_s", 5.0),
            shed_goodput_ratio=getattr(args, "fleet_shed_goodput", 0.5),
            shed_queue_depth=getattr(args, "fleet_shed_queue", 0),
            replica_restart_s=getattr(args, "fleet_restart_s", 0) or None,
        )
    else:
        batcher = _make_batcher()
        if args.warmup:
            t0 = time.perf_counter()
            n = batcher.warmup()
            print(f"[serve] warmup: {n} executables in "
                  f"{time.perf_counter() - t0:.1f}s")
        engine = _make_engine(batcher, hb_root)
    if getattr(args, "prefix_prompt", None):
        # Startup form of POST /prefix: cache the shared prompt head's KV
        # once, before traffic. --prefix_event supplies the stream when
        # the prefix text carries the <event> placeholder.
        pixels = None
        if getattr(args, "prefix_event", None):
            from eventgpt_tpu.ops.image import process_event_file

            _, pixels = process_event_file(
                args.prefix_event, cfg.num_event_frames,
                cfg.vision.image_size,
            )
        plen = engine.set_prefix(args.prefix_prompt, pixels)
        print(f"[serve] shared prefix cached: {plen} positions")
    return cfg, engine


def build_server(args) -> tuple:
    """(ThreadingHTTPServer, engine) — separated from main() so tests
    can run the real stack in-process on an ephemeral port. The engine
    may be a single ``ServingEngine``, a thread ``Fleet`` or a
    ``ProcFleet`` coordinator; the handler serves all three through
    the same surface."""
    cfg, engine = build_engine(args)
    default_deadline = getattr(args, "default_deadline_s", 0) or None
    # Per-class SLO targets (ISSUE 6): a payload {"slo_class": ...}
    # scores the request against these at finish (0 disarms a target).
    from eventgpt_tpu.workload import SLO

    slo_classes = {
        "interactive": SLO(
            "interactive",
            ttft_s=getattr(args, "slo_interactive_ttft_s", 1.0) or None,
            itl_s=getattr(args, "slo_interactive_itl_s", 0.25) or None),
        "batch": SLO(
            "batch",
            latency_s=getattr(args, "slo_batch_latency_s", 30.0) or None),
    }
    httpd = ThreadingHTTPServer(
        (args.host, args.port),
        make_handler(engine, cfg, getattr(args, "event_root", None),
                     default_budget=getattr(args, "max_new_tokens", 64),
                     max_body_bytes=int(
                         getattr(args, "max_body_mb", 32) * 1024 * 1024),
                     default_deadline_s=default_deadline,
                     slo_classes=slo_classes),
    )
    return httpd, engine


def build_parser() -> argparse.ArgumentParser:
    """The serving CLI's full argparse surface, separated from main()
    so the worker-argv regression guard can enumerate every flag and
    assert it is classified (WORKER_FORWARDED_FLAGS /
    WORKER_COORDINATOR_ONLY / WORKER_PER_SLOT)."""
    p = argparse.ArgumentParser()
    p.add_argument("--model_path", default="tiny-random")
    p.add_argument("--tokenizer_path", default=None)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8600)
    p.add_argument("--event_root", default=None,
                   help="directory event_path requests resolve under; "
                        "unset = server-local paths disabled (event_b64 "
                        "only)")
    p.add_argument("--conv_mode", default="eventgpt_v1")
    p.add_argument("--max_body_mb", type=float, default=32.0,
                   help="largest accepted POST body (413 above this); size "
                        "for the biggest event_b64 upload you expect")
    p.add_argument("--max_batch", type=int, default=4)
    p.add_argument("--max_len", type=int, default=1024)
    p.add_argument("--chunk", type=int, default=128)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--max_new_tokens", type=int, default=64)
    p.add_argument("--dtype", default="bfloat16",
                   choices=["bfloat16", "float32"])
    p.add_argument("--quant", default="none", choices=["none", "int8", "int4"])
    p.add_argument("--fuse_params", action="store_true",
                   help="fuse qkv / gate-up before quantization (+4%% at "
                        "wide batches, neutral at batch 1 — PERFORMANCE.md)")
    p.add_argument("--kv_cache", default="bf16", choices=["bf16", "int8"])
    p.add_argument("--kv_layout", default="dense",
                   choices=["dense", "paged"],
                   help="resident KV layout (ISSUE 12): 'paged' replaces "
                        "the dense (batch, max_len) cache with one "
                        "SEQ_BUCKET-block pool + per-row block tables — "
                        "admission gated by free blocks (used tokens), "
                        "prefix hits alias block runs with copy-on-"
                        "write. Chains are byte-identical to 'dense' "
                        "(the A/B escape hatch)")
    p.add_argument("--kv_pool_blocks", type=int, default=0,
                   help="paged pool size in blocks incl. the scratch "
                        "block (0 = dense-equivalent capacity: "
                        "max_batch * max_len/SEQ_BUCKET + 1). Size it by "
                        "expected USED tokens, not worst case — "
                        "GET /memory's kv_blocks shows live pressure")
    p.add_argument("--preempt", action="store_true",
                   help="block-tier preemption (ISSUE 16, paged layout "
                        "only): when free blocks cannot cover an "
                        "interactive admission, preempt the lowest-value "
                        "batch row (worst deadline headroom first) "
                        "instead of deferring the head behind it. Each "
                        "victim either spills its KV run to host RAM "
                        "(--spill_capacity_mb) or drops and re-prefills "
                        "— whichever the measured bytes-vs-FLOPs price "
                        "says is cheaper. Chains stay byte-identical on "
                        "both paths")
    p.add_argument("--spill_capacity_mb", type=int, default=0,
                   help="host-RAM budget for preempted KV runs (0 = no "
                        "spill store: every preemption drops and "
                        "re-prefills). Spilled bytes show on GET /memory "
                        "under the 'spill' component and "
                        "egpt_serve_spill_store_bytes")
    p.add_argument("--speculative", type=int, default=0)
    p.add_argument("--spec_buckets", default="",
                   help="adaptive speculation (ISSUE 13): comma-separated "
                        "draft-window buckets, e.g. '0,2,4,8' (0 = the "
                        "draft-free fallback segment). Each dispatch "
                        "boundary selects one precompiled bucket from the "
                        "measured acceptance EMA and masks low-acceptance "
                        "rows' drafts; --speculative becomes the default/"
                        "fault-degradation window (max bucket when 0). "
                        "Empty = fixed-K serving")
    p.add_argument("--spec_ema_alpha", type=float, default=0.3,
                   help="acceptance-EMA step per harvested segment")
    p.add_argument("--spec_draft_cost", type=float, default=0.05,
                   help="relative marginal verify cost per draft position "
                        "(the controller's cost model: ~0 when decode is "
                        "weight-streaming bound, higher on small models)")
    p.add_argument("--spec_row_window", type=int, default=4,
                   help="per-row acceptance window (segments) behind the "
                        "per-row draft-depth mask")
    p.add_argument("--spec_head_min_yield", type=float, default=0.05,
                   help="prune draft heads/lookup levels whose realized "
                        "yield EMA falls below this")
    p.add_argument("--draft_head", default=None,
                   help="trained Medusa head stack (.npz) for speculative "
                        "drafting (requires --speculative > 0 or "
                        "--spec_buckets)")
    p.add_argument("--prefill_chunk", type=int, default=0)
    p.add_argument("--prefill_budget", type=int, default=-1,
                   help="stall-free admission (ISSUE 5): prompt tokens "
                        "folded into each decode dispatch as piggyback "
                        "prefill lanes while rows are decoding (mixed "
                        "segments). -1 = auto (--chunk tokens per "
                        "boundary, the default); 0 = off — every "
                        "admission runs the exclusive wave/suffix path "
                        "(the A/B escape hatch)")
    p.add_argument("--first_chunk", type=int, default=0,
                   help="TTFT ramp: short segment length while a fresh "
                        "admission owes its first token (0 = off; "
                        "PERFORMANCE.md serving section for the tradeoff)")
    p.add_argument("--warmup", action="store_true")
    p.add_argument("--no_pipeline", action="store_true",
                   help="disable pipelined scheduling (dispatch segment "
                        "N+1 from device-resident state while the host "
                        "harvests segment N); the synchronous escape "
                        "hatch — chains are byte-identical either way")
    p.add_argument("--prefix_prompt", default=None,
                   help="shared prompt-prefix text cached once at startup "
                        "(ContinuousBatcher.set_prefix); may contain the "
                        "<event> placeholder if --prefix_event supplies "
                        "its stream. Also settable at runtime via "
                        "POST /prefix")
    p.add_argument("--prefix_event", default=None,
                   help="event .npy backing the <event> block inside "
                        "--prefix_prompt (prefix-through-event-block "
                        "sessions; suffixes then skip CLIP encode)")
    p.add_argument("--prefix_cache_mb", type=float, default=512.0,
                   help="HBM byte budget for the prefix-KV cache (LRU "
                        "eviction above it; 0 = unbounded). The cache "
                        "populates itself on admission prefill and via "
                        "POST /prefix; GET /prefix_cache shows it")
    p.add_argument("--no_prefix_cache", action="store_true",
                   help="disable the prefix-KV cache entirely (every "
                        "admission full-prefills; the A/B escape hatch — "
                        "chains are byte-identical either way)")
    # -- HBM memory ledger + admission headroom (ISSUE 9) --
    p.add_argument("--mem_headroom_mb", type=float, default=0.0,
                   help="admission headroom guard: defer admission "
                        "waves while the memory ledger predicts the "
                        "next wave would leave less than this many MB "
                        "of device capacity free (0 = off, the A/B "
                        "escape hatch; GET /memory shows the ledger)")
    p.add_argument("--mem_capacity_mb", type=float, default=0.0,
                   help="device capacity the headroom guard budgets "
                        "against (0 = the device's own reported "
                        "bytes_limit; CPU reports none, so set this "
                        "explicitly there)")
    # -- request-lifecycle hardening (ISSUE 1) --
    p.add_argument("--max_queue", type=int, default=256,
                   help="admission-queue bound: submits beyond this get "
                        "429 + Retry-After (0 = unbounded)")
    p.add_argument("--default_deadline_s", type=float, default=0.0,
                   help="per-request deadline applied when the payload "
                        "has no deadline_s (0 = none); expiry returns 504 "
                        "with the tokens committed so far")
    p.add_argument("--breaker_threshold", type=int, default=3,
                   help="consecutive scheduler faults that trip the "
                        "circuit breaker (health -> degraded, POSTs 503)")
    p.add_argument("--breaker_cooldown_s", type=float, default=5.0,
                   help="seconds the tripped breaker refuses work before "
                        "the half-open probe admits traffic again")
    p.add_argument("--heartbeat_dir", default=None,
                   help="directory for the serving heartbeat.json "
                        "(train/resilience.py format; unset = disabled)")
    # -- fleet serving (ISSUE 7; DISTRIBUTED.md "Fleet serving") --
    p.add_argument("--fleet", type=int, default=0,
                   help="run N ServingEngine replicas behind the "
                        "prefix-affinity router (0/1 = single engine). "
                        "Replicas share the weight tree; each owns its "
                        "resident KV cache and scheduler thread")
    # -- process fleet (ISSUE 11; DISTRIBUTED.md "Process fleet") --
    p.add_argument("--proc_fleet", type=int, default=0,
                   help="run N worker PROCESSES (each a full "
                        "ServingEngine + model + jax runtime) behind "
                        "the RPC coordinator (0/1 = single engine). "
                        "Separate failure domains: a worker death is "
                        "drained/redone onto survivors and the slot "
                        "respawns with backoff")
    p.add_argument("--proc_fleet_roles", default=None,
                   help="prefill/decode disaggregation (ISSUE 17): "
                        "'P:D' splits the --proc_fleet workers into P "
                        "prefill-role workers (admission only; each "
                        "activated row's paged-KV block run is gathered "
                        "and shipped) and D decode-role workers (splice "
                        "the shipped run into their own arena and "
                        "decode). P+D must equal --proc_fleet; requires "
                        "--kv_layout paged. Unset = every worker "
                        "colocated (the default, unchanged). Greedy "
                        "chains are byte-identical either way")
    p.add_argument("--procfleet_handoff_retries", type=int, default=3,
                   help="decode workers a shipped handoff is tried "
                        "against before the coordinator falls back to "
                        "REDO (re-submit from its own record)")
    p.add_argument("--role", default="colocated",
                   choices=["colocated", "prefill", "decode"],
                   help="this worker's serving role (set per slot by "
                        "the --proc_fleet_roles coordinator; not a "
                        "user-facing flag)")
    p.add_argument("--worker", action="store_true",
                   help="run as one process-fleet worker: build a "
                        "single engine and serve the length-prefixed "
                        "JSON-over-TCP RPC ops instead of HTTP "
                        "(spawned by the --proc_fleet coordinator; "
                        "needs --worker_ready_file)")
    p.add_argument("--worker_ready_file", default=None,
                   help="path the worker writes its "
                        "{port, pid} readiness handshake to")
    p.add_argument("--worker_slot", type=int, default=0,
                   help="the coordinator slot index this worker fills "
                        "(informational: logs/heartbeat labelling)")
    p.add_argument("--drain_timeout_s", type=float, default=30.0,
                   help="graceful-shutdown bound: seconds SIGTERM/"
                        "SIGINT (and proc-fleet coordinator shutdown) "
                        "waits for in-flight requests before exiting")
    p.add_argument("--procfleet_rpc_deadline_s", type=float, default=15.0,
                   help="per-op RPC deadline the coordinator gives a "
                        "worker call (connect + send + response)")
    p.add_argument("--procfleet_rpc_retries", type=int, default=3,
                   help="transport-failure retries per RPC call "
                        "(exponential backoff + jitter under the "
                        "deadline; mutating ops never retry after "
                        "their bytes were sent)")
    p.add_argument("--procfleet_spawn_timeout_s", type=float,
                   default=180.0,
                   help="seconds a spawned worker may take to become "
                        "ready before the slot books a crash")
    p.add_argument("--procfleet_respawn_backoff_s", type=float,
                   default=0.25,
                   help="initial per-slot respawn backoff after a "
                        "worker death (doubles per consecutive crash)")
    p.add_argument("--procfleet_crash_window_s", type=float, default=60.0,
                   help="crash-loop window: crashes older than this "
                        "stop counting toward the breaker")
    p.add_argument("--procfleet_crash_limit", type=int, default=3,
                   help="crashes inside the window that trip the "
                        "slot's crash-loop breaker (the fleet gives "
                        "the slot up and degrades capacity)")
    p.add_argument("--fleet_shed_goodput", type=float, default=0.5,
                   help="shed batch-class requests while the aggregate "
                        "windowed goodput ratio is below this "
                        "(0 disarms the goodput signal)")
    p.add_argument("--fleet_shed_queue", type=int, default=0,
                   help="shed batch-class requests while the aggregate "
                        "queued-request count is at/above this "
                        "(0 disarms the queue-depth signal)")
    p.add_argument("--fleet_probe_interval_s", type=float, default=0.05,
                   help="supervisor health-probe / collection period")
    p.add_argument("--fleet_heartbeat_stale_s", type=float, default=5.0,
                   help="replica heartbeat age that marks it unroutable "
                        "(fleet mode writes per-replica heartbeats under "
                        "--heartbeat_dir/replicaN)")
    p.add_argument("--fleet_restart_s", type=float, default=0.0,
                   help="auto-revive a killed replica after this many "
                        "seconds (0 = operator restart only)")
    # -- SLO classes + goodput (ISSUE 6; OBSERVABILITY.md) --
    p.add_argument("--slo_interactive_ttft_s", type=float, default=1.0,
                   help="interactive-class TTFT target scored at finish "
                        "(payload slo_class=interactive; 0 disarms)")
    p.add_argument("--slo_interactive_itl_s", type=float, default=0.25,
                   help="interactive-class mean inter-token-gap target "
                        "(0 disarms)")
    p.add_argument("--slo_batch_latency_s", type=float, default=30.0,
                   help="batch-class end-to-end latency target "
                        "(payload slo_class=batch; 0 disarms)")
    p.add_argument("--slo_window", type=int, default=256,
                   help="finished SLO-classed requests in the windowed "
                        "goodput gauge egpt_serve_slo_goodput_ratio")
    # -- telemetry (ISSUE 3; OBSERVABILITY.md) --
    p.add_argument("--journey_keep", type=int, default=512,
                   help="flight recorder: retain the last N finished "
                        "request timelines (GET /requests, "
                        "GET /request?rid=N, per-request debug blocks "
                        "and the egpt_serve_slo_miss_cause_total "
                        "attribution ride it; 0 disarms)")
    p.add_argument("--series_interval_s", type=float, default=1.0,
                   help="time-series store sampling cadence: one "
                        "registry sample + alert-rule evaluation per "
                        "interval (GET /series, GET /alerts; 0 disarms "
                        "the store and the burn-rate alerts)")
    p.add_argument("--series_keep", type=int, default=512,
                   help="time-series ring length in samples (bounded "
                        "retention: keep x interval seconds of history; "
                        "0 disarms)")
    p.add_argument("--trace_buffer", type=int, default=65536,
                   help="request/step trace ring capacity in events "
                        "(GET /trace snapshots it; 0 disarms tracing)")
    p.add_argument("--trace_out", default=None,
                   help="write the trace ring as Chrome trace events "
                        "(Perfetto / chrome://tracing) at shutdown")
    p.add_argument("--profile_dir", default=None,
                   help="destination for POST /profile jax.profiler "
                        "captures; setting it also arms the per-segment "
                        "profiler annotations (unset: captures go to a "
                        "temp dir)")
    p.add_argument("--no_telemetry", action="store_true",
                   help="disarm the metrics registry and the span tracer "
                        "(A/B switch; chains are byte-identical either "
                        "way — the registry just stops counting)")
    p.add_argument("--faults", default=None,
                   help="arm deterministic fault injection, e.g. "
                        "'serve.step:n=5' (see eventgpt_tpu/faults.py; "
                        "EGPT_FAULTS env var equivalent)")
    p.add_argument("--mesh_data", type=int, default=1)
    p.add_argument("--mesh_fsdp", type=int, default=1)
    p.add_argument("--mesh_model", type=int, default=1)
    # prepare_model (shared with infer/eval CLIs) reads these:
    p.add_argument("--use_event_qformer", action="store_true")
    p.add_argument("--pretrain_query_embedder", default=None)
    p.add_argument("--pretrain_attention_layers", default=None)
    return p


def main(argv=None):
    p = build_parser()
    args = p.parse_args(argv)

    if args.worker:
        # Process-fleet worker (ISSUE 11): one engine, RPC instead of
        # HTTP. serve_worker installs its own SIGTERM/SIGINT handlers
        # (stop -> engine.shutdown -> exit 0).
        if not args.worker_ready_file:
            p.error("--worker requires --worker_ready_file")
        from eventgpt_tpu.fleet_proc import serve_worker

        _, engine = build_engine(args, force_single=True)
        return serve_worker(engine, args.worker_ready_file)

    httpd, engine = build_server(args)
    host, port = httpd.server_address[:2]
    print(f"[serve] listening on http://{host}:{port} "
          f"(max_batch={args.max_batch}, chunk={args.chunk})")

    # Graceful drain (ISSUE 11 satellite): SIGTERM/SIGINT stop
    # ADMISSION (the accept loop), let in-flight requests finish
    # (bounded by --drain_timeout_s) so their handler threads write
    # complete responses, then exit 0 — a signal mid-decode no longer
    # kills committed work. httpd.shutdown() must run off the signal
    # handler's thread (it joins the serve_forever loop).
    import signal as _signal

    got_signal = threading.Event()

    def _on_signal(signum, frame):
        got_signal.set()
        threading.Thread(target=httpd.shutdown, daemon=True).start()

    _signal.signal(_signal.SIGTERM, _on_signal)
    _signal.signal(_signal.SIGINT, _on_signal)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        if got_signal.is_set():
            deadline = time.monotonic() + args.drain_timeout_s
            print("[serve] draining in-flight requests "
                  f"(<= {args.drain_timeout_s:.0f}s)")
            while time.monotonic() < deadline:
                s = engine.stats()
                if not (s.get("active_rows", 0) or s.get("queued", 0)):
                    break
                time.sleep(0.05)
            # One breath for handler threads to finish writing the
            # responses of requests that just left the engine.
            time.sleep(0.25)
        engine.shutdown()
        httpd.server_close()
        if got_signal.is_set():
            print("[serve] drained, exiting")


if __name__ == "__main__":
    main()
