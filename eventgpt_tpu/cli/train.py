"""Training CLI: the in-tree replacement for the external LLaVA launch.

Flags mirror the recovered ModelArguments / DataArguments / TrainingArguments
(SURVEY.md §2.2) via dataclass reflection — every field is a ``--flag``.

Usage (projector warm-up on a toy dataset):
  python -m eventgpt_tpu.cli.train --model_name_or_path tiny-random \\
      --data_path data.json --event_folder samples/ --stage 1 --max_steps 20

Stage 2 (LoRA):  add ``--stage 2 --lora_r 64 --lora_alpha 16``.
Multi-host:      run one process per host with EGPT_COORDINATOR /
                 EGPT_NUM_PROCESSES / EGPT_PROCESS_ID set (parallel/dist.py).
"""

from __future__ import annotations

import argparse
import dataclasses
import logging
from typing import Optional, get_args, get_origin

import jax

from eventgpt_tpu.parallel.dist import initialize_distributed
from eventgpt_tpu.train.args import DataArguments, ModelArguments, TrainingArguments
from eventgpt_tpu.train.trainer import Trainer


def _add_dataclass_args(parser: argparse.ArgumentParser, cls) -> None:
    for f in dataclasses.fields(cls):
        tp = f.type if not isinstance(f.type, str) else eval(f.type)  # noqa: S307
        if get_origin(tp) is not None:  # Optional[X] -> X
            inner = [a for a in get_args(tp) if a is not type(None)]
            tp = inner[0] if inner else str
        if tp is bool:
            parser.add_argument(
                f"--{f.name}", type=lambda v: v.lower() in ("true", "1", "yes"),
                default=f.default,
            )
        else:
            parser.add_argument(f"--{f.name}", type=tp, default=f.default)


def _extract(args: argparse.Namespace, cls):
    return cls(**{f.name: getattr(args, f.name) for f in dataclasses.fields(cls)})


def main(argv=None):
    from eventgpt_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()
    logging.basicConfig(level=logging.INFO)
    parser = argparse.ArgumentParser(description="EventGPT-TPU trainer")
    for cls in (ModelArguments, DataArguments, TrainingArguments):
        _add_dataclass_args(parser, cls)
    parser.add_argument(
        "--resume_from", type=str, default=None,
        help="checkpoint dir, or 'auto' to continue from the most recent "
             "ckpt_step*/ckpt_last under --output_dir (crash/preemption "
             "recovery: relaunch the same command with this flag)",
    )
    parser.add_argument(
        "--trace_out", type=str, default=None,
        help="arm the obs span tracer and write the run's Chrome trace "
             "events here at exit (Perfetto / chrome://tracing; "
             "OBSERVABILITY.md)",
    )
    args = parser.parse_args(argv)

    initialize_distributed()

    margs = _extract(args, ModelArguments)
    dargs = _extract(args, DataArguments)
    targs = _extract(args, TrainingArguments)

    from eventgpt_tpu.cli.infer import load_model

    cfg, params, tokenizer = load_model(
        margs.model_name_or_path, "bfloat16" if targs.bf16 else "float32"
    )

    if margs.use_event_qformer and not cfg.use_event_qformer:
        # CLI gate-in (initialize_vision_modules sets use_event_qformer on
        # the config the same way, model/EventChatModel.py:117-121).
        from eventgpt_tpu.config import QFormerConfig

        cfg = dataclasses.replace(
            cfg, use_event_qformer=True,
            qformer=QFormerConfig(hidden_size=cfg.llama.hidden_size),
        )
    if cfg.use_event_qformer and "qformer" not in params:
        # Covers both the CLI gate-in and checkpoints whose config.json
        # already sets use_event_qformer (their state dicts never carry the
        # weights — component files or fresh init fill them).
        from eventgpt_tpu.models.qformer import init_qformer_params

        params["qformer"] = init_qformer_params(
            cfg.qformer, jax.random.PRNGKey(targs.seed + 1)
        )

    if margs.pretrain_mm_mlp_adapter:
        from eventgpt_tpu import checkpoint as ckpt

        params["projector"] = ckpt.load_component(
            margs.pretrain_mm_mlp_adapter, strip_prefix="model.visual_projector."
        )
    if margs.pretrain_feature_adaptor:
        from eventgpt_tpu import checkpoint as ckpt

        params["projector"]["adaptor"] = ckpt.load_component(
            margs.pretrain_feature_adaptor, strip_prefix="model.feature_adaptor."
        )
        if not cfg.projector.use_feature_adaptor:
            # Keep the config in sync or the sharding-spec tree and the
            # param tree disagree at Trainer construction.
            cfg = dataclasses.replace(
                cfg, projector=dataclasses.replace(
                    cfg.projector, use_feature_adaptor=True
                )
            )
    if margs.pretrain_query_embedder or margs.pretrain_attention_layers:
        from eventgpt_tpu.models.qformer import load_qformer_components

        if "qformer" not in params:
            raise ValueError(
                "pretrain_query_embedder/pretrain_attention_layers require "
                "--use_event_qformer true (or a use_event_qformer checkpoint)"
            )
        params["qformer"] = load_qformer_components(
            params["qformer"],
            query_embedder_path=margs.pretrain_query_embedder,
            attention_layers_path=margs.pretrain_attention_layers,
        )

    trainer = Trainer(cfg, params, tokenizer, margs, dargs, targs)
    if args.resume_from == "auto":
        from eventgpt_tpu.checkpoint import find_latest_checkpoint

        latest = find_latest_checkpoint(targs.output_dir)
        if latest:
            logging.getLogger(__name__).info("auto-resuming from %s", latest)
            trainer.resume(latest)
    elif args.resume_from:
        trainer.resume(args.resume_from)
    tracer = None
    if args.trace_out:
        from eventgpt_tpu.obs import trace as obs_trace

        tracer = obs_trace.configure(65536)
    try:
        metrics = trainer.train()
    finally:
        if tracer is not None:
            n = tracer.write(args.trace_out)
            logging.getLogger(__name__).info(
                "wrote %d trace events to %s", n, args.trace_out)
    print(metrics)
    return metrics


if __name__ == "__main__":
    main()
