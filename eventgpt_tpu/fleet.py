"""Fleet serving: replica supervisor + prefix-affinity router (ISSUE 7).

Everything before this module hardens and accelerates ONE engine;
ROADMAP item 3 is the tier that turns "a server" into "a service": a
router that owns N ``ServingEngine`` replicas (threads in one process —
the same engine code path the single-engine CLI runs) and decides, per
request, WHERE it runs and WHETHER it runs at all:

  * **Prefix-affinity routing.** A session goes where its radix prefix
    is hot: the router keys each request by the same ``(ids-head,
    pixels_key)`` identity the ``PrefixCache`` trie uses (the prompt
    head through the event sentinel + the stream's content hash), and
    pins that key to the replica that served it first. Repeat turns of
    a chat session and stream re-submits therefore land on the replica
    whose prefix-KV cache already holds their head — the DistServe /
    Splitwise-style KV-affinity placement, with PR 4's hit ratio as the
    per-replica evidence. Unpinned keys (and pins whose replica left
    the pool) fall back to least queue depth.
  * **SLO-aware shedding.** When the fleet is overloaded — the windowed
    goodput ratio (PR 6's ``egpt_serve_slo_goodput_ratio`` signal,
    aggregated across replicas) drops below ``shed_goodput_ratio``, or
    the aggregate queue depth crosses ``shed_queue_depth`` — the router
    sheds ``batch``-class requests at submit with a class-aware
    Retry-After hint (``retry_after_s``). ``interactive`` requests are
    never policy-shed; they only see natural ``QueueFullError``
    backpressure when every replica's bounded queue is full.
  * **Supervision + failover.** A supervisor thread probes each
    replica's health (circuit-breaker state, liveness heartbeat
    staleness, kill state) and marks unhealthy replicas unroutable.
    When a replica dies (``kill_replica`` / the ``fleet.replica_kill``
    chaos site), its unfinished requests — queued AND in-flight — are
    drained via ``ContinuousBatcher.export_requests`` and re-routed to
    survivors, re-pinning their sessions; requests an engine fault
    already failed (status ``engine_fault``) fail over the same way.
    Failover re-decodes from the prompt: greedy chains are
    deterministic per request, so the failed-over chain is
    byte-identical to an uninterrupted single-engine run (the chaos
    test's acceptance bar). A revived replica (``restart_replica`` or
    ``replica_restart_s`` auto-restart) re-enters the routing pool.

Deliberately jax-free (stdlib + numpy), like ``workload.py``: the
router tier holds no device state — it moves host-side request records
between engines that do. Chaos sites: ``fleet.route`` (a route fault
degrades that submit to least-queue), ``fleet.probe`` (a probe fault
marks the probed replica unroutable until a clean probe),
``fleet.replica_kill`` (the trip IS the scripted kill).
"""

from __future__ import annotations

import hashlib
import math
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from eventgpt_tpu import faults
from eventgpt_tpu.constants import EVENT_TOKEN_INDEX
from eventgpt_tpu.obs import journey as obs_journey
from eventgpt_tpu.obs import metrics as obs_metrics
from eventgpt_tpu.obs import series as obs_series
from eventgpt_tpu.obs import trace as obs_trace

# Per-class base backoff for 429 hints: batch traffic has latency
# headroom by definition, so it is told to stay away longer.
_RETRY_BASE_S = {"interactive": 1.0, "batch": 4.0}
_RETRY_MAX_S = 60.0


class FleetShedError(RuntimeError):
    """The router refused a request under its SLO-aware overload policy
    (batch-class shed — backpressure, not failure). Carries the
    class-aware backoff hint the HTTP layer turns into Retry-After."""

    def __init__(self, msg: str, slo_class: str, retry_after_s: float):
        super().__init__(msg)
        self.slo_class = slo_class
        self.retry_after_s = retry_after_s


def _ledger_summary() -> Dict[str, Any]:
    """Process memory-ledger totals for the fleet /stats poll (host
    ints only; the import stays lazy like ``memory_stats`` so this
    module keeps its jax-free import surface)."""
    from eventgpt_tpu.obs import memory as obs_memory

    return obs_memory.LEDGER.summary()


def retry_after_s(slo_class: str, goodput_ratio: float = 1.0,
                  queue_depth: int = 0, max_queue: int = 0) -> float:
    """Class-aware 429 backoff derived from the CURRENT goodput window
    (ISSUE 7 satellite — replaces the fixed ``Retry-After: 1``): the
    further the windowed SLO-attainment ratio is below 1.0, the longer
    clients are told to stay away (linear, up to 4x the class base),
    scaled up again by relative queue pressure when known. ``batch``
    starts at a higher base than ``interactive`` — shed batch traffic
    must not come back first and re-trigger the shed."""
    base = _RETRY_BASE_S.get(slo_class, _RETRY_BASE_S["batch"])
    g = min(max(float(goodput_ratio), 0.0), 1.0)
    scale = 1.0 + 3.0 * (1.0 - g)
    if max_queue > 0 and queue_depth > 0:
        scale *= 1.0 + min(queue_depth / float(max_queue), 1.0)
    return min(base * scale, _RETRY_MAX_S)


def affinity_key(input_ids: Sequence[int], pixel_values: Any) -> tuple:
    """The routing identity of a request: its prompt head THROUGH the
    event sentinel plus the stream's content hash — the same identity
    the ``PrefixCache`` keys its through-event entries on, so
    same-key => the pinned replica's radix cache holds this head. The
    pixel hash matches ``serve._pixels_key``'s semantics (shape + f32
    content) without importing the jax-heavy module."""
    ids = list(input_ids)
    try:
        head = tuple(ids[: ids.index(EVENT_TOKEN_INDEX) + 1])
    except ValueError:
        head = tuple(ids)
    arr = np.ascontiguousarray(np.asarray(pixel_values, np.float32))
    digest = str(arr.shape).encode() + hashlib.sha1(arr.tobytes()).digest()
    return (head, digest)


@dataclass
class _FleetRequest:
    """One request the router owns end to end. ``replica``/``rid`` are
    the CURRENT assignment (failover re-points them); the client waits
    on ``done``, which only the supervisor (or submit-time shed) sets."""
    frid: int
    input_ids: List[int]
    pixel_values: Any
    max_new_tokens: int
    deadline: Optional[float]          # absolute perf_counter, or None
    slo: Any
    key: tuple
    stream: bool
    replica: int
    rid: int
    t_submit: float
    failovers: int = 0
    done: threading.Event = field(default_factory=threading.Event)
    tokens: Optional[List[int]] = None
    status: str = "ok"
    stats: Dict[str, float] = field(default_factory=dict)
    stream_q: Any = None               # the engine queue object (held so
    #                                    a dead replica's fault can still
    #                                    reach the streaming client)


@dataclass
class Replica:
    """One supervised engine. ``state`` drives routability: only ``ok``
    replicas receive new work; ``degraded`` (breaker open / stale
    heartbeat / probe fault) and ``dead`` (killed) are skipped until a
    clean probe or a restart re-admits them."""
    idx: int
    engine: Any
    state: str = "ok"                  # ok | degraded | dead
    t_dead: float = 0.0
    kills: int = 0
    probe_faults: int = 0

    @property
    def routable(self) -> bool:
        return self.state == "ok"

    def depth(self) -> int:
        """Routing load signal: queued + active rows (host-side reads,
        GIL-atomic enough for a heuristic)."""
        b = self.engine.batcher
        return len(b.queue) + sum(r is not None for r in b.rows)


class _FleetRequestStats:
    """``.get(frid)`` view over finished fleet requests — the shape
    ``make_handler`` expects of ``engine.batcher.request_stats``."""

    def __init__(self, fleet: "Fleet"):
        self._fleet = fleet

    def get(self, frid: int, default=None):
        freq = self._fleet._requests.get(frid)
        if freq is None or not freq.done.is_set():
            return default if default is not None else {}
        return freq.stats


class _FleetBatcherView:
    """The minimal ``engine.batcher`` surface the HTTP handler reads
    (request stats + prefix-cache snapshot), aggregated fleet-wide."""

    def __init__(self, fleet: "Fleet"):
        self._fleet = fleet
        self.request_stats = _FleetRequestStats(fleet)

    def prefix_cache_stats(self) -> Dict[str, Any]:
        per = []
        hits = misses = 0
        for rep in self._fleet.replicas:
            st = rep.engine.batcher.prefix_cache_stats()
            st.pop("entries", None)  # per-entry dumps don't aggregate
            per.append({"replica": rep.idx, **st})
            hits += st.get("hits", 0)
            misses += st.get("misses", 0)
        return {
            "enabled": any(p.get("enabled") for p in per),
            "hits": hits,
            "misses": misses,
            "hit_ratio": hits / (hits + misses) if (hits + misses) else 0.0,
            "replicas": per,
        }

    def slo_stats(self) -> Dict[str, Any]:
        return self._fleet.slo_stats()


class Fleet:
    """Replica supervisor + router with the client surface of a
    ``ServingEngine`` (submit / result / status / cancel / stream_queue
    / stats / breaker_open / set_prefix), so ``cli.serve.make_handler``
    serves a fleet unchanged. See the module docstring for policy.

    Lock discipline (egpt_check rule ``lock``): ``_GUARDED_BY`` is the
    checkable contract. The routing table (``_pins``), the request map's
    WRITES, and every host counter mutate under ``_lock``; ``/w``
    attributes are read lock-free by design (``result`` must not hold
    the lock while waiting; ``status``/``stream_queue`` tolerate
    one-tick staleness on a GIL-atomic dict read). Lock ORDER is fleet
    -> engine: ``submit_ids`` holds ``_lock`` across
    ``engine.submit_ids`` (which takes the engine lock); engine code
    never takes the fleet lock, so the order cannot invert. Replica
    ``state`` strings are a documented exception: single-writer from
    the supervisor thread in steady state, with the rare operator
    ``kill_replica``/``restart_replica`` transitions idempotent —
    cross-object fields are outside the detector's static scope either
    way (see analysis/lock_discipline.py "Known static limits")."""

    _GUARDED_BY = {
        # full guard: routing/bookkeeping state with compound updates
        "_pins": "_lock",
        "_next_frid": "_lock",
        "n_shed": "_lock",
        # writes locked; lock-free reads are the snapshot/flag pattern
        "_requests": "_lock/w",
        "n_requests": "_lock/w",
        "n_failovers": "_lock/w",
        "n_kills": "_lock/w",
        "n_route_faults": "_lock/w",
        "fault": "_lock/w",
    }

    def __init__(self, engines: Sequence[Any], tokenizer=None,
                 conv_mode: str = "eventgpt_v1",
                 probe_interval_s: float = 0.05,
                 heartbeat_stale_s: float = 5.0,
                 shed_goodput_ratio: float = 0.5,
                 shed_min_window: int = 8,
                 shed_queue_depth: int = 0,
                 max_failovers: int = 3,
                 replica_restart_s: Optional[float] = None):
        if not engines:
            raise ValueError("a fleet needs at least one replica engine")
        self.replicas = [Replica(i, e) for i, e in enumerate(engines)]
        self.tokenizer = tokenizer
        self.conv_mode = conv_mode
        self.probe_interval_s = float(probe_interval_s)
        self.heartbeat_stale_s = float(heartbeat_stale_s)
        # Shedding thresholds: 0 disarms that signal. Goodput shedding
        # only engages once the aggregate window holds shed_min_window
        # finishes — an empty window reads 0.0 and would shed a cold
        # fleet forever.
        self.shed_goodput_ratio = float(shed_goodput_ratio)
        self.shed_min_window = int(shed_min_window)
        self.shed_queue_depth = int(shed_queue_depth)
        self.max_failovers = int(max_failovers)
        self.replica_restart_s = replica_restart_s
        self._lock = threading.Lock()
        self._requests: Dict[int, _FleetRequest] = {}
        self._pins: Dict[tuple, int] = {}      # affinity key -> replica idx
        self._next_frid = 0
        self._stop = False
        self.t_start = time.time()
        self.n_requests = 0
        # Host-side counters (bench/tests read these; the egpt_fleet_*
        # registry mirrors them for /metrics):
        self.n_shed: Dict[str, int] = {}
        self.n_failovers = 0
        self.n_kills = 0
        self.n_route_faults = 0
        self.fault: Any = None                 # repr of the last replica loss
        # Flight recorder (ISSUE 10): the router records its own
        # request-level timeline (route / shed / failover / repin) under
        # a fleet owner id; per-replica decode timelines live under each
        # batcher's owner, stitched together by ``journey(frid)``.
        self._journey_owner = obs_journey.register_owner("fleet")
        obs_metrics.FLEET_REPLICAS.set(len(self.replicas))
        obs_metrics.FLEET_ROUTABLE.set(len(self.replicas))
        self._thread = threading.Thread(target=self._supervise, daemon=True)
        self._thread.start()

    # -- client surface ---------------------------------------------------

    @property
    def batcher(self) -> _FleetBatcherView:
        return _FleetBatcherView(self)

    @property
    def n_faults(self) -> int:
        return sum(r.engine.n_faults for r in self.replicas)

    @property
    def n_restarts(self) -> int:
        return sum(r.engine.n_restarts for r in self.replicas)

    def breaker_open(self) -> bool:
        """The fleet refuses work only when NO replica is routable —
        one healthy replica keeps /health green (degraded capacity shows
        in the egpt_fleet_replicas_routable gauge instead)."""
        return not any(r.routable for r in self.replicas)

    def goodput_ratio(self) -> float:
        """Aggregate windowed SLO-attainment across replicas, weighted
        by window occupancy; 1.0 until the window holds anything (an
        empty window must not read as total SLO collapse)."""
        met = 0.0
        n = 0
        for rep in self.replicas:
            st = rep.engine.batcher.slo_stats()
            w = st.get("window_n", 0)
            met += st.get("goodput_ratio", 0.0) * w
            n += w
        return met / n if n else 1.0

    def queue_depth(self) -> int:
        return sum(len(r.engine.batcher.queue) for r in self.replicas)

    def submit(self, query: str, pixels, max_new_tokens: int,
               stream: bool = False, deadline_s: Optional[float] = None,
               slo=None) -> int:
        from eventgpt_tpu.data.conversation import prepare_event_prompt
        from eventgpt_tpu.data.tokenizer import tokenize_with_event

        ids = tokenize_with_event(
            prepare_event_prompt(query, self.conv_mode), self.tokenizer
        )
        return self.submit_ids(ids, pixels, max_new_tokens, stream=stream,
                               deadline_s=deadline_s, slo=slo)

    def submit_ids(self, input_ids: Sequence[int], pixels,
                   max_new_tokens: int, stream: bool = False,
                   deadline_s: Optional[float] = None, slo=None) -> int:
        """Route one request: shed-check, pick a replica (affinity ->
        least-queue), submit there, track for supervision. Raises
        ``FleetShedError`` (policy shed), the replica's
        ``QueueFullError`` (every routable replica full), or
        ``RuntimeError`` when no replica is routable at all."""
        try:
            self._maybe_shed(slo)
        except FleetShedError:
            # A shed is a terminal outcome the flight recorder must
            # still explain: it gets an frid-keyed timeline of its own
            # (submit -> shed -> finish{status: shed}), so /requests
            # shows refusals next to served traffic.
            if obs_journey.enabled():
                with self._lock:
                    frid = self._next_frid
                    self._next_frid += 1
                t = time.perf_counter()
                cls = getattr(slo, "name", None)
                obs_journey.begin(self._journey_owner, frid, t=t,
                                  slo_class=cls)
                obs_journey.event(self._journey_owner, frid, "shed", t=t)
                obs_journey.finish(self._journey_owner, frid, "shed",
                                   t_submit=t, t_done=t, slo_class=cls)
            raise
        key = affinity_key(input_ids, pixels)
        with self._lock:
            rep, reason = self._route_locked(key)
            rid = rep.engine.submit_ids(
                list(input_ids), pixels, max_new_tokens, stream=stream,
                deadline_s=deadline_s, slo=slo)
            obs_metrics.FLEET_ROUTED.inc(reason=reason)
            frid = self._next_frid
            self._next_frid += 1
            freq = _FleetRequest(
                frid=frid, input_ids=list(input_ids), pixel_values=pixels,
                max_new_tokens=max_new_tokens,
                deadline=(time.perf_counter() + deadline_s
                          if deadline_s is not None else None),
                slo=slo, key=key, stream=stream, replica=rep.idx, rid=rid,
                t_submit=time.perf_counter())
            if stream:
                freq.stream_q = rep.engine.stream_queue(rid)
            self._requests[frid] = freq
            self._pins[key] = rep.idx
            self.n_requests += 1
            obs_journey.begin(
                self._journey_owner, frid, t=freq.t_submit,
                budget=max_new_tokens,
                **({"slo_class": slo.name} if slo is not None else {}))
            obs_journey.event(self._journey_owner, frid, "route",
                              t=freq.t_submit, replica=rep.idx,
                              replica_rid=rid, reason=reason)
        obs_metrics.FLEET_QUEUE_DEPTH.set(self.queue_depth())
        return frid

    def result(self, frid: int, timeout: float = 600.0) -> List[int]:
        freq = self._requests[frid]
        if not freq.done.wait(timeout):
            raise TimeoutError(
                f"fleet request {frid} did not finish in {timeout}s")
        if freq.tokens is None:
            raise RuntimeError(
                f"fleet request {frid} failed after {freq.failovers} "
                f"failover(s): {freq.status} ({self.fault})")
        return freq.tokens

    def status(self, frid: int) -> str:
        freq = self._requests.get(frid)
        return freq.status if freq is not None else "ok"

    def replica_of(self, frid: int) -> int:
        """The replica that served (or is serving) the request — test/
        bench introspection for the affinity and failover assertions."""
        return self._requests[frid].replica

    def cancel(self, frid: int) -> bool:
        with self._lock:
            freq = self._requests.get(frid)
            if freq is None or freq.done.is_set():
                return False
            rep = self.replicas[freq.replica]
        return rep.engine.cancel(freq.rid)

    def stream_queue(self, frid: int):
        return self._requests[frid].stream_q

    def set_prefix(self, prefix_prompt: str, pixels=None) -> int:
        """Broadcast an operator prefix insert to EVERY replica (the
        single-engine POST /prefix contract, fleet-wide: a session may
        land anywhere before it has a pin)."""
        plen = 0
        for rep in self.replicas:
            if rep.routable:
                plen = rep.engine.set_prefix(prefix_prompt, pixels)
        return plen

    def stats(self) -> Dict[str, Any]:
        reps = []
        for rep in self.replicas:
            s = rep.engine.snapshot()
            reps.append({
                "replica": rep.idx,
                "state": rep.state,
                "active_rows": s.get("active_rows", 0),
                "queued": s.get("queued", 0),
                "faults": rep.engine.n_faults,
                "restarts": rep.engine.n_restarts,
                "kills": rep.kills,
                "goodput_ratio": s.get("slo", {}).get("goodput_ratio", 0.0),
                "prefix_cache_hit_ratio":
                    rep.engine.batcher.prefix_cache_stats().get(
                        "hit_ratio", 0.0),
                # Per-replica memory share (ISSUE 9): this replica's
                # OWN ledger components (resident cache, lanes, ...) —
                # the shared weight tree lives in the process totals,
                # not here (it is one allocation, not N).
                "memory_bytes": sum(
                    s.get("memory", {}).get("owner", {}).values()),
            })
        with self._lock:
            # _pins/n_shed are compound-mutated (full guard): snapshot
            # under the lock — dict(d) can raise if d resizes mid-copy.
            n_pins = len(self._pins)
            shed = dict(self.n_shed)
        return {
            "uptime_s": round(time.time() - self.t_start, 1),
            "requests": self.n_requests,
            "status": "degraded" if self.breaker_open() else "ok",
            "active_rows": sum(r["active_rows"] for r in reps),
            "queued": sum(r["queued"] for r in reps),
            "fleet": {
                "replicas": len(self.replicas),
                "routable": sum(r.routable for r in self.replicas),
                "pins": n_pins,
                "goodput_ratio": round(self.goodput_ratio(), 4),
                "shed": shed,
                "failovers": self.n_failovers,
                "kills": self.n_kills,
                "route_faults": self.n_route_faults,
                "per_replica": reps,
            },
            "metrics": obs_metrics.REGISTRY.summary(
                ("egpt_serve_", "egpt_fleet_")),
            # Ledger totals ride the fleet poll too (ISSUE 9): one
            # process, one jax runtime — the process ledger IS the
            # fleet's memory story (per-replica shares are in
            # per_replica[].memory_bytes above).
            "memory": _ledger_summary(),
            # Active alert rules + last transitions (ISSUE 15): the
            # store samples the process registry, which already carries
            # the fleet aggregates (egpt_fleet_queue_depth feeds the
            # queue_trend rule), so one store senses the whole fleet.
            "alerts": obs_series.alert_stats(),
        }

    def fleet_stats(self) -> Dict[str, Any]:
        """The /fleet route body (topology + policy + live state)."""
        return {
            **self.stats()["fleet"],
            "policy": {
                "shed_goodput_ratio": self.shed_goodput_ratio,
                "shed_min_window": self.shed_min_window,
                "shed_queue_depth": self.shed_queue_depth,
                "max_failovers": self.max_failovers,
                "probe_interval_s": self.probe_interval_s,
                "heartbeat_stale_s": self.heartbeat_stale_s,
                "replica_restart_s": self.replica_restart_s,
            },
        }

    def memory_stats(self) -> Dict[str, Any]:
        """The fleet ``GET /memory`` payload (ISSUE 9): process ledger
        totals + reconciliation (one process, one jax runtime — the
        ledger IS fleet-wide) plus each replica's own component share.
        The weight tree appears once in the totals: replicas share it
        by construction (one tree, N schedulers)."""
        from eventgpt_tpu.obs import memory as obs_memory

        out = obs_memory.LEDGER.summary()
        out["reconcile"] = obs_memory.LEDGER.reconcile()
        out["replicas"] = [
            {"replica": rep.idx,
             "components": obs_memory.LEDGER.snapshot(
                 rep.engine.batcher._mem_owner)}
            for rep in self.replicas
        ]
        return out

    def series(self, window_s: Optional[float] = None,
               n: Optional[int] = None) -> Dict[str, Any]:
        """The fleet ``GET /series`` payload (ISSUE 15). One process,
        one registry, one store: replicas are threads, the sampler
        already sees the fleet-wide gauges (the router overwrites
        egpt_fleet_queue_depth each route, each replica's scheduler the
        serve gauges — the store samples max of the two). Per-replica
        instantaneous context rides alongside the shared ring."""
        out = obs_series.snapshot(window_s=window_s, n=n)
        out["per_replica"] = [
            {"replica": rep.idx, "state": rep.state,
             "queued": rep.engine.snapshot().get("queued", 0)}
            for rep in self.replicas
        ]
        return out

    def alerts(self) -> Dict[str, Any]:
        """The fleet ``GET /alerts`` payload (ISSUE 15): the shared
        process store's rule state — fleet-wide by construction."""
        return obs_series.alerts()

    def slo_stats(self) -> Dict[str, Any]:
        """Aggregate per-class attainment across replicas (the bench's
        goodput accounting for a fleet point)."""
        classes: Dict[str, Dict[str, int]] = {}
        for rep in self.replicas:
            st = rep.engine.batcher.slo_stats()
            for name, c in st.get("classes", {}).items():
                agg = classes.setdefault(name, {"finished": 0, "met": 0})
                agg["finished"] += c["finished"]
                agg["met"] += c["met"]
        for c in classes.values():
            c["attainment"] = (c["met"] / c["finished"]
                               if c["finished"] else 0.0)
        return {"classes": classes, "goodput_ratio": self.goodput_ratio()}

    def reset_stats(self) -> None:
        """Zero the phase-scoped host counters (the bench's per-point
        reset; replica-level resets are the caller's, as ever)."""
        with self._lock:
            self.n_shed = {}
            self.n_failovers = 0
            self.n_kills = 0
            self.n_route_faults = 0

    def shutdown(self) -> None:
        self._stop = True
        self._thread.join(timeout=10)
        for rep in self.replicas:
            rep.engine.shutdown()

    # -- routing ----------------------------------------------------------

    def _route_locked(self, key: tuple):
        """(replica, reason) for one submit. Affinity first: the key's
        pinned replica, while routable. A ``fleet.route`` chaos trip
        degrades THIS decision to least-queue (the handling contract:
        a broken affinity table must cost locality, not availability)."""
        pool = [r for r in self.replicas if r.routable]
        if not pool:
            raise RuntimeError(
                f"no routable replica ({len(self.replicas)} configured): "
                f"{self.fault}")
        try:
            faults.maybe_fail("fleet.route")
            faults.maybe_delay("fleet.route")
            pinned = self._pins.get(key)
            if pinned is not None and self.replicas[pinned].routable:
                return self.replicas[pinned], "affinity"
        except faults.InjectedFault:
            self.n_route_faults += 1
        return min(pool, key=lambda r: (r.depth(), r.idx)), "least_queue"

    def _maybe_shed(self, slo) -> None:
        """Batch-first admission control at the router edge. Only
        ``batch``-class requests are ever policy-shed; everything else
        rides the replicas' own queue bounds."""
        if slo is None or getattr(slo, "name", None) != "batch":
            return
        overloaded, why = self._overloaded()
        if not overloaded:
            return
        ra = retry_after_s("batch", self.goodput_ratio(),
                           queue_depth=self.queue_depth(),
                           max_queue=max(self.shed_queue_depth, 1))
        with self._lock:
            self.n_shed["batch"] = self.n_shed.get("batch", 0) + 1
        obs_metrics.FLEET_SHED.inc(slo_class="batch")
        obs_trace.instant("fleet_shed", cat="fleet", why=why)
        raise FleetShedError(
            f"fleet shed batch-class request ({why}); retry in ~{ra:.0f}s",
            "batch", ra)

    def _overloaded(self):
        if self.shed_queue_depth > 0:
            q = self.queue_depth()
            if q >= self.shed_queue_depth:
                return True, f"queue depth {q} >= {self.shed_queue_depth}"
        if self.shed_goodput_ratio > 0.0:
            n = sum(r.engine.batcher.slo_stats().get("window_n", 0)
                    for r in self.replicas)
            g = self.goodput_ratio()
            if n >= self.shed_min_window and g < self.shed_goodput_ratio:
                return True, (f"windowed goodput {g:.2f} < "
                              f"{self.shed_goodput_ratio}")
        return False, ""

    # -- supervision ------------------------------------------------------

    def kill_replica(self, idx: int) -> int:
        """Kill one replica NOW (operator API and the chaos handler):
        mark it dead, drain its unfinished requests and re-route them to
        survivors. Returns the number of failed-over requests. Streamed
        requests cannot fail over (bytes already left through their
        chunked body) — their clients get the fault sentinel instead."""
        rep = self.replicas[idx]
        if rep.state == "dead":
            return 0
        rep.state = "dead"
        rep.t_dead = time.monotonic()
        rep.kills += 1
        with self._lock:
            # Counter/fault writes go under the lock (the lock contract;
            # rep.state above is the documented Replica exception).
            # engine.kill() below stays OUTSIDE it: fleet -> engine is
            # the lock order, and kill holds the engine lock for a full
            # drain.
            self.n_kills += 1
            self.fault = f"replica {idx} killed"
        obs_metrics.FLEET_REPLICA_DEATHS.inc()
        obs_trace.instant("replica_kill", cat="fleet")
        self._export_routable_gauge()
        exported = rep.engine.kill()
        by_rid = {rec["rid"]: rec for rec in exported}
        moved = 0
        with self._lock:
            victims = [f for f in self._requests.values()
                       if f.replica == idx and not f.done.is_set()]
            for freq in victims:
                rec = by_rid.get(freq.rid)
                if freq.stream:
                    # Mid-stream failover would replay already-sent
                    # bytes; surface the fault like an engine death.
                    self._finish_locked(freq, None, "engine_fault")
                    if freq.stream_q is not None:
                        freq.stream_q.put({"fault": self.fault})
                    continue
                if rec is None:
                    # Finished at the engine but uncollected: kill()
                    # harvested first, so try_result still serves it on
                    # the next supervisor tick. Leave it tracked.
                    continue
                self._failover_locked(freq, rec.get("deadline_s"))
                moved += 1
        obs_metrics.FLEET_QUEUE_DEPTH.set(self.queue_depth())
        return moved

    def restart_replica(self, idx: int) -> None:
        """Recovery: revive a killed replica and re-admit it to the
        routing pool (the kill -> drain -> re-route -> RECOVERY tail)."""
        rep = self.replicas[idx]
        rep.engine.revive()
        rep.state = "ok"
        obs_trace.instant("replica_restart", cat="fleet")
        self._export_routable_gauge()

    def _failover_locked(self, freq: _FleetRequest,
                         deadline_s: Optional[float]) -> None:
        """Re-route one request to a survivor (caller holds the lock).
        The session's pin MOVES with it — subsequent turns follow the
        failed-over request to its new replica (re-pin), rebuilding
        prefix locality there instead of bouncing per turn."""
        freq.failovers += 1
        if freq.failovers > self.max_failovers:
            self._finish_locked(freq, None, "engine_fault")
            return
        pool = [r for r in self.replicas
                if r.routable and r.idx != freq.replica]
        if not pool:
            pool = [r for r in self.replicas if r.routable]
        if not pool:
            self._finish_locked(freq, None, "engine_fault")
            return
        rep = min(pool, key=lambda r: (r.depth(), r.idx))
        try:
            freq.rid = rep.engine.submit_ids(
                freq.input_ids, freq.pixel_values, freq.max_new_tokens,
                deadline_s=deadline_s, slo=freq.slo)
        except Exception as e:  # survivor refused (full/degraded): give up
            self.fault = repr(e)
            self._finish_locked(freq, None, "engine_fault")
            return
        old_replica = freq.replica
        freq.replica = rep.idx
        self._pins[freq.key] = rep.idx
        self.n_failovers += 1
        obs_metrics.FLEET_FAILOVERS.inc()
        obs_metrics.FLEET_ROUTED.inc(reason="repin")
        obs_journey.event(self._journey_owner, freq.frid, "failover",
                          from_replica=old_replica, to_replica=rep.idx,
                          replica_rid=freq.rid)
        obs_journey.event(self._journey_owner, freq.frid, "repin",
                          replica=rep.idx)

    @staticmethod
    def _assignments_of(events) -> List[tuple]:
        """(replica, rid) per assignment, from a fleet journey's route/
        failover events (works on both the raw and export shapes)."""
        out = []
        for ev in events:
            if ev.get("kind") == "route":
                out.append((ev.get("replica"), ev.get("replica_rid")))
            elif ev.get("kind") == "failover":
                out.append((ev.get("to_replica"), ev.get("replica_rid")))
        return out

    def _stitch_locked(self, freq: _FleetRequest):
        """(t_submit, t_done, phases) of the whole fleet request,
        stitched across its assignments: the FINAL assignment's phase
        decomposition plus ``failover_redo_s`` = the wall time the
        abandoned assignments burned (first replica submit -> final
        replica submit — queued, decoded-and-discarded, and re-routed
        time all land there, which is exactly what a failover costs).
        The sum invariant holds by construction: phases partition
        [first.t_submit, final.t_done]. None when the recorder is
        disarmed or the replica timelines are gone."""
        raw = obs_journey.raw(self._journey_owner, freq.frid)
        if raw is None:
            return None
        raws = []
        for rep_idx, rid in self._assignments_of(raw["events"]):
            if rep_idx is None or rid is None \
                    or not (0 <= rep_idx < len(self.replicas)):
                continue
            b = self.replicas[rep_idx].engine.batcher
            r = obs_journey.raw(getattr(b, "_journey_owner", -1), rid)
            if r is not None:
                raws.append(r)
        final = next((r for r in reversed(raws)
                      if r.get("finished") and r.get("phases")), None)
        if final is None:
            return None
        first = raws[0]
        redo = max(final["t_submit"] - first["t_submit"], 0.0)
        phases = dict(final["phases"])
        phases["failover_redo_s"] = redo
        return first["t_submit"], final["t_done"], phases

    def journey(self, frid: int) -> Optional[Dict[str, Any]]:
        """Fleet passthrough of ``GET /request?rid=N`` (ISSUE 10): the
        router-level timeline (route / shed / failover / repin) with
        each assignment's replica timeline attached, plus the stitched
        decomposition stored at finish."""
        rec = obs_journey.get(self._journey_owner, frid)
        if rec is None:
            return None
        legs = []
        for rep_idx, rid in self._assignments_of(rec["events"]):
            jr = None
            if rep_idx is not None and rid is not None \
                    and 0 <= rep_idx < len(self.replicas):
                jr = self.replicas[rep_idx].engine.batcher.journey(rid)
            legs.append({"replica": rep_idx, "rid": rid, "journey": jr})
        rec["assignments"] = legs
        return rec

    def journeys(self, n: int = 64) -> List[Dict[str, Any]]:
        """Recent finished fleet requests (``GET /requests``)."""
        return obs_journey.index(self._journey_owner, n)

    def _finish_locked(self, freq: _FleetRequest, tokens,
                       status: str) -> None:
        freq.tokens = tokens
        freq.status = status
        if obs_journey.enabled():
            # Close the fleet journey BEFORE releasing the waiter: a
            # client that polls journey(frid) right after result()
            # must see the finished, stitched record.
            stitched = self._stitch_locked(freq)
            slo_met = freq.stats.get("slo_met")
            obs_journey.finish(
                self._journey_owner, freq.frid, status,
                t_submit=(stitched[0] if stitched else freq.t_submit),
                t_done=(stitched[1] if stitched else None),
                slo_class=getattr(freq.slo, "name", None),
                slo_met=(bool(slo_met) if slo_met is not None else None),
                phases=(stitched[2] if stitched else None),
                failovers=freq.failovers)
        freq.done.set()
        # Bounded finished map (the engine's request_stats rule): a
        # long-lived router must not grow per-request state forever.
        while len(self._requests) >= 8192:
            oldest = next(iter(self._requests))
            if not self._requests[oldest].done.is_set():
                break  # never evict a live request
            self._requests.pop(oldest)

    def _supervise(self) -> None:
        """The supervisor loop: probe health, run scripted chaos kills,
        collect finished/faulted requests, auto-restart dead replicas.
        Must never die — every probe failure is a health SIGNAL here."""
        while not self._stop:
            try:
                faults.maybe_delay("fleet.probe")
                for rep in self.replicas:
                    self._probe(rep)
                try:
                    faults.maybe_fail("fleet.replica_kill")
                except faults.InjectedFault:
                    # The chaos trip IS the kill: take down the busiest
                    # routable replica (the worst case — it holds
                    # in-flight decodes that must fail over).
                    pool = [r for r in self.replicas if r.routable]
                    if pool:
                        victim = max(pool, key=lambda r: (r.depth(), -r.idx))
                        self.kill_replica(victim.idx)
                self._collect()
                self._export_routable_gauge()
                obs_metrics.FLEET_QUEUE_DEPTH.set(self.queue_depth())
            except Exception as e:  # defensive: supervision must survive
                with self._lock:
                    self.fault = repr(e)
            time.sleep(self.probe_interval_s)

    def _probe(self, rep: Replica) -> None:
        if rep.state == "dead":
            if (self.replica_restart_s is not None
                    and time.monotonic() - rep.t_dead
                    >= self.replica_restart_s):
                self.restart_replica(rep.idx)
            return
        try:
            faults.maybe_fail("fleet.probe")
        except faults.InjectedFault:
            # A failed probe means health is UNKNOWN: pull the replica
            # from the pool until a clean probe says otherwise — the
            # same action a real probe timeout would take.
            rep.probe_faults += 1
            rep.state = "degraded"
            return
        eng = rep.engine
        healthy = not eng.breaker_open()
        hb = getattr(eng, "_heartbeat", None)
        if healthy and hb is not None:
            from eventgpt_tpu.train.resilience import Heartbeat

            healthy = not Heartbeat.is_stale(hb.path, self.heartbeat_stale_s)
        rep.state = "ok" if healthy else "degraded"

    def _collect(self) -> None:
        """Harvest finished requests and fail over engine-faulted ones
        (an engine fault fails in-flight rows with status engine_fault;
        queued requests a NON-tripped fault kept are simply re-served
        by the restarted scheduler — no failover needed)."""
        with self._lock:
            live = [f for f in self._requests.values()
                    if not f.done.is_set()]
        for freq in live:
            rep = self.replicas[freq.replica]
            if freq.stream:
                st = rep.engine.try_status(freq.rid)
                if st is not None:
                    with self._lock:
                        self._finish_locked(freq, [], st)
                continue
            got = rep.engine.try_result(freq.rid)
            if got is None:
                continue
            tokens, status = got
            if status == "engine_fault":
                with self._lock:
                    remaining = (freq.deadline - time.perf_counter()
                                 if freq.deadline is not None else None)
                    self._failover_locked(freq, remaining)
                continue
            with self._lock:
                freq.stats = dict(
                    rep.engine.batcher.request_stats.get(freq.rid, {}))
                self._finish_locked(freq, tokens, status)

    def _export_routable_gauge(self) -> None:
        obs_metrics.FLEET_ROUTABLE.set(
            sum(r.routable for r in self.replicas))
