"""Deterministic, site-keyed fault injection for the serving/training stack.

Every failure path the runtime claims to survive (scheduler death, NaN
logits, queue overload, slow steps, bootstrap races) must be REACHABLE
from a test, or the handling code is dead weight that rots. This module
is the single switch: call sites name themselves
(``faults.maybe_fail("serve.step")``) and a test/operator chooses which
sites fire, when, and how — with zero overhead when nothing is armed
(one module-global ``is None`` check per call).

Spec grammar (``EGPT_FAULTS`` env var, ``--faults`` CLI flags, or
``faults.configure()``)::

    site:key=value[,key=value];site2:...

  * ``n=K``        fire exactly on the K-th call to the site (1-based) —
                   the deterministic workhorse for chaos tests;
  * ``every=K``    fire on every K-th call (periodic flakiness);
  * ``p=X``        fire with probability X per call, from a per-site
                   ``random.Random`` seeded by (seed, site) — the SAME
                   call sequence fires the SAME calls across runs;
  * ``times=K``    cap total fires at K (default: unlimited for
                   ``p``/``every``, exactly one for ``n``);
  * ``delay=S``    ``maybe_delay`` sleeps S seconds per matching call
                   (same n/every/p gating; default gate = every call).

Examples::

    EGPT_FAULTS="serve.step:n=2"              # 2nd scheduler step dies
    EGPT_FAULTS="serve.admit:p=0.1,times=3"   # ~10% of admissions, max 3
    EGPT_FAULTS="train.step:delay=0.05"       # every micro-step +50 ms

Wired sites (grep ``maybe_fail(`` for the authoritative list; the
telemetry lint's rule 4 asserts every one of them is exercised by a
chaos/faults test):
``serve.step`` / ``serve.admit`` / ``serve.dispatch`` /
``serve.mixed_dispatch``
(``ContinuousBatcher``; ``serve.dispatch`` fires at the pipelined
scheduler's segment-dispatch boundary — a fault there can land with a
segment still in flight, the window the engine's abort/restart path must
survive; ``serve.mixed_dispatch`` fires at the piggyback lane-advance
boundary of a mixed segment — the batcher degrades that boundary to a
plain decode dispatch and re-queues the admitting lanes, decode rows
untouched; ``serve.spec_adapt`` fires at the adaptive-speculation
boundary decision — the controller degrades THAT boundary to the fixed
default window at full depth, chains untouched),
``serve.prefix_copy`` (prefix-cache entry copy at admission),
``serve.preempt`` / ``serve.spill`` (the block-tier preemption path,
ISSUE 16: a preempt trip degrades that admission back to the plain
used-token deferral — no victim is touched; a spill trip fires inside
the gather-to-host boundary BEFORE any pool mutation, so the victim
falls back to drop-and-re-prefill with the pool intact and its chain
byte-identical),
``serve.loop`` (``ServingEngine`` scheduler thread), ``fleet.route`` /
``fleet.probe`` / ``fleet.replica_kill`` (``fleet.Fleet``: a route fault
degrades that submit to least-queue routing, a probe fault marks the
probed replica unroutable until a clean probe, and a replica_kill trip
IS the scripted chaos kill — the supervisor kills a live replica and
must drain + re-route its requests to survivors), ``procfleet.rpc`` /
``procfleet.spawn`` / ``procfleet.worker_kill`` (the process fleet,
``rpc.py`` + ``fleet_proc.py``: an rpc trip is a transport failure the
bounded-backoff retry loop must absorb, a spawn trip fails that worker
spawn attempt — booked as a crash, so the respawn-backoff/crash-loop
policy governs it — and a worker_kill trip IS the scripted SIGKILL of
the busiest worker, whose requests the coordinator must redo on
survivors), ``procfleet.handoff`` (the prefill->decode KV handoff ship
boundary, ISSUE 17: a trip is a transport failure mid-ship — the
coordinator retries the import boundedly against other decode workers
and then falls back to the REDO path, never double-splicing — the
decode worker's import dedup key makes a retried ship idempotent),
``multiproc.launch``
/ ``multiproc.worker`` (``parallel/multiproc.py`` bootstrap), and
``train.step`` (``Trainer`` micro-batch boundary).

Injected failures raise ``InjectedFault`` (a ``RuntimeError``): the
handling layers (engine circuit breaker, trainer divergence policy,
multiproc launcher) must treat it exactly like a real fault — tests that
catch ``InjectedFault`` specifically are asserting the fault *reached*
the handler, not that the handler special-cased it.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Dict, Optional

from eventgpt_tpu.obs import metrics as obs_metrics  # stdlib-only, like us


class InjectedFault(RuntimeError):
    """A deterministic test-injected failure (never raised in production
    unless fault injection was explicitly armed)."""


class _Site:
    __slots__ = ("name", "nth", "every", "p", "times", "delay_s",
                 "calls", "fires", "_rng")

    def __init__(self, name: str, nth: int = 0, every: int = 0,
                 p: float = 0.0, times: int = 0, delay_s: float = 0.0,
                 seed: int = 0):
        self.name = name
        self.nth = nth
        self.every = every
        self.p = p
        # n=K without an explicit cap fires exactly once (the K-th call).
        self.times = times if times else (1 if nth else 0)  # 0 = unlimited
        self.delay_s = delay_s
        self.calls = 0
        self.fires = 0
        # Seeded per (seed, site): deterministic across runs for the same
        # call order, decorrelated between sites.
        self._rng = random.Random(f"{seed}:{name}")

    def should_fire(self) -> bool:
        self.calls += 1
        if self.times and self.fires >= self.times:
            return False
        hit = False
        if self.nth:
            hit = self.calls == self.nth
        elif self.every:
            hit = self.calls % self.every == 0
        elif self.p:
            hit = self._rng.random() < self.p
        elif self.delay_s:
            hit = True  # delay-only spec: gate every call
        if hit:
            self.fires += 1
        return hit


class FaultRegistry:
    """Parsed fault plan: site name -> firing rule. Thread-safe (the
    serving engine probes sites from scheduler + handler threads): the
    site map and the per-site counters it shields only move under
    ``_lock`` (``_GUARDED_BY`` — egpt_check rule ``lock``)."""

    _GUARDED_BY = {"_sites": "_lock"}

    def __init__(self, spec: str, seed: int = 0):
        self.spec = spec
        self.seed = seed
        self._lock = threading.Lock()
        self._sites: Dict[str, _Site] = {}
        for clause in spec.split(";"):
            clause = clause.strip()
            if not clause:
                continue
            if ":" not in clause:
                raise ValueError(
                    f"bad fault clause {clause!r} (want site:key=value,...)")
            name, _, kvs = clause.partition(":")
            kw: Dict[str, float] = {}
            for kv in kvs.split(","):
                k, _, v = kv.strip().partition("=")
                if k not in ("n", "every", "p", "times", "delay"):
                    raise ValueError(
                        f"unknown fault key {k!r} in {clause!r} "
                        f"(known: n, every, p, times, delay)")
                kw[k] = float(v)
            self._sites[name.strip()] = _Site(
                name.strip(), nth=int(kw.get("n", 0)),
                every=int(kw.get("every", 0)), p=kw.get("p", 0.0),
                times=int(kw.get("times", 0)), delay_s=kw.get("delay", 0.0),
                seed=seed,
            )

    def check(self, site: str, want_delay: bool) -> Optional[_Site]:
        """Advance the site's call counter; return the site iff it fires.

        A ``delay=`` clause is a delay rule and only ``maybe_delay``
        drives it; every other clause is a failure rule and only
        ``maybe_fail`` drives it — a site wired with both probes (the
        normal wiring) advances each rule's counters exactly once per
        pass.
        """
        with self._lock:
            # The site lookup moved under the lock with the counters it
            # shields (the race detector's finding): _sites itself is
            # init-built, but reading it lock-free while another thread
            # advances its _Site counters made the guard partial.
            s = self._sites.get(site)
            if s is None or bool(s.delay_s) is not want_delay:
                return None
            return s if s.should_fire() else None

    def stats(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {n: {"calls": s.calls, "fires": s.fires}
                    for n, s in self._sites.items()}


_registry: Optional[FaultRegistry] = None


def configure(spec: Optional[str] = None, seed: Optional[int] = None) -> None:
    """Arm fault injection from ``spec`` (or the ``EGPT_FAULTS`` env var
    when ``spec`` is None). An empty/missing spec disarms."""
    global _registry
    if spec is None:
        spec = os.environ.get("EGPT_FAULTS", "")
    if seed is None:
        seed = int(os.environ.get("EGPT_FAULTS_SEED", "0"))
    _registry = FaultRegistry(spec, seed) if spec.strip() else None


def disable() -> None:
    global _registry
    _registry = None


def enabled() -> bool:
    return _registry is not None


def stats() -> Dict[str, Dict[str, int]]:
    """Per-site {calls, fires} counters of the armed registry ({} when
    disarmed) — the observability hook chaos tests assert against."""
    return _registry.stats() if _registry is not None else {}


def _site_label(site: str) -> str:
    """The bounded metric label for a fault site: wired sites label
    truthfully (the lint cross-checks the enum against the wired-site
    scan, so production names are always members); anything else —
    synthetic test sites, ad-hoc drill names — folds into ``other``
    instead of minting an unbounded Prometheus series (lint rule 5)."""
    enum = obs_metrics.METRIC_LABELS["egpt_fault_trips_total"]["site"]
    return site if site in enum else "other"


def maybe_fail(site: str) -> None:
    """Raise ``InjectedFault`` when the armed plan says this call of
    ``site`` fires. No-op (one global load + compare) when disarmed."""
    if _registry is None:
        return
    s = _registry.check(site, want_delay=False)
    if s is not None:
        # Fault trips reach the telemetry registry so a chaos drill shows
        # on /metrics next to the breaker/restart counters it provokes.
        obs_metrics.FAULT_TRIPS.inc(site=_site_label(site), kind="fail")
        raise InjectedFault(
            f"injected fault at {site} (call #{s.calls}, fire #{s.fires})")


def maybe_delay(site: str) -> float:
    """Sleep the site's configured delay when its rule fires (``delay=S``
    clauses only); returns the seconds slept. No-op when disarmed."""
    if _registry is None:
        return 0.0
    s = _registry.check(site, want_delay=True)
    if s is None:
        return 0.0
    obs_metrics.FAULT_TRIPS.inc(site=_site_label(site), kind="delay")
    time.sleep(s.delay_s)
    return s.delay_s


# Arm from the environment at import: zero-cost when EGPT_FAULTS is unset,
# and child processes (multiproc workers, spawned servers) inherit the
# operator's plan without any plumbing.
if os.environ.get("EGPT_FAULTS"):
    configure()
