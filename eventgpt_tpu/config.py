"""Unified typed configuration for the whole framework.

The reference scatters configuration across four mechanisms (argparse CLI,
HF config JSON with ad-hoc fields, HfArgumentParser dataclasses in the
training pyc, and C++ YAML — SURVEY.md §5 "Config / flag system"). Here there
is exactly one: frozen dataclasses, composable, JSON round-trippable, with a
converter from HF-style ``config.json`` dicts for checkpoint interop
(custom fields ``mm_visual_tower`` / ``event_feature_adaptor`` /
``use_event_qformer`` per ``model/EventChatModel.py:71-81``).
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Optional

from eventgpt_tpu import constants


@dataclass(frozen=True)
class VisionConfig:
    """CLIP ViT vision tower (reference: CLIP ViT-L/14-336, README.md:173-177)."""

    hidden_size: int = 1024
    intermediate_size: int = 4096
    num_layers: int = 24
    num_heads: int = 16
    image_size: int = 336
    patch_size: int = 14
    num_channels: int = 3
    layer_norm_eps: float = 1e-5
    # "quick_gelu" is CLIP's activation; kept configurable for other towers.
    hidden_act: str = "quick_gelu"

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def num_tokens(self) -> int:
        # +1 for the CLS token; ViT-L/14-336 -> 577.
        return self.num_patches + 1

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


@dataclass(frozen=True)
class LlamaConfig:
    """LLaMA/Vicuna decoder-only LM."""

    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    head_dim: Optional[int] = None
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-5
    max_seq_len: int = 2048  # reference context cap: model/EventChatModel.py:378
    tie_word_embeddings: bool = False
    # "dense" = materialized-scores attention; "flash" = Pallas fused kernel
    # for prefill (ops/flash_attention.py); "ring" / "ulysses" = sequence-
    # parallel attention over a context>1 mesh (parallel/ring.py,
    # parallel/ulysses.py). Decode always uses the dense single-query path
    # against the KV cache.
    attn_impl: str = "dense"
    # Rematerialize each layer in the backward pass (jax.checkpoint around
    # the scan body). Identity for forward-only jit; under grad it stops AD
    # from stacking per-layer residuals — without it a 7B train step saves
    # full dequantized/flash-residual copies of the weight set (measured
    # 16.9G of HLO temps on v5e) and cannot fit one chip.
    remat: bool = True
    # Remat POLICY (ISSUE 13 satellite, VERDICT r5 / ROADMAP item 4's
    # enabler): what jax.checkpoint may SAVE instead of recomputing in
    # the backward pass. "full" = save nothing, recompute everything
    # (the pre-sweep behavior; jax's default policy, so it is
    # operationally identical to "nothing_saveable" — kept as two
    # spellings because the sweep reports the literal policy it ran).
    # "dots_saveable" saves matmul outputs — the middle ground between
    # full remat's ~19 TFLOP/step of recompute at 7B stage-2 and
    # remat-off's OOM. Only meaningful under grad with remat=True.
    remat_policy: str = "full"

    _ATTN_IMPLS = ("dense", "flash", "ring", "ulysses")
    _REMAT_POLICIES = ("full", "nothing_saveable", "dots_saveable",
                       "dots_with_no_batch_dims_saveable")

    def __post_init__(self):
        if self.remat_policy not in self._REMAT_POLICIES:
            # llama.prefill maps this string onto jax.checkpoint_policies;
            # a typo would silently fall back to full remat and the sweep
            # would report a policy it never ran.
            raise ValueError(
                f"remat_policy must be one of {self._REMAT_POLICIES}, "
                f"got {self.remat_policy!r}"
            )
        if self.attn_impl not in self._ATTN_IMPLS:
            # llama.prefill dispatches on this string and treats anything
            # unrecognized as dense — a typo would silently drop flash or
            # sequence parallelism instead of failing.
            raise ValueError(
                f"attn_impl must be one of {self._ATTN_IMPLS}, "
                f"got {self.attn_impl!r}"
            )

    def resolved_head_dim(self) -> int:
        return self.head_dim if self.head_dim is not None else self.hidden_size // self.num_heads

    @staticmethod
    def llama_7b() -> "LlamaConfig":
        # Flash prefill by default: measured 4.5x over dense at S=640 on
        # v5e (bench record); decode still uses the single-query dense path.
        return LlamaConfig(attn_impl="flash")

    @staticmethod
    def llama_13b() -> "LlamaConfig":
        return LlamaConfig(
            hidden_size=5120, intermediate_size=13824, num_layers=40,
            num_heads=40, num_kv_heads=40, attn_impl="flash",
        )

    @staticmethod
    def tiny(vocab_size: int = 256) -> "LlamaConfig":
        """Small config for tests / CPU-mesh dry runs."""
        return LlamaConfig(
            vocab_size=vocab_size, hidden_size=64, intermediate_size=128,
            num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=256,
        )


@dataclass(frozen=True)
class ProjectorConfig:
    """Event-feature -> LM-embedding projection stack.

    Mirrors the reference stack: MLP(1024->4096, GELU, 4096->4096) projector
    (``model/EventChatModel.py:87-93``, mlp_depth=2 at ``:67``) plus an optional
    Linear(4096->4096) feature adaptor (``model/EventChatModel.py:75-76``).
    """

    input_dim: int = 1024
    output_dim: int = 4096
    mlp_depth: int = 2
    use_feature_adaptor: bool = True


@dataclass(frozen=True)
class QFormerConfig:
    """Shape of the config-gated event Q-Former (``models/qformer.py``).

    The reference declares the module (``use_event_qformer``,
    ``model/EventChatModel.py:78-81``) but never ships its builder; all
    dims here are this framework's own design."""

    num_queries: int = 32
    num_layers: int = 2
    num_heads: int = 8
    hidden_size: int = 4096   # = LM embedding dim (queries live in LM space)
    mlp_ratio: int = 4

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


@dataclass(frozen=True)
class MeshConfig:
    """Logical device mesh for pjit sharding (SURVEY.md §2.4).

    Axes: ``data`` (pure DP), ``fsdp`` (ZeRO-style param sharding),
    ``model`` (tensor parallel). A ``context`` axis for ring-attention
    sequence parallelism is carved out of ``data`` when ``context > 1``.
    """

    data: int = 1
    fsdp: int = 1
    model: int = 1
    context: int = 1

    @property
    def num_devices(self) -> int:
        return self.data * self.fsdp * self.model * self.context


@dataclass(frozen=True)
class EventChatConfig:
    """Top-level multimodal model config (EventChat_llama equivalent)."""

    vision: VisionConfig = field(default_factory=VisionConfig)
    llama: LlamaConfig = field(default_factory=LlamaConfig)
    projector: ProjectorConfig = field(default_factory=ProjectorConfig)

    # Event pipeline envelope (common/common.py:114,118).
    num_event_frames: int = constants.DEFAULT_NUM_EVENT_FRAMES
    max_event_stream_us: int = constants.MAX_EVENT_STREAM_US
    # None -> num_temporal_tokens == num frames (model/EventChatModel.py:24-25).
    num_temporal_tokens: Optional[int] = None
    # spatial_temporal_encoder flag of the training pyc (SURVEY.md §2.2);
    # False feeds raw per-frame patch tokens to the LM instead of pooling.
    use_spatio_temporal_pool: bool = True

    mm_use_im_start_end: bool = False
    mm_use_im_patch_token: bool = True

    # use_event_qformer gate (model/EventChatModel.py:78-81): the reference
    # declares this path but never ships the builder (SURVEY.md §2.1 P6c);
    # models/qformer.py supplies the TPU-native design. When enabled, the
    # Q-Former's learned queries replace the spatio-temporal pool as the
    # LM's event tokens.
    use_event_qformer: bool = False
    qformer: QFormerConfig = field(default_factory=QFormerConfig)

    @property
    def num_event_tokens(self) -> int:
        """Tokens contributed by one event clip after the encode stage."""
        if self.use_event_qformer:
            return self.qformer.num_queries
        if not self.use_spatio_temporal_pool:
            return self.num_event_frames * self.vision.num_tokens
        t = self.num_temporal_tokens if self.num_temporal_tokens is not None else self.num_event_frames
        return t + self.vision.num_tokens  # 5 + 577 = 582 for defaults

    @staticmethod
    def eventgpt_7b() -> "EventChatConfig":
        return EventChatConfig(llama=LlamaConfig.llama_7b())

    @staticmethod
    def eventgpt_13b() -> "EventChatConfig":
        return EventChatConfig(
            llama=LlamaConfig.llama_13b(),
            projector=ProjectorConfig(output_dim=5120),
        )

    @staticmethod
    def tiny(vocab_size: int = 256) -> "EventChatConfig":
        """Tiny end-to-end config for tests: real structure, toy dims."""
        vision = VisionConfig(
            hidden_size=32, intermediate_size=64, num_layers=2, num_heads=4,
            image_size=28, patch_size=14,
        )
        llama = LlamaConfig.tiny(vocab_size)
        proj = ProjectorConfig(input_dim=32, output_dim=llama.hidden_size)
        return EventChatConfig(vision=vision, llama=llama, projector=proj)


# ---------------------------------------------------------------------------
# Serialization


def to_dict(cfg: Any) -> Any:
    if dataclasses.is_dataclass(cfg):
        return {f.name: to_dict(getattr(cfg, f.name)) for f in dataclasses.fields(cfg)}
    return cfg


_NESTED = {"vision": VisionConfig, "llama": LlamaConfig, "projector": ProjectorConfig,
           "qformer": QFormerConfig}


def event_chat_config_from_dict(data: dict) -> EventChatConfig:
    kwargs = {}
    for f in dataclasses.fields(EventChatConfig):
        if f.name not in data:
            continue
        v = data[f.name]
        if f.name in _NESTED and isinstance(v, dict):
            v = _NESTED[f.name](**v)
        kwargs[f.name] = v
    return EventChatConfig(**kwargs)


def save_config(cfg: EventChatConfig, path: str) -> None:
    with open(path, "w") as f:
        json.dump(to_dict(cfg), f, indent=2)


def load_config(path: str) -> EventChatConfig:
    with open(path) as f:
        return event_chat_config_from_dict(json.load(f))


def default_attn_impl() -> str:
    """Flash prefill on TPU; dense elsewhere (the Pallas kernel only runs in
    slow interpret mode off-TPU)."""
    try:
        import jax

        return "flash" if jax.devices()[0].platform == "tpu" else "dense"
    except Exception:
        return "dense"


def from_hf_config(hf: dict, attn_impl: Optional[str] = None) -> EventChatConfig:
    """Build an EventChatConfig from an HF ``config.json`` dict.

    Understands stock LLaMA fields plus the reference's custom gating fields
    ``event_feature_adaptor`` / ``mm_use_im_start_end`` / ``mm_use_im_patch_token``
    (``model/EventChatModel.py:75``, ``inference.py:33-34``).
    ``attn_impl=None`` resolves per platform (``default_attn_impl``).
    """
    llama = LlamaConfig(
        attn_impl=attn_impl if attn_impl is not None else default_attn_impl(),
        vocab_size=hf.get("vocab_size", 32000),
        hidden_size=hf.get("hidden_size", 4096),
        intermediate_size=hf.get("intermediate_size", 11008),
        num_layers=hf.get("num_hidden_layers", 32),
        num_heads=hf.get("num_attention_heads", 32),
        num_kv_heads=hf.get("num_key_value_heads", hf.get("num_attention_heads", 32)),
        rope_theta=hf.get("rope_theta", 10000.0),
        rms_norm_eps=hf.get("rms_norm_eps", 1e-5),
        max_seq_len=min(hf.get("max_position_embeddings", 2048), 4096),
        tie_word_embeddings=hf.get("tie_word_embeddings", False),
    )
    # The reference identifies its tower by name only (``mm_visual_tower`` ->
    # CLIP ViT-L/14-336, README.md:173-177); an explicit "vision_config" dict
    # (this framework's extension, written by its own config exports)
    # overrides the dims — e.g. tiny synthetic checkpoints in tests.
    if isinstance(hf.get("vision_config"), dict):
        # Filter to known fields: HF-style vision_config dicts carry foreign
        # keys (model_type, projection_dim, ...) that must not crash the load.
        known = {f.name for f in dataclasses.fields(VisionConfig)}
        vision = VisionConfig(
            **{k: v for k, v in hf["vision_config"].items() if k in known}
        )
    else:
        vision = VisionConfig()
    # Presence of the key — not its value — gates the adaptor, matching the
    # reference's hasattr() check at model/EventChatModel.py:75-76.
    proj = ProjectorConfig(
        input_dim=vision.hidden_size,
        output_dim=llama.hidden_size,
        mlp_depth=hf.get("mm_projector_depth", 2),
        use_feature_adaptor="event_feature_adaptor" in hf,
    )
    # Value-respecting gate: a parsed config.json dict contains explicit
    # false values (unlike the reference's hasattr check on a config object,
    # model/EventChatModel.py:77), so presence alone must not enable it.
    qf_kwargs = {}
    if isinstance(hf.get("qformer_config"), dict):
        known_qf = {f.name for f in dataclasses.fields(QFormerConfig)}
        qf_kwargs = {k: v for k, v in hf["qformer_config"].items() if k in known_qf}
    return EventChatConfig(
        vision=vision,
        llama=llama,
        projector=proj,
        use_spatio_temporal_pool=hf.get("spatial_temporal_encoder", True),
        use_event_qformer=bool(hf.get("use_event_qformer", False)),
        qformer=QFormerConfig(hidden_size=llama.hidden_size, **{k: v for k, v in qf_kwargs.items() if k != "hidden_size"}),
        mm_use_im_start_end=hf.get("mm_use_im_start_end", False),
        mm_use_im_patch_token=hf.get("mm_use_im_patch_token", True),
    )
