"""DSEC-format dataset IO: HDF5 event extraction + directory layout.

Re-creation of ``dataset/io.py`` and ``dataset/directory.py`` (P8/P9 in
SURVEY.md §2.1): event extraction by index or time window via the ``ms_to_idx``
millisecond lookup table with ``t_offset`` correction, generic h5/yaml dict
loaders, the content-level directory comparison utility, and the lazy-cached
DSEC directory accessors (images / events / tracks / QA labels).
"""

from __future__ import annotations

import filecmp
import json
import os
from functools import cached_property
from typing import Dict, List, Optional, Tuple

import numpy as np

EventDict = Dict[str, np.ndarray]


# ---------------------------------------------------------------------------
# HDF5 event extraction (dataset/io.py:38-95)


def get_num_events(h5_path: str) -> int:
    """Total event count (``dataset/io.py:59-61``)."""
    import h5py

    with h5py.File(h5_path, "r") as f:
        return int(f["events"]["t"].shape[0])


def extract_from_h5_by_index(h5_path: str, lo: int, hi: int) -> EventDict:
    """Events in [lo, hi) by index (``dataset/io.py:63-65``).

    Timestamps are returned with ``t_offset`` applied, in microseconds.
    """
    import h5py

    with h5py.File(h5_path, "r") as f:
        ev = f["events"]
        t_offset = int(np.asarray(f["t_offset"])) if "t_offset" in f else 0
        return {
            "x": np.asarray(ev["x"][lo:hi]),
            "y": np.asarray(ev["y"][lo:hi]),
            "t": np.asarray(ev["t"][lo:hi], dtype=np.int64) + t_offset,
            "p": np.asarray(ev["p"][lo:hi]),
        }


def extract_from_h5_by_timewindow(
    h5_path: str, t_min_us: int, t_max_us: int
) -> EventDict:
    """Events with t in [t_min_us, t_max_us) using the ``ms_to_idx`` lookup
    (``dataset/io.py:67-87``): the table maps millisecond -> first event
    index, bounding the fine binary search to a 1 ms slab.
    """
    import h5py

    with h5py.File(h5_path, "r") as f:
        ev = f["events"]
        t_offset = int(np.asarray(f["t_offset"])) if "t_offset" in f else 0
        rel_min = t_min_us - t_offset
        rel_max = t_max_us - t_offset

        ms_to_idx = np.asarray(f["ms_to_idx"]) if "ms_to_idx" in f else None
        n = ev["t"].shape[0]
        if ms_to_idx is not None:
            ms_lo = max(min(rel_min // 1000, len(ms_to_idx) - 1), 0)
            lo_bound = int(ms_to_idx[ms_lo])
            ms_hi = rel_max // 1000 + 1
            if ms_hi >= len(ms_to_idx):
                # Window extends past the lookup table: events after the last
                # millisecond tick still belong to it — scan to the end.
                hi_bound = n
            else:
                hi_bound = int(ms_to_idx[max(ms_hi, 0)])
        else:
            lo_bound, hi_bound = 0, n
        t_slab = np.asarray(ev["t"][lo_bound:hi_bound], dtype=np.int64)
        lo = lo_bound + int(np.searchsorted(t_slab, rel_min, side="left"))
        hi = lo_bound + int(np.searchsorted(t_slab, rel_max, side="left"))
        return {
            "x": np.asarray(ev["x"][lo:hi]),
            "y": np.asarray(ev["y"][lo:hi]),
            "t": np.asarray(ev["t"][lo:hi], dtype=np.int64) + t_offset,
            "p": np.asarray(ev["p"][lo:hi]),
        }


def h5_file_to_dict(h5_path: str) -> Dict[str, np.ndarray]:
    """Whole-file flatten (``dataset/io.py:89-91``)."""
    import h5py

    out: Dict[str, np.ndarray] = {}

    def visit(name, obj):
        import h5py as _h

        if isinstance(obj, _h.Dataset):
            out[name] = np.asarray(obj)

    with h5py.File(h5_path, "r") as f:
        f.visititems(visit)
    return out


def yaml_file_to_dict(path: str) -> dict:
    """YAML loader (``dataset/io.py:93-95``)."""
    import yaml

    with open(path) as f:
        return yaml.safe_load(f)


def compare_dirs(dir1: str, dir2: str) -> bool:
    """Recursive content-level directory equality (``dataset/io.py:24-36``)."""
    cmp = filecmp.dircmp(dir1, dir2)
    if cmp.left_only or cmp.right_only or cmp.funny_files:
        return False
    _, mismatch, errors = filecmp.cmpfiles(dir1, dir2, cmp.common_files, shallow=False)
    if mismatch or errors:
        return False
    return all(
        compare_dirs(os.path.join(dir1, d), os.path.join(dir2, d))
        for d in cmp.common_dirs
    )


# ---------------------------------------------------------------------------
# DSEC directory layout (dataset/directory.py:11-53)


class ImageDirectory:
    def __init__(self, root: str):
        self.root = root

    @cached_property
    def timestamps(self) -> np.ndarray:
        return np.loadtxt(os.path.join(self.root, "timestamps.txt"), dtype=np.int64)

    @cached_property
    def image_files(self) -> List[str]:
        d = os.path.join(self.root, "left")
        if not os.path.isdir(d):
            d = self.root
        return sorted(
            os.path.join(d, f) for f in os.listdir(d)
            if f.endswith((".png", ".jpg", ".ppm"))
        )


class EventDirectory:
    def __init__(self, root: str):
        self.root = root

    @property
    def event_file(self) -> str:
        return os.path.join(self.root, "left", "events.h5")

    def num_events(self) -> int:
        return get_num_events(self.event_file)

    def by_index(self, lo: int, hi: int) -> EventDict:
        return extract_from_h5_by_index(self.event_file, lo, hi)

    def by_timewindow(self, t_min_us: int, t_max_us: int) -> EventDict:
        return extract_from_h5_by_timewindow(self.event_file, t_min_us, t_max_us)


class TracksDirectory:
    def __init__(self, root: str):
        self.root = root

    @cached_property
    def tracks(self) -> np.ndarray:
        return np.load(os.path.join(self.root, "left", "tracks.npy"))


class LabelDirectory:
    def __init__(self, root: str):
        self.root = root

    @cached_property
    def qa(self) -> list:
        with open(os.path.join(self.root, "QADataset.json")) as f:
            return json.load(f)


class DSECDirectory:
    """Lazy accessors over a DSEC sequence directory
    (``dataset/directory.py:11-17``): images/, events/, object_detections/,
    and the QA label file."""

    def __init__(self, root: str):
        self.root = root
        self.images = ImageDirectory(os.path.join(root, "images"))
        self.events = EventDirectory(os.path.join(root, "events"))
        self.tracks = TracksDirectory(os.path.join(root, "object_detections"))
        self.labels = LabelDirectory(root)
