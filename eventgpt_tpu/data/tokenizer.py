"""Tokenization utilities: the ``<event>`` sentinel splice.

Parity with ``common/common.py:43-62`` (``tokenizer_event_token``): the prompt
is split on ``<event>``, each chunk is tokenized independently, and the chunks
are rejoined with the sentinel ``EVENT_TOKEN_INDEX`` (-200) standing in for
the event-feature block. A leading BOS is preserved exactly once.

Works with any object exposing the minimal tokenizer protocol used here:
``__call__(text).input_ids`` (or returning a dict) and ``bos_token_id``.
"""

from __future__ import annotations

from typing import Any, List, Sequence

import numpy as np

from eventgpt_tpu.constants import DEFAULT_EVENT_TOKEN, EVENT_TOKEN_INDEX


def _encode(tokenizer: Any, text: str) -> List[int]:
    out = tokenizer(text)
    ids = out["input_ids"] if isinstance(out, dict) else out.input_ids
    return list(ids)


def tokenize_with_event(
    prompt: str,
    tokenizer: Any,
    event_token_index: int = EVENT_TOKEN_INDEX,
) -> List[int]:
    """Tokenize ``prompt``, replacing each ``<event>`` with the sentinel id.

    Exact semantics of the reference (``common/common.py:43-62``): when the
    tokenizer emits BOS at the start of every chunk, the BOS of the first
    chunk is kept and the BOS of subsequent chunks is dropped.
    """
    chunks = [_encode(tokenizer, c) for c in prompt.split(DEFAULT_EVENT_TOKEN)]

    input_ids: List[int] = []
    offset = 0
    if chunks and chunks[0] and chunks[0][0] == getattr(tokenizer, "bos_token_id", None):
        offset = 1
        input_ids.append(chunks[0][0])

    for i, chunk in enumerate(chunks):
        input_ids.extend(chunk[offset:])
        if i < len(chunks) - 1:
            input_ids.append(event_token_index)
    return input_ids


def split_at_event(input_ids: Sequence[int]) -> List[np.ndarray]:
    """Split an id sequence at EVENT_TOKEN_INDEX sentinels (sentinels removed).

    Returns the list of text segments; ``len(segments) == num_events + 1``.
    This is the host-side planning step for the fixed-layout embedding splice
    (the jit-friendly redesign of ``model/EventChatModel.py:292-428``).
    """
    ids = np.asarray(input_ids, dtype=np.int64)
    cut = np.where(ids == EVENT_TOKEN_INDEX)[0]
    segments: List[np.ndarray] = []
    prev = 0
    for c in cut.tolist():
        segments.append(ids[prev:c])
        prev = c + 1
    segments.append(ids[prev:])
    return segments


class ByteTokenizer:
    """Self-contained byte-level tokenizer (offline tests / smoke runs).

    Vocabulary: 0=PAD, 1=BOS, 2=EOS, bytes at 3..258, then dynamically
    registered special tokens. Implements the subset of the HF tokenizer
    protocol this framework touches, so the full pipeline can run without
    any downloaded tokenizer asset.
    """

    def __init__(self) -> None:
        self.pad_token_id = 0
        self.bos_token_id = 1
        self.eos_token_id = 2
        self._byte_offset = 3
        # Literal "<s>"/"</s>" in text map to the real BOS/EOS ids, the
        # behavior LLaVA-style prompt assembly relies on from sentencepiece.
        self._special: dict[str, int] = {"<s>": 1, "</s>": 2}

    _NUM_RESERVED_SPECIAL = 2  # <s>, </s> map into the base vocab

    def __len__(self) -> int:
        return 259 + len(self._special) - self._NUM_RESERVED_SPECIAL

    def add_tokens(self, tokens: Sequence[str], special_tokens: bool = True) -> int:
        added = 0
        for t in tokens:
            if t not in self._special:
                self._special[t] = len(self)
                added += 1
        return added

    def _encode_text(self, text: str) -> List[int]:
        ids: List[int] = []
        i = 0
        specials = sorted(self._special, key=len, reverse=True)
        while i < len(text):
            for s in specials:
                if text.startswith(s, i):
                    ids.append(self._special[s])
                    i += len(s)
                    break
            else:
                ids.extend(b + self._byte_offset for b in text[i].encode("utf-8"))
                i += 1
        return ids

    def __call__(self, text: str, add_special_tokens: bool = True):
        ids = self._encode_text(text)
        if add_special_tokens:
            ids = [self.bos_token_id] + ids
        return {"input_ids": ids}

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        inv = {v: k for k, v in self._special.items()}
        out: List[str] = []
        buf = bytearray()

        def flush() -> None:
            if buf:
                out.append(buf.decode("utf-8", errors="replace"))
                buf.clear()

        for i in ids:
            i = int(i)
            if i in (self.pad_token_id, self.bos_token_id, self.eos_token_id):
                if not skip_special_tokens:
                    flush()
                    out.append({0: "<pad>", 1: "<s>", 2: "</s>"}[i])
                continue
            if i in inv:
                flush()
                if not skip_special_tokens:
                    out.append(inv[i])
                continue
            if i >= self._byte_offset and i < self._byte_offset + 256:
                buf.append(i - self._byte_offset)
        flush()
        return "".join(out)

    def batch_decode(self, batch, skip_special_tokens: bool = True) -> List[str]:
        return [self.decode(ids, skip_special_tokens) for ids in batch]


def load_tokenizer(model_path: str):
    """Load an HF tokenizer from a local path, or the ByteTokenizer fallback.

    Replaces ``AutoTokenizer.from_pretrained(..., use_fast=False)`` at
    ``inference.py:29``; ``model_path='byte'`` selects the offline fallback.
    """
    if model_path == "byte":
        return ByteTokenizer()
    from transformers import AutoTokenizer  # local import: heavy

    return AutoTokenizer.from_pretrained(model_path, use_fast=False)
