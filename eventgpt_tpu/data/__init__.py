from eventgpt_tpu.data.conversation import (  # noqa: F401
    Conversation,
    SeparatorStyle,
    conv_templates,
    default_conversation,
    prepare_event_prompt,
)
from eventgpt_tpu.data.tokenizer import tokenize_with_event  # noqa: F401
