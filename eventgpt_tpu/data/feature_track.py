"""Feature-track → training-data adapter: the consumer of the native
offline generator (SURVEY §2.3's stated seam).

``egpt_feature_track <rig.yaml> tracks.csv <npy_dir>`` (native/src/
feature_track_main.cpp) detects + KLT-tracks features on RGB frames,
projects them into the event camera, and writes per-interval event
windows as structured {x,y,t,p} .npy (the exact layout
``ops/raster.load_event_npy`` reads). This module turns that output into
auto-labeled motion-QA samples in the dataset-JSON schema
``train/data.EventChatDataset`` consumes — so the C++ toolchain's output
feeds training directly, closing the loop the reference's
``preprocess/feature_track/README.md:1-7`` describes but never wires up
(its tracker emits files nothing downstream reads).

Labels are derived, not annotated: the per-interval median track
displacement gives a dominant motion direction (8-way compass in IMAGE
coordinates: +x = right, +y = down) and a pixel speed — the kind of
self-supervised grounding question an event-camera QA model can actually
be trained on from raw footage.
"""

from __future__ import annotations

import csv
import json
import math
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

# 8-way compass by displacement angle; image coords (+y is DOWN).
_DIRS = ["right", "down-right", "down", "down-left",
         "left", "up-left", "up", "up-right"]

MOTION_QUESTION = "What is the dominant motion direction in this clip?"


def load_tracks_csv(path: str) -> List[Dict[str, float]]:
    """Rows of egpt_feature_track's tracks.csv as typed dicts."""
    out: List[Dict[str, float]] = []
    with open(path, newline="") as f:
        for row in csv.DictReader(f):
            out.append({k: float(v) for k, v in row.items()})
    return out


def dominant_motion(rows: Sequence[Dict[str, float]]):
    """Median displacement over one frame's tracks -> (direction word,
    speed px/frame, n_tracks). Median (not mean) so a few RANSAC
    stragglers cannot flip the direction."""
    dx = float(np.median([r["cur_x"] - r["prev_x"] for r in rows]))
    dy = float(np.median([r["cur_y"] - r["prev_y"] for r in rows]))
    speed = math.hypot(dx, dy)
    ang = math.atan2(dy, dx)  # image coords: +y down
    sector = int(round(ang / (math.pi / 4))) % 8
    return _DIRS[sector], speed, len(rows)


def tracks_to_dataset(
    csv_path: str,
    events_dir: str,
    out_json: str,
    min_tracks: int = 3,
    min_speed: float = 0.5,
    still_speed: Optional[float] = None,
) -> int:
    """tracks.csv + events_%06d.npy windows -> EventChatDataset JSON.

    One sample per tracked frame interval with >= ``min_tracks``
    surviving tracks: the interval's event window is the visual input,
    the question asks for the dominant motion, the answer states the
    compass direction. The two speed knobs are independent (ADVICE r4 —
    they were previously conflated): ``still_speed``, when given, labels
    intervals below it "mostly still" (a trainable negative class);
    ``min_speed`` then DROPS intervals below it that were not claimed as
    still — too slow for a direction label, too fast for a still one.
    With the default ``still_speed=None`` slow intervals are simply
    filtered. Returns the number of samples written.
    """
    rows = load_tracks_csv(csv_path)
    by_frame: Dict[int, List[Dict[str, float]]] = {}
    for r in rows:
        by_frame.setdefault(int(r["frame"]), []).append(r)

    entries = []
    for frame in sorted(by_frame):
        rows_f = by_frame[frame]
        if len(rows_f) < min_tracks:
            continue
        npy = f"events_{frame:06d}.npy"
        if not os.path.exists(os.path.join(events_dir, npy)):
            continue
        direction, speed, n = dominant_motion(rows_f)
        if still_speed is not None and speed < still_speed:
            answer = ("The scene is mostly still; the tracked features "
                      "barely move between frames.")
        elif speed < min_speed:
            continue
        else:
            answer = (f"The dominant motion is toward the {direction}, "
                      f"at about {speed:.1f} pixels per frame across "
                      f"{n} tracked features.")
        entries.append({
            "id": f"feature_track_{frame:06d}",
            "event": npy,
            "conversations": [
                {"from": "human", "value": f"<event>\n{MOTION_QUESTION}"},
                {"from": "gpt", "value": answer},
            ],
        })
    with open(out_json, "w") as f:
        json.dump(entries, f, indent=1)
    return len(entries)


def main(argv=None):
    """CLI: egpt_feature_track output -> dataset JSON.

    python -m eventgpt_tpu.data.feature_track tracks.csv win/ qa.json
    then train on it: cli.train --data_path qa.json --event_folder win/
    """
    import argparse

    p = argparse.ArgumentParser(description=main.__doc__)
    p.add_argument("tracks_csv")
    p.add_argument("events_dir")
    p.add_argument("out_json")
    p.add_argument("--min_tracks", type=int, default=3)
    p.add_argument("--min_speed", type=float, default=0.5,
                   help="drop intervals slower than this (px/frame)")
    p.add_argument("--still_speed", type=float, default=None,
                   help="label intervals below this 'mostly still' "
                        "instead of dropping them")
    args = p.parse_args(argv)
    n = tracks_to_dataset(args.tracks_csv, args.events_dir, args.out_json,
                          min_tracks=args.min_tracks,
                          min_speed=args.min_speed,
                          still_speed=args.still_speed)
    print(f"wrote {n} samples to {args.out_json}")
    return n


if __name__ == "__main__":
    main()
