"""Deterministic synthetic motion-QA corpus — the reproducible in-tree
distribution for the trained-draft acceptance study (VERDICT r4 #2).

Each sample is a point cloud drifting in one of 8 compass directions at a
class-determined speed; the event stream is written in the framework's
native structured ``{x,y,t,p}`` npy layout (the same one
``ops/raster.load_event_npy`` and the C++ ``SaveEventsNpy`` share), and the
caption states the direction and speed plus a per-sample track count:

    "moving down-left at 4.0 px per frame over 17 tracks."

Why this shape: the direction/speed mapping is *learnable from pixels* (a
finetuned model becomes deterministic on it), while the track count varies
per sample — so a drafting rule that can only echo previously served text
(``_suffix_vote_drafts``) faces genuine branch points, and trained Medusa
heads, which condition on the model's own hidden state, can be measured
against it fairly on identical traffic.

Everything is seeded; two builds of the same corpus are byte-identical.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Tuple

import numpy as np

# 8-way compass in image coordinates (+y down), matching
# data/feature_track._DIRS vocabulary.
DIRECTIONS: Tuple[str, ...] = (
    "right", "down-right", "down", "down-left",
    "left", "up-left", "up", "up-right",
)

MOTION_QUESTION = "What is the dominant motion direction in this clip?"

_CANVAS = 64          # event-camera resolution of the synthetic scene
_WINDOW_US = 50_000   # one 50 ms stream per sample (the reference's window)


def _class_speed(direction_idx: int) -> float:
    """Speed is a deterministic function of the class so the pixel->caption
    mapping is fully learnable (1.0, 1.5, ... 4.5 px/frame)."""
    return 1.0 + 0.5 * direction_idx


def synth_event_stream(
    direction_idx: int, n_tracks: int, seed: int,
    n_frames: int = 5,
) -> np.ndarray:
    """Structured {x,y,t,p} stream: ``n_tracks`` points drifting along the
    class direction across ``n_frames`` equal-count windows."""
    rng = np.random.default_rng(seed)
    ang = direction_idx * (np.pi / 4.0)
    dx, dy = np.cos(ang), np.sin(ang)  # +y down is implicit in raster
    speed = _class_speed(direction_idx)
    margin = speed * n_frames + 2
    px = rng.uniform(margin, _CANVAS - margin, size=n_tracks)
    py = rng.uniform(margin, _CANVAS - margin, size=n_tracks)
    pol = rng.integers(0, 2, size=n_tracks)

    xs, ys, ts, ps = [], [], [], []
    events_per_frame = 12  # events per track per frame: a visible dot trail
    for f in range(n_frames):
        fx = px + dx * speed * f
        fy = py + dy * speed * f
        jitter = rng.normal(scale=0.4, size=(events_per_frame, n_tracks, 2))
        t0 = f * (_WINDOW_US // n_frames)
        t1 = (f + 1) * (_WINDOW_US // n_frames)
        for e in range(events_per_frame):
            xs.append(fx + jitter[e, :, 0])
            ys.append(fy + jitter[e, :, 1])
            ts.append(rng.integers(t0, t1, size=n_tracks))
            ps.append(pol)
    from eventgpt_tpu.ops.raster import STREAM_DTYPE

    x = np.clip(np.concatenate(xs), 0, _CANVAS - 1)
    y = np.clip(np.concatenate(ys), 0, _CANVAS - 1)
    t = np.concatenate(ts)
    p = np.concatenate(ps)
    order = np.argsort(t, kind="stable")
    out = np.empty(x.shape[0], dtype=STREAM_DTYPE)  # the ONE shared layout
    out["x"], out["y"] = x[order].astype(np.uint16), y[order].astype(np.uint16)
    out["t"], out["p"] = t[order], p[order].astype(np.uint8)
    return out


def caption(direction_idx: int, n_tracks: int) -> str:
    return (f"moving {DIRECTIONS[direction_idx]} at "
            f"{_class_speed(direction_idx):.1f} px per frame over "
            f"{n_tracks} tracks.")


def build_motion_corpus(
    out_dir: str, n_train: int = 96, n_eval: int = 16, seed: int = 0,
) -> Dict[str, str]:
    """Write events/*.npy + train.json + eval.json under ``out_dir``.

    Returns {"train": ..., "eval": ..., "events": ...} paths. Train and
    eval draw from the same class structure but disjoint seeds, so eval
    streams (and their track counts) are unseen.
    """
    ev_dir = os.path.join(out_dir, "events")
    os.makedirs(ev_dir, exist_ok=True)
    rng = np.random.default_rng(seed)

    def make_split(name: str, n: int, seed_base: int) -> str:
        entries: List[dict] = []
        for i in range(n):
            d = i % len(DIRECTIONS)
            n_tracks = int(rng.integers(5, 40))
            stream = synth_event_stream(d, n_tracks, seed_base + i)
            npy = f"{name}_{i:04d}.npy"
            np.save(os.path.join(ev_dir, npy), stream)
            entries.append({
                "id": f"motion_{name}_{i:04d}",
                "event": npy,
                "conversations": [
                    {"from": "human", "value": f"<event>\n{MOTION_QUESTION}"},
                    {"from": "gpt", "value": caption(d, n_tracks)},
                ],
            })
        path = os.path.join(out_dir, f"{name}.json")
        with open(path, "w") as f:
            json.dump(entries, f, indent=1)
        return path

    train = make_split("train", n_train, seed_base=10_000)
    evalp = make_split("eval", n_eval, seed_base=20_000)
    return {"train": train, "eval": evalp, "events": ev_dir}
