"""Conversation templating for event-QA prompts.

Behavioral parity with the reference's ``dataset/conversation.py``: the
``eventgpt_v1`` template is Vicuna-v1 style (two-separator), and
``prepare_event_prompt`` wraps the query with
``<ev_start><event><ev_end>\\n`` (``dataset/conversation.py:212-237``).

This is a clean reimplementation: prompt assembly only (strings in, strings
out). The reference's gradio/base64 image helpers serve an unshipped web UI
and are intentionally out of scope for the framework core.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import List, Optional, Sequence, Tuple

from eventgpt_tpu.constants import (
    DEFAULT_EV_END_TOKEN,
    DEFAULT_EV_START_TOKEN,
    DEFAULT_EVENT_TOKEN,
)


class SeparatorStyle(enum.Enum):
    SINGLE = enum.auto()
    TWO = enum.auto()
    MPT = enum.auto()
    PLAIN = enum.auto()
    LLAMA_2 = enum.auto()


@dataclasses.dataclass
class Conversation:
    """An ordered list of (role, message) turns plus a rendering style.

    Module-level templates are frozen with ``messages=()`` (a tuple) so that
    accidental in-place mutation of a template fails loudly; always work on a
    ``.copy()``, which materializes a fresh list.
    """

    system: str
    roles: Tuple[str, str]
    messages: Sequence[Sequence[Optional[str]]]
    offset: int = 0
    sep_style: SeparatorStyle = SeparatorStyle.SINGLE
    sep: str = "###"
    sep2: Optional[str] = None
    version: str = "unknown"

    def append_message(self, role: str, message: Optional[str]) -> None:
        if not isinstance(self.messages, list):
            raise TypeError(
                "cannot append to a frozen conversation template; use .copy() first"
            )
        self.messages.append([role, message])

    def get_prompt(self) -> str:
        style = self.sep_style
        if style == SeparatorStyle.SINGLE:
            out = [self.system, self.sep]
            for role, msg in self.messages:
                out.append(f"{role}: {msg}{self.sep}" if msg else f"{role}:")
            return "".join(out)
        if style == SeparatorStyle.TWO:
            seps = (self.sep, self.sep2)
            out = [self.system, seps[0]]
            for i, (role, msg) in enumerate(self.messages):
                out.append(f"{role}: {msg}{seps[i % 2]}" if msg else f"{role}:")
            return "".join(out)
        if style == SeparatorStyle.MPT:
            out = [self.system, self.sep]
            for role, msg in self.messages:
                out.append(f"{role}{msg}{self.sep}" if msg else role)
            return "".join(out)
        if style == SeparatorStyle.PLAIN:
            seps = (self.sep, self.sep2)
            out = [self.system]
            for i, (_, msg) in enumerate(self.messages):
                out.append(f"{msg}{seps[i % 2]}" if msg else "")
            return "".join(out)
        if style == SeparatorStyle.LLAMA_2:
            def wrap_sys(m: str) -> str:
                return f"<<SYS>>\n{m}\n<</SYS>>\n\n" if m else m

            out = []
            for i, (role, msg) in enumerate(self.messages):
                if i == 0:
                    if not msg:
                        raise ValueError("first message must be non-empty")
                    if role != self.roles[0]:
                        raise ValueError("first message must come from the user role")
                if not msg:
                    continue
                if i == 0:
                    msg = wrap_sys(self.system) + msg
                if i % 2 == 0:
                    out.append(f"{self.sep}[INST] {msg} [/INST]")
                else:
                    out.append(f" {msg} {self.sep2}")
            return "".join(out).lstrip(self.sep)
        raise ValueError(f"Invalid separator style: {style}")

    def copy(self) -> "Conversation":
        return Conversation(
            system=self.system,
            roles=self.roles,
            messages=[[r, m] for r, m in self.messages],
            offset=self.offset,
            sep_style=self.sep_style,
            sep=self.sep,
            sep2=self.sep2,
            version=self.version,
        )

    def to_dict(self) -> dict:
        return {
            "system": self.system,
            "roles": list(self.roles),
            "messages": self.messages,
            "offset": self.offset,
            "sep": self.sep,
            "sep2": self.sep2,
        }


conv_eventgpt_v1 = Conversation(
    system=(
        "A chat between a curious human and an artificial intelligence assistant. "
        "The assistant gives helpful, detailed, and polite answers to the human's questions."
    ),
    roles=("USER", "ASSISTANT"),
    messages=(),
    offset=0,
    sep_style=SeparatorStyle.TWO,
    sep=" ",
    sep2="</s>",
    version="v1",
)

# Plain style used by the pretraining alignment stage (projector warm-up):
# bare "<event>\ncaption</s>" pairs, mirroring LLaVA's "plain" conversation
# version referenced by preprocess_plain in the training pyc (SURVEY.md §2.2).
conv_eventgpt_plain = Conversation(
    system="",
    roles=("", ""),
    messages=(),
    offset=0,
    sep_style=SeparatorStyle.PLAIN,
    sep="\n",
    sep2="</s>",
    version="plain",
)

default_conversation = conv_eventgpt_v1
conv_templates = {
    "eventgpt_v1": conv_eventgpt_v1,
    "eventgpt_plain": conv_eventgpt_plain,
}


def prepare_event_prompt(query: str, conv_mode: str = "eventgpt_v1") -> str:
    """Render a single-turn event-QA prompt.

    Parity: ``dataset/conversation.py:229-237`` — the query is prefixed with
    ``<ev_start><event><ev_end>\\n`` and rendered with an empty assistant turn.
    """
    qs = DEFAULT_EV_START_TOKEN + DEFAULT_EVENT_TOKEN + DEFAULT_EV_END_TOKEN + "\n" + query
    conv = conv_templates[conv_mode].copy()
    conv.append_message(conv.roles[0], qs)
    conv.append_message(conv.roles[1], None)
    return conv.get_prompt()


def render_multiturn(turns: Sequence[Tuple[str, str]], conv_mode: str = "eventgpt_v1") -> str:
    """Render a full multi-turn conversation (training-time prompt assembly)."""
    conv = conv_templates[conv_mode].copy()
    for role, msg in turns:
        conv.append_message(role, msg)
    return conv.get_prompt()
