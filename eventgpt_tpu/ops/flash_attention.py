"""Pallas TPU flash attention for the prefill path.

The reference relies on flash-attn via pip on GPU (``requirements.txt:31``);
this is the TPU-native equivalent: a fused attention kernel that never
materializes the (S, S) score matrix in HBM. Per (batch*head, q-block) grid
cell, the kernel streams KV blocks through VMEM with online-softmax
accumulation in f32 (the flash recurrence), applying causal + padding masks
inline. Softmax statistics live in registers; the MXU sees one
(BLOCK_Q, hd) x (hd, BLOCK_K) and one (BLOCK_Q, BLOCK_K) x (BLOCK_K, hd)
matmul per step.

On non-TPU backends the kernel runs in interpreter mode (tests on the CPU
mesh); the dense path in ``models/llama.py`` remains the default until the
config opts in (``LlamaConfig.attn_impl = "flash"``).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = float(jnp.finfo(jnp.float32).min)


def _flash_kernel(q_ref, k_ref, v_ref, valid_ref, out_ref, *,
                  block_k: int, causal: bool, scale: float):
    """One (batch*head, q-block) cell: stream KV blocks, online softmax.

    Shapes: q_ref (BQ, hd); k_ref/v_ref (S, hd); valid_ref (1, S) int32;
    out_ref (BQ, hd).
    """
    bq, hd = q_ref.shape
    s = k_ref.shape[0]
    q_start = pl.program_id(1) * bq

    q = q_ref[:].astype(jnp.float32) * scale
    q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    num_kv = s // block_k

    def body(kb, carry):
        acc, m, l = carry
        k_off = kb * block_k
        k_blk = k_ref[pl.ds(k_off, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(k_off, block_k), :].astype(jnp.float32)

        scores = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (BQ, BK)

        k_pos = k_off + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
        mask = valid_ref[0, pl.ds(k_off, block_k)][None, :] > 0
        if causal:
            mask = jnp.logical_and(mask, k_pos <= q_pos)
        scores = jnp.where(mask, scores, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(scores, axis=1))
        p = jnp.exp(scores - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=1)
        pv = jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_new = acc * corr[:, None] + pv
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((bq, hd), jnp.float32)
    m0 = jnp.full((bq,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)

    if causal:
        # KV blocks strictly above the diagonal contribute nothing; bound the
        # loop at the last block this q-block can see.
        num_kv_eff = jax.lax.div(q_start + bq - 1, block_k) + 1
        num_kv_eff = jnp.minimum(num_kv_eff, num_kv)
    else:
        num_kv_eff = num_kv
    acc, m, l = jax.lax.fori_loop(0, num_kv_eff, body, (acc0, m0, l0))

    # Fully-masked rows (padding queries) have l == 0; emit zeros.
    l_safe = jnp.maximum(l, 1e-30)
    out_ref[:] = (acc / l_safe[:, None]).astype(out_ref.dtype)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    valid: jnp.ndarray | None = None,
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Fused attention. q/k/v: (B, S, H, hd) with KV already head-repeated;
    ``valid``: (B, S) bool padding mask. Returns (B, S, H, hd) in q.dtype.

    Differentiable: the forward pass is the Pallas kernel; the backward pass
    recomputes attention densely (standard softmax-attention VJP) — at the
    2048-token parity envelope the (S, S) backward materialization matches
    what the reference's training path did anyway.

    S is padded to a block multiple internally; hd should be a multiple of
    128 for peak MXU utilization (LLaMA-7B: hd=128).
    """
    b, s, h, hd = q.shape
    if valid is None:
        valid = jnp.ones((b, s), bool)
    return _flash_vjp(q, k, v, valid, causal, block_q, block_k, interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_vjp(q, k, v, valid, causal, block_q, block_k, interpret):
    return _flash_forward(q, k, v, valid, causal, block_q, block_k, interpret)


def _flash_vjp_fwd(q, k, v, valid, causal, block_q, block_k, interpret):
    out = _flash_forward(q, k, v, valid, causal, block_q, block_k, interpret)
    return out, (q, k, v, valid)


def _flash_vjp_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, valid = res
    scale = 1.0 / math.sqrt(q.shape[-1])
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    gf = g.astype(jnp.float32)

    s = q.shape[1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", qf, kf,
                        preferred_element_type=jnp.float32) * scale
    mask = valid[:, None, None, :]
    if causal:
        pos = jnp.arange(s)
        mask = mask & (pos[None, None, None, :] <= pos[None, None, :, None])
    scores = jnp.where(mask, scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    # Zero fully-masked (padded-query) rows, matching the forward's zeroing.
    p = p * valid[:, None, :, None]

    dv = jnp.einsum("bhqk,bqhd->bkhd", p, gf, preferred_element_type=jnp.float32)
    dp = jnp.einsum("bqhd,bkhd->bhqk", gf, vf, preferred_element_type=jnp.float32)
    ds = p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True))
    dq = jnp.einsum("bhqk,bkhd->bqhd", ds, kf,
                    preferred_element_type=jnp.float32) * scale
    dk = jnp.einsum("bhqk,bqhd->bkhd", ds, qf,
                    preferred_element_type=jnp.float32) * scale
    import numpy as _np

    dvalid = _np.zeros(valid.shape, dtype=jax.dtypes.float0)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype), dvalid


_flash_vjp.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def _lcm(a: int, b: int) -> int:
    return a * b // math.gcd(a, b)


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret")
)
def _flash_forward(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    valid: jnp.ndarray,
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> jnp.ndarray:
    b, s, h, hd = q.shape
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"

    # Pad to a common multiple so both the q-grid and the kv loop tile S
    # exactly (max() alone under-covers when neither block divides the other).
    unit = _lcm(block_q, block_k)
    s_pad = ((s + unit - 1) // unit) * unit
    if s_pad != s:
        pad = ((0, 0), (0, s_pad - s), (0, 0), (0, 0))
        q = jnp.pad(q, pad)
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
        valid = jnp.pad(valid, ((0, 0), (0, s_pad - s)))

    # (B, S, H, hd) -> (B*H, S, hd)
    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s_pad, hd)

    qh, kh, vh = to_bh(q), to_bh(k), to_bh(v)
    valid_i = jnp.repeat(valid.astype(jnp.int32), h, axis=0)[:, None, :]  # (B*H,1,S)

    scale = 1.0 / math.sqrt(hd)
    kernel = functools.partial(
        _flash_kernel, block_k=block_k, causal=causal, scale=scale
    )
    out = pl.pallas_call(
        kernel,
        grid=(b * h, s_pad // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, hd), lambda bh, qb: (bh, qb, 0)),
            pl.BlockSpec((None, s_pad, hd), lambda bh, qb: (bh, 0, 0)),
            pl.BlockSpec((None, s_pad, hd), lambda bh, qb: (bh, 0, 0)),
            pl.BlockSpec((None, 1, s_pad), lambda bh, qb: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, hd), lambda bh, qb: (bh, qb, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, s_pad, hd), q.dtype),
        interpret=interpret,
    )(qh, kh, vh, valid_i)

    out = out.reshape(b, h, s_pad, hd).transpose(0, 2, 1, 3)[:, :s]
    # Zero padded-query rows (kv masking alone leaves them attending).
    return jnp.where(valid[:, :s, None, None], out, 0)
