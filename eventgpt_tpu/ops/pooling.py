"""Spatio-temporal aggregation of per-frame vision features.

Parity with ``get_spatio_temporal_features`` (``model/EventChatModel.py:15-38``):
given per-frame features (t, s, c),

  * temporal tokens = mean over the spatial axis -> (t, c), row-padded with
    zeros (or truncated) to ``num_temporal_tokens``;
  * spatial tokens  = mean over the temporal axis -> (s, c);
  * output = concat([temporal, spatial]) -> (t' + s, c).

With t=5 frames and s=577 CLIP tokens this yields the reference's 582 event
tokens. Pure jnp; shape-static, so it fuses into the surrounding jit.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp


def spatio_temporal_pool(
    features: jnp.ndarray,
    num_temporal_tokens: Optional[int] = None,
) -> jnp.ndarray:
    """(t, s, c) frame features -> (num_temporal_tokens + s, c) event tokens."""
    if features.ndim != 3:
        raise ValueError(f"expected (t, s, c) features, got shape {features.shape}")
    t = features.shape[0]
    if num_temporal_tokens is None:
        num_temporal_tokens = t

    temporal = features.mean(axis=1)  # (t, c)
    if num_temporal_tokens > t:
        temporal = jnp.pad(temporal, ((0, num_temporal_tokens - t), (0, 0)))
    elif num_temporal_tokens < t:
        temporal = temporal[:num_temporal_tokens]

    spatial = features.mean(axis=0)  # (s, c)
    return jnp.concatenate([temporal, spatial], axis=0)
