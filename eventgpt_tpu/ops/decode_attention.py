"""Pallas fused int8-KV decode attention (single query over the HBM cache).

Why this kernel exists: batch-1 decode at 7B streams the whole weight set
per token (PERFORMANCE.md), and the KV cache is the next-largest stream —
~0.5-0.7 GB/token bf16 at the reference's 512-token budget. The int8 cache
halves those bytes, but through plain XLA the dequantize (int8 * f32 scale
-> bf16) costs more VPU time than the bandwidth it saves: measured a WASH
at batch 1 (12.3 vs 11.9 ms/token, PERFORMANCE.md negative results). This
kernel performs the dequant in VMEM fused into the attention dots, so HBM
traffic actually drops to the int8 payload + per-vector scales and the
wash becomes a win.

Shape/layout contract (matches ``models/llama.py`` cache layout):
  * cache buffers: (L, B, S, KV, hd) int8 payload, (L, B, S, KV, 1) f32
    scales — the kernel receives the FULL stacked-layer buffer and selects
    the layer with a scalar-prefetched index (``PrefetchScalarGridSpec``),
    so the surrounding ``lax.scan`` over layers never materializes a
    per-layer slice copy.
  * q: (B, KV, G, hd) — post-RoPE query heads regrouped per KV head
    (G = H // KV, GQA-aware without repeating K/V).
  * n_valid: (B,) int32 — slots [0, n_valid) are attendable (the caller has
    already written the current token's K/V at slot n_valid-1).

Grid: (B, KV); each cell computes (G, hd) of output from one row's one KV
head: dequantized (S, hd) K/V tiles live only in VMEM. S is padded to a
lane multiple by the caller (cache lengths are bucket-aligned already).

On non-TPU backends the kernel runs in interpreter mode (CPU-mesh tests),
like ``ops/flash_attention.py``.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float(jnp.finfo(jnp.float32).min)

# jax 0.4.x ships the Mosaic compile options as TPUCompilerParams; newer
# releases renamed it to CompilerParams. Same fields either way — the
# shim lives in eventgpt_tpu/compat.py with the other version shims.
from eventgpt_tpu.compat import pallas_compiler_params as _CompilerParams


def _decode_attn_kernel(li_ref, nv_ref, q_ref, kq_ref, ks_ref, vq_ref,
                        vs_ref, o_ref, *, scale: float, block_kv: int):
    """One (batch row, KV-head group) cell: dequant + masked attention.

    Block refs (layer axis dropped by its None block dim): q
    (1, block_kv, G, hd); payloads (1, S, block_kv, hd); scales
    (1, S, block_kv, 1). TPU tiling wants the last two block dims
    (divisible-by-8, 128-multiple-or-full), which is why KV rides in
    groups of ``block_kv`` and the head loop is unrolled here instead of
    gridded.
    """
    b = pl.program_id(0)
    nv = nv_ref[b]

    for h in range(block_kv):
        # Scales are per cache ROW (one f32 per (slot, head)), so they
        # commute past the hd-contraction: score[g,j] = (q . k8[j]) * ks[j],
        # and p @ (v8 * vs) = (p * vs^T) @ v8. Applying them post-dot means
        # the only VMEM temps are bf16 casts of the int8 payloads (int8
        # values are exactly representable in bf16) instead of f32
        # dequantized planes — that difference is what fits the kernel in
        # scoped VMEM at S ~ 1200.
        q = q_ref[0, h].astype(jnp.bfloat16)                     # (G, hd)
        k8 = kq_ref[0, :, h, :].astype(jnp.bfloat16)             # (S, hd)
        s = jax.lax.dot_general(
            q, k8, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * (ks_ref[0, :, h].reshape(1, -1) * scale)             # (G, S)

        g, s_len = s.shape
        j = jax.lax.broadcasted_iota(jnp.int32, (g, s_len), 1)
        s = jnp.where(j < nv, s, NEG_INF)

        m = s.max(axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = p.sum(axis=-1, keepdims=True)
        pv = (p * vs_ref[0, :, h].reshape(1, -1)).astype(jnp.bfloat16)
        v8 = vq_ref[0, :, h, :].astype(jnp.bfloat16)             # (S, hd)
        o = jax.lax.dot_general(
            pv, v8, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) / jnp.maximum(l, 1e-30)
        o_ref[0, h] = o.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def decode_attention_int8(
    q: jnp.ndarray,       # (B, KV, G, hd) post-RoPE queries
    k_q: jnp.ndarray,     # (L, B, S, KV, hd) int8
    k_s: jnp.ndarray,     # (L, B, S, KV, 1) f32
    v_q: jnp.ndarray,     # (L, B, S, KV, hd) int8
    v_s: jnp.ndarray,     # (L, B, S, KV, 1) f32
    li: jnp.ndarray,      # scalar int32 layer index
    n_valid: jnp.ndarray,  # (B,) int32 attendable slot count
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Returns (B, KV, G, hd) attention context in q.dtype."""
    b, kv, g, hd = q.shape
    _, _, s, _, _ = k_q.shape
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    scale = 1.0 / math.sqrt(hd)
    # KV-head group per grid cell: last-two block-dim tiling wants the KV
    # block divisible by 8 (or the full axis); 8 keeps VMEM per cell at
    # ~2.4 MB of int8 payload for S~1152.
    block_kv = 8 if kv % 8 == 0 else kv

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # (li, n_valid)
        grid=(b, kv // block_kv),
        in_specs=[
            pl.BlockSpec((1, block_kv, g, hd),
                         lambda bi, hi, li_r, nv_r: (bi, hi, 0, 0)),
            pl.BlockSpec((None, 1, s, block_kv, hd),
                         lambda bi, hi, li_r, nv_r: (li_r[0], bi, 0, hi, 0)),
            pl.BlockSpec((None, 1, s, block_kv, 1),
                         lambda bi, hi, li_r, nv_r: (li_r[0], bi, 0, hi, 0)),
            pl.BlockSpec((None, 1, s, block_kv, hd),
                         lambda bi, hi, li_r, nv_r: (li_r[0], bi, 0, hi, 0)),
            pl.BlockSpec((None, 1, s, block_kv, 1),
                         lambda bi, hi, li_r, nv_r: (li_r[0], bi, 0, hi, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_kv, g, hd),
                               lambda bi, hi, li_r, nv_r: (bi, hi, 0, 0)),
    )
    return pl.pallas_call(
        functools.partial(_decode_attn_kernel, scale=scale, block_kv=block_kv),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kv, g, hd), q.dtype),
        interpret=interpret,
        # Double-buffered int8 blocks + per-head cast temps exceed the 16 MB
        # default scoped-VMEM budget at S ~ 1200; v5e has 128 MB VMEM.
        compiler_params=_CompilerParams(
            vmem_limit_bytes=64 * 1024 * 1024
        ),
    )(jnp.asarray(li, jnp.int32).reshape(1), jnp.asarray(n_valid, jnp.int32),
      q, k_q, k_s, v_q, v_s)


def decode_attention_int8_reference(q, k_q, k_s, v_q, v_s, li, n_valid):
    """Plain-XLA semantics twin (dequant-then-attend) for tests."""
    b, kv, g, hd = q.shape
    k = (k_q[li].astype(jnp.float32) * k_s[li])  # (B, S, KV, hd)
    v = (v_q[li].astype(jnp.float32) * v_s[li])
    s = jnp.einsum("bkgd,bskd->bkgs", q.astype(jnp.float32), k) / math.sqrt(hd)
    mask = jnp.arange(k.shape[1])[None, None, None, :] < n_valid[:, None, None, None]
    s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bkgs,bskd->bkgd", p, v)
    return o.astype(q.dtype)


# -- paged decode attention (ISSUE 12) ------------------------------------
#
# The serving cache becomes ONE block-pool arena (L, N, bs, KV, hd) plus
# per-row int32 block tables (serve.py kv_layout="paged"). The scheduler's
# CPU-tier fallback gathers the table into the dense (B, S, KV, hd) view
# inside the layer scan (models/llama._cache_read_layer — a per-layer
# TEMPORARY, 1/L of the dense cache's residency). This kernel is the TPU
# form of that read: attention runs block-by-block with a scalar-
# prefetched block table steering the BlockSpec index_map, an online-
# softmax accumulator carrying (m, l, acc) across the block axis — the
# dense view is never materialized at all, and HBM streams only the int8
# payload + scales of the blocks the row actually owns a table entry for.


def _paged_attn_kernel(li_ref, bt_ref, nv_ref, q_ref, kq_ref, ks_ref,
                       vq_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref, *,
                       scale: float, block_kv: int, n_bpr: int):
    """One (row, head group, table entry) cell: dequant + one block's
    masked partial attention, folded into the running online-softmax
    state. Grid order is (b, hi, ni) with ni FASTEST, so the scratch
    (m, l, acc) carries exactly one (b, hi) cell's accumulation: ni == 0
    initializes it, ni == n_bpr - 1 normalizes into the output block
    (revisited across ni — it stays resident in VMEM)."""
    b = pl.program_id(0)
    ni = pl.program_id(2)
    nv = nv_ref[b]
    bs = kq_ref.shape[1]

    @pl.when(ni == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    for h in range(block_kv):
        # Same post-dot scale placement as the dense kernel: bf16 casts
        # of int8 payloads are the only VMEM temps.
        q = q_ref[0, h].astype(jnp.bfloat16)                     # (G, hd)
        k8 = kq_ref[0, :, h, :].astype(jnp.bfloat16)             # (bs, hd)
        s = jax.lax.dot_general(
            q, k8, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * (ks_ref[0, :, h].reshape(1, -1) * scale)             # (G, bs)

        g, _ = s.shape
        j = jax.lax.broadcasted_iota(jnp.int32, (g, bs), 1) + ni * bs
        s = jnp.where(j < nv, s, NEG_INF)

        m_prev = m_ref[h]                                        # (G, 1)
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                                   # (G, bs)
        l_new = l_ref[h] * alpha + p.sum(axis=-1, keepdims=True)
        pv = (p * vs_ref[0, :, h].reshape(1, -1)).astype(jnp.bfloat16)
        v8 = vq_ref[0, :, h, :].astype(jnp.bfloat16)             # (bs, hd)
        acc = acc_ref[h] * alpha + jax.lax.dot_general(
            pv, v8, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[h] = m_new
        l_ref[h] = l_new
        acc_ref[h] = acc

    @pl.when(ni == n_bpr - 1)
    def _finalize():
        for h in range(block_kv):
            o_ref[0, h] = (acc_ref[h]
                           / jnp.maximum(l_ref[h], 1e-30)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def decode_attention_int8_paged(
    q: jnp.ndarray,        # (B, KV, G, hd) post-RoPE queries
    k_q: jnp.ndarray,      # (L, N, bs, KV, hd) int8 pool arena
    k_s: jnp.ndarray,      # (L, N, bs, KV, 1) f32 scales
    v_q: jnp.ndarray,      # (L, N, bs, KV, hd) int8
    v_s: jnp.ndarray,      # (L, N, bs, KV, 1) f32
    li: jnp.ndarray,       # scalar int32 layer index
    block_tables: jnp.ndarray,  # (B, n_bpr) int32 pool block per row slot
    n_valid: jnp.ndarray,  # (B,) int32 attendable LOGICAL slot count
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Returns (B, KV, G, hd) attention context in q.dtype — the paged
    twin of ``decode_attention_int8``: identical math over the blocks
    ``block_tables`` names, streaming only those blocks from HBM."""
    b, kv, g, hd = q.shape
    _, _, bs, _, _ = k_q.shape
    n_bpr = block_tables.shape[1]
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    scale = 1.0 / math.sqrt(hd)
    block_kv = 8 if kv % 8 == 0 else kv

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,  # (li, block_tables, n_valid)
        grid=(b, kv // block_kv, n_bpr),
        in_specs=[
            pl.BlockSpec((1, block_kv, g, hd),
                         lambda bi, hi, ni, li_r, bt_r, nv_r: (bi, hi, 0, 0)),
            pl.BlockSpec((None, 1, bs, block_kv, hd),
                         lambda bi, hi, ni, li_r, bt_r, nv_r:
                         (li_r[0], bt_r[bi, ni], 0, hi, 0)),
            pl.BlockSpec((None, 1, bs, block_kv, 1),
                         lambda bi, hi, ni, li_r, bt_r, nv_r:
                         (li_r[0], bt_r[bi, ni], 0, hi, 0)),
            pl.BlockSpec((None, 1, bs, block_kv, hd),
                         lambda bi, hi, ni, li_r, bt_r, nv_r:
                         (li_r[0], bt_r[bi, ni], 0, hi, 0)),
            pl.BlockSpec((None, 1, bs, block_kv, 1),
                         lambda bi, hi, ni, li_r, bt_r, nv_r:
                         (li_r[0], bt_r[bi, ni], 0, hi, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_kv, g, hd),
                               lambda bi, hi, ni, li_r, bt_r, nv_r:
                               (bi, hi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_kv, g, 1), jnp.float32),   # running max
            pltpu.VMEM((block_kv, g, 1), jnp.float32),   # running sum
            pltpu.VMEM((block_kv, g, hd), jnp.float32),  # running context
        ],
    )
    return pl.pallas_call(
        functools.partial(_paged_attn_kernel, scale=scale,
                          block_kv=block_kv, n_bpr=n_bpr),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kv, g, hd), q.dtype),
        interpret=interpret,
        compiler_params=_CompilerParams(
            vmem_limit_bytes=64 * 1024 * 1024
        ),
    )(jnp.asarray(li, jnp.int32).reshape(1),
      jnp.asarray(block_tables, jnp.int32),
      jnp.asarray(n_valid, jnp.int32),
      q, k_q, k_s, v_q, v_s)


def decode_attention_int8_paged_reference(q, k_q, k_s, v_q, v_s, li,
                                          block_tables, n_valid):
    """Plain-XLA twin: gather the table into the dense view (exactly the
    CPU-tier fallback ``models/llama._cache_read_layer`` runs), then the
    dense reference math."""
    kq = k_q[li][block_tables]  # (B, n_bpr, bs, KV, hd)
    ks = k_s[li][block_tables]
    vq = v_q[li][block_tables]
    vs = v_s[li][block_tables]

    def flat(x):
        return x.reshape((x.shape[0], x.shape[1] * x.shape[2]) + x.shape[3:])

    k = flat(kq).astype(jnp.float32) * flat(ks)
    v = flat(vq).astype(jnp.float32) * flat(vs)
    b, kv, g, hd = q.shape
    s = jnp.einsum("bkgd,bskd->bkgs", q.astype(jnp.float32), k) / math.sqrt(hd)
    mask = jnp.arange(k.shape[1])[None, None, None, :] < \
        n_valid[:, None, None, None]
    s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bkgs,bskd->bkgd", p, v)
    return o.astype(q.dtype)
