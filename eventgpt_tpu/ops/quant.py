"""Weight-only int8 quantization for the decode path.

Batch-1 decode is HBM-bandwidth-bound: every generated token streams the
full weight set out of HBM (~13.5 GB bf16 for 7B), so tokens/sec is capped
at bandwidth / weight-bytes. Storing matmul weights as int8 with per-output-
channel f32 scales halves the bytes read per token; the dequantize
(int8 -> bf16 multiply-by-scale) fuses into the matmul operands on TPU, so
the MXU still sees bf16 inputs while HBM only ever sees int8.

The reference reaches the same class of optimization through bitsandbytes
(``requirements.txt:11``; ``TrainingArguments.bits/quant_type`` in the
training pyc, SURVEY.md §2.2). Here it is a pure-functional tree transform:
``quantize_llama_params`` maps selected weight leaves to
``{"q": int8, "s": f32 scale}`` dicts, and the matmul helper in
``models/llama.py`` dispatches on leaf type — the same jitted decode code
serves both precisions.

Symmetric per-channel scheme: ``s = max|w| / 127`` over the contraction
axis, ``q = round(w / s)``. Activations, norms, embeddings, and the KV cache
stay in the compute dtype.
"""

from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp

QuantizedLeaf = Dict[str, jnp.ndarray]  # {"q": int8 [..., K, N], "s": f32 [..., 1, N]}
# int4 leaf: {"q4": uint8 [..., K/2, N], "s": f32 [..., K/G, N]} — see quantize_tensor4.


def is_quantized(leaf: Any) -> bool:
    return isinstance(leaf, dict) and "q" in leaf and "s" in leaf


def is_quantized4(leaf: Any) -> bool:
    return isinstance(leaf, dict) and "q4" in leaf and "s" in leaf


def is_lora(leaf: Any) -> bool:
    """Apply-form LoRA composite leaf: {"w": base, "a": A*scale, "b": B}.

    ``x @ W_eff`` evaluates as ``x@w + (x@a)@b`` — the rank-r update is two
    skinny matmuls instead of a materialized (K, N) delta, so stage-2 never
    holds a second copy of the 7B weight set (``train/lora.py:apply_lora``).
    """
    return isinstance(leaf, dict) and "w" in leaf and "a" in leaf and "b" in leaf


def _quantize8_impl(w, xp) -> QuantizedLeaf:
    """Shared int8 math, parameterized on the array namespace (jnp on
    device, numpy on host) so the two paths cannot drift."""
    w32 = xp.asarray(w).astype(xp.float32)
    amax = xp.max(xp.abs(w32), axis=-2, keepdims=True)  # (..., 1, N)
    scale = xp.maximum(amax, 1e-8) / 127.0
    q = xp.clip(xp.round(w32 / scale), -127, 127).astype(xp.int8)
    return {"q": q, "s": scale.astype(xp.float32)}


def quantize_tensor(w: jnp.ndarray) -> QuantizedLeaf:
    """Quantize a (..., K, N) matmul weight per output channel (axis -1)."""
    return _quantize8_impl(w, jnp)


def dequantize_tensor(leaf: QuantizedLeaf, dtype=jnp.float32) -> jnp.ndarray:
    return (leaf["q"].astype(jnp.float32) * leaf["s"]).astype(dtype)


def quantize_tensor4(w: jnp.ndarray, group: int = 128) -> QuantizedLeaf:
    """Group-wise symmetric int4 quantization of a (K, N) matmul weight,
    packed two rows per byte.

    Packing is along the CONTRACTION axis: byte ``[r, n]`` holds rows
    ``2r`` (high nibble) and ``2r+1`` (low nibble), stored offset-binary
    (``value + 8``). That layout needs **no interleave at unpack time** —
    ``x @ W == x[0::2] @ hi_plane + x[1::2] @ lo_plane`` where each plane is
    a plain shift/mask of the packed bytes, so the dequantize stays a fusable
    elementwise producer feeding the dot (HBM streams 0.5 bytes/weight).

    Scales are per (group, out-channel): ``s[g, n] = max|w[gG:(g+1)G, n]|/7``
    over ``group`` contraction rows (int4's range is too coarse for the
    per-channel scheme int8 uses). ``group`` must divide K and be even;
    ``group=0`` means one group (per-channel).
    """
    return _quantize4_impl(w, group, jnp)


def _quantize4_impl(w, group: int, xp) -> QuantizedLeaf:
    """Shared int4 math, parameterized on the array namespace (jnp on
    device, numpy on host) so the two paths cannot drift."""
    K, N = w.shape[-2], w.shape[-1]
    if group <= 0:
        group = K
    if K % group or group % 2:
        raise ValueError(f"group {group} must be even and divide K={K}")
    w32 = xp.asarray(w).astype(xp.float32)
    gshape = w32.shape[:-2] + (K // group, group, N)
    wg = w32.reshape(gshape)
    amax = xp.max(xp.abs(wg), axis=-2, keepdims=True)  # (..., K/G, 1, N)
    scale = xp.maximum(amax, 1e-8) / 7.0
    q = xp.clip(xp.round(wg / scale), -8, 7).astype(xp.int32).reshape(
        w32.shape[:-2] + (K, N)
    )
    even, odd = q[..., 0::2, :] + 8, q[..., 1::2, :] + 8
    packed = ((even << 4) | odd).astype(xp.uint8)  # (..., K/2, N)
    return {"q4": packed, "s": scale[..., 0, :].astype(xp.float32)}  # (..., K/G, N)


def _unpack4(q4: jnp.ndarray, dtype) -> tuple:
    """Packed (..., K/2, N) uint8 -> (hi, lo) planes of the same shape in
    ``dtype``: hi = even contraction rows, lo = odd."""
    hi = (q4 >> 4).astype(jnp.int8) - 8
    lo = (q4 & 0xF).astype(jnp.int8) - 8
    return hi.astype(dtype), lo.astype(dtype)


def dequantize_tensor4(leaf: QuantizedLeaf, dtype=jnp.float32) -> jnp.ndarray:
    hi, lo = _unpack4(leaf["q4"], jnp.float32)
    *lead, half_k, n = hi.shape
    k = 2 * half_k
    w = jnp.stack([hi, lo], axis=-2)  # (..., K/2, 2, N)
    w = w.reshape(*lead, k, n)
    gc = leaf["s"].shape[-2]
    w = w.reshape(*lead, gc, k // gc, n) * leaf["s"][..., :, None, :]
    return w.reshape(*lead, k, n).astype(dtype)


def _matmul4(x: jnp.ndarray, leaf: QuantizedLeaf) -> jnp.ndarray:
    """x (..., K) @ int4 leaf -> (..., N) f32 accumulator.

    Dispatches to the Pallas kernel (``ops/int4_matmul.py``) when the
    shapes meet its alignment contract — XLA materializes the nibble
    unpack through HBM, which defeats int4's whole purpose (measured
    slower than int8); the kernel dequantizes in VMEM. The XLA grouped
    two-plane einsum remains the fallback for unaligned (tiny-model)
    shapes."""
    q4, s = leaf["q4"], leaf["s"]
    if q4.ndim == 2:
        from eventgpt_tpu.ops import int4_matmul as i4k

        k = 2 * q4.shape[-2]
        group = k // s.shape[-2]
        if i4k.supported(k, q4.shape[-1], group):
            lead = x.shape[:-1]
            y = i4k.int4_matmul(x.reshape(-1, k), q4, s)
            return y.reshape(*lead, q4.shape[-1])
    if q4.ndim != 2:
        raise ValueError("int4 matmul expects a per-layer (K/2, N) plane; "
                         "stacked trees are sliced by the layer scan")
    half_k, n = q4.shape
    k = 2 * half_k
    gc = s.shape[-2]
    hg = half_k // gc  # packed rows per group
    hi, lo = _unpack4(q4, x.dtype)
    lead = x.shape[:-1]
    xg = x.reshape(-1, gc, hg, 2)  # (..., g, packed-row, parity)
    xe, xo = xg[..., 0], xg[..., 1]
    part = jnp.einsum("bgk,gkn->bgn", xe, hi.reshape(gc, hg, n),
                      preferred_element_type=jnp.float32)
    part += jnp.einsum("bgk,gkn->bgn", xo, lo.reshape(gc, hg, n),
                       preferred_element_type=jnp.float32)
    y = jnp.einsum("bgn,gn->bn", part, s, preferred_element_type=jnp.float32)
    return y.reshape(*lead, n)


def _lora_branch_input(x: jnp.ndarray, w: Any) -> jnp.ndarray:
    """Adapter-branch input, with inverted dropout when the composite leaf
    carries per-layer mask state (``train/lora.py:apply_lora`` with a step
    key). peft semantics: only the A@B branch sees the dropped input."""
    if "k" not in w:
        return x
    import jax

    keep = 1.0 - w["dr"]
    mask = jax.random.bernoulli(w["k"], keep, x.shape)
    return jnp.where(mask, x / keep.astype(x.dtype), jnp.zeros((), x.dtype))


def matmul(x: jnp.ndarray, w: Any) -> jnp.ndarray:
    """x @ w for a plain or quantized weight leaf.

    For quantized leaves the int8->compute-dtype convert fuses into the dot
    (HBM reads int8); the per-channel scale applies to the f32 accumulator
    output, preserving the dense path's f32 accumulation.
    """
    if is_lora(w):
        xl = _lora_branch_input(x, w)
        delta = jnp.matmul(xl, w["a"].astype(x.dtype)) @ w["b"].astype(x.dtype)
        return matmul(x, w["w"]) + delta
    if is_quantized4(w):
        return _matmul4(x, w).astype(x.dtype)
    if is_quantized(w):
        y = jnp.matmul(
            x, w["q"].astype(x.dtype), preferred_element_type=jnp.float32
        )
        return (y * w["s"]).astype(x.dtype)
    return x @ w


def matmul_f32_out(x: jnp.ndarray, w: Any) -> jnp.ndarray:
    """Like ``matmul`` but returns the f32 accumulator (lm_head logits)."""
    if is_lora(w):
        xl = _lora_branch_input(x, w)
        delta = jnp.matmul(xl, w["a"].astype(x.dtype)) @ w["b"].astype(x.dtype)
        return matmul_f32_out(x, w["w"]) + delta.astype(jnp.float32)
    if is_quantized4(w):
        return _matmul4(x, w)
    if is_quantized(w):
        y = jnp.matmul(
            x, w["q"].astype(x.dtype), preferred_element_type=jnp.float32
        )
        return y * w["s"]
    return jnp.matmul(x, w, preferred_element_type=jnp.float32)


def quantize_tensor_host(w) -> QuantizedLeaf:
    """Numpy-side ``quantize_tensor`` for host-resident checkpoints.

    Quantizing a 7B tree on-device would hold the bf16 tree, the growing
    int8 tree, and f32 upcast temps in HBM at once (> 20 GB on a 16 GB
    chip); on host it is just RAM. Use before device placement
    (``cli/infer.py``).
    """
    import numpy as np

    return _quantize8_impl(w, np)


def quantize_tensor4_host(w, group: int = 128) -> QuantizedLeaf:
    """Numpy-side ``quantize_tensor4`` (same rationale as
    ``quantize_tensor_host``: quantize before device placement)."""
    import numpy as np

    return _quantize4_impl(w, group, np)


def quantize_llama_params(params: Dict[str, Any], host: bool = False,
                          bits: int = 8, group: int = 128) -> Dict[str, Any]:
    """Quantize every matmul weight of a llama param tree (embeddings and
    norms untouched). Stacked-layer leaves (L, K, N) quantize per layer and
    channel; the scan over layers slices ``q``/``s`` together.

    ``host=True`` runs the numpy path (see ``quantize_tensor_host``);
    ``bits=4`` selects the packed group-wise int4 scheme (``group`` rows per
    scale)."""
    if bits == 4:
        # Per-leaf group clamp: leaves whose contraction dim is smaller than
        # (or not divisible by) the requested group fall back to one group
        # over the whole K (per-channel) — small models stay quantizable
        # without the caller knowing every layer's K.
        def qt(w):
            k = w.shape[-2]
            g = group if group > 0 and k % group == 0 else k
            return (quantize_tensor4_host(w, g) if host
                    else quantize_tensor4(w, g))
    elif bits == 8:
        qt = quantize_tensor_host if host else quantize_tensor
    else:
        raise ValueError(f"unsupported bits={bits} (4 or 8)")
    out = {k: v for k, v in params.items()}
    out["lm_head"] = qt(params["lm_head"])
    layers = dict(params["layers"])
    layers["attn"] = {k: qt(v) for k, v in params["layers"]["attn"].items()}
    layers["mlp"] = {k: qt(v) for k, v in params["layers"]["mlp"].items()}
    out["layers"] = layers
    return out
