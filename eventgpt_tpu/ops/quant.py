"""Weight-only int8 quantization for the decode path.

Batch-1 decode is HBM-bandwidth-bound: every generated token streams the
full weight set out of HBM (~13.5 GB bf16 for 7B), so tokens/sec is capped
at bandwidth / weight-bytes. Storing matmul weights as int8 with per-output-
channel f32 scales halves the bytes read per token; the dequantize
(int8 -> bf16 multiply-by-scale) fuses into the matmul operands on TPU, so
the MXU still sees bf16 inputs while HBM only ever sees int8.

The reference reaches the same class of optimization through bitsandbytes
(``requirements.txt:11``; ``TrainingArguments.bits/quant_type`` in the
training pyc, SURVEY.md §2.2). Here it is a pure-functional tree transform:
``quantize_llama_params`` maps selected weight leaves to
``{"q": int8, "s": f32 scale}`` dicts, and the matmul helper in
``models/llama.py`` dispatches on leaf type — the same jitted decode code
serves both precisions.

Symmetric per-channel scheme: ``s = max|w| / 127`` over the contraction
axis, ``q = round(w / s)``. Activations, norms, embeddings, and the KV cache
stay in the compute dtype.
"""

from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp

QuantizedLeaf = Dict[str, jnp.ndarray]  # {"q": int8 [..., K, N], "s": f32 [..., 1, N]}


def is_quantized(leaf: Any) -> bool:
    return isinstance(leaf, dict) and "q" in leaf and "s" in leaf


def is_lora(leaf: Any) -> bool:
    """Apply-form LoRA composite leaf: {"w": base, "a": A*scale, "b": B}.

    ``x @ W_eff`` evaluates as ``x@w + (x@a)@b`` — the rank-r update is two
    skinny matmuls instead of a materialized (K, N) delta, so stage-2 never
    holds a second copy of the 7B weight set (``train/lora.py:apply_lora``).
    """
    return isinstance(leaf, dict) and "w" in leaf and "a" in leaf and "b" in leaf


def quantize_tensor(w: jnp.ndarray) -> QuantizedLeaf:
    """Quantize a (..., K, N) matmul weight per output channel (axis -1)."""
    w32 = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=-2, keepdims=True)  # (..., 1, N)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return {"q": q, "s": scale}


def dequantize_tensor(leaf: QuantizedLeaf, dtype=jnp.float32) -> jnp.ndarray:
    return (leaf["q"].astype(jnp.float32) * leaf["s"]).astype(dtype)


def matmul(x: jnp.ndarray, w: Any) -> jnp.ndarray:
    """x @ w for a plain or quantized weight leaf.

    For quantized leaves the int8->compute-dtype convert fuses into the dot
    (HBM reads int8); the per-channel scale applies to the f32 accumulator
    output, preserving the dense path's f32 accumulation.
    """
    if is_lora(w):
        delta = jnp.matmul(x, w["a"].astype(x.dtype)) @ w["b"].astype(x.dtype)
        return matmul(x, w["w"]) + delta
    if is_quantized(w):
        y = jnp.matmul(
            x, w["q"].astype(x.dtype), preferred_element_type=jnp.float32
        )
        return (y * w["s"]).astype(x.dtype)
    return x @ w


def matmul_f32_out(x: jnp.ndarray, w: Any) -> jnp.ndarray:
    """Like ``matmul`` but returns the f32 accumulator (lm_head logits)."""
    if is_lora(w):
        delta = jnp.matmul(x, w["a"].astype(x.dtype)) @ w["b"].astype(x.dtype)
        return matmul_f32_out(x, w["w"]) + delta.astype(jnp.float32)
    if is_quantized(w):
        y = jnp.matmul(
            x, w["q"].astype(x.dtype), preferred_element_type=jnp.float32
        )
        return y * w["s"]
    return jnp.matmul(x, w, preferred_element_type=jnp.float32)


def quantize_tensor_host(w) -> QuantizedLeaf:
    """Numpy-side ``quantize_tensor`` for host-resident checkpoints.

    Quantizing a 7B tree on-device would hold the bf16 tree, the growing
    int8 tree, and f32 upcast temps in HBM at once (> 20 GB on a 16 GB
    chip); on host it is just RAM. Use before device placement
    (``cli/infer.py``).
    """
    import numpy as np

    w32 = np.asarray(w, np.float32)
    amax = np.max(np.abs(w32), axis=-2, keepdims=True)
    scale = np.maximum(amax, 1e-8) / 127.0
    q = np.clip(np.round(w32 / scale), -127, 127).astype(np.int8)
    return {"q": q, "s": scale.astype(np.float32)}


def quantize_llama_params(params: Dict[str, Any], host: bool = False) -> Dict[str, Any]:
    """Quantize every matmul weight of a llama param tree (embeddings and
    norms untouched). Stacked-layer leaves (L, K, N) quantize per layer and
    channel; the scan over layers slices ``q``/``s`` together.

    ``host=True`` runs the numpy path (see ``quantize_tensor_host``)."""
    qt = quantize_tensor_host if host else quantize_tensor
    out = {k: v for k, v in params.items()}
    out["lm_head"] = qt(params["lm_head"])
    layers = dict(params["layers"])
    layers["attn"] = {k: qt(v) for k, v in params["layers"]["attn"].items()}
    layers["mlp"] = {k: qt(v) for k, v in params["layers"]["mlp"].items()}
    out["layers"] = layers
    return out
