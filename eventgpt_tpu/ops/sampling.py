"""Token sampling: greedy, temperature, top-p — jit-friendly.

Replaces the HF GenerationMixin sampling configuration the reference relies
on (``inference.py:52-63``: do_sample iff temperature > 0, top_p, greedy
otherwise). All paths are shape-static and run on device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def greedy(logits: jnp.ndarray) -> jnp.ndarray:
    """(B, V) -> (B,) argmax token ids."""
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def top_p_filter(logits: jnp.ndarray, top_p: float) -> jnp.ndarray:
    """Mask logits outside the smallest nucleus with cumulative prob >= top_p.

    Keeps every token whose inclusion is needed to reach top_p (the standard
    "shift right" nucleus rule: the first token crossing the threshold stays).
    """
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(sorted_probs, axis=-1)
    # Position i is cut iff the cumulative mass *before* it already >= top_p.
    cut = (cum - sorted_probs) >= top_p
    # Translate the sorted-space cut into a per-token logit threshold.
    threshold = jnp.min(jnp.where(cut, jnp.inf, sorted_logits), axis=-1, keepdims=True)
    return jnp.where(logits < threshold, -jnp.inf, logits)


def sample(
    logits: jnp.ndarray,
    key: jax.Array,
    temperature: float = 1.0,
    top_p: float = 1.0,
) -> jnp.ndarray:
    """(B, V) logits -> (B,) sampled ids. temperature <= 0 means greedy."""
    if temperature <= 0.0:
        return greedy(logits)
    scaled = logits.astype(jnp.float32) / temperature
    if top_p < 1.0:
        scaled = top_p_filter(scaled, top_p)
    return jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
