"""Pallas TPU kernel for packed-int4 weight-only matmul (decode path).

Why a kernel at all: XLA will not fuse the nibble unpack of a packed int4
weight into the dot — it materializes the dequantized bf16 planes through
HBM, which makes plain-XLA int4 *slower* than int8 (measured 16.5 vs
70.3 tok/s at 7B batch-1 decode on v5e). Here the packed bytes stream
HBM -> VMEM once and the shift/mask/scale dequant happens in VMEM
feeding the MXU directly, so HBM traffic is 0.5 bytes/weight — half of
int8's, on the path where tokens/sec is weight-bytes/bandwidth.

Layout contract matches ``ops/quant.quantize_tensor4``: byte ``[r, n]``
holds logical contraction rows ``2r`` (high nibble) and ``2r+1`` (low
nibble), offset-binary (value + 8); group scales ``s[g, n]`` cover
``group`` logical rows. The even/odd split means the kernel never
interleaves: ``x @ W = x_even @ hi + x_odd @ lo`` with both planes plain
shift/masks of the block bytes.

Grid: ``(N / BLOCK_N, HK / BLOCK_KP)`` with the packed-row dimension
innermost; the f32 output block is revisited across the K steps and
accumulates in VMEM (init at the first step).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BLOCK_N = 256
BLOCK_KP = 128  # packed rows per step = 256 logical contraction rows


def _int4_kernel(xe_ref, xo_ref, w_ref, s_ref, out_ref, *, half_group: int,
                 groups_per_step: int):
    """One (n-block, k-step) cell.

    xe/xo_ref: (B, BKP) bf16 — even/odd logical rows of x for this k step.
    w_ref: (BKP, BN) uint8 packed. s_ref: (GB, BN) f32 — this step's group
    scales (the host reshapes scales to (k_steps, GB, N) so the block's
    trailing dims equal full array dims, satisfying the sublane tiling rule
    that a raw (GB, BN) block of a (Gc, N) array would break).
    out_ref: (B, BN) f32 accumulator.
    """
    ki = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    # Offset-binary nibbles -> centered int -> bf16 (exact: int4 values are
    # integers <= 8, representable in bf16 losslessly). Bit ops run at i32
    # (Mosaic cannot legalize sub-word shifts: 'arith.shrui' on vector<i8>).
    w = w_ref[:].astype(jnp.int32)
    bkp, bn = w.shape
    gb = groups_per_step
    hi = ((w >> 4) - 8).astype(jnp.bfloat16).reshape(gb, half_group, bn)
    lo = ((w & 0xF) - 8).astype(jnp.bfloat16).reshape(gb, half_group, bn)

    # f32 group scales applied to f32 per-group dot partials — numerically
    # IDENTICAL to the XLA fallback (ops/quant.py:_matmul4). The previous
    # form pre-scaled bf16 nibbles by bf16-cast scales: two roundings whose
    # error depended on shape alignment (kernel vs fallback divergence,
    # ADVICE r2). The MXU still sees pure-integer bf16 operands.
    b = xe_ref.shape[0]
    xe = jnp.swapaxes(xe_ref[:].reshape(b, gb, half_group), 0, 1)  # (gb,B,hg)
    xo = jnp.swapaxes(xo_ref[:].reshape(b, gb, half_group), 0, 1)
    dims = (((2,), (1,)), ((0,), (0,)))
    part = jax.lax.dot_general(xe, hi, dims,
                               preferred_element_type=jnp.float32)
    part += jax.lax.dot_general(xo, lo, dims,
                                preferred_element_type=jnp.float32)
    out_ref[:] += jnp.sum(part * s_ref[:][:, None, :], axis=0)


def supported(k: int, n: int, group: int) -> bool:
    """Shape-alignment gate for the kernel; callers fall back to the XLA
    path otherwise (small/tiny-model dims)."""
    hk = k // 2
    return (
        k % 2 == 0
        and n % BLOCK_N == 0
        and hk % BLOCK_KP == 0
        and group % 2 == 0
        and (group // 2) <= BLOCK_KP
        and BLOCK_KP % (group // 2) == 0
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def int4_matmul(x: jnp.ndarray, q4: jnp.ndarray, s: jnp.ndarray,
                interpret: bool | None = None) -> jnp.ndarray:
    """x (B, K) @ packed-int4 weight -> (B, N) f32.

    q4: (K/2, N) uint8, s: (Gc, N) f32 — the ``quantize_tensor4`` layout.
    """
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    b, k = x.shape
    hk, n = q4.shape
    gc = s.shape[0]
    group = k // gc
    half_group = group // 2

    xb = x.astype(jnp.bfloat16).reshape(b, hk, 2)
    xe, xo = xb[..., 0], xb[..., 1]

    grid = (n // BLOCK_N, hk // BLOCK_KP)
    gb = BLOCK_KP // half_group  # groups per k step
    s_steps = s.reshape(grid[1], gb, n)

    out = pl.pallas_call(
        functools.partial(_int4_kernel, half_group=half_group,
                          groups_per_step=gb),
        grid=grid,
        in_specs=[
            pl.BlockSpec((b, BLOCK_KP), lambda j, ki: (0, ki),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((b, BLOCK_KP), lambda j, ki: (0, ki),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((BLOCK_KP, BLOCK_N), lambda j, ki: (ki, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, gb, BLOCK_N), lambda j, ki: (ki, 0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((b, BLOCK_N), lambda j, ki: (0, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, n), jnp.float32),
        interpret=interpret,
    )(xe, xo, q4, s_steps)
    return out
