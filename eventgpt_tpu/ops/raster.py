"""Event-stream rasterization: raw ``{x, y, t, p}`` -> polarity RGB frames.

Re-designs the reference's host-side per-event Python loop
(``common/common.py:64-74``, the measured host hot spot at ~132k events per
50 ms sample) as vectorized last-write-wins scatters:

  * ``rasterize_events``      — numpy host path (data loading / preprocessing),
  * ``rasterize_events_jax``  — jit-able device path (static frame dims) for
    keeping rasterization on-TPU when events are already device-resident.

Semantics match the reference exactly: white (255,255,255) background; the
*last* event at a pixel wins; polarity 0 -> blue (0,0,255), polarity 1 ->
red (255,0,0); per-frame dims are ``(y.max()+1, x.max()+1)`` computed from
that frame's own events (``common/common.py:65``).

Splitting matches ``get_event_images_list`` (equal event-count slices,
``common/common.py:17-37``) and ``split_event_by_time``
(fixed-width time bins, ``common/common.py:76-107``).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from eventgpt_tpu.constants import MAX_EVENT_STREAM_US

EventDict = Dict[str, np.ndarray]

_RED = np.array([255, 0, 0], dtype=np.uint8)
_BLUE = np.array([0, 0, 255], dtype=np.uint8)


class EventStreamTooLongError(ValueError):
    """Stream span exceeds the supported envelope (common/common.py:114-116)."""


def check_event_stream_length(start_time_us: int, end_time_us: int,
                              max_span_us: int = MAX_EVENT_STREAM_US) -> None:
    if end_time_us - start_time_us >= max_span_us:
        raise EventStreamTooLongError(
            f"Event stream spans {end_time_us - start_time_us} us; "
            f"streams must be shorter than {max_span_us} us."
        )


class _NumpyOnlyUnpickler:
    """Restricted unpickler for legacy event files: only the globals numpy
    needs to rebuild ``{str: ndarray}`` dicts resolve; anything else (the
    arbitrary-code-execution surface of ``allow_pickle=True``) raises.

    The reference loads event .npy with ``allow_pickle=True``
    (``common/common.py:111-112``) and its published samples ARE pickled
    object arrays — refusing them outright would break the reference's own
    inputs, so the fix is to make the pickle path safe rather than gated.
    """

    _ALLOWED = {
        ("numpy.core.multiarray", "_reconstruct"),
        ("numpy._core.multiarray", "_reconstruct"),
        ("numpy.core.multiarray", "scalar"),
        ("numpy._core.multiarray", "scalar"),
        ("numpy", "ndarray"),
        ("numpy", "dtype"),
    }

    def __new__(cls, fp):
        import pickle

        class _U(pickle.Unpickler):
            def find_class(self, module, name):
                if (module, name) in cls._ALLOWED:
                    return super().find_class(module, name)
                raise pickle.UnpicklingError(
                    f"blocked pickle global {module}.{name} in event file "
                    f"(only numpy array payloads are allowed)"
                )

        return _U(fp)


def _load_legacy_pickled_events(path: str) -> EventDict:
    """Read a legacy object-array .npy through the restricted unpickler.

    Parses the npy header with numpy's format module, then unpickles the
    payload with ``_NumpyOnlyUnpickler`` instead of ``np.load``'s
    unrestricted ``pickle.load``.
    """
    from numpy.lib import format as npf

    with open(path, "rb") as f:
        version = npf.read_magic(f)
        npf._check_version(version)
        _shape, _fortran, dtype = npf._read_array_header(f, version)
        if not dtype.hasobject:
            raise ValueError(f"{path}: not an object-array npy")
        obj = _NumpyOnlyUnpickler(f).load()
    d = np.array(obj).item() if isinstance(obj, np.ndarray) else obj
    if not isinstance(d, dict):
        raise ValueError(f"{path}: expected an event dict, got {type(d)}")
    return {str(k): np.asarray(v) for k, v in d.items()}


def load_event_npy(path: str) -> EventDict:
    """Load a ``{x,y,t,p}`` dict from an .npy file (``common/common.py:111-112``).

    Plain structured arrays (this framework's native stream format, e.g.
    ``scripts/stream_demo.py``) load without pickle; legacy pickled dict
    files (the reference's samples) go through a restricted unpickler that
    only admits numpy reconstruction globals — never ``allow_pickle=True``.
    """
    try:
        raw = np.load(path)  # no pickle: safe structured-array path
    except ValueError:
        return _load_legacy_pickled_events(path)
    if raw.dtype.names:
        return {n: np.ascontiguousarray(raw[n]) for n in raw.dtype.names}
    raise ValueError(
        f"{path}: unsupported event npy layout (expected a structured "
        f"array with named fields or a legacy pickled dict)"
    )


# The native threaded reader's on-disk layout (shared with the C++
# SaveEventsNpy writer, native/src/events_io.cpp): one struct per event.
STREAM_DTYPE = np.dtype([("x", "<u2"), ("y", "<u2"),
                         ("t", "<u8"), ("p", "u1")])


def events_to_structured_stream(events: EventDict) -> np.ndarray:
    """{x,y,t,p} dict -> the native reader's structured-array layout.

    The reference's samples are pickled dicts the native reader
    deliberately does not parse; this is the conversion every harness
    (``scripts/stream_demo.py``, ``bench.py --mode stream``) uses to
    replay them through ``native.EventStream``.
    """
    n = len(events["t"])
    arr = np.zeros(n, dtype=STREAM_DTYPE)
    for k in ("x", "y", "t", "p"):
        arr[k] = events[k]
    return arr


def events_window_us(buf: Dict[str, np.ndarray], sel: np.ndarray) -> EventDict:
    """Select a window from a float-seconds event dict, converting ``t``
    to int64 microseconds — the ``events_to_frames`` contract both
    streaming harnesses feed."""
    return {k: (buf[k][sel] if k != "t"
                else (buf["t"][sel] * 1e6).astype(np.int64))
            for k in buf}


def rasterize_events(
    x: np.ndarray,
    y: np.ndarray,
    p: np.ndarray,
    height: Optional[int] = None,
    width: Optional[int] = None,
) -> np.ndarray:
    """Rasterize one event slice into an (H, W, 3) uint8 RGB frame.

    Vectorized last-write-wins: for each pixel, the polarity of the last
    event landing there decides the color, identical to the sequential
    overwrite loop at ``common/common.py:68-73``.
    """
    inferred_dims = height is None and width is None
    if height is None:
        height = int(y.max()) + 1
    if width is None:
        width = int(x.max()) + 1

    # Drop out-of-frame events identically on every path (ADVICE r1: the
    # native kernel bounds-checks and drops, while a raw numpy scatter
    # would raise IndexError — behavior must not depend on which is built).
    # Skipped on the hot path: unsigned coords with dims inferred from the
    # maxima are in-bounds by construction.
    unsigned = (np.issubdtype(np.asarray(x).dtype, np.unsignedinteger)
                and np.issubdtype(np.asarray(y).dtype, np.unsignedinteger))
    if not (inferred_dims and unsigned):
        xi = np.asarray(x).astype(np.int64)
        yi = np.asarray(y).astype(np.int64)
        inb = (xi >= 0) & (xi < width) & (yi >= 0) & (yi < height)
        if not inb.all():
            x, y, p = np.asarray(x)[inb], np.asarray(y)[inb], np.asarray(p)[inb]

    from eventgpt_tpu import native

    # The C ABI takes uint16 coordinates; frames beyond that range (never
    # the case for event cameras) fall back to numpy rather than wrap.
    if native.available() and height <= 65536 and width <= 65536:
        return native.rasterize_events_native(x, y, p, height, width)

    lin = y.astype(np.int64) * width + x.astype(np.int64)
    last = np.full(height * width, -1, dtype=np.int64)
    np.maximum.at(last, lin, np.arange(lin.size, dtype=np.int64))

    frame = np.full((height * width, 3), 255, dtype=np.uint8)
    hit = last >= 0
    pol = np.asarray(p)[last[hit]]
    frame[hit] = np.where(pol[:, None] != 0, _RED, _BLUE)
    return frame.reshape(height, width, 3)


def rasterize_events_jax(
    x: jax.Array,
    y: jax.Array,
    p: jax.Array,
    height: int,
    width: int,
) -> jax.Array:
    """Device-side rasterization with static frame dims (jit/vmap friendly).

    Last-write-wins via a scatter-max of event ordinals, then a gather of the
    winning event's polarity — well-defined under XLA (unlike raw duplicate
    scatter-set). Returns (H, W, 3) uint8.
    """
    n = x.shape[0]
    lin = y.astype(jnp.int32) * width + x.astype(jnp.int32)
    order = jnp.arange(n, dtype=jnp.int32)
    last = jnp.full((height * width,), -1, dtype=jnp.int32).at[lin].max(order)
    hit = last >= 0
    pol = jnp.asarray(p)[jnp.clip(last, 0, None)]
    red = jnp.array([255, 0, 0], dtype=jnp.uint8)
    blue = jnp.array([0, 0, 255], dtype=jnp.uint8)
    white = jnp.array([255, 255, 255], dtype=jnp.uint8)
    colors = jnp.where(pol[:, None] != 0, red[None], blue[None])
    frame = jnp.where(hit[:, None], colors, white[None])
    return frame.reshape(height, width, 3)


def split_events_by_count(events: EventDict, n: int) -> List[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Split a stream into ``n`` equal-event-count slices (last takes remainder).

    Parity: ``common/common.py:17-37`` — slice i covers
    ``[i*total//n, (i+1)*total//n)`` except the last, which runs to the end.
    Returns (x, y, p) triples.
    """
    x, y, p, t = events["x"], events["y"], events["p"], events["t"]
    total = len(t)
    per = total // n
    out = []
    for i in range(n):
        lo = i * per
        hi = (i + 1) * per if i < n - 1 else total
        out.append((x[lo:hi], y[lo:hi], p[lo:hi]))
    return out


def split_events_by_time(events: EventDict, time_interval_us: int = 50_000) -> List[EventDict]:
    """Split a stream into fixed-width time bins (``common/common.py:76-107``)."""
    t = events["t"]
    bins = (t // time_interval_us) * time_interval_us
    out = []
    for b in np.unique(bins):
        sel = bins == b
        out.append({k: events[k][sel] for k in ("p", "t", "x", "y")})
    return out


def events_to_frames(
    events: EventDict,
    n_frames: int = 5,
    max_span_us: int = MAX_EVENT_STREAM_US,
) -> List[np.ndarray]:
    """Full host path: guard span, split by count, rasterize each slice.

    Mirrors ``process_event_data`` up to (but not including) CLIP
    preprocessing (``common/common.py:110-119``).
    """
    t = events["t"]
    if len(t) < n_frames:
        raise ValueError(
            f"event stream has {len(t)} events; at least {n_frames} are needed "
            f"to rasterize {n_frames} frames"
        )
    check_event_stream_length(int(t.min()), int(t.max()), max_span_us)
    return [rasterize_events(x, y, p) for x, y, p in split_events_by_count(events, n_frames)]
