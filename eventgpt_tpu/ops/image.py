"""CLIP image preprocessing with exact HF ``CLIPImageProcessor`` parity.

The reference feeds rasterized event frames through
``CLIPImageProcessor.__call__`` (``common/common.py:121-125``). Pixel-exact
parity matters: an off-by-one in resampling changes every downstream event
token (SURVEY.md §7 "Hard parts"). The host path therefore uses PIL bicubic
resampling — the same code path HF uses — followed by center crop, rescale,
and normalization in numpy. A pure-jnp normalize is provided for frames that
are already device-resident at the target size.
"""

from __future__ import annotations

from typing import Iterable, List, Tuple

import jax.numpy as jnp
import numpy as np
from PIL import Image

# OpenAI CLIP normalization constants (transformers OPENAI_CLIP_MEAN/STD).
CLIP_MEAN = np.array([0.48145466, 0.4578275, 0.40821073], dtype=np.float32)
CLIP_STD = np.array([0.26862954, 0.26130258, 0.27577711], dtype=np.float32)


def _resize_shortest_edge(img: Image.Image, shortest_edge: int) -> Image.Image:
    """Resize preserving aspect ratio so min(H, W) == shortest_edge.

    Matches transformers' ``get_resize_output_image_size`` with an int size:
    the long side becomes ``int(shortest_edge * long / short)`` (floor).
    """
    w, h = img.size
    short, long = (w, h) if w <= h else (h, w)
    new_short = shortest_edge
    new_long = int(shortest_edge * long / short)
    new_w, new_h = (new_short, new_long) if w <= h else (new_long, new_short)
    return img.resize((new_w, new_h), Image.Resampling.BICUBIC)


def _center_crop(arr: np.ndarray, crop: int) -> np.ndarray:
    """Center crop (H, W, C) to (crop, crop, C), zero-padding if smaller.

    Offsets match transformers' ``center_crop`` ((dim - crop) // 2).
    """
    h, w = arr.shape[:2]
    top = (h - crop) // 2
    left = (w - crop) // 2
    if top >= 0 and left >= 0:
        return arr[top : top + crop, left : left + crop]
    out = np.zeros((crop, crop, arr.shape[2]), dtype=arr.dtype)
    dst_top, src_top = max(0, -top), max(0, top)
    dst_left, src_left = max(0, -left), max(0, left)
    hh = min(h, crop)
    ww = min(w, crop)
    out[dst_top : dst_top + hh, dst_left : dst_left + ww] = arr[
        src_top : src_top + hh, src_left : src_left + ww
    ]
    return out


def expand2square(img: np.ndarray, background: Iterable[float] = CLIP_MEAN) -> np.ndarray:
    """Pad an (H, W, C) uint8 image to square, centered, with the CLIP
    ``image_mean`` background.

    Parity with LLaVA's ``expand2square`` used by ``EventChatDataset.
    __getitem__`` for ``image_aspect_ratio='square'`` (training pyc,
    SURVEY.md §2.2): background channels are ``int(mean * 255)`` (floor, as
    LLaVA computes it) and the image is pasted at ``(side - dim) // 2``.
    """
    h, w = img.shape[:2]
    if h == w:
        return img
    side = max(h, w)
    bg = np.array([int(c * 255) for c in background], dtype=img.dtype)
    out = np.full((side, side, img.shape[2]), bg, dtype=img.dtype)
    top = (side - h) // 2
    left = (side - w) // 2
    out[top:top + h, left:left + w] = img
    return out


def clip_preprocess(frame: np.ndarray, image_size: int = 336) -> np.ndarray:
    """uint8 RGB (H, W, 3) -> normalized float32 CHW (3, S, S).

    Pipeline (parity with CLIPImageProcessor defaults): bicubic resize of the
    shortest edge to ``image_size``, center crop to ``image_size``², rescale
    by 1/255, normalize with the OpenAI CLIP mean/std, HWC -> CHW.
    """
    img = Image.fromarray(frame)
    img = _resize_shortest_edge(img, image_size)
    arr = np.asarray(img, dtype=np.float32)
    arr = _center_crop(arr, image_size)
    arr = arr / 255.0
    arr = (arr - CLIP_MEAN) / CLIP_STD
    return np.transpose(arr, (2, 0, 1))


def clip_preprocess_batch(frames: Iterable[np.ndarray], image_size: int = 336) -> np.ndarray:
    """Preprocess a list of frames -> (N, 3, S, S) float32."""
    return np.stack([clip_preprocess(f, image_size) for f in frames])


def clip_normalize_jax(frames: jnp.ndarray) -> jnp.ndarray:
    """Normalize device-resident uint8 NHWC frames already at target size.

    For the on-device rasterize path (``rasterize_events_jax``) where resize
    is done by the raster geometry itself. Returns NCHW float32.
    """
    x = frames.astype(jnp.float32) / 255.0
    x = (x - jnp.asarray(CLIP_MEAN)) / jnp.asarray(CLIP_STD)
    return jnp.transpose(x, (0, 3, 1, 2))


def process_event_file(
    path: str,
    n_frames: int = 5,
    image_size: int = 336,
) -> Tuple[List[int], np.ndarray]:
    """npy path -> (event_image_size, (n_frames, 3, S, S) float32 pixels).

    End-to-end host preprocessing, mirroring ``process_event_data``
    (``common/common.py:110-127``): load, guard 100 ms span, 5-way
    equal-count split, rasterize, CLIP preprocess. ``event_image_size`` is
    the (H, W) of the first rasterized frame (``common/common.py:119``).
    """
    from eventgpt_tpu.ops.raster import events_to_frames, load_event_npy

    events = load_event_npy(path)
    frames = events_to_frames(events, n_frames=n_frames)
    event_image_size = list(frames[0].shape[:2])
    return event_image_size, clip_preprocess_batch(frames, image_size)
