from eventgpt_tpu.ops.raster import (  # noqa: F401
    check_event_stream_length,
    rasterize_events,
    rasterize_events_jax,
    split_events_by_count,
    split_events_by_time,
)
from eventgpt_tpu.ops.image import clip_preprocess, clip_preprocess_batch  # noqa: F401
from eventgpt_tpu.ops.pooling import spatio_temporal_pool  # noqa: F401
