"""Process-global metrics registry with Prometheus text exposition.

The serving/training stack had only ad-hoc counters (a ``/stats`` dict,
three scheduler gauges, the trainer heartbeat); this registry is the one
place a number must be registered to become operable: scrapeable at
``GET /metrics`` (Prometheus text format 0.0.4), summarized into
``/stats``, and dumped per train step into ``telemetry.jsonl``.

Rules (enforced statically by ``scripts/lint_telemetry.py``):

  * every metric name matches ``egpt_[a-z0-9_]+`` and is registered
    EXACTLY ONCE, at import time, in THIS module — call sites import the
    metric object (``SERVE_TTFT.observe(dt)``), they never register;
  * hot paths time with ``time.perf_counter`` (monotonic), never
    ``time.time``.

Thread-safety: every mutation takes the metric's lock (scheduler,
handler and trainer threads all observe). Cost: a histogram observe is
one bisect + three dict writes under a lock — sub-microsecond, a few
dozen per decode segment, measured <2% of serve throughput end to end
(PERFORMANCE.md "Telemetry overhead").

Histograms are FIXED-BUCKET log2: upper bounds at powers of two, so
bucket assignment is a bisect over ~30 floats, merging across processes
is trivial (same bounds always), and the exposition stays small. The
price is factor-of-2 quantile resolution — the right trade for latency
telemetry (you care about 2x regressions, not 5%).

Disarm with ``configure(enabled=False)`` (one module-global bool read
per call when off). Telemetry never touches jax values either way —
chains are byte-identical on/off (tests/test_obs.py).
"""

from __future__ import annotations

import json
import math
import re
import threading
from bisect import bisect_left
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

NAME_RE = re.compile(r"^egpt_[a-z0-9_]+$")

_INF = float("inf")

# Fixed label-value enums per metric (lint rule 5, ISSUE 6 satellite):
# every labelled observation in the runtime tree draws its values from
# the set declared HERE — bounded cardinality by construction. A
# request-shaped label (rid, user id, session id) would grow the
# exposition without bound and is banned outright by
# scripts/lint_telemetry.py. The lint enforces this statically (literal
# values must be members, every label key must have an enum) and the
# metric classes enforce it at observe time for the metrics listed
# below; OBSERVABILITY.md's catalogue documents the same enums. This
# dict is a PURE LITERAL on purpose — the lint reads it with
# ast.literal_eval, no imports.
METRIC_LABELS = {
    "egpt_serve_requests_total": {
        "status": ("ok", "deadline_exceeded", "cancelled",
                   "nan_quarantined", "engine_fault",
                   "resource_exhausted"),
    },
    "egpt_serve_prefill_dispatches_total": {
        "kind": ("full", "wave", "chunk", "suffix", "suffix_wave",
                 "piggyback"),
    },
    "egpt_fault_trips_total": {
        # Mirrors the wired maybe_fail/maybe_delay sites (lint rule 5
        # cross-checks this tuple against rule 4's site scan, so a new
        # site cannot ship without extending the enum); "other" absorbs
        # synthetic/ad-hoc drill sites (faults._site_label clamps).
        "site": ("fleet.probe", "fleet.replica_kill", "fleet.route",
                 "multiproc.launch", "multiproc.worker",
                 "procfleet.handoff", "procfleet.rpc", "procfleet.spawn",
                 "procfleet.worker_kill", "serve.admit",
                 "serve.dispatch", "serve.loop", "serve.mem_guard",
                 "serve.mixed_dispatch", "serve.preempt",
                 "serve.prefix_copy", "serve.spec_adapt", "serve.spill",
                 "serve.step", "train.step", "other"),
        "kind": ("fail", "delay"),
    },
    "egpt_mem_component_bytes": {
        # The memory ledger's component taxonomy (obs/memory.py
        # COMPONENTS — keep the two literals identical; the ledger
        # validates at register time, this enum at observe time).
        # kv_pool / kv_block_table are the paged-layout split of
        # kv_cache (ISSUE 12): the arena scales with blocks, the table
        # with max_batch.
        "component": ("weights", "kv_cache", "kv_pool", "kv_block_table",
                      "logits", "ids_buf", "prefix_cache", "lanes",
                      "draft", "carry", "spill", "other"),
    },
    "egpt_fleet_routed_total": {
        # Routing decisions (ISSUE 7): affinity = the session's pinned
        # replica (its radix prefix is hot), least_queue = fallback by
        # queue depth, repin = failover re-route that moved the
        # session's pin to a survivor.
        "reason": ("affinity", "least_queue", "repin"),
    },
    "egpt_fleet_shed_total": {
        "slo_class": ("interactive", "batch"),
    },
    "egpt_serve_slo_requests_total": {
        "slo_class": ("interactive", "batch"),
        "met": ("true", "false"),
    },
    "egpt_serve_slo_ttft_seconds": {
        "slo_class": ("interactive", "batch"),
    },
    "egpt_serve_slo_itl_seconds": {
        "slo_class": ("interactive", "batch"),
    },
    "egpt_serve_slo_latency_seconds": {
        "slo_class": ("interactive", "batch"),
    },
    "egpt_procfleet_failovers_total": {
        # How a lost worker's requests moved (ISSUE 11): drain = the
        # worker still answered RPC and export_requests() re-routed its
        # in-flight work; redo = the worker died hard (SIGKILL/crash)
        # and the coordinator re-submitted from its own records.
        "path": ("drain", "redo"),
    },
    "egpt_serve_slo_miss_cause_total": {
        # The flight recorder's dominant-miss-cause enum (obs/journey.py
        # MISS_CAUSES — keep the two literals identical; the egpt-check
        # rule-5 cross-check asserts equality, this enum enforces at
        # observe time).
        "slo_class": ("interactive", "batch"),
        "cause": ("queue", "defer", "preempt", "admission", "decode",
                  "host_gap", "failover_redo", "handoff",
                  "nan_quarantine", "shed", "other"),
    },
    "egpt_procfleet_handoff_total": {
        # Prefill->decode KV handoff stages (ISSUE 17): gathered = the
        # prefill worker pulled the block run to host RAM, shipped =
        # the coordinator moved it to a decode worker over RPC,
        # spliced = the decode worker scattered it into its arena.
        # gathered/spliced increment in the worker processes' own
        # registries, shipped in the coordinator's; /stats aggregates
        # the fleet-wide totals from the handoff counters instead.
        "stage": ("gathered", "shipped", "spliced"),
    },
    "egpt_serve_preemptions_total": {
        # How a preempted victim's KV left the arena (ISSUE 16): spill =
        # gathered to the host SpillStore for a byte-exact restore,
        # drop = released for re-prefill on re-admission (policy choice
        # or spill-path fallback).
        "mode": ("spill", "drop"),
    },
    "egpt_alert_active": {
        # The alert evaluator's CLOSED rule enum (obs/series.py
        # ALERT_RULES — keep the two literals identical; the egpt-check
        # rule-5 cross-check asserts equality, this enum enforces at
        # observe time).
        "rule": ("slo_burn", "queue_trend", "cause_shift", "breaker_flap",
                 "mem_shrink"),
    },
    "egpt_alert_transitions_total": {
        # Same enum as egpt_alert_active (ALERT_RULES, obs/series.py).
        "rule": ("slo_burn", "queue_trend", "cause_shift", "breaker_flap",
                 "mem_shrink"),
    },
}


def log2_buckets(lo: float, hi: float) -> Tuple[float, ...]:
    """Power-of-two upper bounds covering [lo, hi]: the first bound is
    the largest 2^k <= lo, the last the smallest 2^k >= hi. (+Inf is
    implicit — every histogram has an overflow bucket.)"""
    if not (lo > 0 and hi > lo):
        raise ValueError(f"need 0 < lo < hi, got ({lo}, {hi})")
    e = math.floor(math.log2(lo) + 1e-12)
    out = []
    while True:
        b = 2.0 ** e
        out.append(b)
        if b >= hi:
            return tuple(out)
        e += 1


# Shared bucket families (the catalogue in OBSERVABILITY.md):
#   LATENCY — 61 us .. 128 s: request-scale times (TTFT, queue wait,
#             completion, admission, train step).
#   SHORT   — 0.95 us .. 8 s: per-token / per-segment times (ITL,
#             segment wait, data wait).
#   ROWS    — 1 .. 1024: batch-occupancy style small counts.
LATENCY_BUCKETS = log2_buckets(2.0 ** -14, 2.0 ** 7)
SHORT_BUCKETS = log2_buckets(2.0 ** -20, 2.0 ** 3)
ROWS_BUCKETS = tuple(float(2 ** e) for e in range(0, 11))


def _fmt(v: float) -> str:
    """Prometheus sample value / le formatting: integral floats render
    without the trailing .0 (golden-test stable across Python versions)."""
    if v == _INF:
        return "+Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_str(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in labels)
    return "{" + inner + "}"


class _Metric:
    """Base: one name, one help string, samples keyed by sorted label
    tuples. Subclasses hold the per-key state under ``self._lock``."""

    kind = "untyped"

    def __init__(self, name: str, help: str, registry: "Registry"):
        self.name = name
        self.help = help
        self._reg = registry
        self._lock = threading.Lock()
        # Declared label enums for THIS metric (None = unlisted, e.g. a
        # test's private registry): observe-time backstop for the static
        # lint — an out-of-enum value raises instead of minting a fresh
        # unbounded series.
        self._enums = METRIC_LABELS.get(name)

    def _key(self, labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
        if not labels:
            return ()
        if self._enums is not None:
            for k, v in labels.items():
                vals = self._enums.get(k)
                if vals is None or str(v) not in vals:
                    raise ValueError(
                        f"metric {self.name}: label {k}={v!r} outside "
                        f"the declared enum (METRIC_LABELS, "
                        f"obs/metrics.py) — labels are bounded-"
                        f"cardinality by contract (lint rule 5)")
        return tuple(sorted((k, str(v)) for k, v in labels.items()))


class Counter(_Metric):
    kind = "counter"

    # Lock contract (egpt_check rule ``lock``): the sample map only
    # mutates/reads under the metric's own lock — scheduler, handler
    # and trainer threads all observe concurrently. Gauge inherits
    # this declaration (same-module base resolution).
    _GUARDED_BY = {"_values": "_lock"}

    def __init__(self, name, help, registry):
        super().__init__(name, help, registry)
        self._values: Dict[tuple, float] = {}

    def inc(self, n: float = 1.0, **labels) -> None:
        if not self._reg.enabled:
            return
        k = self._key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + n

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def total(self) -> float:
        with self._lock:
            return sum(self._values.values())

    def labeled(self) -> Dict[tuple, float]:
        """Snapshot of every label set's value, keyed by the sorted
        ``((key, value), ...)`` tuple — the time-series sampler's
        cumulative read (obs/series.py derives windowed per-label
        rates from deltas of this)."""
        with self._lock:
            return dict(self._values)

    def _reset(self) -> None:
        with self._lock:
            self._values.clear()

    def _render(self, common: tuple) -> List[str]:
        with self._lock:
            items = sorted(self._values.items())
        if not items:
            items = [((), 0.0)]
        return [f"{self.name}{_label_str(common + k)} {_fmt(v)}"
                for k, v in items]

    def _summary(self):
        with self._lock:
            if not self._values:
                return 0.0
            if list(self._values) == [()]:
                return self._values[()]
            return {_label_str(k) or "_": v
                    for k, v in sorted(self._values.items())}


class Gauge(Counter):
    kind = "gauge"

    def set(self, v: float, **labels) -> None:
        if not self._reg.enabled:
            return
        with self._lock:
            self._values[self._key(labels)] = float(v)


class Histogram(_Metric):
    """Fixed-bucket log2 histogram. ``observe(v, n=k)`` adds ``k``
    observations of value ``v`` (one lock round-trip for a whole decode
    segment's worth of per-token gaps)."""

    kind = "histogram"

    _GUARDED_BY = {"_counts": "_lock", "_sums": "_lock",
                   "_totals": "_lock"}

    def __init__(self, name, help, registry,
                 buckets: Sequence[float] = LATENCY_BUCKETS):
        super().__init__(name, help, registry)
        bounds = tuple(float(b) for b in buckets)
        if list(bounds) != sorted(set(bounds)) or (bounds and bounds[-1] == _INF):
            raise ValueError(f"buckets must be strictly increasing and "
                             f"finite (+Inf is implicit): {bounds}")
        self.bounds = bounds
        # per label-key: [counts per bound + overflow], sum, count
        self._counts: Dict[tuple, List[float]] = {}
        self._sums: Dict[tuple, float] = {}
        self._totals: Dict[tuple, float] = {}

    def observe(self, v: float, n: int = 1, **labels) -> None:
        if not self._reg.enabled or n <= 0:
            return
        i = bisect_left(self.bounds, v)  # bucket upper bounds: le semantics
        k = self._key(labels)
        with self._lock:
            c = self._counts.get(k)
            if c is None:
                c = self._counts[k] = [0.0] * (len(self.bounds) + 1)
                self._sums[k] = 0.0
                self._totals[k] = 0.0
            c[i] += n
            self._sums[k] += v * n
            self._totals[k] += n

    def count(self, **labels) -> float:
        with self._lock:
            return self._totals.get(self._key(labels), 0.0)

    def agg_counts(self) -> List[float]:
        """Per-bucket counts aggregated over every label set (overflow
        last, same order as ``bounds`` + implicit +Inf) — the
        time-series sampler's cumulative read: windowed quantiles come
        from deltas of consecutive snapshots (obs/series.py)."""
        with self._lock:
            agg = [0.0] * (len(self.bounds) + 1)
            for c in self._counts.values():
                for i, v in enumerate(c):
                    agg[i] += v
            return agg

    def quantile(self, q: float) -> float:
        """Upper-bound estimate of the q-quantile, aggregated over every
        label set: the smallest bucket bound whose cumulative count
        reaches q * total (log2 buckets -> factor-2 resolution). 0.0
        when empty; the last finite bound stands in for +Inf overflow."""
        with self._lock:
            total = sum(self._totals.values())
            if total <= 0:
                return 0.0
            agg = [0.0] * (len(self.bounds) + 1)
            for c in self._counts.values():
                for i, v in enumerate(c):
                    agg[i] += v
        need = q * total
        cum = 0.0
        for i, v in enumerate(agg):
            cum += v
            if cum >= need - 1e-9:
                return self.bounds[i] if i < len(self.bounds) else self.bounds[-1]
        return self.bounds[-1]

    def _reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self._sums.clear()
            self._totals.clear()

    def _render(self, common: tuple) -> List[str]:
        with self._lock:
            keys = sorted(self._counts)
            rows = [(k, list(self._counts[k]), self._sums[k], self._totals[k])
                    for k in keys]
        if not rows:
            rows = [((), [0.0] * (len(self.bounds) + 1), 0.0, 0.0)]
        out = []
        for k, counts, s, total in rows:
            cum = 0.0
            for bound, c in zip(self.bounds + (_INF,), counts):
                cum += c
                lk = common + k + (("le", _fmt(bound)),)
                out.append(f"{self.name}_bucket{_label_str(lk)} {_fmt(cum)}")
            out.append(f"{self.name}_sum{_label_str(common + k)} {_fmt(s)}")
            out.append(f"{self.name}_count{_label_str(common + k)} {_fmt(total)}")
        return out

    def _summary(self):
        with self._lock:
            total = sum(self._totals.values())
            s = sum(self._sums.values())
        if total <= 0:
            return {"count": 0}
        return {
            "count": int(total),
            "sum": round(s, 6),
            "mean": round(s / total, 6),
            "p50": self.quantile(0.50),
            "p99": self.quantile(0.99),
        }


class Registry:
    """Name -> metric, rendered in registration order. One process-global
    instance (``REGISTRY``) below; tests build private ones.

    Lock contract: the metric map and the common-label tuple mutate
    under ``_lock``; ``_common`` reads are lock-free (``/w`` — an
    atomically swapped tuple, set once at worker start). ``enabled`` is
    deliberately undeclared: a bare bool flag read once per observation
    (the A/B disarm switch), GIL-atomic by construction."""

    _GUARDED_BY = {"_metrics": "_lock", "_common": "_lock/w"}

    def __init__(self):
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()
        self._common: Tuple[Tuple[str, str], ...] = ()
        self.enabled = True

    def _register(self, m: _Metric) -> _Metric:
        with self._lock:
            if m.name in self._metrics:
                raise ValueError(
                    f"metric {m.name!r} is already registered — metrics are "
                    f"defined exactly once, at import, in obs/metrics.py")
            if not NAME_RE.match(m.name):
                raise ValueError(
                    f"metric name {m.name!r} must match {NAME_RE.pattern}")
            self._metrics[m.name] = m
        return m

    def counter(self, name: str, help: str) -> Counter:
        return self._register(Counter(name, help, self))

    def gauge(self, name: str, help: str) -> Gauge:
        return self._register(Gauge(name, help, self))

    def histogram(self, name: str, help: str,
                  buckets: Sequence[float] = LATENCY_BUCKETS) -> Histogram:
        return self._register(Histogram(name, help, self, buckets))

    def configure(self, enabled: bool) -> None:
        """Arm/disarm every metric in this registry (the A/B switch the
        overhead bench and the chain-neutrality test flip)."""
        self.enabled = bool(enabled)

    def set_common_labels(self, **labels) -> None:
        """Labels stamped on every exposed sample — e.g. the per-process
        ``process="3"`` label multiproc workers set so one scrape target
        per host stays disambiguated (DISTRIBUTED.md)."""
        with self._lock:
            self._common = tuple(
                sorted((k, str(v)) for k, v in labels.items()))

    def reset(self) -> None:
        """Zero every value (registration survives) — phase-scoped
        measurement, e.g. bench excluding its warmup traffic."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            m._reset()

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: List[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            lines.append(f"# HELP {m.name} {_escape(m.help)}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            lines.extend(m._render(self._common))
        return "\n".join(lines) + "\n"

    def summary(self, prefixes: Optional[Iterable[str]] = None) -> Dict:
        """Compact dict view (the ``/stats`` merge and the trainer's
        ``telemetry.jsonl`` lines): counters/gauges as values, histograms
        as {count, sum, mean, p50, p99}."""
        pf = tuple(prefixes) if prefixes else None
        with self._lock:
            metrics = list(self._metrics.values())
        return {m.name: m._summary() for m in metrics
                if pf is None or m.name.startswith(pf)}


REGISTRY = Registry()

# --------------------------------------------------------------------------
# The metric catalogue (OBSERVABILITY.md documents each entry). Every
# metric in the process is defined HERE, once — call sites import these
# objects. scripts/lint_telemetry.py enforces the name grammar and the
# register-exactly-once rule statically.

# -- serving (eventgpt_tpu/serve.py + cli/serve.py) --
SERVE_TTFT = REGISTRY.histogram(
    "egpt_serve_ttft_seconds",
    "Submit to first committed token, per request")
SERVE_ITL = REGISTRY.histogram(
    "egpt_serve_itl_seconds",
    "Inter-token latency: mean commit gap per row per harvest, "
    "weighted by tokens (excludes the first token - that is TTFT)",
    SHORT_BUCKETS)
SERVE_QUEUE_WAIT = REGISTRY.histogram(
    "egpt_serve_queue_wait_seconds",
    "Submit to leaving the admission queue, per request")
SERVE_LATENCY = REGISTRY.histogram(
    "egpt_serve_latency_seconds",
    "Submit to terminal status (any status), per request")
SERVE_ADMISSION = REGISTRY.histogram(
    "egpt_serve_admission_seconds",
    "Host admission stall per scheduler step (encode + prefill + insert)",
    SHORT_BUCKETS)
SERVE_SEGMENT = REGISTRY.histogram(
    "egpt_serve_segment_seconds",
    "Host time blocked fetching one decode/spec segment (the un-hidden "
    "device time; pipelined overlap shrinks it, not the device work)",
    SHORT_BUCKETS)
SERVE_OCCUPANCY = REGISTRY.histogram(
    "egpt_serve_batch_occupancy_rows",
    "Unfrozen rows at segment dispatch (batch utilization)",
    ROWS_BUCKETS)
SERVE_REQUESTS = REGISTRY.counter(
    "egpt_serve_requests_total",
    "Finished requests by terminal status "
    "(ok / deadline_exceeded / cancelled / nan_quarantined / "
    "engine_fault / resource_exhausted)")
SERVE_TOKENS = REGISTRY.counter(
    "egpt_serve_tokens_total", "Committed (served) tokens")
SERVE_SEGMENTS = REGISTRY.counter(
    "egpt_serve_segments_total", "Dispatched decode/spec segments")
SERVE_HOST_GAP = REGISTRY.counter(
    "egpt_serve_host_gap_seconds_total",
    "Host scheduler time between segment fetches (harvest bookkeeping, "
    "admission prep, dispatch)")
SERVE_OVERLAP_HIDDEN = REGISTRY.counter(
    "egpt_serve_overlap_hidden_seconds_total",
    "Share of the host gap spent while a dispatched segment was "
    "verifiably still running on the device")
SERVE_QUEUE_DEPTH = REGISTRY.gauge(
    "egpt_serve_queue_depth", "Requests waiting in the admission queue")
SERVE_ACTIVE_ROWS = REGISTRY.gauge(
    "egpt_serve_active_rows", "Rows holding a live request")
SERVE_BREAKER_OPEN = REGISTRY.gauge(
    "egpt_serve_breaker_open",
    "1 while the circuit breaker refuses work (health=degraded), else 0")
SERVE_SCHED_FAULTS = REGISTRY.counter(
    "egpt_serve_scheduler_faults_total",
    "Scheduler-thread faults survived by the engine")
SERVE_SCHED_RESTARTS = REGISTRY.counter(
    "egpt_serve_scheduler_restarts_total",
    "Scheduler-thread restarts after a fault")
# -- prefix-KV cache + batched admission (ISSUE 4, eventgpt_tpu/serve.py) --
SERVE_PREFIX_HITS = REGISTRY.counter(
    "egpt_serve_prefix_cache_hits_total",
    "Admissions served from a cached prefix-KV entry (suffix-only prefill)")
SERVE_PREFIX_MISSES = REGISTRY.counter(
    "egpt_serve_prefix_cache_misses_total",
    "Admissions that found no usable prefix entry (full prefill)")
SERVE_PREFIX_EVICTIONS = REGISTRY.counter(
    "egpt_serve_prefix_cache_evictions_total",
    "Prefix entries LRU-evicted under the HBM byte budget")
SERVE_PREFIX_INSERTIONS = REGISTRY.counter(
    "egpt_serve_prefix_cache_insertions_total",
    "Prefix entries inserted (set_prefix or insert-on-prefill)")
SERVE_PREFIX_BYTES = REGISTRY.gauge(
    "egpt_serve_prefix_cache_bytes",
    "HBM bytes held by cached prefix-KV entries")
SERVE_PREFIX_ENTRIES = REGISTRY.gauge(
    "egpt_serve_prefix_cache_entries",
    "Live prefix-KV cache entries")
SERVE_ADMISSION_WAVE = REGISTRY.histogram(
    "egpt_serve_admission_wave_rows",
    "Full-prefill admissions batched into one prefill dispatch (wave size)",
    ROWS_BUCKETS)
SERVE_PREFILL_DISPATCHES = REGISTRY.counter(
    "egpt_serve_prefill_dispatches_total",
    "Admission prefill dispatches by kind: full (batch-1), wave (one per "
    "BATCH of admissions), chunk (per chunked-prefill advance), suffix "
    "(prefix-cache hit), piggyback (mixed segment carrying prefill lanes)")
# -- stall-free admission: mixed prefill+decode segments (ISSUE 5) --
SERVE_MIXED_SEGMENTS = REGISTRY.counter(
    "egpt_serve_mixed_segments_total",
    "Dispatched MIXED segments: decode/spec body plus live piggyback "
    "prefill lanes in one executable")
SERVE_MIXED_LANES = REGISTRY.histogram(
    "egpt_serve_mixed_lane_rows",
    "Piggyback prefill lanes advanced per mixed segment",
    ROWS_BUCKETS)
SERVE_MIXED_PREFILL_TOKENS = REGISTRY.counter(
    "egpt_serve_mixed_prefill_tokens_total",
    "Prompt positions prefilled inside mixed segments (piggyback lanes), "
    "bounded per boundary by --prefill_budget")
# -- adaptive speculation (ISSUE 13, eventgpt_tpu/serve.py +
#    eventgpt_tpu/serve_spec.py) --
SERVE_SPEC_DEPTH = REGISTRY.histogram(
    "egpt_serve_spec_depth",
    "Speculation window selected per dispatch boundary by the adaptive "
    "controller (--spec_buckets; 1 = the draft-free fallback segment, "
    "the K=0 bucket). Constant at the fixed K without buckets",
    ROWS_BUCKETS)
SERVE_SPEC_ACCEPT = REGISTRY.gauge(
    "egpt_serve_spec_accept_ratio",
    "Controller acceptance EMA: accepted draft positions / offered "
    "draft positions across harvested verifies (the depth-selection "
    "signal; 0 until the first drafted verify lands)")
SERVE_SPEC_MASKED = REGISTRY.counter(
    "egpt_serve_spec_masked_rows",
    "Rows whose per-row draft depth was masked below the selected "
    "bucket's full depth, summed over dispatch boundaries (per-row "
    "windowed acceptance undershot the bucket, or a pruned head/level "
    "capped it)")
# -- SLO classes + goodput (ISSUE 6, eventgpt_tpu/serve.py) --
SERVE_SLO_REQUESTS = REGISTRY.counter(
    "egpt_serve_slo_requests_total",
    "Finished SLO-classed requests by class and attainment (met=true "
    "when every armed target held, inclusive)")
SERVE_SLO_TTFT = REGISTRY.histogram(
    "egpt_serve_slo_ttft_seconds",
    "Submit to first committed token by SLO class (requests that never "
    "committed are excluded, as in egpt_serve_ttft_seconds)")
SERVE_SLO_ITL = REGISTRY.histogram(
    "egpt_serve_slo_itl_seconds",
    "Per-request mean inter-token gap by SLO class (first token "
    "excluded - that interval is TTFT; single-token requests excluded)",
    SHORT_BUCKETS)
SERVE_SLO_LATENCY = REGISTRY.histogram(
    "egpt_serve_slo_latency_seconds",
    "Submit to terminal status by SLO class (every terminal path - "
    "forced finishes stay in the goodput denominator)")
SERVE_SLO_GOODPUT = REGISTRY.gauge(
    "egpt_serve_slo_goodput_ratio",
    "Fraction of the last slo_window SLO-classed finishes that met "
    "their targets (windowed SLO-attainment goodput)")
SERVE_SLO_MISS_CAUSE = REGISTRY.counter(
    "egpt_serve_slo_miss_cause_total",
    "SLO-missed finishes by class and the flight recorder's dominant "
    "miss cause (the largest phase of the request's decomposition: "
    "queue / defer / preempt / admission / decode / host_gap / "
    "failover_redo, plus the non-time causes nan_quarantine / shed / "
    "other); counted while the recorder is armed (--journey_keep > 0)")

# -- fleet serving: replica supervisor + router (ISSUE 7,
#    eventgpt_tpu/fleet.py) --
# Aggregate-only on purpose: a per-replica label would be computed
# (str(idx) — lint rule 5 bans it); per-replica numbers live in the
# fleet's /stats JSON and the bench artifact, read from each replica's
# host-side counters.
FLEET_REPLICAS = REGISTRY.gauge(
    "egpt_fleet_replicas", "Configured replicas in the fleet")
FLEET_ROUTABLE = REGISTRY.gauge(
    "egpt_fleet_replicas_routable",
    "Replicas currently in the routing pool (healthy: breaker closed, "
    "heartbeat fresh, not killed)")
FLEET_QUEUE_DEPTH = REGISTRY.gauge(
    "egpt_fleet_queue_depth",
    "Requests queued across every replica (the router's aggregate "
    "backlog — one of the two shedding signals)")
FLEET_ROUTED = REGISTRY.counter(
    "egpt_fleet_routed_total",
    "Routed submits by decision: affinity (session's pinned replica), "
    "least_queue (fallback), repin (failover moved the pin)")
FLEET_SHED = REGISTRY.counter(
    "egpt_fleet_shed_total",
    "Requests shed by the router's SLO-aware overload policy, by class "
    "(batch sheds first; interactive is never policy-shed)")
FLEET_FAILOVERS = REGISTRY.counter(
    "egpt_fleet_failovers_total",
    "Requests re-routed to a surviving replica after their replica "
    "died or faulted them (re-decoded from the prompt: greedy chains "
    "stay byte-identical)")
FLEET_REPLICA_DEATHS = REGISTRY.counter(
    "egpt_fleet_replica_deaths_total",
    "Replica kills observed by the supervisor (chaos fleet.replica_kill "
    "trips and operator kill_replica calls)")

# -- process fleet: worker processes behind the RPC coordinator
#    (ISSUE 11, eventgpt_tpu/fleet_proc.py + rpc.py) --
# Aggregate-only like the egpt_fleet_* family (a per-slot label would
# be computed — lint rule 5); per-worker numbers live in /fleet and
# the PROCFLEET bench artifact.
PROCFLEET_WORKERS = REGISTRY.gauge(
    "egpt_procfleet_workers",
    "Configured worker-process slots in the process fleet")
PROCFLEET_ROUTABLE = REGISTRY.gauge(
    "egpt_procfleet_workers_routable",
    "Worker processes currently in the routing pool (ready, heartbeat "
    "fresh, answering RPC, not crash-looped)")
PROCFLEET_RPC_RETRIES = REGISTRY.counter(
    "egpt_procfleet_rpc_retries_total",
    "RPC attempts retried after a transport failure (refused/reset "
    "connection, short read, injected procfleet.rpc trip) — each retry "
    "backed off exponentially with jitter under the per-call deadline")
PROCFLEET_WORKER_DEATHS = REGISTRY.counter(
    "egpt_procfleet_worker_deaths_total",
    "Worker processes lost: unexpected exits (SIGKILL/crash), "
    "stale-heartbeat/unreachable drains, and operator kill_worker calls")
PROCFLEET_RESPAWNS = REGISTRY.counter(
    "egpt_procfleet_respawns_total",
    "Worker processes respawned into a dead slot (per-slot exponential "
    "backoff; stops when the crash-loop breaker gives the slot up)")
PROCFLEET_FAILOVERS = REGISTRY.counter(
    "egpt_procfleet_failovers_total",
    "Requests moved off a lost worker, by path: drain (exported over "
    "RPC from a still-answering worker) or redo (re-submitted from the "
    "coordinator's own records after a hard death); both re-decode "
    "from the prompt, so greedy chains stay byte-identical")
PROCFLEET_CRASH_LOOPS = REGISTRY.counter(
    "egpt_procfleet_crash_loop_slots_total",
    "Worker slots the crash-loop breaker gave up on (K crashes inside "
    "the window): capacity degrades, /health stays green while any "
    "other worker is routable")
PROCFLEET_HANDOFFS = REGISTRY.counter(
    "egpt_procfleet_handoff_total",
    "Prefill->decode KV handoffs by stage (ISSUE 17): gathered (block "
    "run pulled to host on the prefill worker), shipped (moved to a "
    "decode worker over the raw-binary RPC frame), spliced (scattered "
    "into the decode worker's arena); per-process registries — "
    "gathered/spliced count in the workers, shipped in the coordinator")
PROCFLEET_HANDOFF_BYTES = REGISTRY.counter(
    "egpt_procfleet_handoff_bytes_total",
    "Bytes of gathered KV handoff records shipped prefill->decode "
    "(coordinator-side; the raw-frame payload, KV planes + scales + "
    "row state, b64-free on the wire)")
PROCFLEET_HANDOFF_SECONDS = REGISTRY.histogram(
    "egpt_procfleet_handoff_seconds",
    "Coordinator wall time to move one handoff record: collect from "
    "the prefill worker through import acknowledged by the decode "
    "worker (the stitched handoff_s phase sums these durations)")

# -- HBM memory ledger (ISSUE 9, eventgpt_tpu/obs/memory.py) --
MEM_COMPONENT = REGISTRY.gauge(
    "egpt_mem_component_bytes",
    "Device bytes the memory ledger attributes to each named component "
    "(weights / kv_cache / kv_pool / kv_block_table / logits / ids_buf "
    "/ prefix_cache / lanes / draft / carry / spill / other; kv_pool + "
    "kv_block_table are the paged layout's split of kv_cache; spill is "
    "HOST bytes — the pinned spill store tier)")
MEM_TOTAL = REGISTRY.gauge(
    "egpt_mem_total_bytes",
    "Sum of all ledger-registered device bytes (the accounted side of "
    "the reconciliation split)")
MEM_PEAK = REGISTRY.gauge(
    "egpt_mem_peak_bytes",
    "High-water mark of egpt_mem_total_bytes since the last "
    "reset_peak() (phase-scoped, like reset_serving_stats)")
MEM_LIVE = REGISTRY.gauge(
    "egpt_mem_live_bytes",
    "jax.live_arrays() device bytes at the last ledger reconcile "
    "(GET /memory refreshes it)")
MEM_UNACCOUNTED = REGISTRY.gauge(
    "egpt_mem_unaccounted_bytes",
    "live_bytes minus ledger total at the last reconcile - bytes no "
    "component claims (transient admission caches, jit constants)")
MEM_GUARD_DEFERRALS = REGISTRY.counter(
    "egpt_mem_guard_deferrals_total",
    "Admission waves deferred by the --mem_headroom_mb guard (the "
    "ledger predicted the next wave would exceed capacity - headroom)")

# -- paged KV block pool (ISSUE 12, eventgpt_tpu/serve_blocks.py) --
SERVE_KV_BLOCKS_USED = REGISTRY.gauge(
    "egpt_serve_kv_blocks_used",
    "Pool blocks currently owned by rows and prefix entries (used "
    "tokens at the SEQ_BUCKET block grain — the quantity that now "
    "gates admission instead of batch x max_len)")
SERVE_KV_BLOCKS_FREE = REGISTRY.gauge(
    "egpt_serve_kv_blocks_free",
    "Pool blocks on the free list (admission headroom in blocks)")
SERVE_KV_COW_COPIES = REGISTRY.counter(
    "egpt_serve_kv_cow_copies_total",
    "Copy-on-write block copies: a prefix-shared run diverged mid-"
    "block and the admission scatter re-created the boundary block in "
    "the row's private reservation")
SERVE_KV_ALLOC_FAILURES = REGISTRY.counter(
    "egpt_serve_kv_alloc_failures_total",
    "Block allocations the pool could not cover (each one defers an "
    "admission or refuses a prefix insert; never a partial grant)")
SERVE_KV_BLOCK_DEFERRALS = REGISTRY.counter(
    "egpt_serve_kv_block_deferrals_total",
    "Admissions deferred by the used-token block gate (the queue head's "
    "whole reservation did not fit the free list, even after "
    "reclaiming unpinned prefix entries)")

# -- block-tier preemption + host-RAM KV spill (ISSUE 16,
#    eventgpt_tpu/serve_blocks.py SpillStore + serve.py preemption) --
SERVE_PREEMPTIONS = REGISTRY.counter(
    "egpt_serve_preemptions_total",
    "Active rows preempted to admit higher-value work, by KV "
    "disposition (mode=spill: gathered to the host SpillStore for a "
    "byte-exact restore; mode=drop: released for re-prefill — the "
    "policy's recompute choice or the spill-path fallback)")
SERVE_SPILL_BYTES = REGISTRY.counter(
    "egpt_serve_spill_bytes_total",
    "KV bytes gathered from the device arena into the host SpillStore "
    "(restore scatters the same bytes back; drops re-prefill instead)")
SERVE_RESTORES = REGISTRY.counter(
    "egpt_serve_restores_total",
    "Spilled requests whose KV run was scattered back into the arena "
    "on re-admission (the byte-exact restore path; drop-and-re-prefill "
    "re-admissions do not count here)")
SERVE_SPILL_STORE_BYTES = REGISTRY.gauge(
    "egpt_serve_spill_store_bytes",
    "Host bytes currently resident in the spill store (bounded by "
    "--spill_capacity_mb; also priced into the ledger's spill "
    "component)")
MEM_COMPILED_TEMP = REGISTRY.gauge(
    "egpt_mem_compiled_temp_bytes",
    "XLA temp allocation of the probed decode/spec segment executable "
    "(compiled-footprint probe, lowered.compile().memory_analysis())")
MEM_COMPILED_ARGUMENT = REGISTRY.gauge(
    "egpt_mem_compiled_argument_bytes",
    "XLA argument size of the probed segment executable (resident "
    "buffers the dispatch reads; donated args alias into outputs)")
MEM_COMPILED_OUTPUT = REGISTRY.gauge(
    "egpt_mem_compiled_output_bytes",
    "XLA output size of the probed segment executable")

# -- time-series store + burn-rate alerting (ISSUE 15,
#    eventgpt_tpu/obs/series.py) --
ALERT_ACTIVE = REGISTRY.gauge(
    "egpt_alert_active",
    "1 while the named alert rule is firing, 0 once it cleared "
    "(hysteresis + multi-window burn rates; the rule enum is "
    "ALERT_RULES in obs/series.py)")
ALERT_TRANSITIONS = REGISTRY.counter(
    "egpt_alert_transitions_total",
    "Alert rule state transitions (firing and cleared both count; an "
    "odd count means the rule is currently active)")

# -- fault injection (eventgpt_tpu/faults.py) --
FAULT_TRIPS = REGISTRY.counter(
    "egpt_fault_trips_total",
    "Armed fault-plan fires, by site and kind (fail / delay)")

# -- training (eventgpt_tpu/train/trainer.py) --
TRAIN_LOSS = REGISTRY.gauge(
    "egpt_train_loss", "Mean loss over the last logged accumulation window")
TRAIN_GRAD_NORM = REGISTRY.gauge(
    "egpt_train_grad_norm",
    "Mean global grad norm over the last logged accumulation window")
TRAIN_STEP_SECONDS = REGISTRY.histogram(
    "egpt_train_step_seconds",
    "Wall time per optimizer step (one accumulation window)")
TRAIN_DATA_WAIT = REGISTRY.histogram(
    "egpt_train_data_wait_seconds",
    "Per micro-batch: host wait for data (iterator + host-to-device)",
    SHORT_BUCKETS)
TRAIN_COMPUTE = REGISTRY.histogram(
    "egpt_train_compute_seconds",
    "Per optimizer step: wall time minus data wait (step dispatch plus "
    "device wait at readback boundaries - the compute side of the split)",
    SHORT_BUCKETS)
TRAIN_STEPS = REGISTRY.counter(
    "egpt_train_steps_total", "Completed optimizer steps")
TRAIN_TOKENS = REGISTRY.counter(
    "egpt_train_tokens_total", "Attention-masked tokens consumed")


def configure(enabled: bool) -> None:
    """Arm/disarm the process-global registry."""
    REGISTRY.configure(enabled)


def enabled() -> bool:
    return REGISTRY.enabled


def serve_summary() -> Dict:
    """The /stats merge: compact summaries of every serving metric."""
    return REGISTRY.summary(("egpt_serve_",))


class JsonlSink:
    """Append-per-record JSONL writer (the trainer's ``telemetry.jsonl``):
    one ``json.dumps`` + append per call, no retained handle, so it is
    preemption-safe and costs nothing when unused."""

    def __init__(self, path: str):
        self.path = path

    def write(self, record: Dict) -> None:
        with open(self.path, "a") as f:
            f.write(json.dumps(record) + "\n")
