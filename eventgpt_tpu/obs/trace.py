"""Ring-buffered request/step tracing in Chrome trace-event format.

A span is one host-observed interval (``perf_counter`` at enter/exit);
the tracer keeps the newest ``capacity`` events in a ring so a
long-lived server holds a bounded, always-current window that
``GET /trace`` snapshots on demand and ``--trace_out`` dumps at
shutdown. Events follow the Chrome trace-event format, so a capture
loads directly in Perfetto (ui.perfetto.dev) or chrome://tracing:

  * ``X`` complete events — scheduler phases (dispatch, harvest,
    admission, batch_to_device);
  * ``b``/``e`` async events keyed by request id — each request's
    lifecycle (``queued`` -> ``active`` -> end with a ``status`` arg),
    which is how a single request's timeline reads across overlapping
    scheduler spans;
  * ``i`` instants — point happenings (faults, breaker trips).

Disarmed (the default) every probe is one module-global ``is None``
check — the ``faults.py`` discipline; no timestamps are read and no
objects allocated, so the hot path pays nothing. Armed, a span is two
``perf_counter`` calls plus one dict append under a lock. Tracing reads
clocks only — never jax values — so chains are byte-identical armed or
disarmed (tests/test_obs.py::test_chain_neutrality).

File format (``write()``): the Chrome JSON Array Format, one event per
line — a ``[`` line, then ``{event},`` lines. The spec makes the
closing ``]`` optional precisely so producers can append and crash
safely; Perfetto and chrome://tracing both load it. ``load_trace()``
reads it back (round-trip tested).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

_US = 1e6


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class _Span:
    __slots__ = ("_tr", "_name", "_cat", "_args", "_t0")

    def __init__(self, tr: "Tracer", name: str, cat: str, args):
        self._tr = tr
        self._name = name
        self._cat = cat
        self._args = args
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        self._tr.complete(self._name, self._t0, t1, cat=self._cat,
                          args=self._args)
        return False


class Tracer:
    """Bounded ring of Chrome trace events. All mutation under one lock
    (scheduler + handler + trainer threads)."""

    def __init__(self, capacity: int = 65536):
        self.capacity = max(int(capacity), 1)
        self._buf: List[Optional[dict]] = [None] * self.capacity
        self._head = 0   # next write slot
        self._n = 0      # events ever added
        self._lock = threading.Lock()
        self._pid = os.getpid()

    # -- recording --------------------------------------------------------

    def _add(self, ev: dict) -> None:
        with self._lock:
            self._buf[self._head] = ev
            self._head = (self._head + 1) % self.capacity
            self._n += 1

    def complete(self, name: str, t0: float, t1: float, cat: str = "serve",
                 args: Optional[Dict[str, Any]] = None) -> None:
        ev = {"name": name, "ph": "X", "cat": cat,
              "ts": t0 * _US, "dur": max(t1 - t0, 0.0) * _US,
              "pid": self._pid, "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        self._add(ev)

    def instant(self, name: str, cat: str = "serve",
                args: Optional[Dict[str, Any]] = None) -> None:
        ev = {"name": name, "ph": "i", "s": "t", "cat": cat,
              "ts": time.perf_counter() * _US,
              "pid": self._pid, "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        self._add(ev)

    def async_begin(self, name: str, id: int, cat: str = "request",
                    args: Optional[Dict[str, Any]] = None) -> None:
        ev = {"name": name, "ph": "b", "cat": cat, "id": int(id),
              "ts": time.perf_counter() * _US,
              "pid": self._pid, "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        self._add(ev)

    def async_end(self, name: str, id: int, cat: str = "request",
                  args: Optional[Dict[str, Any]] = None) -> None:
        ev = {"name": name, "ph": "e", "cat": cat, "id": int(id),
              "ts": time.perf_counter() * _US,
              "pid": self._pid, "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        self._add(ev)

    # -- export -----------------------------------------------------------

    def events(self) -> List[dict]:
        """Snapshot of the ring, oldest first. Chrome trace viewers sort
        by ts anyway; the order here just keeps dumps readable."""
        with self._lock:
            if self._n < self.capacity:
                out = [e for e in self._buf[: self._head]]
            else:
                out = self._buf[self._head:] + self._buf[: self._head]
            return [dict(e) for e in out if e is not None]

    def dropped(self) -> int:
        """Events the ring has overwritten (0 until it wraps)."""
        with self._lock:
            return max(self._n - self.capacity, 0)

    def write(self, path: str) -> int:
        """Dump the ring as a Chrome JSON Array Format file, one event
        per line (the trailing ``]`` is optional per the spec, so the
        file is valid even if a later append crashes). Returns the
        number of events written."""
        evs = self.events()
        with open(path, "w") as f:
            f.write("[\n")
            for ev in evs:
                f.write(json.dumps(ev) + ",\n")
        return len(evs)


def load_trace(path: str) -> List[dict]:
    """Read a ``write()``/Chrome-array trace back into a list of events
    (tolerates the optional trailing ``]`` and per-line commas)."""
    with open(path) as f:
        text = f.read().strip()
    if text.startswith("["):
        text = text[1:]
    if text.endswith("]"):
        text = text[:-1]
    out = []
    for line in text.splitlines():
        line = line.strip().rstrip(",")
        if line:
            out.append(json.loads(line))
    return out


_tracer: Optional[Tracer] = None


def configure(capacity: int = 65536) -> Tracer:
    """Arm tracing with a ring of ``capacity`` events; returns the
    tracer. ``capacity <= 0`` disarms."""
    global _tracer
    if capacity <= 0:
        _tracer = None
        return None  # type: ignore[return-value]
    _tracer = Tracer(capacity)
    return _tracer


def disable() -> None:
    global _tracer
    _tracer = None


def active() -> Optional[Tracer]:
    return _tracer


def enabled() -> bool:
    return _tracer is not None


# -- armed-checked probe helpers (the call-site surface) -------------------
# Each is a single module-global load + None check when disarmed.

def span(name: str, cat: str = "serve", **args):
    t = _tracer
    if t is None:
        return _NULL
    return _Span(t, name, cat, args or None)


def instant(name: str, cat: str = "serve", **args) -> None:
    t = _tracer
    if t is not None:
        t.instant(name, cat=cat, args=args or None)


def async_begin(name: str, id: int, cat: str = "request", **args) -> None:
    t = _tracer
    if t is not None:
        t.async_begin(name, id, cat=cat, args=args or None)


def async_end(name: str, id: int, cat: str = "request", **args) -> None:
    t = _tracer
    if t is not None:
        t.async_end(name, id, cat=cat, args=args or None)
