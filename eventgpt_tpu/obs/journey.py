"""Per-request flight recorder + tail-latency attribution (ISSUE 10).

The stack measures *that* requests miss SLOs (goodput windows, the
memory guard) but not *why*: every signal so far is an aggregate, so a
p99 miss under a bursty trace is indistinguishable between queue wait,
a mem-guard deferral, lane starvation under the prefill budget, a
prefix-cache miss and a fleet failover re-decode. This module records
one bounded, append-only EVENT TIMELINE per request (Orca / Sarathi
judge scheduler changes by exactly this decomposition) and derives from
each finished timeline:

  * a **phase decomposition** — ``queue_s / defer_s / preempt_s /
    admission_s / decode_s / host_gap_s / failover_redo_s`` — that
    partitions the
    request's end-to-end latency exactly (the checkpoints are clamped
    into a monotone chain, so the phases sum to ``t_done - t_submit``
    by construction; property-tested);
  * a **dominant miss cause** (the CLOSED ``MISS_CAUSES`` enum — it is
    a metric label, lint rule 5) exported per finish as
    ``egpt_serve_slo_miss_cause_total{slo_class,cause}``.

Event kinds are a CLOSED enum too (``EVENT_KINDS``): recording an
unknown kind raises, and the egpt-check rule-5 cross-check verifies
call-site literals statically. Segment boundaries are recorded per
HARVEST (count + committed tokens), never per decode step, so a
timeline stays O(budget / chunk) events; a per-timeline cap merges
overflow into the last same-kind event (``merged`` counter) instead of
growing without bound.

Identity: timelines key on ``(owner, rid)`` — request ids are
per-batcher, and a fleet runs N batchers in one process, so a bare rid
would collide. ``register_owner()`` hands out process-unique owner ids
(works armed or disarmed, so a batcher can register at construction
and be recorded the moment the recorder arms).

Armed/disarmed like ``trace.py``: disarmed (the default) every probe is
one module-global ``is None`` check — no timestamps read, no objects
allocated. Recording reads host clocks and host ints ONLY, never jax
values, so decoded chains are byte-identical armed or disarmed (tested,
and re-measured in the workload bench's interleaved A/B). Retention:
live timelines plus a ring of the last ``keep`` finished requests
(``--journey_keep``), snapshotted by ``GET /requests`` /
``GET /request?rid=N``.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

# The CLOSED set of event kinds (bounded by construction; the
# egpt-check rule-5 cross-check verifies call-site literals against
# this tuple, which must stay a PURE LITERAL — it is read with
# ast.literal_eval, no imports):
#   submit          request entered the admission queue
#   queue           request LEFT the queue (queue wait ends here)
#   prefix          prefix-cache decision (hit + matched length, or miss)
#   mem_guard_defer the headroom guard deferred this request's boundary
#   kv_block_defer  the paged pool's used-token gate deferred it (the
#                   queue head's block reservation did not fit the free
#                   list; ISSUE 12) — counts into defer_s like the
#                   byte-headroom deferral
#   lane_join       admission became a piggyback prefill lane
#   lane_finish     the lane covered its prompt (activation follows)
#   admit           row activated into the shared cache
#                   (path = full | wave | suffix | suffix_wave | chunk | lane)
#   segment         one harvest committed tokens to this row
#                   (count + tokens per BOUNDARY, never per step)
#   spec_depth      the adaptive speculation controller SWITCHED this
#                   row's dispatch-boundary window (ISSUE 13; emitted on
#                   change only, to every live row — same-kind merge
#                   keeps it bounded)
#   shed            the fleet router refused the request (policy shed)
#   route           the fleet router placed the request on a replica
#   repin           failover moved the session's affinity pin
#   failover        the request re-routed to a survivor (re-decode)
#   worker_lost     the request's worker PROCESS died hard (SIGKILL /
#                   crash / unreachable) — the redo failover follows
#   respawn         the coordinator spawned a replacement process into
#                   the lost worker's slot while this request was live
#   preempt         an active row was evicted to admit higher-value
#                   work (ISSUE 16; mode = spill | drop) — the request
#                   re-queues and the preempt->resume interval is
#                   carved out as ``preempt_s``
#   spill           the victim's KV run was gathered to the host
#                   SpillStore (bytes + blocks recorded)
#   restore         a spilled run was scattered back into the arena on
#                   re-admission (ends the preempt interval; the drop
#                   path's interval ends at its re-dequeue instead)
#   kv_handoff      the paged prefill->decode handoff (ISSUE 17): one
#                   event per stage — ``gathered`` (prefill worker
#                   pulled the block run to host), ``shipped``
#                   (coordinator moved it to a decode worker over RPC),
#                   ``spliced`` (decode worker scattered it into its
#                   arena) — with bytes + block count
#   nan_quarantine / deadline / cancel   forced-finish markers
#   exported        the replica drained it for re-admission elsewhere
#   finish          terminal bookkeeping (status + slo_met)
EVENT_KINDS = (
    "submit", "queue", "prefix", "mem_guard_defer", "kv_block_defer",
    "lane_join", "lane_finish", "admit", "segment", "spec_depth", "shed",
    "route",
    "repin", "failover", "worker_lost", "respawn", "preempt", "spill",
    "restore", "kv_handoff", "nan_quarantine",
    "deadline", "cancel", "exported", "finish",
)

# The CLOSED dominant-miss-cause enum. It is the ``cause`` label of
# ``egpt_serve_slo_miss_cause_total`` — obs/metrics.py METRIC_LABELS
# mirrors this tuple and the egpt-check rule-5 cross-check asserts the
# two literals stay identical. Phase causes map 1:1 onto the
# decomposition keys (``<cause>_s``); ``nan_quarantine`` and ``shed``
# are the two non-time causes (a poisoned row / a router refusal have
# no meaningful time story); ``other`` absorbs degenerate timelines
# (e2e ~ 0).
MISS_CAUSES = (
    "queue", "defer", "preempt", "admission", "decode", "host_gap",
    "failover_redo", "handoff", "nan_quarantine", "shed", "other",
)

# Decomposition keys in checkpoint order (the partition of
# [t_submit, t_done]; see ``_phases``). ``preempt_s`` is carved out of
# the queue/defer side: a preempted request's wait-to-resume interval
# lands in queue_s/defer_s under the checkpoint clamps (its re-dequeue
# overwrites ``t_dequeue``), so the carve re-attributes it without
# breaking the exact-sum invariant.
PHASE_KEYS = ("queue_s", "defer_s", "preempt_s", "admission_s", "decode_s",
              "host_gap_s", "failover_redo_s", "handoff_s")


def _phases(t_submit: float, t_defer: Optional[float],
            t_dequeue: Optional[float], t_admit: Optional[float],
            t_last_commit: Optional[float], t_done: float,
            preempt_acc: float = 0.0,
            ) -> Dict[str, float]:
    """Partition ``[t_submit, t_done]`` into the phase decomposition.

    Checkpoints are clamped into a monotone chain; a missing checkpoint
    collapses its phase to zero by inheriting the NEXT known boundary
    (a request that expired in the queue spends everything in
    queue/defer; one that never committed spends its post-admission
    time in decode). The phases therefore sum to ``t_done - t_submit``
    EXACTLY — the invariant the property test pins.

      queue_s      submit -> first mem-guard deferral (or dequeue)
      defer_s      first deferral -> dequeue (0 when never deferred)
      admission_s  dequeue -> row activation (encode + prefill + lane
                   prefill + scatter — a prefix miss shows up here)
      decode_s     activation -> last committed token
      host_gap_s   last committed token -> terminal bookkeeping (the
                   finish-side host tail: harvest->finish delay,
                   deadline slack after the final commit)
      preempt_s    accumulated preempt -> resume wait (ISSUE 16).
                   A preempted request's wait lands inside
                   queue_s/defer_s under the clamps (its re-dequeue
                   overwrote ``t_dequeue``), so this carves
                   ``min(preempt_acc, defer_s + queue_s)`` back out —
                   defer_s first, then queue_s — keeping the exact-sum
                   partition.
      failover_redo_s  0 at this layer; the fleet's stitched view adds
                   the abandoned assignments' wall time here.
      handoff_s    0 at this layer; the fleet's stitched view charges
                   the prefill->decode KV move (gather + RPC ship +
                   splice wait) here from coordinator-measured
                   durations (ISSUE 17).
    """
    td = t_done
    tq = t_dequeue if t_dequeue is not None else td
    tq = min(max(tq, t_submit), td)
    ta = t_admit if t_admit is not None else td
    ta = min(max(ta, tq), td)
    tc = t_last_commit if t_last_commit is not None else td
    tc = min(max(tc, ta), td)
    tdef = t_defer if t_defer is not None else tq
    tdef = min(max(tdef, t_submit), tq)
    queue_s = tdef - t_submit
    defer_s = tq - tdef
    host_gap_s = td - tc
    # Carve the preempt wait out of the phases that absorbed it under
    # the clamps: defer_s/queue_s when the request resumed (its
    # re-dequeue overwrote t_dequeue), host_gap_s when it died while
    # still preempted (t_dequeue stayed at the first dequeue, so the
    # wait sits past the last commit). Order: defer, queue, host_gap.
    preempt_s = min(max(float(preempt_acc), 0.0),
                    queue_s + defer_s + host_gap_s)
    rem = preempt_s
    carve = min(rem, defer_s)
    defer_s -= carve
    rem -= carve
    carve = min(rem, queue_s)
    queue_s -= carve
    rem -= carve
    host_gap_s -= rem
    return {
        "queue_s": queue_s,
        "defer_s": defer_s,
        "preempt_s": preempt_s,
        "admission_s": ta - tq,
        "decode_s": tc - ta,
        "host_gap_s": host_gap_s,
        "failover_redo_s": 0.0,
        "handoff_s": 0.0,
    }


def dominant_cause(status: str, phases: Optional[Dict[str, float]]) -> str:
    """The closed-enum dominant miss cause of one finished request:
    non-time terminal statuses first (a poisoned row / a router shed
    have no time story), else the largest decomposition phase (ties
    break in checkpoint order — the earlier phase wins, since later
    time is often a consequence of it), else ``other``."""
    if status == "nan_quarantined":
        return "nan_quarantine"
    if status == "shed":
        return "shed"
    if not phases:
        return "other"
    best_key, best = None, 0.0
    for key in PHASE_KEYS:
        v = float(phases.get(key, 0.0))
        if v > best:
            best_key, best = key, v
    if best_key is None:
        return "other"
    return best_key[: -len("_s")]  # "queue_s" -> "queue", ...


class JourneyRecorder:
    """Bounded, thread-safe store of per-request event timelines.

    One lock guards everything (scheduler threads, HTTP handler
    threads and the fleet supervisor all record/read); every operation
    is a few dict writes, so the armed cost per event is comparable to
    a metric observation. jax-free by construction — timestamps are
    ``time.perf_counter`` floats and fields are host ints/strings.
    """

    # Lock-discipline contract (egpt_check rule ``lock``, ISSUE 10
    # satellite): live + finished maps and the drop counters only
    # mutate/read under the recorder's own lock.
    _GUARDED_BY = {
        "_live": "_lock",
        "_done": "_lock",
        "_dropped_live": "_lock",
        "_duplicate_finishes": "_lock",
    }

    def __init__(self, keep: int = 512, max_events: int = 128,
                 live_cap: int = 4096):
        self.keep = max(int(keep), 1)
        self.max_events = max(int(max_events), 8)
        self.live_cap = max(int(live_cap), self.keep)
        self._lock = threading.Lock()
        self._live: "OrderedDict[Tuple[int, int], dict]" = OrderedDict()
        self._done: "OrderedDict[Tuple[int, int], dict]" = OrderedDict()
        self._dropped_live = 0        # live timelines evicted at cap
        self._duplicate_finishes = 0  # double-finish bugs (audit test: 0)

    # -- recording --------------------------------------------------------

    def _new_rec(self, owner: int, rid: int, t: float) -> dict:
        return {
            "owner": int(owner), "rid": int(rid),
            "t_submit": float(t),
            "events": [{"t": float(t), "kind": "submit"}],
            "t_defer": None, "t_dequeue": None, "t_admit": None,
            "t_last_commit": None,
            "t_preempt": None, "preempt_acc": 0.0,
            "tokens": 0, "segments": 0, "merged": 0,
            "finished": False,
        }

    def begin(self, owner: int, rid: int, t: Optional[float] = None,
              **fields) -> None:
        t = time.perf_counter() if t is None else float(t)
        rec = self._new_rec(owner, rid, t)
        if fields:
            rec["events"][0].update(fields)
            rec.update({k: v for k, v in fields.items()
                        if k in ("prompt_len", "budget", "slo_class")})
        with self._lock:
            self._live[(owner, rid)] = rec
            while len(self._live) > self.live_cap:
                self._live.popitem(last=False)
                self._dropped_live += 1

    def event(self, owner: int, rid: int, kind: str,
              t: Optional[float] = None, **fields) -> None:
        if kind not in EVENT_KINDS:
            raise ValueError(
                f"unknown journey event kind {kind!r}: one of "
                f"{EVENT_KINDS} (the enum is closed — egpt-check rule 5 "
                f"cross-checks call sites)")
        t = time.perf_counter() if t is None else float(t)
        with self._lock:
            rec = self._live.get((owner, rid))
            if rec is None:
                # Armed mid-flight (or an evicted live timeline): start
                # a stub so the tail of the request is still explained.
                rec = self._new_rec(owner, rid, t)
                self._live[(owner, rid)] = rec
            ev = {"t": t, "kind": kind}
            if fields:
                ev.update(fields)
            if len(rec["events"]) >= self.max_events:
                last = rec["events"][-1]
                if last["kind"] == kind:
                    # Merge into the trailing same-kind event (defer
                    # streaks, long decodes): timeline stays bounded,
                    # the checkpoint bookkeeping below stays exact.
                    last["t"] = t
                    last["n"] = int(last.get("n", 1)) + 1
                    if kind == "segment" and "tokens" in fields:
                        last["tokens"] = (int(last.get("tokens", 0))
                                          + int(fields["tokens"]))
                else:
                    rec["merged"] += 1
            else:
                rec["events"].append(ev)
            # Checkpoints for the phase decomposition (kept in the
            # header so truncation can never skew the phases).
            if kind == "queue":
                rec["t_dequeue"] = t
                if rec["t_preempt"] is not None:
                    # A preempted request's re-dequeue ends its wait
                    # (the drop path re-prefills from here; the spill
                    # path's ``restore`` usually lands first).
                    rec["preempt_acc"] += t - rec["t_preempt"]
                    rec["t_preempt"] = None
            elif kind == "preempt":
                rec["t_preempt"] = t
            elif kind == "restore":
                if rec["t_preempt"] is not None:
                    rec["preempt_acc"] += t - rec["t_preempt"]
                    rec["t_preempt"] = None
            elif kind == "admit":
                rec["t_admit"] = t
            elif kind == "segment":
                rec["t_last_commit"] = t
                rec["segments"] += 1
                rec["tokens"] += int(fields.get("tokens", 0))
            elif (kind in ("mem_guard_defer", "kv_block_defer")
                    and rec["t_defer"] is None):
                rec["t_defer"] = t

    def finish(self, owner: int, rid: int, status: str,
               t_submit: Optional[float] = None,
               t_done: Optional[float] = None,
               slo_class: Optional[str] = None,
               slo_met: Optional[bool] = None,
               phases: Optional[Dict[str, float]] = None,
               **fields) -> dict:
        """Terminal bookkeeping: append the ``finish`` event, compute
        the phase decomposition + dominant cause, and move the timeline
        into the finished ring. Returns the finished record (the caller
        exports ``cause`` to the miss-cause metric). ``phases``
        overrides the computed decomposition — the fleet's stitcher
        passes the final assignment's phases plus ``failover_redo_s``
        (pass matching ``t_submit``/``t_done`` so the sum invariant
        holds)."""
        t_done = time.perf_counter() if t_done is None else float(t_done)
        with self._lock:
            rec = self._live.pop((owner, rid), None)
            if rec is None:
                rec = self._new_rec(
                    owner, rid,
                    t_done if t_submit is None else float(t_submit))
            elif t_submit is not None:
                # The caller's submit stamp is authoritative (it is the
                # same float the latency metrics use), so the phase sum
                # equals the reported latency exactly.
                rec["t_submit"] = float(t_submit)
            rec["t_done"] = t_done
            rec["status"] = str(status)
            if slo_class is not None:
                rec["slo_class"] = slo_class
            rec["slo_met"] = slo_met
            rec["e2e_s"] = t_done - rec["t_submit"]
            preempt_acc = float(rec.get("preempt_acc", 0.0))
            if rec.get("t_preempt") is not None:
                # Finished while still preempted (deadline / cancel in
                # the re-queue): the open interval ends at t_done.
                preempt_acc += max(t_done - rec["t_preempt"], 0.0)
                rec["t_preempt"] = None
                rec["preempt_acc"] = preempt_acc
            rec["phases"] = (dict(phases) if phases is not None
                             else _phases(
                                 rec["t_submit"], rec["t_defer"],
                                 rec["t_dequeue"], rec["t_admit"],
                                 rec["t_last_commit"], t_done,
                                 preempt_acc))
            rec["cause"] = dominant_cause(rec["status"], rec["phases"])
            ev = {"t": t_done, "kind": "finish", "status": rec["status"]}
            if slo_met is not None:
                ev["slo_met"] = bool(slo_met)
            if fields:
                ev.update(fields)
            rec["events"].append(ev)
            rec["finished"] = True
            if (owner, rid) in self._done:
                # A second finish for the same request is a terminal-
                # path bug; count it loudly (the audit test pins 0)
                # instead of silently replacing the first record.
                self._duplicate_finishes += 1
            self._done[(owner, rid)] = rec
            while len(self._done) > self.keep:
                self._done.popitem(last=False)
            return rec

    # -- export -----------------------------------------------------------

    def _export_locked(self, rec: dict) -> dict:
        """JSON-shaped copy: event times relative to submit (absolute
        perf_counter floats mean nothing to a client)."""
        t0 = rec["t_submit"]
        out = {
            "rid": rec["rid"], "owner": rec["owner"],
            "finished": rec["finished"],
            "tokens": rec["tokens"], "segments": rec["segments"],
            "events": [
                {**{k: v for k, v in ev.items() if k != "t"},
                 "t_s": round(ev["t"] - t0, 6)}
                for ev in rec["events"]
            ],
        }
        for k in ("prompt_len", "budget", "slo_class", "status",
                  "cause", "merged"):
            if rec.get(k) not in (None, 0):
                out[k] = rec[k]
        if rec.get("slo_met") is not None:
            # Explicit None-check: ``False == 0`` would drop a missed
            # request's verdict from the export (the one field the
            # miss-cause accounting keys on).
            out["slo_met"] = rec["slo_met"]
        if rec["finished"]:
            out["e2e_s"] = rec["e2e_s"]
            out["phases"] = dict(rec["phases"])
            out["t_submit"] = rec["t_submit"]
            out["t_done"] = rec["t_done"]
        return out

    def get(self, owner: int, rid: int) -> Optional[dict]:
        """One timeline (finished preferred, live fallback), export
        shape; None when unknown."""
        with self._lock:
            rec = self._done.get((owner, rid)) \
                or self._live.get((owner, rid))
            return self._export_locked(rec) if rec is not None else None

    def raw(self, owner: int, rid: int) -> Optional[dict]:
        """The internal record (absolute timestamps) — the fleet's
        stitcher and tests read checkpoints from here."""
        with self._lock:
            rec = self._done.get((owner, rid)) \
                or self._live.get((owner, rid))
            return dict(rec) if rec is not None else None

    def index(self, owner: Optional[int] = None, n: int = 64) -> List[dict]:
        """Recent finished requests, newest first: the ``GET /requests``
        payload — rid / status / slo / cause, one line per request."""
        with self._lock:
            recs = [r for r in reversed(self._done.values())
                    if owner is None or r["owner"] == owner]
            out = []
            for rec in recs[: max(int(n), 1)]:
                out.append({
                    "rid": rec["rid"], "owner": rec["owner"],
                    "status": rec.get("status"),
                    "slo_class": rec.get("slo_class"),
                    "slo_met": rec.get("slo_met"),
                    "cause": rec.get("cause"),
                    "e2e_s": round(rec.get("e2e_s", 0.0), 6),
                    "tokens": rec["tokens"],
                })
            return out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "keep": self.keep,
                "live": len(self._live),
                "finished": len(self._done),
                "dropped_live": self._dropped_live,
                "duplicate_finishes": self._duplicate_finishes,
            }


# -- module-global arming (the trace.py discipline) ------------------------

_recorder: Optional[JourneyRecorder] = None

# Owner ids are process-unique and independent of arming, so a batcher
# registered while disarmed records correctly the moment the recorder
# arms (same pattern as the memory ledger's owner namespaces).
_owner_lock = threading.Lock()
_next_owner = 0


def register_owner(label: str = "") -> int:
    global _next_owner
    with _owner_lock:
        owner = _next_owner
        _next_owner += 1
        return owner


def configure(keep: int = 512) -> Optional[JourneyRecorder]:
    """Arm the flight recorder keeping the last ``keep`` finished
    request timelines; ``keep <= 0`` disarms."""
    global _recorder
    if keep <= 0:
        _recorder = None
        return None
    _recorder = JourneyRecorder(keep)
    return _recorder


def disable() -> None:
    global _recorder
    _recorder = None


def active() -> Optional[JourneyRecorder]:
    return _recorder


def enabled() -> bool:
    return _recorder is not None


# -- armed-checked probes (one module-global load + None check when
#    disarmed; no clock read, no allocation) -------------------------------

def begin(owner: int, rid: int, t: Optional[float] = None, **fields) -> None:
    r = _recorder
    if r is not None:
        r.begin(owner, rid, t=t, **fields)


def event(owner: int, rid: int, kind: str, t: Optional[float] = None,
          **fields) -> None:
    r = _recorder
    if r is not None:
        r.event(owner, rid, kind, t=t, **fields)


def finish(owner: int, rid: int, status: str,
           t_submit: Optional[float] = None,
           t_done: Optional[float] = None,
           slo_class: Optional[str] = None,
           slo_met: Optional[bool] = None,
           phases: Optional[Dict[str, float]] = None,
           **fields) -> Optional[dict]:
    r = _recorder
    if r is None:
        return None
    return r.finish(owner, rid, status, t_submit=t_submit, t_done=t_done,
                    slo_class=slo_class, slo_met=slo_met, phases=phases,
                    **fields)


def get(owner: int, rid: int) -> Optional[dict]:
    r = _recorder
    return None if r is None else r.get(owner, rid)


def raw(owner: int, rid: int) -> Optional[dict]:
    r = _recorder
    return None if r is None else r.raw(owner, rid)


def index(owner: Optional[int] = None, n: int = 64) -> List[dict]:
    r = _recorder
    return [] if r is None else r.index(owner, n)
