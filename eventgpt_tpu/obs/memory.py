"""HBM memory ledger — per-component device-byte accounting (ISSUE 9).

The serving stack has deep latency/goodput observability but was blind
on the axis that actually caps it: HBM. The batch ceiling (40 OOMs at
runtime, 48 at compile — PERFORMANCE.md "Batch scaling") and the prefix
cache's byte budget both manage memory with no visibility into what the
rest of the process holds. This module is the instrument that says
where every byte lives, BEFORE the paged-KV block-pool refactor
(ROADMAP item 2) redistributes them:

  * **Ledger** (``LEDGER``, process-global, thread-safe): named
    components — weight tree, resident KV cache, logits/ids buffers,
    prefix-cache entries, mixed-segment lane buffers, Medusa/draft
    buffers, pipelined carry state — updated by explicit
    ``register``/``resize``/``release`` hooks at every allocation site
    (``ContinuousBatcher``, ``PrefixCache``, the lane allocator, model
    load). Tracks current and PEAK totals; exports ``egpt_mem_*``
    gauges and ``mem_alloc``/``mem_release`` trace instants.
  * **Static capacity model** (``estimate``): closed-form bytes per
    row / lane / entry from config — dtype, int8-KV scale planes,
    SEQ_BUCKET grain, batch — with the sharding divisors of
    ``parallel/serving.py`` applied when a mesh shape is given (batch
    over the largest dividing prefix of ``(data, fsdp)``, KV heads
    over ``model`` when divisible, weight matmuls over
    ``fsdp × model``). This is the model that predicts the ceiling
    item 2 must break, and the 13B-over-a-pod fit check
    (``tests/test_13b_readiness.py``).
  * **Compiled-footprint probe** (``compiled_stats``): pulls
    ``lowered.compile().memory_analysis()`` (temp / argument / output
    sizes) from the jit executables the scheduler already runs — the
    XLA-side bytes the ledger cannot see (fusion temps, donation
    aliases). Backend support varies; unsupported backends report
    ``{"unavailable": ...}`` instead of raising.
  * **Reconciliation** (``reconcile``): sums ``jax.live_arrays()`` and
    reports the accounted/unaccounted split — the honesty check that
    keeps the ledger from silently drifting from reality
    (``tests/test_memory_ledger.py`` holds it at ≥ 90% on the CPU
    tiny server).

Like the rest of ``obs/``, the ledger core is jax-free (host ints under
one lock; ``reconcile``/``abstract_params_bytes`` import jax lazily)
and chain-neutral: it reads sizes and counts allocations, never a jax
value — chains are byte-identical with the ledger armed or idle. Lock
order: callers may hold their own lock (``PrefixCache._lock``) when
calling in; the ledger lock is a leaf below them and above the metric
locks (``caller -> MemoryLedger._lock -> _Metric._lock``, never
reversed).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple

from eventgpt_tpu.obs import metrics as obs_metrics
from eventgpt_tpu.obs import trace as obs_trace

# The component taxonomy (OBSERVABILITY.md "Memory ledger"). A CLOSED
# set on purpose: component names become the egpt_mem_component_bytes
# label values (METRIC_LABELS enum, lint rule 5 — bounded cardinality).
COMPONENTS = ("weights", "kv_cache", "kv_pool", "kv_block_table", "logits",
              "ids_buf", "prefix_cache", "lanes", "draft", "carry", "spill",
              "other")


class MemoryLedger:
    """Process-global device-byte ledger: ``(component, key)`` -> bytes.

    ``key`` namespaces an entry to its owner (``"b1a2f/kv_cache"``) so a
    fleet of in-process replicas can each report THEIR resident bytes
    (``snapshot(owner=...)``) while the process totals stay the sum.
    Registering an existing key is a resize (idempotent re-registration
    of a shared weight tree costs nothing); ``release`` drops the entry.

    Thread-safety: the scheduler thread registers/releases while HTTP
    handler threads read ``summary()`` — every mutation and compound
    read takes ``_lock``. Peak tracking (``peak_bytes``) is phase-scoped
    via ``reset_peak()`` (the bench's per-point reset, like
    ``reset_serving_stats``)."""

    # Lock-discipline contract (egpt-check rule ``lock``): byte counters
    # and the entry map only move under the ledger lock. The last
    # reconcile results are snapshot/flag reads (``/w``) — swapped
    # whole under the lock, read lock-free by summary consumers.
    _GUARDED_BY = {
        "_entries": "_lock",
        "_component_totals": "_lock",
        "total_bytes": "_lock",
        "peak_bytes": "_lock",
        "_live_bytes": "_lock/w",
        "_unaccounted_bytes": "_lock/w",
    }

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[Tuple[str, str], int] = {}
        self._component_totals: Dict[str, int] = {}
        self.total_bytes = 0
        self.peak_bytes = 0
        # Last reconcile() results (None until the first run): summary()
        # reads them lock-free — GET /memory refreshes, /stats must not
        # walk jax.live_arrays() once per scheduler step.
        self._live_bytes: Optional[int] = None
        self._unaccounted_bytes: Optional[int] = None

    def register(self, component: str, key: str, nbytes: int) -> None:
        """Attribute ``nbytes`` device bytes to ``(component, key)``.
        Re-registering a key RESIZES it (the delta moves the totals), so
        growth sites (lane-bucket growth, prefix-cache totals) call this
        unconditionally."""
        if component not in COMPONENTS:
            raise ValueError(
                f"unknown memory component {component!r}: one of "
                f"{COMPONENTS} (the taxonomy is a closed metric-label "
                f"enum — extend COMPONENTS + METRIC_LABELS together)")
        nbytes = int(nbytes)
        with self._lock:
            old = self._entries.get((component, key), 0)
            delta = nbytes - old
            if delta == 0 and (component, key) in self._entries:
                return
            self._entries[(component, key)] = nbytes
            self._component_totals[component] = (
                self._component_totals.get(component, 0) + delta)
            self.total_bytes += delta
            if self.total_bytes > self.peak_bytes:
                self.peak_bytes = self.total_bytes
            self._export_gauges_locked(component)
        # Trace outside the lock (instants take the tracer's own lock);
        # armed tracing shows every allocation move on the timeline.
        obs_trace.instant("mem_alloc" if delta > 0 else "mem_release",
                          cat="mem", component=component,
                          delta_bytes=delta, total_bytes=nbytes)

    # resize IS register (the delta form); the alias documents intent at
    # call sites that shrink/grow an existing allocation.
    resize = register

    def release(self, component: str, key: str) -> None:
        """Drop an entry (the allocation was freed). Unknown keys are a
        no-op — release paths run in sweeps that may repeat."""
        with self._lock:
            old = self._entries.pop((component, key), None)
            if old is None:
                return
            self._component_totals[component] = (
                self._component_totals.get(component, 0) - old)
            self.total_bytes -= old
            self._export_gauges_locked(component)
        obs_trace.instant("mem_release", cat="mem", component=component,
                          delta_bytes=-old, total_bytes=0)

    def _export_gauges_locked(self, component: str) -> None:
        obs_metrics.MEM_TOTAL.set(self.total_bytes)
        obs_metrics.MEM_PEAK.set(self.peak_bytes)
        obs_metrics.MEM_COMPONENT.set(
            self._component_totals.get(component, 0), component=component)

    def reset_peak(self) -> None:
        """Phase-scope the peak to the traffic that follows (the bench's
        per-point reset)."""
        with self._lock:
            self.peak_bytes = self.total_bytes
            obs_metrics.MEM_PEAK.set(self.peak_bytes)

    def component_bytes(self, component: str) -> int:
        with self._lock:
            return self._component_totals.get(component, 0)

    def total(self) -> int:
        with self._lock:
            return self.total_bytes

    def snapshot(self, owner: Optional[str] = None) -> Dict[str, int]:
        """Per-component byte totals; ``owner`` filters to keys under
        ``"{owner}/"`` (one replica's resident share of the process)."""
        with self._lock:
            if owner is None:
                return {c: n for c, n in
                        sorted(self._component_totals.items()) if n}
            pre = owner + "/"
            out: Dict[str, int] = {}
            for (comp, key), n in sorted(self._entries.items()):
                if key.startswith(pre):
                    out[comp] = out.get(comp, 0) + n
            return out

    def summary(self) -> Dict[str, Any]:
        """The /stats merge + bench record body: ledger totals plus the
        LAST reconcile's accounted/unaccounted split (None until one
        ran) — all host ints, no jax walk."""
        with self._lock:
            out: Dict[str, Any] = {
                "total_bytes": self.total_bytes,
                "peak_bytes": self.peak_bytes,
                "components": {c: n for c, n in
                               sorted(self._component_totals.items()) if n},
                "entries": len(self._entries),
            }
        out["live_bytes"] = self._live_bytes
        out["unaccounted_bytes"] = self._unaccounted_bytes
        return out

    def reconcile(self) -> Dict[str, Any]:
        """Honesty check: sum ``jax.live_arrays()`` and report the
        accounted/unaccounted split. The ledger attributes what the
        runtime REGISTERS; everything else (transient admission caches
        in flight, jit constants, leaked test fixtures) shows up here
        as unaccounted instead of silently vanishing. Costly relative
        to a counter read (walks every live buffer) — called from
        GET /memory and bench points, never per scheduler step."""
        import jax

        live = 0
        for arr in jax.live_arrays():
            try:
                live += arr.nbytes
            except Exception:  # a deleted/donated array mid-walk
                continue
        with self._lock:
            total = self.total_bytes
            unaccounted = live - total
            self._live_bytes = live
            self._unaccounted_bytes = unaccounted
        obs_metrics.MEM_LIVE.set(live)
        obs_metrics.MEM_UNACCOUNTED.set(unaccounted)
        return {
            "live_bytes": live,
            "accounted_bytes": total,
            "unaccounted_bytes": unaccounted,
            "accounted_ratio": (total / live) if live else 1.0,
        }


LEDGER = MemoryLedger()


def params_bytes(tree: Any) -> int:
    """Sum of leaf ``nbytes`` over a (possibly nested) param tree —
    works on concrete arrays and numpy alike (metadata only, no sync).
    The weight-tree registration helper."""
    import jax

    return int(sum(x.nbytes for x in jax.tree_util.tree_leaves(tree)
                   if hasattr(x, "nbytes")))


def abstract_params_bytes(cfg, quant: str = "bf16", dtype_bytes: int = 2
                          ) -> int:
    """Weight-tree bytes WITHOUT materializing weights: ``eval_shape``
    the init + (optional) int8/int4 quantization transform and sum the
    abstract leaf sizes — the 13B static-capacity check's weights term
    (the same never-materialize discipline as test_13b_readiness)."""
    import jax
    import jax.numpy as jnp

    from eventgpt_tpu.models import eventchat
    from eventgpt_tpu.ops import quant as quant_mod

    dtype = {2: jnp.bfloat16, 4: jnp.float32}[int(dtype_bytes)]
    shapes = jax.eval_shape(
        lambda k: eventchat.init_eventchat_params(cfg, k, dtype),
        jax.random.PRNGKey(0),
    )
    if quant in ("int8", "int4"):
        shapes = {
            **shapes,
            "llama": jax.eval_shape(
                lambda p: quant_mod.quantize_llama_params(
                    p, bits=4 if quant == "int4" else 8),
                shapes["llama"],
            ),
        }
    total = 0
    for leaf in jax.tree_util.tree_leaves(shapes):
        size = 1
        for d in leaf.shape:
            size *= int(d)
        total += size * leaf.dtype.itemsize
    return total


def _grain_round(n: int, grain: int) -> int:
    return ((int(n) + grain - 1) // grain) * grain


def kv_pos_bytes(cfg, kv_quant: bool = False, dtype_bytes: int = 2) -> int:
    """K+V bytes of ONE cache position of ONE row — the unit every
    row/lane/entry estimate multiplies. Mirrors ``llama.init_kv_cache``
    exactly: bf16 stores ``L × 2 × KV × hd`` payload; int8 halves the
    payload and adds one f32 scale per (layer, position, kv-head)."""
    lc = cfg.llama
    hd = lc.resolved_head_dim()
    per_plane = lc.num_layers * lc.num_kv_heads  # per (k|v) per position
    if kv_quant:
        return 2 * per_plane * (hd * 1 + 4)  # int8 payload + f32 scale
    return 2 * per_plane * hd * dtype_bytes


def _mesh_divisors(cfg, mesh_shape: Optional[Dict[str, int]],
                   batch: int) -> Dict[str, int]:
    """The sharding divisors of the serving layout — delegated to
    ``parallel.serving.serving_divisors`` so the capacity model and the
    placement code can never drift (lazy import: the jax-heavy module
    only loads when a mesh shape is actually given)."""
    if not mesh_shape:
        return {"batch": 1, "kv_heads": 1, "weights": 1}
    from eventgpt_tpu.parallel.serving import serving_divisors

    return serving_divisors(cfg.llama.num_kv_heads, mesh_shape, batch)


def estimate(cfg, *, max_batch: int, max_len: int, kv_quant: bool = False,
             dtype_bytes: int = 2, speculative: int = 0,
             prefill_budget: int = 0, prefill_lane_chunk: int = 0,
             lane_bucket: Optional[int] = None,
             prefix_cache_bytes: int = 0, weights_bytes: int = 0,
             vocab: Optional[int] = None,
             mesh_shape: Optional[Dict[str, int]] = None,
             kv_layout: str = "dense", kv_pool_blocks: int = 0,
             kv_block_size: int = 0) -> Dict[str, Any]:
    """Static capacity model: closed-form component bytes for one
    ``ContinuousBatcher`` from its config — what the server WILL hold
    resident, before it is ever built. Mirrors the constructor's own
    arithmetic (grain-rounded ``max_len``, lane cap/chunk policy,
    unquantized lane cache) so ``tests/test_memory_ledger.py`` can hold
    it byte-exact against the live buffers.

    ``weights_bytes``: the weight-tree term, supplied by the caller
    (``params_bytes`` for a live tree, ``abstract_params_bytes`` for a
    never-materialized one) — weight layout (quant/fuse/LoRA) is not
    re-derived here. ``mesh_shape`` ({"data": d, "fsdp": f,
    "model": m}) applies the serving sharding divisors and adds a
    ``per_device`` view — the 13B-over-a-pod fit check."""
    from eventgpt_tpu.constants import SEQ_BUCKET

    grain = 2 * SEQ_BUCKET
    max_len = _grain_round(max_len, grain)
    pos_bytes = kv_pos_bytes(cfg, kv_quant, dtype_bytes)
    row_bytes = max_len * pos_bytes
    vocab = int(vocab if vocab is not None else cfg.llama.vocab_size)

    comp: Dict[str, int] = {}
    if weights_bytes:
        comp["weights"] = int(weights_bytes)
    if kv_layout == "paged":
        # Paged layout (ISSUE 12): one block-pool arena — n_blocks
        # blocks of block_size positions per layer/plane, SCRATCH block
        # included — plus the per-row int32 block tables and the (B,)
        # length plane. Mirrors serve's constructor arithmetic exactly
        # (default pool = dense-equivalent capacity + 1 scratch) so the
        # ledger test can hold it byte-exact against the live arena.
        bs = int(kv_block_size) or SEQ_BUCKET
        nbpr = max_len // bs
        n_blocks = int(kv_pool_blocks) or (max_batch * nbpr + 1)
        comp["kv_pool"] = n_blocks * bs * pos_bytes
        comp["kv_block_table"] = max_batch * nbpr * 4 + max_batch * 4
    else:
        # Resident decode cache: B rows + the (B,) int32 length plane.
        comp["kv_cache"] = max_batch * row_bytes + max_batch * 4
    # Per-row next-token logits carry (f32 by construction).
    comp["logits"] = max_batch * vocab * 4
    if speculative:
        # ids_buf (B, max_len) int32 + the carried drafts (B, W-1) int32.
        comp["ids_buf"] = max_batch * max_len * 4
        comp["draft"] = max_batch * max(speculative - 1, 0) * 4
    if prefill_budget > 0:
        # The constructor's lane policy, verbatim: chunk_p =
        # prefill_lane_chunk or min(budget, SEQ_BUCKET); K_cap =
        # budget // chunk_p capped at max_batch. Lane KV is ALWAYS
        # unquantized (the exactness rule), plus the (K, S, D) embeds.
        lane_chunk = int(prefill_lane_chunk) or min(prefill_budget,
                                                    SEQ_BUCKET)
        lane_chunk = max(1, min(lane_chunk, prefill_budget))
        k_cap = max(1, min(prefill_budget // lane_chunk, max_batch))
        s_lane = _grain_round(lane_bucket or grain, grain)
        s_lane = min(s_lane, max_len)
        lane_pos = kv_pos_bytes(cfg, False, dtype_bytes)
        comp["lanes"] = k_cap * s_lane * (
            lane_pos + cfg.llama.hidden_size * dtype_bytes) + k_cap * 4
    if prefix_cache_bytes:
        # The cache's own LRU budget IS its capacity claim (entries are
        # bucket-grain blocks of the same pos_bytes unit).
        comp["prefix_cache"] = int(prefix_cache_bytes)
    total = sum(comp.values())

    out: Dict[str, Any] = {
        "components": comp,
        "total_bytes": total,
        "row_bytes": row_bytes,
        "kv_pos_bytes": pos_bytes,
        "entry_bytes_per_bucket": grain * pos_bytes,
        "max_len": max_len,
    }
    if mesh_shape:
        div = _mesh_divisors(cfg, mesh_shape, max_batch)
        per: Dict[str, int] = {}
        for name, n in comp.items():
            if name == "weights":
                per[name] = n // div["weights"]
            elif name in ("kv_cache", "lanes"):
                # Batch over (data, fsdp) AND kv-heads over model
                # compose multiplicatively (shard_kv_cache's spec).
                per[name] = n // (div["batch"] * div["kv_heads"])
            elif name == "kv_pool":
                # The arena has no batch axis: blocks replicate over
                # the batch axes (any row may read any block), only the
                # KV-head axis shards (shard_kv_cache's paged branch).
                per[name] = n // div["kv_heads"]
            elif name in ("kv_block_table", "logits", "ids_buf", "draft"):
                per[name] = n // div["batch"]
            else:
                per[name] = n // div["kv_heads"] if name == "prefix_cache" \
                    else n
        out["divisors"] = div
        out["per_device"] = per
        out["per_device_total_bytes"] = sum(per.values())
    return out


def compiled_stats(jitted, *args, **kwargs) -> Dict[str, Any]:
    """Compiled-footprint probe: lower + compile the given jit callable
    at the given (concrete or abstract) args and return XLA's
    ``memory_analysis()`` — temp / argument / output / alias /
    generated-code bytes. AOT lowering never executes, so donated
    resident buffers are safe to pass. With the persistent compile
    cache armed (every serve entry point arms it) the compile is a
    cache load, not a fresh XLA run. Backends without memory analysis
    report ``{"unavailable": ...}`` instead of raising — the probe is
    observability, not a dependency."""
    try:
        ma = jitted.lower(*args, **kwargs).compile().memory_analysis()
        if ma is None:
            return {"unavailable": "backend returned no memory_analysis"}
        out = {
            "temp_bytes": int(ma.temp_size_in_bytes),
            "argument_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "alias_bytes": int(ma.alias_size_in_bytes),
            "generated_code_bytes": int(ma.generated_code_size_in_bytes),
        }
    except Exception as e:
        return {"unavailable": repr(e)}
    obs_metrics.MEM_COMPILED_TEMP.set(out["temp_bytes"])
    obs_metrics.MEM_COMPILED_ARGUMENT.set(out["argument_bytes"])
    obs_metrics.MEM_COMPILED_OUTPUT.set(out["output_bytes"])
    return out


def device_capacity_bytes() -> int:
    """Best-effort device memory limit (``memory_stats()`` of device 0;
    TPU/GPU report ``bytes_limit``). 0 = unknown (CPU) — the headroom
    guard is inert without an explicit ``--mem_capacity_mb``."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
        if stats:
            return int(stats.get("bytes_limit", 0) or 0)
    except Exception:
        pass
    return 0
