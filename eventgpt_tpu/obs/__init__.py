"""Unified telemetry: metrics registry, request/step tracing, profiling.

Three pillars, one package (OBSERVABILITY.md is the operator doc):

  * ``obs.metrics``   — process-global, thread-safe counters / gauges /
    log2-bucket histograms, exposed as Prometheus text (``GET /metrics``
    on the serving front end) and merged into ``/stats``; the trainer
    writes the same registry to a per-step ``telemetry.jsonl``.
  * ``obs.trace``     — ring-buffered ``perf_counter`` span API recording
    request lifecycles and scheduler dispatch/harvest overlap, exported
    as Chrome trace events (``--trace_out``, ``GET /trace``) loadable in
    Perfetto / chrome://tracing.
  * ``obs.profiling`` — ``jax.profiler`` hooks: step/trace annotations
    around train steps and decode segments plus an on-demand capture
    window (``POST /profile``).

Design rules shared by all three (the ``faults.py`` discipline):
stdlib-only at import (``metrics``/``trace`` never import jax, so they
are safe before backend init and in spawned workers), disarmed cost is
one module-global check per call site, and instrumentation is
chain-neutral — it reads clocks and counts events, never touches a jax
array, so decoded chains are byte-identical with telemetry on or off
(tested: ``tests/test_obs.py::test_chain_neutrality``).
"""
