"""Bounded in-process time-series store + SLO burn-rate alerting.

Every signal the stack exposes so far is point-in-time: gauges are
instantaneous, goodput is one windowed deque, and nothing distinguishes
"the queue is *rising*" from "the queue *was* high once". This module
is the sensing layer the ROADMAP item-3 controller consumes: a
jax-free, thread-safe store that samples the metrics registry on a
fixed cadence (``--series_interval_s``) into a ring of the last
``--series_keep`` samples (bounded in-memory series, the Monarch
VLDB '20 design point), derives control signals from the raw samples —
counter -> windowed rate, histogram -> windowed quantiles from bucket
deltas, gauge -> last/min/max over the window, plus an EWMA
arrival-rate estimator over ``note_submit()`` events — and evaluates a
CLOSED rule enum (``ALERT_RULES``) each sample with **hysteresis** and
**multi-window (fast/slow) burn rates** (the Google SRE-workbook
pattern: both windows must breach to fire, so a blip neither fires nor
flaps).

Rules (see OBSERVABILITY.md "Time series + alerts" for the full
threshold table):

  * ``slo_burn``      windowed SLO attainment under the goodput target
                      in BOTH the fast and slow windows (burn rate =
                      (1 - attainment) / (1 - target) >= 1);
  * ``queue_trend``   admission queue depth high AND confirmed as
                      load, not noise: rising vs the slow window
                      (fast mean >= ratio x slow mean), or — when
                      ``queue_arrival_min`` is set — the arrival EWMA
                      above that floor (a deep burst at low offered
                      load drains itself; the same backlog under
                      sustained arrivals is the saturation signature);
  * ``cause_shift``   the dominant SLO-miss cause over the fast window
                      (from ``egpt_serve_slo_miss_cause_total`` deltas)
                      diverged from the slow window's dominant cause;
  * ``breaker_flap``  the circuit breaker changed state >= N times
                      inside the slow window;
  * ``mem_shrink``    ledger headroom below the floor AND shrinking
                      (evaluates only when a capacity is configured).

Transitions export as ``egpt_alert_active{rule}`` /
``egpt_alert_transitions_total{rule}``, append to a bounded
journey-style alert log, and emit trace instants (cat ``alert``).

Armed/disarmed like ``trace.py``/``journey.py``: disarmed (the
default) every probe is one module-global ``is None`` check. Sampling
reads host clocks and the registry's host floats ONLY — never jax
values — so decoded chains are byte-identical armed or disarmed
(tests/test_series.py, re-measured in the workload bench's
interleaved A/B). Exports are **duration-aligned** (ages relative to
the store's own now, like the journey stitcher), so a coordinator can
merge worker series across process-clock domains.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from eventgpt_tpu.obs import metrics as obs_metrics
from eventgpt_tpu.obs import trace as obs_trace

# The CLOSED alert-rule enum. It is the ``rule`` label of
# ``egpt_alert_active`` / ``egpt_alert_transitions_total`` —
# obs/metrics.py METRIC_LABELS mirrors this tuple and the egpt-check
# rule-5 cross-check asserts the literals stay identical. This tuple
# must stay a PURE LITERAL — the lint reads it with ast.literal_eval,
# no imports.
ALERT_RULES = (
    "slo_burn", "queue_trend", "cause_shift", "breaker_flap",
    "mem_shrink",
)


def _window_quantile(bounds: Tuple[float, ...], c0: List[float],
                     c1: List[float], q: float) -> float:
    """Quantile upper bound over the WINDOW [t0, t1]: the histogram
    samples are cumulative per-bucket counts, so the window's
    distribution is their elementwise delta (log2 buckets -> factor-2
    resolution, same semantics as Histogram.quantile)."""
    delta = [max(b - a, 0.0) for a, b in zip(c0, c1)]
    total = sum(delta)
    if total <= 0:
        return 0.0
    need = q * total
    cum = 0.0
    for i, v in enumerate(delta):
        cum += v
        if cum >= need - 1e-9:
            return bounds[i] if i < len(bounds) else bounds[-1]
    return bounds[-1]


def _counter_labeled_sum(values: Dict[tuple, float],
                         key: str, want: str) -> float:
    """Sum a labeled-counter snapshot over entries carrying
    ``(key, want)`` in their label tuple."""
    return sum(v for k, v in values.items() if (key, want) in k)


def _cause_totals(values: Dict[tuple, float]) -> Dict[str, float]:
    """Per-cause cumulative miss counts, summed across SLO classes."""
    out: Dict[str, float] = {}
    for k, v in values.items():
        for lk, lv in k:
            if lk == "cause":
                out[lv] = out.get(lv, 0.0) + v
    return out


class SeriesStore:
    """Bounded, thread-safe ring of registry samples + the alert
    evaluator. One lock guards everything (the sampler thread, HTTP
    handler threads and the ``note_submit`` probe on the scheduler
    path all touch it); a sample is a few dozen host floats, so the
    armed cost per tick is comparable to one ``/stats`` render.
    jax-free by construction.
    """

    # Lock-discipline contract (egpt-check rule ``lock``): the ring,
    # the submit counter, the alert state machine and the alert log
    # only mutate/read under the store's own lock.
    _GUARDED_BY = {
        "_ring": "_lock",
        "_submits": "_lock",
        "_n_samples": "_lock",
        "_alerts": "_lock",
        "_alert_log": "_lock",
        "_sampler_errors": "_lock",
    }

    def __init__(self, interval_s: float = 1.0, keep: int = 512, *,
                 slo_target: float = 0.9,
                 fast_window_s: Optional[float] = None,
                 slow_window_s: Optional[float] = None,
                 slo_min_finished: int = 1,
                 queue_min: float = 8.0,
                 queue_ratio: float = 1.5,
                 queue_arrival_min: float = 0.0,
                 cause_min_misses: int = 4,
                 flap_min: int = 3,
                 mem_capacity_bytes: Optional[int] = None,
                 mem_headroom_frac: float = 0.1,
                 arm_samples: int = 2,
                 clear_samples: int = 3,
                 ewma_tau_s: Optional[float] = None,
                 log_keep: int = 256,
                 clock=time.perf_counter):
        self.interval_s = max(float(interval_s), 1e-3)
        self.keep = max(int(keep), 2)
        # Multi-window burn rates: the fast window reacts, the slow
        # window confirms (SRE workbook). Defaults scale with the
        # cadence so one flag tunes both.
        self.fast_window_s = (float(fast_window_s) if fast_window_s
                              else 5.0 * self.interval_s)
        self.slow_window_s = (float(slow_window_s) if slow_window_s
                              else 20.0 * self.interval_s)
        self.slo_target = min(max(float(slo_target), 0.0), 1.0 - 1e-9)
        # Traffic floor for the burn-rate rule: a single missed request
        # among a handful of finishes reads as a 50% burn in a short
        # window — real burn-rate alerts gate on request volume so
        # one-off noise cannot page (SRE workbook, "low-traffic
        # services").
        self.slo_min_finished = max(int(slo_min_finished), 1)
        self.queue_min = float(queue_min)
        self.queue_ratio = float(queue_ratio)
        # > 0 swaps queue_trend's confirmation signal from "rising vs
        # the slow window" to "arrival EWMA above this floor". The
        # trend test cannot confirm sustained saturation early in a
        # ring (slow ~= fast when history is short) and a lone deep
        # burst passes it trivially (slow ~= 0); arrival pressure
        # orders those two correctly.
        self.queue_arrival_min = float(queue_arrival_min)
        self.cause_min_misses = max(int(cause_min_misses), 1)
        self.flap_min = max(int(flap_min), 1)
        self.mem_capacity_bytes = (int(mem_capacity_bytes)
                                   if mem_capacity_bytes else None)
        self.mem_headroom_frac = float(mem_headroom_frac)
        # Hysteresis: N consecutive breaching samples to fire, M
        # consecutive clear samples to stand down — boundary noise
        # between the fire and clear thresholds moves neither counter
        # far enough to flap.
        self.arm_samples = max(int(arm_samples), 1)
        self.clear_samples = max(int(clear_samples), 1)
        self.ewma_tau_s = (float(ewma_tau_s) if ewma_tau_s
                           else self.fast_window_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._ring: "deque[dict]" = deque(maxlen=self.keep)
        self._submits = 0
        self._n_samples = 0
        self._sampler_errors = 0
        self._alerts: Dict[str, dict] = {
            rule: {"active": False, "breach": 0, "ok": 0,
                   "transitions": 0, "fired": 0, "since": None,
                   "last_change": None, "value": 0.0}
            for rule in ALERT_RULES
        }
        self._alert_log: "deque[dict]" = deque(maxlen=max(int(log_keep), 8))
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- recording --------------------------------------------------------

    def note_submit(self, n: int = 1) -> None:
        """One arrival observed (the EWMA estimator's input). Called
        from the scheduler submit path — a lock round-trip plus an int
        add, comparable to a metric observation."""
        with self._lock:
            self._submits += n

    def _read_registry(self) -> dict:
        """One registry read (each metric takes its OWN lock; the
        store's lock is not held here). Host floats only."""
        m = obs_metrics
        slo = m.SERVE_SLO_REQUESTS.labeled()
        return {
            "queue_depth": max(m.SERVE_QUEUE_DEPTH.value(),
                               m.FLEET_QUEUE_DEPTH.value()),
            "active_rows": m.SERVE_ACTIVE_ROWS.value(),
            "breaker_open": m.SERVE_BREAKER_OPEN.value(),
            "goodput_ratio": m.SERVE_SLO_GOODPUT.value(),
            "slo_finished": sum(slo.values()),
            "slo_met": _counter_labeled_sum(slo, "met", "true"),
            "requests_total": m.SERVE_REQUESTS.total(),
            "tokens_total": m.SERVE_TOKENS.total(),
            "mem_total_bytes": m.MEM_TOTAL.value(),
            "miss_causes": _cause_totals(m.SERVE_SLO_MISS_CAUSE.labeled()),
            "ttft_cum": m.SERVE_TTFT.agg_counts(),
            "latency_cum": m.SERVE_LATENCY.agg_counts(),
        }

    def sample_once(self, now: Optional[float] = None) -> dict:
        """Take one sample and evaluate every alert rule against it.
        ``now`` overrides the clock (the determinism tests drive a
        synthetic timeline through here; the sampler thread passes
        nothing). Returns the recorded sample."""
        now = self._clock() if now is None else float(now)
        raw = self._read_registry()
        with self._lock:
            prev = self._ring[-1] if self._ring else None
            ewma = 0.0
            if prev is not None and now > prev["t"]:
                dt = now - prev["t"]
                inst = (self._submits - prev["submits_total"]) / dt
                alpha = 1.0 - math.exp(-dt / self.ewma_tau_s)
                ewma = alpha * inst + (1.0 - alpha) * prev["arrival_rate_ewma"]
            sample = dict(raw)
            sample["t"] = now
            sample["submits_total"] = self._submits
            sample["arrival_rate_ewma"] = ewma
            self._ring.append(sample)
            self._n_samples += 1
            events = self._evaluate_locked(now)
        # Export OUTSIDE the store lock: the metric objects take their
        # own locks, and the tracer likewise.
        for rule, state, value in events:
            firing = state == "firing"
            obs_metrics.ALERT_ACTIVE.set(1.0 if firing else 0.0, rule=rule)
            obs_metrics.ALERT_TRANSITIONS.inc(rule=rule)
            obs_trace.instant("alert_firing" if firing else "alert_cleared",
                             cat="alert", rule=rule, value=value)
        return sample

    # -- derivations ------------------------------------------------------

    def _window_locked(self, now: float, span_s: float) -> List[dict]:
        # Scan from the newest end: cost is O(window), not O(ring) —
        # the evaluator runs this every sample against short windows
        # while the ring holds hours.
        lo = now - span_s - 1e-9
        out: List[dict] = []
        for s in reversed(self._ring):
            if s["t"] < lo:
                break
            out.append(s)
        out.reverse()
        return out

    @staticmethod
    def _attainment(win: List[dict]) -> Optional[float]:
        """Windowed SLO attainment from the cumulative met/finished
        deltas; None when the window saw no SLO-classed finish."""
        if len(win) < 2:
            return None
        fin = win[-1]["slo_finished"] - win[0]["slo_finished"]
        met = win[-1]["slo_met"] - win[0]["slo_met"]
        if fin <= 0:
            return None
        return max(min(met / fin, 1.0), 0.0)

    @staticmethod
    def _mean(win: List[dict], key: str) -> Optional[float]:
        if not win:
            return None
        return sum(s[key] for s in win) / len(win)

    @staticmethod
    def _cause_deltas(win: List[dict]) -> Dict[str, float]:
        if len(win) < 2:
            return {}
        first, last = win[0]["miss_causes"], win[-1]["miss_causes"]
        return {c: last[c] - first.get(c, 0.0)
                for c in last if last[c] - first.get(c, 0.0) > 0}

    @staticmethod
    def _dominant(deltas: Dict[str, float]) -> Optional[str]:
        best, best_v = None, 0.0
        for c, v in sorted(deltas.items()):
            if v > best_v:
                best, best_v = c, v
        return best

    @staticmethod
    def _flips(win: List[dict], key: str) -> int:
        return sum(1 for a, b in zip(win, win[1:]) if a[key] != b[key])

    def _evaluate_locked(self, now: float) -> List[Tuple[str, str, float]]:
        """Evaluate every rule against the current ring; advance the
        hysteresis state machines; return the transitions to export."""
        fast = self._window_locked(now, self.fast_window_s)
        slow = self._window_locked(now, self.slow_window_s)
        last = self._ring[-1]
        verdicts: Dict[str, Tuple[bool, bool, float, str]] = {}

        # slo_burn: burn rate = (1 - attainment) / (1 - target); both
        # windows must burn >= 1 to fire (multi-window), attainment
        # back above target + half the margin in the fast window to
        # clear (hysteresis gap).
        att_f, att_s = self._attainment(fast), self._attainment(slow)
        fin_f = (fast[-1]["slo_finished"] - fast[0]["slo_finished"]
                 if len(fast) >= 2 else 0)
        clear_target = self.slo_target + 0.5 * (1.0 - self.slo_target)
        breach = (att_f is not None and att_s is not None
                  and fin_f >= self.slo_min_finished
                  and att_f < self.slo_target and att_s < self.slo_target)
        cleared = att_f is None or att_f >= clear_target
        verdicts["slo_burn"] = (breach, cleared,
                                att_f if att_f is not None else 1.0, "")

        # queue_trend: fast-window mean depth above the floor AND
        # confirmed as load rather than noise — rising vs the slow
        # window, or (when queue_arrival_min is armed) the arrival
        # EWMA above its floor. Clears when the depth halves or the
        # trend inverts.
        qf = self._mean(fast, "queue_depth") or 0.0
        qs = self._mean(slow, "queue_depth") or 0.0
        if self.queue_arrival_min > 0:
            confirmed = last["arrival_rate_ewma"] >= self.queue_arrival_min
        else:
            confirmed = qf >= self.queue_ratio * qs if qs > 1e-9 else qf > 0
        breach = qf >= self.queue_min and confirmed
        cleared = qf < 0.5 * self.queue_min or (qs > 1e-9 and qf < qs)
        verdicts["queue_trend"] = (breach, cleared, qf, "")

        # cause_shift: the fast window's dominant miss cause diverged
        # from the slow window's, with enough misses to mean anything.
        df, ds = self._cause_deltas(fast), self._cause_deltas(slow)
        dom_f, dom_s = self._dominant(df), self._dominant(ds)
        n_f = sum(df.values())
        breach = (dom_f is not None and dom_s is not None
                  and dom_f != dom_s and n_f >= self.cause_min_misses)
        cleared = dom_f is None or dom_f == dom_s
        detail = (f"{dom_s}->{dom_f}"
                  if breach and dom_s is not None else "")
        verdicts["cause_shift"] = (breach, cleared, n_f, detail)

        # breaker_flap: state changes inside the slow window.
        flips = self._flips(slow, "breaker_open")
        verdicts["breaker_flap"] = (flips >= self.flap_min, flips == 0,
                                    float(flips), "")

        # mem_shrink: headroom under the floor AND the resident total
        # still growing; needs a configured capacity to judge against.
        if self.mem_capacity_bytes:
            cap = float(self.mem_capacity_bytes)
            headroom = 1.0 - last["mem_total_bytes"] / cap
            mf = self._mean(fast, "mem_total_bytes") or 0.0
            ms = self._mean(slow, "mem_total_bytes") or 0.0
            breach = headroom < self.mem_headroom_frac and mf >= ms
            cleared = headroom >= 1.5 * self.mem_headroom_frac
            verdicts["mem_shrink"] = (breach, cleared, headroom, "")
        else:
            verdicts["mem_shrink"] = (False, True, 1.0, "")

        events: List[Tuple[str, str, float]] = []
        for rule in ALERT_RULES:
            breach, cleared, value, detail = verdicts[rule]
            st = self._alerts[rule]
            st["value"] = value
            if st["active"]:
                st["ok"] = st["ok"] + 1 if cleared else 0
                if st["ok"] >= self.clear_samples:
                    st.update(active=False, ok=0, breach=0,
                              last_change=now)
                    st["transitions"] += 1
                    self._log_locked(now, rule, "cleared", value, detail)
                    events.append((rule, "cleared", value))
            else:
                st["breach"] = st["breach"] + 1 if breach else 0
                if st["breach"] >= self.arm_samples:
                    st.update(active=True, breach=0, ok=0, since=now,
                              last_change=now)
                    st["transitions"] += 1
                    st["fired"] += 1
                    self._log_locked(now, rule, "firing", value, detail)
                    events.append((rule, "firing", value))
        return events

    def _log_locked(self, now: float, rule: str, state: str,
                    value: float, detail: str) -> None:
        ev = {"t": now, "rule": rule, "state": state,
              "value": round(float(value), 6)}
        if detail:
            ev["detail"] = detail
        self._alert_log.append(ev)

    # -- export -----------------------------------------------------------

    _POINT_KEYS = ("queue_depth", "active_rows", "breaker_open",
                   "goodput_ratio", "arrival_rate_ewma",
                   "mem_total_bytes", "requests_total", "tokens_total",
                   "submits_total")

    def snapshot(self, window_s: Optional[float] = None,
                 n: Optional[int] = None,
                 now: Optional[float] = None) -> Dict[str, Any]:
        """The ``GET /series`` payload: the newest ``n`` points with
        ages relative to NOW (duration-aligned — absolute perf_counter
        floats mean nothing across processes) plus windowed
        derivations over ``window_s`` (default: the whole ring)."""
        now = self._clock() if now is None else float(now)
        n = 128 if n is None else max(int(n), 1)
        with self._lock:
            pts = list(self._ring)[-n:]
            span = (window_s if window_s is not None
                    else (now - self._ring[0]["t"] if self._ring else 0.0))
            win = self._window_locked(now, max(float(span), 0.0))
            samples, dropped = self._n_samples, \
                max(self._n_samples - self.keep, 0)
        points = [
            {"age_s": round(now - s["t"], 6),
             **{k: round(float(s[k]), 6) for k in self._POINT_KEYS}}
            for s in pts
        ]
        derived: Dict[str, Any] = {"window_s": round(float(span), 6)}
        if len(win) >= 2:
            dt = win[-1]["t"] - win[0]["t"]
            if dt > 0:
                derived["request_rate_per_s"] = round(
                    (win[-1]["requests_total"] - win[0]["requests_total"])
                    / dt, 6)
                derived["token_rate_per_s"] = round(
                    (win[-1]["tokens_total"] - win[0]["tokens_total"])
                    / dt, 6)
                derived["submit_rate_per_s"] = round(
                    (win[-1]["submits_total"] - win[0]["submits_total"])
                    / dt, 6)
            for key in ("queue_depth", "goodput_ratio", "mem_total_bytes"):
                vals = [s[key] for s in win]
                derived[f"{key}_last"] = round(vals[-1], 6)
                derived[f"{key}_min"] = round(min(vals), 6)
                derived[f"{key}_max"] = round(max(vals), 6)
            derived["breaker_flips"] = self._flips(win, "breaker_open")
            att = self._attainment(win)
            if att is not None:
                derived["attainment_windowed"] = round(att, 6)
            for name, metric in (("ttft", obs_metrics.SERVE_TTFT),
                                 ("latency", obs_metrics.SERVE_LATENCY)):
                c0, c1 = win[0][f"{name}_cum"], win[-1][f"{name}_cum"]
                for q, tag in ((0.5, "p50"), (0.99, "p99")):
                    derived[f"{name}_{tag}_s"] = _window_quantile(
                        metric.bounds, c0, c1, q)
            deltas = self._cause_deltas(win)
            derived["miss_cause_deltas"] = {
                c: round(v, 6) for c, v in sorted(deltas.items())}
            dom = self._dominant(deltas)
            if dom is not None:
                derived["dominant_miss_cause"] = dom
        if win:
            derived["arrival_rate_ewma"] = round(
                win[-1]["arrival_rate_ewma"], 6)
        return {
            "interval_s": self.interval_s,
            "keep": self.keep,
            "samples": samples,
            "dropped": dropped,
            "points": points,
            "derived": derived,
        }

    def alerts_snapshot(self, now: Optional[float] = None,
                        n: int = 64) -> Dict[str, Any]:
        """The ``GET /alerts`` payload: per-rule state + the bounded
        transition log, ages duration-aligned like the series."""
        now = self._clock() if now is None else float(now)
        with self._lock:
            rules = {}
            for rule in ALERT_RULES:
                st = self._alerts[rule]
                rules[rule] = {
                    "active": st["active"],
                    "transitions": st["transitions"],
                    "fired": st["fired"],
                    "value": round(float(st["value"]), 6),
                }
                if st["active"] and st["since"] is not None:
                    rules[rule]["since_age_s"] = round(now - st["since"], 6)
                if st["last_change"] is not None:
                    rules[rule]["last_change_age_s"] = round(
                        now - st["last_change"], 6)
            log = [
                {**{k: v for k, v in ev.items() if k != "t"},
                 "age_s": round(now - ev["t"], 6)}
                for ev in list(self._alert_log)[-max(int(n), 1):]
            ]
        return {
            "rules": rules,
            "active": [r for r in ALERT_RULES if rules[r]["active"]],
            "log": log,
        }

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "interval_s": self.interval_s,
                "keep": self.keep,
                "samples": self._n_samples,
                "submits": self._submits,
                "sampler_errors": self._sampler_errors,
            }

    # -- sampler thread ---------------------------------------------------

    def start(self) -> None:
        """Start the cadence sampler (idempotent). Daemon thread: one
        registry read per interval, nothing jax-adjacent."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="series-sampler", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:
                # The sampler must never die silently mid-serve; the
                # error count is exported via stats() instead.
                with self._lock:
                    self._sampler_errors += 1

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=2.0)
        self._thread = None


# -- module-global arming (the trace.py discipline) ------------------------

_store: Optional[SeriesStore] = None


def configure(interval_s: float = 1.0, keep: int = 512,
              autostart: bool = True, **kwargs) -> Optional[SeriesStore]:
    """Arm the time-series store sampling every ``interval_s`` seconds
    into a ring of ``keep`` samples; ``interval_s <= 0`` or
    ``keep <= 0`` disarms. ``autostart`` launches the cadence thread
    (tests drive ``sample_once`` explicitly instead)."""
    global _store
    if _store is not None:
        _store.stop()
    if interval_s <= 0 or keep <= 0:
        _store = None
        return None
    _store = SeriesStore(interval_s=interval_s, keep=keep, **kwargs)
    # All rules visibly healthy from the start (the gauge renders only
    # observed label sets).
    for rule in ALERT_RULES:
        obs_metrics.ALERT_ACTIVE.set(0.0, rule=rule)
    if autostart:
        _store.start()
    return _store


def disable() -> None:
    global _store
    if _store is not None:
        _store.stop()
    _store = None


def active() -> Optional[SeriesStore]:
    return _store


def enabled() -> bool:
    return _store is not None


# -- armed-checked probes (one module-global load + None check when
#    disarmed; no clock read, no allocation) -------------------------------

def note_submit(n: int = 1) -> None:
    s = _store
    if s is not None:
        s.note_submit(n)


def sample_now() -> Optional[dict]:
    s = _store
    return None if s is None else s.sample_once()


def snapshot(window_s: Optional[float] = None,
             n: Optional[int] = None) -> Dict[str, Any]:
    s = _store
    return {"enabled": False} if s is None else \
        {"enabled": True, **s.snapshot(window_s=window_s, n=n)}


def alerts() -> Dict[str, Any]:
    s = _store
    return {"enabled": False} if s is None else \
        {"enabled": True, **s.alerts_snapshot()}


def alert_stats(n: int = 8) -> Dict[str, Any]:
    """The compact ``/stats`` ``"alerts"`` block: active rules + the
    last few transitions (the full log rides ``GET /alerts``)."""
    s = _store
    if s is None:
        return {"enabled": False, "active": []}
    snap = s.alerts_snapshot(n=n)
    return {
        "enabled": True,
        "active": snap["active"],
        "transitions": {r: st["transitions"]
                        for r, st in snap["rules"].items()},
        "last": snap["log"],
    }
