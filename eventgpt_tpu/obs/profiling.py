"""``jax.profiler`` hooks: annotations + an on-demand capture window.

``utils/profiling.py`` keeps the low-level pieces (``profile_trace``
context manager, fenced ``timed``); this module is the ARMED-GATED layer
the runtime wires through, so un-profiled serving/training pays one
module-global check per step:

  * ``step_annotation(n)`` / ``annotation(name)`` — thin wrappers over
    ``jax.profiler.StepTraceAnnotation`` / ``TraceAnnotation`` that
    no-op unless profiling is armed. The trainer wraps each micro-step,
    the serving scheduler wraps each decode/spec segment dispatch — so
    a capture shows host steps aligned against device activity.
  * ``capture(seconds, logdir)`` — the ``POST /profile {"seconds": N}``
    window: start a ``jax.profiler`` trace, arm annotations for the
    window, sleep, stop. One capture at a time (``CaptureBusyError``).
  * ``start_trace``/``stop_trace`` — manual bracket for the trainer's
    ``--profile_dir`` step window.

Arming is process-wide (``configure(dir)``) because the profiler itself
is process-wide; annotations are cheap-but-not-free (~us each), so they
stay off unless a profile destination exists or a capture is running.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional


class CaptureBusyError(RuntimeError):
    """A profile capture is already running (the profiler is process-
    global; the HTTP layer maps this to 409)."""


_lock = threading.Lock()
_profile_dir: Optional[str] = None   # configured destination (arms annotations)
_capturing = False                   # a start_trace window is open
_armed_depth = 0                     # capture() arms annotations temporarily


def configure(profile_dir: Optional[str]) -> None:
    """Set the default capture destination; a non-empty dir arms the
    step/trace annotations permanently (the --profile_dir flags)."""
    global _profile_dir
    _profile_dir = profile_dir or None


def armed() -> bool:
    return _profile_dir is not None or _armed_depth > 0


class _Null:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _Null()


def step_annotation(step_num: int, name: str = "step"):
    """``jax.profiler.StepTraceAnnotation`` when armed, else a no-op —
    gives XProf/TensorBoard its per-step grouping."""
    if not armed():
        return _NULL
    import jax

    return jax.profiler.StepTraceAnnotation(name, step_num=step_num)


def annotation(name: str):
    """``jax.profiler.TraceAnnotation`` when armed, else a no-op — names
    a host region (e.g. one decode-segment dispatch) on the trace."""
    if not armed():
        return _NULL
    import jax

    return jax.profiler.TraceAnnotation(name)


def start_trace(logdir: Optional[str] = None) -> str:
    """Open a profiler trace (one at a time, process-wide). Returns the
    logdir actually used."""
    global _capturing
    import jax

    with _lock:
        if _capturing:
            raise CaptureBusyError("a profile capture is already running")
        d = logdir or _profile_dir
        if not d:
            import tempfile

            d = tempfile.mkdtemp(prefix="egpt_profile_")
        os.makedirs(d, exist_ok=True)
        jax.profiler.start_trace(d)
        _capturing = True
        return d


def stop_trace() -> None:
    global _capturing
    import jax

    with _lock:
        if not _capturing:
            return
        jax.profiler.stop_trace()
        _capturing = False


def capture(seconds: float, logdir: Optional[str] = None) -> str:
    """Capture a profile for ``seconds`` (blocking the calling thread —
    the scheduler keeps serving; that is the traffic being profiled).
    Temporarily arms the step/segment annotations so the window has
    named host regions even when --profile_dir was never set. Returns
    the trace directory."""
    global _armed_depth
    d = start_trace(logdir)
    _armed_depth += 1
    try:
        time.sleep(max(float(seconds), 0.0))
    finally:
        _armed_depth -= 1
        stop_trace()
    return d
