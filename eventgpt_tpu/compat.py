"""Version shims for the narrow band of jax/pallas APIs that moved.

The repo targets current jax (``jax.shard_map``, ``pltpu.CompilerParams``)
but must keep running on the 0.4.x builds some containers pin — where the
same functionality lives under the old names. Every shim lives HERE, once
(the ``pltpu.TPUCompilerParams`` rename shim started in
``ops/decode_attention.py`` and ISSUE 5 hoists it): call sites import the
compat symbol and never version-sniff themselves.

Shimmed surfaces:

  * ``shard_map`` — ``jax.shard_map`` (new) vs
    ``jax.experimental.shard_map.shard_map`` (0.4.x). The replica-check
    kwarg also renamed (``check_vma`` vs ``check_rep``); callers pass the
    NEW name and the shim translates. This is what unblocks the
    ring/ulysses/flash-shard-map paths on jax 0.4.37 (16 pre-existing
    failures: the modules called ``jax.shard_map`` unconditionally).
  * ``pallas_compiler_params`` — ``pltpu.CompilerParams`` (new) vs
    ``pltpu.TPUCompilerParams`` (0.4.x). Same fields either way.
"""

from __future__ import annotations

from typing import Any, Optional

import jax


def shard_map(f, mesh, in_specs, out_specs,
              check_vma: Optional[bool] = None):
    """``jax.shard_map`` with a fallback to the 0.4.x experimental home.

    ``check_vma=None`` leaves the library default in place; an explicit
    bool maps to ``check_vma`` on new jax and ``check_rep`` on old jax
    (the same knob under its previous name — both skip the
    varying-mesh-axes/replication check Pallas kernels cannot satisfy).
    """
    if hasattr(jax, "shard_map"):
        kw = {} if check_vma is None else {"check_vma": check_vma}
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    # The 0.4.x replication checker miscounts scan carries (jax#...: the
    # library's own error message says "as a temporary workaround pass
    # check_rep=False"), which the ring body trips — so the fallback
    # defaults the check OFF; the in/out specs still pin every layout.
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=bool(check_vma) if check_vma is not None else False,
    )


def axis_size(axis_name) -> Any:
    """``lax.axis_size`` (new) or the ``psum(1, axis)`` idiom (0.4.x) —
    the static size of a mapped mesh axis from inside a shard_map body."""
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def pvary(x, axes) -> Any:
    """Mark a value as varying over mesh axes inside a shard_map body.

    ``lax.pcast(..., to="varying")`` on current jax, ``lax.pvary`` on the
    releases that shipped it, and a no-op on 0.4.x — whose shard_map has
    no varying-mesh-axes typing to satisfy in the first place."""
    from jax import lax

    if hasattr(lax, "pcast"):
        return lax.pcast(x, axes, to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, axes)
    return x


def pallas_compiler_params(**kwargs: Any):
    """Mosaic compile options under whichever name this jax ships."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kwargs)
