"""Shared scaffolding for the two sequence-parallel attention modes
(ring / ulysses): the partition specs both shard_maps use. Kept in one
place so a mesh-axis change cannot desynchronize them.
"""

from jax.sharding import PartitionSpec as P

# q/k/v (B, S, H, hd): batch over (data, fsdp), sequence over context,
# heads over model.
SP_QKV_SPEC = P(("data", "fsdp"), "context", "model", None)
# validity masks (B, S).
SP_VALID_SPEC = P(("data", "fsdp"), "context")
