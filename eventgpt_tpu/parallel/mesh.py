"""Logical device mesh construction.

Axes (SURVEY.md §2.4 "TPU-native plan"):

  * ``data``    — pure data parallelism; gradients psum over this axis.
  * ``fsdp``    — ZeRO-style parameter sharding; params all-gathered at use.
  * ``context`` — sequence/context parallelism (ring attention); 1 for the
                  parity workloads (reference caps context at 2048,
                  ``model/EventChatModel.py:378``) but first-class so long
                  context needs no re-plumbing.
  * ``model``   — tensor parallelism over attention heads / MLP columns.

Mesh axis order is chosen so that ``model`` (the most communication-hungry
axis) maps to the innermost / fastest ICI ring on real TPU topologies via
``mesh_utils.create_device_mesh``.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from eventgpt_tpu.config import MeshConfig

AXES = ("data", "fsdp", "context", "model")


def make_mesh(cfg: MeshConfig, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a ``Mesh`` with logical axes (data, fsdp, context, model).

    ``devices`` defaults to all visible devices; the product of the axis
    sizes must equal the device count.
    """
    shape = (cfg.data, cfg.fsdp, cfg.context, cfg.model)
    if devices is None:
        n = jax.device_count()
        if int(np.prod(shape)) != n:
            raise ValueError(f"mesh {dict(zip(AXES, shape))} needs {np.prod(shape)} "
                             f"devices, have {n}")
        try:
            dev_array = mesh_utils.create_device_mesh(shape)
        except Exception:
            dev_array = np.asarray(jax.devices()).reshape(shape)
    else:
        devices = list(devices)
        if int(np.prod(shape)) != len(devices):
            raise ValueError(f"mesh {dict(zip(AXES, shape))} needs {np.prod(shape)} "
                             f"devices, got {len(devices)}")
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXES)


def single_device_mesh() -> Mesh:
    """1x1x1x1 mesh on the first device — lets every pjit path run unsharded."""
    return make_mesh(MeshConfig(), devices=jax.devices()[:1])


def best_mesh_config(
    n_devices: int,
    *,
    fsdp_pref: int = 8,
    model: int = 1,
    context: int = 1,
) -> MeshConfig:
    """Heuristic mesh for ``n_devices``: fill ``fsdp`` up to ``fsdp_pref``,
    rest goes to ``data``. Matches the BASELINE.json scale points (8 -> 256
    chips: fsdp within a host/slice ring, data across)."""
    inner = model * context
    if n_devices % inner:
        raise ValueError(f"{n_devices} devices not divisible by model*context={inner}")
    rest = n_devices // inner
    fsdp = 1
    for cand in range(min(fsdp_pref, rest), 0, -1):
        if rest % cand == 0:
            fsdp = cand
            break
    return MeshConfig(data=rest // fsdp, fsdp=fsdp, model=model, context=context)
