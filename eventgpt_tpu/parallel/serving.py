"""Mesh-sharded serving: parameter + KV-cache placement for ``generate``.

The reference serves its frozen LLM on one GPU — torch module + HF generate
(``inference.py:28-66``, ``model/EventChatModel.py:237-276``). The BASELINE
north star is the same surface over a pod: HF weights loaded into a
pjit-sharded FSDP/TP layout with the KV cache resident in HBM. This module
is the serving half of ``parallel/sharding.py``: it places an EventChat
param tree (plain, int8, int4 or LoRA-composite leaves) and a KV cache onto
a ``Mesh`` so the existing jit'd prefill/decode units compile to one SPMD
program — computation follows data, XLA inserts the collectives (fsdp
all-gathers, model-axis psums).

Layout decisions specific to serving:

  * Params reuse the training specs (``eventchat_param_specs``): matmul
    contraction dims over ``fsdp`` (ZeRO-style, gathered at use), head /
    column dims over ``model`` (megatron TP, one psum per layer).
  * Quantized leaves shard their int payload exactly like the bf16 weight
    they replace; the per-channel scales replicate over the contraction
    axis (they are 1/256th of the payload — sharding them buys nothing and
    the size-1 / group dims do not always divide the axis).
  * The KV cache shards batch over whatever prefix of ``(data, fsdp)``
    divides the run's batch (pure-TP fallback for batch 1) and KV heads
    over ``model`` — decode reads the cache in place, no resharding per
    step.
  * ``context`` must be 1: sequence parallelism is a prefill-side
    optimization (ring/ulysses in ``parallel/ring.py``/``ulysses.py``)
    whose value is long-context *training*; serving prompts sit far below
    the 2048 context cap and the decode hot loop attends to the whole
    cache from a single query token.
  * The stall-free-admission lane buffers (ISSUE 5: the resident
    (K_cap, S_lane) lane KV cache and (K_cap, S_lane, D) prompt-embed
    buffer that mixed segments advance) place through the SAME helpers —
    ``shard_kv_cache`` at batch K_cap and ``shard_batch_array`` — and
    the mixed-segment jits (``serve._get_sharded_mixed_*``) pin their
    lane outputs to that placement, so the donated lane buffers keep
    aliasing across boundaries exactly like the resident decode cache.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from eventgpt_tpu.ops import quant as quant_mod
from eventgpt_tpu.parallel.sharding import eventchat_param_specs


def _scale_spec(spec: P) -> P:
    """Spec for a quantization-scale leaf: same rank as the weight spec with
    the contraction (second-to-last) axis replicated — int8 scales have a
    size-1 dim there, int4 group counts need not divide ``fsdp``."""
    parts = list(spec) + [None] * 0
    if len(parts) >= 2:
        parts[-2] = None
    return P(*parts)


def _put(x, mesh: Mesh, spec: P, dtype=None):
    arr = jnp.asarray(x) if dtype is None else jnp.asarray(x, dtype)
    return jax.device_put(arr, NamedSharding(mesh, spec))


def _shard_tree(tree: Any, spec: Any, mesh: Mesh, dtype) -> Any:
    """Recursive quant-aware placement. ``spec`` mirrors ``tree`` except at
    composite leaves ({"q","s"} / {"q4","s"} / {"w","a","b"}), where one
    PartitionSpec covers the whole composite."""
    if quant_mod.is_quantized(tree):
        return {"q": _put(tree["q"], mesh, spec),
                "s": _put(tree["s"], mesh, _scale_spec(spec), jnp.float32)}
    if quant_mod.is_quantized4(tree):
        return {"q4": _put(tree["q4"], mesh, spec),
                "s": _put(tree["s"], mesh, _scale_spec(spec), jnp.float32)}
    if quant_mod.is_lora(tree):
        rep = P(*([None] * (len(spec) if spec else 0)))
        return {"w": _shard_tree(tree["w"], spec, mesh, dtype),
                "a": _put(tree["a"], mesh, rep, dtype),
                "b": _put(tree["b"], mesh, rep, dtype)}
    if isinstance(tree, dict):
        return {k: _shard_tree(v, spec[k], mesh, dtype) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return type(tree)(
            _shard_tree(v, s, mesh, dtype) for v, s in zip(tree, spec)
        )
    return _put(tree, mesh, spec, dtype)


def shard_params_for_serving(
    params: Any,
    cfg,
    mesh: Mesh,
    dtype=None,
) -> Any:
    """Place an EventChat param tree on ``mesh`` under the serving layout.

    Accepts host (numpy) or device trees — host trees go straight to their
    sharded placement, so a 7B load never materializes an unsharded copy in
    HBM. ``dtype`` casts float leaves (quantized payloads/scales keep their
    storage types).
    """
    _require_serving_mesh(mesh)
    specs = eventchat_param_specs(
        cfg.projector.use_feature_adaptor,
        cfg.projector.mlp_depth,
        use_qformer="qformer" in params,
    )
    from eventgpt_tpu.parallel.sharding import vocab_safe_llama_specs

    emb = params["llama"]["embed_tokens"]
    vocab = int((emb["q"] if isinstance(emb, dict) else emb).shape[0])
    vocab_safe_llama_specs(specs["llama"], vocab, mesh)
    _adapt_fused_llama_specs(specs["llama"], params["llama"])
    return {k: _shard_tree(v, specs[k], mesh, dtype) for k, v in params.items()}


def _adapt_fused_llama_specs(llama_specs: Any, llama_params: Any) -> None:
    """``fuse_llama_params`` merges q|k|v and gate|up leaves; the fused
    column dim shards over ``model`` exactly like the unfused columns did
    (GSPMD reshards the post-matmul slice boundaries as needed)."""
    attn = llama_params["layers"]["attn"]
    if "qkv" in attn:
        llama_specs["layers"]["attn"] = {
            "qkv": P(None, "fsdp", "model"),
            "o": P(None, "model", "fsdp"),
        }
    if "gate_up" in llama_params["layers"]["mlp"]:
        llama_specs["layers"]["mlp"] = {
            "gate_up": P(None, "fsdp", "model"),
            "down": P(None, "model", "fsdp"),
        }


def _require_serving_mesh(mesh: Mesh) -> None:
    if "context" in mesh.shape and mesh.shape["context"] > 1:
        raise ValueError(
            "serving meshes must have context=1 (sequence parallelism is a "
            "long-context training optimization; decode attends to the full "
            "cache from one query token)"
        )


def serving_divisors(num_kv_heads: int, mesh_shape, batch: int) -> dict:
    """Per-device byte divisors of the serving layout, as pure
    arithmetic on a ``{axis: size}`` mapping — THE sharding rules of
    this module, exported for the memory ledger's capacity model
    (``obs.memory.estimate``), which must fit-check a pod config
    without building a Mesh or materializing a weight:

      * ``batch``: the largest prefix of ``(data, fsdp)`` whose size
        product divides the batch (``serving_batch_axes``);
      * ``kv_heads``: ``model`` when it divides the KV head count
        (``shard_kv_cache`` / ``prefix_block_sharding``);
      * ``weights``: ``fsdp × model`` (``eventchat_param_specs``:
        contraction dims over fsdp, head/column dims over model —
        scales/norms replicate, a rounding the estimate absorbs).
    """
    batch_div = 1
    for ax in ("data", "fsdp"):
        n = int(mesh_shape.get(ax, 1))
        if n > 1 and batch % (batch_div * n) == 0:
            batch_div *= n
    model_n = int(mesh_shape.get("model", 1))
    head_div = model_n if model_n > 1 and num_kv_heads % model_n == 0 else 1
    return {"batch": batch_div, "kv_heads": head_div,
            "weights": int(mesh_shape.get("fsdp", 1)) * model_n}


def serving_batch_axes(mesh: Mesh, batch: int) -> Tuple[str, ...]:
    """Largest prefix of ``(data, fsdp)`` whose size product divides
    ``batch`` — batch 1 on a wide mesh degrades to pure TP + weight
    gathering instead of failing on an unshardable batch dim."""
    axes = []
    prod = 1
    for ax in ("data", "fsdp"):
        n = mesh.shape.get(ax, 1)
        if n > 1 and batch % (prod * n) == 0:
            axes.append(ax)
            prod *= n
    return tuple(axes)


def batch_sharding(mesh: Mesh, batch: int, ndim: int) -> NamedSharding:
    axes = serving_batch_axes(mesh, batch)
    return NamedSharding(mesh, P(axes if axes else None, *([None] * (ndim - 1))))


def shard_batch_array(x, mesh: Mesh, dtype=None):
    """Place a (B, ...) activation with batch over the serving batch axes."""
    arr = jnp.asarray(x) if dtype is None else jnp.asarray(x, dtype)
    return jax.device_put(arr, batch_sharding(mesh, arr.shape[0], arr.ndim))


def replicate(x, mesh: Mesh):
    arr = jnp.asarray(x)
    return jax.device_put(arr, NamedSharding(mesh, P(*([None] * arr.ndim))))


def place_carry(mesh: Mesh, batch: int, frozen, n_rem, base_pos=None):
    """Place the pipelined scheduler's (frozen, n_rem, base_pos) control
    carry on the serving batch axes — the same placement the segment jits
    pin for their carry OUTPUTS, so a host-rebuilt carry (after an
    admission or forced finish) feeds the next dispatch without a
    reshard. ``base_pos`` may be None (plain decode has no gather base)."""
    sh = NamedSharding(mesh, P(serving_batch_axes(mesh, batch) or None))
    put = lambda x: None if x is None else jax.device_put(jnp.asarray(x), sh)
    return put(frozen), put(n_rem), put(base_pos)


def prefix_block_sharding(mesh: Mesh, cfg) -> NamedSharding:
    """Placement of one prefix-KV cache ENTRY block (L, 1, S, KV, hd):
    KV heads over ``model`` exactly like the resident cache (so the
    entry copy at admission — ``serve._prefix_prefill`` reading it, and
    ``serve._slice_prefix_block`` producing it on insert-on-prefill — is
    a local dynamic-slice/update per shard, no resharding), everything
    else replicated: the batch dim is 1, so the (data, fsdp) batch axes
    drop out. The int8-KV scale plane shares the spec (its trailing dim
    is 1; the head axis still divides)."""
    model_n = mesh.shape.get("model", 1)
    head_ax = ("model"
               if model_n > 1 and cfg.num_kv_heads % model_n == 0 else None)
    return NamedSharding(mesh, P(None, None, None, head_ax, None))


def shard_kv_cache(cache: Any, cfg, mesh: Mesh) -> Any:
    """Place a fresh KV cache: (L, B, S, KV, hd) with batch over the serving
    batch axes and KV heads over ``model`` (skipped if it does not divide
    the head count). ``length`` (B,) shards with the batch.

    Paged caches (ISSUE 12, ``"bt"`` present): the arena has NO batch
    axis — which row owns which block is host bookkeeping, so any device
    may need any block — and therefore replicates over the batch axes;
    only the KV-head axis shards over ``model`` (the same per-device
    divisor as the dense cache's head split). The block table and length
    planes shard with the batch like every per-row carry. This trades
    the dense layout's batch-axis KV split for block-granular
    allocation; recovering a sharded arena (blocks over (data, fsdp)
    with placement-aware tables) is the item-1b handoff seam
    (DISTRIBUTED.md)."""
    quant = isinstance(cache["k"], dict)
    if "bt" in cache:
        batch = int(cache["bt"].shape[0])
        baxes = serving_batch_axes(mesh, batch)
        bspec = baxes if baxes else None
        model_n = mesh.shape.get("model", 1)
        head_ax = ("model" if (model_n > 1
                               and cfg.num_kv_heads % model_n == 0) else None)
        pool_spec = P(None, None, None, head_ax, None)

        def put_pool(buf):
            if isinstance(buf, dict):
                return {"q": _put(buf["q"], mesh, pool_spec),
                        "s": _put(buf["s"], mesh, pool_spec)}
            return _put(buf, mesh, pool_spec)

        return {
            "k": put_pool(cache["k"]),
            "v": put_pool(cache["v"]),
            "bt": _put(cache["bt"], mesh, P(bspec, None)),
            "length": _put(cache["length"], mesh, P(bspec)),
        }
    batch = int(
        (cache["k"]["q"] if quant else cache["k"]).shape[1]
    )
    baxes = serving_batch_axes(mesh, batch)
    bspec = baxes if baxes else None
    model_n = mesh.shape.get("model", 1)
    head_ax = "model" if (model_n > 1 and cfg.num_kv_heads % model_n == 0) else None
    buf_spec = P(None, bspec, None, head_ax, None)

    def put_buf(buf):
        if isinstance(buf, dict):
            return {"q": _put(buf["q"], mesh, buf_spec),
                    "s": _put(buf["s"], mesh, buf_spec)}
        return _put(buf, mesh, buf_spec)

    return {
        "k": put_buf(cache["k"]),
        "v": put_buf(cache["v"]),
        "length": _put(cache["length"], mesh, P(bspec)),
    }


def serving_flash_shard_map(mesh: Mesh, batch: int, num_heads: Optional[int] = None):
    """Pallas flash prefill under a serving mesh.

    The flash kernel is an opaque custom call to the SPMD partitioner, so a
    bare call inside the pjit'd prefill would force an all-gather of every
    operand. Wrapped in shard_map it runs fully locally instead: batch over
    the serving batch axes, heads over ``model`` — the same layout the
    surrounding qkv/o matmuls already produce, so no resharding happens at
    the boundary and sharded prefill keeps flash's O(S) memory instead of
    falling back to dense (B, H, T, T) scores. Sequence stays unsharded
    (serving meshes have context=1, ``_require_serving_mesh``); causality is
    therefore purely local. Caller guarantees num_heads %% model == 0.

    Returns ``f(q, k, v, valid) -> out`` with q/k/v (B, S, H, hd) post-GQA
    repeat and valid (B, S) bool.
    """
    from jax.sharding import PartitionSpec as P

    from eventgpt_tpu.ops.flash_attention import flash_attention

    model_n = mesh.shape.get("model", 1)
    if num_heads is not None and num_heads % model_n:
        # Validate at the mechanism layer (every caller), not just at
        # generate()'s downgrade site — otherwise the failure is an opaque
        # shard_map divisibility trace.
        raise ValueError(
            f"flash under a serving mesh shards heads over model: "
            f"num_heads={num_heads} must divide by model={model_n} "
            f"(use dense attention otherwise)"
        )
    baxes = serving_batch_axes(mesh, batch)
    bspec = baxes if baxes else None
    head_ax = "model" if mesh.shape.get("model", 1) > 1 else None
    qkv_spec = P(bspec, None, head_ax, None)
    valid_spec = P(bspec, None)

    def local(q, k, v, valid):
        return flash_attention(q, k, v, valid=valid, causal=True)

    # check_vma=False: the pallas_call's out ShapeDtypeStruct carries no
    # varying-mesh-axes annotation, and the kernel is purely local anyway
    # (no collectives inside). compat.shard_map falls back to the 0.4.x
    # experimental home (check_rep) on builds without jax.shard_map.
    from eventgpt_tpu.compat import shard_map

    return shard_map(
        local, mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, valid_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )


def build_serving_mesh(
    data: int = 1, fsdp: int = 1, model: int = 1,
    devices: Optional[list] = None,
) -> Optional[Mesh]:
    """CLI helper: mesh from --mesh_* flags; None when everything is 1
    (single-chip fast path, no resharding)."""
    if data * fsdp * model <= 1:
        return None
    from eventgpt_tpu.config import MeshConfig
    from eventgpt_tpu.parallel.mesh import make_mesh

    return make_mesh(
        MeshConfig(data=data, fsdp=fsdp, context=1, model=model),
        devices=devices,
    )
