"""PartitionSpec trees for every parameter pytree in the framework.

The layout rules (standard megatron-style TP composed with FSDP, per the
scaling-book recipe — pick a mesh, annotate shardings, let XLA insert the
collectives):

  * Contracting/input feature dims shard over ``fsdp`` (all-gather at use —
    ZeRO-3 semantics, the TPU replacement for DeepSpeed in
    ``requirements.txt:21``).
  * Head/column dims shard over ``model`` (tensor parallel): q/k/v and MLP
    up/gate shard their *output* columns, o/down shard their *input* rows,
    so each layer needs exactly one psum on its output — inserted by XLA.
  * Stacked-layer leading axes are never sharded (they are scanned over).
  * Small params (norms, biases, projector) replicate over model and shard
    nothing — they are noise next to the matmul weights.

Batch dims of activations shard over ``(data, fsdp)`` — fsdp acts as extra
data parallelism for activations, which is what makes it ZeRO rather than
tensor parallelism.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Specs = Dict[str, Any]

BATCH_AXES = ("data", "fsdp")


def batch_spec(ndim: int, seq_axis: Optional[int] = None) -> P:
    """Activations: batch over (data, fsdp); optional sequence over context."""
    parts = [BATCH_AXES] + [None] * (ndim - 1)
    if seq_axis is not None:
        parts[seq_axis] = "context"
    return P(*parts)


def llama_param_specs() -> Specs:
    """Mirrors ``models/llama.py:init_llama_params`` structure exactly."""
    return {
        # (V, D): vocab over model (TP embed/unembed), features over fsdp.
        "embed_tokens": P("model", "fsdp"),
        "layers": {
            "input_norm": P(None, None),
            "attn": {
                "q": P(None, "fsdp", "model"),   # (L, D, QD)
                "k": P(None, "fsdp", "model"),   # (L, D, KVD)
                "v": P(None, "fsdp", "model"),   # (L, D, KVD)
                "o": P(None, "model", "fsdp"),   # (L, QD, D)
            },
            "post_norm": P(None, None),
            "mlp": {
                "gate": P(None, "fsdp", "model"),  # (L, D, I)
                "up": P(None, "fsdp", "model"),    # (L, D, I)
                "down": P(None, "model", "fsdp"),  # (L, I, D)
            },
        },
        "final_norm": P(None),
        "lm_head": P("fsdp", "model"),  # (D, V)
    }


def clip_param_specs() -> Specs:
    """Mirrors ``models/clip.py:init_clip_params``. The tower is frozen and
    small next to the LM; shard the big matmuls, replicate the rest."""
    ln = {"scale": P(None, None), "bias": P(None, None)}

    def lin(spec_k):
        return {"kernel": spec_k, "bias": P(None, None)}

    return {
        "embeddings": {
            "class_embedding": P(None),
            # (patch_dim, D): patch_dim = 3*14*14 = 588 has awkward factors;
            # shard the output features instead.
            "patch_embedding": P(None, "fsdp"),
            "position_embedding": P(None, "fsdp"),       # (N, D)
        },
        "pre_layernorm": {"scale": P(None), "bias": P(None)},
        "layers": {
            "ln1": ln,
            "attn": {
                "q": lin(P(None, "fsdp", "model")),
                "k": lin(P(None, "fsdp", "model")),
                "v": lin(P(None, "fsdp", "model")),
                "o": lin(P(None, "model", "fsdp")),
            },
            "ln2": ln,
            "mlp": {
                "fc1": {"kernel": P(None, "fsdp", "model"), "bias": P(None, "model")},
                "fc2": {"kernel": P(None, "model", "fsdp"), "bias": P(None, None)},
            },
        },
        "post_layernorm": {"scale": P(None), "bias": P(None)},
    }


def projector_param_specs(use_feature_adaptor: bool = True, mlp_depth: int = 2) -> Specs:
    """Projector MLP + adaptor (model/EventChatModel.py:87-93,75-76): a few
    4096x4096 matrices — shard rows over fsdp, replicate over model."""
    lin = {"kernel": P("fsdp", None), "bias": P(None)}
    specs: Specs = {"mlp": [dict(lin) for _ in range(mlp_depth)]}
    if use_feature_adaptor:
        specs["adaptor"] = dict(lin)
    return specs


def qformer_param_specs() -> Specs:
    """Q-Former (models/qformer.py): stacked (L, D, D) cross-attention +
    MLP weights — shard the contraction rows over fsdp like the projector;
    queries and norms replicate."""
    return {
        "query_embeddings": P(None, None),
        "attention_layers": {
            "ln_q": {"scale": P(None, None), "bias": P(None, None)},
            "ln_kv": {"scale": P(None, None), "bias": P(None, None)},
            "attn": {
                "q": P(None, "fsdp", None),
                "k": P(None, "fsdp", None),
                "v": P(None, "fsdp", None),
                "o": P(None, "fsdp", None),
            },
            "ln_mlp": {"scale": P(None, None), "bias": P(None, None)},
            "mlp": {
                "fc1": P(None, "fsdp", None),
                "fc1_bias": P(None, None),
                "fc2": P(None, "fsdp", None),
                "fc2_bias": P(None, None),
            },
        },
    }


def vocab_safe_llama_specs(llama_specs: Specs, vocab_size: int,
                           mesh: Mesh) -> Specs:
    """Drop the vocab-dim ``model`` sharding when it cannot divide.

    Special-token registration grows the vocab to odd sizes (32000 ->
    32003, ``initialize_vision_tokenizer`` parity), and ``device_put``
    rejects non-divisible tilings outright — replicating the vocab dim of
    embed/lm_head (features keep their fsdp sharding) trades a little
    memory for a working TP layout. Returns the (mutated) spec tree.
    """
    model_n = mesh.shape.get("model", 1)
    if model_n > 1 and vocab_size % model_n:
        llama_specs["embed_tokens"] = P(None, "fsdp")
        llama_specs["lm_head"] = P("fsdp", None)
    return llama_specs


def eventchat_param_specs(use_feature_adaptor: bool = True, mlp_depth: int = 2,
                          use_qformer: bool = False) -> Specs:
    specs = {
        "clip": clip_param_specs(),
        "projector": projector_param_specs(use_feature_adaptor, mlp_depth),
        "llama": llama_param_specs(),
    }
    if use_qformer:
        specs["qformer"] = qformer_param_specs()
    return specs


def kv_cache_specs() -> Specs:
    """KV cache (L, B, S, KV, hd): batch over (data, fsdp), heads over model."""
    return {
        "k": P(None, BATCH_AXES, None, "model", None),
        "v": P(None, BATCH_AXES, None, "model", None),
        "length": P(BATCH_AXES),
    }


def tree_shardings(specs, mesh: Mesh):
    """Specs pytree -> NamedSharding pytree (same structure)."""
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_params(params, specs, mesh: Mesh):
    """Place a param pytree onto the mesh according to its spec tree.

    The spec tree must mirror the param tree's structure; a mismatch
    surfaces as a tree_map structure error here rather than deep in pjit.
    """
    shardings = tree_shardings(specs, mesh)
    return jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, s), params, shardings
    )
