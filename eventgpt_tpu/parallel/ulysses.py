"""Ulysses-style all-to-all sequence parallelism over the ``context`` axis.

The second context-parallel mode next to ring attention
(``parallel/ring.py``): instead of rotating KV blocks around a ring (one
ppermute per step, compute overlapping transfer), Ulysses re-shards with two
collectives — an all-to-all that trades the sequence shard for a HEAD shard
(each device ends up with the FULL sequence for H/C of the heads), a plain
local attention over the complete sequence, and an inverse all-to-all back
to sequence sharding. (DeepSpeed-Ulysses; the reference stack has neither
mode — SURVEY.md §2.4.)

Trade-off vs ring: Ulysses moves O(S·H·hd / C) twice per layer regardless of
the context size and runs attention as one dense local call (simple, fast
when heads are plentiful and ICI all-to-all is cheap — the v5e torus);
ring's traffic is comparable but pipelined across C steps, and it keeps
full-head locality (no H % C divisibility requirement). Both enforce
causality with global positions and are dense-equivalent up to f32
summation order; `LlamaConfig.attn_impl` picks "ring" or "ulysses".
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh


def _ulysses_attention_local(
    q: jnp.ndarray,        # (B, S/C, H, hd) local sequence chunk, all heads
    k: jnp.ndarray,
    v: jnp.ndarray,
    q_valid: jnp.ndarray,   # (B, S/C) bool
    kv_valid: jnp.ndarray,  # (B, S/C) bool
    axis_name: str,
    causal: bool = True,
) -> jnp.ndarray:
    """Per-shard body (inside shard_map): all-to-all -> full-sequence local
    attention on a head shard -> inverse all-to-all."""
    # seq-shard -> head-shard: device j receives head block j over the FULL
    # sequence (chunks concatenate in axis order = global token order).
    qh = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    kh = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    vh = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    kvv = lax.all_gather(kv_valid, axis_name, axis=1, tiled=True)  # (B, S)

    b, s, hc, hd = qh.shape
    scale = 1.0 / math.sqrt(hd)
    scores = jnp.einsum("bqhd,bkhd->bhqk", qh, kh,
                        preferred_element_type=jnp.float32) * scale
    mask = kvv[:, None, None, :]
    if causal:
        pos = jnp.arange(s)
        mask = mask & (pos[None, None, None, :] <= pos[None, None, :, None])
    scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(vh.dtype), vh,
                     preferred_element_type=jnp.float32).astype(q.dtype)

    # head-shard -> seq-shard (exact inverse exchange).
    out = lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)
    return jnp.where(q_valid[:, :, None, None], out, 0.0)


def ulysses_attention_shard_map(mesh: Mesh, causal: bool = True,
                                axis_name: str = "context"):
    """Un-jitted shard_map: ``f(q, k, v, q_valid, kv_valid) -> out`` with the
    same calling convention as ``ring_attention_shard_map`` — the form
    ``models/llama.py`` calls inside its own jit when
    ``attn_impl == "ulysses"``. LOCAL heads (H / model) must divide by the
    context size (heads re-shard across the axis); validated here at trace
    time so every caller gets the friendly error, not a shard_map failure."""
    from eventgpt_tpu.parallel.sp_common import SP_QKV_SPEC, SP_VALID_SPEC

    inner = jax.shard_map(
        functools.partial(_ulysses_attention_local, axis_name=axis_name,
                          causal=causal),
        mesh=mesh,
        in_specs=(SP_QKV_SPEC, SP_QKV_SPEC, SP_QKV_SPEC,
                  SP_VALID_SPEC, SP_VALID_SPEC),
        out_specs=SP_QKV_SPEC,
    )

    def checked(q, k, v, q_valid, kv_valid):
        local_heads = q.shape[2] // mesh.shape["model"]
        ctx = mesh.shape[axis_name]
        if local_heads % max(ctx, 1):
            raise ValueError(
                f"ulysses re-shards heads over the context axis: "
                f"H/model = {local_heads} must divide by context={ctx} "
                f"(use ring attention otherwise)"
            )
        return inner(q, k, v, q_valid, kv_valid)

    return checked


def ulysses_self_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    valid: Optional[jnp.ndarray] = None,
    causal: bool = True,
    axis_name: str = "context",
) -> jnp.ndarray:
    """Jitted convenience entry: global-shape q/k/v (B, S, H, hd); S must
    divide by the context axis and H by (context x model)."""
    b, s, h, hd = q.shape
    if valid is None:
        valid = jnp.ones((b, s), bool)
    return _ulysses_jitted(mesh, causal, axis_name)(q, k, v, valid, valid)


@functools.lru_cache(maxsize=32)
def _ulysses_jitted(mesh: Mesh, causal: bool, axis_name: str):
    return jax.jit(ulysses_attention_shard_map(mesh, causal, axis_name))
