"""Ulysses-style all-to-all sequence parallelism over the ``context`` axis.

The second context-parallel mode next to ring attention
(``parallel/ring.py``): instead of rotating KV blocks around a ring (one
ppermute per step, compute overlapping transfer), Ulysses re-shards with two
collectives — an all-to-all that trades the sequence shard for a HEAD shard
(each device ends up with the FULL sequence for H/C of the heads), a plain
local attention over the complete sequence, and an inverse all-to-all back
to sequence sharding. (DeepSpeed-Ulysses; the reference stack has neither
mode — SURVEY.md §2.4.)

Trade-off vs ring: Ulysses moves O(S·H·hd / C) twice per layer regardless of
the context size and runs attention as one dense local call (simple, fast
when heads are plentiful and ICI all-to-all is cheap — the v5e torus);
ring's traffic is comparable but pipelined across C steps, and it keeps
full-head locality (no H % C divisibility requirement). Both enforce
causality with global positions and are dense-equivalent up to f32
summation order; `LlamaConfig.attn_impl` picks "ring" or "ulysses".
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh


def _repeat_heads(x: jnp.ndarray, rep: int) -> jnp.ndarray:
    """(B, S, KV, hd) -> (B, S, KV*rep, hd) GQA head replication — the one
    canonical implementation lives in models/llama.py."""
    from eventgpt_tpu.models.llama import _repeat_kv

    return _repeat_kv(x, rep)


def _ulysses_attention_local(
    q: jnp.ndarray,        # (B, S/C, H, hd) local sequence chunk
    k: jnp.ndarray,        # (B, S/C, KV, hd) — UN-repeated GQA heads
    v: jnp.ndarray,
    q_valid: jnp.ndarray,   # (B, S/C) bool
    kv_valid: jnp.ndarray,  # (B, S/C) bool
    axis_name: str,
    causal: bool = True,
    inner: str = "flash",
) -> jnp.ndarray:
    """Per-shard body (inside shard_map): all-to-all -> full-sequence local
    attention on a head shard -> inverse all-to-all.

    GQA traffic (ADVICE r2): K/V cross the ICI with their NATIVE head count
    and are repeated to the query heads only AFTER the exchange — a
    pre-repeat would multiply all-to-all bytes by H/KV. The post-exchange
    repeat is exact when contiguous query-head blocks map to contiguous KV
    blocks (KV % C == 0 and (H/C) % rep == 0); otherwise the pre-repeat
    fallback keeps correctness on odd head splits.

    ``inner="flash"`` runs the blockwise Pallas kernel over the gathered
    sequence — O(S·block) forward memory instead of the dense (B,H,S,S)
    f32 score matrix (the long-context regime is this mode's whole
    purpose). ``inner="dense"`` keeps the materialized form.
    """
    from eventgpt_tpu.compat import axis_size

    ctx = axis_size(axis_name)
    rep = q.shape[2] // k.shape[2]
    post_repeat = (
        rep > 1 and k.shape[2] % ctx == 0 and (q.shape[2] // ctx) % rep == 0
    )
    if rep > 1 and not post_repeat:
        k = _repeat_heads(k, rep)
        v = _repeat_heads(v, rep)

    # seq-shard -> head-shard: device j receives head block j over the FULL
    # sequence (chunks concatenate in axis order = global token order).
    qh = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    kh = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    vh = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    kvv = lax.all_gather(kv_valid, axis_name, axis=1, tiled=True)  # (B, S)
    if post_repeat:
        # Query block [j*H/C, (j+1)*H/C) consumes exactly KV block
        # [j*KV/C, (j+1)*KV/C) under contiguous GQA mapping (head i -> kv
        # i // rep), so the local repeat reproduces the pre-repeat layout.
        kh = _repeat_heads(kh, rep)
        vh = _repeat_heads(vh, rep)

    b, s, hc, hd = qh.shape
    if inner == "flash":
        from eventgpt_tpu.ops.flash_attention import flash_attention

        out = flash_attention(qh, kh, vh, valid=kvv, causal=causal)
    else:
        scale = 1.0 / math.sqrt(hd)
        scores = jnp.einsum("bqhd,bkhd->bhqk", qh, kh,
                            preferred_element_type=jnp.float32) * scale
        mask = kvv[:, None, None, :]
        if causal:
            pos = jnp.arange(s)
            mask = mask & (pos[None, None, None, :] <= pos[None, None, :, None])
        scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(vh.dtype), vh,
                         preferred_element_type=jnp.float32).astype(q.dtype)

    # head-shard -> seq-shard (exact inverse exchange).
    out = lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)
    return jnp.where(q_valid[:, :, None, None], out, 0.0)


def ulysses_attention_shard_map(mesh: Mesh, causal: bool = True,
                                axis_name: str = "context",
                                inner: str = "flash"):
    """Un-jitted shard_map: ``f(q, k, v, q_valid, kv_valid) -> out`` with the
    same calling convention as ``ring_attention_shard_map`` — the form
    ``models/llama.py`` calls inside its own jit when
    ``attn_impl == "ulysses"``. LOCAL heads (H / model) must divide by the
    context size (heads re-shard across the axis); validated here at trace
    time so every caller gets the friendly error, not a shard_map failure.

    K/V may be passed with their native (un-repeated) GQA head count —
    ``accepts_unrepeated_kv`` advertises this to the caller; the repeat
    happens after the all-to-all (ICI bytes scale with KV, not H)."""
    from eventgpt_tpu.compat import shard_map
    from eventgpt_tpu.parallel.sp_common import SP_QKV_SPEC, SP_VALID_SPEC

    fn = shard_map(
        functools.partial(_ulysses_attention_local, axis_name=axis_name,
                          causal=causal, inner=inner),
        mesh=mesh,
        in_specs=(SP_QKV_SPEC, SP_QKV_SPEC, SP_QKV_SPEC,
                  SP_VALID_SPEC, SP_VALID_SPEC),
        out_specs=SP_QKV_SPEC,
        # The Pallas flash kernel's out_shape carries no varying-mesh-axes
        # annotation; skip the vma check (the specs above pin the layout).
        check_vma=False,
    )

    def checked(q, k, v, q_valid, kv_valid):
        local_heads = q.shape[2] // mesh.shape["model"]
        ctx = mesh.shape[axis_name]
        if local_heads % max(ctx, 1):
            raise ValueError(
                f"ulysses re-shards heads over the context axis: "
                f"H/model = {local_heads} must divide by context={ctx} "
                f"(use ring attention otherwise)"
            )
        return fn(q, k, v, q_valid, kv_valid)

    checked.accepts_unrepeated_kv = True
    return checked


def ulysses_self_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    valid: Optional[jnp.ndarray] = None,
    causal: bool = True,
    axis_name: str = "context",
) -> jnp.ndarray:
    """Jitted convenience entry: global-shape q/k/v (B, S, H, hd); S must
    divide by the context axis and H by (context x model)."""
    b, s, h, hd = q.shape
    if valid is None:
        valid = jnp.ones((b, s), bool)
    return _ulysses_jitted(mesh, causal, axis_name)(q, k, v, valid, valid)


@functools.lru_cache(maxsize=32)
def _ulysses_jitted(mesh: Mesh, causal: bool, axis_name: str):
    return jax.jit(ulysses_attention_shard_map(mesh, causal, axis_name))
