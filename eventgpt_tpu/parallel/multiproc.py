"""Multi-process dry run: the distributed stack across real OS processes.

Everything else in the test/dryrun surface runs ONE process with N virtual
devices, which never exercises a process boundary. This module is the proof
that the pieces of SURVEY §2.4/§5's distributed story actually compose across
processes the way the reference's NCCL/mpi4py/DeepSpeed stack did
(``/root/reference/requirements.txt:85,65,21`` — one rank per GPU, collective
gradient reduction, rank-0-gated artifact writes):

  * ``initialize_distributed`` (``parallel/dist.py``) bootstraps N processes
    through the ``EGPT_*`` env contract against a real coordinator;
  * a ``Mesh`` spanning both processes runs the stage-2 train step, with the
    gradient psum riding cross-process collectives (Gloo on CPU — the same
    pjit program that rides ICI on a pod);
  * the loss matches a single-process run of the identical global program;
  * checkpoints are written the trainer's way — orbax save as a collective,
    ``STEP``/component files gated by ``is_primary()`` — and restored on the
    *other* rank;
  * a preemption signal landing on ONE rank propagates through
    ``GracefulShutdown.globally_requested()``'s allgather so BOTH ranks take
    a coordinated checkpoint (``train/resilience.py`` — the mismatched-
    collective deadlock this prevents only exists with >= 2 processes).

Topology: ``n_processes`` workers x ``local_devices`` virtual CPU devices
each, so 2 x 8 doubles as the 16-device mesh proof. The launcher runs the
workers plus a single-process reference job and compares losses.
"""

from __future__ import annotations

import functools
import json
import os
import socket
import subprocess
import sys
import tempfile
from typing import Dict, List, Optional, Sequence

# Parsed by the launcher from worker stdout; versioned so stale workers fail
# loudly rather than mis-parse.
_RESULT_TAG = "MPRESULT1"


def _reserve_port() -> socket.socket:
    """Bind an ephemeral port and HOLD the socket (ADVICE r5: closing
    before the coordinator binds leaves a window where another process
    claims the port — a spurious bootstrap failure under parallel CI).
    The caller closes it just before spawning workers; SO_REUSEADDR lets
    the coordinator rebind the briefly-TIME_WAIT-free port immediately."""
    s = socket.socket()
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind(("127.0.0.1", 0))
    return s


# ---------------------------------------------------------------------------
# Worker (child process) side


def _put_global(tree, specs, mesh):
    """Host pytree -> global sharded arrays, multi-process safe.

    ``jax.device_put`` onto a sharding with non-addressable devices is not
    portable; ``make_array_from_callback`` is — every process holds the full
    host value (same seed everywhere) and contributes its addressable shards.
    """
    import jax
    import numpy as np

    from eventgpt_tpu.parallel.sharding import tree_shardings

    shardings = tree_shardings(specs, mesh)

    def put(x, s):
        x = np.asarray(jax.device_get(x))
        return jax.make_array_from_callback(x.shape, s, lambda idx: x[idx])

    return jax.tree_util.tree_map(put, tree, shardings)


@functools.lru_cache(maxsize=8)
def _gather_jit(rep):
    """One replicate-to-host executable per target sharding — rebuilding
    ``jax.jit(lambda ...)`` inside ``gather`` re-traced per LEAF (the
    jit-hygiene rule's untracked-creation case); shardings are hashable,
    so the lru key is the executable's identity."""
    import jax

    return jax.jit(lambda v: v, out_shardings=rep)


def _replicate_to_host(tree):
    """Gather a (possibly cross-process) sharded pytree to host numpy on
    every process: jit to a fully-replicated layout, then device_get."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def gather(x):
        mesh = x.sharding.mesh
        rep = NamedSharding(mesh, P())
        return jax.device_get(_gather_jit(rep)(x))

    return jax.tree_util.tree_map(gather, tree)


def worker_main() -> None:
    """Entry for both the multi-process workers and the single-process
    reference job (distinguished by the presence of the EGPT_* contract)."""
    # Workers simulate standalone hosts: ambient pod-autodetect vars must
    # not reach initialize_distributed's autodetection. Scrubbing the spawn
    # env is NOT enough — the axon image's sitecustomize re-injects
    # TPU_WORKER_HOSTNAMES into every fresh interpreter.
    from eventgpt_tpu import faults
    from eventgpt_tpu.parallel.dist import POD_AUTODETECT_VARS

    # Chaos hook for the process-boundary story: EGPT_FAULTS propagates
    # through the spawn env, so 'multiproc.worker:n=1' kills the first
    # worker's bootstrap — the launcher's round-robin poll must surface
    # it as that rank's failure, not a coordinator deadlock.
    faults.maybe_fail("multiproc.worker")
    for k in POD_AUTODETECT_VARS:
        os.environ.pop(k, None)
    import jax

    # The axon TPU plugin ignores JAX_PLATFORMS (memory: env var not
    # honored); the config update below must land before backend init.
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_default_matmul_precision", "highest")

    import numpy as np

    from eventgpt_tpu import checkpoint as ckpt
    from eventgpt_tpu.config import EventChatConfig, MeshConfig
    from eventgpt_tpu.models import eventchat
    from eventgpt_tpu.parallel import make_mesh
    from eventgpt_tpu.parallel.dist import barrier, initialize_distributed, is_primary
    from eventgpt_tpu.parallel.sharding import (
        batch_spec, clip_param_specs, llama_param_specs, projector_param_specs,
    )
    from eventgpt_tpu.train import steps as steps_mod
    from eventgpt_tpu.train.data import synthetic_multimodal_batch
    from eventgpt_tpu.train.lora import LoraConfig, lora_param_specs
    from eventgpt_tpu.train.optim import linear_warmup_cosine, make_optimizer
    from eventgpt_tpu.train.resilience import GracefulShutdown

    multi = initialize_distributed()
    rank = jax.process_index()
    nproc = jax.process_count()

    # Per-process metric labels (ISSUE 3 / DISTRIBUTED.md): every sample a
    # worker exposes (or dumps into telemetry.jsonl) carries its rank, so
    # scrapes from N processes on one host stay disambiguated without any
    # name mangling. The same call is the pattern for real pod launches.
    from eventgpt_tpu.obs import metrics as _obs_metrics

    _obs_metrics.REGISTRY.set_common_labels(process=str(rank))

    mesh_shape = [int(x) for x in os.environ["EGPT_MP_MESH"].split(",")]
    n_steps = int(os.environ.get("EGPT_MP_STEPS", "2"))
    outdir = os.environ["EGPT_MP_OUTDIR"]
    attn_impl = os.environ.get("EGPT_MP_ATTN", "dense")

    mcfg = MeshConfig(data=mesh_shape[0], fsdp=mesh_shape[1],
                      context=mesh_shape[2], model=mesh_shape[3])
    mesh = make_mesh(mcfg)  # all global devices — spans both processes

    import dataclasses

    cfg = EventChatConfig.tiny()
    cfg = dataclasses.replace(
        cfg, llama=dataclasses.replace(cfg.llama, attn_impl=attn_impl))

    params = eventchat.init_eventchat_params(cfg, jax.random.PRNGKey(0))
    lcfg = LoraConfig(r=4)
    trainable, frozen = steps_mod.split_stage2(
        params, cfg, lcfg, jax.random.PRNGKey(1))
    trainable = _put_global(
        trainable,
        {"projector": projector_param_specs(
            cfg.projector.use_feature_adaptor, cfg.projector.mlp_depth),
         "lora": lora_param_specs(lcfg.targets)},
        mesh)
    frozen = _put_global(
        frozen, {"clip": clip_param_specs(), "llama": llama_param_specs()},
        mesh)

    opt = make_optimizer(linear_warmup_cosine(1e-3, 10, 0))
    state = steps_mod.init_train_state(trainable, frozen, opt)
    step_fn = steps_mod.make_train_step(
        cfg, opt, steps_mod.make_stage2_combine(lcfg), donate=False, mesh=mesh)

    batch_size = mcfg.data * mcfg.fsdp
    host_batch = synthetic_multimodal_batch(cfg, batch_size, 64, event_offset=8)
    ctx = mesh.shape["context"]
    batch = _put_global(
        host_batch,
        {k: batch_spec(
            np.ndim(v),
            seq_axis=1 if np.ndim(v) == 2 and v.shape[1] % ctx == 0 else None)
         for k, v in host_batch.items()},
        mesh)

    losses: List[float] = []
    for _ in range(n_steps):
        state, metrics = step_fn(state, batch)
        losses.append(float(jax.device_get(metrics["loss"])))
    if any(l != l for l in losses):
        raise RuntimeError(f"rank {rank}: NaN loss in multiproc dry run: {losses}")

    resumed_ok: Optional[bool] = None
    preempt_line = ""
    if multi:
        # --- Checkpoint leg: the trainer's exact write discipline ---------
        # orbax save is a collective (every process writes its shards);
        # STEP is primary-only (trainer.save, train/trainer.py:356-368).
        ckpt_dir = os.path.join(outdir, "ckpt_mp")
        ckpt.save_checkpoint(ckpt_dir, {"trainable": state.trainable,
                                        "step": state.step})
        if is_primary():
            with open(os.path.join(ckpt_dir, "STEP"), "w") as f:
                f.write(str(int(jax.device_get(state.step))))
        barrier("ckpt_mp_written")

        # Resume on the NON-primary rank: restore into the live shardings
        # and verify the restored tree matches what this rank holds.
        restored = ckpt.load_checkpoint(
            ckpt_dir, target={"trainable": state.trainable, "step": state.step})
        live = _replicate_to_host(state.trainable)
        back = _replicate_to_host(restored["trainable"])
        flat_live = jax.tree_util.tree_leaves(live)
        flat_back = jax.tree_util.tree_leaves(back)
        resumed_ok = (
            int(jax.device_get(restored["step"])) == n_steps
            and len(flat_live) == len(flat_back)
            and all(np.array_equal(a, b) for a, b in zip(flat_live, flat_back))
        )
        if not resumed_ok:
            raise RuntimeError(
                f"rank {rank}: restored checkpoint diverges from live state")

        # --- Preemption leg ------------------------------------------------
        # SIGTERM lands on ONE host (rank 1 here, via the programmatic
        # trigger the fault-injection tests use); every rank must agree
        # through the allgather before touching a collective save.
        shutdown = GracefulShutdown()
        if rank == 1:
            shutdown.request("simulated-preemption")
        agreed = shutdown.globally_requested()
        if not agreed:
            raise RuntimeError(
                f"rank {rank}: preemption allgather missed the rank-1 signal")
        if rank == 0 and shutdown.requested:
            raise RuntimeError("rank 0 local flag set — test wiring broken")
        # Coordinated checkpoint: both ranks enter the same collective.
        pre_dir = os.path.join(outdir, "ckpt_preempt_mp")
        ckpt.save_checkpoint(pre_dir, {"trainable": state.trainable,
                                       "step": state.step})
        if is_primary():
            with open(os.path.join(pre_dir, "STEP"), "w") as f:
                f.write(str(int(jax.device_get(state.step))))
        barrier("preempt_ckpt_written")
        if not os.path.isdir(pre_dir):
            raise RuntimeError(f"rank {rank}: coordinated checkpoint missing")
        preempt_line = (
            f"local_flag(rank{rank})={shutdown.requested} agreed={agreed}")

    print(_RESULT_TAG + json.dumps({
        "rank": rank, "n_processes": nproc,
        "local_devices": jax.local_device_count(),
        "global_devices": jax.device_count(),
        "mesh": {"data": mcfg.data, "fsdp": mcfg.fsdp,
                 "context": mcfg.context, "model": mcfg.model},
        "attn": attn_impl, "losses": losses,
        "resumed_ok": resumed_ok, "preempt": preempt_line,
    }), flush=True)


# ---------------------------------------------------------------------------
# Launcher (parent) side


def _worker_env(base: Dict[str, str], local_devices: int) -> Dict[str, str]:
    env = dict(base)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    flags.append(f"--xla_force_host_platform_device_count={local_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    # A worker must never inherit a half-set contract from the caller, nor
    # the ambient pod-autodetect vars (the axon session exports
    # TPU_WORKER_HOSTNAMES, which would push the single-process reference
    # job into jax.distributed.initialize with no coordinator).
    from eventgpt_tpu.parallel.dist import POD_AUTODETECT_VARS

    for k in ("EGPT_COORDINATOR", "EGPT_NUM_PROCESSES",
              "EGPT_PROCESS_ID") + POD_AUTODETECT_VARS:
        env.pop(k, None)
    return env


def _parse_result(stdout: str, who: str) -> dict:
    for line in stdout.splitlines():
        if line.startswith(_RESULT_TAG):
            return json.loads(line[len(_RESULT_TAG):])
    raise RuntimeError(f"{who}: no {_RESULT_TAG} line in output:\n{stdout[-2000:]}")


def launch_multiprocess_dryrun(
    n_processes: int = 2,
    local_devices: int = 8,
    mesh_shape: Sequence[int] = (2, 2, 2, 2),
    n_steps: int = 2,
    attn_impl: str = "ring",
    timeout: float = 1500.0,
    rtol: float = 1e-5,
) -> dict:
    """Run the multi-process dry run + single-process reference; compare.

    Returns the summary dict (also printed as artifact lines). Raises on any
    worker failure, loss mismatch, or missing leg.
    """
    import math

    global_devices = n_processes * local_devices
    if math.prod(mesh_shape) != global_devices:
        raise ValueError(f"mesh {tuple(mesh_shape)} needs "
                         f"{math.prod(mesh_shape)} devices, have "
                         f"{n_processes}x{local_devices}={global_devices}")

    from eventgpt_tpu import faults

    faults.maybe_fail("multiproc.launch")
    port_sock = _reserve_port()
    port = port_sock.getsockname()[1]
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    cmd = [sys.executable, "-m", "eventgpt_tpu.parallel.multiproc", "--worker"]

    with tempfile.TemporaryDirectory(prefix="egpt_mp_") as outdir:
        common = {
            "EGPT_MP_MESH": ",".join(str(x) for x in mesh_shape),
            "EGPT_MP_STEPS": str(n_steps),
            "EGPT_MP_OUTDIR": outdir,
            "EGPT_MP_ATTN": attn_impl,
        }
        # Worker output goes to FILES, not pipes: a rank blocked writing
        # into an undrained 64 KiB pipe would stall out of its collectives
        # — turning any verbose crash into a generic cross-rank timeout —
        # and files let the poll loop below read everything post-mortem.
        procs = []
        logs = []
        # Release the reserved port at the last possible moment: the
        # rank-0 worker's coordinator binds it next.
        port_sock.close()
        for rank in range(n_processes):
            env = _worker_env(os.environ, local_devices)
            env.update(common)
            env["EGPT_COORDINATOR"] = f"127.0.0.1:{port}"
            env["EGPT_NUM_PROCESSES"] = str(n_processes)
            env["EGPT_PROCESS_ID"] = str(rank)
            out_path = os.path.join(outdir, f"rank{rank}.out")
            err_path = os.path.join(outdir, f"rank{rank}.err")
            logs.append((out_path, err_path))
            with open(out_path, "w") as fo, open(err_path, "w") as fe:
                procs.append(subprocess.Popen(
                    cmd, env=env, cwd=repo, stdout=fo, stderr=fe))
        # Round-robin poll rather than sequential waits: whichever rank
        # dies first must surface immediately — its survivors are blocked
        # in collectives that can never complete, and a sequential wait on
        # a lower-indexed survivor would burn the whole timeout and then
        # misreport the crash as a coordinator deadlock.
        import time as _time

        deadline = _time.monotonic() + timeout
        pending = set(range(n_processes))
        failed_rank = None
        while pending:
            for rank in sorted(pending):
                rc = procs[rank].poll()
                if rc is None:
                    continue
                pending.discard(rank)
                if rc != 0 and failed_rank is None:
                    failed_rank = rank
            if failed_rank is not None and pending:
                # Short grace for survivors, then put them down.
                grace = _time.monotonic() + 5.0
                while pending and _time.monotonic() < grace:
                    for rank in list(pending):
                        if procs[rank].poll() is not None:
                            pending.discard(rank)
                    _time.sleep(0.1)
                for rank in pending:
                    procs[rank].kill()
                    procs[rank].wait()
                pending.clear()
            elif pending:
                if _time.monotonic() > deadline:
                    stuck = sorted(pending)
                    for q in procs:
                        q.kill()
                        q.wait()  # reap before reading logs (no zombies)
                    tails = []
                    for rank in stuck:
                        try:
                            with open(logs[rank][1]) as fe:
                                tails.append(f"-- rank {rank} stderr --\n"
                                             f"{fe.read()[-1000:]}")
                        except OSError:
                            pass
                    raise RuntimeError(
                        f"multiproc ranks {stuck} still running after "
                        f"{timeout}s (coordinator deadlock?)\n"
                        + "\n".join(tails))
                _time.sleep(0.2)
        outs = []
        for rank in range(n_processes):
            with open(logs[rank][0]) as fo, open(logs[rank][1]) as fe:
                outs.append((fo.read(), fe.read()))
        if failed_rank is not None:
            raise RuntimeError(
                f"multiproc worker rank {failed_rank} failed "
                f"(rc={procs[failed_rank].returncode}):\n"
                f"{outs[failed_rank][1][-3000:]}")
        results = [_parse_result(out, f"rank {i}") for i, (out, _) in enumerate(outs)]

        # Single-process reference: the identical global program on one
        # process with all devices local (no EGPT_* contract -> fast path).
        env = _worker_env(os.environ, global_devices)
        env.update(common)
        ref_proc = subprocess.run(
            cmd, env=env, cwd=repo, capture_output=True, text=True,
            timeout=timeout)
        if ref_proc.returncode != 0:
            raise RuntimeError(
                f"single-process reference failed (rc={ref_proc.returncode}):\n"
                f"{ref_proc.stderr[-3000:]}")
        ref = _parse_result(ref_proc.stdout, "single-process reference")

    by_rank = {r["rank"]: r for r in results}
    losses_mp = by_rank[0]["losses"]
    losses_ref = ref["losses"]
    for i, (a, b) in enumerate(zip(losses_mp, losses_ref)):
        if not math.isclose(a, b, rel_tol=rtol, abs_tol=0.0):
            raise RuntimeError(
                f"multiproc loss diverges from single-process at step {i}: "
                f"{a!r} vs {b!r} (rtol {rtol})")
    for r in results:
        if r["n_processes"] != n_processes or not r["resumed_ok"]:
            raise RuntimeError(f"bad worker result: {r}")
        if "agreed=True" not in r["preempt"]:
            raise RuntimeError(f"preemption leg missing on rank {r['rank']}: {r}")

    mesh = by_rank[0]["mesh"]
    summary = {
        "n_processes": n_processes, "local_devices": local_devices,
        "global_devices": by_rank[0]["global_devices"], "mesh": mesh,
        "attn": attn_impl, "losses_multiproc": losses_mp,
        "losses_single_process": losses_ref, "rtol": rtol,
    }
    print(f"dryrun_multiproc: n_processes={n_processes} x "
          f"local_devices={local_devices} = {summary['global_devices']} "
          f"global devices, mesh={mesh} attn={attn_impl}: "
          f"loss {losses_mp} == single-process {losses_ref} (rtol {rtol})")
    print("dryrun_multiproc: orbax checkpoint saved collectively, STEP "
          "primary-only, restored + verified on every rank incl. non-primary")
    print("dryrun_multiproc: preemption on rank 1 only -> "
          "GracefulShutdown.globally_requested() allgather agreed on all "
          "ranks -> coordinated checkpoint on both")
    return summary


def main(argv: Optional[List[str]] = None) -> None:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "--worker":
        worker_main()
        return
    launch_multiprocess_dryrun()


if __name__ == "__main__":
    main()
