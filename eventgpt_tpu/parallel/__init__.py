"""Parallelism layer: device mesh, sharding rules, distributed bootstrap.

The reference has zero in-tree parallelism (SURVEY.md §2.4) — its distributed
story was torch DDP / DeepSpeed ZeRO / NCCL in external packages. Here the
equivalent is a first-class subsystem built on ``jax.sharding``:

  * :mod:`eventgpt_tpu.parallel.mesh`     — logical ``Mesh(data, fsdp, context, model)``
  * :mod:`eventgpt_tpu.parallel.sharding` — PartitionSpec trees for every param pytree
  * :mod:`eventgpt_tpu.parallel.dist`     — multi-host bootstrap (NCCL/MPI analog)
  * :mod:`eventgpt_tpu.parallel.ring`     — ring attention over the ``context``
    axis (planned; the ``context`` mesh axis is reserved for it)
"""

from eventgpt_tpu.parallel.mesh import make_mesh, best_mesh_config  # noqa: F401
from eventgpt_tpu.parallel.sharding import (  # noqa: F401
    eventchat_param_specs,
    llama_param_specs,
    clip_param_specs,
    projector_param_specs,
    shard_params,
    batch_spec,
    kv_cache_specs,
)
