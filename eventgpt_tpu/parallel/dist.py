"""Multi-host distributed bootstrap — the NCCL/MPI analog.

The reference's communication backend was NCCL + optional MPI pulled in by
torch/DeepSpeed (``requirements.txt:85,65,21``); nothing in-tree. The TPU
equivalent is ``jax.distributed.initialize`` (one call per host process)
after which pjit-compiled collectives ride ICI within a slice and DCN across
slices with no explicit communication code (SURVEY.md §5 "Distributed
communication backend").

Environment contract (mirrors the torchrun/deepspeed launcher env vars):

  EGPT_COORDINATOR   coordinator address host:port (a la MASTER_ADDR/PORT)
  EGPT_NUM_PROCESSES total process count            (a la WORLD_SIZE)
  EGPT_PROCESS_ID    this process's rank            (a la RANK)

On TPU pods / GKE these are auto-detected by JAX and the variables may be
omitted entirely; ``initialize_distributed()`` is then a thin safe wrapper.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import jax

log = logging.getLogger("eventgpt_tpu.dist")

_INITIALIZED = False

# Presence of any of these means a cloud/pod launcher will feed
# jax.distributed.initialize its coordination parameters. Exported so test
# harnesses that simulate standalone hosts scrub exactly this set
# (parallel/multiproc.py) — a private copy would drift.
POD_AUTODETECT_VARS = (
    "TPU_WORKER_HOSTNAMES", "TPU_SKYLARK_HOSTS",
    "MEGASCALE_COORDINATOR_ADDRESS",
)


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Bootstrap multi-host JAX. Returns True if a multi-process runtime was
    initialized, False for the single-process fast path.

    Safe to call repeatedly (idempotent) and safe to call in single-host
    runs: with no coordinator configured and no cloud autodetection
    available, it degrades to a no-op.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return True

    coordinator_address = coordinator_address or os.environ.get("EGPT_COORDINATOR")
    if num_processes is None and "EGPT_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["EGPT_NUM_PROCESSES"])
    if process_id is None and "EGPT_PROCESS_ID" in os.environ:
        process_id = int(os.environ["EGPT_PROCESS_ID"])

    explicit = coordinator_address is not None
    autodetectable = any(v in os.environ for v in POD_AUTODETECT_VARS)
    if not explicit and not autodetectable:
        if num_processes is not None or process_id is not None:
            # Half-configured launch: running on silently would give N
            # independent single-process trainers all claiming primary.
            raise ValueError(
                "EGPT_NUM_PROCESSES/EGPT_PROCESS_ID are set but "
                "EGPT_COORDINATOR is not; refusing to fall back to a "
                "single-process run"
            )
        log.info("single-process run; skipping jax.distributed.initialize")
        return False

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _INITIALIZED = True
    log.info(
        "distributed runtime up: process %d/%d, %d local / %d global devices",
        jax.process_index(), jax.process_count(),
        jax.local_device_count(), jax.device_count(),
    )
    return True


def is_primary() -> bool:
    """True on the process that should write checkpoints / logs."""
    return jax.process_index() == 0


def barrier(name: str = "barrier") -> None:
    """Cross-host sync point (debug/checkpoint fencing)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)
