"""Ring attention: sequence/context parallelism over the ``context`` mesh axis.

The reference has no long-context story at all — context is hard-capped at
2048 (``model/EventChatModel.py:378``) and no sequence parallelism exists
anywhere in its stack (SURVEY.md §2.4). This module is the designed-in
escape hatch: Q/K/V are sharded along the sequence axis over the ``context``
mesh axis; each device computes blockwise attention against its local KV
chunk while KV blocks rotate around the ring via ``lax.ppermute`` (one ICI
hop per step), with flash-style online-softmax accumulation so the full
score matrix never materializes. Compute on step i overlaps the transfer
for step i+1 (XLA schedules the ppermute DMA concurrently with the matmuls).

Causality is enforced with *global* positions, so results are bit-compatible
with dense causal attention up to f32 summation order.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh


def _ring_attention_local(
    q: jnp.ndarray,       # (B, Sq, H, hd)  local query chunk
    k: jnp.ndarray,       # (B, Sk, H, hd)  local key chunk (start of ring)
    v: jnp.ndarray,       # (B, Sk, H, hd)
    q_valid: jnp.ndarray,  # (B, Sq) bool — padding mask for local queries
    kv_valid: jnp.ndarray,  # (B, Sk) bool
    axis_name: str,
    causal: bool = True,
) -> jnp.ndarray:
    """Per-shard body (inside shard_map): online-softmax over ring steps."""
    axis_size = lax.psum(1, axis_name)
    axis_idx = lax.axis_index(axis_name)

    b, sq, h, hd = q.shape
    sk = k.shape[1]
    scale = 1.0 / math.sqrt(hd)
    q_pos = axis_idx * sq + jnp.arange(sq)  # global query positions

    neg = jnp.finfo(jnp.float32).min
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def step(i, carry):
        o, m, l, k_cur, v_cur, kvv_cur = carry
        # Chunk currently held arrived from device (axis_idx - i) mod n.
        src = (axis_idx - i) % axis_size
        k_pos = src * sk + jnp.arange(sk)

        s = jnp.einsum("bqhd,bkhd->bhqk", q, k_cur,
                       preferred_element_type=jnp.float32) * scale
        valid = kvv_cur[:, None, None, :]
        if causal:
            valid = valid & (k_pos[None, None, None, :] <= q_pos[None, None, :, None])
        s = jnp.where(valid, s, neg)

        m_new = jnp.maximum(m, s.max(axis=-1))
        # exp(neg - m_new) underflows to 0 for fully-masked rows.
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v_cur.dtype), v_cur,
                        preferred_element_type=jnp.float32)
        o_new = o * corr.transpose(0, 2, 1)[..., None] + pv

        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        kvv_nxt = lax.ppermute(kvv_cur, axis_name, perm)
        return o_new, m_new, l_new, k_nxt, v_nxt, kvv_nxt

    # Fresh zeros are "unvarying" under shard_map's manual-axes typing while
    # the loop outputs vary per device; pcast marks them explicitly
    # (pvary's replacement — it was deprecated in jax 0.9; compat.pvary
    # no-ops on 0.4.x, which has no varying-axes typing at all).
    from eventgpt_tpu.compat import pvary
    from eventgpt_tpu.parallel.mesh import AXES

    def _vary(x):
        return pvary(x, AXES)

    o0 = _vary(jnp.zeros((b, sq, h, hd), jnp.float32))
    m0 = _vary(jnp.full((b, h, sq), neg, jnp.float32))
    l0 = _vary(jnp.zeros((b, h, sq), jnp.float32))
    o, m, l, _, _, _ = lax.fori_loop(
        0, axis_size, step, (o0, m0, l0, k, v, kv_valid)
    )
    l = jnp.maximum(l, 1e-30)
    out = o / l.transpose(0, 2, 1)[..., None]
    out = jnp.where(q_valid[:, :, None, None], out, 0.0)
    return out.astype(q.dtype)


def ring_self_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    valid: Optional[jnp.ndarray] = None,
    causal: bool = True,
    axis_name: str = "context",
) -> jnp.ndarray:
    """Sequence-parallel causal attention over ``mesh``'s ``context`` axis.

    Shapes (global): q/k/v (B, S, H, hd); S must divide by the context axis
    size. ``valid`` (B, S) marks real tokens (None -> all real). Batch
    shards over (data, fsdp), heads over model, sequence over context.
    """
    b, s, h, hd = q.shape
    if valid is None:
        valid = jnp.ones((b, s), bool)
    return _ring_jitted(mesh, causal, axis_name)(q, k, v, valid, valid)


def ring_attention_shard_map(mesh: Mesh, causal: bool = True,
                             axis_name: str = "context"):
    """Un-jitted shard_map over the ring body: ``f(q, k, v, q_valid,
    kv_valid) -> out``. This is the form model code calls *inside* its own
    jit (``models/llama.py`` when ``attn_impl == 'ring'``); shard_map
    composes with the surrounding GSPMD partitioning. Goes through
    ``compat.shard_map`` so 0.4.x builds (no ``jax.shard_map``) fall back
    to the experimental home instead of failing at call time."""
    from eventgpt_tpu.compat import shard_map
    from eventgpt_tpu.parallel.sp_common import SP_QKV_SPEC, SP_VALID_SPEC

    return shard_map(
        functools.partial(_ring_attention_local, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(SP_QKV_SPEC, SP_QKV_SPEC, SP_QKV_SPEC,
                  SP_VALID_SPEC, SP_VALID_SPEC),
        out_specs=SP_QKV_SPEC,
    )


@functools.lru_cache(maxsize=32)
def _ring_jitted(mesh: Mesh, causal: bool, axis_name: str):
    """One jitted shard_map per (mesh, causal, axis) — rebuilding it per call
    would retrace and recompile on every invocation."""
    return jax.jit(ring_attention_shard_map(mesh, causal, axis_name))


def dense_reference_attention(q, k, v, valid=None, causal=True):
    """Unsharded reference implementation (tests / single chip)."""
    b, s, h, hd = q.shape
    if valid is None:
        valid = jnp.ones((b, s), bool)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) / math.sqrt(hd)
    mask = valid[:, None, None, :]
    if causal:
        pos = jnp.arange(s)
        mask = mask & (pos[None, None, None, :] <= pos[None, None, :, None])
    scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    out = jnp.where(valid[:, :, None, None], out, 0.0)
    return out.astype(q.dtype)
