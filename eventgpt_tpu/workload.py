"""Trace-driven serving workloads: generation, replay, SLO scoring.

Every scheduler win so far was measured under uniform round-robin
sessions on an idle host — exactly the drift VERDICT r5 flagged. Orca
(OSDI '22) and Sarathi-Serve (arXiv 2403.02310) judge serving systems by
**SLO-attainment goodput under realistic traffic**; this module is the
traffic half of that measurement:

  * ``WorkloadSpec`` + ``generate_trace``: a SEEDED, fully deterministic
    request trace — Poisson / heavy-tailed-bursty (Gamma shape < 1) /
    on-off arrival processes, lognormal (capped) prompt and output
    lengths, and a session mix of one-shot event QA, multi-turn chat
    (turns of one session share the system + through-event prompt heads,
    so the radix prefix cache is exercised) and streaming-style
    re-submits (one short query repeated against a live stream).
  * ``save_trace`` / ``load_trace``: JSONL persistence. The same spec
    always serializes to the byte-identical file (sorted keys, rounded
    arrival stamps), so a measured run is replayable byte-for-byte and a
    checked-in trace is diff-stable.
  * ``SLO`` / ``SLO_CLASSES``: per-request service-level objectives.
    ``interactive`` requests carry TTFT/ITL targets, ``batch`` requests
    an end-to-end latency target; ``SLO.met`` is THE attainment
    predicate (inclusive — a request exactly on target has met it),
    shared by the batcher's finish-time scoring and the bench's goodput
    accounting.
  * ``replay``: open-loop replay of a trace against a
    ``ContinuousBatcher`` — requests are submitted at their scheduled
    arrival times (scaled by ``rate_mult``, the offered-load dial)
    regardless of whether the server keeps up, which is what makes
    goodput-vs-load curves honest (closed-loop replay self-throttles and
    hides saturation).

Deliberately jax-free (numpy + stdlib): trace generation and SLO math
must run on any host — the bench driver, a router tier, tests — without
owning an accelerator. ``eventgpt_tpu/serve.py`` imports the SLO types
from here, not the other way around.
"""

from __future__ import annotations

import json
import time
from dataclasses import asdict, dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from eventgpt_tpu.constants import EVENT_TOKEN_INDEX

# The CLOSED set of SLO class names (bounded metric-label cardinality:
# obs/metrics.py METRIC_LABELS mirrors it, and scripts/lint_telemetry.py
# rule 5 bans labels outside a declared enum). submit() validates
# against this tuple so an unknown class fails loudly at the edge, not
# as a fresh Prometheus series.
SLO_CLASSES = ("interactive", "batch")

ARRIVALS = ("poisson", "gamma", "onoff")
KINDS = ("oneshot", "chat", "stream")


@dataclass(frozen=True)
class SLO:
    """One request's service-level objective. ``None`` targets are
    unarmed; ``met`` requires every ARMED target to hold, inclusively —
    a request exactly on its target has met it (the synthetic-clock
    tests in tests/test_workload.py pin this boundary)."""

    name: str = "interactive"
    ttft_s: Optional[float] = None      # submit -> first committed token
    itl_s: Optional[float] = None       # mean inter-token gap
    latency_s: Optional[float] = None   # submit -> terminal status

    def met(self, ttft_s: float, itl_s: float, latency_s: float) -> bool:
        if self.ttft_s is not None and ttft_s > self.ttft_s:
            return False
        if self.itl_s is not None and itl_s > self.itl_s:
            return False
        if self.latency_s is not None and latency_s > self.latency_s:
            return False
        return True


@dataclass(frozen=True)
class WorkloadSpec:
    """Everything that determines a trace. Two specs that compare equal
    generate byte-identical JSONL — the replayability contract."""

    seed: int = 0
    n_requests: int = 32
    rate_rps: float = 4.0          # mean offered arrival rate
    arrival: str = "poisson"       # poisson | gamma | onoff
    # gamma: inter-arrivals ~ Gamma(shape, 1/(rate*shape)) — same mean
    # rate, CV = 1/sqrt(shape); shape < 1 is burstier than Poisson.
    gamma_shape: float = 0.25
    # onoff: Poisson bursts at rate*(on+off)/on during ON windows,
    # silence during OFF — same mean rate, maximally clumped.
    on_s: float = 1.0
    off_s: float = 3.0
    # Session mix (normalized): one-shot event QA / multi-turn chat /
    # streaming re-submits.
    p_oneshot: float = 0.25
    p_chat: float = 0.5
    p_stream: float = 0.25
    sessions: int = 4              # persistent chat/stream sessions
    head_len: int = 12             # shared system-text head (tokens, incl BOS)
    # Heavy-tailed TEXT tail lengths: lognormal(mu, sigma), capped.
    prompt_mu: float = 2.3
    prompt_sigma: float = 0.8
    prompt_min: int = 4
    prompt_max: int = 48
    output_mu: float = 2.6
    output_sigma: float = 0.9
    output_min: int = 4
    output_max: int = 32
    stream_output: int = 6         # streaming re-submits: short budgets
    # Per-class SLO targets (None/0 disables that target).
    interactive_ttft_s: float = 1.0
    interactive_itl_s: float = 0.25
    batch_latency_s: float = 30.0
    # Token-id range for generated text (kept clear of special ids).
    vocab_lo: int = 5
    vocab_hi: int = 97

    def slo_for(self, slo_class: str) -> SLO:
        """The class's SLO object (the targets the batcher scores)."""
        if slo_class == "interactive":
            return SLO("interactive",
                       ttft_s=self.interactive_ttft_s or None,
                       itl_s=self.interactive_itl_s or None)
        if slo_class == "batch":
            return SLO("batch", latency_s=self.batch_latency_s or None)
        raise ValueError(f"unknown SLO class {slo_class!r}: "
                         f"one of {SLO_CLASSES}")


@dataclass
class TraceRequest:
    """One request of a trace. ``input_ids`` carries exactly one event
    sentinel; ``pixels_seed`` derives the event stream deterministically
    at replay time (``stream_pixels``) instead of storing frames in the
    JSONL — same stream seed = same stream, which is what keys the
    prefix cache's wrong-stream guard."""

    idx: int
    t_arrival: float               # seconds from trace start
    session: int
    kind: str                      # oneshot | chat | stream
    slo_class: str                 # interactive | batch
    input_ids: List[int] = field(default_factory=list)
    pixels_seed: int = 0
    max_new_tokens: int = 8
    turn: int = 0                  # chat turn index within the session


def _inter_arrivals(spec: WorkloadSpec, rng: np.random.Generator
                    ) -> np.ndarray:
    n, rate = spec.n_requests, float(spec.rate_rps)
    if spec.arrival == "poisson":
        return rng.exponential(1.0 / rate, n)
    if spec.arrival == "gamma":
        shape = float(spec.gamma_shape)
        return rng.gamma(shape, 1.0 / (rate * shape), n)
    if spec.arrival == "onoff":
        # Exponential gaps at the boosted ON rate; a gap that crosses an
        # ON-window boundary carries the OFF silence with it.
        period = spec.on_s + spec.off_s
        boosted = rate * period / spec.on_s
        gaps = rng.exponential(1.0 / boosted, n)
        out = np.empty(n)
        t = 0.0
        for i, g in enumerate(gaps):
            t += g
            while (t % period) >= spec.on_s:
                t += spec.off_s - ((t % period) - spec.on_s)
            out[i] = t
        return np.diff(out, prepend=0.0)
    raise ValueError(f"unknown arrival process {spec.arrival!r}: "
                     f"one of {ARRIVALS}")


def _capped_lognormal(rng: np.random.Generator, mu: float, sigma: float,
                      lo: int, hi: int) -> int:
    return int(np.clip(round(float(rng.lognormal(mu, sigma))), lo, hi))


def generate_trace(spec: WorkloadSpec) -> List[TraceRequest]:
    """Deterministic trace from ``spec`` (one rng, fixed draw order —
    the same spec always yields the same requests)."""
    rng = np.random.default_rng(spec.seed)
    arrivals = np.cumsum(_inter_arrivals(spec, rng))
    probs = np.asarray([spec.p_oneshot, spec.p_chat, spec.p_stream], float)
    probs = probs / probs.sum()
    # Shared system head: identical TEXT across every stream (the
    # cross-session radix hit); BOS + a fixed filler token, the
    # tests/bench prompt idiom.
    head = [1] + [7] * max(spec.head_len - 1, 0)

    def tail(n: int) -> List[int]:
        return [int(t) for t in
                rng.integers(spec.vocab_lo, spec.vocab_hi, n)]

    # Per-session state: chat dialogs accumulate turns (shared
    # through-event heads grow), streams repeat one fixed short query.
    dialogs: Dict[int, List[int]] = {s: [] for s in range(spec.sessions)}
    turns: Dict[int, int] = {s: 0 for s in range(spec.sessions)}
    stream_query: Dict[int, List[int]] = {}
    out: List[TraceRequest] = []
    n_oneshot = 0
    for i in range(spec.n_requests):
        kind = KINDS[int(rng.choice(3, p=probs))]
        budget = _capped_lognormal(rng, spec.output_mu, spec.output_sigma,
                                   spec.output_min, spec.output_max)
        if kind == "oneshot":
            # Fresh stream, fresh query: only the TEXT head repeats.
            session = spec.sessions + n_oneshot
            n_oneshot += 1
            pixels_seed = 5000 + session
            body = tail(_capped_lognormal(
                rng, spec.prompt_mu, spec.prompt_sigma,
                spec.prompt_min, spec.prompt_max))
            turn = 0
            slo_class = "batch"
        else:
            session = int(rng.integers(0, spec.sessions))
            pixels_seed = 1000 + session
            if kind == "stream":
                # The SAME short query resubmitted against a live
                # stream — a full-prompt repeat, the deepest radix hit.
                if session not in stream_query:
                    stream_query[session] = tail(spec.prompt_min)
                body = list(stream_query[session])
                budget = min(budget, spec.stream_output)
                turn = 0
            else:  # chat: the dialog grows, sharing its head with
                   # every earlier turn of the session
                new = tail(_capped_lognormal(
                    rng, spec.prompt_mu, spec.prompt_sigma,
                    spec.prompt_min, spec.prompt_max))
                if len(dialogs[session]) + len(new) > spec.prompt_max:
                    dialogs[session] = []     # conversation rolls over
                    turns[session] = 0
                dialogs[session] = dialogs[session] + new
                body = list(dialogs[session])
                turns[session] += 1
                turn = turns[session]
            slo_class = "interactive"
        out.append(TraceRequest(
            idx=i,
            t_arrival=round(float(arrivals[i]), 6),
            session=session,
            kind=kind,
            slo_class=slo_class,
            input_ids=head + [EVENT_TOKEN_INDEX] + body,
            pixels_seed=pixels_seed,
            max_new_tokens=budget,
        ))
    return out


def cache_positions(req: TraceRequest, num_event_tokens: int) -> int:
    """Prompt length in KV-cache positions (text tokens + the event
    block's expansion) — the server-sizing arithmetic."""
    n_text = sum(1 for t in req.input_ids if t != EVENT_TOKEN_INDEX)
    return n_text + num_event_tokens


def stream_pixels(shape: Tuple[int, ...], seed: int) -> np.ndarray:
    """The event stream behind ``pixels_seed``: deterministic f32 frames
    (same seed = byte-identical stream, so traces replay byte-for-byte
    without storing pixels)."""
    return np.random.default_rng(seed).normal(size=shape).astype(np.float32)


# -- JSONL persistence -----------------------------------------------------

def save_trace(path: str, spec: WorkloadSpec,
               trace: List[TraceRequest]) -> None:
    """Header line (version + spec) then one line per request. Sorted
    keys + the generator's rounded arrival stamps make the file a pure
    function of ``spec``: regenerating writes the byte-identical file."""
    with open(path, "w") as f:
        f.write(json.dumps({"version": 1, "spec": asdict(spec)},
                           sort_keys=True) + "\n")
        for r in trace:
            f.write(json.dumps(asdict(r), sort_keys=True) + "\n")


def load_trace(path: str) -> Tuple[WorkloadSpec, List[TraceRequest]]:
    with open(path) as f:
        header = json.loads(f.readline())
        if header.get("version") != 1:
            raise ValueError(f"unknown trace version in {path}: "
                             f"{header.get('version')!r}")
        spec = WorkloadSpec(**header["spec"])
        trace = [TraceRequest(**json.loads(line))
                 for line in f if line.strip()]
    return spec, trace


# -- open-loop replay ------------------------------------------------------

def replay(batcher, trace: List[TraceRequest], *,
           pixels_for: Callable[[TraceRequest], Any],
           rate_mult: float = 1.0, paced: bool = True,
           slo_for: Optional[Callable[[TraceRequest], Optional[SLO]]] = None,
           ) -> Dict[str, Any]:
    """Replay ``trace`` against a live ``ContinuousBatcher``.

    OPEN loop: request i is submitted at ``t_arrival / rate_mult`` on
    the wall clock whether or not the server has room — backlog grows
    when the server falls behind, which is exactly what the goodput
    curve must see (``rate_mult`` is the offered-load dial). ``paced=
    False`` submits in arrival order as fast as the loop runs (the
    throughput/A-B form — per-row greedy chains are scheduling-
    independent, so chains match the paced replay byte-for-byte).

    ``slo_for`` maps a trace request to the SLO object submitted with it
    (None = plain submit, the disarmed A/B arm). Returns ``finished``
    keyed by TRACE idx (not rid), the rid map, and the wall duration.
    """
    rid_of: Dict[int, int] = {}
    i, n = 0, len(trace)

    def busy() -> bool:
        return bool(batcher.queue) or any(
            r is not None for r in batcher.rows)

    t0 = time.perf_counter()
    while i < n or busy():
        now = time.perf_counter() - t0
        while i < n and (not paced
                         or trace[i].t_arrival / rate_mult <= now):
            r = trace[i]
            rid_of[r.idx] = batcher.submit(
                r.input_ids, pixels_for(r), r.max_new_tokens,
                slo=slo_for(r) if slo_for is not None else None,
            )
            i += 1
        if busy():
            batcher.step()
        elif i < n:
            # Idle server, next arrival in the future: sleep toward it
            # in short hops so a submit never lands very late.
            now = time.perf_counter() - t0
            time.sleep(min(max(
                trace[i].t_arrival / rate_mult - now, 0.0), 0.005))
    # Queue and rows are drained; collect the accumulated finishes (and
    # any trailing in-flight segment) through the normal drain path.
    finished_by_rid = batcher.run_until_drained()
    duration = time.perf_counter() - t0
    return {
        "rids": rid_of,
        "finished": {idx: finished_by_rid[rid]
                     for idx, rid in rid_of.items()},
        "duration_s": duration,
    }
