"""Adaptive speculation controller (ISSUE 13 tentpole, ROADMAP item 4).

``speculative=K`` was a server-lifetime constant, yet the measured spec
spread on this repo's own bench is ~8x (564-583 tok/s ceiling vs a
~71 tok/s floor at the r05 shapes) and which end of it a deployment
lands on is decided ENTIRELY by realized acceptance.  Greedy
verification commits the target chain byte-for-byte at ANY draft depth
(Leviathan et al., arXiv 2211.17192), so depth is a pure latency knob —
this module turns it into a per-dispatch-boundary decision driven by
measured acceptance, with zero jax in sight (host policy only; the
device sees a different precompiled bucket executable, never a
recompile).

Three decisions per boundary, all deterministic functions of the
harvested acceptance history (same trace + same seed => same choice
sequence, the replay-determinism contract ``tests/test_spec_adaptive``
pins):

  * **bucket selection** — the verification window W for this boundary,
    from the closed ``--spec_buckets`` set (every bucket's executable is
    primed by ``warmup()``; K=0 maps to the draft-free W=1 segment, the
    baseline-cost fallback for pathological traffic).  Policy: the
    classic speculative-decoding expectation.  With per-draft acceptance
    probability a, a depth-d window commits E(d) = (1-a^(d+1))/(1-a)
    tokens per verify while a verify over d drafts costs ~(1 + c*d)
    relative to a plain decode step (c = ``draft_cost``, the marginal
    per-draft-position verify cost — near 0 when decode is
    weight-streaming bound, higher on small models / CPU).  The bucket
    maximizing E(d)/(1 + c*d) wins; ties break toward the SMALLER
    bucket.  ``hysteresis`` keeps the current bucket unless the winner
    beats it by the given margin, so boundary-to-boundary EMA jitter
    does not thrash executables.
  * **per-row depth masking** — rows whose own windowed acceptance
    undershoots the bucket get their draft positions ≥ depth masked to
    the ``-1`` unmatchable filler (``models/eventchat._spec_draft_verify``
    already defines -1 as never-accepted in BOTH the greedy and the
    rejection-sampled commit), capping that row's effective depth with
    no new executable.  Fresh rows start at full depth (optimistic).
  * **head/tree pruning** (the Medusa path, Cai et al. 2401.10774) —
    the segment harvests PER-POSITION accept/offer counts, so the
    controller knows each draft head's realized yield; positions whose
    yield EMA drops below ``head_min_yield`` are pruned from the depth
    cap for every row.  The same rule prunes deep lookup positions —
    the suffix-vote "tree" is a chain, so pruning a level prunes the
    branch.  Under a mixed boundary the admission token budget also
    caps depth: live_rows * depth drafts may not exceed
    ``draft_budget`` (default: the mixed-segment prefill budget), the
    same per-boundary token-budget admission already enforces.

The controller never touches chains: masked drafts and smaller windows
only change how many tokens commit per verify, and verification makes
any draft exact.  ``serve.py`` consults it at the dispatch boundary and
feeds it at the harvest; the ``serve.spec_adapt`` fault site degrades a
boundary to the fixed default window at full depth (chaos-tested).
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Dict, List, Optional, Sequence, Tuple

__all__ = ["SpecController", "parse_spec_buckets", "expected_commits"]


def parse_spec_buckets(spec: Optional[str]) -> Optional[Tuple[int, ...]]:
    """``--spec_buckets`` grammar: comma-separated K values ("0,2,4,8").
    K=0 (and K=1) mean the draft-free window-1 segment.  Returns a
    sorted de-duplicated tuple of WINDOW widths, or None for an
    empty/missing spec (fixed-K serving, the pre-ISSUE-13 behavior)."""
    if not spec:
        return None
    out = set()
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        k = int(part)
        if k < 0:
            raise ValueError(f"spec bucket must be >= 0, got {k}")
        out.add(max(k, 1))  # K=0 -> the draft-free window-1 segment
    if not out:
        return None
    return tuple(sorted(out))


def expected_commits(accept: float, depth: int) -> float:
    """E[tokens committed per verify] at ``depth`` drafts under i.i.d.
    per-draft acceptance probability ``accept`` — the Leviathan et al.
    geometric-series expectation: 1 + a + a^2 + ... + a^depth."""
    a = min(max(accept, 0.0), 1.0)
    if a >= 1.0:
        return float(depth + 1)
    return (1.0 - a ** (depth + 1)) / (1.0 - a)


class SpecController:
    """Acceptance-driven draft-depth policy.  jax-free; owned by one
    ``ContinuousBatcher`` and called only under the engine lock (the
    batcher's ``_EXTERNAL_LOCK`` contract) — it must never grow a
    thread or lock of its own."""

    def __init__(
        self,
        windows: Sequence[int],
        default_window: int,
        ema_alpha: float = 0.3,
        draft_cost: float = 0.05,
        hysteresis: float = 0.05,
        row_window: int = 4,
        head_min_yield: float = 0.05,
        draft_budget: int = 0,
    ):
        ws = tuple(sorted({max(int(w), 1) for w in windows}))
        if not ws:
            raise ValueError("spec controller needs at least one window")
        self.windows = ws
        self.default_window = max(int(default_window), 1)
        if self.default_window not in ws:
            # The fault-degradation bucket must itself be a primed
            # executable — warmup() warms self.windows, so membership
            # is the cheap static guarantee.
            self.windows = tuple(sorted(ws + (self.default_window,)))
        self.max_window = max(self.windows)
        self.ema_alpha = float(ema_alpha)
        self.draft_cost = max(float(draft_cost), 0.0)
        self.hysteresis = max(float(hysteresis), 0.0)
        self.row_window = max(int(row_window), 1)
        self.head_min_yield = min(max(float(head_min_yield), 0.0), 1.0)
        self.draft_budget = max(int(draft_budget), 0)
        # Acceptance state.  ``accept_ema`` is the per-draft-position
        # acceptance probability (accepted drafts / offered drafts),
        # None until the first drafted verify lands — selection is
        # optimistic (largest bucket) until the traffic says otherwise.
        self.accept_ema: Optional[float] = None
        # Per-position (= per Medusa head / lookup level) yield EMAs,
        # sized to the largest window's draft count; None = no data yet.
        self.pos_yield: List[Optional[float]] = \
            [None] * max(self.max_window - 1, 0)
        # Per-request windowed acceptance: rid -> deque of
        # (accepted, offered) per harvested segment.
        self._rows: Dict[int, Deque[Tuple[int, int]]] = {}
        self.current_window = min(self.default_window, self.max_window)
        # Counters (host-side, surfaced via serving stats + bench).
        self.boundaries = 0
        self.switches = 0
        self.masked_row_boundaries = 0
        self.accepted_total = 0
        self.offered_total = 0

    # -- harvest side -----------------------------------------------------

    def observe(self, per_row: Sequence[Tuple[int, int, int]],
                pos_acc: Sequence[int], pos_off: Sequence[int]) -> None:
        """Feed one harvested segment.  ``per_row``: (rid, accepted,
        offered) per live row; ``pos_acc``/``pos_off``: per-draft-
        position accept/offer counts over the whole segment (length =
        segment window - 1; shorter than max_window is fine)."""
        seg_acc = 0
        seg_off = 0
        for rid, acc, off in per_row:
            if off <= 0:
                continue
            seg_acc += acc
            seg_off += off
            hist = self._rows.get(rid)
            if hist is None:
                hist = self._rows[rid] = deque(maxlen=self.row_window)
            hist.append((acc, off))
        if seg_off > 0:
            self.accepted_total += seg_acc
            self.offered_total += seg_off
            ratio = seg_acc / seg_off
            if self.accept_ema is None:
                self.accept_ema = ratio
            else:
                self.accept_ema += self.ema_alpha * (ratio - self.accept_ema)
        for i, (pa, po) in enumerate(zip(pos_acc, pos_off)):
            if po <= 0 or i >= len(self.pos_yield):
                continue
            y = pa / po
            cur = self.pos_yield[i]
            self.pos_yield[i] = y if cur is None else \
                cur + self.ema_alpha * (y - cur)

    def forget(self, rid: int) -> None:
        """Drop a finished/exported request's window (terminal paths)."""
        self._rows.pop(rid, None)

    # -- dispatch side ----------------------------------------------------

    def _value(self, window: int, accept: float) -> float:
        d = window - 1
        return expected_commits(accept, d) / (1.0 + self.draft_cost * d)

    def select_window(self, live_rows: int = 0,
                      mixed: bool = False) -> int:
        """Pick this boundary's bucket.  Deterministic in the observed
        acceptance history; optimistic (largest bucket) before any
        drafted verify has landed."""
        self.boundaries += 1
        if self.accept_ema is None:
            choice = self.max_window
        else:
            a = self.accept_ema
            best, best_v = None, -1.0
            for w in self.windows:
                v = self._value(w, a)
                if v > best_v + 1e-12:  # ties -> smaller bucket
                    best, best_v = w, v
            cur_v = self._value(self.current_window, a)
            # Hysteresis: keep the incumbent unless the winner clears it
            # by the margin — EMA jitter must not thrash buckets.
            choice = best if best_v > cur_v * (1.0 + self.hysteresis) \
                else self.current_window
        if mixed and self.draft_budget and live_rows > 0:
            # The mixed-boundary draft budget: live_rows * (W-1) draft
            # positions per verify must fit the same per-boundary token
            # budget the lane admission enforces. Degrade to the largest
            # bucket that fits (window 1 always does: zero drafts).
            fitting = [w for w in self.windows
                       if live_rows * (w - 1) <= self.draft_budget]
            cap = max(fitting) if fitting else min(self.windows)
            choice = min(choice, cap)
        if choice != self.current_window:
            self.switches += 1
            self.current_window = choice
        return choice

    def head_cap(self, window: int) -> int:
        """Depth cap from per-position yields (Medusa head pruning /
        lookup-level pruning): the first position whose yield EMA is
        known and below ``head_min_yield`` prunes itself and everything
        deeper (a chain draft's level i is unreachable when level i-1
        dies, so pruning a level prunes the branch)."""
        cap = window - 1
        for i in range(min(cap, len(self.pos_yield))):
            y = self.pos_yield[i]
            if y is not None and y < self.head_min_yield:
                return i
        return cap

    def row_depth(self, rid: int, window: int) -> int:
        """Per-row effective depth in [0, window-1]: the depth whose
        expected value is best under the ROW's windowed acceptance.
        Rows without history run at full depth (optimistic start)."""
        full = window - 1
        hist = self._rows.get(rid)
        if not hist:
            return full
        acc = sum(a for a, _ in hist)
        off = sum(o for _, o in hist)
        if off <= 0:
            return full
        a = acc / off
        best_d, best_v = 0, -1.0
        for d in range(full + 1):
            v = expected_commits(a, d) / (1.0 + self.draft_cost * d)
            if v > best_v + 1e-12:
                best_d, best_v = d, v
        return best_d

    def depths(self, rids: Sequence[Optional[int]],
               window: int) -> Tuple[List[int], int]:
        """Per-row depth vector for one boundary (None rid = free/frozen
        slot, full depth — it commits nothing anyway) and the count of
        rows masked below full depth, after the head-pruning cap."""
        full = window - 1
        cap = min(full, self.head_cap(window))
        out: List[int] = []
        masked = 0
        for rid in rids:
            d = full if rid is None else min(self.row_depth(rid, window), cap)
            if rid is not None and d < full:
                masked += 1
            out.append(d)
        self.masked_row_boundaries += masked
        return out, masked

    # -- observability ----------------------------------------------------

    def stats(self) -> Dict[str, object]:
        return {
            "windows": list(self.windows),
            "current_window": self.current_window,
            "accept_ema": (round(self.accept_ema, 4)
                           if self.accept_ema is not None else None),
            "accept_ratio_total": (
                round(self.accepted_total / self.offered_total, 4)
                if self.offered_total else None),
            "boundaries": self.boundaries,
            "switches": self.switches,
            "masked_row_boundaries": self.masked_row_boundaries,
            "pos_yield": [round(y, 4) if y is not None else None
                          for y in self.pos_yield],
            "tracked_rows": len(self._rows),
        }
