"""Path confinement for request-supplied file names.

One helper shared by every surface that turns an externally supplied
string into a server-local file read (``cli/serve.py``'s HTTP
``event_path``, ``scripts/serve_demo.py``'s ``--event_root`` mode), so the
allowlist logic exists exactly once (VERDICT r4 weak #6: the demo is the
same engine one flag away from a socket).
"""

from __future__ import annotations

import os
from typing import Optional


def resolve_event_path(event_root: Optional[str], requested: str) -> str:
    """Resolve ``requested`` strictly inside ``event_root``.

    * ``event_root is None`` -> refused outright: surfaces without a
      configured root must not read server-local paths on behalf of a
      request (clients upload inline instead).
    * Symlinks and ``..`` are neutralized by resolving to real paths and
      requiring the result to stay under the real root.

    Returns the resolved absolute path; raises ``ValueError`` otherwise.
    """
    if event_root is None:
        raise ValueError(
            "event paths are disabled (configure --event_root DIR to allow "
            "files under DIR, or send the stream inline via event_b64)"
        )
    root = os.path.realpath(event_root)
    path = os.path.realpath(os.path.join(root, str(requested).lstrip("/")))
    if path != root and not path.startswith(root + os.sep):
        raise ValueError("event path escapes --event_root")
    return path
