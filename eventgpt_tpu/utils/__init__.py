"""Utilities: profiling, structured metrics."""

from eventgpt_tpu.utils.profiling import profile_trace, timed  # noqa: F401
