"""Persistent XLA compilation cache (cold-start mitigation, VERDICT r2 #2).

The serving cold-start is pure XLA compile time: ~8.3 s CLIP-encode +
~6.6 s prefill per process at 7B (BENCH_r02). The reference never pays
this (torch eager + HF generate), but it also never amortizes — every
process re-runs cuDNN autotune. Here one flag flip makes every compile
land in an on-disk cache keyed by HLO fingerprint: the second process
deserializes executables instead of recompiling, which is what makes the
50 ms streaming story (reference README.md:119, scripts/stream_demo.py)
hold across restarts.

Call ``enable_compile_cache()`` before the first jit executes (any later
call still helps subsequent compiles). Opt out with
``EVENTGPT_COMPILE_CACHE=off``; redirect with ``EVENTGPT_COMPILE_CACHE=<dir>``.
"""

from __future__ import annotations

import os
from typing import Optional

_DEFAULT_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "eventgpt_tpu", "xla_cache"
)


def enable_compile_cache(cache_dir: Optional[str] = None) -> Optional[str]:
    """Turn on JAX's persistent compilation cache. Returns the cache dir,
    or None when disabled via ``EVENTGPT_COMPILE_CACHE=off``."""
    env = os.environ.get("EVENTGPT_COMPILE_CACHE")
    if env == "off":
        return None

    import jax

    # TPU only: XLA:CPU cache entries embed host machine features
    # (avx512 etc.) and reload with SIGILL warnings on heterogeneous
    # hosts; CPU compiles are fast enough to not need caching.
    if jax.default_backend() != "tpu":
        return None
    path = cache_dir or env or _DEFAULT_DIR
    os.makedirs(path, exist_ok=True)

    jax.config.update("jax_compilation_cache_dir", path)
    # Default thresholds skip small/fast compiles; serving wants everything
    # cached — the CLIP encode alone is dozens of small jits around the big
    # ones, and the per-process budget they cost is the point of this file.
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    return path
