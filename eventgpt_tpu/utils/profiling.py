"""Profiling hooks: jax.profiler traces + host-readback-fenced timing.

The reference has no tracing/profiling at all (SURVEY.md §5 — Timer.h is an
unshipped external, nvtx a dep only). These are the TPU equivalents:

  * ``profile_trace(logdir)`` — context manager around ``jax.profiler`` so a
    training/inference region can be inspected in TensorBoard/XProf.
  * ``timed(fn)`` — wall-clock timing with a host-readback fence; plain
    ``block_until_ready`` is NOT a reliable fence on tunneled devices (see
    bench.py), so the fence sums the outputs to force completion.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Callable, Tuple


@contextlib.contextmanager
def profile_trace(logdir: str):
    """Capture a jax.profiler trace for the enclosed region."""
    import jax

    jax.profiler.start_trace(logdir, create_perfetto_link=False)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def _fence(x: Any) -> float:
    import jax
    import jax.numpy as jnp

    total = 0.0
    for leaf in jax.tree_util.tree_leaves(x):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.number):
            total += float(jnp.sum(leaf.astype(jnp.float32)))
    return total


def timed(fn: Callable, *args, iters: int = 10, warmup: int = 2) -> Tuple[float, Any]:
    """(seconds_per_iter, last_output) with compile excluded and a
    host-readback fence after the timed loop."""
    out = None
    for _ in range(max(warmup, 1)):
        out = fn(*args)
    _fence(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    _fence(out)
    return (time.perf_counter() - t0) / iters, out
