"""Checkpoint / resume: orbax for full sharded state, npz for components.

The reference's persistence story is HF ``from_pretrained`` plus raw
``torch.load`` partial checkpoints with key-prefix rewriting for the small
vision modules (``model/EventChatModel.py:124-163``, SURVEY.md §5
"Checkpoint / resume"); optimizer-state resume lived off-tree in DeepSpeed.
The TPU-native equivalent:

  * **Full checkpoints** (params, optimizer state, step) via orbax —
    sharded-array aware, multi-host safe, atomic.
  * **Component checkpoints** (projector / feature adaptor) as plain npz —
    small, portable artifacts mirroring the reference's stage-1 outputs, with
    the same prefix-rewrite semantics on load.
  * **HF import** lives in ``models/convert.py``; this module persists the
    converted trees so torch never enters the hot path again.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import numpy as np

Params = Dict[str, Any]


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def save_checkpoint(path: str, tree: Any) -> None:
    """Atomically save a pytree (params / TrainState fields) to ``path``."""
    ckptr = _checkpointer()
    ckptr.save(os.path.abspath(path), tree, force=True)
    ckptr.wait_until_finished()


def load_checkpoint(path: str, target: Optional[Any] = None) -> Any:
    """Restore a pytree. With ``target`` (a tree of like-shaped arrays —
    e.g. ``jax.eval_shape`` output placed on a mesh), arrays restore directly
    into the target's shardings; without it, arrays restore unsharded."""
    ckptr = _checkpointer()
    if target is None:
        return ckptr.restore(os.path.abspath(path))
    # Abstract target (shape/dtype/sharding skeleton) drives sharded restore.
    abstract = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(x, "sharding", None))
        if hasattr(x, "shape") else x,
        target,
    )
    return ckptr.restore(os.path.abspath(path), abstract)


# ---------------------------------------------------------------------------
# Component (partial) checkpoints — stage-1 artifacts


def _flatten(tree: Any, prefix: str = "") -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}." if not isinstance(v, (np.ndarray, jax.Array)) else f"{prefix}{k}"))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten(v, f"{prefix}{i}." if not isinstance(v, (np.ndarray, jax.Array)) else f"{prefix}{i}"))
    else:
        out[prefix.rstrip(".")] = np.asarray(tree)
    return out


def _unflatten(flat: Dict[str, np.ndarray]) -> Any:
    tree: Dict[str, Any] = {}
    for key, val in flat.items():
        parts = key.split(".")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = val

    def listify(node):
        if not isinstance(node, dict):
            return node
        keys = list(node.keys())
        if keys and all(k.isdigit() for k in keys):
            return [listify(node[str(i)]) for i in range(len(keys))]
        return {k: listify(v) for k, v in node.items()}

    return listify(tree)


def save_component(path: str, tree: Params, prefix: str = "") -> None:
    """Save a small module subtree (e.g. the projector) as one npz file.

    ``prefix`` is prepended to every key — the write-side analog of the
    reference's ``model.visual_projector.``-style prefixes.
    """
    flat = {prefix + k: v for k, v in _flatten(tree).items()}
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    np.savez(path, **flat)


def find_latest_checkpoint(output_dir: str) -> Optional[str]:
    """Most recent checkpoint under ``output_dir``, or None.

    The restart-after-failure recipe (``--resume_from auto``): a crashed or
    preempted run re-launches with the same command and continues from the
    last durable state — the TPU-era replacement for the reference stack's
    (absent) recovery story, SURVEY.md §5 "Failure detection".

    Ordering: the RECORDED STEP is the primary key — every save writes a
    ``STEP`` file inside the checkpoint dir (``trainer.save``), and for
    older dirs without one the ``ckpt_step{N}`` name supplies it
    (``ckpt_preempt_step{N}`` wins a tie at the same N since preemption
    strikes after the periodic save). mtime is only the arbiter BETWEEN
    checkpoints with no recorded step at all (legacy ``ckpt_last`` /
    ``ckpt_preempt``), and those never beat a step-recorded checkpoint —
    directory mtimes are synthetic on gcsfuse-style filesystems, fabricated
    by rsync/copies (ADVICE r2: a copied stale ckpt_last with a fresh mtime
    must not silently discard training), and resuming from a mis-ordered
    save silently loses work. Only COMPLETED checkpoint names are eligible:
    orbax writes in-progress saves to a sibling
    ``*.orbax-checkpoint-tmp-*`` directory, and a run killed mid-save must
    not hand that half-written state to the relaunch.
    """
    import re

    if not os.path.isdir(output_dir):
        return None

    def mtime(p):
        try:
            return os.path.getmtime(p)
        except OSError:
            return 0.0

    def recorded_step(p):
        try:
            with open(os.path.join(p, "STEP")) as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return None

    best_step = (-1, -1, None)  # (step, name-rank tiebreak, path)
    stepless = []
    for name in os.listdir(output_dir):
        path = os.path.join(output_dir, name)
        if not os.path.isdir(path):
            continue
        m = re.fullmatch(r"ckpt_(preempt_)?step(\d+)", name)
        named = re.fullmatch(r"ckpt_(last|preempt)", name)
        if not (m or named):
            continue
        step = recorded_step(path)
        if step is None and m:
            step = int(m.group(2))
        # Equal-step tiebreak by write order within a run: the preemption
        # save lands after the periodic save, and ckpt_last is a completed
        # run's final save after its last ckpt_step.
        if (m and m.group(1)) or name == "ckpt_preempt":
            rank = 2
        elif name == "ckpt_last":
            rank = 1
        else:
            rank = 0
        if step is not None:
            if (step, rank) > best_step[:2]:
                best_step = (step, rank, path)
        else:
            stepless.append(path)
    if best_step[2] is not None:
        return best_step[2]
    best = None
    for path in stepless:
        if best is None or mtime(path) > mtime(best):
            best = path
    return best


def load_component(path: str, strip_prefix: str = "") -> Params:
    """Load an npz component, rewriting keys by stripping ``strip_prefix`` —
    the semantics of the reference's partial ``torch.load`` +
    ``startswith/replace`` key surgery (``model/EventChatModel.py:124-139``).

    Keys that do not carry ``strip_prefix`` are rejected loudly (ADVICE r1:
    passing them through silently injects foreign leaves that only surface
    later as a tree-structure mismatch); the reference's startswith filter
    likewise ignores everything else.
    """
    with np.load(path) as data:
        flat = {}
        for k in data.files:
            if strip_prefix and not k.startswith(strip_prefix):
                raise ValueError(
                    f"component file {path} holds key {k!r} without the "
                    f"expected prefix {strip_prefix!r} — wrong artifact?"
                )
            flat[k[len(strip_prefix):] if strip_prefix else k] = data[k]
    return _unflatten(flat)
