"""Model-level token constants.

Parity with the reference's ``dataset/constants.py:7-13``. The LLaVA serving
heartbeat constants (``dataset/constants.py:1-4``) are deliberately dropped —
no controller/worker server ships in the reference and none is needed here.
"""

# Label value ignored by the cross-entropy loss (masked positions).
IGNORE_INDEX = -100

# Sentinel id spliced into ``input_ids`` where event features are inserted.
# Negative so it can never collide with a real vocabulary id.
EVENT_TOKEN_INDEX = -200

DEFAULT_EVENT_TOKEN = "<event>"
DEFAULT_EVENT_PATCH_TOKEN = "<ev_patch>"
DEFAULT_EV_START_TOKEN = "<ev_start>"
DEFAULT_EV_END_TOKEN = "<ev_end>"
EVENT_PLACEHOLDER = "<event-placeholder>"

# Input envelope of the reference pipeline (``common/common.py:114,118``):
# event streams are capped at 100 ms and rasterized into 5 frames.
MAX_EVENT_STREAM_US = 100_000
DEFAULT_NUM_EVENT_FRAMES = 5

# The ONE sequence-length grain for shape-stable compilation: training
# collation pads T to a multiple of this, serving buckets the KV cache
# length on it, and beam search aligns its gather bound to it. A single
# constant because the pieces interact — mesh_context must divide the
# collated T, and a sharded generate must agree with the trainer about
# padded shapes (VERDICT r2 weak #6).
SEQ_BUCKET = 64
