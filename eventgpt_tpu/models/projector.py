"""Event-feature projection stack: MLP projector + optional feature adaptor.

Parity with the reference stack: ``build_mlp_projector`` — Linear(1024->D),
then (GELU, Linear(D->D)) x (mlp_depth-1) (``model/EventChatModel.py:87-93``)
— and the Linear(D->D) ``feature_adaptor`` (``model/EventChatModel.py:75-76``).
GELU is torch's default (exact erf form).
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from eventgpt_tpu.config import ProjectorConfig

Params = Dict[str, Any]


def init_projector_params(cfg: ProjectorConfig, key: jax.Array, dtype=jnp.float32) -> Params:
    keys = jax.random.split(key, cfg.mlp_depth + 1)

    def linear(k, fan_in, fan_out):
        # torch nn.Linear default: U(-1/sqrt(fan_in), 1/sqrt(fan_in)) for both.
        bound = 1.0 / math.sqrt(fan_in)
        wk, bk = jax.random.split(k)
        return {
            "kernel": jax.random.uniform(wk, (fan_in, fan_out), dtype, -bound, bound),
            "bias": jax.random.uniform(bk, (fan_out,), dtype, -bound, bound),
        }

    layers = [linear(keys[0], cfg.input_dim, cfg.output_dim)]
    for i in range(1, cfg.mlp_depth):
        layers.append(linear(keys[i], cfg.output_dim, cfg.output_dim))
    params: Params = {"mlp": layers}
    if cfg.use_feature_adaptor:
        params["adaptor"] = linear(keys[-1], cfg.output_dim, cfg.output_dim)
    return params


def apply_projector(params: Params, features: jnp.ndarray) -> jnp.ndarray:
    """(..., input_dim) CLIP features -> (..., output_dim) LM-space features."""
    x = features
    for i, layer in enumerate(params["mlp"]):
        if i > 0:
            x = jax.nn.gelu(x, approximate=False)
        x = x @ layer["kernel"] + layer["bias"]
    return x


def apply_adaptor(params: Params, features: jnp.ndarray) -> jnp.ndarray:
    """Feature adaptor Linear; identity when the adaptor is disabled."""
    ad: Optional[Params] = params.get("adaptor")
    if ad is None:
        return features
    return features @ ad["kernel"] + ad["bias"]
