"""LLaMA/Vicuna decoder-only LM, TPU-first.

Functional JAX reimplementation of the reference's HF ``LlamaForCausalLM``
backbone (``model/EventChatModel.py:166-176``): RMSNorm, RoPE, GQA-capable
attention, SwiGLU MLP. Numerics match HF LLaMA.

TPU-first design (SURVEY.md §7):
  * layers stacked on a leading axis, driven by ``lax.scan`` — O(1) compile
    time in depth; the stacked axis shards cleanly under fsdp;
  * the decode path is split into three jit units — ``prefill`` (batched
    matmuls over the whole prompt, writes the KV cache) and ``decode_step``
    (one token, reads the HBM-resident cache) — mirroring the reference's
    one-shot multimodal embed + HF generate loop seam
    (``model/EventChatModel.py:296-297``, SURVEY.md §3.3);
  * f32 softmax/logit accumulation under bf16 params;
  * accepts ``inputs_embeds`` directly, because the multimodal path splices
    event features into the embedding sequence before the LM ever runs.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from eventgpt_tpu.config import LlamaConfig
from eventgpt_tpu.ops.quant import matmul as _mm, matmul_f32_out as _mm_f32

Params = Dict[str, Any]
KVCache = Dict[str, jnp.ndarray]  # {"k": [L,B,S,KV,hd], "v": [L,B,S,KV,hd], "length": [B]}


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float) -> jnp.ndarray:
    x32 = x.astype(jnp.float32)
    norm = x32 * lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (norm * weight.astype(jnp.float32)).astype(x.dtype)


def rope_tables(cfg: LlamaConfig, positions: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin tables for given positions: (..., head_dim) each, f32.

    HF convention: inv_freq over even indices, table is concat(freqs, freqs),
    rotation by rotate_half (split at head_dim/2).
    """
    hd = cfg.resolved_head_dim()
    inv_freq = 1.0 / (cfg.rope_theta ** (jnp.arange(0, hd, 2, dtype=jnp.float32) / hd))
    freqs = positions.astype(jnp.float32)[..., None] * inv_freq  # (..., hd/2)
    emb = jnp.concatenate([freqs, freqs], axis=-1)
    return jnp.cos(emb), jnp.sin(emb)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray) -> jnp.ndarray:
    """x: (B, S, H, hd); cos/sin: (B, S, hd) -> rotated x (HF rotate_half)."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    cos = cos[:, :, None, :].astype(x.dtype)
    sin = sin[:, :, None, :].astype(x.dtype)
    return x * cos + rotated * sin


def init_llama_params(cfg: LlamaConfig, key: jax.Array, dtype=jnp.float32) -> Params:
    d, i, l = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    hd = cfg.resolved_head_dim()
    qd, kvd = cfg.num_heads * hd, cfg.num_kv_heads * hd
    keys = jax.random.split(key, 8)

    def dense(k, fan_in, shape):
        return jax.random.normal(k, shape, dtype) * (1.0 / math.sqrt(fan_in))

    return {
        "embed_tokens": jax.random.normal(keys[0], (cfg.vocab_size, d), dtype) * 0.02,
        "layers": {
            "input_norm": jnp.ones((l, d), dtype),
            "attn": {
                "q": dense(keys[1], d, (l, d, qd)),
                "k": dense(keys[2], d, (l, d, kvd)),
                "v": dense(keys[3], d, (l, d, kvd)),
                "o": dense(keys[4], qd, (l, qd, d)),
            },
            "post_norm": jnp.ones((l, d), dtype),
            "mlp": {
                "gate": dense(keys[5], d, (l, d, i)),
                "up": dense(keys[6], d, (l, d, i)),
                "down": dense(keys[7], i, (l, i, d)),
            },
        },
        "final_norm": jnp.ones((d,), dtype),
        "lm_head": dense(keys[0], d, (d, cfg.vocab_size)),
    }


def embed_tokens(params: Params, input_ids: jnp.ndarray) -> jnp.ndarray:
    return params["embed_tokens"][input_ids]


def _remat_policy(cfg: LlamaConfig):
    """Map ``cfg.remat_policy`` onto a ``jax.checkpoint`` policy (ISSUE
    13 satellite — the stage-2 remat sweep). "full" is jax's default
    (save nothing, recompute every layer activation — the pre-sweep
    behavior, byte-identical HLO to passing no policy at all);
    "nothing_saveable" is the same semantics via the explicit policy
    object; "dots_saveable" (and the no-batch-dims variant) save matmul
    outputs, trading HBM for the ~19 TFLOP/step of stage-2 recompute
    full remat pays at 7B. Forward-only callers (serving) never hit the
    policy: it only shapes the backward pass."""
    name = getattr(cfg, "remat_policy", "full")
    if name == "full":
        return None
    return getattr(jax.checkpoint_policies, name)


def resize_token_embeddings(params: Params, new_vocab_size: int) -> Params:
    """Grow embed/lm_head rows, initializing new rows to the mean of old ones.

    Mirrors ``resize_token_embeddings`` + the mean-init of
    ``initialize_vision_tokenizer`` (``model/EventChatModel.py:202-212``,
    ``inference.py:39``). Shrinking truncates.
    """
    embed = params["embed_tokens"]
    head = params["lm_head"]
    old = embed.shape[0]
    if new_vocab_size <= old:
        return {**params, "embed_tokens": embed[:new_vocab_size],
                "lm_head": head[:, :new_vocab_size]}
    n_new = new_vocab_size - old
    embed_new = jnp.concatenate(
        [embed, jnp.broadcast_to(embed.mean(axis=0, keepdims=True), (n_new, embed.shape[1]))]
    )
    head_new = jnp.concatenate(
        [head, jnp.broadcast_to(head.mean(axis=1, keepdims=True), (head.shape[0], n_new))],
        axis=1,
    )
    return {**params, "embed_tokens": embed_new, "lm_head": head_new}


def fuse_llama_params(params: Params) -> Params:
    """Inference-time transform: concat q|k|v and gate|up along the output
    axis so each decode layer runs 5 weight matmuls instead of 7.

    The standard serving-stack transform (vLLM/TensorRT fuse qkv the same
    way). Measured on v5e batch-1 int8 decode it is perf-neutral (83.6 vs
    84.1 tok/s — XLA already pipelines the split dots at bandwidth), so it
    stays opt-in; it mainly helps wider batches and shorter layers. Fuse
    AFTER loading (and BEFORE quantization, so scales are computed on the
    fused tensor and stream with it). Not for training: LoRA targets
    address the unfused names.
    """
    import numpy as np

    layers = params["layers"]
    attn, mlp = layers["attn"], layers["mlp"]
    # Host (numpy) trees fuse on host — a jnp.concatenate here would pull
    # the whole 7B tree onto the device before quantization/sharding.
    xp = jnp if isinstance(attn["q"], jax.Array) else np
    fused = {
        **params,
        "layers": {
            **layers,
            "attn": {
                "qkv": xp.concatenate(
                    [attn["q"], attn["k"], attn["v"]], axis=-1
                ),
                "o": attn["o"],
            },
            "mlp": {
                "gate_up": xp.concatenate(
                    [mlp["gate"], mlp["up"]], axis=-1
                ),
                "down": mlp["down"],
            },
        },
    }
    return fused


def _project_qkv(cfg: LlamaConfig, y: jnp.ndarray, layer: Params):
    """y (B, T, D) -> (q, k, v) pre-RoPE, honoring fused or split leaves.
    q: (B, T, H*hd); k/v: (B, T, KV, hd)."""
    b, t, _ = y.shape
    hd = cfg.resolved_head_dim()
    qd, kvd = cfg.num_heads * hd, cfg.num_kv_heads * hd
    attn = layer["attn"]
    if "qkv" in attn:
        qkv = _mm(y, attn["qkv"])
        q, k, v = qkv[..., :qd], qkv[..., qd:qd + kvd], qkv[..., qd + kvd:]
    else:
        q = _mm(y, attn["q"])
        k = _mm(y, attn["k"])
        v = _mm(y, attn["v"])
    return q, k.reshape(b, t, cfg.num_kv_heads, hd), v.reshape(b, t, cfg.num_kv_heads, hd)


def _repeat_kv(x: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """(B, S, KV, hd) -> (B, S, KV*n_rep, hd), GQA head replication."""
    if n_rep == 1:
        return x
    b, s, kv, hd = x.shape
    return jnp.broadcast_to(x[:, :, :, None, :], (b, s, kv, n_rep, hd)).reshape(b, s, kv * n_rep, hd)


def _attn_block(cfg: LlamaConfig, q_proj: jnp.ndarray, layer: Params,
                cos: jnp.ndarray, sin: jnp.ndarray,
                k_full: jnp.ndarray, v_full: jnp.ndarray,
                mask: Optional[jnp.ndarray] = None,
                valid: Optional[jnp.ndarray] = None,
                use_flash: bool = False,
                ring_fn=None,
                flash_fn=None) -> jnp.ndarray:
    """Shared attention plumbing (RoPE on the precomputed q projection + GQA
    repeat + o proj) with a score-computation switch: dense additive ``mask``
    (B,1,Q,S), the Pallas flash kernel with a (B,S) ``valid`` padding mask
    (causal implied), a ring-attention shard_map ``ring_fn`` for sequence
    parallelism over the ``context`` mesh axis, or a serving-mesh flash
    shard_map ``flash_fn`` (``parallel/serving.py:serving_flash_shard_map``).
    q_proj: (B,Q,H*hd) from ``_project_qkv`` (possibly a fused-qkv slice);
    k/v_full: (B,S,KV,hd)."""
    b, q_len, _ = q_proj.shape
    h, kvh, hd = cfg.num_heads, cfg.num_kv_heads, cfg.resolved_head_dim()

    q = q_proj.reshape(b, q_len, h, hd)
    q = apply_rope(q, cos, sin)
    if ring_fn is not None and getattr(ring_fn, "accepts_unrepeated_kv", False):
        # Ulysses repeats GQA heads AFTER its all-to-all — the exchange
        # moves KV-count bytes, not H-count (ADVICE r2).
        ctx = ring_fn(q, k_full, v_full, valid, valid).reshape(b, q_len, h * hd)
        return _mm(ctx, layer["attn"]["o"])
    k = _repeat_kv(k_full, h // kvh)
    v = _repeat_kv(v_full, h // kvh)

    if ring_fn is not None:
        ctx = ring_fn(q, k, v, valid, valid).reshape(b, q_len, h * hd)
    elif flash_fn is not None:
        ctx = flash_fn(q, k, v, valid).reshape(b, q_len, h * hd)
    elif use_flash:
        from eventgpt_tpu.ops.flash_attention import flash_attention

        ctx = flash_attention(q, k, v, valid=valid, causal=True)
        ctx = ctx.reshape(b, q_len, h * hd)
    else:
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
        scores = scores * (1.0 / math.sqrt(hd)) + mask
        probs = jax.nn.softmax(scores, axis=-1).astype(q_proj.dtype)
        ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(b, q_len, h * hd)
    return _mm(ctx, layer["attn"]["o"])


def _mlp_block(x: jnp.ndarray, layer: Params) -> jnp.ndarray:
    mlp = layer["mlp"]
    if "gate_up" in mlp:
        gu = _mm(x, mlp["gate_up"])
        i = gu.shape[-1] // 2
        gate, up = gu[..., :i], gu[..., i:]
    else:
        gate, up = _mm(x, mlp["gate"]), _mm(x, mlp["up"])
    return _mm(jax.nn.silu(gate) * up, mlp["down"])


def init_kv_cache(cfg: LlamaConfig, batch: int, max_len: int, dtype=jnp.bfloat16,
                  quant: bool = False) -> KVCache:
    """KV cache buffers. ``quant=True`` stores int8 payloads with one f32
    scale per (layer, row, position, head) — half the HBM footprint and
    stream bandwidth of bf16 (the cache is the dominant batched-decode
    allocation: 369 MB/row at 7B)."""
    hd = cfg.resolved_head_dim()
    shape = (cfg.num_layers, batch, max_len, cfg.num_kv_heads, hd)
    if quant:
        def qbuf():
            return {"q": jnp.zeros(shape, jnp.int8),
                    "s": jnp.zeros(shape[:-1] + (1,), jnp.float32)}

        return {"k": qbuf(), "v": qbuf(),
                "length": jnp.zeros((batch,), jnp.int32)}
    return {
        "k": jnp.zeros(shape, dtype),
        "v": jnp.zeros(shape, dtype),
        "length": jnp.zeros((batch,), jnp.int32),
    }


def init_paged_kv_cache(cfg: LlamaConfig, batch: int, max_len: int,
                        n_blocks: int, block_size: int,
                        dtype=jnp.bfloat16, quant: bool = False) -> KVCache:
    """Paged KV cache (ISSUE 12): ONE static block-pool arena per plane —
    (L, n_blocks, block_size, KV, hd) — plus a per-row int32 block table
    ``bt`` (batch, max_len // block_size). Rows no longer own dense
    ``max_len`` runs: logical position ``p`` of row ``r`` lives at pool
    slot ``(bt[r, p // bs], p % bs)``, so resident bytes scale with the
    blocks actually reserved, not ``batch × max_len``. Every shape stays
    static for XLA; the dynamic part (which block backs which row) is
    host bookkeeping (``serve_blocks.BlockPool``). Tables start at block
    0 — the pool's reserved scratch block — so an unadmitted row's
    unconditional frozen writes land in storage nothing reads."""
    if max_len % block_size:
        raise ValueError(
            f"max_len {max_len} must be a block_size {block_size} multiple")
    hd = cfg.resolved_head_dim()
    shape = (cfg.num_layers, n_blocks, block_size, cfg.num_kv_heads, hd)
    if quant:
        def qbuf():
            return {"q": jnp.zeros(shape, jnp.int8),
                    "s": jnp.zeros(shape[:-1] + (1,), jnp.float32)}

        k, v = qbuf(), qbuf()
    else:
        k, v = jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)
    return {
        "k": k,
        "v": v,
        "bt": jnp.zeros((batch, max_len // block_size), jnp.int32),
        "length": jnp.zeros((batch,), jnp.int32),
    }


def _kv_is_quant(cache: KVCache) -> bool:
    return isinstance(cache["k"], dict)


def _kv_is_paged(cache: KVCache) -> bool:
    return "bt" in cache


def _kv_max_len(cache: KVCache) -> int:
    """Logical per-row KV capacity: dense reads it off the buffer's slot
    axis; paged, off the block table (rows × blocks-per-row view)."""
    buf = cache["k"]["q"] if _kv_is_quant(cache) else cache["k"]
    if _kv_is_paged(cache):
        return cache["bt"].shape[1] * buf.shape[2]
    return buf.shape[2]


def _cache_write(buf, li, batch_idx, slots, vals, quant: bool, bt=None):
    """Write new K/V rows into layer ``li`` of a cache buffer — THE cache
    write for both decode paths, so the bf16-vs-int8 handling cannot drift
    between them. ``slots`` (B,) writes one slot per row (decode_step's hot
    loop — lowers to an in-place dynamic-update-slice); (B, K) writes a
    verification window per row (decode_kstep — a scatter). ``vals`` has a
    matching leading shape + (KV, hd).

    ``bt`` (paged cache): logical slots translate through the row's block
    table to (pool block, offset) pairs. Values written are identical to
    the dense path's — the translation is pure indexing — which is what
    keeps paged chains byte-identical to dense ones. Writable blocks are
    exclusively owned by construction (copy-on-write in the serving
    allocator), so the scatter indices of live rows never collide; frozen
    rows' garbage writes all land in the shared scratch block, whose
    content no attention read ever sees (masked above ``length``)."""
    if bt is not None:
        bs = (buf["q"] if quant else buf).shape[2]
        blk = slots // bs
        off = slots % bs
        blocks = (bt[batch_idx, blk] if slots.ndim == 1
                  else bt[batch_idx[:, None], blk])
        if quant:
            qs = _kv_quantize(vals)
            return {"q": buf["q"].at[li, blocks, off].set(qs["q"]),
                    "s": buf["s"].at[li, blocks, off].set(qs["s"])}
        return buf.at[li, blocks, off].set(vals.astype(buf.dtype))
    idx = batch_idx if slots.ndim == 1 else batch_idx[:, None]
    if quant:
        qs = _kv_quantize(vals)
        return {"q": buf["q"].at[li, idx, slots].set(qs["q"]),
                "s": buf["s"].at[li, idx, slots].set(qs["s"])}
    return buf.at[li, idx, slots].set(vals.astype(buf.dtype))


def _cache_read_layer(buf, li, dtype, quant: bool, bt=None):
    """Layer ``li`` of a cache buffer as (B, S, KV, hd) in ``dtype``. For the
    int8 cache the dequant fuses into the attention einsum's operand reads:
    HBM streams int8 payloads + 1/hd scales instead of bf16.

    ``bt`` (paged cache): the pure-jnp gather fallback — pool blocks
    gather through the block table into the same (B, S, KV, hd) view the
    dense path reads (S = blocks_per_row × block_size), so the attention
    math downstream is untouched and bitwise identical (a gather is a
    copy). The view is a per-layer TEMPORARY — 1/L of the dense cache's
    residency — not a resident buffer; the paged Pallas kernel
    (``ops/decode_attention.decode_attention_int8_paged``) computes
    attention block-by-block without materializing it at all, and is the
    TPU wiring for this seam."""
    if bt is not None:
        b, nbpr = bt.shape
        if quant:
            lq = lax.dynamic_index_in_dim(buf["q"], li, keepdims=False)[bt]
            ls = lax.dynamic_index_in_dim(buf["s"], li, keepdims=False)[bt]
            x = _kv_dequant({"q": lq, "s": ls}, dtype)
        else:
            x = lax.dynamic_index_in_dim(buf, li, keepdims=False)[bt]
            x = x.astype(dtype)
        # (B, nbpr, bs, KV, hd) -> (B, nbpr * bs, KV, hd)
        return x.reshape(b, nbpr * x.shape[2], x.shape[3], x.shape[4])
    if quant:
        leaf = {"q": lax.dynamic_index_in_dim(buf["q"], li, keepdims=False),
                "s": lax.dynamic_index_in_dim(buf["s"], li, keepdims=False)}
        return _kv_dequant(leaf, dtype)
    return lax.dynamic_index_in_dim(buf, li, keepdims=False).astype(dtype)


def _kv_quantize(x: jnp.ndarray) -> Dict[str, jnp.ndarray]:
    """(..., hd) -> {"q": int8, "s": f32 (..., 1)}; symmetric per-vector."""
    x32 = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x32), axis=-1, keepdims=True)
    s = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x32 / s), -127, 127).astype(jnp.int8)
    return {"q": q, "s": s}


def _kv_dequant(leaf: Dict[str, jnp.ndarray], dtype) -> jnp.ndarray:
    return (leaf["q"].astype(jnp.float32) * leaf["s"]).astype(dtype)


def prefill(
    params: Params,
    cfg: LlamaConfig,
    inputs_embeds: jnp.ndarray,
    attention_mask: jnp.ndarray,
    cache: KVCache,
    last_only: bool = False,
    mesh=None,
    return_hidden: bool = False,
) -> Tuple[jnp.ndarray, KVCache]:
    """Run the full prompt; returns (logits, filled cache).

    ``attention_mask`` is bool (B, T): True = real token, False = right pad.
    The prompt occupies cache slots [0, T); cache["length"] records the true
    per-row prompt length for the decode phase.

    ``last_only=False`` -> logits (B, T, V) (training/eval). ``last_only=True``
    -> logits (B, V) at each row's final real token — the only position
    ``generate`` consumes; skipping the other T-1 lm_head columns saves
    T x vocab f32 per row (0.66 GB at B=8, S=640).

    ``attn_impl == "ring"`` (or ``"ulysses"``) with a ``mesh`` whose
    ``context`` axis is > 1 runs sequence-parallel attention: ring rotates
    KV blocks via ppermute (``parallel/ring.py``); ulysses re-shards
    sequence<->heads with two all-to-alls and runs full-sequence local
    attention (``parallel/ulysses.py``; local heads must divide by the
    context size). T must divide the context axis size. Both fall back to
    dense on a context-1 mesh.
    """
    if _kv_is_paged(cache):
        # Serving never prefills into the pool directly: admission
        # prefills a dense per-request row cache and SCATTERS it into
        # allocated blocks (serve._admit_row_paged) — the seam that
        # keeps one prefill executable per bucket, pool-size-agnostic.
        raise ValueError(
            "prefill writes dense caches; scatter into a paged pool via "
            "the serving admission path")
    b, t, d = inputs_embeds.shape
    positions = jnp.cumsum(attention_mask.astype(jnp.int32), axis=1) - 1
    positions = jnp.maximum(positions, 0)
    cos, sin = rope_tables(cfg, positions)

    ring_fn = None
    flash_fn = None
    if mesh is not None and mesh.shape.get("context", 1) > 1:
        if cfg.attn_impl == "ring":
            from eventgpt_tpu.parallel.ring import ring_attention_shard_map

            ring_fn = ring_attention_shard_map(mesh, causal=True)
        elif cfg.attn_impl == "ulysses":
            from eventgpt_tpu.parallel.ulysses import ulysses_attention_shard_map

            ring_fn = ulysses_attention_shard_map(mesh, causal=True)
    elif mesh is not None and cfg.attn_impl == "flash":
        # Serving mesh (context=1): flash runs per-shard under shard_map —
        # batch over (data, fsdp), heads over model (the bare Pallas call is
        # opaque to GSPMD and would all-gather every operand).
        from eventgpt_tpu.parallel.serving import serving_flash_shard_map

        flash_fn = serving_flash_shard_map(mesh, b, num_heads=cfg.num_heads)
    use_flash = cfg.attn_impl == "flash" and flash_fn is None
    if use_flash or ring_fn is not None or flash_fn is not None:
        mask = None  # causal + padding masks applied inline
    else:
        causal = jnp.tril(jnp.ones((t, t), bool))
        visible = causal[None, None] & attention_mask[:, None, None, :]
        mask = jnp.where(visible, 0.0, jnp.finfo(jnp.float32).min)

    x = inputs_embeds

    def block(carry, xs):
        layer, = xs
        h_in = carry
        y = rms_norm(h_in, layer["input_norm"], cfg.rms_norm_eps)
        q_proj, k, v = _project_qkv(cfg, y, layer)
        k = apply_rope(k, cos, sin)
        h_mid = h_in + _attn_block(cfg, q_proj, layer, cos, sin, k, v,
                                   mask=mask, valid=attention_mask,
                                   use_flash=use_flash, ring_fn=ring_fn,
                                   flash_fn=flash_fn)
        y2 = rms_norm(h_mid, layer["post_norm"], cfg.rms_norm_eps)
        h_out = h_mid + _mlp_block(y2, layer)
        return h_out, (k, v)

    block_fn = (jax.checkpoint(block, prevent_cse=False,
                               policy=_remat_policy(cfg))
                if cfg.remat else block)
    x, (k_all, v_all) = lax.scan(block_fn, x, (params["layers"],))

    # In-place slot write (aliases the donated cache buffers; jnp.pad here
    # would materialize a second full-size cache copy).
    lengths = attention_mask.astype(jnp.int32).sum(axis=1)

    def write(buf, vals):
        if isinstance(buf, dict):  # int8 cache: quantize the new slots
            qs = _kv_quantize(vals)
            return {"q": buf["q"].at[:, :, :t].set(qs["q"]),
                    "s": buf["s"].at[:, :, :t].set(qs["s"])}
        return buf.at[:, :, :t].set(vals.astype(buf.dtype))

    new_cache = {
        "k": write(cache["k"], k_all),
        "v": write(cache["v"], v_all),
        "length": lengths,
    }
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    # return_hidden uniformly appends the final-norm hidden as a THIRD
    # element — (B, D) at the last real token with last_only, (B, T, D)
    # otherwise (Medusa head seeding / training, models/medusa.py). A
    # caller that ignores unused outputs pays nothing: XLA dead-code
    # eliminates the lm_head matmul when only the hidden is consumed.
    if last_only:
        last = jnp.take_along_axis(
            x, jnp.maximum(lengths - 1, 0)[:, None, None], axis=1
        )[:, 0]  # (B, D)
        if return_hidden:
            return _mm_f32(last, params["lm_head"]), last, new_cache
        return _mm_f32(last, params["lm_head"]), new_cache
    logits = _mm_f32(x, params["lm_head"])
    if return_hidden:
        return logits, x, new_cache
    return logits, new_cache


def decode_step(
    params: Params,
    cfg: LlamaConfig,
    token_embeds: jnp.ndarray,
    cache: KVCache,
) -> Tuple[jnp.ndarray, KVCache]:
    """One decode step. token_embeds: (B, 1, D). Returns (logits [B, V], cache).

    The new token lands at slot ``cache["length"]`` with position id equal to
    the number of real tokens so far (right-pad-free positions).
    """
    b = token_embeds.shape[0]
    max_len = _kv_max_len(cache)
    pos = cache["length"]  # (B,)
    cos, sin = rope_tables(cfg, pos[:, None])

    slot = pos  # write index per batch row
    valid = jnp.arange(max_len)[None, :] <= slot[:, None]  # (B, S) incl. new slot
    mask = jnp.where(valid[:, None, None, :], 0.0, jnp.finfo(jnp.float32).min)

    batch_idx = jnp.arange(b)
    quant = _kv_is_quant(cache)
    bt = cache.get("bt")  # paged: logical->pool block translation

    # The cache rides the scan as CARRY (not xs/ys): XLA aliases carry
    # buffers across iterations, so the (B,)-slot _cache_write lowers to an
    # in-place one-slot dynamic-update-slice. The previous xs/ys form
    # restacked the full (L, B, S, KV, hd) k and v buffers every decode
    # step — ~800 MB of pure copy traffic per token at 7B/S=768, measured
    # ~2 ms/token.
    def block(carry, xs):
        h_in, k_buf, v_buf = carry
        layer, li = xs
        y = rms_norm(h_in, layer["input_norm"], cfg.rms_norm_eps)
        q_proj, k_new, v_new = _project_qkv(cfg, y, layer)
        k_new = apply_rope(k_new, cos, sin)
        k_buf = _cache_write(k_buf, li, batch_idx, slot, k_new[:, 0], quant,
                             bt=bt)
        v_buf = _cache_write(v_buf, li, batch_idx, slot, v_new[:, 0], quant,
                             bt=bt)
        h_mid = h_in + _attn_block(cfg, q_proj, layer, cos, sin,
                                   _cache_read_layer(k_buf, li, h_in.dtype,
                                                     quant, bt=bt),
                                   _cache_read_layer(v_buf, li, h_in.dtype,
                                                     quant, bt=bt),
                                   mask)
        y2 = rms_norm(h_mid, layer["post_norm"], cfg.rms_norm_eps)
        h_out = h_mid + _mlp_block(y2, layer)
        return (h_out, k_buf, v_buf), None

    (x, k_all, v_all), _ = lax.scan(
        block, (token_embeds, cache["k"], cache["v"]),
        (params["layers"], jnp.arange(cfg.num_layers)),
    )
    new_cache = {"k": k_all, "v": v_all, "length": cache["length"] + 1}
    if bt is not None:
        new_cache["bt"] = bt
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    logits = _mm_f32(x[:, 0], params["lm_head"])
    return logits, new_cache


def decode_kstep(
    params: Params,
    cfg: LlamaConfig,
    token_embeds: jnp.ndarray,
    cache: KVCache,
    return_hidden: bool = False,
) -> Tuple[jnp.ndarray, KVCache]:
    """K-token verification step for speculative decoding.

    token_embeds: (B, K, D) — a window of candidate tokens appended after the
    cache contents. Returns (logits (B, K, V) f32, cache with K slots written
    and length advanced by K). The caller commits a prefix of the window by
    rolling ``length`` back to ``old_length + accepted`` — slots above
    ``length`` are masked out of every future attention read and are
    overwritten by the next window, so partial acceptance needs no undo.

    Query i sits at global position length+i and sees cache slots
    [0, length+i] — exactly what ``decode_step`` would have seen feeding the
    window one token at a time, so greedy argmax over these logits equals the
    sequential greedy chain (the speculative path's correctness contract).
    Weight streaming is the decode bottleneck (PERFORMANCE.md): the K-row
    GEMMs read the same bytes as one decode_step, which is why verifying K
    tokens costs ~one token's wall time at batch 1.
    """
    b, kq, _ = token_embeds.shape
    max_len = _kv_max_len(cache)
    base = cache["length"]  # (B,) tokens already cached
    offs = jnp.arange(kq)
    pos = base[:, None] + offs[None, :]  # (B, K) global positions
    cos, sin = rope_tables(cfg, pos)

    # Query i attends to slots [0, base+i] (its own slot included).
    valid = jnp.arange(max_len)[None, None, :] <= pos[:, :, None]  # (B, K, S)
    mask = jnp.where(valid[:, None], 0.0, jnp.finfo(jnp.float32).min)  # (B,1,K,S)

    batch_idx = jnp.arange(b)
    quant = _kv_is_quant(cache)
    bt = cache.get("bt")  # paged: logical->pool block translation

    def block(carry, xs):
        h_in, k_buf, v_buf = carry
        layer, li = xs
        y = rms_norm(h_in, layer["input_norm"], cfg.rms_norm_eps)
        q_proj, k_new, v_new = _project_qkv(cfg, y, layer)
        k_new = apply_rope(k_new, cos, sin)
        k_buf = _cache_write(k_buf, li, batch_idx, pos, k_new, quant, bt=bt)
        v_buf = _cache_write(v_buf, li, batch_idx, pos, v_new, quant, bt=bt)
        h_mid = h_in + _attn_block(cfg, q_proj, layer, cos, sin,
                                   _cache_read_layer(k_buf, li, h_in.dtype,
                                                     quant, bt=bt),
                                   _cache_read_layer(v_buf, li, h_in.dtype,
                                                     quant, bt=bt),
                                   mask)
        y2 = rms_norm(h_mid, layer["post_norm"], cfg.rms_norm_eps)
        h_out = h_mid + _mlp_block(y2, layer)
        return (h_out, k_buf, v_buf), None

    (x, k_all, v_all), _ = lax.scan(
        block, (token_embeds, cache["k"], cache["v"]),
        (params["layers"], jnp.arange(cfg.num_layers)),
    )
    new_cache = {"k": k_all, "v": v_all, "length": cache["length"] + kq}
    if bt is not None:
        new_cache["bt"] = bt
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    logits = _mm_f32(x, params["lm_head"])  # (B, K, V)
    if return_hidden:
        # Per-window-position final-norm hidden: the Medusa draft path
        # selects the correction position's hidden to seed the next
        # window's drafts (models/eventchat._spec_draft_verify).
        return logits, x, new_cache
    return logits, new_cache


def forward(
    params: Params,
    cfg: LlamaConfig,
    inputs_embeds: jnp.ndarray,
    attention_mask: Optional[jnp.ndarray] = None,
    mesh=None,
) -> jnp.ndarray:
    """Cache-free full forward -> logits (B, T, V). Training / eval path.
    The cache written by prefill is unused here and DCE'd by XLA."""
    b, t, _ = inputs_embeds.shape
    if attention_mask is None:
        attention_mask = jnp.ones((b, t), bool)
    cache = init_kv_cache(cfg, b, t, dtype=inputs_embeds.dtype)
    logits, _ = prefill(params, cfg, inputs_embeds, attention_mask, cache,
                        mesh=mesh)
    return logits
