"""HF checkpoint -> JAX param-tree conversion.

Converts PyTorch EventChat/LLaMA/CLIP state dicts into this framework's
stacked-layer pytrees. Understands the reference's checkpoint layout, where
the vision tower and projector live inside the LLM state dict under the
prefixes established at ``model/EventChatModel.py:72-76,128-161``:

  model.visual_tower.visual_tower.vision_model.*   (HF CLIPVisionModel)
  model.visual_projector.{0,2}.{weight,bias}        (nn.Sequential MLP)
  model.feature_adaptor.{weight,bias}
  model.layers.* / model.embed_tokens / model.norm / lm_head  (HF LLaMA)

Also reads the reference's *partial* component checkpoints (raw torch.load
files holding just projector/adaptor weights, ``model/EventChatModel.py:
124-139``) so stage-1 artifacts can be imported directly.

All functions take/return numpy-backed dicts; torch is only touched inside
the file loaders so converted checkpoints can be cached as orbax and torch
never enters the TPU hot path.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import numpy as np

from eventgpt_tpu.config import EventChatConfig, LlamaConfig, VisionConfig

StateDict = Dict[str, np.ndarray]
Params = Dict[str, Any]


def _t(x: np.ndarray) -> np.ndarray:
    """torch Linear stores (out, in); JAX matmul kernels want (in, out)."""
    return np.ascontiguousarray(x.T)


def clip_params_from_hf(sd: StateDict, cfg: VisionConfig, prefix: str = "vision_model.") -> Params:
    g = lambda k: np.asarray(sd[prefix + k])
    d = cfg.hidden_size

    patch = g("embeddings.patch_embedding.weight")  # (D, C, P, P)
    patch = patch.reshape(d, -1).T  # -> (C*P*P, D), (c,i,j) flatten order

    def stack(fmt, transpose=False):
        rows = [np.asarray(sd[prefix + fmt.format(i)]) for i in range(cfg.num_layers)]
        return np.stack([_t(r) if transpose else r for r in rows])

    return {
        "embeddings": {
            "class_embedding": g("embeddings.class_embedding"),
            "patch_embedding": patch,
            "position_embedding": g("embeddings.position_embedding.weight"),
        },
        # sic: HF spells it "pre_layrnorm".
        "pre_layernorm": {"scale": g("pre_layrnorm.weight"), "bias": g("pre_layrnorm.bias")},
        "layers": {
            "ln1": {
                "scale": stack("encoder.layers.{}.layer_norm1.weight"),
                "bias": stack("encoder.layers.{}.layer_norm1.bias"),
            },
            "attn": {
                "q": {"kernel": stack("encoder.layers.{}.self_attn.q_proj.weight", True),
                      "bias": stack("encoder.layers.{}.self_attn.q_proj.bias")},
                "k": {"kernel": stack("encoder.layers.{}.self_attn.k_proj.weight", True),
                      "bias": stack("encoder.layers.{}.self_attn.k_proj.bias")},
                "v": {"kernel": stack("encoder.layers.{}.self_attn.v_proj.weight", True),
                      "bias": stack("encoder.layers.{}.self_attn.v_proj.bias")},
                "o": {"kernel": stack("encoder.layers.{}.self_attn.out_proj.weight", True),
                      "bias": stack("encoder.layers.{}.self_attn.out_proj.bias")},
            },
            "ln2": {
                "scale": stack("encoder.layers.{}.layer_norm2.weight"),
                "bias": stack("encoder.layers.{}.layer_norm2.bias"),
            },
            "mlp": {
                "fc1": {"kernel": stack("encoder.layers.{}.mlp.fc1.weight", True),
                        "bias": stack("encoder.layers.{}.mlp.fc1.bias")},
                "fc2": {"kernel": stack("encoder.layers.{}.mlp.fc2.weight", True),
                        "bias": stack("encoder.layers.{}.mlp.fc2.bias")},
            },
        },
        "post_layernorm": {"scale": g("post_layernorm.weight"), "bias": g("post_layernorm.bias")},
    }


def llama_params_from_hf(sd: StateDict, cfg: LlamaConfig, prefix: str = "model.") -> Params:
    def stack(fmt):
        return np.stack([_t(np.asarray(sd[prefix + fmt.format(i)])) for i in range(cfg.num_layers)])

    def stack_norm(fmt):
        return np.stack([np.asarray(sd[prefix + fmt.format(i)]) for i in range(cfg.num_layers)])

    embed = np.asarray(sd[prefix + "embed_tokens.weight"])
    if "lm_head.weight" in sd:
        lm_head = _t(np.asarray(sd["lm_head.weight"]))
    else:  # tied embeddings
        lm_head = _t(embed)

    return {
        "embed_tokens": embed,
        "layers": {
            "input_norm": stack_norm("layers.{}.input_layernorm.weight"),
            "attn": {
                "q": stack("layers.{}.self_attn.q_proj.weight"),
                "k": stack("layers.{}.self_attn.k_proj.weight"),
                "v": stack("layers.{}.self_attn.v_proj.weight"),
                "o": stack("layers.{}.self_attn.o_proj.weight"),
            },
            "post_norm": stack_norm("layers.{}.post_attention_layernorm.weight"),
            "mlp": {
                "gate": stack("layers.{}.mlp.gate_proj.weight"),
                "up": stack("layers.{}.mlp.up_proj.weight"),
                "down": stack("layers.{}.mlp.down_proj.weight"),
            },
        },
        "final_norm": np.asarray(sd[prefix + "norm.weight"]),
        "lm_head": lm_head,
    }


def projector_params_from_hf(sd: StateDict, mlp_depth: int = 2,
                             prefix: str = "model.visual_projector.",
                             adaptor_prefix: Optional[str] = "model.feature_adaptor.") -> Params:
    """Sequential [Linear, GELU, Linear, ...] -> our layer list (index 2j)."""
    layers = []
    for j in range(mlp_depth):
        layers.append({
            "kernel": _t(np.asarray(sd[f"{prefix}{2 * j}.weight"])),
            "bias": np.asarray(sd[f"{prefix}{2 * j}.bias"]),
        })
    params: Params = {"mlp": layers}
    if adaptor_prefix is not None and adaptor_prefix + "weight" in sd:
        params["adaptor"] = {
            "kernel": _t(np.asarray(sd[adaptor_prefix + "weight"])),
            "bias": np.asarray(sd[adaptor_prefix + "bias"]),
        }
    return params


def eventchat_params_from_hf(sd: StateDict, cfg: EventChatConfig) -> Params:
    """Full EventChat_llama state dict -> {clip, projector, llama} pytree."""
    # A qformer-gated config converts its base model normally; Q-Former
    # weights never live inside released LM state dicts (the reference loads
    # them through per-component torch.load hooks, model/EventChatModel.py:
    # 141-163) — callers init/load them separately (cli/infer.py,
    # models/qformer.py:load_qformer_components).
    return {
        "clip": clip_params_from_hf(
            sd, cfg.vision, prefix="model.visual_tower.visual_tower.vision_model."
        ),
        "projector": projector_params_from_hf(sd, cfg.projector.mlp_depth),
        "llama": llama_params_from_hf(sd, cfg.llama, prefix="model."),
    }


# ---------------------------------------------------------------------------
# JAX -> HF export (inverse of the readers above; used to publish checkpoints
# a reference-stack user can load, and to synthesize real-format checkpoint
# directories in tests)


def clip_params_to_hf(params: Params, cfg: VisionConfig,
                      prefix: str = "vision_model.") -> StateDict:
    sd: StateDict = {}
    emb = params["embeddings"]
    d = cfg.hidden_size
    sd[prefix + "embeddings.class_embedding"] = np.asarray(emb["class_embedding"])
    sd[prefix + "embeddings.patch_embedding.weight"] = np.ascontiguousarray(
        np.asarray(emb["patch_embedding"]).T
    ).reshape(d, cfg.num_channels, cfg.patch_size, cfg.patch_size)
    sd[prefix + "embeddings.position_embedding.weight"] = np.asarray(emb["position_embedding"])
    sd[prefix + "pre_layrnorm.weight"] = np.asarray(params["pre_layernorm"]["scale"])
    sd[prefix + "pre_layrnorm.bias"] = np.asarray(params["pre_layernorm"]["bias"])
    L = params["layers"]
    pairs = [
        ("layer_norm1.weight", L["ln1"]["scale"], False),
        ("layer_norm1.bias", L["ln1"]["bias"], False),
        ("self_attn.q_proj.weight", L["attn"]["q"]["kernel"], True),
        ("self_attn.q_proj.bias", L["attn"]["q"]["bias"], False),
        ("self_attn.k_proj.weight", L["attn"]["k"]["kernel"], True),
        ("self_attn.k_proj.bias", L["attn"]["k"]["bias"], False),
        ("self_attn.v_proj.weight", L["attn"]["v"]["kernel"], True),
        ("self_attn.v_proj.bias", L["attn"]["v"]["bias"], False),
        ("self_attn.out_proj.weight", L["attn"]["o"]["kernel"], True),
        ("self_attn.out_proj.bias", L["attn"]["o"]["bias"], False),
        ("layer_norm2.weight", L["ln2"]["scale"], False),
        ("layer_norm2.bias", L["ln2"]["bias"], False),
        ("mlp.fc1.weight", L["mlp"]["fc1"]["kernel"], True),
        ("mlp.fc1.bias", L["mlp"]["fc1"]["bias"], False),
        ("mlp.fc2.weight", L["mlp"]["fc2"]["kernel"], True),
        ("mlp.fc2.bias", L["mlp"]["fc2"]["bias"], False),
    ]
    for i in range(cfg.num_layers):
        for name, stacked, transpose in pairs:
            row = np.asarray(stacked[i])
            sd[f"{prefix}encoder.layers.{i}.{name}"] = _t(row) if transpose else row
    sd[prefix + "post_layernorm.weight"] = np.asarray(params["post_layernorm"]["scale"])
    sd[prefix + "post_layernorm.bias"] = np.asarray(params["post_layernorm"]["bias"])
    return sd


def llama_params_to_hf(params: Params, cfg: LlamaConfig, prefix: str = "model.") -> StateDict:
    sd: StateDict = {}
    sd[prefix + "embed_tokens.weight"] = np.asarray(params["embed_tokens"])
    L = params["layers"]
    names = [
        ("layers.{}.input_layernorm.weight", L["input_norm"], False),
        ("layers.{}.self_attn.q_proj.weight", L["attn"]["q"], True),
        ("layers.{}.self_attn.k_proj.weight", L["attn"]["k"], True),
        ("layers.{}.self_attn.v_proj.weight", L["attn"]["v"], True),
        ("layers.{}.self_attn.o_proj.weight", L["attn"]["o"], True),
        ("layers.{}.post_attention_layernorm.weight", L["post_norm"], False),
        ("layers.{}.mlp.gate_proj.weight", L["mlp"]["gate"], True),
        ("layers.{}.mlp.up_proj.weight", L["mlp"]["up"], True),
        ("layers.{}.mlp.down_proj.weight", L["mlp"]["down"], True),
    ]
    for i in range(cfg.num_layers):
        for fmt, stacked, transpose in names:
            row = np.asarray(stacked[i])
            sd[prefix + fmt.format(i)] = _t(row) if transpose else row
    sd[prefix + "norm.weight"] = np.asarray(params["final_norm"])
    sd["lm_head.weight"] = _t(np.asarray(params["lm_head"]))
    return sd


def projector_params_to_hf(params: Params,
                           prefix: str = "model.visual_projector.",
                           adaptor_prefix: str = "model.feature_adaptor.") -> StateDict:
    sd: StateDict = {}
    for j, layer in enumerate(params["mlp"]):
        sd[f"{prefix}{2 * j}.weight"] = _t(np.asarray(layer["kernel"]))
        sd[f"{prefix}{2 * j}.bias"] = np.asarray(layer["bias"])
    if "adaptor" in params:
        sd[adaptor_prefix + "weight"] = _t(np.asarray(params["adaptor"]["kernel"]))
        sd[adaptor_prefix + "bias"] = np.asarray(params["adaptor"]["bias"])
    return sd


def eventchat_params_to_hf(params: Params, cfg: EventChatConfig) -> StateDict:
    """{clip, projector, llama} pytree -> reference-layout state dict
    (prefix conventions of ``model/EventChatModel.py:72-76,128-161``).
    Round-trips with ``eventchat_params_from_hf``."""
    sd: StateDict = {}
    sd.update(clip_params_to_hf(
        params["clip"], cfg.vision,
        prefix="model.visual_tower.visual_tower.vision_model.",
    ))
    sd.update(projector_params_to_hf(params["projector"]))
    sd.update(llama_params_to_hf(params["llama"], cfg.llama, prefix="model."))
    return sd


def hf_config_dict(cfg: EventChatConfig,
                   visual_tower: str = "openai/clip-vit-large-patch14-336",
                   has_adaptor: Optional[bool] = None,
                   include_qformer: Optional[bool] = None) -> dict:
    """EventChatConfig -> the reference's ``config.json`` field set
    (custom gating fields per ``model/EventChatModel.py:71-81`` +
    ``inference.py:33-34``), plus this framework's explicit extensions
    (``vision_config``, ``mm_projector_depth``, ``qformer_config``) so
    non-default towers/projectors round-trip.

    ``has_adaptor`` / ``include_qformer`` override the cfg-derived gates —
    presence fields must track the TENSORS actually persisted next to this
    config, not the config object (a gate without weights makes the
    reference stack construct an unloaded module and makes this framework
    fabricate a fresh one)."""
    from eventgpt_tpu.config import to_dict

    out = {
        "model_type": "EventChat_llama",
        "architectures": ["EventChatModel"],
        "vocab_size": cfg.llama.vocab_size,
        "hidden_size": cfg.llama.hidden_size,
        "intermediate_size": cfg.llama.intermediate_size,
        "num_hidden_layers": cfg.llama.num_layers,
        "num_attention_heads": cfg.llama.num_heads,
        "num_key_value_heads": cfg.llama.num_kv_heads,
        "rms_norm_eps": cfg.llama.rms_norm_eps,
        "rope_theta": cfg.llama.rope_theta,
        "max_position_embeddings": cfg.llama.max_seq_len,
        "tie_word_embeddings": cfg.llama.tie_word_embeddings,
        "mm_visual_tower": visual_tower,
        "mm_projector_depth": cfg.projector.mlp_depth,
        "spatial_temporal_encoder": cfg.use_spatio_temporal_pool,
        "mm_use_im_start_end": cfg.mm_use_im_start_end,
        "mm_use_im_patch_token": cfg.mm_use_im_patch_token,
        "vision_config": to_dict(cfg.vision),
    }
    adaptor = (cfg.projector.use_feature_adaptor if has_adaptor is None
               else has_adaptor)
    if adaptor:
        out["event_feature_adaptor"] = True
    qformer = (cfg.use_event_qformer if include_qformer is None
               else include_qformer)
    if qformer:
        out["use_event_qformer"] = True
        out["qformer_config"] = to_dict(cfg.qformer)
    return out


def write_hf_checkpoint(params: Params, cfg: EventChatConfig, out_dir: str,
                        num_shards: int = 2,
                        visual_tower: str = "openai/clip-vit-large-patch14-336") -> str:
    """Full JAX tree -> loadable HF-style checkpoint directory (sharded
    safetensors + config.json). The handoff artifact for reference-stack
    users; inverse of ``load_state_dict`` + ``eventchat_params_from_hf``."""
    import json

    import jax

    sd = eventchat_params_to_hf(
        jax.tree_util.tree_map(np.asarray, params), cfg
    )
    save_sharded_safetensors(sd, out_dir, num_shards=num_shards)
    # Q-Former weights have no place inside the reference's state dict
    # (its load path is per-component files, model/EventChatModel.py:
    # 141-163) — persist them as sibling component artifacts, and only
    # advertise the gate when the weights actually ship.
    has_qformer = cfg.use_event_qformer and "qformer" in params
    if has_qformer:
        from eventgpt_tpu.models.qformer import save_qformer_components

        save_qformer_components(
            params["qformer"],
            os.path.join(out_dir, "query_embedder.npz"),
            os.path.join(out_dir, "attention_layers.npz"),
            num_heads=cfg.qformer.num_heads,
        )
    cfg_dict = hf_config_dict(
        cfg, visual_tower,
        has_adaptor="adaptor" in params.get("projector", {}),
        include_qformer=has_qformer,
    )
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump(cfg_dict, f, indent=2)
    return out_dir


def save_sharded_safetensors(sd: StateDict, out_dir: str, num_shards: int = 2) -> None:
    """Write an HF-style sharded safetensors checkpoint directory
    (``model-0000i-of-0000N.safetensors`` + ``model.safetensors.index.json``)."""
    import json

    from safetensors.numpy import save_file

    os.makedirs(out_dir, exist_ok=True)
    keys = sorted(sd)
    per = (len(keys) + num_shards - 1) // num_shards
    index = {"metadata": {"total_size": int(sum(v.nbytes for v in sd.values()))},
             "weight_map": {}}
    for s in range(num_shards):
        shard_keys = keys[s * per:(s + 1) * per]
        if not shard_keys:
            continue
        name = f"model-{s + 1:05d}-of-{num_shards:05d}.safetensors"
        save_file({k: np.ascontiguousarray(sd[k]) for k in shard_keys},
                  os.path.join(out_dir, name))
        for k in shard_keys:
            index["weight_map"][k] = name
    with open(os.path.join(out_dir, "model.safetensors.index.json"), "w") as f:
        json.dump(index, f, indent=2)


# ---------------------------------------------------------------------------
# File loaders (torch/safetensors touched only here)


def load_state_dict(model_path: str) -> StateDict:
    """Load a (possibly sharded) HF checkpoint directory into numpy arrays.

    Handles ``*.safetensors`` shards and ``pytorch_model*.bin`` torch files —
    the loading surface behind ``from_pretrained`` at ``inference.py:30``.
    """
    sd: StateDict = {}
    entries = sorted(os.listdir(model_path))
    safes = [e for e in entries if e.endswith(".safetensors")]
    bins = [e for e in entries if e.startswith("pytorch_model") and e.endswith(".bin")]
    if safes:
        from safetensors import safe_open

        for shard in safes:
            with safe_open(os.path.join(model_path, shard), framework="np") as f:
                for k in f.keys():
                    sd[k] = f.get_tensor(k)
    elif bins:
        import torch

        for shard in bins:
            for k, v in torch.load(
                os.path.join(model_path, shard), map_location="cpu", weights_only=True
            ).items():
                sd[k] = v.float().numpy() if v.dtype == torch.bfloat16 else v.numpy()
    else:
        raise FileNotFoundError(f"no safetensors/bin checkpoint found under {model_path}")
    return sd


def load_partial_module(path: str, strip_prefix: str) -> StateDict:
    """Read a reference-style partial checkpoint (raw torch.load dict).

    Mirrors the key-prefix rewriting at ``model/EventChatModel.py:124-139``:
    e.g. ``strip_prefix='model.feature_adaptor.'``.
    """
    import torch

    raw = torch.load(path, map_location="cpu", weights_only=True)
    out: StateDict = {}
    for k, v in raw.items():
        if k.startswith(strip_prefix):
            k = k[len(strip_prefix):]
        out[k] = v.float().numpy() if v.dtype == torch.bfloat16 else v.numpy()
    return out


def state_dict_from_torch_module(module) -> StateDict:
    """torch nn.Module -> numpy state dict (test utility)."""
    return {
        k: (v.float().numpy() if str(v.dtype) == "torch.bfloat16" else v.detach().numpy())
        for k, v in module.state_dict().items()
    }
