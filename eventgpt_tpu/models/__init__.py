from eventgpt_tpu.models import clip, convert, eventchat, llama, projector  # noqa: F401
