"""Medusa-style trained draft heads — the learned alternative to
prompt-lookup speculative drafting.

The reference serves with plain HF generate (``inference.py:52-63``) and
has no speculative path at all; this module is the second half of the
framework's drafting story (VERDICT r3 #3): where the lookup rule
(``models/eventchat._suffix_vote_drafts``) can only echo text it has seen,
K trained heads predict tokens t+2..t+K+1 from the final-norm hidden state
at t (Cai et al., "Medusa: Simple LLM inference acceleration framework
with multiple decoding heads", arXiv:2401.10774 — architecture only; all
code here is original). The verification forward makes ANY draft exact
(greedy chain identity / rejection-sampling distribution), so head quality
affects only speed, never correctness — tested with random heads in
``tests/test_medusa.py``.

TPU shape: one residual SiLU block per head, stacked as a single
(K, D, D) einsum so all heads run in one MXU matmul; logits reuse the
frozen (possibly int8/int4-quantized) lm_head. Heads initialize to ZERO,
making each head's logits exactly the base model's next-token logits (the
paper's identity start) — training only has to learn the *offset* from
that baseline.

Training (``train/medusa.py``) freezes the whole model and fits only the
(K, D, D) stack with the existing optimizer/trainer machinery — the same
"frozen base + small trainable set" recipe as stage-2 LoRA.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from eventgpt_tpu.config import LlamaConfig
from eventgpt_tpu.ops.quant import matmul_f32_out as _mm_f32

MedusaParams = Dict[str, Any]


def init_medusa_params(
    cfg: LlamaConfig, num_heads: int, dtype=jnp.float32
) -> MedusaParams:
    """K draft heads: ``w`` (K, D, D). Zeros => silu(x @ 0) = 0 => each
    head's hidden equals x, so its logits equal the base model's own
    next-token logits (identity start; no RNG needed)."""
    d = cfg.hidden_size
    return {"w": jnp.zeros((num_heads, d, d), dtype)}


def num_draft_heads(medusa: MedusaParams) -> int:
    return int(medusa["w"].shape[0])


def medusa_hidden(medusa: MedusaParams, x: jnp.ndarray,
                  k: Optional[int] = None) -> jnp.ndarray:
    """(..., D) -> (..., K, D): h_k = x + silu(x @ w_k) — all heads in one
    stacked einsum (a single (K*D, D)-shaped MXU contraction). ``k``
    statically prunes the head stack to the first k heads BEFORE the
    einsum (ISSUE 13 head pruning: a smaller speculation bucket's
    executable must not pay the pruned heads' matmul + lm_head at every
    verify; None = all heads, the training/eval form)."""
    w = medusa["w"] if k is None else medusa["w"][:k]
    proj = jnp.einsum("...d,kde->...ke", x, w.astype(x.dtype))
    return x[..., None, :] + jax.nn.silu(proj)


def medusa_logits(
    llama_params: Any, medusa: MedusaParams, x: jnp.ndarray,
    k: Optional[int] = None,
) -> jnp.ndarray:
    """(..., D) -> (..., K, V) f32 through the frozen (possibly quantized)
    lm_head. Head k's logits score the token at stream offset k+2 from
    the position whose hidden is ``x`` (offset +1 is the base lm_head's
    own prediction). ``k`` prunes the stack (see ``medusa_hidden``)."""
    return _mm_f32(medusa_hidden(medusa, x, k), llama_params["lm_head"])


def medusa_drafts(
    llama_params: Any, medusa: MedusaParams, x: jnp.ndarray, k: int
) -> jnp.ndarray:
    """Greedy drafts for the next verification window: (B, D) -> (B, k)
    int32 (argmax per head, truncated/validated to k heads). The
    truncation happens in the HEAD STACK (``medusa_hidden``), so a
    window-W speculation bucket only computes its W-1 heads."""
    n = num_draft_heads(medusa)
    if k > n:
        raise ValueError(
            f"window needs {k} drafts but the Medusa stack has {n} heads "
            f"(train with num_heads >= window - 1)"
        )
    logits = medusa_logits(llama_params, medusa, x, k)  # (B, k, V)
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def medusa_loss(
    llama_params: Any,
    medusa: MedusaParams,
    hidden: jnp.ndarray,     # (B, T, D) final-norm hidden (llama.prefill
                             # with return_hidden=True / forward path)
    labels: jnp.ndarray,     # (B, T) token ids; IGNORE_INDEX masked out
    ignore_index: int = -100,
):
    """Sum over heads of next-(k+2)-token cross-entropy.

    Head k at position t predicts ``labels[t + k + 2]`` (offset +1 is the
    base model's own next token — not a draft). Positions whose target is
    out of range or IGNORE_INDEX contribute nothing. Returns
    (scalar loss, per-head mean CE (K,)) — the per-head curve is the
    diagnostic: later heads are strictly harder.
    """
    b, t, _ = hidden.shape
    k = num_draft_heads(medusa)
    logits = medusa_logits(llama_params, medusa, hidden)  # (B, T, K, V)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    losses = []
    for ki in range(k):
        off = ki + 2
        val = t - off
        if val <= 0:
            losses.append(jnp.float32(0.0))
            continue
        tgt = labels[:, off:]                      # (B, T-off)
        lp = logp[:, :val, ki]                     # (B, T-off, V)
        valid = tgt != ignore_index
        safe = jnp.where(valid, tgt, 0)
        ce = -jnp.take_along_axis(lp, safe[:, :, None], axis=2)[:, :, 0]
        n = jnp.maximum(valid.sum(), 1)
        losses.append(jnp.where(valid, ce, 0.0).sum() / n)
    per_head = jnp.stack(losses)
    return per_head.sum(), per_head


def save_medusa(path: str, medusa: MedusaParams) -> None:
    """Head-stack npz IO lives HERE (not train/medusa.py) so inference
    entry points can load heads without importing the optax/training
    stack."""
    import numpy as np

    np.savez(path, w=np.asarray(medusa["w"]))


def load_medusa(path: str, dtype=None) -> MedusaParams:
    import numpy as np

    with np.load(path) as z:
        w = z["w"]
    arr = jnp.asarray(w) if dtype is None else jnp.asarray(w, dtype)
    return {"w": arr}
