"""CLIP ViT vision encoder, TPU-first.

Functional JAX reimplementation of the frozen vision tower the reference
wraps (``model/EventChatModel.py:45-59`` wrapping HF ``CLIPVisionModel``;
ViT-L/14-336 per README.md:173-177). Numerics match HF's
``CLIPVisionModel(...).last_hidden_state`` — i.e. the final encoder layer
output *without* post-layernorm, which is exactly what the reference feeds
the projector (``model/EventChatModel.py:185-191``).

TPU-first choices:
  * patch embedding as a single flattened matmul (MXU-friendly; equivalent to
    the stride=kernel conv),
  * all encoder layers stacked on a leading axis and driven by ``lax.scan``
    (O(1) compile time in depth, natural fsdp/tp sharding of the stack),
  * f32 softmax accumulation inside attention regardless of param dtype.
"""

from __future__ import annotations

import math
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax

from eventgpt_tpu.config import VisionConfig

Params = Dict[str, Any]


def quick_gelu(x: jnp.ndarray) -> jnp.ndarray:
    return x * jax.nn.sigmoid(1.702 * x)


def layer_norm(x: jnp.ndarray, scale: jnp.ndarray, bias: jnp.ndarray, eps: float) -> jnp.ndarray:
    x32 = x.astype(jnp.float32)
    mean = x32.mean(axis=-1, keepdims=True)
    var = x32.var(axis=-1, keepdims=True)
    out = (x32 - mean) * lax.rsqrt(var + eps)
    return (out * scale + bias).astype(x.dtype)


def init_clip_params(cfg: VisionConfig, key: jax.Array, dtype=jnp.float32) -> Params:
    """Random init with HF-compatible shapes (for tests and cold starts)."""
    d, i, l = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    patch_dim = cfg.num_channels * cfg.patch_size**2
    keys = jax.random.split(key, 12)

    def dense(k, fan_in, shape):
        return jax.random.normal(k, shape, dtype) * (1.0 / math.sqrt(fan_in))

    return {
        "embeddings": {
            "class_embedding": jax.random.normal(keys[0], (d,), dtype) * 0.02,
            "patch_embedding": dense(keys[1], patch_dim, (patch_dim, d)),
            "position_embedding": jax.random.normal(keys[2], (cfg.num_tokens, d), dtype) * 0.02,
        },
        "pre_layernorm": {"scale": jnp.ones((d,), dtype), "bias": jnp.zeros((d,), dtype)},
        "layers": {
            "ln1": {"scale": jnp.ones((l, d), dtype), "bias": jnp.zeros((l, d), dtype)},
            "attn": {
                "q": {"kernel": dense(keys[3], d, (l, d, d)), "bias": jnp.zeros((l, d), dtype)},
                "k": {"kernel": dense(keys[4], d, (l, d, d)), "bias": jnp.zeros((l, d), dtype)},
                "v": {"kernel": dense(keys[5], d, (l, d, d)), "bias": jnp.zeros((l, d), dtype)},
                "o": {"kernel": dense(keys[6], d, (l, d, d)), "bias": jnp.zeros((l, d), dtype)},
            },
            "ln2": {"scale": jnp.ones((l, d), dtype), "bias": jnp.zeros((l, d), dtype)},
            "mlp": {
                "fc1": {"kernel": dense(keys[7], d, (l, d, i)), "bias": jnp.zeros((l, i), dtype)},
                "fc2": {"kernel": dense(keys[8], i, (l, i, d)), "bias": jnp.zeros((l, d), dtype)},
            },
        },
        "post_layernorm": {"scale": jnp.ones((d,), dtype), "bias": jnp.zeros((d,), dtype)},
    }


def _embed_patches(params: Params, cfg: VisionConfig, pixel_values: jnp.ndarray) -> jnp.ndarray:
    """(B, C, H, W) -> (B, 1 + N, D) token embeddings with CLS + positions."""
    b = pixel_values.shape[0]
    p = cfg.patch_size
    g = cfg.image_size // p
    # Flatten each patch in (c, i, j) order to match the HF Conv2d kernel layout.
    x = pixel_values.reshape(b, cfg.num_channels, g, p, g, p)
    x = x.transpose(0, 2, 4, 1, 3, 5).reshape(b, g * g, cfg.num_channels * p * p)
    patches = x @ params["embeddings"]["patch_embedding"]
    cls = jnp.broadcast_to(params["embeddings"]["class_embedding"], (b, 1, cfg.hidden_size))
    tokens = jnp.concatenate([cls.astype(patches.dtype), patches], axis=1)
    return tokens + params["embeddings"]["position_embedding"]


def _attention(x: jnp.ndarray, attn: Params, cfg: VisionConfig) -> jnp.ndarray:
    b, s, d = x.shape
    h, hd = cfg.num_heads, cfg.head_dim

    def proj(p):
        return (x @ p["kernel"] + p["bias"]).reshape(b, s, h, hd)

    q = proj(attn["q"]) * (1.0 / math.sqrt(hd))
    k = proj(attn["k"])
    v = proj(attn["v"])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
    ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(b, s, d)
    return ctx @ attn["o"]["kernel"] + attn["o"]["bias"]


def clip_encode(params: Params, cfg: VisionConfig, pixel_values: jnp.ndarray,
                pin=None) -> jnp.ndarray:
    """(B, C, H, W) pixels -> (B, num_tokens, D) last hidden state (no post-LN).

    ``pin``: optional sharding-constraint callable applied to the layer-scan
    carry. Under a sharded train step GSPMD otherwise flip-flops the
    activation sharding between the batch-sharded input and the fsdp/model-
    sharded weights on every scan iteration ("involuntary full
    rematerialization" — VERDICT r5 weak #1); pinning the carry keeps the
    whole tower batch-sharded. Identity when None (single-chip paths).
    """
    x = _embed_patches(params, cfg, pixel_values)
    x = layer_norm(x, params["pre_layernorm"]["scale"], params["pre_layernorm"]["bias"],
                   cfg.layer_norm_eps)
    if pin is not None:
        x = pin(x)

    def block(carry, layer):
        y = layer_norm(carry, layer["ln1"]["scale"], layer["ln1"]["bias"], cfg.layer_norm_eps)
        carry = carry + _attention(y, layer["attn"], cfg)
        y = layer_norm(carry, layer["ln2"]["scale"], layer["ln2"]["bias"], cfg.layer_norm_eps)
        y = quick_gelu(y @ layer["mlp"]["fc1"]["kernel"] + layer["mlp"]["fc1"]["bias"])
        y = y @ layer["mlp"]["fc2"]["kernel"] + layer["mlp"]["fc2"]["bias"]
        out = carry + y
        return (pin(out) if pin is not None else out), None

    x, _ = lax.scan(block, x, params["layers"])
    return x


def clip_pooled(params: Params, cfg: VisionConfig, pixel_values: jnp.ndarray) -> jnp.ndarray:
    """Post-layernormed CLS token (HF ``pooler_output`` equivalent)."""
    last = clip_encode(params, cfg, pixel_values)
    return layer_norm(last[:, 0], params["post_layernorm"]["scale"],
                      params["post_layernorm"]["bias"], cfg.layer_norm_eps)
