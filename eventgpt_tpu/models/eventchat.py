"""EventChat: the multimodal composition (vision tower + projector + LLM).

TPU-first redesign of ``model/EventChatModel.py``. The reference interleaves
ragged Python list surgery with HF generate (``prepare_inputs_labels_for_
multimodal``, ``:292-428``); here the same semantics factor into three clean
jit units (the seam identified in SURVEY.md §3.3):

  1. ``encode_events``  — CLIP -> projector -> adaptor -> spatio-temporal pool
  2. ``prefill``        — spliced prompt embeddings through the LM, KV cache fill
  3. ``decode_step``    — single-token autoregressive step on the HBM cache

The embedding splice itself (``splice_embeddings``) is static-shape: the
host splits ids at the -200 sentinel once, and the device concatenates
[text embeds | event tokens | text embeds]. Batching right-pads to a shared
length exactly like the reference (``model/EventChatModel.py:383-413``,
padding_side='right'), and the spliced sequence is truncated to the model
context (``:378-381``).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from eventgpt_tpu.config import EventChatConfig
from eventgpt_tpu.constants import SEQ_BUCKET
from eventgpt_tpu.models import clip as clip_mod
from eventgpt_tpu.models import llama as llama_mod
from eventgpt_tpu.models import projector as proj_mod
from eventgpt_tpu.ops.pooling import spatio_temporal_pool
from eventgpt_tpu.ops.sampling import sample

Params = Dict[str, Any]


def init_eventchat_params(cfg: EventChatConfig, key: jax.Array, dtype=jnp.float32) -> Params:
    k1, k2, k3, k4 = jax.random.split(key, 4)
    params = {
        "clip": clip_mod.init_clip_params(cfg.vision, k1, dtype),
        "projector": proj_mod.init_projector_params(cfg.projector, k2, dtype),
        "llama": llama_mod.init_llama_params(cfg.llama, k3, dtype),
    }
    if cfg.use_event_qformer:
        from eventgpt_tpu.models import qformer as qformer_mod

        params["qformer"] = qformer_mod.init_qformer_params(cfg.qformer, k4, dtype)
    return params


def _encode_feats(params: Params, cfg: EventChatConfig, frames: jnp.ndarray,
                  pin=None) -> jnp.ndarray:
    """(N, C, H, W) frames -> (N, num_tokens, D_lm) projected features:
    CLIP -> stop_gradient -> MLP projector -> feature adaptor. The
    stop_gradient is the exact JAX statement of the reference's
    detach-then-requires_grad trick that confines gradients to the
    projector stack (``model/EventChatModel.py:185-191``). ``pin``:
    optional batch-sharding constraint threaded through the CLIP layer
    scan and applied after each projector stage (see ``clip_encode``)."""
    feats = clip_mod.clip_encode(params["clip"], cfg.vision, frames, pin=pin)
    feats = jax.lax.stop_gradient(feats)
    feats = proj_mod.apply_projector(params["projector"], feats)
    if pin is not None:
        feats = pin(feats)
    feats = proj_mod.apply_adaptor(params["projector"], feats)
    if pin is not None:
        feats = pin(feats)
    return feats


def _encode_tail(params: Params, cfg: EventChatConfig, feats: jnp.ndarray) -> jnp.ndarray:
    """Per-sample (T, num_tokens, D) projected features -> (num_event_tokens,
    D) event tokens: Q-Former aggregation, raw patch concatenation, or the
    spatio-temporal pool (``model/EventChatModel.py:304-312``)."""
    if cfg.use_event_qformer:
        # Config-gated Q-Former path (use_event_qformer, model/
        # EventChatModel.py:78-81): learned queries aggregate the projected
        # frames into cfg.qformer.num_queries LM tokens.
        from eventgpt_tpu.models import qformer as qformer_mod

        return qformer_mod.qformer_encode(params["qformer"], cfg.qformer, feats)
    if not cfg.use_spatio_temporal_pool:
        # spatial_temporal_encoder=False path: raw per-frame patch tokens,
        # frames concatenated along the token axis.
        return feats.reshape(-1, feats.shape[-1])
    return spatio_temporal_pool(feats, cfg.num_temporal_tokens)


@functools.partial(jax.jit, static_argnames=("cfg",))
def encode_events(params: Params, cfg: EventChatConfig, pixel_values: jnp.ndarray) -> jnp.ndarray:
    """(T, C, H, W) frames -> (num_event_tokens, D_lm) pooled event tokens."""
    return _encode_tail(params, cfg, _encode_feats(params, cfg, pixel_values))


@functools.partial(jax.jit, static_argnames=("cfg", "mesh"))
def encode_events_batch(params: Params, cfg: EventChatConfig,
                        pixel_values: jnp.ndarray, mesh=None) -> jnp.ndarray:
    """(B, T, C, H, W) -> (B, num_event_tokens, D_lm).

    The CLIP tower and projector run batched over the flattened B*T frame
    axis instead of ``vmap``-per-sample: the former nested ``jit`` under
    ``vmap`` was an opaque call boundary to the SPMD partitioner, which
    forced per-layer "involuntary full rematerialization" resharding of
    the CLIP activations on every sharded train step (VERDICT r5 weak
    #1). ``mesh`` (static) additionally pins the tower's scan carry to
    the batch sharding so the sharded step's dryrun artifact is
    warning-free; None (the single-chip default) changes nothing.
    """
    b, t = pixel_values.shape[:2]
    pin = None
    if mesh is not None:
        from jax.sharding import NamedSharding

        from eventgpt_tpu.parallel.sharding import batch_spec

        pin = lambda x: jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, batch_spec(x.ndim))
        )
    flat = pixel_values.reshape((b * t,) + pixel_values.shape[2:])
    feats = _encode_feats(params, cfg, flat, pin=pin)
    feats = feats.reshape((b, t) + feats.shape[1:])
    return jax.vmap(lambda f: _encode_tail(params, cfg, f))(feats)


def splice_embeddings(
    params: Params,
    cfg: EventChatConfig,
    segments: Sequence[np.ndarray],
    event_tokens: jnp.ndarray,
    max_context: Optional[int] = None,
) -> jnp.ndarray:
    """Interleave text-segment embeddings with event-token blocks.

    ``segments`` are the host-side id chunks around each -200 sentinel
    (``split_at_event``); ``event_tokens`` is (num_events, n_tok, D) or
    (n_tok, D) for a single clip. Returns (T, D), truncated to the smaller
    of the model context and ``max_context`` (the reference's 2048 cap,
    ``model/EventChatModel.py:378-381``).
    """
    if event_tokens.ndim == 2:
        event_tokens = event_tokens[None]
    num_events = len(segments) - 1
    if event_tokens.shape[0] != num_events:
        raise ValueError(
            f"{num_events} event sentinel(s) in prompt but "
            f"{event_tokens.shape[0]} event clip(s) provided"
        )
    embed_dtype = params["llama"]["embed_tokens"].dtype
    parts: List[jnp.ndarray] = []
    for kind, val in _interleave_segments(segments):
        if kind == "text":
            ids = jnp.asarray(np.asarray(val, dtype=np.int32))
            parts.append(llama_mod.embed_tokens(params["llama"], ids))
        else:
            parts.append(event_tokens[val].astype(embed_dtype))
    out = jnp.concatenate(parts, axis=0)
    limit = cfg.llama.max_seq_len if max_context is None else min(cfg.llama.max_seq_len, max_context)
    if out.shape[0] > limit:
        # Text overflow truncates silently (reference parity, model/
        # EventChatModel.py:378-381) — but cutting into an event block would
        # silently destroy the visual input, so that fails loudly instead
        # (e.g. non-pool mode: 5*577 event tokens vs a 2048 context).
        n_text = sum(len(s) for s in segments)
        last_event_end = out.shape[0] - len(segments[-1])
        if num_events and last_event_end > limit:
            raise ValueError(
                f"spliced sequence ({out.shape[0]} tokens: {n_text} text + "
                f"{num_events}x{event_tokens.shape[1]} event) exceeds the "
                f"context cap {limit} inside an event block; raise "
                f"max_seq_len/--context_len or enable spatio-temporal pooling"
            )
    return out[:limit]


def _interleave_segments(segments: Sequence[np.ndarray]):
    """THE spliced-sequence layout: yields ("text", seg) / ("event", i) parts
    in order, skipping empty text segments. ``splice_embeddings`` (embedding
    stream) and ``_spliced_text_ids`` (token-id stream for the speculative
    n-gram lookup) both iterate this, so the two views of the sequence cannot
    drift apart."""
    num_events = len(segments) - 1
    for i, seg in enumerate(segments):
        if len(seg):
            yield ("text", seg)
        if i < num_events:
            yield ("event", i)


def _spliced_text_ids(
    segments: Sequence[np.ndarray], n_event_tok: int, limit: int
) -> np.ndarray:
    """Token-id layout of the spliced sequence: text ids in place, event-block
    positions filled with -1 (present in the embedding stream but not
    matchable / draftable by the speculative n-gram lookup)."""
    parts: List[np.ndarray] = []
    for kind, val in _interleave_segments(segments):
        if kind == "text":
            parts.append(np.asarray(val, dtype=np.int32))
        else:
            parts.append(np.full((n_event_tok,), -1, np.int32))
    out = np.concatenate(parts) if parts else np.zeros((0,), np.int32)
    return out[:limit]


def _pad_batch(embeds: List[jnp.ndarray]) -> Tuple[jnp.ndarray, jnp.ndarray, np.ndarray]:
    """Right-pad per-sample (T_i, D) embeds to (B, T_max, D) + bool mask."""
    lens = np.array([int(e.shape[0]) for e in embeds])
    t_max = int(lens.max())
    padded = jnp.stack([
        jnp.pad(e, ((0, t_max - e.shape[0]), (0, 0))) for e in embeds
    ])
    mask = jnp.asarray(np.arange(t_max)[None, :] < lens[:, None])
    return padded, mask, lens


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "last_only", "return_hidden"),
    donate_argnames=("cache",),
)
def _prefill_jit(params, cfg: EventChatConfig, embeds, mask, cache,
                 last_only=False, return_hidden=False):
    return llama_mod.prefill(
        params["llama"], cfg.llama, embeds, mask, cache, last_only=last_only,
        return_hidden=return_hidden,
    )


@functools.lru_cache(maxsize=32)
def _get_sharded_prefill(cfg: EventChatConfig, flat_sh, treedef, logits_sh,
                         mesh, hidden_sh=None):
    """Serving-mesh prefill with pinned output shardings.

    Without the pin, GSPMD is free to lay the written cache out differently
    from the donated input cache, which silently breaks buffer aliasing —
    a second full-size cache allocation per prefill (the donation warnings
    the CPU-mesh tests would otherwise print). Keyed per (cfg, cache
    shardings): one compile per serving configuration. ``mesh`` reaches
    ``llama_mod.prefill`` so a flash config runs the kernel per-shard
    (``serving_flash_shard_map``) instead of downgrading to dense scores.
    ``hidden_sh`` (set by the Medusa draft path) additionally returns the
    last real token's final-norm hidden state.
    """
    cache_sh = jax.tree_util.tree_unflatten(treedef, list(flat_sh))
    if hidden_sh is not None:
        return jax.jit(
            lambda params, embeds, mask, cache: llama_mod.prefill(
                params["llama"], cfg.llama, embeds, mask, cache,
                last_only=True, mesh=mesh, return_hidden=True,
            ),
            donate_argnums=(3,),
            out_shardings=(logits_sh, hidden_sh, cache_sh),
        )
    return jax.jit(
        lambda params, embeds, mask, cache: llama_mod.prefill(
            params["llama"], cfg.llama, embeds, mask, cache, last_only=True,
            mesh=mesh,
        ),
        donate_argnums=(3,),
        out_shardings=(logits_sh, cache_sh),
    )


def _prefill_sharded(params, cfg: EventChatConfig, embeds, mask, cache, mesh,
                     return_hidden=False):
    from jax.sharding import NamedSharding, PartitionSpec as P

    from eventgpt_tpu.parallel.serving import serving_batch_axes

    cache_sh = jax.tree_util.tree_map(lambda x: x.sharding, cache)
    flat, treedef = jax.tree_util.tree_flatten(cache_sh)
    baxes = serving_batch_axes(mesh, embeds.shape[0])
    bspec = baxes if baxes else None
    model_n = mesh.shape.get("model", 1)
    vocab_ax = (
        "model"
        if model_n > 1 and cfg.llama.vocab_size % model_n == 0
        else None
    )
    logits_sh = NamedSharding(mesh, P(bspec, vocab_ax))
    hidden_sh = NamedSharding(mesh, P(bspec, None)) if return_hidden else None
    fn = _get_sharded_prefill(cfg, tuple(flat), treedef, logits_sh, mesh,
                              hidden_sh)
    return fn(params, embeds, mask, cache)


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def _decode_jit(params, cfg: EventChatConfig, tokens, cache):
    token_embeds = llama_mod.embed_tokens(params["llama"], tokens[:, None])
    return llama_mod.decode_step(params["llama"], cfg.llama, token_embeds, cache)


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "max_new_tokens", "temperature", "top_p", "eos_token_id"),
    donate_argnames=("cache",),
)
def _decode_loop_jit(
    params,
    cfg: EventChatConfig,
    first_logits,
    cache,
    key,
    max_new_tokens: int,
    temperature: float,
    top_p: float,
    eos_token_id: int,
):
    """Whole autoregressive loop on device (lax.while_loop): no per-token
    host sync — the HF generate loop re-entered Python every step
    (SURVEY.md §3.1 hot loop); here the host reads back once at the end.

    Returns (tokens [B, max_new_tokens] int32, n_generated [B], cache).
    Rows that hit EOS are frozen to EOS thereafter. The final cache is
    returned ONLY so XLA can alias the donated input cache into an output
    buffer — without a matching output the donation is unusable ("donated
    buffers were not usable") and the while_loop carry double-buffers the
    cache, which at 7B batch 8 is the difference between fitting HBM and
    OOM. Callers drop it immediately.
    """
    b = first_logits.shape[0]
    tokens0 = jnp.zeros((b, max(max_new_tokens, 1)), jnp.int32)
    done0 = jnp.zeros((b,), bool)

    def cond(state):
        step, _, done, _, _, _ = state
        return (step < max_new_tokens) & ~done.all()

    def body(state):
        step, tokens, done, logits, cache, key = state
        key, sub = jax.random.split(key)
        next_tok = sample(logits, sub, temperature, top_p)
        next_tok = jnp.where(done, eos_token_id, next_tok)
        tokens = tokens.at[:, step].set(next_tok)
        done = done | (next_tok == eos_token_id)

        # Unconditional advance: a lax.cond pass-through branch here would
        # break XLA's aliasing of the donated KV cache through the
        # while_loop (a second full cache copy stays live — 3 GB at B=8).
        # The cost is one trailing decode_step past the stop condition.
        token_embeds = llama_mod.embed_tokens(params["llama"], next_tok[:, None])
        logits, cache = llama_mod.decode_step(
            params["llama"], cfg.llama, token_embeds, cache
        )
        return step + 1, tokens, done, logits, cache, key

    step, tokens, done, _, cache, _ = lax.while_loop(
        cond, body, (jnp.int32(0), tokens0, done0, first_logits, cache, key)
    )
    return tokens[:, :max_new_tokens], step, cache


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "num_beams", "max_new_tokens", "eos_token_id",
                     "gather_start"),
    # No cache donation: the first op repeats the cache to num_beams x its
    # size, so the donated buffers could never be reused anyway (XLA would
    # just warn on every call).
)
def _beam_loop_jit(
    params,
    cfg: EventChatConfig,
    first_logits,
    cache,
    num_beams: int,
    max_new_tokens: int,
    eos_token_id: int,
    gather_start: int = 0,
):
    """On-device deterministic beam search (length-normalized, HF
    ``length_penalty=1.0`` semantics): cumulative log-prob divided by the
    generated length at selection time.

    The reference exposes ``num_beams`` through HF generate
    (``inference.py:22``, default 1). Beams live as an expanded batch
    (B*num_beams rows) over the same decode_step; each iteration re-gathers
    the KV cache rows by parent-beam index.

    ``gather_start`` bounds that regather (VERDICT r2 weak #4): slots below
    the shortest prompt length are byte-identical across beams (repeated
    from one prefill row, decode writes only at slot >= prompt length), so
    each step permutes just the tail ``[gather_start, S)`` — copy traffic
    O(L*B*k*(S - gather_start)) per token instead of O(L*B*k*S).

    Returns (tokens [B, max_new_tokens] of the best beam, lengths [B]).
    """
    b, v = first_logits.shape
    k = num_beams
    neg = jnp.float32(-1e30)

    logp0 = jax.nn.log_softmax(first_logits.astype(jnp.float32), axis=-1)
    scores, tok0 = lax.top_k(logp0, k)                       # (B, k)
    # tree_map keeps this agnostic to the cache payload (bf16 arrays or
    # int8 {"q","s"} dicts).
    rep = lambda t, ax: jax.tree_util.tree_map(lambda x: jnp.repeat(x, k, axis=ax), t)
    cache = {
        "k": rep(cache["k"], 1),
        "v": rep(cache["v"], 1),
        "length": jnp.repeat(cache["length"], k, axis=0),
    }
    tokens0 = jnp.zeros((b, k, max_new_tokens), jnp.int32).at[:, :, 0].set(tok0)
    done0 = tok0 == eos_token_id
    lengths0 = jnp.ones((b, k), jnp.int32)
    rows = jnp.arange(b)[:, None]

    # Done beams may only extend with EOS at zero extra log-prob, freezing
    # their score while open beams keep accumulating.
    eos_only = jnp.full((v,), neg).at[eos_token_id].set(0.0)

    def cond(state):
        step, _, _, done, _, _ = state
        return (step < max_new_tokens) & ~done.all()

    def body(state):
        step, tokens, scores, done, lengths, cache = state
        last = jnp.take_along_axis(
            tokens, jnp.full((b, k, 1), step - 1, jnp.int32), axis=2
        )[:, :, 0]
        emb = llama_mod.embed_tokens(params["llama"], last.reshape(b * k)[:, None])
        logits, cache = llama_mod.decode_step(params["llama"], cfg.llama, emb, cache)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1).reshape(b, k, v)
        logp = jnp.where(done[:, :, None], eos_only[None, None, :], logp)

        cand = (scores[:, :, None] + logp).reshape(b, k * v)
        new_scores, idx = lax.top_k(cand, k)                  # (B, k)
        parent = idx // v
        tok = idx % v

        tokens = tokens[rows, parent].at[:, :, step].set(tok)
        par_done = done[rows, parent]
        lengths = jnp.where(par_done, lengths[rows, parent],
                            lengths[rows, parent] + 1)
        done = par_done | (tok == eos_token_id)

        flat_parent = (rows * k + parent).reshape(-1)
        sel = lambda t: jax.tree_util.tree_map(
            lambda x: x.at[:, :, gather_start:].set(
                x[:, flat_parent, gather_start:]
            ),
            t,
        )
        cache = {
            "k": sel(cache["k"]),
            "v": sel(cache["v"]),
            "length": cache["length"][flat_parent],
        }
        return step + 1, tokens, new_scores, done, lengths, cache

    _, tokens, scores, done, lengths, _ = lax.while_loop(
        cond, body,
        (jnp.int32(1), tokens0, scores, done0, lengths0, cache),
    )
    norm = scores / jnp.maximum(lengths, 1).astype(jnp.float32)
    best = jnp.argmax(norm, axis=1)                           # (B,)
    row = jnp.arange(b)
    return tokens[row, best], lengths[row, best]


def _spec_probs(logits, temperature: float, top_p: float):
    """Sampling distribution at each verify position: temperature scaling +
    nucleus filter, matching the plain path (``ops/sampling.sample``)."""
    from eventgpt_tpu.ops.sampling import top_p_filter

    scaled = logits.astype(jnp.float32) / temperature
    if top_p < 1.0:
        scaled = top_p_filter(scaled, top_p)  # rank-agnostic (axis=-1 ops)
    return jax.nn.softmax(scaled, axis=-1)


def _spec_commit_sampled(p, drafts, u, key):
    """Rejection-sampling acceptance for point-mass (n-gram) drafts.

    ``p``: (B, W, V) target distributions — ``p[:, i]`` is P(next token |
    window prefix through position i). ``drafts``: (B, W-1) proposed tokens
    for window positions 1..W-1 (-1 = unmatchable filler, never accepted).
    ``u``: (B, W-1) uniforms. The draft "distribution" q is a point mass, so
    draft i+1 is accepted with probability p_i(d) (Leviathan/Chen speculative
    sampling with degenerate q), and the first rejection resamples from
    norm(max(p - q, 0)) = p with the rejected token zeroed — the committed
    chain is exactly distributed as sequential sampling from p.

    Returns (a, corrected): a (B,) accepted-draft count; corrected (B,) the
    token sampled at the first rejection (or from the final position's p on
    full acceptance).
    """
    b, w, v = p.shape
    bidx = jnp.arange(b)
    if w == 1:  # degenerate window: no drafts, sample the one token
        corrected = jax.random.categorical(
            key, jnp.log(jnp.maximum(p[:, 0], 1e-38)), axis=-1
        ).astype(jnp.int32)
        return jnp.zeros((b,), jnp.int32), corrected
    d_valid = drafts >= 0
    d_safe = jnp.clip(drafts, 0, v - 1)
    p_draft = jnp.where(
        d_valid,
        jnp.take_along_axis(p[:, :-1], d_safe[:, :, None], axis=2)[:, :, 0],
        0.0,
    )  # (B, W-1): acceptance probability of each draft
    acc = jnp.cumprod((u < p_draft).astype(jnp.int32), axis=1)
    a = acc.sum(axis=1)  # (B,) accepted prefix length

    p_a = p[bidx, a]  # (B, V) distribution at the first rejection point
    # Zero the rejected token's mass (only when a < W-1: full acceptance
    # samples the bonus token from the untouched final distribution).
    rej = jnp.where(a < w - 1, d_safe[bidx, jnp.minimum(a, w - 2)], -1)
    rej_valid = (a < w - 1) & d_valid[bidx, jnp.minimum(a, w - 2)]
    onehot = jax.nn.one_hot(jnp.maximum(rej, 0), v, dtype=p_a.dtype)
    p_adj = jnp.where(rej_valid[:, None], p_a * (1.0 - onehot), p_a)
    corrected = jax.random.categorical(
        key, jnp.log(jnp.maximum(p_adj, 1e-38)), axis=-1
    ).astype(jnp.int32)
    return a, corrected


# Longest-suffix lookup depth for speculative drafting: matches of up to
# this many trailing tokens are scored; the deepest match level wins.
# 8 covers the clause-length echoes in the reference's published answers
# (scripts/spec_acceptance_sim.py sweeps 4/8/16: flat beyond 8).
SPEC_LOOKUP_MAX = 8


def _vocab_size(params: Params) -> int:
    """Actual vocab from the lm_head leaf (special-token registration can
    grow it past cfg.llama.vocab_size; int4 packs the contraction dim, the
    vocab (last) dim is unpacked either way)."""
    head = params["llama"]["lm_head"]
    leaf = (head.get("q", head.get("q4")) if isinstance(head, dict)
            else head)
    return int(leaf.shape[-1])


def _suffix_match_levels(tokens, suffix):
    """Per-position RAW suffix-match depth. ``tokens`` (..., P) is a
    lookup buffer (-1 = unmatchable filler), ``suffix`` (B, LMAX) the
    current tail newest-first. Returns (levels (B, P) int32, cont
    (B or 1, P) continuation tokens). A match of depth l ends at position
    j iff tokens[j-k] == suffix[:, k] for all k < l (fillers never match:
    suffix entries < 0 are skipped). Callers gate the returned depth by
    their committed/continuation mask; keeping the raw depth separate is
    what lets ``_advance_match_levels`` extend it in O(P) per drafted
    token instead of re-running this LMAX-deep scan.
    """
    lmax = suffix.shape[1]
    p = tokens.shape[-1]
    idx = jnp.arange(p)
    toks2d = tokens if tokens.ndim == 2 else tokens[None, :]
    shifted = jnp.stack(
        [jnp.roll(toks2d, k, axis=-1) for k in range(lmax)]
    )  # (LMAX, rows, P): shifted[k, :, j] = tokens[:, j-k] (wrapped)
    run = jnp.ones(toks2d.shape, bool)
    levels = jnp.zeros(toks2d.shape, jnp.int32)
    for k in range(lmax):
        tok_k = suffix[:, k][:, None]  # (B, 1)
        eq = (shifted[k] == tok_k) & (tok_k >= 0) & (idx >= k)[None, :]
        run = run & eq
        levels = levels + run.astype(jnp.int32)
    cont = jnp.roll(toks2d, -1, axis=-1)  # cont[:, j] = tokens[:, j+1]
    return levels, cont


def _advance_match_levels(tokens, levels, d):
    """Advance raw match depths when the suffix gains ``d`` (B,) on its
    newest side: depth(j | [d]+suffix) = tokens[j]==d ? 1 +
    min(depth(j-1 | suffix), LMAX-1) : 0 — every old match must continue
    through the new newest token, one position later, and the suffix
    window still holds only SPEC_LOOKUP_MAX entries (the min). Exactly
    the depth the full rescan would compute, at O(P) instead of
    O(LMAX * P) per draft position — the vectorization that keeps the
    speculative draft's traced graph (and the serving segment built on
    it) at LMAX + window ops instead of LMAX * window.
    """
    toks2d = tokens if tokens.ndim == 2 else tokens[None, :]
    prev = jnp.concatenate(
        [jnp.zeros_like(levels[:, :1]), levels[:, :-1]], axis=1
    )  # depth at j-1 under the old suffix; position 0 has no predecessor
    hit = (toks2d == d[:, None]) & (d[:, None] >= 0)
    return jnp.where(hit, 1 + jnp.minimum(prev, SPEC_LOOKUP_MAX - 1), 0)


def _suffix_vote_drafts(
    params, ids_buf, pos, window: int, history=None,
):
    """Draft ``window - 1`` tokens by longest-suffix majority vote
    (replaces round 3's latest-bigram rule; ``scripts/
    spec_acceptance_sim.py`` measures 1.26 vs 1.19 tokens/iteration on the
    reference's published multi-turn answers, 1.34 with a server history).

    Per draft position (re-queried as drafts extend the suffix — a drafted
    token can seed the next lookup): score every committed position of
    ``ids_buf[:, :pos-1]`` (and the optional server-wide ``history``
    buffer) by how many trailing tokens match the current suffix
    (up to ``SPEC_LOOKUP_MAX``); among positions at the deepest match
    level, majority-vote their continuation tokens (ties -> smallest id,
    argmax order); no match at all falls back to repeating the newest
    token (the r3 filler rule). Fillers (-1) never match or vote.

    The LMAX-deep scan (``_suffix_match_levels``) runs ONCE per verify;
    each further draft position extends the depths incrementally
    (``_advance_match_levels``) — identical drafts, at a fraction of the
    traced ops per window.
    """
    b, s_ids = ids_buf.shape
    if window <= 1:
        return jnp.zeros((b, 0), jnp.int32)
    bidx = jnp.arange(b)
    v = _vocab_size(params)
    idx = jnp.arange(s_ids)

    sidx = pos[:, None] - 1 - jnp.arange(SPEC_LOOKUP_MAX)[None, :]
    suffix = jnp.where(
        sidx >= 0,
        ids_buf[bidx[:, None], jnp.clip(sidx, 0, s_ids - 1)],
        -1,
    )  # (B, LMAX) newest-first
    committed = idx[None, :] <= (pos - 2)[:, None]  # ends with committed cont
    raw, cont = _suffix_match_levels(ids_buf, suffix)
    gate = committed & (cont >= 0)
    if history is not None:
        h = history.shape[-1]
        hcommitted = (jnp.arange(h) <= h - 2)[None, :]
        hraw, hcont = _suffix_match_levels(history, suffix)
        hgate = hcommitted & (hcont >= 0)

    newest = suffix[:, 0]  # fallback source: the tail's newest token
    drafts = []
    for i in range(window - 1):
        if i:
            raw = _advance_match_levels(ids_buf, raw, newest)
            if history is not None:
                hraw = _advance_match_levels(history, hraw, newest)
        levels = jnp.where(gate, raw, 0)
        lstar = levels.max(axis=1)  # (B,)
        if history is not None:
            hlevels = jnp.where(hgate, hraw, 0)
            lstar = jnp.maximum(lstar, hlevels.max(axis=1))
        at_max = (levels == lstar[:, None]) & (lstar[:, None] > 0)
        votes = jnp.zeros((b, v), jnp.int32).at[
            bidx[:, None], jnp.clip(cont, 0, v - 1)
        ].add(at_max.astype(jnp.int32))
        if history is not None:
            h_at_max = (hlevels == lstar[:, None]) & (lstar[:, None] > 0)
            votes = votes.at[
                bidx[:, None],
                jnp.clip(jnp.broadcast_to(hcont, (b, h)), 0, v - 1),
            ].add(h_at_max.astype(jnp.int32))
        d = jnp.argmax(votes, axis=1).astype(jnp.int32)
        d = jnp.where(lstar > 0, d, newest)  # fallback: repeat newest
        drafts.append(d)
        newest = d
    return jnp.stack(drafts, axis=1)  # (B, W-1)


def _spec_draft_verify(
    params,
    cfg: EventChatConfig,
    ids_buf,
    pos,             # (B,) next unwritten ids_buf slot per row
    cache,
    key,
    window: int,
    temperature: float,
    top_p: float,
    eos: int,
    history=None,    # optional (H,) server-wide served-text lookup buffer
    medusa=None,     # optional trained draft heads (models/medusa.py)
    drafts_in=None,  # (B, W-1) drafts carried from the previous window
                     # (Medusa mode: heads ran at the last correction's
                     # hidden state, one iteration ago)
    depth=None,      # optional (B,) int32 per-row draft-depth cap
                     # (ISSUE 13): draft positions >= depth[r] are masked
                     # to the -1 unmatchable filler, capping row r's
                     # effective window at depth[r]+1 committed tokens
                     # per verify WITHOUT a new executable. Exact by the
                     # same rule that makes drafts exact: a masked draft
                     # is simply never accepted (greedy: -1 != argmax;
                     # sampled: d_valid gates acceptance), so the chain
                     # is byte-identical at any mask. None = full depth.
):
    """THE speculative draft-and-verify step, shared by the one-shot loop
    (``_spec_loop_jit``) and the serving segment
    (``serve._spec_segment_jit``) so the exact-chain contract cannot drift
    between them.

    Drafts window-1 tokens by longest-suffix majority-vote lookup over
    ``ids_buf[:, :pos]`` (+ the optional server ``history`` buffer —
    ``_suffix_vote_drafts``) — or, when ``medusa`` is given, consumes the
    trained-head drafts carried in ``drafts_in`` and emits the NEXT
    window's drafts from the correction position's hidden state. Either
    way the window is verified in one ``decode_kstep`` (greedy argmax at
    temperature 0, rejection sampling otherwise) and the commit window
    built identically — draft quality affects speed, never the chain.
    The cache is returned with ``length`` RESTORED to its entry value —
    the caller advances it by however many tokens it actually commits
    (budget caps differ between callers).

    Returns (commit (B, W), m_count (B,), first_eos (B,), hit (B,),
    cache, key, next_drafts): ``commit[:, :m]`` are committable tokens,
    ``m_count`` the un-capped commit count (accepted + correction),
    ``first_eos``/``hit`` locate an EOS inside the commit prefix;
    ``next_drafts`` echoes ``drafts_in`` in lookup mode.
    """
    b, s_ids = ids_buf.shape
    bidx = jnp.arange(b)
    iarr = jnp.arange(window)[None, :]
    sampled = temperature > 0.0

    c0 = ids_buf[bidx, jnp.maximum(pos - 1, 0)]  # newest committed token
    if medusa is not None:
        drafts = drafts_in
    else:
        drafts = _suffix_vote_drafts(params, ids_buf, pos, window, history)
    if depth is not None and window > 1:
        # Per-row depth mask (ISSUE 13): positions past the row's cap
        # become the unmatchable filler — acceptance stops there, the
        # correction token still comes from logits that only attended
        # to accepted (target-equal) positions, so the commit is exact.
        drafts = jnp.where(
            jnp.arange(window - 1)[None, :] < depth[:, None], drafts, -1)

    wtoks = jnp.concatenate([c0[:, None], drafts], axis=1)  # (B, W)
    prev_len = cache["length"]
    embeds = llama_mod.embed_tokens(params["llama"], wtoks)
    if medusa is not None:
        logits, hidden, cache = llama_mod.decode_kstep(
            params["llama"], cfg.llama, embeds, cache, return_hidden=True
        )
    else:
        logits, cache = llama_mod.decode_kstep(
            params["llama"], cfg.llama, embeds, cache
        )
    if sampled:
        key, ku, kc = jax.random.split(key, 3)
        p = _spec_probs(logits, temperature, top_p)
        u = jax.random.uniform(ku, (b, window - 1))
        a, corrected = _spec_commit_sampled(p, drafts, u, kc)
    else:
        g = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # (B, W)
        # Accepted prefix: drafts[:, :a] all equal their greedy target.
        acc = jnp.cumprod((drafts == g[:, :-1]).astype(jnp.int32), axis=1)
        a = acc.sum(axis=1)                       # (B,) in [0, W-1]
        corrected = g[bidx, a]
    drafts_p = jnp.concatenate([drafts, jnp.zeros((b, 1), jnp.int32)], axis=1)
    commit = jnp.where(iarr < a[:, None], drafts_p, corrected[:, None])
    m_count = a + 1

    is_eos = (commit == eos) & (iarr < m_count[:, None])
    first_eos = jnp.min(jnp.where(is_eos, iarr, window), axis=1)
    hit = first_eos < window
    cache = {**cache, "length": prev_len}
    if medusa is not None:
        from eventgpt_tpu.models import medusa as medusa_mod

        # The correction token was sampled from position ``a``'s logits;
        # the heads at that SAME position's hidden predict the tokens
        # after it — the next window's drafts, with no extra forward.
        x_sel = hidden[bidx, a]  # (B, D)
        next_drafts = medusa_mod.medusa_drafts(
            params["llama"], medusa, x_sel, window - 1
        )
    else:
        next_drafts = drafts_in
    return commit, m_count, first_eos, hit, cache, key, next_drafts


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "max_new_tokens", "window", "eos_token_id",
                     "temperature", "top_p"),
    donate_argnames=("cache",),
)
def _spec_loop_jit(
    params,
    cfg: EventChatConfig,
    first_logits,
    cache,
    ids_buf,
    prompt_lens,
    max_new_tokens: int,
    window: int,
    eos_token_id: int,
    temperature: float = 0.0,
    top_p: float = 1.0,
    key=None,
    medusa=None,
    first_drafts=None,
):
    """Speculative decoding: lookup (or trained-head) drafting + one
    K-token verification forward per iteration. Greedy (temperature 0) or
    sampled (temperature > 0, nucleus top_p — the reference's default run
    shape, ``inference.py:19-22``). With ``medusa`` (models/medusa.py),
    drafts come from the trained heads instead of the suffix lookup:
    ``first_drafts`` seeds the first window (heads applied to the prefill
    hidden), and each verify step emits the next window's drafts from the
    correction position's hidden — same exactness contracts either way.

    Decode at batch 1 is weight-bandwidth-bound (PERFORMANCE.md): one
    ``decode_step`` streams ~3.4 GB of int8 weights to emit ONE token. A
    ``decode_kstep`` window streams the same bytes to score ``window``
    candidate positions, so every accepted draft token is a whole
    weight-streaming pass saved. Drafts come from a bigram match against the
    prompt + generated text (`prompt lookup decoding`: the most recent
    earlier occurrence of the current bigram predicts its continuation) —
    no draft model, no extra weights.

    Correctness contracts: at temperature 0, a draft is committed only when
    it equals the verifier's argmax at its position and the first mismatch
    is replaced by that argmax — EXACTLY the plain greedy chain. At
    temperature > 0, drafts go through rejection sampling against the
    verifier's distribution (``_spec_commit_sampled``) — the committed chain
    is EXACTLY DISTRIBUTED as sequential sampling, token for token (not the
    same stream as the plain loop, which burns its PRNG differently).
    Worst case (no draft ever accepted) each iteration still commits one
    token — the plain chain at ~decode cost plus the small window overhead.

    ``ids_buf`` is the committed-token buffer: spliced-prompt text ids with
    event-block positions holding -1 (never matchable), generated ids
    appended at ``prompt_lens + n_gen``. Invariant at each iteration head:
    ``cache["length"] == prompt_lens + n_gen - 1`` — every committed token
    except the newest has its KV cached; the verification window feeds that
    newest token plus ``window - 1`` drafts.

    Returns (ids_buf, n_gen [B], n_iters, cache) — outputs are read back
    from ``ids_buf`` at [prompt_lens, prompt_lens + n_gen). The cache is
    returned only to keep the donated input buffers aliasable (see
    ``_decode_loop_jit``); callers drop it.
    """
    b = first_logits.shape[0]
    s_ids = ids_buf.shape[1]
    bidx = jnp.arange(b)
    iarr = jnp.arange(window)[None, :]
    eos = eos_token_id
    if key is None:
        key = jax.random.PRNGKey(0)

    key, k0 = jax.random.split(key)
    t0 = sample(first_logits, k0, temperature, top_p)  # argmax at T=0
    ids_buf0 = ids_buf.at[bidx, prompt_lens].set(t0)
    n_gen0 = jnp.ones((b,), jnp.int32)
    done0 = t0 == eos
    drafts0 = (first_drafts if medusa is not None
               else jnp.zeros((b, max(window - 1, 0)), jnp.int32))

    def cond(state):
        _, n_gen, done, _, _, _, _ = state
        return (~done & (n_gen < max_new_tokens)).any()

    def body(state):
        ids_buf, n_gen, done, cache, n_iters, key, drafts = state
        active = ~done & (n_gen < max_new_tokens)
        pos = prompt_lens + n_gen          # next ids_buf write slot
        commit, m_count, first_eos, hit, cache, key, drafts = (
            _spec_draft_verify(
                params, cfg, ids_buf, pos, cache, key, window,
                temperature, top_p, eos, medusa=medusa, drafts_in=drafts,
            )
        )
        # EOS stops the commit window at (and including) the EOS token;
        # this loop allows budget overshoot (clipped at readback).
        m_eff = jnp.where(active, jnp.where(hit, first_eos + 1, m_count), 0)

        wpos = jnp.clip(pos[:, None] + iarr, 0, s_ids - 1)
        cur = ids_buf[bidx[:, None], wpos]
        ids_buf = ids_buf.at[bidx[:, None], wpos].set(
            jnp.where(iarr < m_eff[:, None], commit, cur)
        )
        n_gen = n_gen + m_eff
        done = done | (active & hit)
        # Keep KV only for committed tokens minus the newest (stale slots
        # above length are masked everywhere and overwritten by the next
        # window).
        cache = {**cache, "length": cache["length"] + m_eff}
        return ids_buf, n_gen, done, cache, n_iters + 1, key, drafts

    ids_buf, n_gen, done, cache, n_iters, _, _ = lax.while_loop(
        cond, body,
        (ids_buf0, n_gen0, done0, cache, jnp.int32(0), key, drafts0),
    )
    return ids_buf, n_gen, n_iters, cache


def generate(
    params: Params,
    cfg: EventChatConfig,
    input_ids_batch: Sequence[Sequence[int]],
    pixel_values_batch: jnp.ndarray,
    max_new_tokens: int = 512,
    temperature: float = 0.0,
    top_p: float = 1.0,
    eos_token_id: Optional[int] = 2,
    seed: int = 0,
    # Serving cache grain: 2x the training SEQ_BUCKET — a multiple keeps the
    # train/serve shape interactions aligned (the reason the constant is
    # shared) while preserving the coarser serving granularity: halving it
    # to 64 would double the set of compiled prefill/decode shapes a server
    # cycles through across prompt lengths (a full XLA compile each).
    bucket: int = 2 * SEQ_BUCKET,
    max_context: Optional[int] = None,
    num_beams: int = 1,
    kv_quant: bool = False,
    mesh=None,
    speculative: int = 0,
    spec_stats: Optional[Dict[str, int]] = None,
    draft_head=None,
) -> List[List[int]]:
    """Autoregressive generation over a batch of event-QA prompts.

    Flag parity with the reference run (``inference.py:52-63``): sampling is
    enabled iff temperature > 0, nucleus top_p, greedy otherwise; decode
    stops per-row at EOS or after ``max_new_tokens``. ``num_beams > 1``
    switches to deterministic length-normalized beam search (temperature /
    top_p are ignored, as with HF ``do_sample=False`` beam decoding).

    ``mesh``: a serving ``Mesh`` (data/fsdp/model axes, context=1). Params
    must already be placed by ``parallel.serving.shard_params_for_serving``;
    this function shards the activations and KV cache to match, and the
    existing jit units compile to one SPMD program (the BASELINE north-star
    layout: pjit-sharded FSDP/TP weights, HBM-resident sharded cache —
    vs the reference's single-GPU ``inference.py:52-63``).

    ``speculative``: verify-window size K > 0 enables speculative decoding
    (suffix-lookup draft + K-token verify, ``_spec_loop_jit``) — at
    temperature 0 exactly the plain greedy chain; at temperature > 0
    rejection-sampled to the exact sampling distribution. Usually far
    fewer weight-streaming passes. Composes with ``kv_quant`` and
    ``mesh``; requires num_beams 1. ``draft_head``: a trained Medusa stack
    (``models/medusa.py``) switches drafting from lookup to the learned
    heads (needs >= speculative-1 heads); same exactness contracts.

    ``input_ids_batch``: token ids containing -200 sentinels.
    ``pixel_values_batch``: (B, T_frames, C, H, W).
    """
    from eventgpt_tpu.data.tokenizer import split_at_event

    compute_dtype = jax.tree_util.tree_leaves(params["llama"])[0].dtype

    if speculative and num_beams > 1:
        raise ValueError(
            "speculative decoding composes with greedy/sampled decode, "
            "not beam search: num_beams must be 1"
        )

    serving = None
    if mesh is not None:
        import dataclasses

        from eventgpt_tpu.parallel import serving as serving_mod

        serving = serving_mod
        serving._require_serving_mesh(mesh)
        model_n = mesh.shape.get("model", 1)
        if (cfg.llama.attn_impl == "flash"
                and cfg.llama.num_heads % model_n != 0):
            # Flash under a serving mesh runs per-shard via shard_map
            # (``serving_flash_shard_map`` — heads over model, batch over
            # data/fsdp). That requires the head count to divide the model
            # axis; otherwise dense scores (which GSPMD partitions freely)
            # are the safe prefill fallback — one-shot, off the decode hot
            # path.
            cfg = dataclasses.replace(
                cfg, llama=dataclasses.replace(cfg.llama, attn_impl="dense")
            )
        pixel_values_batch = serving.shard_batch_array(
            pixel_values_batch, mesh, compute_dtype
        )

    event_tokens = encode_events_batch(
        params, cfg, jnp.asarray(pixel_values_batch, dtype=compute_dtype)
    )
    embeds = [
        splice_embeddings(params, cfg, split_at_event(ids), event_tokens[i], max_context)
        for i, ids in enumerate(input_ids_batch)
    ]
    padded, mask, lens = _pad_batch(embeds)
    b, t = padded.shape[:2]

    # Bucket the cache length to stabilize compiled shapes across prompts.
    # Speculative windows overshoot by up to `speculative` committed tokens
    # and write one full window past the last commit — reserve 2 windows.
    max_len = t + max_new_tokens + (2 * speculative if speculative else 0)
    max_len = ((max_len + bucket - 1) // bucket) * bucket
    cache = llama_mod.init_kv_cache(
        cfg.llama, b, max_len, dtype=compute_dtype, quant=kv_quant
    )
    if serving is not None:
        padded = serving.shard_batch_array(padded, mesh)
        mask = serving.shard_batch_array(mask, mesh)
        cache = serving.shard_kv_cache(cache, cfg.llama, mesh)

    want_hidden = bool(speculative) and draft_head is not None
    last_hidden = None
    if serving is not None:
        pre = _prefill_sharded(params, cfg, padded, mask, cache, mesh,
                               return_hidden=want_hidden)
    else:
        pre = _prefill_jit(params, cfg, padded, mask, cache, True,
                           return_hidden=want_hidden)
    if want_hidden:
        last_logits, last_hidden, cache = pre
    else:
        last_logits, cache = pre

    key = jax.random.PRNGKey(seed)
    if serving is not None:
        key = serving.replicate(key, mesh)
    if max_new_tokens == 0:
        return [[] for _ in range(b)]
    # EOS sentinel: a real id stops rows early; None decodes the full budget
    # (an out-of-vocab sentinel that never matches a sampled token).
    eos = eos_token_id if eos_token_id is not None else -1
    if num_beams > 1:
        # Bucketed down to the SEQ_BUCKET grain (a lower bound on lens.min()
        # is all correctness needs): gather_start is a STATIC jit arg, and
        # an exact lens.min() would recompile the whole beam loop per
        # distinct prompt length.
        tokens, lengths = _beam_loop_jit(
            params, cfg, last_logits, cache, int(num_beams),
            max_new_tokens, int(eos),
            gather_start=(int(lens.min()) // SEQ_BUCKET) * SEQ_BUCKET,
        )
        out_tokens = np.asarray(jax.device_get(tokens))
        out_lengths = np.asarray(jax.device_get(lengths))
        results = []
        for i in range(b):
            ids = [int(t) for t in out_tokens[i, : out_lengths[i]]]
            if ids and eos_token_id is not None and ids[-1] == eos_token_id:
                ids = ids[:-1]
            results.append(ids)
        return results
    if speculative:
        window = int(speculative)
        limit = (
            cfg.llama.max_seq_len
            if max_context is None
            else min(cfg.llama.max_seq_len, max_context)
        )
        n_ev = int(event_tokens.shape[1])
        ids_host = np.full((b, max_len), -1, np.int32)
        for i, ids in enumerate(input_ids_batch):
            row = _spliced_text_ids(split_at_event(ids), n_ev, limit)
            ids_host[i, : len(row)] = row
        ids_buf = jnp.asarray(ids_host)
        plens = jnp.asarray(lens.astype(np.int32))
        if serving is not None:
            # Everything in the loop is batch-parallel (per-row scatter
            # writes, bigram scan, argmax over the model-sharded vocab) —
            # GSPMD partitions it like the plain decode loop.
            ids_buf = serving.shard_batch_array(ids_buf, mesh)
            plens = serving.shard_batch_array(plens, mesh)
        first_drafts = None
        if draft_head is not None:
            from eventgpt_tpu.models import medusa as medusa_mod

            first_drafts = medusa_mod.medusa_drafts(
                params["llama"], draft_head, last_hidden, window - 1
            )
        out_buf, n_gen, n_iters, cache = _spec_loop_jit(
            params, cfg, last_logits, cache, ids_buf, plens,
            max_new_tokens, window, int(eos),
            temperature=float(temperature), top_p=float(top_p), key=key,
            medusa=draft_head, first_drafts=first_drafts,
        )
        del cache  # returned only for donation aliasing
        out_np = np.asarray(jax.device_get(out_buf))
        gen_np = np.asarray(jax.device_get(n_gen))
        if spec_stats is not None:
            spec_stats["iterations"] = int(jax.device_get(n_iters))
            spec_stats["tokens"] = int(np.minimum(gen_np, max_new_tokens).sum())
        results = []
        for i in range(b):
            row = out_np[i, lens[i] : lens[i] + min(int(gen_np[i]), max_new_tokens)]
            ids_out: List[int] = []
            for tid in row:
                if eos_token_id is not None and tid == eos_token_id:
                    break
                ids_out.append(int(tid))
            results.append(ids_out)
        return results
    tokens, num_steps, cache = _decode_loop_jit(
        params, cfg, last_logits, cache, key,
        max_new_tokens, float(temperature), float(top_p), int(eos),
    )
    del cache  # returned only for donation aliasing
    out_tokens = np.asarray(jax.device_get(tokens))  # single host readback
    num_steps = int(num_steps)

    results: List[List[int]] = []
    for i in range(b):
        ids: List[int] = []
        for tid in out_tokens[i, :num_steps]:
            if eos_token_id is not None and tid == eos_token_id:
                break
            ids.append(int(tid))
        results.append(ids)
    return results


def forward_train(
    params: Params,
    cfg: EventChatConfig,
    inputs_embeds: jnp.ndarray,
    attention_mask: jnp.ndarray,
) -> jnp.ndarray:
    """Training forward: spliced embeds -> logits (B, T, V)."""
    return llama_mod.forward(params["llama"], cfg.llama, inputs_embeds, attention_mask)
