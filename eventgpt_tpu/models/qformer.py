"""Event Q-Former: learned-query cross-attention aggregator (config-gated).

The reference gates a Q-Former on ``use_event_qformer`` and ships its
parameter surface — ``query_embeddings`` plus an ``attention_layers``
ModuleList with per-component partial-checkpoint load hooks
(``model/EventChatModel.py:78-81``, ``:117-121``, ``:141-163``) — but the
``build_event_qformer`` builder itself is ABSENT from the released code
(SURVEY.md §2.1 P6c: config-gated dead path). This module supplies a
TPU-native design for that declared-but-unshipped surface:

  * BLIP-2-style aggregation: ``num_queries`` learned query vectors
    cross-attend to the projected per-frame event features and replace the
    spatio-temporal pool as the LM's event tokens (a fixed, much smaller
    token budget: e.g. 32 instead of 582).
  * Layers are stacked on a leading axis and driven by ``lax.scan`` like
    every other tower in this framework; pre-LN cross-attention + GELU MLP,
    f32 softmax under bf16 params.
  * Checkpoint interop keeps the reference's component-file conventions:
    ``model.query_embedder.*`` / ``model.attention_layers.{i}.*`` prefix
    rewriting (``load_qformer_components``).
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from eventgpt_tpu.config import QFormerConfig

Params = Dict[str, Any]


def init_qformer_params(qcfg: QFormerConfig, key: jax.Array, dtype=jnp.float32) -> Params:
    d, l = qcfg.hidden_size, qcfg.num_layers
    m = d * qcfg.mlp_ratio
    keys = jax.random.split(key, 7)

    def dense(k, fan_in, shape):
        return jax.random.normal(k, shape, dtype) * (1.0 / math.sqrt(fan_in))

    return {
        "query_embeddings": jax.random.normal(keys[0], (qcfg.num_queries, d), dtype) * 0.02,
        "attention_layers": {
            "ln_q": {"scale": jnp.ones((l, d), dtype), "bias": jnp.zeros((l, d), dtype)},
            "ln_kv": {"scale": jnp.ones((l, d), dtype), "bias": jnp.zeros((l, d), dtype)},
            "attn": {
                "q": dense(keys[1], d, (l, d, d)),
                "k": dense(keys[2], d, (l, d, d)),
                "v": dense(keys[3], d, (l, d, d)),
                "o": dense(keys[4], d, (l, d, d)),
            },
            "ln_mlp": {"scale": jnp.ones((l, d), dtype), "bias": jnp.zeros((l, d), dtype)},
            "mlp": {
                "fc1": dense(keys[5], d, (l, d, m)),
                "fc1_bias": jnp.zeros((l, m), dtype),
                "fc2": dense(keys[6], m, (l, m, d)),
                "fc2_bias": jnp.zeros((l, d), dtype),
            },
        },
    }


def _layer_norm(x: jnp.ndarray, w: Params, eps: float = 1e-5) -> jnp.ndarray:
    x32 = x.astype(jnp.float32)
    mu = x32.mean(axis=-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(axis=-1, keepdims=True)
    out = (x32 - mu) * lax.rsqrt(var + eps)
    return (out * w["scale"].astype(jnp.float32)
            + w["bias"].astype(jnp.float32)).astype(x.dtype)


def qformer_encode(params: Params, qcfg: QFormerConfig, feats: jnp.ndarray) -> jnp.ndarray:
    """Aggregate event features into ``num_queries`` LM tokens.

    feats: (T, S, D) projected per-frame features (post projector+adaptor)
    or (N, D) already flattened. Returns (num_queries, D).
    """
    if feats.ndim == 3:
        feats = feats.reshape(-1, feats.shape[-1])
    h, hd = qcfg.num_heads, qcfg.head_dim
    q = params["query_embeddings"].astype(feats.dtype)  # (Q, D)

    def block(carry, layer):
        q = carry
        qn = _layer_norm(q, layer["ln_q"])
        kvn = _layer_norm(feats, layer["ln_kv"])
        qh = (qn @ layer["attn"]["q"]).reshape(-1, h, hd)        # (Q, H, hd)
        kh = (kvn @ layer["attn"]["k"]).reshape(-1, h, hd)       # (N, H, hd)
        vh = (kvn @ layer["attn"]["v"]).reshape(-1, h, hd)
        scores = jnp.einsum("qhd,nhd->hqn", qh, kh,
                            preferred_element_type=jnp.float32)
        probs = jax.nn.softmax(scores * (1.0 / math.sqrt(hd)), axis=-1)
        ctx = jnp.einsum("hqn,nhd->qhd", probs.astype(q.dtype), vh)
        q = q + ctx.reshape(-1, h * hd) @ layer["attn"]["o"]
        yn = _layer_norm(q, layer["ln_mlp"])
        mlp = layer["mlp"]
        y = jax.nn.gelu(yn @ mlp["fc1"] + mlp["fc1_bias"], approximate=True)
        q = q + (y @ mlp["fc2"] + mlp["fc2_bias"])
        return q, None

    q, _ = lax.scan(block, q, params["attention_layers"])
    return q


# ---------------------------------------------------------------------------
# Reference-convention component loading (model/EventChatModel.py:141-163)


def load_qformer_components(
    qparams: Params,
    query_embedder_path: Optional[str] = None,
    attention_layers_path: Optional[str] = None,
) -> Params:
    """Partial-checkpoint load with the reference's prefix conventions.

    ``query_embedder``: keys prefixed ``model.query_embedder.`` (the
    embedding tensor itself under ``weight``). ``attention_layers``: keys
    prefixed ``model.attention_layers.{i}.<leaf path>`` — per-layer files
    are restacked onto the leading layer axis, mirroring the reference's
    per-index ``load_state_dict`` loop.
    """
    import numpy as np

    out = dict(qparams)
    if query_embedder_path:
        from eventgpt_tpu.checkpoint import load_component

        tree = load_component(query_embedder_path,
                              strip_prefix="model.query_embedder.")
        if isinstance(tree, dict):
            if "weight" not in tree:
                raise ValueError(
                    f"query_embedder component {query_embedder_path} has no "
                    f"'weight' leaf (keys: {sorted(tree)}) — wrong artifact?"
                )
            tree = tree["weight"]
        weight = jnp.asarray(tree)
        if weight.shape != out["query_embeddings"].shape:
            raise ValueError(
                f"query_embedder shape {weight.shape} != configured "
                f"{out['query_embeddings'].shape}"
            )
        out["query_embeddings"] = weight.astype(out["query_embeddings"].dtype)

    if attention_layers_path:
        data = np.load(attention_layers_path)
        num_layers = jax.tree_util.tree_leaves(out["attention_layers"])[0].shape[0]
        per_layer: list = [dict() for _ in range(num_layers)]
        prefix = "model.attention_layers."
        for key in data.files:
            if key.startswith("qformer_meta."):
                continue  # artifact metadata (num_heads), not weights
            if not key.startswith(prefix):
                raise ValueError(
                    f"attention_layers component has key {key!r} without "
                    f"expected prefix {prefix!r} — wrong artifact?"
                )
            idx_str, leaf_path = key[len(prefix):].split(".", 1)
            idx = int(idx_str)
            if idx >= num_layers:
                raise ValueError(
                    f"layer index {idx} in {key!r} out of range "
                    f"(configured num_layers={num_layers})"
                )
            per_layer[idx][leaf_path] = data[key]

        def restack(path: str, stacked: jnp.ndarray) -> jnp.ndarray:
            leaves = []
            for i in range(num_layers):
                if path not in per_layer[i]:
                    raise ValueError(
                        f"attention_layers component missing "
                        f"model.attention_layers.{i}.{path}"
                    )
                leaves.append(np.asarray(per_layer[i][path]))
            got = np.stack(leaves)
            if got.shape != stacked.shape:
                raise ValueError(
                    f"attention_layers.{path}: shape {got.shape} != "
                    f"configured {stacked.shape}"
                )
            return jnp.asarray(got, stacked.dtype)

        out["attention_layers"] = jax.tree_util.tree_map_with_path(
            lambda kp, leaf: restack(
                ".".join(k.key for k in kp), leaf
            ),
            out["attention_layers"],
        )
    return out


def save_qformer_components(
    qparams: Params, query_embedder_path: str, attention_layers_path: str,
    num_heads: Optional[int] = None,
) -> None:
    """Write-side counterpart of ``load_qformer_components``: two npz
    artifacts in the reference's key conventions (per-layer indexed keys
    for ``attention_layers``). ``num_heads`` is stored as artifact metadata
    (``qformer_meta.num_heads``) — the head split is not recoverable from
    the square projection shapes, and serving with a different split than
    training silently computes different attention."""
    import os

    import numpy as np

    from eventgpt_tpu.checkpoint import save_component

    save_component(query_embedder_path,
                   {"weight": np.asarray(qparams["query_embeddings"])},
                   prefix="model.query_embedder.")

    flat: Dict[str, Any] = {}

    def walk(tree, path=""):
        for k, v in tree.items():
            if isinstance(v, dict):
                walk(v, f"{path}{k}.")
            else:
                arr = np.asarray(v)
                for i in range(arr.shape[0]):
                    flat[f"model.attention_layers.{i}.{path}{k}"] = arr[i]

    walk(qparams["attention_layers"])
    if num_heads is not None:
        flat["qformer_meta.num_heads"] = np.asarray(num_heads)
    os.makedirs(os.path.dirname(os.path.abspath(attention_layers_path)),
                exist_ok=True)
    np.savez(attention_layers_path, **flat)


def qformer_config_from_artifacts(
    query_embedder_path: Optional[str] = None,
    attention_layers_path: Optional[str] = None,
) -> QFormerConfig:
    """Recover the QFormerConfig dims from trained component artifacts so a
    serving CLI needs no side-channel config: num_queries/hidden from the
    query embeddings, num_layers/mlp_ratio from the layer files. num_heads
    comes from the ``qformer_meta.num_heads`` metadata the saver embeds;
    legacy artifacts without it fall back to the largest power of two <= 8
    dividing the hidden size (the init default)."""
    import numpy as np

    num_queries, hidden, num_layers, mlp_ratio = 32, 4096, 2, 4
    heads = None
    if query_embedder_path:
        q = np.load(query_embedder_path)["model.query_embedder.weight"]
        num_queries, hidden = int(q.shape[0]), int(q.shape[1])
    if attention_layers_path:
        data = np.load(attention_layers_path)
        idxs = set()
        for key in data.files:
            if key == "qformer_meta.num_heads":
                heads = int(data[key])
                continue
            if key.startswith("qformer_meta."):
                continue
            rest = key[len("model.attention_layers."):]
            idxs.add(int(rest.split(".", 1)[0]))
            if rest.endswith("mlp.fc1"):
                fc1 = data[key]
                hidden = int(fc1.shape[0])
                mlp_ratio = int(fc1.shape[1]) // hidden
        num_layers = max(idxs) + 1
    if heads is None:
        heads = next(h for h in (8, 4, 2, 1) if hidden % h == 0)
        import logging

        # A Q-Former trained with a different split would silently compute
        # different attention at serve time (ADVICE r2) — make the guess
        # loud; metadata-carrying artifacts (qformer_meta.num_heads) never
        # hit this path.
        logging.getLogger("eventgpt_tpu.qformer").warning(
            "attention_layers artifact carries no qformer_meta.num_heads; "
            "GUESSING num_heads=%d from hidden=%d — re-export the artifact "
            "with this framework (metadata included) or verify the trained "
            "head count matches",
            heads, hidden,
        )
    return QFormerConfig(num_queries=num_queries, num_layers=num_layers,
                         num_heads=heads, hidden_size=hidden,
                         mlp_ratio=mlp_ratio)
