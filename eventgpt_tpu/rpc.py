"""Minimal length-prefixed JSON-over-TCP RPC for the process fleet.

The process-fleet tier (ISSUE 11, ``fleet_proc.py``) needs exactly one
thing from a transport: move small host-side records (token ids, pixel
arrays, stats dicts) between a coordinator and worker processes on
localhost, and FAIL LOUDLY AND BOUNDEDLY when the other side is slow,
wedged, or dead. This module is that transport and nothing more — no
pickling (a killed worker must never be able to corrupt the
coordinator beyond a parse error), no connection pooling, no service
discovery. One call = one connection = one request + one response,
each framed as a 4-byte big-endian length prefix + UTF-8 JSON.

Robustness contract (the tentpole's layer 1):

  * **Every call carries a deadline.** ``call(..., deadline_s=...)``
    bounds the WHOLE call — connect, send, and the response read all
    share one budget; exhausting it raises ``RpcTimeout``. A worker
    that stops answering costs the caller ``deadline_s``, never a hung
    thread.
  * **Bounded exponential backoff + jitter.** Transport failures
    (refused/reset connections, short reads, injected
    ``procfleet.rpc`` trips) retry up to ``retries`` times with
    ``backoff_s * 2^attempt`` sleeps (capped, jittered to decorrelate
    a thundering coordinator) while the deadline allows.
  * **Mutating ops never blind-retry.** A retry after the request
    bytes left the socket could double-submit a request whose first
    copy was actually delivered (the response, not the request, was
    lost). Callers pass ``retry_sent=False`` for non-idempotent ops:
    failures before the payload is sent retry normally; failures after
    it raise immediately and the caller decides (the coordinator
    treats that worker as suspect and re-routes).
  * **Remote exceptions are data.** A handler exception returns as
    ``{"error": {"type", "msg"}}`` and re-raises as
    ``RpcRemoteError`` — never retried (the op REACHED the worker; the
    failure is semantic, e.g. ``QueueFullError``, and the caller maps
    it back to the engine exception it mirrors).

The fault site ``procfleet.rpc`` fires per ATTEMPT, before any bytes
move — a transport-shaped failure the retry loop must absorb — so the
chaos tests drive the real retry/backoff path, not a mock.

Wire values beyond JSON: numpy arrays ride as
``{"__nd__": [shape, dtype, b64]}`` (bit-exact round trip — the chain
identity tests depend on pixels surviving verbatim), bytes as
``{"__b64__": ...}``, and the ``workload.SLO`` dataclass as
``{"__slo__": {...}}`` (an allowlisted type, not arbitrary class
hydration). Deliberately jax-free.

Raw-binary frame (ISSUE 17): a paged-KV handoff record is megabytes of
ndarray, and riding it through ``__nd__`` costs ~33% b64 inflation plus
a full JSON parse of the blob. ``dumps_frame``/``loads_frame`` add a
TAGGED alternative encoding of the same value space: when a message
contains ndarrays, the frame becomes ``b"EGRB" + u32(header_len) +
header_json + raw_blob_bytes`` — the header is ordinary RPC JSON with
each array replaced by ``{"__blob__": i, "shape", "dtype"}`` and a blob
length table, and the arrays' raw bytes are concatenated after it
(length-prefixed by the table; byte-exact round trip, tested).
Blob-free messages fall back to the plain JSON encoding verbatim, and
``loads_frame`` dispatches on the magic prefix, so both frame forms
interoperate on one socket and old-format peers keep working. ``call``
and ``RpcServer`` use the frame codec symmetrically — every op
(submit pixels, export_requests, the KV handoff) gets the raw path
for free.
"""

from __future__ import annotations

import base64
import json
import random
import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from eventgpt_tpu import faults
from eventgpt_tpu.obs import metrics as obs_metrics

_LEN = struct.Struct(">I")
# One frame must hold a pixel stream (tiny: ~60 KB b64) or an exported
# request batch; 64 MiB is far above any legitimate record and far
# below "a corrupt length prefix allocates the host away".
MAX_MSG_BYTES = 64 * 1024 * 1024

class RpcError(RuntimeError):
    """Transport/protocol failure talking to a worker (connect refused,
    reset, short read, frame too large, deadline pressure)."""


class RpcTimeout(RpcError):
    """The per-call deadline elapsed before a response arrived."""


class RpcRemoteError(RuntimeError):
    """The worker's handler raised: ``type_name`` is the remote
    exception class name (the coordinator maps known names back onto
    the engine exceptions they mirror, e.g. ``QueueFullError``)."""

    def __init__(self, type_name: str, msg: str):
        super().__init__(f"{type_name}: {msg}")
        self.type_name = type_name
        self.remote_msg = msg


# -- wire encoding ---------------------------------------------------------

def _enc_default(o):
    import numpy as np

    if isinstance(o, np.ndarray):
        # list(o.shape), not the contiguous copy's: ascontiguousarray
        # promotes 0-d to 1-d (ndmin=1), which would silently turn a
        # scalar leaf (a cache length, a base_pos) into shape (1,).
        arr = np.ascontiguousarray(o)
        return {"__nd__": [list(o.shape), str(arr.dtype),
                           base64.b64encode(arr.tobytes()).decode()]}
    if isinstance(o, np.integer):
        return int(o)
    if isinstance(o, np.floating):
        return float(o)
    if isinstance(o, (bytes, bytearray)):
        return {"__b64__": base64.b64encode(bytes(o)).decode()}
    # SLO is the one dataclass that crosses the boundary (submit_ids /
    # export_requests records). Encoded by field, decoded through the
    # real constructor — an allowlist of one, not generic hydration.
    from eventgpt_tpu.workload import SLO

    if isinstance(o, SLO):
        return {"__slo__": {"name": o.name, "ttft_s": o.ttft_s,
                            "itl_s": o.itl_s, "latency_s": o.latency_s}}
    raise TypeError(f"cannot encode {type(o).__name__} for RPC")


def _dec_hook(d: Dict[str, Any]):
    if "__nd__" in d and len(d) == 1:
        import numpy as np

        shape, dtype, b64 = d["__nd__"]
        return np.frombuffer(
            base64.b64decode(b64), dtype=np.dtype(dtype)
        ).reshape(shape).copy()
    if "__b64__" in d and len(d) == 1:
        return base64.b64decode(d["__b64__"])
    if "__slo__" in d and len(d) == 1:
        from eventgpt_tpu.workload import SLO

        return SLO(**d["__slo__"])
    return d


def dumps(obj: Any) -> bytes:
    return json.dumps(obj, default=_enc_default).encode()


def loads(data: bytes) -> Any:
    return json.loads(data.decode(), object_hook=_dec_hook)


# -- raw-binary frame (ISSUE 17) -------------------------------------------
#
# Layout:  RAW_MAGIC | u32 header_len | header JSON | blob 0 | blob 1 | ...
# header = {"h": <payload with arrays as __blob__ refs>, "b": [len, ...]}
# The magic cannot collide with the JSON form (which always starts with
# "{", 0x7B), so one recv path decodes both.

RAW_MAGIC = b"EGRB"
_BLOB_KEYS = frozenset(("__blob__", "shape", "dtype"))


def _extract_blobs(o: Any, blobs: list) -> Any:
    import numpy as np

    if isinstance(o, np.ndarray):
        # Same 0-d rule as _enc_default: the shape comes from ``o``,
        # not the ndmin=1 contiguous copy.
        arr = np.ascontiguousarray(o)
        blobs.append(arr.tobytes())
        return {"__blob__": len(blobs) - 1,
                "shape": list(o.shape), "dtype": str(arr.dtype)}
    if isinstance(o, dict):
        return {k: _extract_blobs(v, blobs) for k, v in o.items()}
    if isinstance(o, (list, tuple)):
        return [_extract_blobs(v, blobs) for v in o]
    return o


def dumps_frame(obj: Any) -> bytes:
    """Encode ``obj`` for the wire: the raw-binary frame when it
    carries ndarrays (their bytes ride verbatim after the JSON header
    — no b64 inflation), the plain JSON encoding otherwise."""
    blobs: list = []
    header_obj = _extract_blobs(obj, blobs)
    if not blobs:
        return dumps(obj)
    header = json.dumps(
        {"h": header_obj, "b": [len(b) for b in blobs]},
        default=_enc_default).encode()
    return b"".join([RAW_MAGIC, _LEN.pack(len(header)), header] + blobs)


def _restore_blobs(o: Any, blobs: list) -> Any:
    import numpy as np

    if isinstance(o, dict):
        if _BLOB_KEYS.issuperset(o) and "__blob__" in o:
            # .copy(): writable, owns its memory (same contract as the
            # __nd__ decode path).
            return np.frombuffer(
                blobs[int(o["__blob__"])], dtype=np.dtype(o["dtype"])
            ).reshape(o["shape"]).copy()
        return {k: _restore_blobs(v, blobs) for k, v in o.items()}
    if isinstance(o, list):
        return [_restore_blobs(v, blobs) for v in o]
    return o


def loads_frame(data: bytes) -> Any:
    """Decode either frame form (dispatch on the magic prefix)."""
    if not data.startswith(RAW_MAGIC):
        return loads(data)
    if len(data) < len(RAW_MAGIC) + _LEN.size:
        raise RpcError("raw frame truncated before its header length")
    (hlen,) = _LEN.unpack_from(data, len(RAW_MAGIC))
    off = len(RAW_MAGIC) + _LEN.size
    if off + hlen > len(data):
        raise RpcError(
            f"raw frame header of {hlen} bytes overruns the "
            f"{len(data)}-byte frame")
    header = json.loads(data[off:off + hlen].decode(),
                        object_hook=_dec_hook)
    off += hlen
    blobs = []
    for n in header["b"]:
        if off + n > len(data):
            raise RpcError("raw frame blob table overruns the frame")
        blobs.append(data[off:off + n])
        off += n
    if off != len(data):
        raise RpcError(f"raw frame has {len(data) - off} trailing bytes")
    return _restore_blobs(header["h"], blobs)


# -- framing ---------------------------------------------------------------

def send_msg(sock: socket.socket, data: bytes) -> None:
    if len(data) > MAX_MSG_BYTES:
        raise RpcError(f"message of {len(data)} bytes exceeds the "
                       f"{MAX_MSG_BYTES}-byte frame cap")
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            raise RpcError(f"connection closed mid-frame "
                           f"({len(buf)}/{n} bytes)")
        buf.extend(chunk)
    return bytes(buf)


def recv_msg(sock: socket.socket) -> bytes:
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n > MAX_MSG_BYTES:
        raise RpcError(f"frame of {n} bytes exceeds the "
                       f"{MAX_MSG_BYTES}-byte cap (corrupt prefix?)")
    return _recv_exact(sock, n)


# -- client ----------------------------------------------------------------

def call(addr: Tuple[str, int], op: str, payload: Optional[dict] = None,
         *, deadline_s: float = 10.0, retries: int = 3,
         backoff_s: float = 0.05, backoff_max_s: float = 2.0,
         retry_sent: bool = True) -> Any:
    """One RPC against ``addr``: returns the handler's result.

    ``deadline_s`` bounds the whole call (all attempts + backoffs).
    ``retries`` bounds the transport-failure retry count.
    ``retry_sent=False`` marks the op non-idempotent: a failure AFTER
    the request bytes were sent raises instead of retrying (see the
    module docstring). Raises ``RpcTimeout`` / ``RpcError`` on
    transport exhaustion, ``RpcRemoteError`` on a handler exception
    (never retried — the op reached the worker)."""
    t_deadline = time.monotonic() + float(deadline_s)
    request = dumps_frame({"op": op, "payload": payload or {}})
    attempt = 0
    last: Optional[BaseException] = None
    # Host-timing jitter only (never touches decoded chains): an
    # unseeded RNG is exactly right — correlated coordinator retries
    # are the failure mode jitter exists to break.
    rng = random.Random()
    while True:
        sent = False
        try:
            # The chaos seam (tentpole layer 1): a trip here IS a
            # transport failure, upstream of any socket work, so the
            # handling below — classify, back off, retry, give up at
            # the deadline — is the same code path a real refused
            # connection takes.
            faults.maybe_fail("procfleet.rpc")
            faults.maybe_delay("procfleet.rpc")
            remaining = t_deadline - time.monotonic()
            if remaining <= 0:
                raise RpcTimeout(
                    f"rpc {op!r} to {addr}: deadline of {deadline_s}s "
                    f"exhausted after {attempt} attempt(s)")
            with socket.create_connection(addr, timeout=remaining) as s:
                s.settimeout(max(t_deadline - time.monotonic(), 0.001))
                sent = True
                send_msg(s, request)
                resp = loads_frame(recv_msg(s))
            if "error" in resp:
                err = resp["error"]
                raise RpcRemoteError(err.get("type", "RuntimeError"),
                                     err.get("msg", ""))
            return resp.get("result")
        except RpcRemoteError:
            raise
        except (OSError, RpcError, faults.InjectedFault, ValueError) as e:
            last = e
            attempt += 1
            if sent and not retry_sent:
                raise RpcError(
                    f"rpc {op!r} to {addr} failed after the request was "
                    f"sent; not retried (non-idempotent): {e!r}") from e
            if attempt > retries or time.monotonic() >= t_deadline:
                if isinstance(e, RpcTimeout) \
                        or time.monotonic() >= t_deadline:
                    raise RpcTimeout(
                        f"rpc {op!r} to {addr} timed out after "
                        f"{attempt} attempt(s): {last!r}") from e
                raise RpcError(
                    f"rpc {op!r} to {addr} failed after {attempt} "
                    f"attempt(s): {last!r}") from e
            obs_metrics.PROCFLEET_RPC_RETRIES.inc()
            delay = min(backoff_s * (2.0 ** (attempt - 1)), backoff_max_s)
            delay *= 1.0 + 0.5 * rng.random()  # decorrelating jitter
            time.sleep(max(min(delay, t_deadline - time.monotonic()), 0.0))


# -- server ----------------------------------------------------------------

class RpcServer:
    """Thread-per-connection server over a handler callable
    ``handler(op, payload) -> result``. One call per connection (the
    client's connection-per-call discipline keeps both sides free of
    pooled-socket state). Handler exceptions become ``{"error": ...}``
    responses; transport errors on one connection never touch another.

    Shared state is two self-synchronizing primitives (the bound
    socket, closed exactly once via ``_stop``'s Event gate) — there is
    deliberately no mutable map for egpt-check's lock rule to guard.
    """

    def __init__(self, handler: Callable[[str, dict], Any],
                 host: str = "127.0.0.1", port: int = 0,
                 read_timeout_s: float = 30.0):
        self._handler = handler
        self._read_timeout_s = float(read_timeout_s)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.addr: Tuple[str, int] = self._sock.getsockname()[:2]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    @property
    def port(self) -> int:
        return self.addr[1]

    def stop(self) -> None:
        if self._stop.is_set():
            return
        self._stop.set()
        try:
            self._sock.close()  # unblocks accept()
        except OSError:
            pass
        self._thread.join(timeout=5)

    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                break  # socket closed by stop()
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn: socket.socket) -> None:
        with conn:
            try:
                conn.settimeout(self._read_timeout_s)
                msg = loads_frame(recv_msg(conn))
            except (OSError, RpcError, ValueError):
                return  # half-open/garbage connection: drop it
            try:
                result = self._handler(msg.get("op", ""),
                                       msg.get("payload") or {})
                resp = {"result": result}
            except Exception as e:  # handler errors are DATA (see doc)
                resp = {"error": {"type": type(e).__name__,
                                  "msg": str(e)}}
            try:
                send_msg(conn, dumps_frame(resp))
            except (OSError, RpcError, TypeError):
                pass  # client went away / unencodable: nothing to do
