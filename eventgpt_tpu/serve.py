"""Continuous-batching serving loop (iteration-level request scheduling).

The reference serves one request per process (``inference.py`` — load,
generate, print; its ``dataset/constants.py:1-4`` controller/worker
heartbeat constants are vestiges of a LLaVA serving stack that never
shipped). This module is the serving runtime the reference implies but
lacks: a fixed-shape decode batch whose ROWS are a resource — requests
join a running batch as rows free up, instead of waiting for the whole
batch to drain.

TPU-shaped design (everything jit-visible is static-shape):

  * One KV cache of (max_batch, max_len) rows lives in HBM for the life of
    the server; rows are FREE or ACTIVE.
  * Admission: a batch-1 prefill at the prompt's bucketed length, then the
    row's prompt KV/logits are written into the shared cache at the free
    row index (``_admit_row_jit`` — a per-buffer dynamic-update on the
    batch axis). One prefill executable per prompt bucket, reused forever.
  * Decode runs in fixed ``chunk``-token segments (``_decode_segment_jit``:
    the whole-budget ``lax.while_loop`` of ``_decode_loop_jit`` with
    per-row budgets and a frozen mask). Between segments the host harvests
    finished rows and admits queued requests — the segment size is the
    scheduling latency, and at 32 tokens the extra dispatch overhead is
    ~2-3% of decode (PERFORMANCE.md: whole-budget vs 64-token budgets).
  * Frozen/free rows keep flowing through the fused step (a ``lax.cond``
    skip would break the donated cache aliasing — same reasoning as
    ``_decode_loop_jit``); their writes land above their frozen lengths —
    kept in bounds by ``submit()``'s slack reservation (prompt + budget +
    slack <= max_len, so a finished row's write slot never reaches the
    buffer edge; XLA *drops*, not clamps, out-of-bounds scatter updates,
    so the slack is the invariant that matters) — are masked out of every
    attention read, and are overwritten when the row is re-admitted.
  * PREFIX-KV CACHE (ISSUE 4): a token-id trie of prompt-head KV blocks
    (``PrefixCache``) replaces the old single ``set_prefix`` slot —
    populated by the operator AND automatically on admission prefill
    (system-prompt / event-block heads), matched longest-prefix at
    admission, refcount-pinned while rows decode from an entry, LRU-
    evicted under an HBM byte budget (``prefix_cache_bytes``). Repeated
    heads across many concurrent sessions admit by a KV copy + suffix
    prefill instead of recompute; an event entry never serves a request
    whose pixels are a different stream.
  * BATCHED ADMISSION PREFILL: all full-prefill admissions ready at one
    dispatch boundary run as ONE padded batched prefill (``_admit_wave``
    — N x ~100 ms dispatch tax -> ~100 ms per wave), scattered into the
    shared cache in one more dispatch.
  * STALL-FREE ADMISSION (ISSUE 5): when ``prefill_budget > 0`` and rows
    are actively decoding, admissions no longer pause the batch for an
    exclusive prefill/suffix wave. Each admitting request becomes a
    piggyback LANE: its prompt embeddings (for a prefix-cache hit, the
    entry's KV copy is the lane's starting offset and only the suffix
    embeds load) sit in a resident (K, S_lane, D) buffer, and every
    decode dispatch becomes a MIXED segment — the existing decode/spec
    body plus a batched ``decode_kstep`` advancing each live lane by
    ``chunk_p`` prompt positions against its own lane-cache row, all in
    ONE executable (compiled per (batch, chunk, K, S_lane, chunk_p)
    bucket). In-flight rows therefore commit tokens at every admission
    boundary; the per-boundary prompt-token budget is
    ``K_cap * chunk_p <= prefill_budget``. A finished lane joins the
    shared cache through the same scatter/activation path as every other
    admission (NaN quarantine, insert-on-prefill, Medusa seeding, TTFT
    ramp), so chains stay byte-identical to the exclusive paths. With no
    active decode rows (nothing to stall) the scheduler still picks the
    wave/exclusive prefill — fastest to completion; the policy chooses
    per boundary.
  * PIPELINED scheduling (default): the between-segment control state
    (frozen mask, per-row budgets, gather base) is ALSO device-resident,
    updated in-graph by the segment kernels, so segment N+1 dispatches
    from device state while the host is still harvesting segment N —
    detokenization, history/draft bookkeeping and admission prep overlap
    device compute instead of serializing between dispatches. At most
    one segment is in flight; row mutations (admission, cancel,
    deadline) drain the pipeline at the dispatch boundary first. Chains
    are byte-identical to the synchronous path (``pipeline=False``).

Mesh-sharded serving (``mesh=``): the resident cache / logits / ids_buf
are placed by ``parallel/serving.py``'s layout (batch over ``(data,
fsdp)``, KV heads and vocab over ``model``) and every scheduler jit gets
pinned out-shardings so the donated cache keeps aliasing in place —
the composition of this module with ``parallel/serving.py`` that the
BASELINE north star (13B continuous batching over a pod) requires.

Greedy equivalence: rows are independent in attention (per-row lengths,
positions, masks), so a request decoded in a shared batch commits the same
greedy chain as ``eventchat.generate`` run alone — tested exactly on the
CPU f32 suite (``tests/test_serve.py``); on TPU bf16 the usual
batch-tiling numerics apply.
"""

from __future__ import annotations

import functools
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from eventgpt_tpu import faults
from eventgpt_tpu import serve_blocks
from eventgpt_tpu import serve_spec
from eventgpt_tpu.config import EventChatConfig
from eventgpt_tpu.obs import journey as obs_journey
from eventgpt_tpu.obs import memory as obs_memory
from eventgpt_tpu.obs import metrics as obs_metrics
from eventgpt_tpu.obs import profiling as obs_profiling
from eventgpt_tpu.obs import series as obs_series
from eventgpt_tpu.obs import trace as obs_trace
from eventgpt_tpu.constants import SEQ_BUCKET
from eventgpt_tpu.models import eventchat, llama as llama_mod
from eventgpt_tpu.ops.sampling import sample
from eventgpt_tpu.workload import SLO, SLO_CLASSES


class QueueFullError(RuntimeError):
    """submit() refused: the admission queue is at ``max_queue``. The HTTP
    layer maps this to 429 + Retry-After (backpressure, not failure)."""


# Terminal request statuses (``ContinuousBatcher.finish_status``). "ok"
# covers both EOS and budget exhaustion; everything else is a forced
# finish whose row was freed without burning the remaining budget.
STATUS_OK = "ok"
STATUS_DEADLINE = "deadline_exceeded"
STATUS_CANCELLED = "cancelled"
STATUS_NAN = "nan_quarantined"
# Both memory tiers exhausted (ISSUE 16): the block pool cannot cover
# the admission even after the preemption scan AND the host spill store
# has no budget left — the request is refused NOW (the HTTP layer maps
# it to 503 + Retry-After) instead of hanging deferred past its
# deadline. Only raised with preemption armed; defer-only servers keep
# the pre-16 behavior.
STATUS_RESOURCE = "resource_exhausted"

# Forced-finish statuses -> the flight-recorder event kind that marks
# them in the request's timeline (obs/journey.py EVENT_KINDS).
_JOURNEY_FORCED_KIND = {
    STATUS_DEADLINE: "deadline",
    STATUS_CANCELLED: "cancel",
    STATUS_NAN: "nan_quarantine",
}


def _pixels_key(pixel_values) -> bytes:
    """Content key of an event-pixel tensor (shape + sha1 of the f32
    bytes) — the event-block prefix guard's identity check (ADVICE r5
    medium: token ids alone cannot distinguish two streams)."""
    import hashlib

    # egpt-check: ignore[hot-sync] -- request pixels are host numpy by the submit() contract; this hashes host bytes, no device value exists here
    arr = np.ascontiguousarray(np.asarray(pixel_values, np.float32))
    return str(arr.shape).encode() + hashlib.sha1(arr.tobytes()).digest()


@dataclass
class _PrefixEntry:
    """One cached prompt-head KV block (ISSUE 4 tentpole). ``ids`` is the
    token path (includes the event sentinel for through-event entries);
    ``pixels_key`` pins an event entry to ITS stream — the wrong-stream
    guard lives in the lookup, not at the call site. ``kv`` holds the
    bucket-length (L, 1, bucket, KV, hd) K/V blocks (quant-aware), never
    donated to any jit, so eviction/replacement can only ever drop the
    last Python reference AFTER every in-flight copy completed."""
    ids: tuple
    pixels_key: Optional[bytes]
    has_event: bool
    kv: Optional[Dict[str, Any]]
    length: int          # real cache positions the entry covers
    bucket: int          # stored block length (serving bucket grain)
    nbytes: int
    pins: int = 0        # rows currently decoding that admitted from this
    tick: int = 0        # LRU clock at last insert/hit
    hits: int = 0
    # Paged layout (ISSUE 12): the entry IS a pinned run of pool blocks
    # (``kv`` is None) — "copy" on a hit is block-table aliasing with a
    # refcount, eviction is a ``BlockPool.decref``, and the dense
    # (L, 1, bucket) view the exclusive suffix/lane paths read is
    # gathered on demand (``ContinuousBatcher._entry_kv``).
    blocks: Optional[List[int]] = None
    # Detached (evicted/replaced) while pinned: a DENSE entry's arrays
    # stay alive through plain object references, but a paged entry's
    # storage is pool blocks — releasing them under a pinned entry
    # would hand a still-needed prefix to the next admission. The
    # release defers to the LAST pin drain (``_drain_entry_pin``).
    detached: bool = False


class PrefixCache:
    """Token-id trie of prompt-head KV blocks with LRU eviction — the
    multi-entry replacement for the single ``set_prefix`` slot (the
    RadixAttention idea at this server's SEQ_BUCKET granularity: entries
    are stored at the prompt bucket grain and keyed on ``(ids,
    pixels_key)``). Populated by ``set_prefix`` (operator insert, the old
    API) AND automatically on admission prefill (the system-prompt and
    event-block heads of every fully-prefilled prompt), so repeated heads
    across many concurrent sessions become cache hits without operator
    action.

    Rules:
      * longest-prefix match wins (``lookup``); an event entry never
        serves a request whose own pixels are a different stream;
      * ``budget`` bytes of HBM (0 = unbounded): inserts evict the
        least-recently-used UNPINNED entries until the new total fits;
      * a pinned entry (``pins`` > 0: some row admitted from it is still
        decoding) is never evicted — the refcount drains at row finish,
        so replacement under pressure cannot yank a hot session's head
        (and the detached-object rule in ``insert`` makes replacing a
        pinned key safe: pins drain on the detached entry, whose KV the
        in-flight rows' own references keep alive).

    Mutations are host-side dict ops under ``_lock`` (the scheduler
    thread inserts/looks up; HTTP handler threads read ``stats()``).
    Device arrays are only ever referenced, never mutated in place.
    ``budget`` is immutable after construction (undeclared below on
    purpose); ``_PrefixEntry.pins`` mutates under the OWNING engine's
    lock (every pin/drain site is scheduler-thread code), which the
    eviction sweep also runs under — the entry objects ride the
    batcher's external serialization, not this lock.
    """

    # Lock-discipline contract (egpt_check rule ``lock``): every
    # read/write of these goes through ``with self._lock`` or a
    # ``*_locked`` helper.
    _GUARDED_BY = {
        "_root": "_lock",
        "bytes": "_lock",
        "n_entries": "_lock",
        "hits": "_lock",
        "misses": "_lock",
        "evictions": "_lock",
        "insertions": "_lock",
        "_tick": "_lock",
    }

    def __init__(self, budget_bytes: int = 0):
        import threading

        self.budget = int(budget_bytes)
        # Paged servers attach their BlockPool here (immutable after
        # construction, like ``budget``): dropping an entry then also
        # decrefs its pinned block run. Lock order: PrefixCache._lock ->
        # BlockPool._lock (leafward, like the ledger/metric locks).
        self.pool = None
        self._root: Dict[str, Any] = {"c": {}, "e": {}}
        self._lock = threading.Lock()
        self.bytes = 0
        self.n_entries = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.insertions = 0
        self._tick = 0
        # Memory-ledger identity (ISSUE 9): this cache's entry bytes are
        # one "prefix_cache" component entry, resized on insert/evict
        # (lock order: PrefixCache._lock -> MemoryLedger._lock, leafward
        # like the metric locks).
        self._mem_key = f"pc{id(self):x}/entries"

    def _iter_nodes_locked(self):
        stack = [self._root]
        while stack:
            node = stack.pop()
            yield node
            stack.extend(node["c"].values())

    def entries(self) -> List[_PrefixEntry]:
        with self._lock:
            return [e for node in self._iter_nodes_locked()
                    for e in node["e"].values()]

    def get(self, ids, pixels_key) -> Optional[_PrefixEntry]:
        """Exact-key entry, or None (the insert-on-prefill dedupe)."""
        with self._lock:
            node = self._root
            for tok in ids:
                node = node["c"].get(tok)
                if node is None:
                    return None
            return node["e"].get(pixels_key)

    def lookup(self, ids, pixels_key) -> Optional[_PrefixEntry]:
        """Longest-prefix match: the deepest entry whose token path is a
        PROPER prefix of ``ids`` and whose stream identity is compatible
        with the request — a text entry needs the event sentinel in the
        remaining suffix, an event entry needs it consumed AND the
        request's own pixels to BE its stream (``pixels_key`` None =
        suffix-only session traffic, which inherits the entry's stream by
        construction). Among entries at one node the most recently used
        matching one wins. Hit/miss counting is the caller's (the
        admission path counts after its fit check)."""
        try:
            from eventgpt_tpu.constants import EVENT_TOKEN_INDEX
            sent = list(ids).index(EVENT_TOKEN_INDEX)
        except ValueError:
            sent = -1
        best = None
        with self._lock:
            node = self._root
            for d, tok in enumerate(ids):
                node = node["c"].get(tok)
                if node is None:
                    break
                if d + 1 >= len(ids):
                    break  # entry must be a PROPER prefix
                cand = None
                for e in node["e"].values():
                    if e.has_event:
                        if sent < 0 or sent > d:
                            continue  # sentinel must be inside the entry
                        if (pixels_key is not None
                                and e.pixels_key != pixels_key):
                            continue  # wrong stream: never serve this KV
                    elif sent <= d:
                        continue  # text entry: sentinel must be in suffix
                    if cand is None or e.tick > cand.tick:
                        cand = e
                if cand is not None:
                    best = cand  # deeper nodes visited later: longest wins
        return best

    def count_hit(self, entry: _PrefixEntry) -> None:
        with self._lock:
            self._tick += 1
            entry.tick = self._tick
            entry.hits += 1
            self.hits += 1
        obs_metrics.SERVE_PREFIX_HITS.inc()

    def count_miss(self) -> None:
        with self._lock:
            self.misses += 1
        obs_metrics.SERVE_PREFIX_MISSES.inc()

    def insert(self, entry: _PrefixEntry) -> bool:
        """Insert (or replace) the entry at its ``(ids, pixels_key)`` key,
        then evict LRU unpinned entries until the budget holds. False =
        refused (the entry alone exceeds the budget)."""
        if self.budget and entry.nbytes > self.budget:
            return False
        with self._lock:
            node = self._root
            for tok in entry.ids:
                node = node["c"].setdefault(tok, {"c": {}, "e": {}})
            old = node["e"].pop(entry.pixels_key, None)
            if old is not None:
                # Replacement detaches the old entry object; any pins on
                # it drain harmlessly there, and its KV stays alive via
                # the in-flight rows' references until they finish. A
                # paged entry's block run drops ITS refcount only — rows
                # aliasing those blocks keep their own refs.
                self.bytes -= old.nbytes
                self.n_entries -= 1
                self._release_blocks_locked(old)
            self._tick += 1
            entry.tick = self._tick
            node["e"][entry.pixels_key] = entry
            self.bytes += entry.nbytes
            self.n_entries += 1
            self.insertions += 1
            self._evict_locked()
            # Gauge export reads bytes/n_entries: stay under the lock
            # (metric locks are leaf locks — the order here is always
            # PrefixCache._lock -> _Metric._lock, never reversed).
            self._export_gauges_locked()
            # Ledger resize rides the same critical section so the
            # component bytes can never disagree with self.bytes
            # (the spy-lock test in tests/test_memory_ledger.py holds
            # the mutation inside it).
            obs_memory.LEDGER.resize("prefix_cache", self._mem_key,
                                     self.bytes)
        obs_metrics.SERVE_PREFIX_INSERTIONS.inc()
        return True

    def _evict_locked(self) -> None:
        if not self.budget:
            return
        while self.bytes > self.budget:
            victim_node, victim_key, victim = None, None, None
            for node in self._iter_nodes_locked():
                for key, e in node["e"].items():
                    if e.pins > 0:
                        continue  # refcount pin: in-flight rows admit from it
                    if victim is None or e.tick < victim.tick:
                        victim_node, victim_key, victim = node, key, e
            if victim is None:
                # Everything left is pinned: stay over budget until the
                # pins drain (the next insert retries the sweep).
                return
            del victim_node["e"][victim_key]
            self.bytes -= victim.nbytes
            self.n_entries -= 1
            self.evictions += 1
            self._release_blocks_locked(victim)
            obs_metrics.SERVE_PREFIX_EVICTIONS.inc()

    def _release_blocks_locked(self, entry: _PrefixEntry) -> None:
        """Drop a detached paged entry's block refs (its share only —
        aliasing rows hold their own). A PINNED entry (selected for an
        in-flight admission, seeding a pending lane, or backing active
        rows) defers the release to its last pin drain — the paged twin
        of the dense detached-object rule."""
        if entry.blocks and self.pool is not None:
            if entry.pins > 0:
                entry.detached = True
                return
            self.pool.decref(entry.blocks)
            entry.blocks = None

    def reclaim_blocks(self, pool, need: int) -> int:
        """Block-pressure eviction (ISSUE 12): evict LRU UNPINNED entries
        until ``pool`` has ``need`` free blocks or nothing evictable is
        left — the paged admission gate's reclaim path, which unifies
        prefix-entry eviction with row allocation (an idle entry's
        pinned run is the only reclaimable pool capacity). Returns the
        number of entries evicted."""
        evicted = 0
        with self._lock:
            while pool.free_blocks() < need:
                victim_node, victim_key, victim = None, None, None
                for node in self._iter_nodes_locked():
                    for key, e in node["e"].items():
                        if e.pins > 0 or not e.blocks:
                            continue
                        if victim is None or e.tick < victim.tick:
                            victim_node, victim_key, victim = node, key, e
                if victim is None:
                    break
                del victim_node["e"][victim_key]
                self.bytes -= victim.nbytes
                self.n_entries -= 1
                self.evictions += 1
                evicted += 1
                self._release_blocks_locked(victim)
                obs_metrics.SERVE_PREFIX_EVICTIONS.inc()
            if evicted:
                self._export_gauges_locked()
                obs_memory.LEDGER.resize("prefix_cache", self._mem_key,
                                         self.bytes)
        return evicted

    def evict_covering(self, blocks) -> int:
        """Evict every UNPINNED entry whose block run intersects
        ``blocks`` — the spill path's targeted sweep (ISSUE 16): an
        insert-on-prefill entry aliases its creator row's run at ref 2,
        and the pool refuses to spill a block another owner could still
        read, so preempting that row first evicts the idle entries
        riding its blocks (dropping them to ref 1). Pinned entries stay
        — a pending lane or selected admission is still reading them,
        and the caller degrades to drop-and-re-prefill. Returns the
        number of entries evicted."""
        want = set(blocks)
        if not want:
            return 0
        evicted = 0
        with self._lock:
            for node in self._iter_nodes_locked():
                for key in [k for k, e in node["e"].items()
                            if e.pins <= 0 and e.blocks
                            and not want.isdisjoint(e.blocks)]:
                    victim = node["e"].pop(key)
                    self.bytes -= victim.nbytes
                    self.n_entries -= 1
                    self.evictions += 1
                    evicted += 1
                    self._release_blocks_locked(victim)
                    obs_metrics.SERVE_PREFIX_EVICTIONS.inc()
            if evicted:
                self._export_gauges_locked()
                obs_memory.LEDGER.resize("prefix_cache", self._mem_key,
                                         self.bytes)
        return evicted

    def _export_gauges_locked(self) -> None:
        obs_metrics.SERVE_PREFIX_BYTES.set(self.bytes)
        obs_metrics.SERVE_PREFIX_ENTRIES.set(self.n_entries)

    def __del__(self):
        # A replaced/dropped cache must not leave stale bytes in the
        # memory ledger (the bench swaps in a fresh cache per measured
        # point). Best-effort: interpreter teardown may have torn the
        # ledger down first.
        try:
            obs_memory.LEDGER.release("prefix_cache", self._mem_key)
        except Exception:
            pass

    def clear(self) -> None:
        """Drop every entry (the bench's per-leg reset): paged entries
        release their block runs through the same deferred-on-pins rule
        as eviction, the trie/bytes reset, counters KEEP counting (a
        fresh-counter reset is ``ContinuousBatcher.reset_prefix_cache``,
        which swaps in a new cache)."""
        with self._lock:
            for node in self._iter_nodes_locked():
                for e in node["e"].values():
                    self._release_blocks_locked(e)
            self._root = {"c": {}, "e": {}}
            self.bytes = 0
            self.n_entries = 0
            self._export_gauges_locked()
            obs_memory.LEDGER.resize("prefix_cache", self._mem_key, 0)

    def stats(self) -> Dict[str, Any]:
        """Snapshot for ``GET /prefix_cache`` (lock-held, host-only)."""
        with self._lock:
            entries = [
                {"ids_len": len(e.ids), "has_event": e.has_event,
                 "length": e.length, "bucket": e.bucket,
                 "nbytes": e.nbytes, "pins": e.pins, "hits": e.hits}
                for node in self._iter_nodes_locked() for e in node["e"].values()
            ]
            return {
                "entries": sorted(entries, key=lambda d: -d["hits"]),
                "n_entries": self.n_entries,
                "bytes": self.bytes,
                "budget_bytes": self.budget,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "insertions": self.insertions,
                "hit_ratio": (self.hits / (self.hits + self.misses)
                              if (self.hits + self.misses) else 0.0),
            }


def _decode_segment(
    params,
    cfg: EventChatConfig,
    logits,          # (B, V) per-row next-token logits
    cache,
    key,
    frozen,          # (B,) bool — FREE rows or rows already finished
    n_rem,           # (B,) int32 remaining token budget per row
    chunk: int,
    eos_token_id: int,
    temperature: float = 0.0,
    top_p: float = 1.0,
    nan_gate: bool = True,
):
    """Up to ``chunk`` decode steps over the shared batch.

    Returns (tokens (B, chunk), n_new (B,), done (B,), finite, logits,
    cache, key, frozen_out, n_rem_out): ``tokens[r, :n_new[r]]`` are row
    r's newly committed tokens; ``done[r]`` marks rows that hit EOS inside
    this segment (budget exhaustion is the host's bookkeeping via
    n_rem - n_new == 0). ``frozen_out``/``n_rem_out`` are the NEXT
    segment's control state computed in-graph — the exact bookkeeping the
    host harvest applies (freeze on EOS / budget exhaustion / non-finite
    logits when ``nan_gate``), kept device-resident so the pipelined
    scheduler can dispatch segment N+1 from them before segment N's
    outputs are ever fetched to the host.
    """
    b = logits.shape[0]
    tokens0 = jnp.full((b, chunk), eos_token_id, jnp.int32)
    n_new0 = jnp.zeros((b,), jnp.int32)
    done0 = jnp.zeros((b,), bool)

    def cond(state):
        t, _, n_new, done, _, _, _ = state
        live = ~(frozen | done) & (n_new < n_rem)
        return (t < chunk) & live.any()

    def body(state):
        t, tokens, n_new, done, logits, cache, key = state
        key, sub = jax.random.split(key)
        nxt = sample(logits, sub, temperature, top_p)
        commit = ~(frozen | done) & (n_new < n_rem)
        nxt = jnp.where(commit, nxt, eos_token_id)
        tokens = tokens.at[:, t].set(jnp.where(commit, nxt, tokens[:, t]))
        n_new = n_new + commit.astype(jnp.int32)
        done = done | (commit & (nxt == eos_token_id))

        # Unconditional advance preserves donated-cache aliasing through the
        # while_loop (see _decode_loop_jit). Frozen rows' slot writes stay
        # in bounds via submit()'s slack reservation and are masked out of
        # every attention read.
        emb = llama_mod.embed_tokens(params["llama"], nxt[:, None])
        new_logits, cache = llama_mod.decode_step(
            params["llama"], cfg.llama, emb, cache
        )
        # Frozen rows keep their pre-segment logits AND their length: the
        # row must resume exactly where it stopped when the next segment
        # runs (length would otherwise creep by one per segment step).
        logits = jnp.where(commit[:, None], new_logits, logits)
        cache = {**cache, "length": jnp.where(
            commit, cache["length"], cache["length"] - 1
        )}
        return t + 1, tokens, n_new, done, logits, cache, key

    t, tokens, n_new, done, logits, cache, key = lax.while_loop(
        cond, body, (jnp.int32(0), tokens0, n_new0, done0, logits, cache, key)
    )
    # Per-row non-finite-logit flag, computed IN-GRAPH (one fused reduce
    # per segment, no extra host dispatch): the scheduler quarantines a
    # non-finite row instead of letting NaN logits poison the engine.
    finite = jnp.isfinite(logits).all(axis=-1)
    # Device-resident scheduler carry: mirror the host harvest's row
    # bookkeeping (budget decrement, freeze on EOS / exhaustion / NaN
    # quarantine) so the next segment can dispatch without a host sync.
    n_rem_out = n_rem - n_new
    frozen_out = frozen | done | (n_rem_out <= 0)
    if nan_gate:
        frozen_out = frozen_out | ~finite
    n_rem_out = jnp.where(frozen_out, 0, n_rem_out)
    return (tokens, n_new, done, finite, logits, cache, key,
            frozen_out, n_rem_out)


_decode_segment_jit = functools.partial(
    jax.jit,
    static_argnames=("cfg", "chunk", "eos_token_id", "temperature", "top_p",
                     "nan_gate"),
    donate_argnames=("cache",),
)(_decode_segment)


def _spec_segment(
    params,
    cfg: EventChatConfig,
    cache,
    key,
    ids_buf,          # (B, S) committed ids; -1 at event/pad positions
    base_pos,         # (B,) next unwritten ids_buf slot at segment start
    frozen,           # (B,) bool
    n_rem,            # (B,) int32 remaining budget per row
    n_iters: int,
    window: int,
    eos_token_id: int,
    temperature: float = 0.0,
    top_p: float = 1.0,
    history=None,     # (H,) server-wide served-text lookup buffer
    medusa=None,      # trained draft heads (models/medusa.py)
    drafts=None,      # (B, >=W-1) per-row carried drafts (Medusa mode);
                      # may be WIDER than this window (the adaptive
                      # server keeps one (B, max_window-1) resident
                      # buffer across buckets — only the first W-1
                      # columns are consumed/updated, the rest pass
                      # through untouched)
    depth=None,       # (B,) int32 per-row draft-depth cap (ISSUE 13);
                      # None = full depth (the fixed-K server)
):
    """``n_iters`` speculative verify iterations over the shared batch —
    the serving form of ``models/eventchat._spec_loop_jit`` (same
    suffix-vote or trained-head drafting, same greedy/rejection-sampled
    verification) with per-row budgets and a frozen mask, stopping for
    admission every segment. In Medusa mode the drafts ride the loop
    carry (each verify emits the next window's drafts from the correction
    position's hidden); a row whose commit was budget-capped drops out of
    ``live`` the same iteration, so stale drafts are never consumed —
    admission reseeds them from the prefill hidden.

    Invariant per active row: ``cache["length"] == base_pos + n_new - 1``
    (every committed token except the newest has its KV cached; the
    admission path seeds it by committing the prefill argmax/sample as the
    first token). Commits are CAPPED at the remaining budget (no
    overshoot — the row may be harvested right after this segment), and a
    row is ``done`` only when its EOS lands within that cap.

    Returns (ids_buf, n_new (B,), done (B,), cache, key, drafts,
    n_iters_run, frozen_out, n_rem_out, base_pos_out, row_acc (B,),
    row_off (B,), pos_acc (W-1,), pos_off (W-1,)) — ``n_iters_run``
    is the executed iteration count, so the server can report REALIZED
    acceptance (committed tokens per verify iteration) on live traffic
    instead of inferring it; ``frozen_out``/``n_rem_out``/``base_pos_out``
    are the next segment's device-resident control state (the same
    bookkeeping the host harvest applies), so the pipelined scheduler can
    dispatch segment N+1 before fetching segment N. The trailing four are
    the adaptive controller's food (ISSUE 13), all UNCAPPED acceptance
    (budget caps are scheduling, not draft quality): per-row accepted /
    offered draft counts over the segment, and the same split per draft
    POSITION — realized per-head yield for Medusa pruning, per-level
    yield for the lookup chain.
    """
    from eventgpt_tpu.models.eventchat import _spec_draft_verify

    b, s_ids = ids_buf.shape
    bidx = jnp.arange(b)
    iarr = jnp.arange(window)[None, :]
    d_w = max(window - 1, 0)
    iarr1 = jnp.arange(d_w)[None, :]
    eos = eos_token_id
    if drafts is None:
        drafts = jnp.zeros((b, d_w), jnp.int32)

    def cond(state):
        it, _, n_new, done = state[:4]
        live = ~(frozen | done) & (n_new < n_rem)
        return (it < n_iters) & live.any()

    def body(state):
        (it, ids_buf, n_new, done, cache, key, drafts,
         row_acc, row_off, pos_acc, pos_off) = state
        active = ~(frozen | done) & (n_new < n_rem)
        pos = base_pos + n_new
        # The adaptive server's resident draft buffer is max_window
        # wide; this bucket consumes/updates only its first W-1 columns
        # (static slice — identity when the widths match).
        drafts_w = drafts[:, :d_w]
        commit, m_count, first_eos, hit, cache, key, drafts_w = (
            _spec_draft_verify(
                params, cfg, ids_buf, pos, cache, key, window,
                temperature, top_p, eos, history=history,
                medusa=medusa, drafts_in=drafts_w, depth=depth,
            )
        )
        drafts = drafts.at[:, :d_w].set(drafts_w)
        # Acceptance accounting (ISSUE 13): accepted = m_count - 1
        # (the correction token is not a draft), offered = the row's
        # effective depth this verify — both UNCAPPED by budget.
        offered = (jnp.minimum(depth, d_w) if depth is not None
                   else jnp.full((b,), d_w, jnp.int32))
        offered = jnp.where(active, offered, 0)
        acc_i = jnp.where(active, m_count - 1, 0)
        row_acc = row_acc + acc_i
        row_off = row_off + offered
        if d_w:
            pos_acc = pos_acc + (
                (iarr1 < acc_i[:, None]) & active[:, None]
            ).astype(jnp.int32).sum(axis=0)
            pos_off = pos_off + (
                (iarr1 < offered[:, None]) & active[:, None]
            ).astype(jnp.int32).sum(axis=0)
        # Unlike the one-shot loop, commits are CAPPED at the remaining
        # budget (the row may be harvested right after this segment) and a
        # row is done only when its EOS lands within the cap.
        cap = jnp.where(active, n_rem - n_new, 0)
        m_eff = jnp.minimum(jnp.where(hit, first_eos + 1, m_count), cap)

        wpos = jnp.clip(pos[:, None] + iarr, 0, s_ids - 1)
        cur = ids_buf[bidx[:, None], wpos]
        ids_buf = ids_buf.at[bidx[:, None], wpos].set(
            jnp.where(iarr < m_eff[:, None], commit, cur)
        )
        n_new = n_new + m_eff
        done = done | (active & hit & (first_eos + 1 <= cap))
        cache = {**cache, "length": cache["length"] + m_eff}
        return (it + 1, ids_buf, n_new, done, cache, key, drafts,
                row_acc, row_off, pos_acc, pos_off)

    (it, ids_buf, n_new, done, cache, key, drafts,
     row_acc, row_off, pos_acc, pos_off) = lax.while_loop(
        cond, body,
        (jnp.int32(0), ids_buf, jnp.zeros((b,), jnp.int32),
         jnp.zeros((b,), bool), cache, key, drafts,
         jnp.zeros((b,), jnp.int32), jnp.zeros((b,), jnp.int32),
         jnp.zeros((d_w,), jnp.int32), jnp.zeros((d_w,), jnp.int32)),
    )
    # Device-resident scheduler carry (see _decode_segment): the
    # speculative path's NaN gate is the admission check, so the carry is
    # just EOS/budget bookkeeping plus the advanced gather base.
    n_rem_out = n_rem - n_new
    frozen_out = frozen | done | (n_rem_out <= 0)
    n_rem_out = jnp.where(frozen_out, 0, n_rem_out)
    base_pos_out = base_pos + n_new
    return (ids_buf, n_new, done, cache, key, drafts, it,
            frozen_out, n_rem_out, base_pos_out,
            row_acc, row_off, pos_acc, pos_off)


_spec_segment_jit = functools.partial(
    jax.jit,
    static_argnames=("cfg", "n_iters", "window", "eos_token_id",
                     "temperature", "top_p"),
    donate_argnames=("cache",),
)(_spec_segment)


def _admit_row(cache, logits_buf, row, row_cache, row_logits):
    """Insert a batch-1 prefill result at batch row ``row`` of the shared
    cache (dynamic-update on the batch axis; the prompt bucket length of
    ``row_cache`` is a static shape — one compile per bucket)."""

    def ins(buf, rbuf):
        if isinstance(buf, dict):
            return {"q": ins(buf["q"], rbuf["q"]), "s": ins(buf["s"], rbuf["s"])}
        return lax.dynamic_update_slice(
            buf, rbuf.astype(buf.dtype),
            (0, row, 0) + (0,) * (buf.ndim - 3),
        )

    new_cache = {
        "k": ins(cache["k"], row_cache["k"]),
        "v": ins(cache["v"], row_cache["v"]),
        "length": cache["length"].at[row].set(row_cache["length"][0]),
    }
    return new_cache, logits_buf.at[row].set(row_logits[0])


_admit_row_jit = functools.partial(
    jax.jit, donate_argnames=("cache", "logits_buf")
)(_admit_row)


def _admit_wave(cache, logits_buf, rows, wave_k, wave_v, wave_len,
                wave_logits):
    """Scatter one BATCHED admission prefill into the shared cache: every
    wave member's row lands in ONE dispatch instead of N ``_admit_row``
    calls. ``rows`` (Nb,) carries the destination row per wave slot;
    slots padded to the power-of-two wave size (and NaN-quarantined
    members) carry ``row == max_batch``, which is out of bounds — XLA
    DROPS out-of-bounds scatter updates (the same rule the frozen-row
    slack reservation relies on), so pad slots write nothing."""
    s1 = (wave_k["q"] if isinstance(wave_k, dict) else wave_k).shape[2]

    def ins(buf, wbuf):
        if isinstance(buf, dict):
            return {"q": ins(buf["q"], wbuf["q"]),
                    "s": ins(buf["s"], wbuf["s"])}
        return buf.at[:, rows, :s1].set(wbuf.astype(buf.dtype))

    new_cache = {
        "k": ins(cache["k"], wave_k),
        "v": ins(cache["v"], wave_v),
        "length": cache["length"].at[rows].set(
            wave_len.astype(cache["length"].dtype)),
    }
    return new_cache, logits_buf.at[rows].set(wave_logits)


_admit_wave_jit = functools.partial(
    jax.jit, donate_argnames=("cache", "logits_buf")
)(_admit_wave)


def _pool_scatter(buf, dst_blocks, src):
    """Scatter a dense (L, N, S, ...) cache buffer into pool blocks: the
    source's position axis splits into S/block_size whole blocks (S is
    bucket-grained, block_size == SEQ_BUCKET, so it always divides) and
    each lands at ``dst_blocks[i]`` of the (L, n_blocks, block_size, ...)
    arena. Destinations >= n_blocks (the OOB sentinel) are DROPPED by
    XLA's out-of-bounds scatter rule — prefix-ALIASED source blocks
    (their pool content is shared, never rewritten), pad blocks beyond a
    row's reservation, and warmup's dead dispatch all ride it."""
    if isinstance(buf, dict):
        return {"q": _pool_scatter(buf["q"], dst_blocks, src["q"]),
                "s": _pool_scatter(buf["s"], dst_blocks, src["s"])}
    l, bs = buf.shape[0], buf.shape[2]
    n_src = (src.shape[1] * src.shape[2]) // bs
    r = src.reshape((l, n_src, bs) + buf.shape[3:])
    return buf.at[:, dst_blocks.reshape(-1)].set(r.astype(buf.dtype))


def _admit_row_paged(cache, logits_buf, row, dst_blocks, bt_row, row_cache,
                     row_logits):
    """Paged form of ``_admit_row``: scatter the batch-1 prefilled row
    cache into the row's allocated pool blocks and install its block
    table. ``dst_blocks`` (s1/bs,) carries the pool destination per
    source block (OOB = dropped: aliased prefix blocks and beyond-
    reservation pad); ``bt_row`` (nbpr,) is the row's new table (scratch
    0 above the reservation). ``row == max_batch`` drops the bt/length/
    logits update — warmup's dead dispatch."""
    new_cache = {
        "k": _pool_scatter(cache["k"], dst_blocks, row_cache["k"]),
        "v": _pool_scatter(cache["v"], dst_blocks, row_cache["v"]),
        "bt": cache["bt"].at[row].set(bt_row),
        "length": cache["length"].at[row].set(row_cache["length"][0]),
    }
    return new_cache, logits_buf.at[row].set(row_logits[0])


_admit_row_paged_jit = functools.partial(
    jax.jit, donate_argnames=("cache", "logits_buf")
)(_admit_row_paged)


def _admit_wave_paged(cache, logits_buf, rows, dst_blocks, bt_rows, wave_k,
                      wave_v, wave_len, wave_logits):
    """Paged form of ``_admit_wave``: every member's row cache scatters
    into ITS block run in one dispatch. ``dst_blocks`` (Nb, s1/bs) maps
    (member, source block) -> pool block (OOB = dropped: pad members,
    NaN-quarantined members, aliased prefix blocks, beyond-reservation
    pad); ``rows``/``bt_rows`` install tables and lengths with the same
    OOB-drop rule as the dense wave scatter."""
    new_cache = {
        "k": _pool_scatter(cache["k"], dst_blocks, wave_k),
        "v": _pool_scatter(cache["v"], dst_blocks, wave_v),
        "bt": cache["bt"].at[rows].set(bt_rows),
        "length": cache["length"].at[rows].set(
            wave_len.astype(cache["length"].dtype)),
    }
    return new_cache, logits_buf.at[rows].set(wave_logits)


_admit_wave_paged_jit = functools.partial(
    jax.jit, donate_argnames=("cache", "logits_buf")
)(_admit_wave_paged)


def _gather_blocks(k, v, blocks):
    """Dense (L, 1, m*bs, KV, hd) view of ``m`` pool blocks — a paged
    prefix entry's KV for the exclusive suffix / lane-seed paths (the
    same values ``_slice_prefix_block`` would have copied out of a dense
    row; a gather is a copy, so chains stay byte-identical). Inputs are
    never donated: the pool is the resident cache."""

    def g(buf):
        if isinstance(buf, dict):
            return {"q": g(buf["q"]), "s": g(buf["s"])}
        x = buf[:, blocks]  # (L, m, bs, KV, hd)
        return x.reshape((x.shape[0], 1, x.shape[1] * x.shape[2])
                         + x.shape[3:])

    return g(k), g(v)


_gather_blocks_jit = functools.partial(
    jax.jit, donate_argnames=()
)(_gather_blocks)


def _pool_write(cache, dst_blocks, src_k, src_v):
    """Write dense (L, 1, S) K/V buffers into entry-owned pool blocks —
    the operator ``set_prefix`` insert (admissions ride the richer
    ``_admit_row_paged``)."""
    return {**cache,
            "k": _pool_scatter(cache["k"], dst_blocks, src_k),
            "v": _pool_scatter(cache["v"], dst_blocks, src_v)}


_pool_write_jit = functools.partial(
    jax.jit, donate_argnames=("cache",)
)(_pool_write)


def _slice_prefix_block(k, v, row, bucket: int):
    """Copy cache positions [0, bucket) of batch row ``row`` out of a
    prefilled row/wave cache — the insert-on-prefill entry copy (one
    small device-to-device slice per NEW head; repeat heads dedupe before
    ever reaching here). The inputs are not donated: the source cache is
    still owed to the row admission scatter."""

    def sl(buf):
        if isinstance(buf, dict):
            return {"q": sl(buf["q"]), "s": sl(buf["s"])}
        sizes = (buf.shape[0], 1, bucket) + buf.shape[3:]
        start = (jnp.int32(0), row, jnp.int32(0)) + (jnp.int32(0),) * (buf.ndim - 3)
        return lax.dynamic_slice(buf, start, sizes)

    return sl(k), sl(v)


_slice_prefix_jit = functools.partial(
    jax.jit, static_argnames=("bucket",)
)(_slice_prefix_block)


def _chunk_prefill(params, cfg: EventChatConfig, embeds, cache,
                   start, new_len, last_idx, chunk: int):
    """One chunked-admission advance: feed prompt positions
    [start, start+chunk) of ``embeds`` (1, S1, D) through the speculative
    verification kernel (``decode_kstep`` — identical attention semantics
    to one-shot prefill: query i at cache position length+i attends to
    slots [0, length+i]), then pin the cache length to ``new_len`` (the
    real prompt prefix filled so far — trailing chunk positions past the
    prompt are pad, masked from every future read).

    ``start`` must satisfy start+chunk <= S1 (the batcher validates that
    ``chunk`` divides the bucket grain, so dynamic_slice never clamps —
    a clamped slice would desynchronize embed positions from the cache
    write slots). Returns (last_logits (1, V) f32 and last_hidden (1, D)
    at window index ``last_idx`` — the prompt's final real token on the
    finishing chunk, unused otherwise — and the advanced cache).
    """
    emb = lax.dynamic_slice(
        embeds, (0, start, 0), (1, chunk, embeds.shape[-1])
    )
    logits, hidden, cache = llama_mod.decode_kstep(
        params["llama"], cfg.llama, emb, cache, return_hidden=True
    )
    last = jnp.take_along_axis(
        logits, jnp.reshape(last_idx, (1, 1, 1)), axis=1
    )[:, 0]
    # Final-norm hidden at the same position: seeds the Medusa drafts at
    # admission (XLA DCEs it when the caller drops it).
    last_hidden = jnp.take_along_axis(
        hidden, jnp.reshape(last_idx, (1, 1, 1)), axis=1
    )[:, 0]
    return last, last_hidden, {**cache, "length": new_len}


_chunk_prefill_jit = functools.partial(
    jax.jit, static_argnames=("cfg", "chunk"), donate_argnames=("cache",)
)(_chunk_prefill)


def _lane_advance(params, cfg: EventChatConfig, lane_embeds, lane_cache,
                  start, new_len, last_idx, chunk_p: int):
    """One piggybacked chunked-prefill advance over the K resident lanes
    (ISSUE 5): each lane row gathers its own ``chunk_p``-wide window of
    prompt embeddings at ``start`` and runs it through ``decode_kstep``
    against its own lane-cache row — the batched form of
    ``_chunk_prefill``, with the same pad rule (trailing positions past
    the prompt write garbage above ``new_len``, masked from every future
    read). ``start`` is authoritative for the write base (the carried
    lane-cache length is overwritten), so idle/ready lane slots passed
    with ``start == new_len`` advance nothing real — their garbage writes
    land above their pinned length. Gather indices clip at the buffer
    edge, which only ever touches pad positions (the batcher sizes the
    lane bucket to hold every member's prompt).

    Returns (last_logits (K, V), last_hidden (K, D), lane_cache) — the
    last-real-token row of each lane's window, meaningful only on a
    lane's finishing chunk (the batcher slices it there).
    """
    k, s, _ = lane_embeds.shape
    idx = jnp.clip(
        start[:, None] + jnp.arange(chunk_p)[None, :], 0, s - 1
    )
    emb = jnp.take_along_axis(lane_embeds, idx[:, :, None], axis=1)
    lane_cache = {**lane_cache, "length": start}
    logits, hidden, lane_cache = llama_mod.decode_kstep(
        params["llama"], cfg.llama, emb, lane_cache, return_hidden=True
    )
    last = jnp.take_along_axis(
        logits, jnp.reshape(last_idx, (-1, 1, 1)), axis=1
    )[:, 0]
    last_hidden = jnp.take_along_axis(
        hidden, jnp.reshape(last_idx, (-1, 1, 1)), axis=1
    )[:, 0]
    return last, last_hidden, {**lane_cache, "length": new_len}


def _mixed_decode_segment(
    params, cfg: EventChatConfig, logits, cache, key, frozen, n_rem,
    lane_embeds, lane_cache, lane_start, lane_new_len, lane_last_idx,
    chunk: int, chunk_p: int, eos_token_id: int,
    temperature: float = 0.0, top_p: float = 1.0, nan_gate: bool = True,
):
    """The mixed-segment executable (ISSUE 5 tentpole, plain-decode
    form): the unchanged ``_decode_segment`` body PLUS the piggybacked
    prefill lanes, in one dispatch. The two halves touch disjoint state
    (shared cache rows vs lane-cache rows; rows are independent in
    attention), so XLA is free to interleave them and the decode rows'
    tokens commit in the same dispatch that advances the admissions —
    the stall class the exclusive prefill wave had is gone by
    construction. Returns the decode outputs followed by the lane
    outputs of ``_lane_advance``."""
    dec = _decode_segment(
        params, cfg, logits, cache, key, frozen, n_rem, chunk,
        eos_token_id, temperature, top_p, nan_gate,
    )
    lane = _lane_advance(
        params, cfg, lane_embeds, lane_cache, lane_start, lane_new_len,
        lane_last_idx, chunk_p,
    )
    return dec + lane


_mixed_decode_segment_jit = functools.partial(
    jax.jit,
    static_argnames=("cfg", "chunk", "chunk_p", "eos_token_id",
                     "temperature", "top_p", "nan_gate"),
    donate_argnames=("cache", "lane_cache"),
)(_mixed_decode_segment)


def _mixed_spec_segment(
    params, cfg: EventChatConfig, cache, key, ids_buf, base_pos, frozen,
    n_rem, lane_embeds, lane_cache, lane_start, lane_new_len,
    lane_last_idx, n_iters: int, window: int, chunk_p: int,
    eos_token_id: int, temperature: float = 0.0, top_p: float = 1.0,
    history=None, medusa=None, drafts=None, depth=None,
):
    """Mixed segment, speculative form: ``_spec_segment`` + the
    piggybacked prefill lanes in one dispatch (see
    ``_mixed_decode_segment``)."""
    spec = _spec_segment(
        params, cfg, cache, key, ids_buf, base_pos, frozen, n_rem,
        n_iters, window, eos_token_id, temperature, top_p,
        history=history, medusa=medusa, drafts=drafts, depth=depth,
    )
    lane = _lane_advance(
        params, cfg, lane_embeds, lane_cache, lane_start, lane_new_len,
        lane_last_idx, chunk_p,
    )
    return spec + lane


_mixed_spec_segment_jit = functools.partial(
    jax.jit,
    static_argnames=("cfg", "n_iters", "window", "chunk_p",
                     "eos_token_id", "temperature", "top_p"),
    donate_argnames=("cache", "lane_cache"),
)(_mixed_spec_segment)


def _lane_seed(lane_cache, slot, pk, pv):
    """Copy a prefix-cache entry's KV block into lane row ``slot`` at
    position 0 — the 'suffix copies become the piggybacked lane's
    starting offset' rule (ISSUE 5): the lane then advances only the
    suffix, reading the seeded prefix through ``decode_kstep``'s
    attention window exactly as ``_prefix_prefill`` would. The lane
    cache is ALWAYS unquantized (see ``_lane_extract``), so an int8
    entry block dequantizes here — the same values the exclusive suffix
    path's attention reads."""

    def ins(buf, src):
        if isinstance(src, dict):  # int8 entry into the unquant lane
            src = llama_mod._kv_dequant(src, buf.dtype)
        return lax.dynamic_update_slice(
            buf, src.astype(buf.dtype),
            (0, slot, 0) + (0,) * (buf.ndim - 3),
        )

    return {"k": ins(lane_cache["k"], pk), "v": ins(lane_cache["v"], pv),
            "length": lane_cache["length"]}


_lane_seed_jit = functools.partial(
    jax.jit, donate_argnames=("lane_cache",)
)(_lane_seed)


def _lane_extract(lane_k, lane_v, slot, pk, pv, bucket: int, quant: bool,
                  plen: int = 0):
    """Slice lane row ``slot`` into a (1, bucket) admission row cache.

    The lane prefills UNQUANTIZED even on an int8-KV server: one-shot
    ``prefill`` attends over full-precision K/V and quantizes only at
    the cache write, so a lane that quantized per chunk (as
    ``decode_kstep`` does on a quant cache) would read back dequantized
    values mid-prompt and drift off the one-shot chain. Instead the
    quantization happens ONCE, here, from the same full-precision values
    prefill's write sees — byte-identical resident rows. A seeded prefix
    entry's ORIGINAL (q, s) block overlays its region afterwards, so the
    prefix lands exactly as the exclusive suffix path copies it (a
    requantize of the dequantized seed could wobble the scales). Only
    the entry's REAL region [0, plen) overlays — its stored block is
    bucket-length with pad above ``plen``, which must not clobber the
    lane's freshly-prefilled suffix positions."""
    k, v = _slice_prefix_block(lane_k, lane_v, slot, bucket)
    if quant:
        k, v = llama_mod._kv_quantize(k), llama_mod._kv_quantize(v)

        def overlay(buf, src):
            if isinstance(buf, dict):
                return {"q": overlay(buf["q"], src["q"]),
                        "s": overlay(buf["s"], src["s"])}
            src = src[:, :, :plen]
            return lax.dynamic_update_slice(
                buf, src.astype(buf.dtype), (0,) * buf.ndim
            )

        if pk is not None:
            k, v = overlay(k, pk), overlay(v, pv)
    return k, v


_lane_extract_jit = functools.partial(
    jax.jit, static_argnames=("bucket", "quant", "plen")
)(_lane_extract)


def _prefix_prefill(params, cfg: EventChatConfig, pk, pv, plen,
                    cache, suffix_embeds, new_len, last_idx):
    """Admission with a shared-prefix KV seed (VERDICT r4 #7): copy the
    prefix's cached K/V block into the fresh row cache, pin the length to
    the prefix length, and run ONLY the suffix through ``decode_kstep`` —
    identical attention semantics to prefilling the whole prompt (suffix
    query i at position plen+i attends to [0, plen+i], reading the shared
    prefix K/V), at the cost of the suffix instead of the prompt. The
    reference recomputes the full prompt per request
    (``/root/reference/inference.py:52-63``); this is the beyond-parity
    axis for shared-prompt-head traffic.

    BATCHED since ISSUE 4: the same body serves the suffix-admission
    WAVE — ``pk``/``pv`` carry N stacked entry blocks (mixed entries are
    fine: each row copies ITS block; rows are independent in attention),
    ``plen``/``new_len``/``last_idx`` are per-row. The batch-1 call sites
    pass N = 1 and a scalar ``last_idx`` unchanged.

    Trailing suffix-pad positions write garbage K/V above ``new_len`` —
    masked from every future read, same as ``_chunk_prefill``'s pad rule.
    Returns (last_logits (N, V), last_hidden (N, D), advanced cache).
    """

    def copy(buf, src):
        if isinstance(buf, dict):  # quantized plane: payload + scales
            return {"q": copy(buf["q"], src["q"]),
                    "s": copy(buf["s"], src["s"])}
        return lax.dynamic_update_slice(
            buf, src.astype(buf.dtype), (0,) * buf.ndim
        )

    cache = {
        "k": copy(cache["k"], pk),
        "v": copy(cache["v"], pv),
        "length": plen,
    }
    logits, hidden, cache = llama_mod.decode_kstep(
        params["llama"], cfg.llama, suffix_embeds, cache, return_hidden=True
    )
    last = jnp.take_along_axis(
        logits, jnp.reshape(last_idx, (-1, 1, 1)), axis=1
    )[:, 0]
    last_hidden = jnp.take_along_axis(
        hidden, jnp.reshape(last_idx, (-1, 1, 1)), axis=1
    )[:, 0]
    return last, last_hidden, {**cache, "length": new_len}


_prefix_prefill_jit = functools.partial(
    jax.jit, static_argnames=("cfg",), donate_argnames=("cache",)
)(_prefix_prefill)


@functools.partial(jax.jit, static_argnames=("width",))
def _gather_new_jit(ids_buf, base_pos, width: int):
    """Per-row window ``ids_buf[r, base_pos[r] : base_pos[r] + width]`` —
    the speculative harvest reads back only the slots a segment could have
    written (width >= n_iters * window) instead of the whole (B, max_len)
    buffer, so host-transfer cost scales with tokens produced, not cache
    size."""
    b, s = ids_buf.shape
    idx = jnp.clip(
        base_pos[:, None] + jnp.arange(width)[None, :], 0, s - 1
    )
    return ids_buf[jnp.arange(b)[:, None], idx]


# -- mesh-sharded scheduler jits ------------------------------------------
#
# Same bodies as the single-chip jits above, with OUTPUT SHARDINGS PINNED
# to the resident buffers' placement. Without the pin, GSPMD may lay the
# returned cache out differently from the donated input cache, silently
# breaking buffer aliasing — a second full-size cache allocation per
# segment (the _get_sharded_prefill reasoning, models/eventchat.py).
# Keyed per (config, statics, shardings): one compile per serving setup.


@functools.lru_cache(maxsize=16)
def _get_sharded_decode_segment(
    cfg, chunk, eos_token_id, temperature, top_p, nan_gate,
    flat_cache_sh, cache_treedef, logits_sh, toks_sh, b_sh, key_sh,
):
    cache_sh = jax.tree_util.tree_unflatten(cache_treedef, list(flat_cache_sh))
    return jax.jit(
        lambda params, logits, cache, key, frozen, n_rem: _decode_segment(
            params, cfg, logits, cache, key, frozen, n_rem,
            chunk, eos_token_id, temperature, top_p, nan_gate,
        ),
        donate_argnums=(2,),
        # The trailing (b_sh, b_sh) pins the device-resident carry
        # (frozen_out, n_rem_out) to the batch placement so the pipelined
        # re-dispatch feeds it straight back without a reshard.
        out_shardings=(toks_sh, b_sh, b_sh, b_sh, logits_sh, cache_sh,
                       key_sh, b_sh, b_sh),
    )


@functools.lru_cache(maxsize=16)
def _get_sharded_spec_segment(
    cfg, n_iters, window, eos_token_id, temperature, top_p,
    flat_cache_sh, cache_treedef, ids_sh, b_sh, key_sh, drafts_sh,
):
    cache_sh = jax.tree_util.tree_unflatten(cache_treedef, list(flat_cache_sh))
    scalar_sh = jax.sharding.NamedSharding(
        key_sh.mesh, jax.sharding.PartitionSpec()
    )
    return jax.jit(
        lambda params, cache, key, ids_buf, base_pos, frozen, n_rem, history,
        medusa, drafts, depth=None:
        _spec_segment(
            params, cfg, cache, key, ids_buf, base_pos, frozen, n_rem,
            n_iters, window, eos_token_id, temperature, top_p,
            history=history, medusa=medusa, drafts=drafts, depth=depth,
        ),
        donate_argnums=(1,),
        # (b_sh, b_sh, b_sh) after it: the pipelined carry pins
        # (frozen_out, n_rem_out, base_pos_out) — see the decode
        # variant. Trailing: acceptance accounting (row_* batch-placed,
        # pos_* replicated — ISSUE 13).
        out_shardings=(ids_sh, b_sh, b_sh, cache_sh, key_sh, drafts_sh,
                       scalar_sh, b_sh, b_sh, b_sh,
                       b_sh, b_sh, scalar_sh, scalar_sh),
    )


@functools.lru_cache(maxsize=16)
def _get_sharded_admit(flat_cache_sh, cache_treedef, logits_sh):
    cache_sh = jax.tree_util.tree_unflatten(cache_treedef, list(flat_cache_sh))
    return jax.jit(
        _admit_row,
        donate_argnums=(0, 1),
        out_shardings=(cache_sh, logits_sh),
    )


@functools.lru_cache(maxsize=16)
def _get_sharded_chunk_prefill(cfg, chunk, flat_row_sh, row_treedef, last_sh,
                               hidden_sh):
    row_sh = jax.tree_util.tree_unflatten(row_treedef, list(flat_row_sh))
    return jax.jit(
        lambda params, embeds, cache, start, new_len, last_idx:
        _chunk_prefill(
            params, cfg, embeds, cache, start, new_len, last_idx, chunk
        ),
        donate_argnums=(2,),
        out_shardings=(last_sh, hidden_sh, row_sh),
    )


@functools.lru_cache(maxsize=16)
def _get_sharded_prefix_prefill(cfg, flat_row_sh, row_treedef, last_sh,
                                hidden_sh):
    row_sh = jax.tree_util.tree_unflatten(row_treedef, list(flat_row_sh))
    return jax.jit(
        lambda params, pk, pv, plen, cache, suffix_embeds, new_len, last_idx:
        _prefix_prefill(
            params, cfg, pk, pv, plen, cache, suffix_embeds, new_len,
            last_idx,
        ),
        donate_argnums=(4,),
        out_shardings=(last_sh, hidden_sh, row_sh),
    )


@functools.lru_cache(maxsize=16)
def _get_sharded_admit_wave(flat_cache_sh, cache_treedef, logits_sh):
    """Batched-admission scatter with the shared cache/logits placement
    pinned (same aliasing reasoning as ``_get_sharded_admit``: an
    unpinned output would silently break the donated-cache aliasing)."""
    cache_sh = jax.tree_util.tree_unflatten(cache_treedef, list(flat_cache_sh))
    return jax.jit(
        _admit_wave,
        donate_argnums=(0, 1),
        out_shardings=(cache_sh, logits_sh),
    )


@functools.lru_cache(maxsize=16)
def _get_sharded_admit_paged(flat_cache_sh, cache_treedef, logits_sh):
    """Paged row admission under a mesh, with the pool/table placement
    pinned (the donated-cache aliasing rule, same as the dense admit)."""
    cache_sh = jax.tree_util.tree_unflatten(cache_treedef, list(flat_cache_sh))
    return jax.jit(
        _admit_row_paged,
        donate_argnums=(0, 1),
        out_shardings=(cache_sh, logits_sh),
    )


@functools.lru_cache(maxsize=16)
def _get_sharded_admit_wave_paged(flat_cache_sh, cache_treedef, logits_sh):
    cache_sh = jax.tree_util.tree_unflatten(cache_treedef, list(flat_cache_sh))
    return jax.jit(
        _admit_wave_paged,
        donate_argnums=(0, 1),
        out_shardings=(cache_sh, logits_sh),
    )


@functools.lru_cache(maxsize=16)
def _get_sharded_pool_write(flat_cache_sh, cache_treedef):
    cache_sh = jax.tree_util.tree_unflatten(cache_treedef, list(flat_cache_sh))
    return jax.jit(
        _pool_write, donate_argnums=(0,), out_shardings=cache_sh,
    )


@functools.lru_cache(maxsize=32)
def _get_sharded_gather_blocks(block_sh, quant):
    """Paged entry-KV gather under a mesh: output block pinned to the
    prefix-entry placement (``parallel/serving.prefix_block_sharding``),
    same as the dense ``_get_sharded_slice_prefix``."""
    out_sh = ({"q": block_sh, "s": block_sh} if quant else block_sh)
    return jax.jit(_gather_blocks, out_shardings=(out_sh, out_sh))


@functools.lru_cache(maxsize=32)
def _get_sharded_slice_prefix(bucket, block_sh, quant):
    """Entry copy (insert-on-prefill) under a mesh, with the output block
    pinned to the prefix-entry placement (``parallel/serving.
    prefix_block_sharding``: KV heads over ``model``, everything else
    replicated — batch is 1, so the batch axes drop out)."""
    out_sh = ({"q": block_sh, "s": block_sh} if quant else block_sh)
    return jax.jit(
        lambda k, v, row: _slice_prefix_block(k, v, row, bucket),
        out_shardings=(out_sh, out_sh),
    )


@functools.lru_cache(maxsize=16)
def _get_sharded_mixed_decode_segment(
    cfg, chunk, chunk_p, eos_token_id, temperature, top_p, nan_gate,
    flat_cache_sh, cache_treedef, logits_sh, toks_sh, b_sh, key_sh,
    flat_lane_sh, lane_treedef, lane_emb_sh, lane_last_sh, lane_hidden_sh,
):
    """Mixed decode segment under the serving mesh: the decode half pins
    the same carry/cache shardings as ``_get_sharded_decode_segment``;
    the lane half pins the lane cache to its resident placement
    (``parallel/serving.shard_kv_cache`` at batch K) so the donated lane
    buffers keep aliasing across boundaries."""
    cache_sh = jax.tree_util.tree_unflatten(cache_treedef, list(flat_cache_sh))
    lane_sh = jax.tree_util.tree_unflatten(lane_treedef, list(flat_lane_sh))
    return jax.jit(
        lambda params, logits, cache, key, frozen, n_rem, lane_embeds,
        lane_cache, lane_start, lane_new_len, lane_last_idx:
        _mixed_decode_segment(
            params, cfg, logits, cache, key, frozen, n_rem, lane_embeds,
            lane_cache, lane_start, lane_new_len, lane_last_idx,
            chunk, chunk_p, eos_token_id, temperature, top_p, nan_gate,
        ),
        donate_argnums=(2, 7),
        out_shardings=(toks_sh, b_sh, b_sh, b_sh, logits_sh, cache_sh,
                       key_sh, b_sh, b_sh,
                       lane_last_sh, lane_hidden_sh, lane_sh),
    )


@functools.lru_cache(maxsize=16)
def _get_sharded_mixed_spec_segment(
    cfg, n_iters, window, chunk_p, eos_token_id, temperature, top_p,
    flat_cache_sh, cache_treedef, ids_sh, b_sh, key_sh, drafts_sh,
    flat_lane_sh, lane_treedef, lane_emb_sh, lane_last_sh, lane_hidden_sh,
):
    cache_sh = jax.tree_util.tree_unflatten(cache_treedef, list(flat_cache_sh))
    lane_sh = jax.tree_util.tree_unflatten(lane_treedef, list(flat_lane_sh))
    scalar_sh = jax.sharding.NamedSharding(
        key_sh.mesh, jax.sharding.PartitionSpec()
    )
    return jax.jit(
        lambda params, cache, key, ids_buf, base_pos, frozen, n_rem,
        history, medusa, drafts, lane_embeds, lane_cache, lane_start,
        lane_new_len, lane_last_idx, depth=None:
        _mixed_spec_segment(
            params, cfg, cache, key, ids_buf, base_pos, frozen, n_rem,
            lane_embeds, lane_cache, lane_start, lane_new_len,
            lane_last_idx, n_iters, window, chunk_p, eos_token_id,
            temperature, top_p, history=history, medusa=medusa,
            drafts=drafts, depth=depth,
        ),
        donate_argnums=(1, 11),
        out_shardings=(ids_sh, b_sh, b_sh, cache_sh, key_sh, drafts_sh,
                       scalar_sh, b_sh, b_sh, b_sh,
                       b_sh, b_sh, scalar_sh, scalar_sh,
                       lane_last_sh, lane_hidden_sh, lane_sh),
    )


@functools.lru_cache(maxsize=32)
def _get_sharded_lane_extract(bucket, quant, block_sh, plen):
    """Lane-row extraction under a mesh, with the admission row-cache
    block pinned to the prefix-entry placement (same reasoning as
    ``_get_sharded_slice_prefix``)."""
    out_sh = ({"q": block_sh, "s": block_sh} if quant else block_sh)
    return jax.jit(
        lambda k, v, slot, pk, pv: _lane_extract(
            k, v, slot, pk, pv, bucket, quant, plen),
        out_shardings=(out_sh, out_sh),
    )


@functools.lru_cache(maxsize=16)
def _get_sharded_lane_seed(flat_lane_sh, lane_treedef):
    """Entry-KV seed of one lane row with the lane cache's placement
    pinned (the donated-buffer aliasing rule, same as every other
    resident-state jit here)."""
    lane_sh = jax.tree_util.tree_unflatten(lane_treedef, list(flat_lane_sh))
    return jax.jit(
        _lane_seed, donate_argnums=(0,), out_shardings=lane_sh,
    )


@dataclass
class _PendingLane:
    """One piggybacked admission (ISSUE 5): the row is reserved (frozen),
    the prompt embeddings sit in lane-embeds slot ``slot``, and every
    mixed segment advances the lane ``chunk_p`` prompt positions against
    its lane-cache row until ``filled >= prompt_len`` — then the lane's
    row cache is sliced out and joins the shared cache through the
    normal admission tail (``_finish_admission``). For a prefix-cache
    hit, the entry's KV was seeded at [0, filled0) and only the suffix
    embeds were loaded."""
    req: "_Request"
    row: int
    slot: int
    prompt_len: int
    filled: int = 0
    entry: Optional["_PrefixEntry"] = None
    last_logits: Any = None   # (1, V) future, valid after the final chunk
    last_hidden: Any = None   # (1, D) future, Medusa seeding


@dataclass
class _PendingAdmission:
    """A chunked admission in flight: the row is reserved (frozen), the
    prompt prefix [0, filled) is prefilled into ``row_cache``, and one
    chunk advances per scheduler step so active rows keep decoding."""
    req: "_Request"
    row: int
    embeds: Any          # (1, S1, D) padded prompt embeddings
    prompt_len: int
    row_cache: Any
    filled: int = 0
    last_logits: Any = None


@dataclass
class _Request:
    rid: int
    input_ids: Sequence[int]
    pixel_values: Any
    max_new_tokens: int
    tokens: List[int] = field(default_factory=list)
    row: int = -1
    # Cache positions the prompt will occupy (text + event tokens) —
    # computed once at submit; the memory headroom guard predicts the
    # next admission wave's bytes from it without re-walking input_ids.
    prompt_len: int = 0
    # Service timestamps (time.perf_counter at submit / first committed
    # token / completion) — the continuous-batching latency story: TTFT
    # and completion latency per request, aggregated by bench --mode serve.
    t_submit: float = 0.0
    t_first: Optional[float] = None
    t_done: Optional[float] = None
    # Imported handoffs rebase t_submit into the past by the prefill
    # leg's shipped duration so stats/SLO score the whole life; the
    # journey leg must stay LOCAL (the coordinator stitches legs from
    # durations) — this holds the local begin stamp for it.
    t_journey: Optional[float] = None
    # Last harvest that committed tokens for this row (inter-token-latency
    # telemetry: gaps between consecutive harvests, weighted by tokens).
    t_last: Optional[float] = None
    # Telemetry phase of the request's async trace span: "queued" until it
    # leaves the admission queue, then "active"; _record_finish closes
    # whichever is open (obs/trace.py request-lifecycle events).
    phase: str = "queued"
    # Absolute perf_counter deadline (None = no deadline). Enforced both
    # while queued and between decode segments: an expired row is frozen
    # and finished with STATUS_DEADLINE instead of burning its budget.
    deadline: Optional[float] = None
    # Prefix-cache entry this row admitted from (refcount pin: the entry
    # cannot be LRU-evicted until the row finishes; _record_finish drains
    # it). None for full-prefill admissions.
    prefix_entry: Optional["_PrefixEntry"] = None
    # Service-level objective (ISSUE 6): the class + targets this
    # request is scored against at finish (workload.SLO; None = unscored
    # — the pre-SLO behavior). Scoring reads clocks and host state only,
    # so chains are byte-identical with or without an SLO attached.
    slo: Optional[SLO] = None
    # Paged KV reservation (ISSUE 12): pool blocks this request holds —
    # ``owned`` at refcount 1 (its private writable run), ``aliased``
    # shared with a prefix entry (incref'd full blocks below the
    # divergence point). Both decref on EVERY terminal/export path
    # (``_paged_release``); ``kv_bt_written`` marks that the row's
    # device block table points at them and must be reset to scratch.
    kv_blocks_owned: List[int] = field(default_factory=list)
    kv_blocks_aliased: List[int] = field(default_factory=list)
    kv_bt_written: bool = False
    # Block-tier preemption (ISSUE 16): while a preempted request waits
    # re-queued, ``spill_run`` names its BlockPool spill registry entry
    # (None = the drop-and-re-prefill path, or never preempted) and the
    # SpillStore holds its gathered KV under ``rid``. ``preempts``
    # counts evictions (observability; bench records it per request).
    spill_run: Optional[int] = None
    preempts: int = 0
    # Prefill/decode disaggregation (ISSUE 17): on a decode-role worker,
    # the gathered block-run record this request arrived with (the
    # spill-record shape, shipped over RPC). ``_admit`` splices it into
    # the local arena instead of re-prefilling; cleared once spliced.
    handoff_rec: Optional[Dict[str, Any]] = None


class ContinuousBatcher:
    """Row-level continuous batching over one resident KV cache.

    >>> srv = ContinuousBatcher(params, cfg, max_batch=4, max_len=1024)
    >>> rid = srv.submit(input_ids, pixel_values, max_new_tokens=64)
    >>> answers = srv.run_until_drained()   # {rid: [token ids]}

    Greedy by default (temperature 0); sampling configs apply serverwide.

    ``mesh``: a serving ``Mesh`` (data/fsdp/model, context=1). ``params``
    must already be placed by ``parallel.serving.shard_params_for_serving``;
    the batcher places its resident cache / logits / ids_buf to match and
    pins every scheduler jit's out-shardings (BASELINE config 5: 13B
    continuous batching needs the serving mesh AND row-level admission at
    once — vs the reference's single-GPU one-shot ``inference.py:52-63``).

    Threading contract (egpt_check rule ``lock``): this class is
    single-threaded BY DESIGN — every method touches resident device
    buffers, and the owning ``ServingEngine`` serializes all access
    behind its ``_lock`` (``_EXTERNAL_LOCK`` below). It must never
    spawn a thread or grow a lock of its own; state shared lock-free
    with handler threads (``request_stats``, ``finished`` snapshots)
    is read-only on their side and bounded here.

    Dispatch-path contract (rule ``hot-sync``): the hot set rooted at
    ``step``/``_dispatch_segment`` (``_HOT_ROOTS``) contains no host
    sync — ``.item()``, ``jax.device_get``, ``np.asarray`` of device
    values, ``block_until_ready`` — except at the three annotated
    harvest points (``_harvest_segment``; the admission NaN-quarantine
    readbacks in ``_scatter_wave``/``_finish_admission``). That is the
    static guarantee behind the pipelined scheduler's overlap ratio.
    """

    _EXTERNAL_LOCK = "ServingEngine._lock"
    _HOT_ROOTS = ("step", "_dispatch_segment")

    def __init__(
        self,
        params,
        cfg: EventChatConfig,
        max_batch: int = 4,
        max_len: int = 1024,
        chunk: int = 32,
        temperature: float = 0.0,
        top_p: float = 1.0,
        eos_token_id: Optional[int] = 2,
        seed: int = 0,
        kv_quant: bool = False,
        speculative: int = 0,
        mesh=None,
        prefill_chunk: int = 0,
        history_len: int = 2048,
        draft_head=None,
        first_chunk: int = 0,
        max_queue: int = 0,
        nan_check: bool = True,
        pipeline: bool = True,
        prefix_cache: bool = True,
        prefix_cache_bytes: int = 0,
        prefix_insert: bool = True,
        prefill_budget: int = 0,
        prefill_lane_chunk: int = 0,
        slo_window: int = 256,
        mem_headroom_bytes: int = 0,
        mem_capacity_bytes: int = 0,
        kv_layout: str = "dense",
        kv_pool_blocks: int = 0,
        preempt: bool = False,
        spill_capacity_mb: int = 0,
        spec_buckets=None,
        spec_ema_alpha: float = 0.3,
        spec_draft_cost: float = 0.05,
        spec_hysteresis: float = 0.05,
        spec_row_window: int = 4,
        spec_head_min_yield: float = 0.05,
        role: str = "colocated",
    ):
        if prefill_chunk and (2 * SEQ_BUCKET) % prefill_chunk:
            # A chunk that does not divide the bucket grain would force
            # dynamic_slice to clamp the final chunk's start, desyncing
            # embed positions from cache write slots (_chunk_prefill).
            raise ValueError(
                f"prefill_chunk must divide the prompt bucket grain "
                f"{2 * SEQ_BUCKET}, got {prefill_chunk}"
            )
        if mesh is not None:
            import dataclasses

            from eventgpt_tpu.parallel import serving as serving_mod

            serving_mod._require_serving_mesh(mesh)
            model_n = mesh.shape.get("model", 1)
            if (cfg.llama.attn_impl == "flash"
                    and cfg.llama.num_heads % model_n != 0):
                # Same downgrade as generate(): flash under a mesh runs
                # per-shard with heads over model; dense scores are the
                # safe prefill fallback when heads don't divide.
                cfg = dataclasses.replace(
                    cfg,
                    llama=dataclasses.replace(cfg.llama, attn_impl="dense"),
                )
        self.mesh = mesh
        self.params, self.cfg = params, cfg
        # Admission pads prompts to the serving bucket grain; a max_len off
        # the grain would let a bucketed row_cache outgrow the shared cache
        # (a trace-time shape crash). Round up once here.
        grain = 2 * SEQ_BUCKET
        max_len = ((max_len + grain - 1) // grain) * grain
        self.max_batch, self.max_len, self.chunk = max_batch, max_len, chunk
        # TTFT ramp: while any active row still owes its FIRST token, run
        # segments of this length instead of the full chunk, so fresh
        # admissions surface a token after ~first_chunk iterations rather
        # than a whole segment (VERDICT r4 #4 — the 0.2 s prefill /
        # multi-second TTFT gap is segment granularity, not prefill).
        # 0 disables; costs one extra cached executable per segment kind.
        # Speculative rows commit their first token AT admission
        # (_admit_speculative), so the ramp predicate (an active row with
        # t_first unset) is unsatisfiable there — drop the flag rather
        # than compile a ramp executable no segment can ever select.
        self.first_chunk = (
            min(int(first_chunk), chunk)
            if first_chunk and not speculative and not spec_buckets else 0
        )
        self.temperature, self.top_p = float(temperature), float(top_p)
        self.eos = eos_token_id if eos_token_id is not None else -1
        self.eos_token_id = eos_token_id
        self._dtype = jax.tree_util.tree_leaves(params["llama"])[0].dtype
        if self._dtype not in (jnp.bfloat16, jnp.float32):
            self._dtype = jnp.bfloat16  # quantized tree: compute in bf16
        self.kv_quant = kv_quant
        # KV layout (ISSUE 12 tentpole): "dense" keeps one (B, max_len)
        # row per batch slot; "paged" replaces it with ONE block-pool
        # arena (n_blocks × SEQ_BUCKET positions per layer/plane) plus
        # per-row int32 block tables — allocation becomes block-granular
        # (admission gated by FREE BLOCKS, not batch × max_len), prefix
        # "copies" become table aliasing with copy-on-write, and every
        # jit-visible shape stays static. Chains are byte-identical
        # across layouts (the gather/scatter translation is pure
        # indexing — tests/test_paged_blocks.py holds the full matrix).
        if kv_layout not in ("dense", "paged"):
            raise ValueError(
                f"kv_layout must be 'dense' or 'paged', got {kv_layout!r}")
        # Disaggregated serving role (ISSUE 17): "colocated" (default)
        # admits AND decodes — the single-engine behavior, unchanged.
        # "prefill" runs chunked/batched admission only: each activated
        # row's block run is gathered and parked in ``handoff_ready``
        # for the fleet coordinator to ship (``_handoff_sweep``).
        # "decode" additionally accepts gathered records through
        # ``import_handoff`` and splices them into its own arena. The
        # handoff record is block-shaped (the PR 16 spill record), so
        # split roles require the paged layout.
        if role not in ("colocated", "prefill", "decode"):
            raise ValueError(
                f"role must be 'colocated', 'prefill' or 'decode', "
                f"got {role!r}")
        if role != "colocated" and kv_layout != "paged":
            raise ValueError(
                f"role={role!r} requires kv_layout='paged' (the handoff "
                f"moves block runs)")
        self.role = role
        if role == "prefill":
            # Piggyback lanes advance inside the decode dispatch, which
            # a prefill-role scheduler never runs — lanes would starve.
            # Chunked/wave admission covers the prefill worker's job.
            prefill_budget = 0
        self.kv_layout = kv_layout
        self._paged = kv_layout == "paged"
        self._pool: Optional[serve_blocks.BlockPool] = None
        if self._paged:
            self._kv_block_size = SEQ_BUCKET
            self._nbpr = max_len // SEQ_BUCKET  # table width (blocks/row)
            # Default pool = dense-equivalent capacity (+1 scratch): the
            # layout change alone never shrinks what fits. Operators cap
            # it lower (--kv_pool_blocks) to trade peak concurrency for
            # HBM — the bench's paged batch-sweep leg does exactly that.
            n_blocks = int(kv_pool_blocks) or (max_batch * self._nbpr + 1)
            min_blocks = (2 * SEQ_BUCKET) // SEQ_BUCKET + 1
            if n_blocks < min_blocks:
                # One prompt-grain bucket + scratch is the floor; a
                # request needing more than the pool holds is rejected
                # loudly at submit() (the per-request fit rule).
                raise ValueError(
                    f"kv_pool_blocks={n_blocks} cannot hold one prompt "
                    f"bucket ({min_blocks - 1} blocks + 1 scratch)")
            self.cache = llama_mod.init_paged_kv_cache(
                cfg.llama, max_batch, max_len, n_blocks, SEQ_BUCKET,
                dtype=self._dtype, quant=kv_quant,
            )
        else:
            self.cache = llama_mod.init_kv_cache(
                cfg.llama, max_batch, max_len, dtype=self._dtype,
                quant=kv_quant
            )
        # Vocab from the actual lm_head leaf, not cfg: special-token
        # registration can grow the embeddings past cfg.llama.vocab_size
        # (prepare_model's resize).
        vocab = eventchat._vocab_size(params)
        self.logits = jnp.zeros((max_batch, vocab), jnp.float32)
        # Speculative serving (window > 0): rows draft from their own
        # committed-token buffer; the prefill argmax/sample is committed at
        # admission (the _spec_segment_jit invariant) so no logits state
        # carries between segments.
        self.speculative = int(speculative)
        # Adaptive speculation (ISSUE 13 tentpole): ``spec_buckets``
        # (e.g. "0,2,4,8") makes the verification window a PER-DISPATCH-
        # BOUNDARY decision — the jax-free ``serve_spec.SpecController``
        # tracks the realized acceptance EMA + per-row windows the
        # harvest feeds it, and each boundary selects one precompiled
        # bucket executable (K=0 -> the draft-free window-1 segment, so
        # pathological traffic degrades to baseline cost) plus a per-row
        # draft-depth mask. ``speculative`` becomes the DEFAULT window
        # (the fault-degradation bucket; max bucket when 0). Chains are
        # byte-identical to any fixed K — verification makes every
        # draft exact, depth only moves latency.
        _buckets = serve_spec.parse_spec_buckets(spec_buckets) \
            if isinstance(spec_buckets, (str, type(None))) \
            else tuple(sorted({max(int(k), 1) for k in spec_buckets}))
        self._spec_ctl: Optional[serve_spec.SpecController] = None
        self.spec_windows: Optional[tuple] = None
        if _buckets:
            if not self.speculative:
                self.speculative = max(_buckets)
            self._spec_ctl = serve_spec.SpecController(
                _buckets, default_window=self.speculative,
                ema_alpha=spec_ema_alpha, draft_cost=spec_draft_cost,
                hysteresis=spec_hysteresis, row_window=spec_row_window,
                head_min_yield=spec_head_min_yield,
                # The mixed-boundary draft budget is the SAME token
                # budget lane admission enforces (ISSUE 5): drafts and
                # piggybacked prefill compete for boundary latency.
                draft_budget=max(int(prefill_budget), 0),
            )
            self.spec_windows = self._spec_ctl.windows
        # Buffer/slack sizing bound: the largest window any boundary can
        # select (== speculative for the fixed-K server).
        self.spec_max = (self._spec_ctl.max_window if self._spec_ctl
                         else self.speculative)
        self.draft_head = draft_head
        if draft_head is not None:
            if not self.speculative:
                raise ValueError(
                    "draft_head requires speculative=K > 0 (the heads "
                    "draft into the K-token verification window)"
                )
            from eventgpt_tpu.models.medusa import num_draft_heads

            n_heads = num_draft_heads(draft_head)
            if n_heads < self.spec_max - 1:
                # Validate at construction: the first medusa_drafts call
                # otherwise raises at ADMISSION time, tearing down the
                # serving loop mid-drain (the submit()-validation rule).
                # Adaptive serving seeds/carries max_window-1 drafts.
                raise ValueError(
                    f"draft_head has {n_heads} heads but the largest "
                    f"speculation window {self.spec_max} needs "
                    f"{self.spec_max - 1}"
                )
        if self.speculative:
            self.ids_buf = jnp.full((max_batch, self.max_len), -1, jnp.int32)
            self.base_pos = np.zeros((max_batch,), np.int64)
            # Per-row carried drafts (consumed only in Medusa mode; a
            # zeros dummy otherwise keeps the segment signature uniform).
            # Sized to the LARGEST bucket — every bucket's executable
            # consumes/updates its first W-1 columns of the same
            # resident buffer (no per-switch reshape, no extra dispatch).
            self.spec_drafts = jnp.zeros(
                (max_batch, max(self.spec_max - 1, 0)), jnp.int32
            )
        # Server-wide served-text history: a chronological buffer of prompt
        # text + committed answers across ALL requests, used as extra
        # lookup context by the speculative draft (_suffix_vote_drafts) —
        # cross-request echo ("The scene depicts...") is draftable even on
        # a request's first turn. 0 disables.
        self._history = (
            np.full((int(history_len),), -1, np.int64)
            if self.speculative and history_len else None
        )
        self.key = jax.random.PRNGKey(seed)
        if mesh is not None:
            self._init_mesh_placement(vocab)
        self.frozen = np.ones((max_batch,), bool)   # all rows FREE
        self.n_rem = np.zeros((max_batch,), np.int64)
        self.rows: List[Optional[_Request]] = [None] * max_batch
        self.queue: deque[_Request] = deque()
        # Prefill->decode handoff outbox (ISSUE 17): records the
        # prefill role's sweep gathered, awaiting coordinator
        # collection (``pop_handoffs``); the counters feed the /fleet
        # role block and the /stats fleet-wide aggregation.
        self.handoff_ready: List[Dict[str, Any]] = []
        self.handoffs_gathered = 0
        self.handoffs_gathered_bytes = 0
        self.handoffs_spliced = 0
        self.handoffs_spliced_bytes = 0
        self.finished: Dict[int, List[int]] = {}
        # Terminal status per finished rid (STATUS_*): drained by the
        # serving engine at harvest; bounded for direct batcher users the
        # same way request_stats is.
        self.finish_status: Dict[int, str] = {}
        # 0 = unbounded (library default; the HTTP front end passes its
        # --max_queue). A bounded queue turns overload into an explicit
        # QueueFullError at submit instead of unbounded host growth.
        self.max_queue = int(max_queue)
        self.nan_check = bool(nan_check)
        # Live requests carrying a deadline (maintained by submit /
        # _record_finish): the per-step expiry scan is skipped outright
        # when zero, so deadline-less traffic pays nothing.
        self._n_deadlines = 0
        self._next_rid = 0
        self.prefill_chunk = int(prefill_chunk)
        self._pending: Optional[_PendingAdmission] = None
        # Prefix-KV cache (ISSUE 4 tentpole): the multi-entry trie that
        # replaced the single set_prefix slot. ``prefix_cache=False`` is
        # the A/B escape hatch (every admission full-prefills);
        # ``prefix_insert=False`` keeps lookups but disables the
        # automatic insert-on-prefill population (operator-set entries
        # only — the r5 single-slot behavior, for benchmarking).
        self._prefix_cache = (
            PrefixCache(int(prefix_cache_bytes)) if prefix_cache else None
        )
        self.prefix_insert = bool(prefix_insert)
        # Per-position K+V bytes of one resident cache row — the prefix
        # cache's accounting unit (entry nbytes = bucket * this; derived
        # from the live buffers so int8-KV halves it automatically).
        _kv_leaves = jax.tree_util.tree_leaves(
            {"k": self.cache["k"], "v": self.cache["v"]})
        _kv_positions = (
            self._pool_n_blocks() * self._kv_block_size if self._paged
            else max_batch * self.max_len)
        self._kv_pos_bytes = max(
            1, sum(x.nbytes for x in _kv_leaves) // _kv_positions)
        if self._paged:
            # The ONE allocator rows, prefix entries and COW share
            # (serve_blocks.BlockPool): refcounted free list over the
            # arena, scratch block 0 reserved for dead-row writes.
            self._pool = serve_blocks.BlockPool(
                self._pool_n_blocks(), SEQ_BUCKET,
                block_bytes=SEQ_BUCKET * self._kv_pos_bytes)
            self.block_deferrals = 0
            if self._prefix_cache is not None:
                # Paged entries pin pool blocks; eviction decrefs them.
                self._prefix_cache.pool = self._pool
        # Block-tier preemption + host-RAM KV spill (ISSUE 16): with
        # ``preempt`` armed (paged layout only), an interactive
        # admission the free list cannot cover EVICTS the lowest-value
        # active rows instead of deferring — each victim's KV either
        # spills to the pinned host store (byte-exact restore through
        # the paged admission seam) or drops for re-prefill, chosen per
        # request by measured spill bytes/bandwidth vs recompute FLOPs.
        # Off by default: the defer-only baseline is unchanged.
        self.preempt = bool(preempt) and self._paged
        self._spill_store: Optional[serve_blocks.SpillStore] = None
        if self._paged:
            self._spill_store = serve_blocks.SpillStore(
                max(int(spill_capacity_mb), 0) * (1 << 20),
                owner=f"b{id(self):x}")
        self.preemptions = 0
        # Spill-vs-recompute policy state: device->host bandwidth EWMA
        # (re-measured at every gather) and the recompute rate seed.
        # Recompute is priced estimate()-consistently: ~2 * params *
        # positions FLOPs re-prefilled at the assumed sustained rate.
        self._spill_bw_Bps = 5e9
        self._spill_param_count = max(
            obs_memory.params_bytes(params) // 2, 1)
        self._recompute_flops_per_s = 5e12
        # Pipelined scheduling (the default): between-segment control state
        # (frozen / n_rem / base_pos) ALSO lives on device, updated
        # in-graph by the segment kernels, so segment N+1 is dispatched
        # from device state before segment N's outputs are fetched and the
        # host harvest runs concurrently with device compute. Double-
        # buffered: at most ONE segment in flight; admissions, cancels and
        # deadline expiries drain the pipeline first (they mutate rows).
        # ``pipeline=False`` is the synchronous escape hatch — byte-
        # identical chains either way (rows are independent in attention
        # and greedy decode is deterministic per row).
        self.pipeline = bool(pipeline)
        # Stall-free admission (ISSUE 5): a per-boundary prompt-token
        # budget folded into the decode dispatch itself. 0 = off (every
        # admission runs the exclusive wave/suffix/chunked paths — the
        # A/B escape hatch and the library default). When on, up to
        # ``_lane_cap`` admissions ride as piggyback lanes, each advanced
        # ``_lane_chunk`` prompt positions per mixed segment, so
        # lanes * chunk_p <= prefill_budget tokens of prefill land per
        # boundary while every in-flight row keeps committing tokens.
        self.prefill_budget = max(int(prefill_budget), 0)
        lane_chunk = int(prefill_lane_chunk) or min(
            self.prefill_budget, SEQ_BUCKET)
        self._lane_chunk = (
            max(1, min(lane_chunk, self.prefill_budget))
            if self.prefill_budget else 0)
        self._lane_cap = (
            max(1, min(self.prefill_budget // self._lane_chunk, max_batch))
            if self.prefill_budget else 0)
        self._lanes: List[_PendingLane] = []
        self._lane_free: List[int] = list(range(self._lane_cap))
        self._lane_cache = None       # resident (K_cap, S_lane) KV rows
        self._lane_embeds = None      # resident (K_cap, S_lane, D) embeds
        self._lane_bucket = 0         # S_lane: grown to the largest member
        self._inflight: Optional[dict] = None  # dispatched, unharvested
        # (frozen, n_rem, base_pos) device arrays as of the LAST dispatch;
        # None = stale (host mutated rows) -> rebuilt from the host mirror
        # at the next dispatch. Host mutations only happen drained, so the
        # mirror is authoritative whenever this is None.
        self._dev_carry = None
        # Service metrics: per-request TTFT / completion latency keyed by
        # rid, plus the phase-scoped counters reset_serving_stats() owns
        # (admission stall totals/max — the bound chunked prefill exists
        # to cut — and realized speculative acceptance: committed tokens
        # per verify iteration, AGGREGATE across batch rows = tokens per
        # weight-streaming pass, so it exceeds the per-chain window bound
        # when several rows are active).
        self.request_stats: Dict[int, Dict[str, float]] = {}
        # Windowed goodput (ISSUE 6): the last ``slo_window`` SLO-classed
        # finishes, True per request that met every armed target — the
        # egpt_serve_slo_goodput_ratio gauge is their mean.
        self._slo_window_len = max(int(slo_window), 1)
        # HBM memory ledger (ISSUE 9): attribute every resident buffer
        # this server holds to a named component. Keys are namespaced by
        # owner so fleet replicas report their own share; the weight
        # tree is keyed by the TREE's identity — N replicas built off
        # one tree register the same entry once (a resize to the same
        # size is a no-op).
        self._mem_owner = f"b{id(self):x}"
        # Flight recorder (ISSUE 10): request ids are per-batcher, so
        # each batcher records its timelines under a process-unique
        # owner id (a fleet runs N batchers in one process). Owner
        # registration works disarmed too — arming later just starts
        # recording.
        self._journey_owner = obs_journey.register_owner(self._mem_owner)
        if self._prefix_cache is not None:
            # Re-key the cache's ledger entry under this server's owner
            # namespace so the per-replica view (GET /fleet) includes
            # its prefix bytes (safe pre-insert: no entry exists yet).
            self._prefix_cache._mem_key = \
                f"{self._mem_owner}/prefix_cache"
        obs_memory.LEDGER.register(
            "weights", f"shared/params-{id(params):x}",
            obs_memory.params_bytes(params))
        if self._paged:
            # Ledger split (ISSUE 12 satellite): the arena and the table
            # are separate components, so /memory shows where paged
            # bytes live (the table is the only term that scales with
            # max_batch; the pool scales with blocks).
            obs_memory.LEDGER.register(
                "kv_pool", f"{self._mem_owner}/kv_pool",
                obs_memory.params_bytes(
                    {"k": self.cache["k"], "v": self.cache["v"]}))
            obs_memory.LEDGER.register(
                "kv_block_table", f"{self._mem_owner}/kv_block_table",
                self.cache["bt"].nbytes + self.cache["length"].nbytes)
        else:
            obs_memory.LEDGER.register(
                "kv_cache", f"{self._mem_owner}/kv_cache",
                obs_memory.params_bytes(self.cache))
        obs_memory.LEDGER.register(
            "logits", f"{self._mem_owner}/logits", self.logits.nbytes)
        if self.speculative:
            obs_memory.LEDGER.register(
                "ids_buf", f"{self._mem_owner}/ids_buf",
                self.ids_buf.nbytes)
            obs_memory.LEDGER.register(
                "draft", f"{self._mem_owner}/spec_drafts",
                self.spec_drafts.nbytes)
        if draft_head is not None:
            obs_memory.LEDGER.register(
                "draft", f"shared/medusa-{id(draft_head):x}",
                obs_memory.params_bytes(draft_head))
        if self.pipeline:
            # Device-resident scheduler carry (frozen bool + n_rem i32
            # + base_pos i32): small, but it IS a named resident
            # allocation — the taxonomy stays exhaustive.
            self._mem_carry_bytes = max_batch * (
                1 + 4 + (4 if self.speculative else 0))
            obs_memory.LEDGER.register(
                "carry", f"{self._mem_owner}/carry", self._mem_carry_bytes)
        # Admission headroom guard (ISSUE 9): defer admission waves when
        # the ledger predicts the next wave would push the accounted
        # total past capacity - headroom. 0 = off (the A/B escape
        # hatch and the library default). Capacity: explicit override,
        # else the device's reported limit (0 on CPU -> guard inert).
        self.mem_headroom_bytes = max(int(mem_headroom_bytes), 0)
        self._mem_capacity = int(mem_capacity_bytes) or (
            obs_memory.device_capacity_bytes()
            if self.mem_headroom_bytes else 0)
        self.mem_deferrals = 0
        # Compiled-footprint probe result (warmup() fills it; lazily
        # probed on first memory_stats() otherwise).
        self._compiled_footprint: Optional[Dict[str, Any]] = None
        # Last chosen speculation window (journey spec_depth events fire
        # on CHANGE only; persists across reset_serving_stats).
        self._spec_last_window = self.speculative
        self.reset_serving_stats()

    def __del__(self):
        # A dropped batcher must not leave stale owner-keyed bytes in
        # the memory ledger (multi-server processes: fleet rebuilds,
        # bench legs, tests). The shared weight-tree entry stays — the
        # tree may outlive this server. Best-effort: interpreter
        # teardown may have torn the ledger down first.
        owner = getattr(self, "_mem_owner", None)
        if owner is None:
            return  # __init__ raised before registration
        try:
            for comp, key in (("kv_cache", "kv_cache"),
                              ("kv_pool", "kv_pool"),
                              ("kv_block_table", "kv_block_table"),
                              ("logits", "logits"),
                              ("ids_buf", "ids_buf"),
                              ("draft", "spec_drafts"),
                              ("carry", "carry"),
                              ("lanes", "lanes"),
                              ("spill", "spill")):
                obs_memory.LEDGER.release(comp, f"{owner}/{key}")
        except Exception:
            pass

    def _init_mesh_placement(self, vocab: int) -> None:
        """Place the resident buffers on the serving mesh and record their
        shardings (the out-sharding pins for every scheduler jit)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from eventgpt_tpu.parallel import serving as serving_mod

        mesh = self.mesh
        self._serving = serving_mod
        self.cache = serving_mod.shard_kv_cache(self.cache, self.cfg.llama, mesh)
        baxes = serving_mod.serving_batch_axes(mesh, self.max_batch)
        bspec = baxes if baxes else None
        model_n = mesh.shape.get("model", 1)
        vocab_ax = "model" if (model_n > 1 and vocab % model_n == 0) else None
        self._logits_sh = NamedSharding(mesh, P(bspec, vocab_ax))
        # Batch-1 admission logits (chunked prefill's last-token output).
        self._row_logits_sh = NamedSharding(mesh, P(None, vocab_ax))
        self.logits = jax.device_put(self.logits, self._logits_sh)
        self._b_sh = NamedSharding(mesh, P(bspec))
        self._toks_sh = NamedSharding(mesh, P(bspec, None))
        self._key_sh = NamedSharding(mesh, P())
        self.key = jax.device_put(self.key, self._key_sh)
        if self.speculative:
            self._ids_sh = NamedSharding(mesh, P(bspec, None))
            self.ids_buf = jax.device_put(self.ids_buf, self._ids_sh)
            self._drafts_sh = NamedSharding(mesh, P(bspec, None))
            self.spec_drafts = jax.device_put(self.spec_drafts,
                                              self._drafts_sh)
        cache_sh = jax.tree_util.tree_map(lambda x: x.sharding, self.cache)
        flat, treedef = jax.tree_util.tree_flatten(cache_sh)
        self._cache_flat_sh, self._cache_treedef = tuple(flat), treedef

    # -- client surface ---------------------------------------------------

    def warmup(self, prompt_lens: Optional[Sequence[int]] = None) -> int:
        """Precompile every executable a request could hit — the vision
        encoder, one prefill per prompt bucket (+ the chunked-prefill
        kernel when enabled), row admission, and the decode/spec segment —
        so no request pays XLA compile (or persistent-cache executable
        load) mid-service. ``prompt_lens``: expected prompt lengths (text +
        event tokens); default warms every bucket up to max_len/context.

        Runs the REAL jit callables against the live resident state: a
        zeros batch-1 prefill admitted into row 0 is dead storage (the row
        stays FREE/frozen; its cache slots and logits are overwritten at
        the next real admission), and a segment with every row frozen
        exits its while_loop at entry — a no-op dispatch that still
        compiles and caches the executable. That reasoning only holds on
        an idle server — warming into a live row 0 (or zeroing active
        cache lengths) would corrupt in-flight requests, so admission
        must not have started yet.  Returns the number of warmed
        callables.
        """
        from eventgpt_tpu.models.eventchat import _prefill_jit, _prefill_sharded

        if (self.queue or self._pending is not None
                or any(r is not None for r in self.rows)):
            raise RuntimeError(
                "warmup() must run before any request is admitted: it "
                "writes dummy state into row 0 and resets cache lengths, "
                "which would corrupt in-flight rows"
            )

        grain = 2 * SEQ_BUCKET
        if prompt_lens is None:
            limit = min(
                self.max_len,
                ((self.cfg.llama.max_seq_len + grain - 1) // grain) * grain,
            )
            buckets = list(range(grain, limit + 1, grain))
        else:
            buckets = sorted({
                min(((max(int(p), 1) + grain - 1) // grain) * grain,
                    self.max_len)
                for p in prompt_lens
            })
        n = 0
        pv = jnp.zeros(
            (1, self.cfg.num_event_frames, 3, self.cfg.vision.image_size,
             self.cfg.vision.image_size), self._dtype,
        )
        if self.mesh is not None:
            pv = self._serving.shard_batch_array(pv, self.mesh)
        jax.block_until_ready(
            eventchat.encode_events_batch(self.params, self.cfg, pv)
        )
        n += 1
        d = self.cfg.llama.hidden_size
        want_hidden = self.draft_head is not None
        for s1 in buckets:
            padded = jnp.zeros((1, s1, d), self._dtype)
            mask = jnp.ones((1, s1), bool)
            row_cache = self._new_row_cache(s1)
            if self.mesh is not None:
                padded = self._serving.shard_batch_array(padded, self.mesh)
                mask = self._serving.shard_batch_array(mask, self.mesh)
                pre = _prefill_sharded(
                    self.params, self.cfg, padded, mask, row_cache,
                    self.mesh, return_hidden=want_hidden,
                )
            else:
                pre = _prefill_jit(
                    self.params, self.cfg, padded, mask, row_cache, True,
                    return_hidden=want_hidden,
                )
            row_logits, row_cache = pre[0], pre[-1]
            n += 1
            if self.prefill_chunk:
                # One chunk at this bucket's embed shape compiles the
                # chunked-admission executable (its dummy cache is dropped).
                chunk_cache = self._new_row_cache(s1)
                start_arr = jnp.asarray(0, jnp.int32)
                new_len = jnp.asarray([1], jnp.int32)
                last_idx = jnp.asarray(0, jnp.int32)
                if self.mesh is not None:
                    from jax.sharding import PartitionSpec as P

                    row_sh = jax.tree_util.tree_map(
                        lambda x: x.sharding, chunk_cache
                    )
                    flat, treedef = jax.tree_util.tree_flatten(row_sh)
                    fn = _get_sharded_chunk_prefill(
                        self.cfg, self.prefill_chunk, tuple(flat),
                        treedef, self._row_logits_sh,
                        jax.sharding.NamedSharding(self.mesh, P(None, None)),
                    )
                    fn(self.params, padded, chunk_cache, start_arr,
                       new_len, last_idx)
                else:
                    _chunk_prefill_jit(
                        self.params, self.cfg, padded, chunk_cache,
                        start_arr, new_len, last_idx, self.prefill_chunk,
                    )
                n += 1
            # Admission executable (keyed per bucket): write into row 0 —
            # dead storage for a FREE row, overwritten at real admission.
            # Paged: every destination is the OOB sentinel (all writes
            # dropped) and the row index is out of bounds too — the
            # executable compiles, the pool stays untouched.
            if self._paged:
                oob_dst = jnp.full((s1 // self._kv_block_size,),
                                   self._pool.n_blocks, jnp.int32)
                btr = jnp.zeros((self._nbpr,), jnp.int32)
                if self.mesh is not None:
                    oob_dst = self._serving.replicate(oob_dst, self.mesh)
                    btr = self._serving.replicate(btr, self.mesh)
                    admit = _get_sharded_admit_paged(
                        self._cache_flat_sh, self._cache_treedef,
                        self._logits_sh
                    )
                else:
                    admit = _admit_row_paged_jit
                self.cache, self.logits = admit(
                    self.cache, self.logits, self.max_batch, oob_dst, btr,
                    row_cache, row_logits
                )
            else:
                if self.mesh is not None:
                    admit = _get_sharded_admit(
                        self._cache_flat_sh, self._cache_treedef,
                        self._logits_sh
                    )
                else:
                    admit = _admit_row_jit
                self.cache, self.logits = admit(
                    self.cache, self.logits, 0, row_cache, row_logits
                )
            n += 1
        # Zero the dummy row length so its pre-admission frozen-row write
        # slot stays far from the buffer edge (hygiene; writes above the
        # length are masked/dropped either way).
        self.cache = {**self.cache, "length": self.cache["length"] * 0}
        # Segment executable(s): all rows frozen -> no-op dispatch that
        # still compiles and caches. Dispatched with an explicit carry and
        # record_carry=False so the resident pipeline carry (and the armed
        # fault plan's serve.dispatch counters) stay untouched.
        warm_carry = [
            jnp.asarray(np.ones((self.max_batch,), bool)),
            jnp.zeros((self.max_batch,), jnp.int32),
            (jnp.zeros((self.max_batch,), jnp.int32)
             if self.speculative else None),
        ]
        if self.mesh is not None:
            warm_carry = list(self._serving.place_carry(
                self.mesh, self.max_batch, *warm_carry
            ))
        chunks = [None] + ([self.first_chunk] if self.first_chunk else [])
        # Adaptive speculation (ISSUE 13): every bucket in the window
        # set is its own (n_iters, window)-keyed executable — prime
        # them ALL here, so a mid-serve depth switch NEVER compiles
        # (the no-new-compilation contract tests/test_spec_adaptive
        # pins via the jit cache size).
        windows = (list(self.spec_windows) if self.spec_windows
                   else [None])
        for ck in chunks:
            for w in windows:
                # The TTFT-ramp segment is its own executable (chunk is
                # a static arg) — warm it too or the first admission
                # pays it.
                rec = self._dispatch_segment(
                    chunk=ck, carry=tuple(warm_carry), record_carry=False,
                    probe_faults=False, window=w,
                )
                jax.block_until_ready(rec["n_new"])
                n += 1
        if self.prefill_budget:
            # Mixed-segment executables (ISSUE 5): idle lanes against the
            # largest requested prompt bucket — the decode half exits at
            # entry, the lane half runs a garbage chunk above length 0
            # (masked); nothing touches resident rows.
            self._ensure_lane_buffers(buckets[-1])
            for ck in chunks:
                for w in windows:
                    rec = self._dispatch_segment(
                        chunk=ck, carry=tuple(warm_carry),
                        record_carry=False, probe_faults=False,
                        warm_mixed=True, window=w,
                    )
                    jax.block_until_ready(rec["n_new"])
                    n += 1
        self._dev_carry = None
        if self._prefix_cache is not None and self._prefix_cache.n_entries:
            # Prefix-admission (suffix) executables, one per distinct
            # entry shape (_prefix_prefill at the smallest suffix bucket
            # — query tails; a longer real suffix compiles its own). The
            # dummy row caches are discarded, nothing touches the
            # resident state, and record=False keeps the warmup
            # dispatches out of the hit/dispatch telemetry and the armed
            # fault plans (the serve.prefix_copy site counts only real
            # admissions).
            from eventgpt_tpu.constants import EVENT_TOKEN_INDEX

            dummy_pv = np.zeros(
                (self.cfg.num_event_frames, 3, self.cfg.vision.image_size,
                 self.cfg.vision.image_size), np.float32,
            )
            warmed_shapes = set()
            for entry in self._prefix_cache.entries():
                shape_key = (entry.bucket, entry.has_event, entry.length)
                if shape_key in warmed_shapes:
                    continue
                dummy = [0] if entry.has_event else [EVENT_TOKEN_INDEX]
                if self._prefix_admit(entry, dummy_pv, dummy,
                                      record=False) is not None:
                    warmed_shapes.add(shape_key)
                    n += 1
        # Compiled-footprint probe (ISSUE 9): the segment executable was
        # compiled moments ago, so the AOT re-lower here is a compile-
        # cache load — record its temp/argument/output sizes while the
        # server is still idle (compiled_stats never raises).
        self._compiled_footprint = self._probe_compiled_footprint()
        return n

    def set_prefix(self, input_ids: Sequence[int],
                   pixel_values=None) -> int:
        """Prefill a shared prompt prefix ONCE and INSERT it into the
        prefix-KV cache (since ISSUE 4 this is one entry among many — the
        cache also populates itself on admission prefill; POST /prefix is
        an insert, not a replacement). Admissions whose prompts start
        with these exact ids skip its encode + prefill and run only their
        suffix (``_prefix_prefill``). Two regimes:

          * text-only prefix (the system-prompt head): suffixes carry the
            event sentinel and still pay CLIP encode;
          * prefix THROUGH the event block (``pixel_values`` given):
            multi-turn-session traffic over one stream — suffixes are
            plain text, so admission skips the CLIP encode too.

        Non-matching prompts fall back to the full prefill path
        untouched. Returns the prefix length in cache positions."""
        from eventgpt_tpu.constants import EVENT_TOKEN_INDEX
        from eventgpt_tpu.data.tokenizer import split_at_event
        from eventgpt_tpu.models.eventchat import _pad_batch, _prefill_jit, \
            _prefill_sharded, splice_embeddings

        if self._prefix_cache is None:
            raise RuntimeError(
                "prefix cache is disabled (prefix_cache=False); set_prefix "
                "has nowhere to insert"
            )
        ids = list(input_ids)
        n_ev = sum(1 for t in ids if t == EVENT_TOKEN_INDEX)
        if n_ev > 1:
            raise ValueError(f"prefix may contain at most one event "
                             f"sentinel, got {n_ev}")
        if n_ev == 1 and pixel_values is None:
            raise ValueError("prefix contains the event sentinel; "
                             "pixel_values is required")
        if n_ev == 1:
            pv = jnp.asarray(pixel_values, self._dtype)[None]
            if self.mesh is not None:
                pv = self._serving.shard_batch_array(pv, self.mesh)
            ev = eventchat.encode_events_batch(self.params, self.cfg, pv)
            embeds = [splice_embeddings(
                self.params, self.cfg, split_at_event(ids), ev[0]
            )]
        else:
            embeds = [llama_mod.embed_tokens(
                self.params["llama"], jnp.asarray([ids], jnp.int32)
            )[0]]
        padded, mask, lens = _pad_batch(embeds)
        p_len = int(lens[0])
        grain = 2 * SEQ_BUCKET
        s1p = min(((p_len + grain - 1) // grain) * grain, self.max_len)
        if p_len + SEQ_BUCKET > self.max_len:
            # Loud fit check (submit()'s rule): the prefix plus at least
            # one suffix bucket must fit the server, or every admission
            # would fall back to full prefill (and the pad below would
            # crash on a negative width for a prefix past max_len).
            raise ValueError(
                f"prefix ({p_len} positions) does not fit server "
                f"max_len {self.max_len} with room for a suffix"
            )
        padded = jnp.pad(padded, ((0, 0), (0, s1p - p_len), (0, 0)))
        mask = jnp.pad(mask, ((0, 0), (0, s1p - p_len)))
        row_cache = self._new_row_cache(s1p)
        if self.mesh is not None:
            padded = self._serving.shard_batch_array(padded, self.mesh)
            mask = self._serving.shard_batch_array(mask, self.mesh)
            _, row_cache = _prefill_sharded(
                self.params, self.cfg, padded, mask, row_cache, self.mesh
            )
        else:
            _, row_cache = _prefill_jit(
                self.params, self.cfg, padded, mask, row_cache, True
            )
        blocks = None
        kv = {"k": row_cache["k"], "v": row_cache["v"]}
        if self._paged:
            # The operator entry owns its own block run (refcount 1 from
            # the cache): scatter the prefilled row into fresh pool
            # blocks; admissions then alias them like any other entry.
            nblk = s1p // self._kv_block_size
            blocks = self._pool.alloc(nblk)
            if blocks is None:
                self._prefix_cache.reclaim_blocks(self._pool, nblk)
                blocks = self._pool.alloc(nblk)
            if blocks is None:
                raise ValueError(
                    f"prefix entry needs {nblk} pool blocks; only "
                    f"{self._pool.free_blocks()} free (raise "
                    f"--kv_pool_blocks)")
            dst = jnp.asarray(blocks, jnp.int32)
            if self.mesh is not None:
                dst = self._serving.replicate(dst, self.mesh)
                fn = _get_sharded_pool_write(
                    self._cache_flat_sh, self._cache_treedef)
                self.cache = fn(self.cache, dst, row_cache["k"],
                                row_cache["v"])
            else:
                self.cache = _pool_write_jit(
                    self.cache, dst, row_cache["k"], row_cache["v"])
            kv = None
        entry = _PrefixEntry(
            ids=tuple(ids),
            # Identity of the prefix's event stream: admissions whose
            # pixels differ must NOT reuse this KV.
            pixels_key=(_pixels_key(pixel_values) if n_ev == 1 else None),
            has_event=n_ev == 1,
            kv=kv, blocks=blocks,
            length=p_len, bucket=s1p,
            nbytes=s1p * self._kv_pos_bytes,
        )
        if not self._prefix_cache.insert(entry):
            if blocks:
                self._pool.decref(blocks)
            raise ValueError(
                f"prefix entry ({entry.nbytes} bytes at bucket {s1p}) "
                f"exceeds the prefix-cache budget "
                f"{self._prefix_cache.budget} (raise --prefix_cache_mb)"
            )
        return p_len

    def _prefix_lookup(self, req) -> Optional[tuple]:
        """Longest-prefix match of ``req``'s prompt against the cache:
        (entry, suffix_ids) of the deepest compatible entry, or None
        (full-prefill fallback). The wrong-stream guard (ADVICE r5
        medium) lives in ``PrefixCache.lookup``: an event entry whose
        pixels differ from the request's own stream is never returned —
        though the request may still hit a shallower TEXT entry, whose
        KV carries no event content."""
        pc = self._prefix_cache
        if pc is None or pc.n_entries == 0:
            return None
        pk = (None if req.pixel_values is None
              else _pixels_key(req.pixel_values))
        ids = list(req.input_ids)
        entry = pc.lookup(ids, pk)
        if entry is None:
            return None
        return entry, ids[len(entry.ids):]

    def _prefix_suffix_ids(self, req) -> Optional[List[int]]:
        """Suffix of ``req``'s prompt after the longest matching cached
        prefix, or None when nothing matches (full-prefill fallback)."""
        hit = self._prefix_lookup(req)
        return None if hit is None else hit[1]

    def _prefix_fit(self, entry: _PrefixEntry,
                    suffix_ids) -> Optional[tuple]:
        """Bucket arithmetic of a suffix admission against ``entry``:
        (suf_len, prompt_len, chunk, s1), or None when the row bucket
        can't host entry block + padded suffix (full-prefill fallback).
        Runs BEFORE any encode, so a falling-back request pays its CLIP
        once, on the full path — and before wave grouping, which keys on
        (chunk, s1)."""
        from eventgpt_tpu.constants import EVENT_TOKEN_INDEX

        p_len = entry.length
        if entry.has_event:
            suf_len = len(suffix_ids)
        else:
            suf_len = (
                sum(1 for t in suffix_ids if t != EVENT_TOKEN_INDEX)
                + self.cfg.num_event_tokens
            )
        prompt_len = p_len + suf_len
        chunk = ((suf_len + SEQ_BUCKET - 1) // SEQ_BUCKET) * SEQ_BUCKET
        grain = 2 * SEQ_BUCKET
        s1 = min(
            ((max(prompt_len, p_len + chunk) + grain - 1) // grain) * grain,
            self.max_len,
        )
        if p_len + chunk > s1 or s1 < entry.bucket:
            # Prompt too close to max_len for the padded suffix, or the
            # row bucket can't host the entry's stored block.
            return None
        return suf_len, prompt_len, chunk, s1

    def _suffix_embed(self, entry: _PrefixEntry, pixel_values, suffix_ids,
                      chunk: int, suf_len: int):
        """(1, chunk, D) padded suffix embeddings for one admission: a
        through-event entry's suffix is plain text (no CLIP); a text
        entry's suffix carries the sentinel and pays its own encode."""
        from eventgpt_tpu.data.tokenizer import split_at_event
        from eventgpt_tpu.models.eventchat import splice_embeddings

        if entry.has_event:
            emb = llama_mod.embed_tokens(
                self.params["llama"], jnp.asarray([suffix_ids], jnp.int32)
            )
        else:
            pv = jnp.asarray(pixel_values, self._dtype)[None]
            if self.mesh is not None:
                pv = self._serving.shard_batch_array(pv, self.mesh)
            ev = eventchat.encode_events_batch(self.params, self.cfg, pv)
            emb = splice_embeddings(
                self.params, self.cfg, split_at_event(suffix_ids), ev[0]
            )[None]
        assert emb.shape[1] == suf_len, (emb.shape, suf_len)
        return jnp.pad(emb, ((0, 0), (0, chunk - suf_len), (0, 0)))

    def _suffix_wave_sh(self, nb: int):
        """(last_sh, hidden_sh) pins for a batch-``nb`` suffix prefill
        under the serving mesh (batch over the serving batch axes, vocab
        axis reused from the resident logits placement)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        baxes = self._serving.serving_batch_axes(self.mesh, nb)
        bspec = baxes if baxes else None
        vocab_ax = self._logits_sh.spec[1]
        return (NamedSharding(self.mesh, P(bspec, vocab_ax)),
                NamedSharding(self.mesh, P(bspec, None)))

    def _prefix_admit(self, entry: _PrefixEntry, pixel_values, suffix_ids,
                      record: bool = True):
        """Suffix-only admission against one cached prefix-KV entry.
        Returns (row_cache, row_logits, row_hidden, prompt_len), or None
        when ``_prefix_fit`` rejects (fall back to full prefill).
        ``record=False`` (warmup) skips the ``serve.prefix_copy`` fault
        probe and the dispatch/trace telemetry."""
        fit = self._prefix_fit(entry, suffix_ids)
        if fit is None:
            return None
        suf_len, prompt_len, chunk, s1 = fit
        emb = self._suffix_embed(entry, pixel_values, suffix_ids, chunk,
                                 suf_len)
        if record:
            # The copy boundary is its own fault site (ISSUE 4 satellite):
            # a fault HERE lands with a row reserved and an entry about to
            # be read — exactly the window the engine's sweep and the
            # entry's never-donated KV must survive.
            faults.maybe_fail("serve.prefix_copy")
            faults.maybe_delay("serve.prefix_copy")
        t0 = time.perf_counter()
        row_cache = self._new_row_cache(s1)
        new_len = jnp.asarray([prompt_len], jnp.int32)
        last_idx = jnp.asarray(suf_len - 1, jnp.int32)
        plen_arr = jnp.asarray([entry.length], jnp.int32)
        ekv = self._entry_kv(entry)
        if self.mesh is not None:
            emb = self._serving.shard_batch_array(emb, self.mesh)
            row_sh = jax.tree_util.tree_map(lambda x: x.sharding, row_cache)
            flat, treedef = jax.tree_util.tree_flatten(row_sh)
            from jax.sharding import PartitionSpec as P

            hidden_sh = jax.sharding.NamedSharding(self.mesh, P(None, None))
            fn = _get_sharded_prefix_prefill(
                self.cfg, tuple(flat), treedef, self._row_logits_sh,
                hidden_sh,
            )
            last, hidden, row_cache = fn(
                self.params, ekv["k"], ekv["v"], plen_arr,
                row_cache, emb, new_len, last_idx,
            )
        else:
            last, hidden, row_cache = _prefix_prefill_jit(
                self.params, self.cfg, ekv["k"], ekv["v"],
                plen_arr, row_cache, emb, new_len, last_idx,
            )
        if record:
            obs_metrics.SERVE_PREFILL_DISPATCHES.inc(kind="suffix")
            tr = obs_trace.active()
            if tr is not None:
                tr.complete("prefix_copy", t0, time.perf_counter(),
                            cat="sched", args={"plen": entry.length,
                                               "suffix": suf_len})
        return row_cache, last, hidden, prompt_len

    def _admit_suffix_wave(self, members: List[tuple]) -> None:
        """BATCHED suffix admission: N prefix-cache hits sharing the
        padded (chunk, s1) shape run ONE stacked entry-copy +
        ``decode_kstep`` dispatch, scattered into the shared cache with
        the same one-dispatch wave insert as ``_admit_wave``. Entries may
        DIFFER per member (each row copies its own stacked block) — this
        is what makes round-robin session traffic, which hits S distinct
        heads at every boundary, N→1 instead of N sequential suffix
        dispatches. Members: (req, row, entry, suffix_ids, fit) tuples."""
        n = len(members)
        nb = 1 << (n - 1).bit_length()
        _, _, chunk, s1 = members[0][4]
        for req, row, entry, suffix_ids, fit in members:
            self._prefix_cache.count_hit(entry)
        faults.maybe_fail("serve.prefix_copy")
        faults.maybe_delay("serve.prefix_copy")
        t0 = time.perf_counter()
        s_pre = max(m[2].bucket for m in members)

        def pad_block(buf, width):
            if isinstance(buf, dict):
                return {"q": pad_block(buf["q"], width),
                        "s": pad_block(buf["s"], width)}
            return jnp.pad(buf, ((0, 0), (0, 0), (0, width - buf.shape[2]))
                           + ((0, 0),) * (buf.ndim - 3))

        def cat_blocks(blocks):
            if isinstance(blocks[0], dict):
                return {"q": jnp.concatenate([b["q"] for b in blocks], 1),
                        "s": jnp.concatenate([b["s"] for b in blocks], 1)}
            return jnp.concatenate(blocks, axis=1)

        ekvs = [self._entry_kv(m[2]) for m in members]
        pks = [pad_block(kv["k"], s_pre) for kv in ekvs]
        pvs = [pad_block(kv["v"], s_pre) for kv in ekvs]
        if nb > n:
            # Pad slots reuse the first member's block (their rows scatter
            # out of bounds and their length is pinned to 1 below).
            pks += [pks[0]] * (nb - n)
            pvs += [pvs[0]] * (nb - n)
        wave_pk, wave_pv = cat_blocks(pks), cat_blocks(pvs)
        embs = [self._suffix_embed(m[2], m[0].pixel_values, m[3], chunk,
                                   m[4][0])
                for m in members]
        emb = jnp.concatenate(
            embs + [jnp.zeros_like(embs[0])] * (nb - n), axis=0)
        plen_arr = jnp.asarray(
            [m[2].length for m in members] + [1] * (nb - n), jnp.int32)
        new_len = jnp.asarray(
            [m[4][1] for m in members] + [1] * (nb - n), jnp.int32)
        last_idx = jnp.asarray(
            [m[4][0] - 1 for m in members] + [0] * (nb - n), jnp.int32)
        prompt_lens = [m[4][1] for m in members]
        row_cache = llama_mod.init_kv_cache(
            self.cfg.llama, nb, s1, dtype=self._dtype, quant=self.kv_quant)
        if self.mesh is not None:
            emb = self._serving.shard_batch_array(emb, self.mesh)
            row_cache = self._serving.shard_kv_cache(
                row_cache, self.cfg.llama, self.mesh)
            row_sh = jax.tree_util.tree_map(lambda x: x.sharding, row_cache)
            flat, treedef = jax.tree_util.tree_flatten(row_sh)
            last_sh, hidden_sh = self._suffix_wave_sh(nb)
            fn = _get_sharded_prefix_prefill(
                self.cfg, tuple(flat), treedef, last_sh, hidden_sh,
            )
            last, hidden, row_cache = fn(
                self.params, wave_pk, wave_pv, plen_arr, row_cache, emb,
                new_len, last_idx,
            )
        else:
            last, hidden, row_cache = _prefix_prefill_jit(
                self.params, self.cfg, wave_pk, wave_pv, plen_arr,
                row_cache, emb, new_len, last_idx,
            )
        obs_metrics.SERVE_PREFILL_DISPATCHES.inc(kind="suffix_wave")
        tr = obs_trace.active()
        if tr is not None:
            tr.complete("prefix_copy", t0, time.perf_counter(),
                        cat="sched", args={"wave": n})
        self._scatter_wave(
            [(m[0], m[1]) for m in members], row_cache, last,
            hidden if self.draft_head is not None else None, prompt_lens,
            entries=[m[2] for m in members], path="suffix_wave",
        )
        for m in members:
            # Selection pins drain after the wave read every entry
            # (surviving rows hold their own activation pins).
            self._drain_entry_pin(m[2])

    def submit(self, input_ids: Sequence[int], pixel_values,
               max_new_tokens: int = 64,
               deadline_s: Optional[float] = None,
               slo: Optional[SLO] = None) -> int:
        """Enqueue one request; raises immediately if it cannot fit, so one
        oversized request never tears down the serving loop mid-drain.

        ``deadline_s``: seconds from now after which the request is
        finished with ``STATUS_DEADLINE`` (whatever tokens it committed so
        far are returned) instead of holding a batch row for its full
        budget. Raises ``QueueFullError`` when the admission queue is at
        ``max_queue`` (backpressure — the caller should retry later).

        ``slo``: the request's service-level objective (``workload.SLO``
        — class name + TTFT/ITL/latency targets). Scored at finish
        (``_record_finish``) into the ``egpt_serve_slo_*`` metrics and
        ``slo_stats()``; purely observational — scheduling is unchanged
        and chains stay byte-identical with or without it. The class
        name must be one of ``SLO_CLASSES`` (it becomes a metric label;
        bounded cardinality, lint rule 5)."""
        from eventgpt_tpu.constants import EVENT_TOKEN_INDEX

        if slo is not None and slo.name not in SLO_CLASSES:
            raise ValueError(
                f"unknown SLO class {slo.name!r}: one of {SLO_CLASSES} "
                f"(class names are metric labels and must stay a closed "
                f"set)"
            )
        if self.max_queue and len(self.queue) >= self.max_queue:
            raise QueueFullError(
                f"admission queue is full ({len(self.queue)}/"
                f"{self.max_queue} requests queued); retry later"
            )

        ids = list(input_ids)
        n_text = sum(1 for t in ids if t != EVENT_TOKEN_INDEX)
        n_ev = sum(1 for t in ids if t == EVENT_TOKEN_INDEX)
        if n_ev != 1:
            # splice_embeddings would reject this during _admit, AFTER the
            # request left the queue — validate here so the loop never
            # tears down mid-drain.
            raise ValueError(
                f"prompt must contain exactly one {EVENT_TOKEN_INDEX} event "
                f"sentinel, got {n_ev}"
            )
        prompt_len = min(
            n_text + self.cfg.num_event_tokens, self.cfg.llama.max_seq_len
        )
        # Speculative rows write one verify window past their last commit.
        slack = 1 + self.spec_max
        if prompt_len + max_new_tokens + slack > self.max_len:
            raise ValueError(
                f"request does not fit: prompt {prompt_len} + budget "
                f"{max_new_tokens} exceeds server max_len {self.max_len}"
            )
        if self._paged:
            need = self._blocks_needed(prompt_len, max_new_tokens)
            if need > self._pool.usable:
                # Same loud-at-submit rule as the max_len check: a
                # request no pool state could ever cover must not sit in
                # the queue deferring forever.
                raise ValueError(
                    f"request does not fit: needs {need} KV blocks, the "
                    f"pool holds {self._pool.usable} (raise "
                    f"--kv_pool_blocks)"
                )
        rid = self._next_rid
        self._next_rid += 1
        req = _Request(rid, ids, pixel_values, max_new_tokens)
        req.prompt_len = prompt_len
        req.slo = slo
        req.t_submit = time.perf_counter()
        if deadline_s is not None:
            req.deadline = req.t_submit + float(deadline_s)
            self._n_deadlines += 1
        self.queue.append(req)
        obs_metrics.SERVE_QUEUE_DEPTH.set(len(self.queue))
        obs_trace.async_begin(
            "queued", rid, prompt_len=prompt_len, budget=max_new_tokens,
            **({"slo_class": slo.name} if slo is not None else {}))
        obs_journey.begin(
            self._journey_owner, rid, t=req.t_submit,
            prompt_len=prompt_len, budget=max_new_tokens,
            **({"slo_class": slo.name} if slo is not None else {}))
        obs_series.note_submit()
        return rid

    def cancel(self, rid: int) -> bool:
        """Cancel a queued or in-flight request: its row is freed (or it
        leaves the queue / pending admission), whatever tokens it already
        committed are finished under ``STATUS_CANCELLED``. Returns False
        when the rid is unknown or already finished."""
        for req in self.queue:
            if req.rid == rid:
                self.queue.remove(req)
                self._finish_forced(req, STATUS_CANCELLED)
                return True
        if self._pending is not None and self._pending.req.rid == rid:
            p, self._pending = self._pending, None
            self.rows[p.row] = None  # row stays frozen; cache untouched
            self._finish_forced(p.req, STATUS_CANCELLED)
            return True
        for l in self._lanes:
            if l.req.rid == rid:
                # A piggybacked admission mid-prefill: drop the lane and
                # free the reserved row (same contract as a cancelled
                # pending chunked admission — no tokens were committed).
                self._lanes.remove(l)
                self._lane_free.append(l.slot)
                self.rows[l.row] = None
                if l.entry is not None:
                    self._drain_entry_pin(l.entry)
                self._finish_forced(l.req, STATUS_CANCELLED)
                return True
        for r, req in enumerate(self.rows):
            if req is not None and req.rid == rid:
                # Cancelling an ACTIVE row mutates frozen/n_rem: settle
                # the in-flight segment first so the forced finish applies
                # at the dispatch boundary (the tokens it committed in
                # that segment are kept — same contract as the
                # synchronous path).
                self._drain()
                if self.rows[r] is not req:
                    # The drained segment finished the row itself.
                    return False
                self._finish_row(r, status=STATUS_CANCELLED)
                return True
        return False

    def export_requests(self) -> List[Dict[str, Any]]:
        """Drain hook (ISSUE 7): settle the pipeline, then strip EVERY
        unfinished request — active rows, piggyback lanes, the pending
        chunked admission, the queue — out of the scheduler and return
        their re-admission records, in submission order. The fleet
        supervisor re-routes these to surviving replicas when a replica
        dies (``ServingEngine.kill``).

        Committed tokens are DISCARDED on purpose: failover re-decodes
        from the prompt, and greedy chains are deterministic per request
        (rows are independent in attention), so the survivor's chain is
        byte-identical to an uninterrupted run. Deadlines export as the
        REMAINING headroom (absolute perf_counter deadlines do not
        transfer between submit calls). Nothing reaches ``finished`` /
        ``finish_status`` — the request is not over, it is moving."""
        self._drain()
        by_rid: Dict[int, _Request] = {}
        for req in self.queue:
            by_rid[req.rid] = req
        self.queue.clear()
        if self._pending is not None:
            p, self._pending = self._pending, None
            self.rows[p.row] = None  # row stays frozen; cache untouched
            by_rid[p.req.rid] = p.req
        for l in self._lanes:
            self.rows[l.row] = None  # lane KV is dead storage
            if l.entry is not None:
                self._drain_entry_pin(l.entry)
            by_rid[l.req.rid] = l.req
        self._lanes = []
        self._lane_free = list(range(self._lane_cap))
        for r, req in enumerate(self.rows):
            if req is None:
                continue
            self.rows[r] = None
            self.frozen[r] = True
            self.n_rem[r] = 0
            by_rid[req.rid] = req
        # The host mirror changed under the device carry: rebuild at the
        # next dispatch (same rule as every external forced finish).
        self._dev_carry = None
        now = time.perf_counter()
        out: List[Dict[str, Any]] = []
        for rid in sorted(by_rid):
            req = by_rid[rid]
            if self._paged:
                # A drained request's blocks free EXACTLY (the fleet
                # handoff seam): owned + aliased refs drop here; the
                # device tables reset wholesale below.
                req.kv_bt_written = False
                self._paged_release(req)
                if req.spill_run is not None:
                    # A spilled request exports like any other: its host
                    # record drops (the survivor re-decodes from the
                    # prompt — same byte-identical argument as rows).
                    self._pool.drop_spilled(req.spill_run)
                    req.spill_run = None
                    self._spill_store.drop(req.rid)
            if req.prefix_entry is not None:
                # Same pin-drain rule as _record_finish: the entry must
                # not stay unevictable behind a request that left.
                self._drain_entry_pin(req.prefix_entry)
                req.prefix_entry = None
            if req.deadline is not None:
                self._n_deadlines -= 1
            if self._spec_ctl is not None:
                self._spec_ctl.forget(req.rid)
            obs_trace.async_end(req.phase, req.rid, status="exported")
            # The request is not over, it is MOVING: close this
            # replica's timeline as "exported" (a journey-only
            # terminal — finish_status is never written here) so the
            # fleet's stitched view can attribute the abandoned
            # assignment's wall time to failover_redo_s.
            obs_journey.event(self._journey_owner, req.rid, "exported",
                              t=now)
            obs_journey.finish(
                self._journey_owner, req.rid, "exported",
                t_submit=req.t_submit, t_done=now,
                slo_class=(req.slo.name if req.slo is not None
                           else None))
            out.append({
                "rid": req.rid,
                "input_ids": list(req.input_ids),
                "pixel_values": req.pixel_values,
                "max_new_tokens": req.max_new_tokens,
                "deadline_s": (req.deadline - now
                               if req.deadline is not None else None),
                "slo": req.slo,
            })
        if self._paged:
            # Every row left the scheduler: all tables back to scratch,
            # so no dead row's frozen writes can reach a block the next
            # admissions re-allocate.
            self.cache = {**self.cache,
                          "bt": jnp.zeros_like(self.cache["bt"])}
        obs_metrics.SERVE_QUEUE_DEPTH.set(0)
        obs_metrics.SERVE_ACTIVE_ROWS.set(0)
        return out

    def run_until_drained(self) -> Dict[int, List[int]]:
        while self.queue or any(r is not None for r in self.rows):
            self.step()
        # A trailing all-frozen segment can still be in flight after the
        # final harvest freed every row; collect it before returning.
        self._drain()
        out, self.finished = self.finished, {}
        return out

    def reset_prefix_cache(self) -> None:
        """Swap in a fresh (same-budget) prefix cache — the bench's
        per-measured-point reset. This is THE supported reset: replacing
        ``_prefix_cache`` by hand would orphan a paged cache's pinned
        block runs (their refs would never decref — the pool drains
        monotonically until admission livelocks on the block gate).
        ``clear()`` releases the old entries' blocks under the
        deferred-on-pins rule first; the old object's ledger key is
        detached so its GC cannot release the successor's bytes."""
        if self._prefix_cache is None:
            return
        old = self._prefix_cache
        old.clear()
        # Tombstone the old key: __del__ would otherwise release the
        # NEW cache's ledger entry (same owner-derived key).
        old._mem_key = f"{self._mem_owner}/prefix_cache_dropped{id(old):x}"
        self._prefix_cache = PrefixCache(old.budget)
        self._prefix_cache._mem_key = f"{self._mem_owner}/prefix_cache"
        if self._paged:
            self._prefix_cache.pool = self._pool

    def prefix_cache_stats(self) -> Dict[str, Any]:
        """Prefix-KV cache snapshot (``GET /prefix_cache``): entry list,
        byte budget/usage, hit/miss/eviction counters."""
        if self._prefix_cache is None:
            return {"enabled": False}
        return {"enabled": True, "insert_on_prefill": self.prefix_insert,
                **self._prefix_cache.stats()}

    def journey(self, rid: int) -> Optional[Dict[str, Any]]:
        """One request's flight-recorder timeline (ISSUE 10): the full
        event list plus, once finished, the phase decomposition and
        dominant cause (``GET /request?rid=N``). None when the recorder
        is disarmed or the rid has left the retention ring."""
        return obs_journey.get(self._journey_owner, rid)

    def journey_index(self, n: int = 64) -> List[Dict[str, Any]]:
        """Recent finished request timelines, newest first — the
        ``GET /requests`` index (rid / status / slo / cause / e2e)."""
        return obs_journey.index(self._journey_owner, n)

    def memory_summary(self) -> Dict[str, Any]:
        """Cheap ledger view (host ints only — safe once per scheduler
        step): process totals + this server's own component share + the
        headroom-guard state. ``/stats`` merges it under ``"memory"``
        the way ``"slo"`` rides the snapshot."""
        s = obs_memory.LEDGER.summary()
        s["owner"] = obs_memory.LEDGER.snapshot(self._mem_owner)
        s["guard"] = {
            "headroom_bytes": self.mem_headroom_bytes,
            "capacity_bytes": self._mem_capacity,
            "deferrals": self.mem_deferrals,
        }
        if self._paged:
            s["kv_blocks"] = self._pool.stats()
            s["kv_blocks"]["deferrals"] = self.block_deferrals
            s["kv_blocks"]["preemptions"] = self.preemptions
            s["spill"] = self._spill_store.stats()
            s["spill"]["preempt"] = self.preempt
        return s

    def memory_estimate(self) -> Dict[str, Any]:
        """The static capacity model at THIS server's exact config
        (``obs.memory.estimate``): what the resident components should
        cost, from closed-form arithmetic — the number the ledger is
        reconciled against and the planning tool for configs that do
        not exist yet."""
        return obs_memory.estimate(
            self.cfg, max_batch=self.max_batch, max_len=self.max_len,
            kv_quant=self.kv_quant,
            dtype_bytes=jnp.dtype(self._dtype).itemsize,
            speculative=self.speculative,
            prefill_budget=self.prefill_budget,
            prefill_lane_chunk=self._lane_chunk,
            lane_bucket=self._lane_bucket or None,
            prefix_cache_bytes=(self._prefix_cache.budget
                                if self._prefix_cache is not None else 0),
            weights_bytes=obs_memory.params_bytes(self.params),
            vocab=int(self.logits.shape[1]),
            mesh_shape=(dict(self.mesh.shape)
                        if self.mesh is not None else None),
            kv_layout=self.kv_layout,
            kv_pool_blocks=(self._pool_n_blocks() if self._paged else 0),
            kv_block_size=(self._kv_block_size if self._paged else 0),
        )

    def memory_stats(self, reconcile: bool = True) -> Dict[str, Any]:
        """The ``GET /memory`` payload: ledger summary + a FRESH
        ``jax.live_arrays()`` reconciliation + the static estimate + the
        compiled-footprint probe. Walks every live buffer — poll-route
        cost, never per-step (``memory_summary`` is the cheap form)."""
        out = self.memory_summary()
        if reconcile:
            out["reconcile"] = obs_memory.LEDGER.reconcile()
        out["estimate"] = self.memory_estimate()
        out["compiled"] = self.compiled_footprint()
        return out

    def compiled_footprint(self, probe: bool = True) -> Dict[str, Any]:
        """XLA-side bytes of the segment executable this server
        dispatches (temp/argument/output sizes via
        ``memory_analysis()``) — the allocations the ledger cannot see.
        ``warmup()`` fills it right after compiling the executables (the
        AOT re-lower is a compile-cache load there); otherwise probed
        lazily on first call. ``probe=False`` only reports what exists."""
        if self._compiled_footprint is None and probe:
            self._compiled_footprint = self._probe_compiled_footprint()
        return self._compiled_footprint or {"probed": False}

    def _probe_compiled_footprint(self) -> Dict[str, Any]:
        """Lower + compile the resident decode/spec segment at the live
        shapes and pull ``memory_analysis()`` (``obs.memory.
        compiled_stats``). AOT lowering never executes, so the donated
        resident buffers are safe to pass."""
        frozen = jnp.asarray(np.ones((self.max_batch,), bool))
        n_rem = jnp.zeros((self.max_batch,), jnp.int32)
        base_pos = (jnp.zeros((self.max_batch,), jnp.int32)
                    if self.speculative else None)
        if self.mesh is not None:
            frozen, n_rem, base_pos = self._serving.place_carry(
                self.mesh, self.max_batch, frozen, n_rem, base_pos)
        if self.speculative:
            n_iters = max(1, self.chunk // self.speculative)
            history = (jnp.asarray(self._history.astype(np.int32))
                       if self._history is not None else None)
            # Adaptive servers probe the executable the live traffic
            # actually runs — depth array included (fixed-K probes the
            # depth-less trace, same as before ISSUE 13).
            probe_depth = (jnp.zeros((self.max_batch,), jnp.int32)
                           if self._spec_ctl is not None else None)
            if self.mesh is not None:
                if history is not None:
                    history = self._serving.replicate(history, self.mesh)
                if probe_depth is not None:
                    probe_depth = jax.device_put(probe_depth, self._b_sh)
                fn = _get_sharded_spec_segment(
                    self.cfg, n_iters, self.speculative, int(self.eos),
                    self.temperature, self.top_p, self._cache_flat_sh,
                    self._cache_treedef, self._ids_sh, self._b_sh,
                    self._key_sh, self._drafts_sh,
                )
                stats = obs_memory.compiled_stats(
                    fn, self.params, self.cache, self.key, self.ids_buf,
                    base_pos, frozen, n_rem, history, self.draft_head,
                    self.spec_drafts, probe_depth,
                )
            else:
                stats = obs_memory.compiled_stats(
                    _spec_segment_jit, self.params, self.cfg, self.cache,
                    self.key, self.ids_buf, base_pos, frozen, n_rem,
                    n_iters, self.speculative, int(self.eos),
                    self.temperature, self.top_p, history=history,
                    medusa=self.draft_head, drafts=self.spec_drafts,
                    depth=probe_depth,
                )
        elif self.mesh is not None:
            fn = _get_sharded_decode_segment(
                self.cfg, self.chunk, int(self.eos), self.temperature,
                self.top_p, self.nan_check, self._cache_flat_sh,
                self._cache_treedef, self._logits_sh, self._toks_sh,
                self._b_sh, self._key_sh,
            )
            stats = obs_memory.compiled_stats(
                fn, self.params, self.logits, self.cache, self.key,
                frozen, n_rem,
            )
        else:
            stats = obs_memory.compiled_stats(
                _decode_segment_jit, self.params, self.cfg, self.logits,
                self.cache, self.key, frozen, n_rem, self.chunk,
                int(self.eos), self.temperature, self.top_p,
                self.nan_check,
            )
        return {"segment": "spec" if self.speculative else "decode",
                "chunk": self.chunk, **stats}

    def slo_stats(self) -> Dict[str, Any]:
        """SLO-attainment snapshot (ISSUE 6): per-class finished/met
        counts + attainment ratio, and the windowed goodput ratio —
        host-side counters, so the numbers exist with telemetry disarmed
        (the `/stats` merge and the bench read them here; /metrics
        exposes the same story as ``egpt_serve_slo_*``)."""
        classes: Dict[str, Dict[str, Any]] = {}
        for (name, met), n in sorted(self.slo_counts.items()):
            c = classes.setdefault(name, {"finished": 0, "met": 0})
            c["finished"] += n
            if met:
                c["met"] += n
        for c in classes.values():
            c["attainment"] = (c["met"] / c["finished"]
                               if c["finished"] else 0.0)
        w = len(self._slo_window)
        return {
            "classes": classes,
            "window_n": w,
            "window_size": self._slo_window_len,
            "goodput_ratio": (sum(self._slo_window) / w) if w else 0.0,
        }

    def spec_tokens_per_iteration(self) -> float:
        """Realized aggregate acceptance: committed tokens per verify
        iteration (= per weight-streaming pass, summed across batch rows
        — exceeds the per-chain window bound when several rows are
        active). THE definition; /stats and the bench both read it here."""
        return self.spec_tokens / max(self.spec_iterations, 1)

    def spec_stats(self) -> Dict[str, Any]:
        """Adaptive-speculation snapshot (ISSUE 13): the bench columns
        (accepted tokens per dispatch, mean chosen window, masked rows)
        plus the controller's own state. Host-side counters — available
        with telemetry disarmed, the prefix-cache counter convention."""
        out: Dict[str, Any] = {
            "speculative": self.speculative,
            "accepted_per_dispatch": round(
                self.spec_tokens / max(self.spec_dispatches, 1), 3),
            "spec_depth_mean": round(
                self.spec_depth_sum / max(self.spec_dispatches, 1), 3),
            "masked_rows": self.spec_masked_rows,
            "dispatches": self.spec_dispatches,
            "tokens_per_iteration": round(
                self.spec_tokens_per_iteration(), 3),
        }
        if self._spec_ctl is not None:
            out["adaptive"] = self._spec_ctl.stats()
        return out

    def reset_serving_stats(self) -> None:
        """Zero the phase-scoped counters (admission stalls, speculative
        acceptance, pipeline overlap) — e.g. after warmup or an unmeasured
        first request, so a measured window reports only its own traffic."""
        self.admission_s = 0.0
        self.admission_max_s = 0.0
        self.spec_iterations = 0
        self.spec_tokens = 0
        # Adaptive speculation (ISSUE 13), phase-scoped like the
        # acceptance counters above: dispatches + chosen-window sum
        # (their ratio is the bench's spec_depth_mean), rows masked
        # below full depth, and the bounded chosen-window trace the
        # replay-determinism test compares run-to-run. Controller EMA
        # state is NOT reset — it is live policy, not a statistic.
        self.spec_dispatches = 0
        self.spec_depth_sum = 0
        self.spec_masked_rows = 0
        self.spec_depth_trace: deque = deque(maxlen=4096)
        # Pipeline overlap accounting (all host-observable, definitions in
        # PERFORMANCE.md "Pipelined scheduling"):
        #   device_segment_s  — host time BLOCKED waiting on the device
        #                       (the visible, un-hidden device time);
        #   host_gap_s        — host scheduler time between a fetch
        #                       returning and the next fetch blocking
        #                       (harvest bookkeeping, admission prep,
        #                       dispatch calls);
        #   overlap_hidden_s  — the part of host_gap_s spent while a
        #                       dispatched segment was verifiably still
        #                       running on the device (counted only when
        #                       the following fetch actually blocked).
        self.seg_count = 0
        self.device_segment_s = 0.0
        self.host_gap_s = 0.0
        self.overlap_hidden_s = 0.0
        self._t_prev_fetch_end: Optional[float] = None
        # Stall-free admission evidence (ISSUE 5, definitions in
        # PERFORMANCE.md "Stall-free admission"): mixed_boundaries counts
        # harvested segments that carried live piggyback lanes alongside
        # live decode rows; mixed_zero_harvests counts those where the
        # decode rows committed ZERO tokens — by construction this stays
        # 0 (a live row commits at least one token per segment), and the
        # bench asserts it: in-flight rows receive tokens during every
        # admission boundary. mixed_prefill_tokens totals the prompt
        # positions advanced inside mixed segments.
        self.mixed_boundaries = 0
        self.mixed_zero_harvests = 0
        self.mixed_prefill_tokens = 0
        # SLO attainment (ISSUE 6), phase-scoped like everything above:
        # (class, met) -> finished-request counts (host-side, so goodput
        # is reportable with telemetry disarmed too, the prefix-cache
        # counter convention), plus the windowed-goodput ring.
        self.slo_counts: Dict[tuple, int] = {}
        self._slo_window: deque = deque(maxlen=self._slo_window_len)

    def overlap_ratio(self) -> float:
        """Fraction of host scheduler work hidden behind device compute
        (0 on the synchronous path: the fetch starts right after its own
        dispatch, so nothing is ever in flight during host work)."""
        return (self.overlap_hidden_s / self.host_gap_s
                if self.host_gap_s > 0 else 0.0)

    # -- scheduler core ---------------------------------------------------

    def step(self) -> None:
        """One scheduling iteration: expire deadlines, admit into free
        rows (one prefill chunk when a chunked admission is in flight),
        dispatch one decode segment, harvest finished rows.

        Pipelined (the default): the segment is dispatched from the
        device-resident carry FIRST, then the PREVIOUS segment's outputs
        are fetched — so detokenization, history/draft bookkeeping and
        admission prep run while the chip is already computing the next
        segment. Anything that must mutate rows (an expired deadline, an
        admission into a freed row, a pending chunked prefill) drains the
        pipeline at the dispatch boundary before it is applied. With
        ``pipeline=False`` (or while the TTFT ramp owes a first token)
        every step harvests its own segment — the synchronous schedule.
        """
        faults.maybe_fail("serve.step")
        faults.maybe_delay("serve.step")
        piggy = (self.prefill_budget > 0
                 and (bool(self._lanes) or not bool(self.frozen.all())))
        if self._inflight is not None and (
                self._deadline_expired()
                or self._pending is not None
                or any(l.filled >= l.prompt_len for l in self._lanes)
                or (self.queue and not piggy
                    and any(r is None for r in self.rows))):
            # A forced finish or admission is about to mutate rows: apply
            # it against settled state, at the dispatch boundary. A
            # piggyback JOIN is exempt (ISSUE 5): it only reserves a row
            # (host-side) and touches the lane buffers, never the decode
            # carry — so lane boundaries keep the pipeline full; only a
            # lane FINISH (activation) drains.
            self._drain()
        self._expire_deadlines()
        t0 = time.perf_counter()
        admitted = self._admit()
        dt_admit = time.perf_counter() - t0
        self.admission_s += dt_admit
        self.admission_max_s = max(self.admission_max_s, dt_admit)
        if admitted:
            # Only steps that did admission work (popped the queue or
            # advanced a pending chunked prefill) are observed — no-op
            # probes would drown the stall distribution in microseconds.
            obs_metrics.SERVE_ADMISSION.observe(dt_admit)
            tr = obs_trace.active()
            if tr is not None:
                tr.complete("admit", t0, t0 + dt_admit, cat="sched")
        if self.role == "prefill":
            # Prefill role: admission IS the job. Activated rows never
            # decode here — the sweep gathers each one's block run into
            # the handoff outbox for the coordinator to ship to a decode
            # worker; chunked admissions keep advancing through _admit
            # above. Nothing dispatches, so there is never an in-flight
            # segment to drain.
            self._handoff_sweep()
            return
        if all(r is None for r in self.rows):
            self._drain()  # trailing all-frozen segment, if any
            return
        if bool(self.frozen.all()) and not self._lanes:
            # Only reserved (pending-admission) rows exist — nothing to
            # decode yet; the pending prefill advanced above. (The mirror
            # only lags toward MORE-frozen, so mirror-all-frozen implies
            # the device carry is all-frozen too.) With live piggyback
            # lanes we fall through instead: the mixed dispatch advances
            # them even though the decode half no-ops — the starvation
            # guard that keeps lanes draining when nothing is decoding.
            self._drain()
            return
        chunk = self.chunk
        ramp = bool(self.first_chunk) and any(
            req is not None and not self.frozen[r] and req.t_first is None
            for r, req in enumerate(self.rows)
        )
        if ramp:
            # A fresh admission owes its first token: run the short ramp
            # segment so TTFT is ~first_chunk iterations, not a full chunk
            # — and harvest it synchronously, which is exactly what a
            # TTFT-sensitive phase wants.
            chunk = self.first_chunk
        prev, self._inflight = self._inflight, None
        rec = self._dispatch_segment(chunk=chunk)
        if prev is not None:
            # Harvest segment N while N+1 runs: THE overlap — this fetch
            # returns as soon as N's outputs exist, not when N+1 ends.
            self._harvest_segment(prev)
        if self.pipeline and not ramp:
            self._inflight = rec
        else:
            self._harvest_segment(rec)

    def _drain(self) -> None:
        """Harvest the in-flight segment (if any): after this the host
        mirror of frozen/n_rem/base_pos is settled and rows may be
        mutated."""
        if self._inflight is not None:
            rec, self._inflight = self._inflight, None
            self._harvest_segment(rec)

    def abort_pipeline(self) -> None:
        """Discard the in-flight segment record and the device carry (the
        engine's fault path): the dangling dispatch's outputs are ignored
        — its rows are being failed anyway — and the next dispatch
        re-uploads the repaired host view."""
        self._inflight = None
        self._dev_carry = None

    def _deadline_expired(self) -> bool:
        """Cheap host predicate: does any live deadline need a forced
        finish this step? (Gates the pipeline drain — deadline-less
        traffic, and traffic whose deadlines have headroom, never
        serializes on it.)"""
        if self._n_deadlines <= 0:
            return False
        now = time.perf_counter()

        def expired(req):
            return req.deadline is not None and now > req.deadline

        return (any(expired(q) for q in self.queue)
                or (self._pending is not None
                    and expired(self._pending.req))
                or any(req is not None and expired(req)
                       for req in self.rows))

    def _expire_deadlines(self) -> None:
        """Forced finish for every request past its deadline: queued ones
        leave the queue, a pending admission is dropped (its row stays
        frozen), and active rows are frozen mid-decode — each finished
        with ``STATUS_DEADLINE`` and its committed-so-far tokens."""
        if self._n_deadlines <= 0:
            return  # deadline-less traffic: zero per-step scan cost
        now = time.perf_counter()

        def expired(req):
            return req.deadline is not None and now > req.deadline

        if self.queue and any(expired(q) for q in self.queue):
            keep = deque()
            for req in self.queue:
                if expired(req):
                    self._finish_forced(req, STATUS_DEADLINE)
                else:
                    keep.append(req)
            self.queue = keep
        if self._pending is not None and expired(self._pending.req):
            p, self._pending = self._pending, None
            self.rows[p.row] = None
            self._finish_forced(p.req, STATUS_DEADLINE)
        for l in [x for x in self._lanes if expired(x.req)]:
            # A piggybacked admission expired mid-prefill: drop the lane
            # (its slot's KV is dead storage) and free the reserved row.
            # No drain needed — the lane never touched the decode carry.
            self._lanes.remove(l)
            self._lane_free.append(l.slot)
            self.rows[l.row] = None
            if l.entry is not None:
                self._drain_entry_pin(l.entry)
            self._finish_forced(l.req, STATUS_DEADLINE)
        for r, req in enumerate(self.rows):
            if req is not None and not self.frozen[r] and expired(req):
                # A deadline can cross between step()'s drain check and
                # this scan: settle any in-flight segment before mutating
                # the row (idempotent when already drained), and re-check
                # — the harvest may have finished the row itself.
                self._drain()
                if self.rows[r] is req and not self.frozen[r]:
                    self._finish_row(r, status=STATUS_DEADLINE)

    def _spec_boundary(self, forced: Optional[int] = None,
                       mixed: bool = False, record: bool = True):
        """Resolve this dispatch boundary's speculation window and
        per-row draft-depth mask (ISSUE 13). Fixed-K servers (no
        ``spec_buckets``) return (K, None) — the pre-adaptive
        executables, unchanged. Adaptive servers consult the
        ``SpecController`` (or honor ``forced`` — warmup priming a
        specific bucket) and ALWAYS return a depth array, so every
        boundary runs the same executable signature the warmup
        compiled. The ``serve.spec_adapt`` fault site fires here: a
        trip degrades THIS boundary to the fixed default window at
        full depth — adaptive policy off for one boundary, service
        untouched (chaos-tested)."""
        if self._spec_ctl is None:
            w = forced if forced is not None else self.speculative
            if record:
                # Fixed-K boundaries count too: accepted-per-dispatch /
                # depth-mean columns must be comparable across the
                # adaptive-vs-fixed A/B.
                self.spec_dispatches += 1
                self.spec_depth_sum += w
                self.spec_depth_trace.append(w)
            return w, None
        ctl = self._spec_ctl
        w = forced
        depths = None
        masked = 0
        if w is None:
            try:
                faults.maybe_fail("serve.spec_adapt")
                faults.maybe_delay("serve.spec_adapt")
                live = sum(1 for r, req in enumerate(self.rows)
                           if req is not None and not self.frozen[r])
                w = ctl.select_window(live_rows=live, mixed=mixed)
                depths, masked = ctl.depths(
                    [req.rid if req is not None else None
                     for req in self.rows], w)
            except faults.InjectedFault:
                w = ctl.default_window
                depths = None
                masked = 0
        if depths is None:
            depths = [w - 1] * self.max_batch
        # depths is a host-built policy list — the comprehension keeps
        # that visible to the hot-sync lint (no device value in sight).
        depth = jnp.asarray(np.asarray([int(d) for d in depths], np.int32))
        if self.mesh is not None:
            depth = jax.device_put(depth, self._b_sh)
        if record:
            self.spec_dispatches += 1
            self.spec_depth_sum += w
            self.spec_masked_rows += masked
            self.spec_depth_trace.append(w)
            obs_metrics.SERVE_SPEC_DEPTH.observe(w)
            if masked:
                obs_metrics.SERVE_SPEC_MASKED.inc(masked)
            if w != self._spec_last_window:
                # Depth SWITCH: stamp every live row's timeline (the
                # requests whose latency the new bucket shapes);
                # same-kind merge keeps the journey bounded.
                self._spec_last_window = w
                for r, req in enumerate(self.rows):
                    if req is not None and not self.frozen[r]:
                        obs_journey.event(
                            self._journey_owner, req.rid, "spec_depth",
                            window=w)
        return w, depth

    def _dispatch_segment(self, chunk: Optional[int] = None, carry=None,
                          record_carry: bool = True,
                          probe_faults: bool = True,
                          warm_mixed: bool = False,
                          window: Optional[int] = None) -> dict:
        """Dispatch one decode/spec segment on the resident state WITHOUT
        waiting for it, and advance the device-resident carry. Returns the
        in-flight record ``_harvest_segment`` consumes — every entry a
        device array future, so the call returns as soon as XLA enqueues
        the work.

        ``chunk`` defaults to the full segment length; the TTFT ramp
        passes ``first_chunk`` (each distinct value is its own cached
        executable). ``carry`` overrides the (frozen, n_rem, base_pos)
        inputs and ``record_carry=False`` leaves the resident carry
        untouched — the warmup path, which dispatches an all-frozen
        segment purely to compile/cache the executable (the while_loop
        exits at entry). ``probe_faults=False`` also skips the
        ``serve.dispatch`` fault site there, so armed chaos plans count
        only scheduler dispatches. ``warm_mixed`` forces the MIXED
        executable with idle lanes (warmup's compile of the piggyback
        path). ``window`` forces a specific speculation bucket (warmup
        priming every bucket's executable); None lets the adaptive
        controller choose (ISSUE 13) — or uses the fixed K.

        With live piggyback lanes (ISSUE 5) the dispatch is a MIXED
        segment: the same decode/spec body plus every lane advancing
        ``chunk_p`` prompt positions, one executable, one dispatch — the
        in-flight rows commit tokens at every admission boundary. The
        ``serve.mixed_dispatch`` fault site fires at the lane-advance
        boundary; a fault there degrades THIS boundary to a plain decode
        dispatch with every lane re-queued (``_requeue_lanes``): the
        admitting requests re-admit later, the decode rows never notice."""
        if chunk is None:
            chunk = self.chunk
        if probe_faults:
            # The dispatch boundary is its own fault site: a fault HERE
            # lands with a segment possibly in flight, which is exactly
            # the window the engine's abort/restart path must survive.
            faults.maybe_fail("serve.dispatch")
            faults.maybe_delay("serve.dispatch")
        if carry is not None:
            frozen, n_rem, base_pos = carry
        elif self._dev_carry is not None:
            frozen, n_rem, base_pos = self._dev_carry
        else:
            # Host mutated rows (admission / forced finish / init) — all
            # of which happen drained, so the mirror is authoritative.
            frozen = jnp.asarray(self.frozen)
            n_rem = jnp.asarray(self.n_rem.astype(np.int32))
            base_pos = (jnp.asarray(self.base_pos.astype(np.int32))
                        if self.speculative else None)
            if self.mesh is not None:
                frozen, n_rem, base_pos = self._serving.place_carry(
                    self.mesh, self.max_batch, frozen, n_rem, base_pos
                )
        mixed = (warm_mixed or bool(self._lanes)) \
            and self._lane_cache is not None
        if mixed and self._lanes:
            try:
                # The lane-advance boundary is its own fault site: a
                # fault HERE lands with admissions mid-prefill riding
                # the decode dispatch — the lane-degradation handler
                # must re-queue them without touching decode rows.
                faults.maybe_fail("serve.mixed_dispatch")
                faults.maybe_delay("serve.mixed_dispatch")
            except Exception:
                self._requeue_lanes()
                mixed = False
        if mixed:
            (lane_start, lane_new_len, lane_last_idx, lane_adv,
             lane_tok) = self._lane_args()
        # Per-boundary speculation decision (ISSUE 13): window bucket +
        # per-row depth mask, BEFORE the dispatch so the executable is
        # picked host-side with zero device sync.
        spec_w = spec_depth = None
        if self.speculative:
            spec_w, spec_depth = self._spec_boundary(
                window, mixed=mixed and bool(self._lanes),
                record=record_carry)
        rec = {"chunk": chunk, "frozen_in": frozen,
               "wait_at_dispatch": self.device_segment_s}
        if record_carry:
            # Warmup's all-frozen compile dispatches pass record_carry=False
            # and stay out of the telemetry the same way they stay out of
            # the overlap counters.
            obs_metrics.SERVE_SEGMENTS.inc()
            obs_metrics.SERVE_OCCUPANCY.observe(
                int(self.max_batch - int(self.frozen.sum())))
        t_disp0 = time.perf_counter()
        _ann = obs_profiling.annotation("serve.segment_dispatch")
        _ann.__enter__()
        lane_out = None
        if self.speculative:
            n_iters = max(1, chunk // spec_w)
            history = (jnp.asarray(self._history.astype(np.int32))
                       if self._history is not None else None)
            if self.mesh is not None:
                if history is not None:
                    history = self._serving.replicate(history, self.mesh)
                if mixed:
                    last_sh, hidden_sh = self._suffix_wave_sh(self._lane_cap)
                    fn = _get_sharded_mixed_spec_segment(
                        self.cfg, n_iters, spec_w,
                        self._lane_chunk, int(self.eos),
                        self.temperature, self.top_p,
                        self._cache_flat_sh, self._cache_treedef,
                        self._ids_sh, self._b_sh, self._key_sh,
                        self._drafts_sh, self._lane_flat_sh,
                        self._lane_treedef, self._lane_emb_sh,
                        last_sh, hidden_sh,
                    )
                    (self.ids_buf, n_new, done, self.cache, self.key,
                     self.spec_drafts, it, frozen_out, n_rem_out,
                     base_pos_out, row_acc, row_off, pos_acc, pos_off,
                     *lane_out) = fn(
                        self.params, self.cache, self.key, self.ids_buf,
                        base_pos, frozen, n_rem, history, self.draft_head,
                        self.spec_drafts, self._lane_embeds,
                        self._lane_cache, lane_start, lane_new_len,
                        lane_last_idx, spec_depth,
                    )
                else:
                    fn = _get_sharded_spec_segment(
                        self.cfg, n_iters, spec_w, int(self.eos),
                        self.temperature, self.top_p,
                        self._cache_flat_sh, self._cache_treedef,
                        self._ids_sh, self._b_sh, self._key_sh,
                        self._drafts_sh,
                    )
                    (self.ids_buf, n_new, done, self.cache, self.key,
                     self.spec_drafts, it, frozen_out, n_rem_out,
                     base_pos_out, row_acc, row_off, pos_acc,
                     pos_off) = fn(
                        self.params, self.cache, self.key, self.ids_buf,
                        base_pos, frozen, n_rem, history, self.draft_head,
                        self.spec_drafts, spec_depth,
                    )
            elif mixed:
                (self.ids_buf, n_new, done, self.cache, self.key,
                 self.spec_drafts, it, frozen_out, n_rem_out,
                 base_pos_out, row_acc, row_off, pos_acc, pos_off,
                 *lane_out) = (
                    _mixed_spec_segment_jit(
                        self.params, self.cfg, self.cache, self.key,
                        self.ids_buf, base_pos, frozen, n_rem,
                        self._lane_embeds, self._lane_cache, lane_start,
                        lane_new_len, lane_last_idx, n_iters,
                        spec_w, self._lane_chunk,
                        int(self.eos), self.temperature, self.top_p,
                        history=history, medusa=self.draft_head,
                        drafts=self.spec_drafts, depth=spec_depth,
                    )
                )
            else:
                (self.ids_buf, n_new, done, self.cache, self.key,
                 self.spec_drafts, it, frozen_out, n_rem_out,
                 base_pos_out, row_acc, row_off, pos_acc, pos_off) = (
                    _spec_segment_jit(
                        self.params, self.cfg, self.cache, self.key,
                        self.ids_buf, base_pos,
                        frozen, n_rem, n_iters, spec_w,
                        int(self.eos), self.temperature, self.top_p,
                        history=history, medusa=self.draft_head,
                        drafts=self.spec_drafts, depth=spec_depth,
                    )
                )
            # Read back only the window a segment could have written
            # (n_iters * window <= max(chunk, window) slots per row), not
            # the whole (B, max_len) buffer. The gather runs on the
            # OUTPUT ids_buf at the PRE-segment base — enqueued now, so
            # the harvest is one device_get with no extra dispatch.
            width = max(chunk, spec_w)
            rec.update(
                gather=_gather_new_jit(self.ids_buf, base_pos, width),
                it=it, n_new=n_new, done=done, window=spec_w,
                row_acc=row_acc, row_off=row_off,
                pos_acc=pos_acc, pos_off=pos_off,
            )
        else:
            if self.mesh is not None:
                if mixed:
                    last_sh, hidden_sh = self._suffix_wave_sh(self._lane_cap)
                    fn = _get_sharded_mixed_decode_segment(
                        self.cfg, chunk, self._lane_chunk, int(self.eos),
                        self.temperature, self.top_p, self.nan_check,
                        self._cache_flat_sh, self._cache_treedef,
                        self._logits_sh, self._toks_sh, self._b_sh,
                        self._key_sh, self._lane_flat_sh,
                        self._lane_treedef, self._lane_emb_sh,
                        last_sh, hidden_sh,
                    )
                    (tokens, n_new, done, fin, self.logits, self.cache,
                     self.key, frozen_out, n_rem_out, *lane_out) = fn(
                        self.params, self.logits, self.cache, self.key,
                        frozen, n_rem, self._lane_embeds,
                        self._lane_cache, lane_start, lane_new_len,
                        lane_last_idx,
                    )
                else:
                    fn = _get_sharded_decode_segment(
                        self.cfg, chunk, int(self.eos),
                        self.temperature, self.top_p, self.nan_check,
                        self._cache_flat_sh, self._cache_treedef,
                        self._logits_sh, self._toks_sh, self._b_sh,
                        self._key_sh,
                    )
                    (tokens, n_new, done, fin, self.logits, self.cache,
                     self.key, frozen_out, n_rem_out) = fn(
                        self.params, self.logits, self.cache, self.key,
                        frozen, n_rem,
                    )
            elif mixed:
                (tokens, n_new, done, fin, self.logits, self.cache,
                 self.key, frozen_out, n_rem_out, *lane_out) = (
                    _mixed_decode_segment_jit(
                        self.params, self.cfg, self.logits, self.cache,
                        self.key, frozen, n_rem, self._lane_embeds,
                        self._lane_cache, lane_start, lane_new_len,
                        lane_last_idx, chunk, self._lane_chunk,
                        int(self.eos), self.temperature, self.top_p,
                        self.nan_check,
                    )
                )
            else:
                (tokens, n_new, done, fin, self.logits, self.cache,
                 self.key, frozen_out, n_rem_out) = (
                    _decode_segment_jit(
                        self.params, self.cfg, self.logits, self.cache,
                        self.key, frozen, n_rem, chunk, int(self.eos),
                        self.temperature, self.top_p, self.nan_check,
                    )
                )
            base_pos_out = None
            rec.update(tokens=tokens, n_new=n_new, done=done, fin=fin)
        if lane_out is not None:
            # Lane bookkeeping happens at DISPATCH (not harvest): the
            # advance is deterministic, so the pipelined scheduler can
            # build the NEXT boundary's lane args before this segment's
            # outputs are fetched. A lane that just covered its prompt
            # keeps its final-chunk logits/hidden as futures — sliced and
            # fetched only when the (drained) finish path runs.
            lane_last, lane_hidden, self._lane_cache = lane_out
            for l, end in lane_adv:
                l.filled = end
                if l.filled >= l.prompt_len:
                    l.last_logits = lane_last[l.slot: l.slot + 1]
                    l.last_hidden = lane_hidden[l.slot: l.slot + 1]
            if record_carry and lane_adv:
                self.mixed_prefill_tokens += lane_tok
                obs_metrics.SERVE_MIXED_SEGMENTS.inc()
                obs_metrics.SERVE_MIXED_LANES.observe(len(lane_adv))
                obs_metrics.SERVE_MIXED_PREFILL_TOKENS.inc(lane_tok)
                obs_metrics.SERVE_PREFILL_DISPATCHES.inc(kind="piggyback")
                rec["n_lanes"] = len(lane_adv)
        if record_carry:
            self._dev_carry = (frozen_out, n_rem_out, base_pos_out)
            self.seg_count += 1
        _ann.__exit__(None, None, None)
        rec["t_dispatch"] = time.perf_counter()
        tr = obs_trace.active()
        if tr is not None:
            tr.complete("dispatch", t_disp0, rec["t_dispatch"], cat="sched",
                        args={"chunk": chunk})
        return rec

    # egpt-check: harvest -- THE designed blocking point: fetches a settled segment; downstream runs on harvested host state
    def _harvest_segment(self, rec: dict) -> None:
        """Fetch one dispatched segment's outputs (the host blocks HERE,
        and only here) and apply the row bookkeeping: commit tokens,
        stamp TTFT, decrement budgets, finish EOS/exhausted/NaN rows —
        the same transitions the segment already applied to the device
        carry, so no re-upload is needed on this path."""
        t_fetch = time.perf_counter()
        if self._t_prev_fetch_end is not None:
            gap = t_fetch - self._t_prev_fetch_end
            self.host_gap_s += gap
            obs_metrics.SERVE_HOST_GAP.inc(gap)
        if self.speculative:
            (new_np, it_v, n_new, done, frozen_in, row_acc, row_off,
             pos_acc, pos_off) = jax.device_get(
                (rec["gather"], rec["it"], rec["n_new"], rec["done"],
                 rec["frozen_in"], rec["row_acc"], rec["row_off"],
                 rec["pos_acc"], rec["pos_off"])
            )
            new_np = np.asarray(new_np)
            tokens = None
            finite = None
        else:
            # The quarantine mask is computed in-graph and rides the same
            # device_get as the segment outputs — no extra dispatch or
            # round trip on the hot path.
            tokens, n_new, done, finite, frozen_in = jax.device_get(
                (rec["tokens"], rec["n_new"], rec["done"], rec["fin"],
                 rec["frozen_in"])
            )
            finite = np.asarray(finite) if self.nan_check else None
            tokens = np.asarray(tokens)
            new_np = None
        t_end = time.perf_counter()
        wait = t_end - t_fetch
        if wait > 1e-4:
            # The device was still busy when the host arrived: everything
            # the host did since this segment's dispatch — minus any time
            # it spent blocked fetching the previous segment — ran hidden
            # behind device compute.
            blocked_since = self.device_segment_s - rec["wait_at_dispatch"]
            hidden = max(0.0, t_fetch - rec["t_dispatch"] - blocked_since)
            self.overlap_hidden_s += hidden
            obs_metrics.SERVE_OVERLAP_HIDDEN.inc(hidden)
        self.device_segment_s += wait
        obs_metrics.SERVE_SEGMENT.observe(wait)
        tr = obs_trace.active()
        if tr is not None:
            # The fetch block IS the visible device time: one span per
            # segment, so Perfetto shows the un-hidden device share
            # against the dispatch/harvest host spans.
            tr.complete("segment_fetch", t_fetch, t_end, cat="sched",
                        args={"wait_s": round(wait, 6)})
        self._t_prev_fetch_end = t_end
        if self.speculative:
            self.spec_iterations += int(it_v)
            self.spec_tokens += int(n_new.sum())
            if self._spec_ctl is not None:
                # Feed the controller the segment's UNCAPPED acceptance
                # (per-row and per-position) — the depth policy for the
                # NEXT boundary; in pipelined mode one boundary of lag,
                # deterministically (the choice for N+1 was already made
                # at its dispatch).
                r_acc = np.asarray(row_acc)
                r_off = np.asarray(row_off)
                f_in = np.asarray(frozen_in)
                self._spec_ctl.observe(
                    [(req.rid, int(r_acc[r]), int(r_off[r]))
                     for r, req in enumerate(self.rows)
                     if req is not None and not f_in[r]],
                    [int(x) for x in np.asarray(pos_acc)],
                    [int(x) for x in np.asarray(pos_off)],
                )
                obs_metrics.SERVE_SPEC_ACCEPT.set(
                    self._spec_ctl.accept_ema or 0.0)
        n_new = np.asarray(n_new)
        done = np.asarray(done)
        frozen_in = np.asarray(frozen_in)
        if rec.get("n_lanes"):
            # Stall-free evidence (ISSUE 5): this segment carried live
            # piggyback lanes. If decode rows were live too, they must
            # have committed tokens in the SAME dispatch — a zero-token
            # harvest here would be exactly the stall class the mixed
            # segment exists to remove.
            live = ~frozen_in
            if live.any():
                self.mixed_boundaries += 1
                if int(n_new[live].sum()) == 0:
                    self.mixed_zero_harvests += 1
        now = time.perf_counter()
        for r, req in enumerate(self.rows):
            # frozen_in is the segment's INPUT freeze mask (the host
            # mirror may already be one segment ahead of this harvest):
            # rows frozen at dispatch produced nothing here.
            if req is None or frozen_in[r]:
                continue
            if finite is not None and not finite[r]:
                # Non-finite logits poison only this ROW: its segment
                # tokens (sampled from NaN/inf logits) are discarded, the
                # row is frozen and the request fails with a structured
                # status — the batch and the engine keep running. (The
                # in-graph carry froze it the same way: nan_gate mirrors
                # nan_check.)
                self._finish_row(r, status=STATUS_NAN, stale_carry=False)
                continue
            if self.speculative:
                new = new_np[r, : n_new[r]]
                self.base_pos[r] += int(n_new[r])
            else:
                new = tokens[r, : n_new[r]]
            if len(new):
                obs_journey.event(self._journey_owner, req.rid,
                                  "segment", t=now, tokens=len(new))
                if req.t_first is None:
                    req.t_first = now
                elif req.t_last is not None:
                    # Inter-token latency: tokens land in harvest-sized
                    # groups, so the observable per-token gap is the mean
                    # over this harvest interval, weighted by its token
                    # count. A row's FIRST harvest is excluded — those
                    # gaps live inside TTFT.
                    obs_metrics.SERVE_ITL.observe(
                        (now - req.t_last) / len(new), n=len(new))
                req.t_last = now
                obs_metrics.SERVE_TOKENS.inc(len(new))
            req.tokens.extend(int(t) for t in new)
            self.n_rem[r] -= int(n_new[r])
            if done[r] or self.n_rem[r] <= 0:
                # The device carry already froze this row in-graph — the
                # harvest only mirrors it, so the carry stays valid.
                self._finish_row(r, stale_carry=False)

    def _finish_row(self, r: int, status: str = STATUS_OK,
                    stale_carry: bool = True) -> None:
        req = self.rows[r]
        self.rows[r] = None
        self.frozen[r] = True
        self.n_rem[r] = 0
        if stale_carry:
            # External forced finish (deadline / cancel): the device carry
            # no longer matches the host view — rebuild it from the
            # mirror at the next dispatch. Callers guarantee the pipeline
            # is drained first, so the mirror is settled. Harvest-driven
            # finishes pass False: the segment froze the row in-graph
            # already, and invalidating here would roll the carry back
            # behind a segment that is already in flight.
            self._dev_carry = None
        self._record_finish(req, status)

    def _finish_forced(self, req: _Request, status: str) -> None:
        """Terminal bookkeeping for a request that never held (or no
        longer holds) a batch row — expired in the queue, cancelled, or
        quarantined at admission."""
        self._record_finish(req, status)

    def _record_finish(self, req: _Request, status: str) -> None:
        if self._paged:
            # Block reservation drains on EVERY terminal path (EOS,
            # budget, deadline, cancel, quarantine) — the paged twin of
            # the prefix-pin drain below; freed blocks are what the
            # admission gate hands the next deferred request.
            self._paged_release(req)
            if req.spill_run is not None:
                # Died while spilled (deadline in the re-queue, cancel):
                # the registry entry and the host record drain here —
                # the one non-restore exit of the spill lifecycle.
                self._pool.drop_spilled(req.spill_run)
                req.spill_run = None
                self._spill_store.drop(req.rid)
        if req.prefix_entry is not None:
            # Drain the refcount pin on EVERY terminal path (EOS, budget,
            # deadline, cancel, quarantine): the entry becomes evictable
            # once its last in-flight row is gone (and a detached paged
            # entry's deferred block run frees on the last drain).
            self._drain_entry_pin(req.prefix_entry)
            req.prefix_entry = None
        if req.deadline is not None:
            self._n_deadlines -= 1
        if self._spec_ctl is not None:
            # Drop the per-row acceptance window on every terminal path
            # (the controller's host state must not grow per request).
            self._spec_ctl.forget(req.rid)
        ids = req.tokens
        if (self.eos_token_id is not None and ids
                and ids[-1] == self.eos_token_id):
            ids = ids[:-1]
        req.t_done = time.perf_counter()
        # Bounded: a long-lived server must not grow host state per
        # request forever (oldest-first eviction; dicts are
        # insertion-ordered). finish_status is drained at harvest by the
        # engine; the same bound protects direct batcher users.
        while len(self.request_stats) >= 8192:
            self.request_stats.pop(next(iter(self.request_stats)))
        while len(self.finish_status) >= 8192:
            self.finish_status.pop(next(iter(self.finish_status)))
        ttft = (req.t_first if req.t_first is not None
                else req.t_done) - req.t_submit
        latency = req.t_done - req.t_submit
        # Realized mean inter-token gap over the request (first token
        # excluded — that interval is TTFT). Tokens land in harvest-sized
        # groups, so this is the request-level mean of the same quantity
        # the egpt_serve_itl_seconds histogram samples per harvest.
        n_commit = len(req.tokens)
        itl = ((req.t_last - req.t_first) / (n_commit - 1)
               if (req.t_first is not None and req.t_last is not None
                   and n_commit > 1) else 0.0)
        self.request_stats[req.rid] = {
            "ttft_s": ttft,
            "latency_s": latency,
            "itl_s": itl,
        }
        if req.t_first is not None:
            # Forced finishes that never committed a token (expired in the
            # queue, cancelled pre-admission) have no first token; their
            # t_done stand-in would pollute the TTFT distribution.
            obs_metrics.SERVE_TTFT.observe(ttft)
        obs_metrics.SERVE_LATENCY.observe(latency)
        obs_metrics.SERVE_REQUESTS.inc(status=status)
        slo_met: Optional[bool] = None
        if req.slo is not None:
            # SLO attainment (ISSUE 6): score the request against its
            # class targets on EVERY terminal path — a deadline-expired
            # interactive request that never committed scores on its
            # t_done stand-in TTFT, which is a miss whenever the target
            # is tighter than the time already burned (Sarathi-style
            # goodput counts completions within SLO, so forced finishes
            # must not vanish from the denominator).
            slo_met = req.slo.met(ttft, itl, latency)
            key = (req.slo.name, slo_met)
            self.slo_counts[key] = self.slo_counts.get(key, 0) + 1
            self._slo_window.append(slo_met)
            self.request_stats[req.rid]["slo_met"] = float(slo_met)
            obs_metrics.SERVE_SLO_REQUESTS.inc(
                slo_class=req.slo.name,
                met="true" if slo_met else "false")
            if req.t_first is not None:
                obs_metrics.SERVE_SLO_TTFT.observe(
                    ttft, slo_class=req.slo.name)
            if n_commit > 1:
                obs_metrics.SERVE_SLO_ITL.observe(
                    itl, slo_class=req.slo.name)
            obs_metrics.SERVE_SLO_LATENCY.observe(
                latency, slo_class=req.slo.name)
            obs_metrics.SERVE_SLO_GOODPUT.set(
                sum(self._slo_window) / len(self._slo_window))
        obs_metrics.SERVE_ACTIVE_ROWS.set(
            sum(r is not None for r in self.rows))
        obs_metrics.SERVE_QUEUE_DEPTH.set(len(self.queue))
        obs_trace.async_end(
            req.phase, req.rid, status=status, tokens=len(ids),
            **({"slo_class": req.slo.name, "slo_met": slo_met}
               if req.slo is not None else {}))
        # Flight recorder (ISSUE 10): mark forced finishes, close the
        # timeline (computes the phase decomposition + dominant cause)
        # and export the miss cause for SLO-missed finishes. Host
        # clocks/ints only — chains are byte-identical armed or not.
        forced_kind = _JOURNEY_FORCED_KIND.get(status)
        if forced_kind is not None:
            obs_journey.event(self._journey_owner, req.rid, forced_kind,
                              t=req.t_done)
        jrec = obs_journey.finish(
            self._journey_owner, req.rid, status,
            t_submit=(req.t_journey if req.t_journey is not None
                      else req.t_submit), t_done=req.t_done,
            slo_class=(req.slo.name if req.slo is not None else None),
            slo_met=slo_met)
        if jrec is not None and req.slo is not None and not slo_met:
            obs_metrics.SERVE_SLO_MISS_CAUSE.inc(
                slo_class=req.slo.name, cause=jrec["cause"])
        if status == STATUS_OK:
            self._history_append(ids)
        self.finished[req.rid] = ids
        self.finish_status[req.rid] = status

    def _history_append(self, toks) -> None:
        """Append committed/prompt text to the chronological history ring
        (oldest tokens shift out; -1 fillers are dropped at the source so
        they never waste lookup slots)."""
        if self._history is None:
            return
        arr = np.asarray([t for t in toks if t >= 0], np.int64)
        if not len(arr):
            return
        h = len(self._history)
        if len(arr) >= h:
            self._history[:] = arr[-h:]
        else:
            self._history[:-len(arr)] = self._history[len(arr):]
            self._history[-len(arr):] = arr

    # -- stall-free admission lanes (ISSUE 5) -----------------------------

    def _ensure_lane_buffers(self, s1: int) -> None:
        """Allocate (or grow to bucket ``s1``) the resident lane buffers:
        a (K_cap, S_lane) KV cache and a (K_cap, S_lane, D) prompt-embed
        buffer. Growth pads the position axis, preserving live lanes'
        state; each distinct S_lane compiles its own mixed executable, so
        buckets stay at the prompt grain (rare growth, bounded
        executables). Safe with a segment in flight: the pads enqueue on
        the donated buffers' output futures."""
        grain = 2 * SEQ_BUCKET
        s1 = min(((s1 + grain - 1) // grain) * grain, self.max_len)
        if self._lane_cache is not None and s1 <= self._lane_bucket:
            return
        d = self.cfg.llama.hidden_size
        if self._lane_cache is None:
            # ALWAYS unquantized, even on an int8-KV server: the lane's
            # attention must read the same full-precision K/V one-shot
            # prefill reads; quantization happens once, at finish
            # (_lane_extract) — exactly where prefill's write does.
            self._lane_cache = llama_mod.init_kv_cache(
                self.cfg.llama, self._lane_cap, s1, dtype=self._dtype,
                quant=False)
            self._lane_embeds = jnp.zeros(
                (self._lane_cap, s1, d), self._dtype)
        else:
            pad = s1 - self._lane_bucket

            def grow(buf):
                if isinstance(buf, dict):
                    return {"q": grow(buf["q"]), "s": grow(buf["s"])}
                return jnp.pad(buf, ((0, 0), (0, 0), (0, pad))
                               + ((0, 0),) * (buf.ndim - 3))

            self._lane_cache = {
                "k": grow(self._lane_cache["k"]),
                "v": grow(self._lane_cache["v"]),
                "length": self._lane_cache["length"],
            }
            self._lane_embeds = jnp.pad(
                self._lane_embeds, ((0, 0), (0, pad), (0, 0)))
        self._lane_bucket = s1
        if self.mesh is not None:
            self._lane_cache = self._serving.shard_kv_cache(
                self._lane_cache, self.cfg.llama, self.mesh)
            self._lane_embeds = self._serving.shard_batch_array(
                self._lane_embeds, self.mesh)
            lane_sh = jax.tree_util.tree_map(
                lambda x: x.sharding, self._lane_cache)
            flat, treedef = jax.tree_util.tree_flatten(lane_sh)
            self._lane_flat_sh, self._lane_treedef = tuple(flat), treedef
            self._lane_emb_sh = self._lane_embeds.sharding
        # Ledger resize (ISSUE 9): lane growth is the one resident
        # allocation that moves mid-service — account it where it
        # happens (metadata reads only; no host sync on this path).
        obs_memory.LEDGER.resize(
            "lanes", f"{self._mem_owner}/lanes",
            obs_memory.params_bytes(self._lane_cache)
            + self._lane_embeds.nbytes)

    def _start_full_lane(self, req: "_Request", row: int) -> None:
        """Open a piggyback lane for a full-prefill admission: the whole
        prompt's embeddings load into the lane slot; the mixed segments
        advance it ``chunk_p`` positions per boundary from position 0."""
        padded, _, prompt_len = self._prep_request(req)
        self._ensure_lane_buffers(padded.shape[1])
        slot = self._lane_free.pop()
        emb = padded[0]
        self._lane_embeds = self._lane_embeds.at[
            slot, : emb.shape[0]].set(emb)
        if self.mesh is not None:
            self._lane_embeds = jax.device_put(
                self._lane_embeds, self._lane_emb_sh)
        self._lanes.append(_PendingLane(req, row, slot, prompt_len))
        obs_journey.event(self._journey_owner, req.rid, "lane_join",
                          slot=slot, filled=0, prompt_len=prompt_len)

    def _start_suffix_lane(self, req: "_Request", row: int,
                           entry: _PrefixEntry, suffix_ids,
                           fit: tuple) -> None:
        """Open a piggyback lane for a prefix-cache hit: the entry's KV
        block seeds the lane row at [0, entry.length) (the copy is the
        lane's starting offset) and only the SUFFIX embeds load — the
        lane advances from ``filled = entry.length``."""
        suf_len, prompt_len, _, s1 = fit
        self._prefix_cache.count_hit(entry)
        # Same fault site as the exclusive suffix paths: the copy
        # boundary, with a row reserved and an entry about to be read.
        faults.maybe_fail("serve.prefix_copy")
        faults.maybe_delay("serve.prefix_copy")
        # LANE pin (past the fault probes, so a tripped admission never
        # leaks it): the lane re-reads the entry at finish (the int8
        # overlay) and its seed blocks must stay un-recycled for the
        # lane's whole pendency; every lane-termination path drains it.
        entry.pins += 1
        t0 = time.perf_counter()
        self._ensure_lane_buffers(max(s1, entry.bucket))
        slot = self._lane_free.pop()
        slot_arr = jnp.asarray(slot, jnp.int32)
        if self.mesh is not None:
            seed = _get_sharded_lane_seed(
                self._lane_flat_sh, self._lane_treedef)
        else:
            seed = _lane_seed_jit
        ekv = self._entry_kv(entry)
        self._lane_cache = seed(
            self._lane_cache, slot_arr, ekv["k"], ekv["v"])
        emb = self._suffix_embed(entry, req.pixel_values, suffix_ids,
                                 suf_len, suf_len)
        plen = entry.length
        self._lane_embeds = self._lane_embeds.at[
            slot, plen: plen + suf_len].set(emb[0])
        if self.mesh is not None:
            self._lane_embeds = jax.device_put(
                self._lane_embeds, self._lane_emb_sh)
        tr = obs_trace.active()
        if tr is not None:
            tr.complete("prefix_copy", t0, time.perf_counter(),
                        cat="sched", args={"plen": plen, "suffix": suf_len,
                                           "lane": slot})
        self._lanes.append(_PendingLane(
            req, row, slot, prompt_len, filled=plen, entry=entry))
        obs_journey.event(self._journey_owner, req.rid, "lane_join",
                          slot=slot, filled=plen, prompt_len=prompt_len)

    def _lane_args(self) -> tuple:
        """Per-boundary lane inputs for the mixed dispatch: (start,
        new_len, last_idx) over all K_cap slots plus the list of
        (lane, end) pairs this boundary actually advances and their
        total real prompt tokens. Idle and already-finished slots run a
        no-op chunk (start == new_len; garbage above the pinned length,
        masked)."""
        k = self._lane_cap
        start = np.zeros((k,), np.int32)
        new_len = np.zeros((k,), np.int32)
        last_idx = np.zeros((k,), np.int32)
        advancing: List[tuple] = []
        n_tok = 0
        for l in self._lanes:
            start[l.slot] = l.filled
            if l.filled >= l.prompt_len:
                new_len[l.slot] = l.filled  # ready: pinned, no advance
                continue
            end = min(l.filled + self._lane_chunk, l.prompt_len)
            new_len[l.slot] = end
            last_idx[l.slot] = max(0, min(l.prompt_len - 1 - l.filled,
                                          self._lane_chunk - 1))
            advancing.append((l, end))
            n_tok += end - l.filled
        return (jnp.asarray(start), jnp.asarray(new_len),
                jnp.asarray(last_idx), advancing, n_tok)

    def _requeue_lanes(self) -> None:
        """Lane-degradation handler (the ``serve.mixed_dispatch`` fault
        path): every piggybacked admission goes back to the FRONT of the
        queue (original order), its reserved row is released, and the
        boundary degrades to a plain decode dispatch — decode rows are
        untouched. Re-admission re-prefills from scratch through
        whichever path the next boundary picks."""
        for l in reversed(self._lanes):
            self.rows[l.row] = None  # row stays frozen; lane KV is dead
            if l.entry is not None:
                self._drain_entry_pin(l.entry)
            self.queue.appendleft(l.req)
        self._lanes = []
        self._lane_free = list(range(self._lane_cap))
        obs_metrics.SERVE_QUEUE_DEPTH.set(len(self.queue))

    def _finish_ready_lanes(self) -> bool:
        """Complete every lane whose prompt is fully prefilled: slice its
        lane-cache row out and run the NORMAL admission tail
        (``_finish_admission`` — NaN quarantine, insert-on-prefill,
        shared-cache scatter, activation incl. Medusa seeding), so a
        piggybacked admission is indistinguishable from an exclusive one
        from the row's first decoded token onward. Callers guarantee the
        pipeline is drained (activation rewrites the carry)."""
        done = False
        for l in [x for x in self._lanes if x.filled >= x.prompt_len]:
            self._lanes.remove(l)
            self._lane_free.append(l.slot)
            done = True
            pk = pv = None
            plen = 0
            if self.kv_quant and l.entry is not None:
                ekv = self._entry_kv(l.entry)
                pk, pv = ekv["k"], ekv["v"]
                plen = l.entry.length
            slot_arr = jnp.asarray(l.slot, jnp.int32)
            if self.mesh is not None:
                fn = _get_sharded_lane_extract(
                    self._lane_bucket, self.kv_quant,
                    self._serving.prefix_block_sharding(
                        self.mesh, self.cfg.llama),
                    plen,
                )
                k, v = fn(self._lane_cache["k"], self._lane_cache["v"],
                          slot_arr, pk, pv)
            else:
                k, v = _lane_extract_jit(
                    self._lane_cache["k"], self._lane_cache["v"],
                    slot_arr, pk, pv, self._lane_bucket, self.kv_quant,
                    plen,
                )
            row_cache = {"k": k, "v": v,
                         "length": jnp.asarray([l.prompt_len], jnp.int32)}
            obs_journey.event(self._journey_owner, l.req.rid,
                              "lane_finish", slot=l.slot,
                              prompt_len=l.prompt_len)
            self._finish_admission(
                l.req, l.row, l.prompt_len, row_cache, l.last_logits,
                l.last_hidden if self.draft_head is not None else None,
                prefix_entry=l.entry, path="lane",
            )
            if l.entry is not None:
                # Lane pin drains once the activation holds its own.
                self._drain_entry_pin(l.entry)
        return done

    # -- paged KV block pool (ISSUE 12) -----------------------------------

    def _pool_n_blocks(self) -> int:
        buf = (self.cache["k"]["q"] if isinstance(self.cache["k"], dict)
               else self.cache["k"])
        return buf.shape[1]

    def _blocks_needed(self, prompt_len: int, max_new: int) -> int:
        """Blocks one request reserves at admission: cover its prompt
        BUCKET (the admission scatter writes whole bucket-grain blocks)
        and its decode horizon ``prompt + budget + slack`` (the same
        slack submit() validates — speculative rows write one verify
        window past their last commit). Reserving the full horizon up
        front is what makes block admission deadlock-free: a row that
        admitted can always finish, no mid-decode allocation, no
        preemption machinery."""
        grain = 2 * SEQ_BUCKET
        bucket = min(((prompt_len + grain - 1) // grain) * grain,
                     self.max_len)
        slack = 1 + self.spec_max
        cover = min(max(bucket, prompt_len + max_new + slack), self.max_len)
        return self._pool.blocks_for(cover)

    def _paged_admit_gate(self) -> bool:
        """Used-token admission (the tentpole's scheduling half): the
        queue head admits only when its whole block reservation fits the
        pool's FREE list — not when a dense row would have fit. Under
        pressure the gate first reclaims LRU unpinned prefix entries
        (their pinned runs are the only idle pool capacity — eviction
        and row allocation share the one allocator); still short, the
        head stays queued and finishing rows free the blocks it needs.
        Deferral is pure timing: whatever chain a request decodes is
        unchanged, same as the byte-headroom guard."""
        req = self.queue[0]
        need = self._blocks_needed(req.prompt_len, req.max_new_tokens)
        if self._pool.free_blocks() >= need:
            return True
        if self._prefix_cache is not None:
            self._prefix_cache.reclaim_blocks(self._pool, need)
            if self._pool.free_blocks() >= need:
                return True
        if self.preempt and self._preempt_for(req, need):
            return True
        if (self.preempt and req.slo is not None
                and req.slo.name == "interactive"
                and self._spill_store.enabled
                and not self._spill_store.would_fit(
                    self._pool.block_bytes or 1)):
            # Both tiers exhausted (ISSUE 16 satellite): the scan found
            # no victims to cover the head and the host store cannot
            # take even one more block — refuse NOW with
            # ``resource_exhausted`` (HTTP 503 + Retry-After) instead
            # of letting the request hang deferred past its deadline.
            self.queue.popleft()
            obs_metrics.SERVE_QUEUE_DEPTH.set(len(self.queue))
            self._finish_forced(req, STATUS_RESOURCE)
            return False
        self._paged_defer(req, need)
        return False

    def _paged_defer(self, req, need: int) -> None:
        self.block_deferrals += 1
        obs_metrics.SERVE_KV_BLOCK_DEFERRALS.inc()
        obs_trace.instant("kv_block_defer", cat="mem", need_blocks=need,
                          free_blocks=self._pool.free_blocks())
        if obs_journey.enabled():
            obs_journey.event(self._journey_owner, req.rid,
                              "kv_block_defer", need_blocks=need,
                              free_blocks=self._pool.free_blocks())

    def _paged_requeue(self, req, row: int) -> None:
        """Undo a pop whose reservation failed: release the row, put the
        request back at the queue FRONT (original order), count the
        deferral. Nothing was allocated (alloc never partially grants)
        and nothing touched device state."""
        self.rows[row] = None
        req.row = -1
        self.queue.appendleft(req)
        obs_metrics.SERVE_QUEUE_DEPTH.set(len(self.queue))
        self._paged_defer(
            req, self._blocks_needed(req.prompt_len, req.max_new_tokens))

    def _paged_reserve(self, req, s1: int,
                       entry: Optional[_PrefixEntry] = None) -> bool:
        """Allocate the request's block reservation (aliasing the entry's
        full blocks below the divergence point on a prefix hit). False =
        pool cannot cover it right now — the caller re-queues the
        request (never a partial grant)."""
        slack = 1 + self.spec_max
        cover = min(max(s1, req.prompt_len + req.max_new_tokens + slack),
                    self.max_len)
        total = self._pool.blocks_for(cover)
        aliased: List[int] = []
        if entry is not None and entry.blocks:
            n_shared = min(entry.length // self._kv_block_size, total,
                           len(entry.blocks))
            aliased = list(entry.blocks[:n_shared])
        owned = self._pool.alloc(total - len(aliased))
        if owned is None:
            return False
        if aliased:
            self._pool.incref(aliased)
            if entry.length % self._kv_block_size:
                # The entry diverges mid-block: the admission scatter
                # re-creates that block's shared head in the row's first
                # OWNED block — THE copy-on-write copy, counted here.
                self._pool.note_cow()
        req.kv_blocks_aliased = aliased
        req.kv_blocks_owned = owned
        return True

    def _paged_bt_row(self, req) -> np.ndarray:
        """The row's block table: reservation first (aliased run, then
        owned), scratch block 0 above it (frozen writes land there)."""
        bt = np.full((self._nbpr,), serve_blocks.SCRATCH_BLOCK, np.int32)
        run = req.kv_blocks_aliased + req.kv_blocks_owned
        bt[: len(run)] = run
        return bt

    def _paged_dst_blocks(self, req, s1: int) -> np.ndarray:
        """Scatter destinations for the row's (s1-bucket) prefilled
        cache: aliased source blocks and blocks beyond the reservation
        (pure pad — a wave/lane bucket can exceed a short member's own)
        go to the OOB sentinel, which XLA drops."""
        n_src = s1 // self._kv_block_size
        oob = self._pool.n_blocks
        dst = np.full((n_src,), oob, np.int32)
        na = len(req.kv_blocks_aliased)
        own = req.kv_blocks_owned
        for j in range(na, n_src):
            if j - na < len(own):
                dst[j] = own[j - na]
        return dst

    def _paged_release(self, req) -> None:
        """Return the request's reservation on EVERY terminal/export
        path, and point its dead row's table back at scratch so the
        segment kernels' unconditional frozen writes can never land in
        a recycled block."""
        if req.kv_blocks_owned:
            self._pool.decref(req.kv_blocks_owned)
            req.kv_blocks_owned = []
        if req.kv_blocks_aliased:
            self._pool.decref(req.kv_blocks_aliased)
            req.kv_blocks_aliased = []
        if req.kv_bt_written and req.row >= 0:
            self.cache = {
                **self.cache,
                "bt": self.cache["bt"].at[req.row].set(
                    serve_blocks.SCRATCH_BLOCK),
            }
            req.kv_bt_written = False

    # -- block-tier preemption + host-RAM KV spill (ISSUE 16) -------------

    def _preempt_for(self, req, need: int) -> bool:
        """Preemption scan (the tentpole): evict the lowest-value active
        rows — batch class only, worst deadline headroom first (a
        no-deadline row has nothing to miss and goes first) — until the
        interactive head's ``need`` blocks fit the free list. Never
        preempts interactive for interactive (thrash), never for batch
        heads (they defer like today). The ``serve.preempt`` fault site
        degrades the whole scan back to the plain used-token deferral —
        no victim is touched on a trip."""
        if req.slo is None or req.slo.name != "interactive":
            return False
        try:
            faults.maybe_fail("serve.preempt")
            faults.maybe_delay("serve.preempt")
        except faults.InjectedFault:
            return False
        # Settle any in-flight segment first (the export_requests rule:
        # rows may only be mutated drained) — the harvest itself can
        # finish rows and free enough blocks to cover the head.
        self._drain()
        if self._pool.free_blocks() >= need:
            return True
        now = time.perf_counter()
        victims = []
        for r, vic in enumerate(self.rows):
            if vic is None or self.frozen[r]:
                continue  # free, lane-reserved or pending rows
            if vic.slo is not None and vic.slo.name == "interactive":
                continue
            headroom = (vic.deadline - now
                        if vic.deadline is not None else float("-inf"))
            victims.append((headroom, r, vic))
        if not victims:
            return False
        victims.sort(key=lambda x: (x[0], x[1]))
        for _, r, vic in victims:
            if self._pool.free_blocks() >= need:
                break
            if self.rows[r] is not vic or self.frozen[r]:
                continue  # the drain's harvest finished it meanwhile
            self._preempt_row(vic)
        return self._pool.free_blocks() >= need

    def _preempt_row(self, vic) -> None:
        """Evict ONE active row: spill its KV run to the host store when
        the policy prefers it (falling back to drop on any spill-path
        failure — fault trip, budget refusal, pinned run), else release
        the blocks for re-prefill; either way the victim re-queues at
        the BACK with its committed chain obligation intact (restored
        byte-exact, or re-decoded from the prompt — greedy chains are
        deterministic per row, the export_requests argument)."""
        row = vic.row
        mode = "spill" if (self._spill_choose(vic)
                           and self._spill_victim(vic)) else "drop"
        if mode == "drop":
            # Re-prefill re-decodes the whole chain from the prompt:
            # committed tokens are DISCARDED so the re-admission path
            # (prefill sample + segments) rebuilds them byte-identical.
            self._paged_release(vic)
            vic.tokens = []
        if vic.prefix_entry is not None:
            self._drain_entry_pin(vic.prefix_entry)
            vic.prefix_entry = None
        self.rows[row] = None
        vic.row = -1
        self.frozen[row] = True
        self.n_rem[row] = 0
        if self.speculative:
            self.base_pos[row] = 0
        if self._spec_ctl is not None:
            self._spec_ctl.forget(vic.rid)
        # Host row state changed under the device carry: rebuild at the
        # next dispatch (we are drained — _preempt_for settled it).
        self._dev_carry = None
        obs_trace.async_end("active", vic.rid, status="preempted")
        obs_trace.async_begin("queued", vic.rid)
        vic.phase = "queued"
        vic.preempts += 1
        self.preemptions += 1
        self.queue.append(vic)
        obs_metrics.SERVE_QUEUE_DEPTH.set(len(self.queue))
        obs_metrics.SERVE_ACTIVE_ROWS.set(
            sum(r is not None for r in self.rows))
        obs_metrics.SERVE_PREEMPTIONS.inc(mode=mode)
        obs_journey.event(self._journey_owner, vic.rid, "preempt",
                          mode=mode, row=row)

    def _spill_choose(self, vic) -> bool:
        """The spill-vs-recompute policy: spill only an exclusively
        owned run (aliased/pinned blocks have other owners — the pool
        would refuse) that fits the host budget, and only when the
        measured round-trip (bytes out + back at the gather-bandwidth
        EWMA) undercuts re-prefilling the positions decoded so far
        (~2 * params * positions FLOPs at the assumed sustained rate —
        the same closed-form pricing estimate() uses for bytes)."""
        store = self._spill_store
        if (store is None or not store.enabled
                or vic.kv_blocks_aliased or not vic.kv_blocks_owned):
            return False
        if any(self._pool.ref(b) != 1 for b in vic.kv_blocks_owned):
            # Insert-on-prefill aliased part of the run to idle cache
            # entries (ref 2). Those entries are about to outlive their
            # creator anyway — evict the unpinned ones covering this run
            # and re-check; a surviving pin means a live reader, so drop.
            if self._prefix_cache is not None:
                self._prefix_cache.evict_covering(vic.kv_blocks_owned)
            if any(self._pool.ref(b) != 1 for b in vic.kv_blocks_owned):
                return False
        nbytes = len(vic.kv_blocks_owned) * (
            self._pool.block_bytes or self._kv_block_size)
        if not store.would_fit(nbytes):
            return False
        positions = vic.prompt_len + len(vic.tokens)
        spill_s = 2.0 * nbytes / max(self._spill_bw_Bps, 1.0)
        recompute_s = (2.0 * self._spill_param_count * positions
                       / max(self._recompute_flops_per_s, 1.0))
        return spill_s <= recompute_s

    def _spill_victim(self, vic) -> bool:
        """Execute one spill, fault-safely ordered: the ``serve.spill``
        site + the gather + the store admission all happen BEFORE any
        pool mutation, so a trip or refusal anywhere leaves the pool
        (and the victim's reservation) exactly as it was and the caller
        degrades to drop-and-re-prefill."""
        try:
            faults.maybe_fail("serve.spill")
            faults.maybe_delay("serve.spill")
            rec = self._gather_spill_record(vic)
        except faults.InjectedFault:
            return False
        if not self._spill_store.put(vic.rid, rec, rec["nbytes_kv"]):
            return False
        try:
            run_id = self._pool.spill_out(vic.kv_blocks_owned)
        except serve_blocks.BlockPoolError:
            # A pin raced the eligibility check: undo the store record
            # and drop instead — the pool is untouched (spill_out
            # validates before mutating).
            self._spill_store.drop(vic.rid)
            return False
        vic.spill_run = run_id
        vic.kv_blocks_owned = []
        if vic.kv_bt_written and vic.row >= 0:
            # Same dead-row rule as _paged_release: the row's table must
            # point at scratch before its blocks are re-allocated.
            self.cache = {
                **self.cache,
                "bt": self.cache["bt"].at[vic.row].set(
                    serve_blocks.SCRATCH_BLOCK),
            }
            vic.kv_bt_written = False
        obs_journey.event(self._journey_owner, vic.rid, "spill",
                          bytes=rec["nbytes_kv"], blocks=rec["n_blocks"])
        return True

    # egpt-check: harvest -- spill gathers the victim's KV run + row state to host RAM; the preemption boundary is a drained admission decision, outside the pipelined dispatch overlap
    def _gather_spill_record(self, vic,
                             blocks: Optional[List[int]] = None
                             ) -> Dict[str, Any]:
        """The victim's complete re-activation state, gathered dense to
        host RAM: its block run's KV (the same ``_gather_blocks`` copy
        ``export_requests``' drain seam and the prefix entries use),
        cache length, logits row, and the speculative row state
        (ids_buf / base_pos / medusa drafts). Whole-block copies are
        byte-exact — attention masks positions past ``length``, so the
        restore scatter reproduces the row bit-for-bit.

        ``blocks`` overrides the gathered run (the prefill->decode
        handoff gathers the aliased+owned table run, trimmed to the
        blocks covering ``length``); default is the spill path's
        exclusively-owned run."""
        row = vic.row
        block_ids = vic.kv_blocks_owned if blocks is None else blocks
        blocks = jnp.asarray(block_ids, jnp.int32)
        if self.mesh is not None:
            blocks = self._serving.replicate(blocks, self.mesh)
            fn = _get_sharded_gather_blocks(
                self._serving.prefix_block_sharding(self.mesh,
                                                    self.cfg.llama),
                self.kv_quant,
            )
            k, v = fn(self.cache["k"], self.cache["v"], blocks)
        else:
            k, v = _gather_blocks_jit(self.cache["k"], self.cache["v"],
                                      blocks)
        dev = {"k": k, "v": v, "length": self.cache["length"][row],
               "logits": self.logits[row]}
        if self.speculative:
            dev["ids"] = self.ids_buf[row]
        if self.draft_head is not None and self.spec_max > 1:
            dev["drafts"] = self.spec_drafts[row]
        t0 = time.perf_counter()
        host = jax.device_get(dev)
        elapsed = time.perf_counter() - t0
        nbytes = int(sum(np.asarray(x).nbytes
                         for x in jax.tree_util.tree_leaves(host)))
        # Bandwidth EWMA feeding _spill_choose (measured, not assumed).
        self._spill_bw_Bps = (0.7 * self._spill_bw_Bps
                              + 0.3 * nbytes / max(elapsed, 1e-6))
        host["n_blocks"] = len(block_ids)
        host["nbytes_kv"] = nbytes
        host["base_pos"] = (int(self.base_pos[row])
                            if self.speculative else 0)
        return host

    def _paged_restore(self, req, row: int) -> bool:
        """Re-admit a spilled request (the RESTORE half of the seam):
        fresh blocks from the pool's spill registry, then the SAME
        ``_admit_row_paged`` scatter a prefill admission rides — host KV
        in, block table + length + logits row installed in one donated
        dispatch. False = the pool cannot cover the run right now (the
        caller re-queues; the run and the store record stay put)."""
        rec = self._spill_store.peek(req.rid)
        if rec is None:  # lifecycle bug — fail loudly, not silently
            raise serve_blocks.BlockPoolError(
                f"request {req.rid} has spill_run={req.spill_run} but "
                f"no spill record")
        blocks = self._pool.restore(req.spill_run, rec["n_blocks"])
        if blocks is None:
            return False
        self._spill_store.take(req.rid)
        req.spill_run = None
        req.kv_blocks_owned = blocks
        req.kv_blocks_aliased = []
        dst = jnp.asarray(blocks, jnp.int32)
        btr = jnp.asarray(self._paged_bt_row(req))
        row_cache = {"k": rec["k"], "v": rec["v"],
                     "length": np.asarray([rec["length"]], np.int32)}
        row_logits = rec["logits"][None]
        if self.mesh is not None:
            dst = self._serving.replicate(dst, self.mesh)
            btr = self._serving.replicate(btr, self.mesh)
            admit = _get_sharded_admit_paged(
                self._cache_flat_sh, self._cache_treedef,
                self._logits_sh)
        else:
            admit = _admit_row_paged_jit
        self.cache, self.logits = admit(
            self.cache, self.logits, row, dst, btr, row_cache, row_logits
        )
        req.kv_bt_written = True
        self.rows[row] = req
        req.row = row
        self.frozen[row] = False
        self.n_rem[row] = req.max_new_tokens - len(req.tokens)
        if self.speculative:
            self.ids_buf = self.ids_buf.at[row].set(
                jnp.asarray(rec["ids"]))
            if self.mesh is not None:
                self.ids_buf = jax.device_put(self.ids_buf, self._ids_sh)
            self.base_pos[row] = rec["base_pos"]
        if "drafts" in rec:
            self.spec_drafts = self.spec_drafts.at[row].set(
                jnp.asarray(rec["drafts"]))
            if self.mesh is not None:
                self.spec_drafts = jax.device_put(
                    self.spec_drafts, self._drafts_sh)
        self._dev_carry = None
        obs_trace.async_end("queued", req.rid)
        obs_trace.async_begin("active", req.rid)
        req.phase = "active"
        obs_metrics.SERVE_RESTORES.inc()
        obs_metrics.SERVE_ACTIVE_ROWS.set(
            sum(r is not None for r in self.rows))
        obs_journey.event(self._journey_owner, req.rid, "restore",
                          row=row, blocks=rec["n_blocks"])
        return True

    # -- prefill/decode disaggregation: paged-KV handoff (ISSUE 17) --------

    def _handoff_sweep(self) -> None:
        """Prefill role only (``step`` calls this instead of
        dispatching): every ACTIVATED row leaves the scheduler through
        the handoff outbox — its block run gathered to host RAM, its
        reservation released — so the next admission wave always finds
        free rows and free blocks. Reserved rows (a pending chunked
        admission, a piggyback lane) stay: they are mid-admission and
        sweep on a later step, once activated."""
        for row, req in enumerate(self.rows):
            if req is None or self.frozen[row]:
                continue
            if self.n_rem[row] <= 0:
                # The budget was met inside the admission dispatch (a
                # 1-token speculative budget commits t0 at activation):
                # nothing is left to decode, so nothing moves — finish
                # here like a colocated harvest would.
                self._finish_row(row)
                continue
            self._handoff_gather(req)

    def _handoff_gather(self, req) -> None:
        """Gather one activated row into a handoff record and tear the
        row down (the per-request half of ``export_requests``' drain
        seam). The record is the spill record plus routing state: the
        shipped KV covers only the blocks up to ``length`` (attention
        masks everything past it and decode overwrites positions before
        reading them — the spill byte-identity argument), while
        ``n_total`` names the full reservation the decode worker must
        re-allocate. Prefix-aliased blocks ship as part of the run —
        sharing does not cross the wire; the decode side owns a private
        copy."""
        row = req.row
        length = req.prompt_len + len(req.tokens)
        run = req.kv_blocks_aliased + req.kv_blocks_owned
        n_ship = min(max(self._pool.blocks_for(length), 1), len(run))
        rec = self._gather_spill_record(req, blocks=run[:n_ship])
        rec["n_total"] = len(run)
        self._paged_release(req)
        if req.prefix_entry is not None:
            self._drain_entry_pin(req.prefix_entry)
            req.prefix_entry = None
        self.rows[row] = None
        req.row = -1
        self.frozen[row] = True
        self.n_rem[row] = 0
        if self.speculative:
            self.base_pos[row] = 0
        if self._spec_ctl is not None:
            self._spec_ctl.forget(req.rid)
        if req.deadline is not None:
            self._n_deadlines -= 1
        self._dev_carry = None
        now = time.perf_counter()
        obs_trace.async_end(req.phase, req.rid, status="handoff")
        self.handoffs_gathered += 1
        self.handoffs_gathered_bytes += rec["nbytes_kv"]
        obs_metrics.PROCFLEET_HANDOFFS.inc(stage="gathered")
        obs_metrics.SERVE_ACTIVE_ROWS.set(
            sum(r is not None for r in self.rows))
        obs_journey.event(self._journey_owner, req.rid, "kv_handoff",
                          stage="gathered", bytes=rec["nbytes_kv"],
                          blocks=rec["n_blocks"])
        # The request is not over, it is MOVING (the export_requests
        # rule): "handoff" is a journey-only terminal — finish_status is
        # never written — and the closed prefill-leg journey rides the
        # outbox record so the coordinator can stitch both legs plus
        # the wire time into one exact-sum timeline.
        obs_journey.finish(
            self._journey_owner, req.rid, "handoff",
            t_submit=req.t_submit, t_done=now,
            slo_class=(req.slo.name if req.slo is not None else None))
        self.handoff_ready.append({
            "rid": req.rid,
            "input_ids": list(req.input_ids),
            "tokens": list(req.tokens),
            "max_new_tokens": req.max_new_tokens,
            "prompt_len": req.prompt_len,
            # Durations, not timestamps (clocks don't cross processes):
            # the decode worker rebases its local clock by elapsed_s so
            # TTFT / latency / SLO attainment score the request's WHOLE
            # life, not just the decode leg. t_gather stays worker-local
            # (the handler refreshes elapsed_s with the outbox wait at
            # each collect and strips it from the wire record).
            "t_gather": now,
            "elapsed_s": now - req.t_submit,
            "ttft_s": (req.t_first - req.t_submit
                       if req.t_first is not None else None),
            "deadline_s": (req.deadline - now
                           if req.deadline is not None else None),
            "slo": req.slo,
            "preempts": req.preempts,
            "journey": obs_journey.get(self._journey_owner, req.rid),
            "rec": rec,
        })

    def pop_handoffs(self) -> List[Dict[str, Any]]:
        """Drain the handoff outbox (the coordinator's collection hook).
        Delivery past this point is the caller's problem — the worker
        handler keeps popped records replayable until the coordinator
        acks them, so a collect lost to a transport fault re-serves."""
        out, self.handoff_ready = self.handoff_ready, []
        return out

    def import_handoff(self, input_ids: Sequence[int],
                       max_new_tokens: int, rec: Dict[str, Any],
                       tokens: Sequence[int] = (), prompt_len: int = 0,
                       deadline_s: Optional[float] = None,
                       slo: Optional[SLO] = None,
                       elapsed_s: float = 0.0,
                       ttft_s: Optional[float] = None) -> int:
        """Decode role: accept a prefill worker's gathered block-run
        record. The request enqueues like a submit but SPLICES at
        admission (``_handoff_splice``) instead of prefilling, and it
        bypasses ``max_queue`` — it was already admitted into the system
        at the prefill worker's queue, and bouncing it here would strand
        KV that no longer exists anywhere else. ``pixel_values`` are
        deliberately absent: the splice never re-prefills, and the REDO
        path re-routes from the coordinator's own submission record."""
        if self.role == "prefill":
            raise ValueError(
                "a prefill-role scheduler cannot import handoffs")
        if not self._paged:
            raise ValueError("import_handoff requires kv_layout='paged'")
        if slo is not None and slo.name not in SLO_CLASSES:
            raise ValueError(
                f"unknown SLO class {slo.name!r}: one of {SLO_CLASSES}")
        need = self._blocks_needed(int(prompt_len), max_new_tokens)
        if need > self._pool.usable:
            raise ValueError(
                f"handoff does not fit: needs {need} KV blocks, the "
                f"pool holds {self._pool.usable} (raise "
                f"--kv_pool_blocks)")
        rid = self._next_rid
        self._next_rid += 1
        req = _Request(rid, list(input_ids), None, max_new_tokens)
        req.tokens = list(tokens)
        req.prompt_len = int(prompt_len)
        req.slo = slo
        now = time.perf_counter()
        # Rebase the request's clock by the prefill leg + wire time
        # (shipped as a DURATION — absolute stamps never cross
        # processes): t_submit lands in the past and t_first at the
        # prefill worker's commit offset, so every downstream stat —
        # ttft_s, itl_s (the handoff gap is one inter-token interval),
        # latency_s, slo.met — scores the request's whole life exactly
        # like a colocated run, with no special-casing in _finish_row.
        # The deadline anchors at NOW: deadline_s is the REMAINING
        # headroom, already net of the elapsed time.
        req.t_submit = now - max(float(elapsed_s or 0.0), 0.0)
        req.t_journey = now
        if ttft_s is not None:
            req.t_first = req.t_submit + float(ttft_s)
        if deadline_s is not None:
            req.deadline = now + float(deadline_s)
            self._n_deadlines += 1
        req.handoff_rec = rec
        self.queue.append(req)
        obs_metrics.SERVE_QUEUE_DEPTH.set(len(self.queue))
        obs_trace.async_begin("queued", rid, prompt_len=req.prompt_len,
                              budget=max_new_tokens)
        # No obs_series.note_submit(): the arrival was already counted
        # at the prefill worker — an import is a continuation, and
        # double-counting would skew the fleet-wide arrival series.
        # The journey leg stays LOCAL (t=now, not the rebased stamp):
        # the coordinator stitches prefill phases + handoff_s + this
        # leg from durations, so a rebased begin would double-count.
        obs_journey.begin(
            self._journey_owner, rid, t=now,
            prompt_len=req.prompt_len, budget=max_new_tokens,
            **({"slo_class": slo.name} if slo is not None else {}))
        return rid

    def _handoff_splice(self, req, row: int) -> bool:
        """Splice an imported handoff record into the local arena: a
        fresh fully-owned allocation for the FULL reservation
        (``n_total`` — the same blocks-for-cover arithmetic both roles
        compute from identical flags), then the SAME ``_admit_row_paged``
        scatter every paged admission rides, over the shipped prefix of
        the run. False = the pool cannot cover the reservation right
        now (only an allocation race against the gate's pre-check — the
        caller re-queues, the record stays put)."""
        rec = req.handoff_rec
        total = int(rec.get("n_total", rec["n_blocks"]))
        blocks = self._pool.alloc(total)
        if blocks is None:
            return False
        req.handoff_rec = None
        req.kv_blocks_owned = blocks
        req.kv_blocks_aliased = []
        n_ship = int(rec["n_blocks"])
        dst = jnp.asarray(blocks[:n_ship], jnp.int32)
        btr = jnp.asarray(self._paged_bt_row(req))
        row_cache = {"k": rec["k"], "v": rec["v"],
                     "length": np.asarray([rec["length"]], np.int32)}
        row_logits = np.asarray(rec["logits"])[None]  # egpt-check: ignore[hot-sync] -- rec came off the RPC wire: every plane is already host-resident numpy (the raw-frame decoder builds them), so this asarray is a view, never a device fetch
        if self.mesh is not None:
            dst = self._serving.replicate(dst, self.mesh)
            btr = self._serving.replicate(btr, self.mesh)
            admit = _get_sharded_admit_paged(
                self._cache_flat_sh, self._cache_treedef,
                self._logits_sh)
        else:
            admit = _admit_row_paged_jit
        self.cache, self.logits = admit(
            self.cache, self.logits, row, dst, btr, row_cache, row_logits
        )
        req.kv_bt_written = True
        self.rows[row] = req
        req.row = row
        self.frozen[row] = False
        self.n_rem[row] = req.max_new_tokens - len(req.tokens)
        if self.speculative:
            self.ids_buf = self.ids_buf.at[row].set(
                jnp.asarray(rec["ids"]))
            if self.mesh is not None:
                self.ids_buf = jax.device_put(self.ids_buf, self._ids_sh)
            self.base_pos[row] = rec["base_pos"]
        if "drafts" in rec:
            self.spec_drafts = self.spec_drafts.at[row].set(
                jnp.asarray(rec["drafts"]))
            if self.mesh is not None:
                self.spec_drafts = jax.device_put(
                    self.spec_drafts, self._drafts_sh)
        self._dev_carry = None
        obs_trace.async_end("queued", req.rid)
        obs_trace.async_begin("active", req.rid)
        req.phase = "active"
        nbytes = int(rec.get("nbytes_kv", 0))
        self.handoffs_spliced += 1
        self.handoffs_spliced_bytes += nbytes
        obs_metrics.PROCFLEET_HANDOFFS.inc(stage="spliced")
        obs_metrics.SERVE_ACTIVE_ROWS.set(
            sum(r is not None for r in self.rows))
        obs_journey.event(self._journey_owner, req.rid, "kv_handoff",
                          stage="spliced", row=row, blocks=n_ship,
                          bytes=nbytes)
        return True

    def _drain_entry_pin(self, entry: _PrefixEntry) -> None:
        """Drop one refcount pin; on the LAST drain of a DETACHED paged
        entry, release its deferred block run (see
        ``PrefixCache._release_blocks_locked``). Every pin site —
        selection (hit chosen for this boundary's admission), pending
        lane, active row — drains through here, so a replaced/evicted
        entry's blocks can never free while something still reads
        them."""
        entry.pins -= 1
        if (entry.pins <= 0 and entry.detached and entry.blocks
                and self._pool is not None):
            self._pool.decref(entry.blocks)
            entry.blocks = None
            entry.detached = False

    def _entry_kv(self, entry: _PrefixEntry) -> Dict[str, Any]:
        """The entry's dense (L, 1, bucket) KV view: stored buffers for
        dense-layout entries; a pool gather for paged ones (same values
        the dense copy would carry — the exclusive suffix / lane paths
        stay layout-agnostic)."""
        if entry.kv is not None:
            return entry.kv
        blocks = jnp.asarray(entry.blocks, jnp.int32)
        if self.mesh is not None:
            blocks = self._serving.replicate(blocks, self.mesh)
            fn = _get_sharded_gather_blocks(
                self._serving.prefix_block_sharding(self.mesh,
                                                    self.cfg.llama),
                self.kv_quant,
            )
            k, v = fn(self.cache["k"], self.cache["v"], blocks)
        else:
            k, v = _gather_blocks_jit(self.cache["k"], self.cache["v"],
                                      blocks)
        return {"k": k, "v": v}

    def _admit(self) -> bool:
        """Returns True when this step did admission work (advanced a
        pending chunked prefill or popped the queue) — the telemetry
        gate for the admission-stall histogram.

        Admission policy per popped request (ISSUE 5): with a
        ``prefill_budget`` armed AND rows actively decoding (or lanes
        already live), the request becomes a PIGGYBACK LANE — prefix-KV
        hits seed the lane with the entry's block, misses load the whole
        prompt — advanced inside the decode dispatch itself, up to
        ``K_cap`` lanes at a time (excess requests stay queued; decode
        keeps flowing either way). Otherwise (nothing to stall, or
        budget off): longest-prefix match against the prefix-KV cache
        (suffix-only admission), else the chunked path (when actives are
        decoding), else collected into this step's FULL-PREFILL WAVE —
        every wave member runs in ONE batched prefill dispatch
        (``_admit_wave``) instead of N sequential batch-1 prefills."""
        from eventgpt_tpu.models.eventchat import _prefill_jit, _prefill_sharded

        faults.maybe_fail("serve.admit")
        faults.maybe_delay("serve.admit")
        did_work = False
        if self._lanes:
            # step() drained the pipeline when any lane was ready, so
            # the activations below apply against settled state.
            did_work |= self._finish_ready_lanes()
        if self._pending is not None:
            did_work = True
            self._advance_pending()
        # Piggyback is the per-boundary choice only while something is
        # decoding (or lanes are mid-flight — join them); with every row
        # frozen there is nothing to stall and the exclusive wave is the
        # fastest path to completion.
        piggy = (self.prefill_budget > 0
                 and (bool(self._lanes) or not bool(self.frozen.all())))
        # Memory headroom guard (ISSUE 9): when the ledger predicts the
        # next admission wave would exceed capacity - headroom, the
        # queue stays queued this boundary — decode keeps flowing, and
        # finishing rows free the bytes the deferred wave needs.
        mem_defer = self._mem_guard_defers()
        wave: List[tuple] = []  # (req, row) full-prefill admissions
        hits: List[tuple] = []  # (req, row, entry, suffix_ids, fit)
        while (self._pending is None and self.queue and not mem_defer
               and any(self.rows[r] is None
                       for r in range(self.max_batch))):
            if piggy and not self._lane_free:
                break  # lanes at the token budget: the rest stay queued
            if self._paged and not self._paged_admit_gate():
                break  # pool can't cover the head's block reservation
            req = self.queue.popleft()
            did_work = True
            t_deq = time.perf_counter()
            obs_metrics.SERVE_QUEUE_DEPTH.set(len(self.queue))
            obs_metrics.SERVE_QUEUE_WAIT.observe(t_deq - req.t_submit)
            obs_journey.event(self._journey_owner, req.rid, "queue",
                              t=t_deq, depth=len(self.queue))
            if req.phase == "queued":
                obs_trace.async_end("queued", req.rid)
                obs_trace.async_begin("active", req.rid)
                req.phase = "active"
            row = next(r for r in range(self.max_batch)
                       if self.rows[r] is None)
            # Reserve the row NOW (it stays frozen until activation): a
            # fault mid-admission (serve.prefix_copy, a prefill error)
            # must leave the request somewhere the engine's sweep can
            # fail cleanly instead of stranding its waiter.
            self.rows[row] = req
            req.row = row
            if self._paged and req.spill_run is not None:
                # A preempted-and-spilled head restores through the
                # paged admission seam instead of re-prefilling: fresh
                # blocks + the byte-exact scatter of its gathered KV
                # (ISSUE 16). The gate pre-checked the same reservation
                # arithmetic, so failure here is only an eviction race.
                if self._paged_restore(req, row):
                    continue
                self._paged_requeue(req, row)
                break
            if self._paged and req.handoff_rec is not None:
                # A prefill worker's handoff splices through the same
                # paged admission seam (ISSUE 17): fresh blocks for the
                # full reservation, the shipped run scattered byte-exact
                # — never a re-prefill. The gate pre-checked the same
                # reservation arithmetic, so failure is only an
                # allocation race.
                if self._handoff_splice(req, row):
                    continue
                self._paged_requeue(req, row)
                break
            hit = None
            if self._prefix_cache is not None:
                t0 = time.perf_counter()
                hit = self._prefix_lookup(req)
                tr = obs_trace.active()
                if tr is not None:
                    tr.complete("prefix_lookup", t0, time.perf_counter(),
                                cat="sched", args={"hit": hit is not None})
            if hit is not None:
                entry, suffix_ids = hit
                fit = self._prefix_fit(entry, suffix_ids)
                if fit is not None and self._paged and not \
                        self._paged_reserve(req, fit[3], entry):
                    # The gate pre-checked the FULL (no-aliasing) need,
                    # but a racing entry eviction or a one-grain suffix
                    # overshoot can still lose the allocation: requeue
                    # at the front, never a partial grant.
                    self._paged_requeue(req, row)
                    break
                if fit is not None:
                    obs_journey.event(
                        self._journey_owner, req.rid, "prefix", hit=True,
                        matched=entry.length, entry_tokens=len(entry.ids))
                    if piggy:
                        self._start_suffix_lane(req, row, entry,
                                                suffix_ids, fit)
                        continue
                    # SELECTION pin: the entry must survive (and a paged
                    # entry's blocks must stay un-recycled) until this
                    # boundary's suffix admission has read it — the
                    # block-gate's entry reclaim skips pinned entries.
                    entry.pins += 1
                    hits.append((req, row, entry, suffix_ids, fit))
                    continue
            if self._prefix_cache is not None:
                self._prefix_cache.count_miss()
                obs_journey.event(self._journey_owner, req.rid, "prefix",
                                  hit=False)
            if self._paged:
                grain = 2 * SEQ_BUCKET
                s1 = min(((req.prompt_len + grain - 1) // grain) * grain,
                         self.max_len)
                if not self._paged_reserve(req, s1):
                    self._paged_requeue(req, row)
                    break
            if piggy:
                self._start_full_lane(req, row)
                continue
            if self.prefill_chunk and not bool(self.frozen.all()):
                # Active rows are decoding: chunked admission. The row is
                # reserved (kept frozen) and ONE prefill chunk advances
                # per scheduler step, so a long prompt stalls each decode
                # segment by at most one chunk instead of its full prefill.
                padded, mask, prompt_len = self._prep_request(req)
                row_cache = self._new_row_cache(padded.shape[1])
                self._pending = _PendingAdmission(
                    req, row, padded, prompt_len, row_cache
                )
                self._advance_pending()
                break
            wave.append((req, row))
        # Suffix admissions first, grouped into waves by padded shape:
        # round-robin session traffic hits S DIFFERENT heads at one
        # boundary, so the wave stacks per-member entry blocks — batching
        # by entry alone would leave S sequential dispatches.
        groups: Dict[tuple, List[tuple]] = {}
        for h in hits:
            groups.setdefault((h[4][2], h[4][3]), []).append(h)
        for (_, _), members in sorted(groups.items()):
            obs_metrics.SERVE_ADMISSION_WAVE.observe(len(members))
            if len(members) == 1:
                req, row, entry, suffix_ids, fit = members[0]
                try:
                    pre_admit = self._prefix_admit(entry,
                                                   req.pixel_values,
                                                   suffix_ids)
                    if pre_admit is None:  # unreachable: fit pre-checked
                        wave.append((req, row))
                        continue
                    self._prefix_cache.count_hit(entry)
                    (row_cache, row_logits, row_hidden,
                     prompt_len) = pre_admit
                    self._finish_admission(
                        req, row, prompt_len, row_cache, row_logits,
                        row_hidden if self.draft_head is not None
                        else None,
                        prefix_entry=entry, path="suffix",
                    )
                finally:
                    # Selection pin drains once the admission read the
                    # entry — or on the fault path (serve.prefix_copy),
                    # where the engine sweep fails the request.
                    self._drain_entry_pin(entry)
            else:
                try:
                    self._admit_suffix_wave(members)
                except BaseException:
                    for m in members:
                        self._drain_entry_pin(m[2])
                    raise
        if not wave:
            return did_work
        obs_metrics.SERVE_ADMISSION_WAVE.observe(len(wave))
        if len(wave) > 1:
            self._admit_wave(wave)
            return True
        # Single admission: the batch-1 path (its executables are the
        # ones warmup precompiles). Medusa mode also needs the prompt's
        # last hidden to seed the row's first draft window.
        req, row = wave[0]
        padded, mask, prompt_len = self._prep_request(req)
        row_cache = self._new_row_cache(padded.shape[1])
        want_hidden = self.draft_head is not None
        row_hidden = None
        if self.mesh is not None:
            pre = _prefill_sharded(
                self.params, self.cfg, padded, mask, row_cache,
                self.mesh, return_hidden=want_hidden,
            )
        else:
            pre = _prefill_jit(
                self.params, self.cfg, padded, mask, row_cache, True,
                return_hidden=want_hidden,
            )
        obs_metrics.SERVE_PREFILL_DISPATCHES.inc(kind="full")
        if want_hidden:
            row_logits, row_hidden, row_cache = pre
        else:
            row_logits, row_cache = pre
        self._finish_admission(req, row, prompt_len, row_cache,
                               row_logits, row_hidden)
        return did_work

    def _mem_next_wave_bytes(self) -> int:
        """Predicted device bytes of admitting the queue head(s) that
        COULD land this boundary (one per free row): the grain-rounded
        row-cache block per member, doubled when insert-on-prefill will
        also copy a prefix entry — conservative on purpose (a guard
        that under-predicts is a guard that OOMs)."""
        grain = 2 * SEQ_BUCKET
        free = sum(1 for r in self.rows if r is None)
        if self._paged:
            # Paged repricing (ISSUE 12 satellite): the wave is priced
            # at the BLOCK grain — each head's actual reservation — not
            # as dense rows, and without the insert-on-prefill doubling
            # (paged insert aliases the row's blocks; it copies
            # nothing). The transient admission row-cache is bucket-
            # sized, which the reservation already covers, so the old
            # dense pricing would double-count headroom the pool no
            # longer needs.
            total = 0
            for i, req in enumerate(self.queue):
                if i >= free:
                    break
                total += (self._blocks_needed(req.prompt_len,
                                              req.max_new_tokens)
                          * self._pool.block_bytes)
            return total
        factor = 2 if (self._prefix_cache is not None
                       and self.prefix_insert) else 1
        total = 0
        for i, req in enumerate(self.queue):
            if i >= free:
                break
            bucket = min(((req.prompt_len + grain - 1) // grain) * grain,
                         self.max_len)
            total += factor * bucket * self._kv_pos_bytes
        return total

    def _mem_guard_defers(self) -> bool:
        """One headroom-guard decision per admission boundary. Deferral
        is pure TIMING — whatever chain a request decodes is unchanged
        (rows are independent in attention), so armed-vs-disarmed runs
        stay byte-identical; ``mem_headroom_bytes == 0`` (the default)
        or an unknown capacity disarms it outright. The guard never
        starves an idle server: with nothing in flight to free bytes,
        deferring would deadlock, so admission proceeds regardless."""
        if not (self.mem_headroom_bytes and self._mem_capacity
                and self.queue):
            return False
        if (self._pending is None and not self._lanes
                and all(r is None for r in self.rows)):
            return False  # nothing in flight will ever free bytes
        try:
            # The guard decision is its own fault site: a trip degrades
            # THIS boundary to guard-off (availability over protection)
            # — admission proceeds, the trip is counted.
            faults.maybe_fail("serve.mem_guard")
            faults.maybe_delay("serve.mem_guard")
        except faults.InjectedFault:
            return False
        predicted = self._mem_next_wave_bytes()
        budget = self._mem_capacity - self.mem_headroom_bytes
        if obs_memory.LEDGER.total() + predicted <= budget:
            return False
        self.mem_deferrals += 1
        obs_metrics.MEM_GUARD_DEFERRALS.inc()
        obs_trace.instant("mem_guard_defer", cat="mem",
                          predicted_bytes=predicted)
        if obs_journey.enabled():
            # Flight recorder (ISSUE 10): the deferral lands in the
            # timeline of every queue head that COULD have admitted
            # this boundary (the same heads _mem_next_wave_bytes
            # predicted) — their decomposition's defer_s starts here.
            free = sum(1 for r in self.rows if r is None)
            for i, q in enumerate(self.queue):
                if i >= free:
                    break
                obs_journey.event(self._journey_owner, q.rid,
                                  "mem_guard_defer",
                                  predicted_bytes=predicted)
        return True

    def _prep_request(self, req: _Request):
        """Host + encode prep for one admission: CLIP encode, splice, pad
        to the prompt bucket. Returns (padded (1, S1, D), mask, prompt_len).
        submit() validated the fit and max_len is grain-aligned, so the
        bucketed prompt can never outgrow the shared cache."""
        from eventgpt_tpu.data.tokenizer import split_at_event
        from eventgpt_tpu.models.eventchat import _pad_batch, splice_embeddings

        pv = jnp.asarray(req.pixel_values, self._dtype)[None]
        if self.mesh is not None:
            pv = self._serving.shard_batch_array(pv, self.mesh)
        ev = eventchat.encode_events_batch(self.params, self.cfg, pv)
        embeds = [splice_embeddings(
            self.params, self.cfg, split_at_event(req.input_ids), ev[0]
        )]
        padded, mask, lens = _pad_batch(embeds)
        prompt_len = int(lens[0])
        bucket = 2 * SEQ_BUCKET
        s1 = min(((prompt_len + bucket - 1) // bucket) * bucket, self.max_len)
        padded = jnp.pad(padded, ((0, 0), (0, s1 - prompt_len), (0, 0)))
        mask = jnp.pad(mask, ((0, 0), (0, s1 - prompt_len)))
        if self.mesh is not None:
            padded = self._serving.shard_batch_array(padded, self.mesh)
            mask = self._serving.shard_batch_array(mask, self.mesh)
        return padded, mask, prompt_len

    def _new_row_cache(self, s1: int):
        row_cache = llama_mod.init_kv_cache(
            self.cfg.llama, 1, s1, dtype=self._dtype, quant=self.kv_quant
        )
        if self.mesh is not None:
            row_cache = self._serving.shard_kv_cache(
                row_cache, self.cfg.llama, self.mesh
            )
        return row_cache

    def _advance_pending(self) -> None:
        """Run one prefill chunk of the in-flight admission; on the final
        chunk, insert the row into the shared cache and activate it.
        Starvation guard: when no row is actively decoding (nothing to
        stall — chunk-per-step would just serialize the admission against
        no-op segments), drain ALL remaining chunks at once."""
        while self._pending is not None:
            self._advance_pending_one()
            if self._pending is None or not bool(self.frozen.all()):
                return

    def _advance_pending_one(self) -> None:
        p = self._pending
        c = self.prefill_chunk
        start = p.filled
        end = min(start + c, p.prompt_len)
        start_arr = jnp.asarray(start, jnp.int32)
        new_len = jnp.asarray([end], jnp.int32)
        last_idx = jnp.asarray(
            max(0, min(p.prompt_len - 1 - start, c - 1)), jnp.int32
        )
        if self.mesh is not None:
            from jax.sharding import PartitionSpec as P

            row_sh = jax.tree_util.tree_map(
                lambda x: x.sharding, p.row_cache
            )
            flat, treedef = jax.tree_util.tree_flatten(row_sh)
            hidden_sh = jax.sharding.NamedSharding(self.mesh, P(None, None))
            fn = _get_sharded_chunk_prefill(
                self.cfg, c, tuple(flat), treedef, self._row_logits_sh,
                hidden_sh,
            )
            last, last_hidden, p.row_cache = fn(
                self.params, p.embeds, p.row_cache, start_arr, new_len,
                last_idx,
            )
        else:
            last, last_hidden, p.row_cache = _chunk_prefill_jit(
                self.params, self.cfg, p.embeds, p.row_cache,
                start_arr, new_len, last_idx, c,
            )
        obs_metrics.SERVE_PREFILL_DISPATCHES.inc(kind="chunk")
        p.filled = end
        p.last_logits = last
        if p.filled >= p.prompt_len:
            self._finish_admission(
                p.req, p.row, p.prompt_len, p.row_cache, last,
                last_hidden if self.draft_head is not None else None,
                path="chunk",
            )
            self._pending = None

    def _admit_wave(self, wave: List[tuple]) -> None:
        """BATCHED admission prefill (the tentpole's second half): N
        admissions ready at one dispatch boundary run ONE prefill at a
        common bucket instead of N sequential batch-1 dispatches — on
        hardware every dispatch pays the ~100 ms tunnel tax, so a wave
        costs ~1/N of the sequential path (the r4 batch-16 leg was
        "bounded by the 16 per-request prefills"). The CLIP encode is
        batched the same way. Members pad to the widest member's prompt
        bucket and to the next power-of-two wave size (log-bounded
        executable count); pad slots scatter to row index ``max_batch``,
        which XLA drops as out of bounds. Chains are unchanged: rows are
        independent in attention, and the per-row kernel is the same one
        ``generate`` already runs batched (bit-exact on the CPU f32
        suite, tests/test_prefix_cache.py)."""
        from eventgpt_tpu.data.tokenizer import split_at_event
        from eventgpt_tpu.models.eventchat import (
            _pad_batch, _prefill_jit, _prefill_sharded, splice_embeddings,
        )

        n = len(wave)
        nb = 1 << (n - 1).bit_length()
        pv = jnp.stack([jnp.asarray(req.pixel_values, self._dtype)
                        for req, _ in wave])
        if nb > n:
            pv = jnp.concatenate(
                [pv, jnp.zeros((nb - n,) + pv.shape[1:], self._dtype)])
        if self.mesh is not None:
            pv = self._serving.shard_batch_array(pv, self.mesh)
        ev = eventchat.encode_events_batch(self.params, self.cfg, pv)
        embeds = [splice_embeddings(self.params, self.cfg,
                                    split_at_event(req.input_ids), ev[i])
                  for i, (req, _) in enumerate(wave)]
        padded, mask, lens = _pad_batch(embeds)
        prompt_lens = [int(x) for x in lens]
        grain = 2 * SEQ_BUCKET
        s1 = min(((max(prompt_lens) + grain - 1) // grain) * grain,
                 self.max_len)
        padded = jnp.pad(
            padded, ((0, nb - n), (0, s1 - padded.shape[1]), (0, 0)))
        mask = jnp.pad(mask, ((0, nb - n), (0, s1 - mask.shape[1])))
        if nb > n:
            # Pad rows keep ONE real position: their (dropped) garbage KV
            # stays finite instead of feeding an all-masked softmax.
            mask = mask.at[n:, 0].set(True)
        wave_cache = llama_mod.init_kv_cache(
            self.cfg.llama, nb, s1, dtype=self._dtype, quant=self.kv_quant)
        want_hidden = self.draft_head is not None
        if self.mesh is not None:
            padded = self._serving.shard_batch_array(padded, self.mesh)
            mask = self._serving.shard_batch_array(mask, self.mesh)
            wave_cache = self._serving.shard_kv_cache(
                wave_cache, self.cfg.llama, self.mesh)
            pre = _prefill_sharded(
                self.params, self.cfg, padded, mask, wave_cache, self.mesh,
                return_hidden=want_hidden,
            )
        else:
            pre = _prefill_jit(
                self.params, self.cfg, padded, mask, wave_cache, True,
                return_hidden=want_hidden,
            )
        obs_metrics.SERVE_PREFILL_DISPATCHES.inc(kind="wave")
        if want_hidden:
            wave_logits, wave_hidden, wave_cache = pre
        else:
            (wave_logits, wave_cache), wave_hidden = pre, None
        self._scatter_wave(wave, wave_cache, wave_logits, wave_hidden,
                           prompt_lens)

    # egpt-check: harvest -- admission NaN quarantine is a mandated readback of the wave logits before they touch the shared cache
    def _scatter_wave(self, members: List[tuple], wave_cache, wave_logits,
                      wave_hidden, prompt_lens: List[int],
                      entries: Optional[List[_PrefixEntry]] = None,
                      path: str = "wave") -> None:
        """Common tail of both admission waves: per-member NaN
        quarantine, insert-on-prefill of new heads, the one-dispatch
        scatter of every surviving row into the shared cache, then row
        activation. ``members`` are (req, row) pairs; quarantined and
        pow2-pad slots keep row index ``max_batch`` (dropped by the
        scatter's out-of-bounds rule)."""
        n = len(members)
        nb = (wave_cache["k"]["q"] if isinstance(wave_cache["k"], dict)
              else wave_cache["k"]).shape[1]
        rows = np.full((nb,), self.max_batch, np.int32)  # OOB = dropped
        good = []
        finite = None
        if self.nan_check:
            finite = np.isfinite(
                np.asarray(jax.device_get(wave_logits))[:n]).all(axis=-1)
        for i, (req, row) in enumerate(members):
            if finite is not None and not finite[i]:
                # Same per-request quarantine as the batch-1 path: the
                # poisoned member never touches the shared cache (its
                # wave slot scatters out of bounds); siblings admit.
                self.rows[row] = None
                self.frozen[row] = True
                self._finish_forced(req, STATUS_NAN)
                continue
            self._insert_prefix_on_prefill(req, wave_cache, src_row=i)
            rows[i] = row
            good.append((i, req, row))
        rows_arr = jnp.asarray(rows)
        if self._paged:
            wk = wave_cache["k"]
            s1 = (wk["q"] if isinstance(wk, dict) else wk).shape[2]
            oob = self._pool.n_blocks
            n_src = s1 // self._kv_block_size
            dst = np.full((nb, n_src), oob, np.int32)
            bt_rows = np.full((nb, self._nbpr),
                              serve_blocks.SCRATCH_BLOCK, np.int32)
            for i, req, row in good:
                # Quarantined/pad slots keep all-OOB rows: their wave KV
                # never touches the pool (their reservations were freed
                # by _record_finish before this scatter was built).
                dst[i] = self._paged_dst_blocks(req, s1)
                bt_rows[i] = self._paged_bt_row(req)
                req.kv_bt_written = True
            dst_arr, bt_arr = jnp.asarray(dst), jnp.asarray(bt_rows)
            if self.mesh is not None:
                rows_arr = self._serving.replicate(rows_arr, self.mesh)
                dst_arr = self._serving.replicate(dst_arr, self.mesh)
                bt_arr = self._serving.replicate(bt_arr, self.mesh)
                admit = _get_sharded_admit_wave_paged(
                    self._cache_flat_sh, self._cache_treedef,
                    self._logits_sh
                )
            else:
                admit = _admit_wave_paged_jit
            self.cache, self.logits = admit(
                self.cache, self.logits, rows_arr, dst_arr, bt_arr,
                wave_cache["k"], wave_cache["v"], wave_cache["length"],
                wave_logits,
            )
        else:
            if self.mesh is not None:
                rows_arr = self._serving.replicate(rows_arr, self.mesh)
                admit = _get_sharded_admit_wave(
                    self._cache_flat_sh, self._cache_treedef,
                    self._logits_sh
                )
            else:
                admit = _admit_wave_jit
            self.cache, self.logits = admit(
                self.cache, self.logits, rows_arr, wave_cache["k"],
                wave_cache["v"], wave_cache["length"], wave_logits,
            )
        for i, req, row in good:
            row_hidden = (wave_hidden[i:i + 1]
                          if wave_hidden is not None else None)
            obs_journey.event(self._journey_owner, req.rid, "admit",
                              path=path, row=row)
            self._activate_row(req, row, prompt_lens[i],
                               wave_logits[i:i + 1], row_hidden,
                               entries[i] if entries is not None else None)

    def _insert_prefix_on_prefill(self, req, row_cache,
                                  src_row: int = 0) -> None:
        """Insert-on-prefill (the tentpole's population rule): after any
        admission that filled a row cache through the request's whole
        prompt, slice its reusable heads into the prefix cache — the
        TEXT head before the event sentinel (shared across ALL streams)
        and the head THROUGH the event block (keyed to this request's
        stream). The next request repeating a head admits by copy. Repeat
        heads dedupe on the exact ``(ids, pixels_key)`` key, so steady
        traffic pays one trie probe here, not a device copy."""
        pc = self._prefix_cache
        if pc is None or not self.prefix_insert:
            return
        from eventgpt_tpu.constants import EVENT_TOKEN_INDEX

        ids = list(req.input_ids)
        try:
            sent = ids.index(EVENT_TOKEN_INDEX)
        except ValueError:
            return
        heads = []
        if sent >= 1:
            heads.append((tuple(ids[:sent]), None, False, sent))
        if req.pixel_values is not None:
            heads.append((tuple(ids[:sent + 1]),
                          _pixels_key(req.pixel_values), True,
                          sent + self.cfg.num_event_tokens))
        grain = 2 * SEQ_BUCKET
        for hid, pk, has_ev, hlen in heads:
            if hlen + SEQ_BUCKET > self.max_len:
                continue  # no room for any suffix: a match could never admit
            if pc.get(hid, pk) is not None:
                continue  # already cached (the hit path touches its LRU)
            bucket = min(((hlen + grain - 1) // grain) * grain, self.max_len)
            nbytes = bucket * self._kv_pos_bytes
            if pc.budget and nbytes > pc.budget:
                continue  # would be refused: skip the device copy outright
            if self._paged:
                # Paged insert-on-prefill is ZERO-COPY: the entry ALIASES
                # the admitting row's block run over [0, bucket) — one
                # incref, no device slice. Positions < hlen are append-
                # only (never rewritten); the creator's own writes above
                # hlen in the tail block are masked from every consumer
                # (entry readers pin length = hlen), the same pad rule
                # the dense entry snapshot carries.
                nblk = bucket // self._kv_block_size
                run = (req.kv_blocks_aliased + req.kv_blocks_owned)[:nblk]
                if len(run) < nblk:
                    continue  # reservation shorter than the head bucket
                self._pool.incref(run)
                if not pc.insert(_PrefixEntry(
                        ids=hid, pixels_key=pk, has_event=has_ev,
                        kv=None, blocks=run, length=hlen, bucket=bucket,
                        nbytes=nbytes)):
                    self._pool.decref(run)
                continue
            k, v = self._slice_prefix(row_cache, bucket, src_row)
            pc.insert(_PrefixEntry(
                ids=hid, pixels_key=pk, has_event=has_ev,
                kv={"k": k, "v": v}, length=hlen, bucket=bucket,
                nbytes=nbytes,
            ))

    def _slice_prefix(self, cache, bucket: int, src_row: int = 0):
        """(k, v) blocks of cache positions [0, bucket) at batch row
        ``src_row`` — the entry-copy primitive (sharded variant pins the
        block placement, ``parallel/serving.prefix_block_sharding``)."""
        row_arr = jnp.asarray(src_row, jnp.int32)
        if self.mesh is not None:
            quant = isinstance(cache["k"], dict)
            block_sh = self._serving.prefix_block_sharding(
                self.mesh, self.cfg.llama)
            fn = _get_sharded_slice_prefix(bucket, block_sh, quant)
            return fn(cache["k"], cache["v"], row_arr)
        return _slice_prefix_jit(cache["k"], cache["v"], row_arr, bucket)

    # egpt-check: harvest -- admission NaN quarantine reads back the row logits before the row joins the shared cache
    def _finish_admission(self, req, row, prompt_len, row_cache,
                          row_logits, row_hidden=None,
                          prefix_entry=None, path: str = "full") -> None:
        """Insert the prefilled row into the shared cache + activate it."""
        if self.nan_check and not bool(
                np.isfinite(np.asarray(jax.device_get(row_logits))).all()):
            # Prefill produced non-finite logits: quarantine the REQUEST
            # before it touches the shared cache (the speculative path's
            # only NaN gate — it commits the prefill sample at admission
            # and carries no per-segment logits to check).
            self.rows[row] = None
            self.frozen[row] = True
            self._finish_forced(req, STATUS_NAN)
            return
        self._insert_prefix_on_prefill(req, row_cache)
        if self._paged:
            rk = row_cache["k"]
            s1 = (rk["q"] if isinstance(rk, dict) else rk).shape[2]
            dst = jnp.asarray(self._paged_dst_blocks(req, s1))
            btr = jnp.asarray(self._paged_bt_row(req))
            if self.mesh is not None:
                dst = self._serving.replicate(dst, self.mesh)
                btr = self._serving.replicate(btr, self.mesh)
                admit = _get_sharded_admit_paged(
                    self._cache_flat_sh, self._cache_treedef,
                    self._logits_sh)
            else:
                admit = _admit_row_paged_jit
            self.cache, self.logits = admit(
                self.cache, self.logits, row, dst, btr, row_cache,
                row_logits
            )
            req.kv_bt_written = True
        else:
            if self.mesh is not None:
                admit = _get_sharded_admit(
                    self._cache_flat_sh, self._cache_treedef,
                    self._logits_sh
                )
            else:
                admit = _admit_row_jit
            self.cache, self.logits = admit(
                self.cache, self.logits, row, row_cache, row_logits
            )
        obs_journey.event(self._journey_owner, req.rid, "admit",
                          path=path, row=row)
        self._activate_row(req, row, prompt_len, row_logits, row_hidden,
                           prefix_entry)

    def _activate_row(self, req, row, prompt_len, row_logits,
                      row_hidden=None, prefix_entry=None) -> None:
        """Post-insert activation bookkeeping, shared by the batch-1 and
        wave admission paths."""
        self.rows[row] = req
        req.row = row
        if prefix_entry is not None:
            # Refcount pin (ISSUE 4 satellite): the entry must survive
            # LRU pressure while this row decodes from its KV — a hot
            # session's head is the worst possible victim. Drained by
            # _record_finish on ANY terminal path.
            prefix_entry.pins += 1
            req.prefix_entry = prefix_entry
        obs_metrics.SERVE_ACTIVE_ROWS.set(
            sum(r is not None for r in self.rows))
        # Row activation below rewrites frozen/n_rem (and base_pos for
        # speculative rows): the next dispatch re-uploads the host mirror.
        # _admit only runs drained, so the mirror is settled here.
        self._dev_carry = None
        if self.draft_head is not None and self.spec_max > 1:
            from eventgpt_tpu.models import medusa as medusa_mod

            # Seed the row's first draft window from the prompt's last
            # hidden (the heads at that position predict the tokens after
            # the prefill-argmax commit — the _spec_segment carry rule).
            # The FULL max-window buffer is seeded: any bucket a later
            # boundary selects finds its first W-1 columns fresh.
            row_drafts = medusa_mod.medusa_drafts(
                self.params["llama"], self.draft_head, row_hidden,
                self.spec_max - 1,
            )
            self.spec_drafts = self.spec_drafts.at[row].set(row_drafts[0])
            if self.mesh is not None:
                self.spec_drafts = jax.device_put(
                    self.spec_drafts, self._drafts_sh
                )
        if self.speculative:
            self._admit_speculative(req, row, prompt_len, row_logits)
            return
        self.frozen[row] = False
        self.n_rem[row] = req.max_new_tokens

    def _admit_speculative(self, req, row: int, prompt_len: int,
                           row_logits) -> None:
        """Speculative-row bookkeeping: reset + write the row's token-id
        view of the spliced prompt (the bigram-lookup context) and commit
        the prefill token as the first generated token (the
        ``_spec_segment_jit`` invariant: cache length == committed - 1)."""
        from eventgpt_tpu.data.tokenizer import split_at_event
        from eventgpt_tpu.models.eventchat import _spliced_text_ids

        if req.max_new_tokens == 0:
            # Parity with one-shot generate (and the plain server): a zero
            # budget returns zero tokens — skip the prefill-token commit
            # that seeds the speculative invariant.
            req.tokens = []
            self._finish_row(row)
            return
        row_ids = _spliced_text_ids(
            split_at_event(req.input_ids), self.cfg.num_event_tokens,
            self.cfg.llama.max_seq_len,
        )[: self.max_len]
        self._history_append(row_ids)  # prompt text joins the lookup pool
        # Canonical sampler (argmax at T=0) — the same first-token commit
        # rule as _spec_loop_jit.
        import time

        self.key, sub = jax.random.split(self.key)
        t0 = int(sample(row_logits, sub, self.temperature, self.top_p)[0])
        req.t_first = time.perf_counter()
        req.t_last = req.t_first
        self.ids_buf = (
            self.ids_buf.at[row].set(-1)
            .at[row, : len(row_ids)].set(jnp.asarray(row_ids))
            .at[row, prompt_len].set(t0)
        )
        if self.mesh is not None:
            # Scatter chains can drop the batch sharding; re-pin so the next
            # spec segment's pinned input/output shardings stay aliasing.
            self.ids_buf = jax.device_put(self.ids_buf, self._ids_sh)
        self.base_pos[row] = prompt_len + 1
        req.tokens = [t0]
        self.n_rem[row] = req.max_new_tokens - 1
        hit_eos = self.eos_token_id is not None and t0 == self.eos_token_id
        if hit_eos or self.n_rem[row] <= 0:
            self.frozen[row] = True
            self._finish_row(row)
        else:
            self.frozen[row] = False
