"""Continuous-batching serving loop (iteration-level request scheduling).

The reference serves one request per process (``inference.py`` — load,
generate, print; its ``dataset/constants.py:1-4`` controller/worker
heartbeat constants are vestiges of a LLaVA serving stack that never
shipped). This module is the serving runtime the reference implies but
lacks: a fixed-shape decode batch whose ROWS are a resource — requests
join a running batch as rows free up, instead of waiting for the whole
batch to drain.

TPU-shaped design (everything jit-visible is static-shape):

  * One KV cache of (max_batch, max_len) rows lives in HBM for the life of
    the server; rows are FREE or ACTIVE.
  * Admission: a batch-1 prefill at the prompt's bucketed length, then the
    row's prompt KV/logits are written into the shared cache at the free
    row index (``_admit_row_jit`` — a per-buffer dynamic-update on the
    batch axis). One prefill executable per prompt bucket, reused forever.
  * Decode runs in fixed ``chunk``-token segments (``_decode_segment_jit``:
    the whole-budget ``lax.while_loop`` of ``_decode_loop_jit`` with
    per-row budgets and a frozen mask). Between segments the host harvests
    finished rows and admits queued requests — the segment size is the
    scheduling latency, and at 32 tokens the extra dispatch overhead is
    ~2-3% of decode (PERFORMANCE.md: whole-budget vs 64-token budgets).
  * Frozen/free rows keep flowing through the fused step (a ``lax.cond``
    skip would break the donated cache aliasing — same reasoning as
    ``_decode_loop_jit``); their writes land above their frozen lengths
    (clamped at the last slot), are masked out of every attention read,
    and are overwritten when the row is re-admitted.

Greedy equivalence: rows are independent in attention (per-row lengths,
positions, masks), so a request decoded in a shared batch commits the same
greedy chain as ``eventchat.generate`` run alone — tested exactly on the
CPU f32 suite (``tests/test_serve.py``); on TPU bf16 the usual
batch-tiling numerics apply.
"""

from __future__ import annotations

import functools
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from eventgpt_tpu.config import EventChatConfig
from eventgpt_tpu.constants import SEQ_BUCKET
from eventgpt_tpu.models import eventchat, llama as llama_mod
from eventgpt_tpu.ops.sampling import sample


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "chunk", "eos_token_id", "temperature", "top_p"),
    donate_argnames=("cache",),
)
def _decode_segment_jit(
    params,
    cfg: EventChatConfig,
    logits,          # (B, V) per-row next-token logits
    cache,
    key,
    frozen,          # (B,) bool — FREE rows or rows already finished
    n_rem,           # (B,) int32 remaining token budget per row
    chunk: int,
    eos_token_id: int,
    temperature: float = 0.0,
    top_p: float = 1.0,
):
    """Up to ``chunk`` decode steps over the shared batch.

    Returns (tokens (B, chunk), n_new (B,), done (B,), logits, cache, key):
    ``tokens[r, :n_new[r]]`` are row r's newly committed tokens;
    ``done[r]`` marks rows that hit EOS inside this segment (budget
    exhaustion is the host's bookkeeping via n_rem - n_new == 0).
    """
    b = logits.shape[0]
    tokens0 = jnp.full((b, chunk), eos_token_id, jnp.int32)
    n_new0 = jnp.zeros((b,), jnp.int32)
    done0 = jnp.zeros((b,), bool)

    def cond(state):
        t, _, n_new, done, _, _, _ = state
        live = ~(frozen | done) & (n_new < n_rem)
        return (t < chunk) & live.any()

    def body(state):
        t, tokens, n_new, done, logits, cache, key = state
        key, sub = jax.random.split(key)
        nxt = sample(logits, sub, temperature, top_p)
        commit = ~(frozen | done) & (n_new < n_rem)
        nxt = jnp.where(commit, nxt, eos_token_id)
        tokens = tokens.at[:, t].set(jnp.where(commit, nxt, tokens[:, t]))
        n_new = n_new + commit.astype(jnp.int32)
        done = done | (commit & (nxt == eos_token_id))

        # Unconditional advance preserves donated-cache aliasing through the
        # while_loop (see _decode_loop_jit). Frozen rows' slot writes clamp
        # at the last slot and stay masked out of attention reads.
        emb = llama_mod.embed_tokens(params["llama"], nxt[:, None])
        new_logits, cache = llama_mod.decode_step(
            params["llama"], cfg.llama, emb, cache
        )
        # Frozen rows keep their pre-segment logits AND their length: the
        # row must resume exactly where it stopped when the next segment
        # runs (length would otherwise creep by one per segment step).
        logits = jnp.where(commit[:, None], new_logits, logits)
        cache = {**cache, "length": jnp.where(
            commit, cache["length"], cache["length"] - 1
        )}
        return t + 1, tokens, n_new, done, logits, cache, key

    t, tokens, n_new, done, logits, cache, key = lax.while_loop(
        cond, body, (jnp.int32(0), tokens0, n_new0, done0, logits, cache, key)
    )
    return tokens, n_new, done, logits, cache, key


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "n_iters", "window", "eos_token_id",
                     "temperature", "top_p"),
    donate_argnames=("cache",),
)
def _spec_segment_jit(
    params,
    cfg: EventChatConfig,
    cache,
    key,
    ids_buf,          # (B, S) committed ids; -1 at event/pad positions
    base_pos,         # (B,) next unwritten ids_buf slot at segment start
    frozen,           # (B,) bool
    n_rem,            # (B,) int32 remaining budget per row
    n_iters: int,
    window: int,
    eos_token_id: int,
    temperature: float = 0.0,
    top_p: float = 1.0,
):
    """``n_iters`` speculative verify iterations over the shared batch —
    the serving form of ``models/eventchat._spec_loop_jit`` (same bigram
    drafting, same greedy/rejection-sampled verification) with per-row
    budgets and a frozen mask, stopping for admission every segment.

    Invariant per active row: ``cache["length"] == base_pos + n_new - 1``
    (every committed token except the newest has its KV cached; the
    admission path seeds it by committing the prefill argmax/sample as the
    first token). Commits are CAPPED at the remaining budget (no
    overshoot — the row may be harvested right after this segment), and a
    row is ``done`` only when its EOS lands within that cap.

    Returns (ids_buf, n_new (B,), done (B,), cache, key).
    """
    from eventgpt_tpu.models.eventchat import _spec_draft_verify

    b, s_ids = ids_buf.shape
    bidx = jnp.arange(b)
    iarr = jnp.arange(window)[None, :]
    eos = eos_token_id

    def cond(state):
        it, _, n_new, done, _, _ = state
        live = ~(frozen | done) & (n_new < n_rem)
        return (it < n_iters) & live.any()

    def body(state):
        it, ids_buf, n_new, done, cache, key = state
        active = ~(frozen | done) & (n_new < n_rem)
        pos = base_pos + n_new
        commit, m_count, first_eos, hit, cache, key = _spec_draft_verify(
            params, cfg, ids_buf, pos, cache, key, window,
            temperature, top_p, eos,
        )
        # Unlike the one-shot loop, commits are CAPPED at the remaining
        # budget (the row may be harvested right after this segment) and a
        # row is done only when its EOS lands within the cap.
        cap = jnp.where(active, n_rem - n_new, 0)
        m_eff = jnp.minimum(jnp.where(hit, first_eos + 1, m_count), cap)

        wpos = jnp.clip(pos[:, None] + iarr, 0, s_ids - 1)
        cur = ids_buf[bidx[:, None], wpos]
        ids_buf = ids_buf.at[bidx[:, None], wpos].set(
            jnp.where(iarr < m_eff[:, None], commit, cur)
        )
        n_new = n_new + m_eff
        done = done | (active & hit & (first_eos + 1 <= cap))
        cache = {**cache, "length": cache["length"] + m_eff}
        return it + 1, ids_buf, n_new, done, cache, key

    _, ids_buf, n_new, done, cache, key = lax.while_loop(
        cond, body,
        (jnp.int32(0), ids_buf, jnp.zeros((b,), jnp.int32),
         jnp.zeros((b,), bool), cache, key),
    )
    return ids_buf, n_new, done, cache, key


@functools.partial(jax.jit, donate_argnames=("cache", "logits_buf"))
def _admit_row_jit(cache, logits_buf, row, row_cache, row_logits):
    """Insert a batch-1 prefill result at batch row ``row`` of the shared
    cache (dynamic-update on the batch axis; the prompt bucket length of
    ``row_cache`` is a static shape — one compile per bucket)."""

    def ins(buf, rbuf):
        if isinstance(buf, dict):
            return {"q": ins(buf["q"], rbuf["q"]), "s": ins(buf["s"], rbuf["s"])}
        return lax.dynamic_update_slice(
            buf, rbuf.astype(buf.dtype),
            (0, row, 0) + (0,) * (buf.ndim - 3),
        )

    new_cache = {
        "k": ins(cache["k"], row_cache["k"]),
        "v": ins(cache["v"], row_cache["v"]),
        "length": cache["length"].at[row].set(row_cache["length"][0]),
    }
    return new_cache, logits_buf.at[row].set(row_logits[0])


@dataclass
class _Request:
    rid: int
    input_ids: Sequence[int]
    pixel_values: Any
    max_new_tokens: int
    tokens: List[int] = field(default_factory=list)
    row: int = -1


class ContinuousBatcher:
    """Row-level continuous batching over one resident KV cache.

    >>> srv = ContinuousBatcher(params, cfg, max_batch=4, max_len=1024)
    >>> rid = srv.submit(input_ids, pixel_values, max_new_tokens=64)
    >>> answers = srv.run_until_drained()   # {rid: [token ids]}

    Greedy by default (temperature 0); sampling configs apply serverwide.
    Single-chip for now — the serving-mesh path (parallel/serving.py)
    composes with one-shot ``generate``.
    """

    def __init__(
        self,
        params,
        cfg: EventChatConfig,
        max_batch: int = 4,
        max_len: int = 1024,
        chunk: int = 32,
        temperature: float = 0.0,
        top_p: float = 1.0,
        eos_token_id: Optional[int] = 2,
        seed: int = 0,
        kv_quant: bool = False,
        speculative: int = 0,
    ):
        self.params, self.cfg = params, cfg
        # Admission pads prompts to the serving bucket grain; a max_len off
        # the grain would let a bucketed row_cache outgrow the shared cache
        # (a trace-time shape crash). Round up once here.
        grain = 2 * SEQ_BUCKET
        max_len = ((max_len + grain - 1) // grain) * grain
        self.max_batch, self.max_len, self.chunk = max_batch, max_len, chunk
        self.temperature, self.top_p = float(temperature), float(top_p)
        self.eos = eos_token_id if eos_token_id is not None else -1
        self.eos_token_id = eos_token_id
        self._dtype = jax.tree_util.tree_leaves(params["llama"])[0].dtype
        if self._dtype not in (jnp.bfloat16, jnp.float32):
            self._dtype = jnp.bfloat16  # quantized tree: compute in bf16
        self.kv_quant = kv_quant
        self.cache = llama_mod.init_kv_cache(
            cfg.llama, max_batch, max_len, dtype=self._dtype, quant=kv_quant
        )
        # Vocab from the actual lm_head leaf, not cfg: special-token
        # registration can grow the embeddings past cfg.llama.vocab_size
        # (prepare_model's resize). int4 leaves pack K/2 on the
        # second-to-last dim; the vocab (last) dim is unpacked either way.
        head = params["llama"]["lm_head"]
        vocab = (head.get("q", head.get("q4"))
                 if isinstance(head, dict) else head).shape[-1]
        self.logits = jnp.zeros((max_batch, vocab), jnp.float32)
        # Speculative serving (window > 0): rows draft from their own
        # committed-token buffer; the prefill argmax/sample is committed at
        # admission (the _spec_segment_jit invariant) so no logits state
        # carries between segments.
        self.speculative = int(speculative)
        if self.speculative:
            self.ids_buf = jnp.full((max_batch, self.max_len), -1, jnp.int32)
            self.base_pos = np.zeros((max_batch,), np.int64)
        self.key = jax.random.PRNGKey(seed)
        self.frozen = np.ones((max_batch,), bool)   # all rows FREE
        self.n_rem = np.zeros((max_batch,), np.int64)
        self.rows: List[Optional[_Request]] = [None] * max_batch
        self.queue: deque[_Request] = deque()
        self.finished: Dict[int, List[int]] = {}
        self._next_rid = 0

    # -- client surface ---------------------------------------------------

    def submit(self, input_ids: Sequence[int], pixel_values,
               max_new_tokens: int = 64) -> int:
        """Enqueue one request; raises immediately if it cannot fit, so one
        oversized request never tears down the serving loop mid-drain."""
        from eventgpt_tpu.constants import EVENT_TOKEN_INDEX

        ids = list(input_ids)
        n_text = sum(1 for t in ids if t != EVENT_TOKEN_INDEX)
        n_ev = sum(1 for t in ids if t == EVENT_TOKEN_INDEX)
        if n_ev != 1:
            # splice_embeddings would reject this during _admit, AFTER the
            # request left the queue — validate here so the loop never
            # tears down mid-drain.
            raise ValueError(
                f"prompt must contain exactly one {EVENT_TOKEN_INDEX} event "
                f"sentinel, got {n_ev}"
            )
        prompt_len = min(
            n_text + self.cfg.num_event_tokens, self.cfg.llama.max_seq_len
        )
        # Speculative rows write one verify window past their last commit.
        slack = 1 + self.speculative
        if prompt_len + max_new_tokens + slack > self.max_len:
            raise ValueError(
                f"request does not fit: prompt {prompt_len} + budget "
                f"{max_new_tokens} exceeds server max_len {self.max_len}"
            )
        rid = self._next_rid
        self._next_rid += 1
        self.queue.append(_Request(rid, ids, pixel_values, max_new_tokens))
        return rid

    def run_until_drained(self) -> Dict[int, List[int]]:
        while self.queue or any(r is not None for r in self.rows):
            self.step()
        out, self.finished = self.finished, {}
        return out

    # -- scheduler core ---------------------------------------------------

    def step(self) -> None:
        """One scheduling iteration: admit into free rows, run one decode
        segment, harvest finished rows."""
        self._admit()
        if all(r is None for r in self.rows):
            return
        frozen = jnp.asarray(self.frozen)
        n_rem = jnp.asarray(self.n_rem.astype(np.int32))
        if self.speculative:
            n_iters = max(1, self.chunk // self.speculative)
            self.ids_buf, n_new, done, self.cache, self.key = (
                _spec_segment_jit(
                    self.params, self.cfg, self.cache, self.key,
                    self.ids_buf, jnp.asarray(self.base_pos.astype(np.int32)),
                    frozen, n_rem, n_iters, self.speculative, int(self.eos),
                    self.temperature, self.top_p,
                )
            )
            ids_np = np.asarray(jax.device_get(self.ids_buf))
            tokens = None
        else:
            tokens, n_new, done, self.logits, self.cache, self.key = (
                _decode_segment_jit(
                    self.params, self.cfg, self.logits, self.cache, self.key,
                    frozen, n_rem, self.chunk, int(self.eos),
                    self.temperature, self.top_p,
                )
            )
            tokens = np.asarray(jax.device_get(tokens))
        n_new = np.asarray(jax.device_get(n_new))
        done = np.asarray(jax.device_get(done))
        for r, req in enumerate(self.rows):
            if req is None or self.frozen[r]:
                continue
            if self.speculative:
                new = ids_np[r, self.base_pos[r]: self.base_pos[r] + n_new[r]]
                self.base_pos[r] += int(n_new[r])
            else:
                new = tokens[r, : n_new[r]]
            req.tokens.extend(int(t) for t in new)
            self.n_rem[r] -= int(n_new[r])
            if done[r] or self.n_rem[r] <= 0:
                self._finish_row(r)

    def _finish_row(self, r: int) -> None:
        req = self.rows[r]
        ids = req.tokens
        if (self.eos_token_id is not None and ids
                and ids[-1] == self.eos_token_id):
            ids = ids[:-1]
        self.finished[req.rid] = ids
        self.rows[r] = None
        self.frozen[r] = True

    def _admit(self) -> None:
        from eventgpt_tpu.data.tokenizer import split_at_event
        from eventgpt_tpu.models.eventchat import (
            _pad_batch, _prefill_jit, splice_embeddings,
        )

        while self.queue and any(self.rows[r] is None
                                 for r in range(self.max_batch)):
            req = self.queue.popleft()
            row = next(r for r in range(self.max_batch)
                       if self.rows[r] is None)
            pv = jnp.asarray(req.pixel_values, self._dtype)
            ev = eventchat.encode_events_batch(self.params, self.cfg, pv[None])
            embeds = [splice_embeddings(
                self.params, self.cfg, split_at_event(req.input_ids), ev[0]
            )]
            padded, mask, lens = _pad_batch(embeds)
            prompt_len = int(lens[0])
            bucket = 2 * SEQ_BUCKET
            # submit() validated the fit and max_len is grain-aligned, so
            # the bucketed prompt can never outgrow the shared cache.
            s1 = min(((prompt_len + bucket - 1) // bucket) * bucket,
                     self.max_len)
            padded = jnp.pad(padded, ((0, 0), (0, s1 - prompt_len), (0, 0)))
            mask = jnp.pad(mask, ((0, 0), (0, s1 - prompt_len)))
            row_cache = llama_mod.init_kv_cache(
                self.cfg.llama, 1, s1, dtype=self._dtype, quant=self.kv_quant
            )
            row_logits, row_cache = _prefill_jit(
                self.params, self.cfg, padded, mask, row_cache, True
            )
            self.cache, self.logits = _admit_row_jit(
                self.cache, self.logits, row, row_cache, row_logits
            )
            self.rows[row] = req
            req.row = row
            if self.speculative:
                self._admit_speculative(req, row, prompt_len, row_logits)
                continue
            self.frozen[row] = False
            self.n_rem[row] = req.max_new_tokens

    def _admit_speculative(self, req, row: int, prompt_len: int,
                           row_logits) -> None:
        """Speculative-row bookkeeping: reset + write the row's token-id
        view of the spliced prompt (the bigram-lookup context) and commit
        the prefill token as the first generated token (the
        ``_spec_segment_jit`` invariant: cache length == committed - 1)."""
        from eventgpt_tpu.data.tokenizer import split_at_event
        from eventgpt_tpu.models.eventchat import _spliced_text_ids

        row_ids = _spliced_text_ids(
            split_at_event(req.input_ids), self.cfg.num_event_tokens,
            self.cfg.llama.max_seq_len,
        )[: self.max_len]
        # Canonical sampler (argmax at T=0) — the same first-token commit
        # rule as _spec_loop_jit.
        self.key, sub = jax.random.split(self.key)
        t0 = int(sample(row_logits, sub, self.temperature, self.top_p)[0])
        self.ids_buf = (
            self.ids_buf.at[row].set(-1)
            .at[row, : len(row_ids)].set(jnp.asarray(row_ids))
            .at[row, prompt_len].set(t0)
        )
        self.base_pos[row] = prompt_len + 1
        req.tokens = [t0]
        self.n_rem[row] = req.max_new_tokens - 1
        hit_eos = self.eos_token_id is not None and t0 == self.eos_token_id
        if hit_eos or self.n_rem[row] <= 0:
            self.frozen[row] = True
            self._finish_row(row)
        else:
            self.frozen[row] = False
