"""Telemetry rules 1-5, migrated from ``scripts/lint_telemetry.py``
(ISSUE 8 satellite). Semantics and messages are UNCHANGED — the shim in
``scripts/lint_telemetry.py`` re-renders these findings in the legacy
``file:line: message`` form so ``tests/test_lint_telemetry.py`` keeps
asserting the same strings — but the rules now ride the shared
``analysis.core`` walk and report through ``scripts/egpt_check.py``
alongside the lock/hot-sync/jit analyzers.

Rule ids (waiver grammar ``egpt-check: ignore[<id>] -- <reason>``):

  * ``tele-clock``  — hot paths use ``time.perf_counter``, never
    ``time.time`` (rule 1).
  * ``tele-metric`` — metric-name grammar + registered exactly once
    (rule 2; fails closed when the scan finds nothing).
  * ``tele-doc``    — every registered ``egpt_*`` metric has an
    OBSERVABILITY.md catalogue row (rule 3).
  * ``tele-fault``  — every wired fault site is exercised by a
    chaos/faults test (rule 4).
  * ``tele-label``  — labelled observations stay inside the
    ``METRIC_LABELS`` enums; wired fault sites must be members of the
    fault-trip site enum (rule 5).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Tuple

from eventgpt_tpu.analysis.core import Context, Finding, Rule, Source

HOT_PATHS = (
    "eventgpt_tpu/serve.py",
    "eventgpt_tpu/faults.py",
    "eventgpt_tpu/obs/",
    "eventgpt_tpu/train/steps.py",
    "eventgpt_tpu/train/prefetch.py",
    "eventgpt_tpu/ops/",
)

METRIC_NAME_RE = re.compile(r"^egpt_[a-z0-9_]+$")
_REG_RE = re.compile(
    r"\.(?:counter|gauge|histogram)\(\s*['\"]([A-Za-z0-9_.:-]+)['\"]")
_FAULT_SITE_RE = re.compile(
    r"maybe_(?:fail|delay)\(\s*['\"]([A-Za-z0-9_.]+)['\"]")
_FAULT_TEST_RE = re.compile(r"faults\.configure\(|EGPT_FAULTS")
_OBS_METHODS = ("inc", "observe", "set")
_NON_LABEL_KWARGS = ("n",)
_BANNED_LABEL_KEYS = ("rid", "request_id", "req_id", "id", "uid",
                      "user", "user_id", "session_id")


def _is_hot(rel: str) -> bool:
    return any(rel == h or (h.endswith("/") and rel.startswith(h))
               for h in HOT_PATHS)


def _lineno(src: str, pos: int) -> int:
    return src.count("\n", 0, pos) + 1


def registrations(ctx: Context) -> Dict[str, Tuple[str, int]]:
    """Metric name -> first (rel, line) registration site, raw-regex
    over the scanned text (registrations wrap the name to the next line
    in the catalogue's house style, which ``\\s`` crosses)."""
    seen: Dict[str, Tuple[str, int]] = {}
    for s in ctx.sources:
        for m in _REG_RE.finditer(s.text):
            name = m.group(1)
            if name not in seen:
                seen[name] = (s.rel, _lineno(s.text, m.start()))
    return seen


def fault_sites(ctx: Context) -> Dict[str, Tuple[str, int]]:
    """Wired fault-site name -> first wiring site, runtime tree only."""
    sites: Dict[str, Tuple[str, int]] = {}
    for s in ctx.sources:
        if not s.rel.startswith("eventgpt_tpu/"):
            continue
        for m in _FAULT_SITE_RE.finditer(s.text):
            sites.setdefault(m.group(1), (s.rel, _lineno(s.text, m.start())))
    return sites


class HotClockRule(Rule):
    id = "tele-clock"
    doc = ("hot paths time with time.perf_counter, never time.time "
           "(wall-clock jumps corrupt latency accounting)")

    def run(self, ctx: Context) -> List[Finding]:
        out: List[Finding] = []
        for s in ctx.sources:
            if s.tree is None or not _is_hot(s.rel):
                continue
            for node in ast.walk(s.tree):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "time"
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "time"):
                    out.append(Finding(
                        self.id, s.rel, node.lineno,
                        "time.time() in a hot path "
                        "(use time.perf_counter)"))
                if (isinstance(node, ast.ImportFrom)
                        and node.module == "time"
                        and any(a.name == "time" for a in node.names)):
                    out.append(Finding(
                        self.id, s.rel, node.lineno,
                        "'from time import time' in a hot path "
                        "(use time.perf_counter)"))
        return out


class MetricRegistrationRule(Rule):
    id = "tele-metric"
    doc = ("metric names match egpt_[a-z0-9_]+ and register exactly "
           "once, in obs/metrics.py; fails closed on an empty scan")

    def run(self, ctx: Context) -> List[Finding]:
        out: List[Finding] = []
        seen: Dict[str, str] = {}
        found = False
        for s in ctx.sources:
            for m in _REG_RE.finditer(s.text):
                found = True
                name = m.group(1)
                line = _lineno(s.text, m.start())
                site = f"{s.rel}:{line}"
                if not METRIC_NAME_RE.match(name):
                    out.append(Finding(
                        self.id, s.rel, line,
                        f"metric name {name!r} does not match "
                        f"{METRIC_NAME_RE.pattern}"))
                if name in seen:
                    out.append(Finding(
                        self.id, s.rel, line,
                        f"metric {name!r} registered twice "
                        f"(first at {seen[name]}) — define metrics once, "
                        f"in obs/metrics.py"))
                else:
                    seen[name] = site
        if not found:
            out.append(Finding(
                self.id, "", 0,
                "no metric registrations found — the scan "
                "pattern or tree layout changed under the lint"))
        return out


class CatalogueRule(Rule):
    id = "tele-doc"
    doc = "every registered egpt_* metric has an OBSERVABILITY.md row"

    def run(self, ctx: Context) -> List[Finding]:
        try:
            with open(os.path.join(ctx.root, "OBSERVABILITY.md")) as f:
                doc = f.read()
        except OSError:
            doc = ""
        out: List[Finding] = []
        for name, (rel, line) in sorted(registrations(ctx).items()):
            if METRIC_NAME_RE.match(name) and name not in doc:
                out.append(Finding(
                    self.id, rel, line,
                    f"metric {name!r} has no catalogue row in "
                    f"OBSERVABILITY.md — document every registered "
                    f"metric"))
        return out


class FaultCoverageRule(Rule):
    id = "tele-fault"
    doc = ("every wired maybe_fail/maybe_delay site appears in a tests/ "
           "file that arms injection")

    def run(self, ctx: Context) -> List[Finding]:
        sites = fault_sites(ctx)
        out: List[Finding] = []
        if not sites:
            if os.path.isdir(os.path.join(ctx.root, "eventgpt_tpu")):
                out.append(Finding(
                    self.id, "", 0,
                    "no fault sites found under eventgpt_tpu/ — "
                    "the scan pattern changed under the lint"))
            return out
        chaos_text = []
        tests = os.path.join(ctx.root, "tests")
        if os.path.isdir(tests):
            for f in sorted(os.listdir(tests)):
                if not f.endswith(".py"):
                    continue
                with open(os.path.join(tests, f)) as fh:
                    src = fh.read()
                if _FAULT_TEST_RE.search(src):
                    chaos_text.append(src)
        blob = "\n".join(chaos_text)
        for name, (rel, line) in sorted(sites.items()):
            if name not in blob:
                out.append(Finding(
                    self.id, rel, line,
                    f"fault site {name!r} is not exercised by any "
                    f"chaos/faults test (no tests/ file arming injection "
                    f"mentions it) — unreachable failure handling rots"))
        return out


def _metric_var_map(sources) -> Dict[str, str]:
    """Assignment targets bound to a metric registration — how label
    checks resolve an observation's receiver back to its catalogue
    entry."""
    out: Dict[str, str] = {}
    for s in sources:
        if s.tree is None:
            continue
        for node in ast.walk(s.tree):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and isinstance(node.value.func, ast.Attribute)
                    and node.value.func.attr in ("counter", "gauge",
                                                 "histogram")
                    and node.value.args
                    and isinstance(node.value.args[0], ast.Constant)
                    and isinstance(node.value.args[0].value, str)):
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = node.value.args[0].value
    return out


def _metric_label_enums(sources) -> Dict[str, Dict[str, tuple]]:
    """``METRIC_LABELS`` from obs/metrics.py — a pure literal by
    contract, read statically."""
    for s in sources:
        if not s.rel.endswith("obs/metrics.py") or s.tree is None:
            continue
        for node in ast.walk(s.tree):
            if (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name)
                            and t.id == "METRIC_LABELS"
                            for t in node.targets)):
                try:
                    return ast.literal_eval(node.value)
                except ValueError:
                    return {}
    return {}


def _literal_label_values(node: ast.AST) -> List[str]:
    if isinstance(node, ast.Constant):
        return [node.value] if isinstance(node.value, str) else []
    if isinstance(node, ast.IfExp):
        return (_literal_label_values(node.body)
                + _literal_label_values(node.orelse))
    return []


def _journey_enums(sources) -> Dict[str, Tuple[tuple, str, int]]:
    """``EVENT_KINDS`` / ``MISS_CAUSES`` from obs/journey.py — pure
    literals by contract (ISSUE 10), read statically like
    METRIC_LABELS. Returns name -> (tuple, rel, line)."""
    out: Dict[str, Tuple[tuple, str, int]] = {}
    for s in sources:
        if not s.rel.endswith("obs/journey.py") or s.tree is None:
            continue
        for node in ast.walk(s.tree):
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if (isinstance(tgt, ast.Name)
                        and tgt.id in ("EVENT_KINDS", "MISS_CAUSES")):
                    try:
                        out[tgt.id] = (tuple(ast.literal_eval(node.value)),
                                       s.rel, node.lineno)
                    except ValueError:
                        pass
    return out


def _series_enums(sources) -> Dict[str, Tuple[tuple, str, int]]:
    """``ALERT_RULES`` from obs/series.py — a pure literal by contract
    (ISSUE 15), read statically like METRIC_LABELS. Returns
    name -> (tuple, rel, line)."""
    out: Dict[str, Tuple[tuple, str, int]] = {}
    for s in sources:
        if not s.rel.endswith("obs/series.py") or s.tree is None:
            continue
        for node in ast.walk(s.tree):
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "ALERT_RULES":
                    try:
                        out[tgt.id] = (tuple(ast.literal_eval(node.value)),
                                       s.rel, node.lineno)
                    except ValueError:
                        pass
    return out


def _journey_aliases(tree) -> set:
    """Names the journey module is bound to in one source file
    (``from eventgpt_tpu.obs import journey as obs_journey`` et al) —
    how the kind cross-check resolves ``<alias>.event(...)`` sites."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) \
                and node.module == "eventgpt_tpu.obs":
            for a in node.names:
                if a.name == "journey":
                    out.add(a.asname or a.name)
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "eventgpt_tpu.obs.journey" and a.asname:
                    out.add(a.asname)
    return out


class LabelEnumRule(Rule):
    id = "tele-label"
    doc = ("labelled metric observations draw values from the fixed "
           "METRIC_LABELS enums (bounded cardinality); wired fault "
           "sites must be members of the fault-trip site enum; journey "
           "event kinds / miss causes stay inside the obs/journey.py "
           "closed enums")

    def run(self, ctx: Context) -> List[Finding]:
        out: List[Finding] = []
        var_map = _metric_var_map(ctx.sources)
        enums = _metric_label_enums(ctx.sources)
        for s in ctx.sources:
            if s.tree is None:
                continue
            for node in ast.walk(s.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in _OBS_METHODS):
                    continue
                recv = node.func.value
                var = (recv.id if isinstance(recv, ast.Name)
                       else recv.attr if isinstance(recv, ast.Attribute)
                       else None)
                metric = var_map.get(var or "")
                if metric is None:
                    continue  # not a metric object (Event.set, queue, ..)
                declared = enums.get(metric, {})
                for kw in node.keywords:
                    if kw.arg is None or kw.arg in _NON_LABEL_KWARGS:
                        continue
                    if kw.arg in _BANNED_LABEL_KEYS:
                        out.append(Finding(
                            self.id, s.rel, node.lineno,
                            f"metric {metric!r} labelled with "
                            f"{kw.arg!r} — per-request identity labels "
                            f"are unbounded cardinality, banned "
                            f"outright"))
                        continue
                    allowed = declared.get(kw.arg)
                    if allowed is None:
                        out.append(Finding(
                            self.id, s.rel, node.lineno,
                            f"metric {metric!r} label {kw.arg!r} has "
                            f"no declared enum in obs/metrics.py "
                            f"METRIC_LABELS — labelled observations "
                            f"must draw values from a fixed catalogue "
                            f"enum"))
                        continue
                    if isinstance(kw.value, ast.JoinedStr) or (
                            isinstance(kw.value, ast.Call)
                            and isinstance(kw.value.func, ast.Name)
                            and kw.value.func.id in ("str", "repr",
                                                     "format")):
                        out.append(Finding(
                            self.id, s.rel, node.lineno,
                            f"metric {metric!r} label {kw.arg!r} is "
                            f"computed (f-string/str()) — unbounded "
                            f"label values are banned; use an enum "
                            f"member"))
                        continue
                    if (isinstance(kw.value, ast.Constant)
                            and not isinstance(kw.value.value, str)):
                        out.append(Finding(
                            self.id, s.rel, node.lineno,
                            f"metric {metric!r} label {kw.arg!r} is "
                            f"the non-string literal "
                            f"{kw.value.value!r} — request-id-shaped "
                            f"labels are banned"))
                        continue
                    for lit in _literal_label_values(kw.value):
                        if lit not in allowed:
                            out.append(Finding(
                                self.id, s.rel, node.lineno,
                                f"metric {metric!r} label "
                                f"{kw.arg!r}={lit!r} outside the "
                                f"declared enum {tuple(allowed)}"))
        trip_sites = enums.get("egpt_fault_trips_total", {}).get("site")
        if trip_sites is not None:
            for name, (rel, line) in sorted(fault_sites(ctx).items()):
                if name not in trip_sites:
                    out.append(Finding(
                        self.id, rel, line,
                        f"fault site {name!r} missing from "
                        f"egpt_fault_trips_total's site enum "
                        f"(obs/metrics.py METRIC_LABELS) — its first "
                        f"trip would raise at observe time"))
        # Flight-recorder enum cross-checks (ISSUE 10 satellite): the
        # miss-cause metric's label enum must BE obs/journey.py's
        # MISS_CAUSES literal, and every ``<journey alias>.event(...)``
        # call site with a literal kind must draw it from EVENT_KINDS
        # (the runtime raises on unknown kinds; this catches them
        # before they ship).
        jenums = _journey_enums(ctx.sources)
        if "MISS_CAUSES" in jenums:
            causes, rel, line = jenums["MISS_CAUSES"]
            declared = enums.get(
                "egpt_serve_slo_miss_cause_total", {}).get("cause")
            if declared is not None and tuple(declared) != causes:
                out.append(Finding(
                    self.id, rel, line,
                    f"obs/journey.py MISS_CAUSES {causes} diverged "
                    f"from egpt_serve_slo_miss_cause_total's cause "
                    f"enum {tuple(declared)} (obs/metrics.py "
                    f"METRIC_LABELS) — keep the two literals "
                    f"identical"))
        # Alert-rule enum cross-check (ISSUE 15 satellite): the alert
        # metrics' ``rule`` label enums must BE obs/series.py's
        # ALERT_RULES literal — the evaluator exports
        # ``egpt_alert_active{rule=...}`` for every member on every
        # transition, so a divergence raises at the first sample.
        senums = _series_enums(ctx.sources)
        if "ALERT_RULES" in senums:
            rules, rel, line = senums["ALERT_RULES"]
            for metric in ("egpt_alert_active",
                           "egpt_alert_transitions_total"):
                declared = enums.get(metric, {}).get("rule")
                if declared is not None and tuple(declared) != rules:
                    out.append(Finding(
                        self.id, rel, line,
                        f"obs/series.py ALERT_RULES {rules} diverged "
                        f"from {metric}'s rule enum "
                        f"{tuple(declared)} (obs/metrics.py "
                        f"METRIC_LABELS) — keep the two literals "
                        f"identical"))
        if "EVENT_KINDS" in jenums:
            kinds = jenums["EVENT_KINDS"][0]
            for s in ctx.sources:
                if s.tree is None or not s.rel.startswith("eventgpt_tpu/"):
                    continue
                aliases = _journey_aliases(s.tree)
                if not aliases:
                    continue
                for node in ast.walk(s.tree):
                    if not (isinstance(node, ast.Call)
                            and isinstance(node.func, ast.Attribute)
                            and node.func.attr == "event"
                            and isinstance(node.func.value, ast.Name)
                            and node.func.value.id in aliases):
                        continue
                    kind_node = (node.args[2] if len(node.args) >= 3
                                 else next((kw.value for kw in node.keywords
                                            if kw.arg == "kind"), None))
                    for lit in _literal_label_values(kind_node) \
                            if kind_node is not None else []:
                        if lit not in kinds:
                            out.append(Finding(
                                self.id, s.rel, node.lineno,
                                f"journey event kind {lit!r} outside "
                                f"the closed EVENT_KINDS enum "
                                f"(obs/journey.py) — recording it "
                                f"would raise at runtime"))
        return out


TELEMETRY_RULES = (HotClockRule(), MetricRegistrationRule(),
                   CatalogueRule(), FaultCoverageRule(), LabelEnumRule())
