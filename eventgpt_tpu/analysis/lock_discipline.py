"""Lock-discipline race detector (ISSUE 8 tentpole, rule ``lock``).

The serving stack is multi-threaded (HTTP handler threads + per-replica
scheduler threads + the fleet supervisor probe loop), and until this PR
every lock convention — ``with self._lock`` around shared dicts,
``*_locked`` helper methods, lock-free snapshot reads — lived only in
docstrings. This rule makes the conventions checkable:

A class DECLARES its guarded attributes::

    _GUARDED_BY = {
        "_requests": "_lock",      # reads AND writes under the lock
        "_snapshot": "_lock/w",    # writes under the lock; reads are
    }                              # lock-free by design (snapshot pattern)

and the analyzer verifies, method by method:

  * every read/write of a guarded attribute (``self._requests[...]``,
    ``self._snapshot = ...``) happens inside a ``with self._lock:``
    block, inside ``__init__`` (construction precedes sharing), or
    inside a ``*_locked`` method — the repo's "caller holds the lock"
    naming convention;
  * ``*_locked`` methods are only CALLED from lock scope (a ``with``
    block, another ``*_locked`` method, or ``__init__``) — and never
    re-take the lock they assert (``threading.Lock`` is non-reentrant:
    that is a deadlock, not a style issue);
  * every lock named by the declaration is actually created in
    ``__init__``;
  * ``/w`` ("writes-only") encodes the deliberate lock-free-read
    contract (GIL-atomic snapshot/flag reads) so it is visible at the
    declaration instead of silently assumed per call site.

``_EXTERNAL_LOCK = "Owner._lock"`` declares a class that holds shared
mutable state but is serialized ENTIRELY by its owner's lock
(``ContinuousBatcher`` under ``ServingEngine._lock``): the analyzer then
verifies the class manufactures no concurrency of its own — no
``threading.Thread(...)`` and no ``threading.Lock()`` stored on self —
so the external-serialization claim stays true.

Known static limits (documented, not silent): accesses through OTHER
objects (``engine.batcher.queue`` from a module function) and attributes
not listed in ``_GUARDED_BY`` are out of scope; nested functions are
analyzed as lock-NOT-held (a closure may escape the lock scope it was
built in). Deliberate benign races carry a waiver (the core grammar:
``egpt-check: ignore[<rule>] -- <reason>`` in a trailing comment).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from eventgpt_tpu.analysis.core import (Context, Finding, Rule,
                                        class_literal)

GUARDED_ATTR = "_GUARDED_BY"
EXTERNAL_ATTR = "_EXTERNAL_LOCK"


def _parse_spec(spec: str) -> Tuple[str, bool]:
    """'LOCK' -> (lock, reads_guarded=True); 'LOCK/w' -> (lock, False)."""
    if spec.endswith("/w"):
        return spec[:-2], False
    return spec, True


def _is_self_attr(node: ast.AST, attr: Optional[str] = None) -> bool:
    return (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and (attr is None or node.attr == attr))


def _with_locks(node: ast.With) -> Set[str]:
    """Lock names this ``with`` acquires via ``self.<name>``."""
    out: Set[str] = set()
    for item in node.items:
        if _is_self_attr(item.context_expr):
            out.add(item.context_expr.attr)
    return out


class _MethodChecker(ast.NodeVisitor):
    """Walks one method tracking which ``self.<lock>`` locks are held.
    Records guarded-attribute accesses and ``*_locked`` calls that
    happen outside lock scope."""

    def __init__(self, rule: "LockDisciplineRule", rel: str,
                 cls_name: str, method: str, guarded: Dict[str, Tuple],
                 exempt: bool, findings: List[Finding]):
        self.rule = rule
        self.rel = rel
        self.cls_name = cls_name
        self.method = method
        self.guarded = guarded
        self.exempt = exempt            # __init__ / *_locked methods
        self.findings = findings
        self.held: Set[str] = set()
        self.locks = {lock for lock, _ in guarded.values()}

    # -- scope handling ---------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        got = _with_locks(node) & self.locks
        added = got - self.held
        self.held |= added
        for item in node.items:
            if item.context_expr is not None:
                self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        self.held -= added

    def visit_FunctionDef(self, node) -> None:
        # A nested def may run after the lock is released (callbacks,
        # threads): analyze it with no lock held and no exemption.
        inner = _MethodChecker(self.rule, self.rel, self.cls_name,
                               f"{self.method}.<{node.name}>",
                               self.guarded, False, self.findings)
        for stmt in node.body:
            inner.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        inner = _MethodChecker(self.rule, self.rel, self.cls_name,
                               f"{self.method}.<lambda>",
                               self.guarded, False, self.findings)
        inner.visit(node.body)

    # -- access checks ----------------------------------------------------

    def _flag(self, node: ast.AST, msg: str, hint: str) -> None:
        self.findings.append(Finding(
            self.rule.id, self.rel, node.lineno,
            f"{self.cls_name}.{self.method}: {msg}", hint=hint))

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if _is_self_attr(node) and node.attr in self.guarded:
            lock, reads_guarded = self.guarded[node.attr]
            write = isinstance(node.ctx, (ast.Store, ast.Del))
            needs = write or reads_guarded
            if needs and lock not in self.held and not self.exempt:
                kind = "write to" if write else "read of"
                self._flag(
                    node,
                    f"{kind} guarded attribute 'self.{node.attr}' "
                    f"outside 'with self.{lock}'",
                    f"take self.{lock}, move into a *_locked method, or "
                    f"waive with justification")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if (_is_self_attr(fn) and fn.attr.endswith("_locked")
                and not self.exempt
                and not (self.held & self.locks)):
            self._flag(
                node,
                f"call to 'self.{fn.attr}()' outside lock scope — "
                f"*_locked methods assume the caller holds the lock",
                "call it under 'with self.<lock>' or from another "
                "*_locked method")
        self.generic_visit(node)


class LockDisciplineRule(Rule):
    id = "lock"
    doc = ("guarded attributes (declared via _GUARDED_BY) are only "
           "touched under their lock / in *_locked methods; *_locked "
           "methods are only called from lock scope")

    def run(self, ctx: Context) -> List[Finding]:
        findings: List[Finding] = []
        for s in ctx.sources:
            if s.tree is None:
                continue
            classes = {n.name: n for n in ast.walk(s.tree)
                       if isinstance(n, ast.ClassDef)}
            for cls in classes.values():
                self._check_class(s, cls, classes, findings)
        return findings

    def _resolve_guarded(self, cls: ast.ClassDef,
                         classes: Dict[str, ast.ClassDef],
                         rel: str, findings: List[Finding],
                         _depth: int = 0) -> Dict[str, Tuple[str, bool]]:
        """Merge ``_GUARDED_BY`` down the (same-module) base chain —
        ``Gauge(Counter)`` inherits the Counter declaration."""
        out: Dict[str, Tuple[str, bool]] = {}
        if _depth > 8:
            return out
        for base in cls.bases:
            if isinstance(base, ast.Name) and base.id in classes:
                out.update(self._resolve_guarded(
                    classes[base.id], classes, rel, findings, _depth + 1))
        try:
            decl, line = class_literal(cls, GUARDED_ATTR)
        except ValueError as e:
            findings.append(Finding(
                self.id, rel, cls.lineno, f"{cls.name}: {e}",
                hint="declare _GUARDED_BY as a plain dict literal"))
            return out
        if decl is not None:
            if not isinstance(decl, dict) or not all(
                    isinstance(k, str) and isinstance(v, str)
                    for k, v in decl.items()):
                findings.append(Finding(
                    self.id, rel, line,
                    f"{cls.name}: {GUARDED_ATTR} must map attribute "
                    f"names to lock specs ('LOCK' or 'LOCK/w')"))
                return out
            for attr, spec in decl.items():
                out[attr] = _parse_spec(spec)
        return out

    def _check_class(self, s, cls: ast.ClassDef,
                     classes: Dict[str, ast.ClassDef],
                     findings: List[Finding]) -> None:
        try:
            external, ext_line = class_literal(cls, EXTERNAL_ATTR)
        except ValueError as e:
            findings.append(Finding(
                self.id, s.rel, cls.lineno, f"{cls.name}: {e}"))
            external, ext_line = None, 0
        if external is not None:
            self._check_external(s, cls, external, ext_line, findings)
        guarded = self._resolve_guarded(cls, classes, s.rel, findings)
        if not guarded:
            return
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]
        # Every referenced lock must exist: created in __init__ — this
        # class's or a (same-module) base's, since subclasses inherit
        # the base lock (Gauge/Histogram under _Metric).
        made_locks: Set[str] = set()
        found_init = False
        chain, seen_cls = [cls], {cls.name}
        while chain:
            c = chain.pop()
            c_init = next(
                (m for m in c.body
                 if isinstance(m, ast.FunctionDef)
                 and m.name == "__init__"), None)
            if c_init is not None:
                found_init = True
                for node in ast.walk(c_init):
                    if (isinstance(node, ast.Assign)
                            and any(_is_self_attr(t)
                                    for t in node.targets)):
                        made_locks |= {t.attr for t in node.targets
                                       if _is_self_attr(t)}
            for base in c.bases:
                if isinstance(base, ast.Name) and base.id in classes \
                        and base.id not in seen_cls:
                    seen_cls.add(base.id)
                    chain.append(classes[base.id])
        for lock in sorted({lk for lk, _ in guarded.values()}):
            if found_init and lock not in made_locks:
                findings.append(Finding(
                    self.id, s.rel, cls.lineno,
                    f"{cls.name}: _GUARDED_BY references "
                    f"'self.{lock}' but __init__ never creates it",
                    hint="create the lock in __init__ or fix the "
                         "declaration"))
        for m in methods:
            if m.name == "__init__":
                continue
            is_locked = m.name.endswith("_locked")
            checker = _MethodChecker(self, s.rel, cls.name, m.name,
                                     guarded, is_locked, findings)
            for stmt in m.body:
                checker.visit(stmt)
            if is_locked:
                # A *_locked method that re-takes its own lock deadlocks
                # (threading.Lock is non-reentrant).
                locks = {lk for lk, _ in guarded.values()}
                for node in ast.walk(m):
                    if isinstance(node, ast.With) \
                            and _with_locks(node) & locks:
                        findings.append(Finding(
                            self.id, s.rel, node.lineno,
                            f"{cls.name}.{m.name}: *_locked method "
                            f"takes the lock it asserts is already "
                            f"held — deadlock on a non-reentrant "
                            f"Lock"))

    def _check_external(self, s, cls: ast.ClassDef, external,
                        line: int, findings: List[Finding]) -> None:
        """``_EXTERNAL_LOCK``: the class claims to be serialized by its
        owner — so it must not manufacture concurrency of its own."""
        if not isinstance(external, str):
            findings.append(Finding(
                self.id, s.rel, line,
                f"{cls.name}: {EXTERNAL_ATTR} must be the owning "
                f"'Class.lock' string"))
            return
        for node in ast.walk(cls):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "Thread" \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == "threading":
                findings.append(Finding(
                    self.id, s.rel, node.lineno,
                    f"{cls.name}: declared externally serialized by "
                    f"{external} but spawns its own thread — the "
                    f"external-lock claim is false"))
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and isinstance(node.value.func, ast.Attribute) \
                    and node.value.func.attr in ("Lock", "RLock") \
                    and any(_is_self_attr(t) for t in node.targets):
                findings.append(Finding(
                    self.id, s.rel, node.lineno,
                    f"{cls.name}: declared externally serialized by "
                    f"{external} but creates its own lock — declare "
                    f"_GUARDED_BY instead"))
